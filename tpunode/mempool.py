"""Mempool actor: the unconfirmed-transaction lifecycle in front of the
batch verify engine.

The reference haskoin-node drops every ``inv`` on the floor and never
deduplicates transaction pushes — each of N peers relaying the same tx
costs a full extract + verify.  For a node whose distinguishing feature
is the TPU batch verify hot path (README north star), ingest dedup and
admission ARE the workload shape: batch slots spent re-verifying known
txs are stolen straight from the roofline (PERF.md).  This actor owns:

* **Inv-driven relay** — ``inv`` tx announcements are coalesced across
  peers into a want-list; unseen txids are fetched in batches over the
  existing ``peer.get_txs`` RPC with per-peer in-flight limits, and a
  failed/notfound/stalled fetch is retried from another announcer (the
  prefetch-with-reassignment shape).
* **Admission dedup** — a bounded seen/verdict LRU keyed by txid (with a
  wtxid alias for witness serializations, so the fast-path key is one
  double-SHA over the raw bytes, no parse) short-circuits duplicate
  pushes BEFORE the verify pipeline; each unique tx is extracted and
  verified exactly once, and a re-push or re-announcement of a
  known-invalid tx costs zero verify work and feeds a per-peer
  misbehavior count.
* **Orphan pool** — a tx whose witness-bearing inputs spend unknown
  prevouts (not in the mempool, not resolvable via the embedder's
  ``NodeConfig.prevout_lookup`` oracle) would verify degraded
  (unsupported inputs), so it parks in a size- and age-bounded orphan
  set and re-enters admission when its parent arrives (push, fetch or
  block).  Parked orphans' missing parents join the want-list — the
  relaying peer likely has them.  An orphan leaving the pool
  unresolved — aged out or size-evicted — is admitted anyway
  (verify-what's-extractable — the pre-mempool behavior) so the
  embedder still gets a verdict; size pressure never loses one.
* **Confirmation eviction** — block connect (txids from the block
  ingest path, C++-computed on the native path) flips entries to
  CONFIRMED, drops their payloads, and re-checks waiting orphans.
* **Backpressure** — fetch scheduling defers while the node's ingest
  accumulator is saturated (``VerifyShed``/``MAX_TX_ACCUM`` machinery in
  node.py), so a flooding peer degrades into a stale want-list instead
  of unbounded memory.

Single-threaded like chain.py/peermgr.py: all state mutation happens in
the actor loop; the handle methods only enqueue mailbox messages.
Everything is instrumented under the ``mempool.*`` metric/event layer
(OBSERVABILITY.md) and the admission path is spanned
(``span.mempool.admit``) so BENCH can report admission p50/p99.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Optional

from .actors import LinkedTasks, Mailbox, Supervisor
from .events import events
from .metrics import metrics
from .params import Network
from .peer import CannotDecodePayload, Peer, get_txs
from .seenlru import SeenLru
from .trace import span
from .tracectx import discard_active as _discard_active_trace
from .txverify import needs_prevout
from .util import double_sha256, hash_to_hex

__all__ = ["MempoolConfig", "Mempool", "TxState"]

log = logging.getLogger("tpunode.mempool")


class TxState:
    """Lifecycle states of a seen txid."""

    PENDING = "pending"  # admitted, verdict not yet published
    VALID = "valid"  # verified: every extracted signature passed
    INVALID = "invalid"  # verified: at least one signature failed
    CONFIRMED = "confirmed"  # seen in a connected block
    ORPHAN = "orphan"  # parked: waiting for missing parents


@dataclass
class MempoolConfig:
    """Bounds and cadences for the mempool actor.  Every bound exists so
    a hostile or flooding peer degrades service instead of growing
    memory (the same policy as the bounded user bus, actors.py)."""

    # seen/verdict LRU: unique txids remembered for dedup + verdict cache
    max_txs: int = 50_000
    # orphan pool size bound (evict-oldest) and age bound; either way
    # out, the orphan is admitted degraded instead of silently dropped
    max_orphans: int = 1_000
    orphan_ttl: float = 600.0
    # want-list bound: announced-but-unfetched txids, and how long one
    # may sit unfetched (announcers pinned at their in-flight cap, or
    # stalling) before its slot is reclaimed
    max_wanted: int = 50_000
    want_ttl: float = 120.0
    # fetch scheduler: txids per getdata batch, concurrent batches per
    # peer, per-batch timeout, and how many announcers to try per txid
    fetch_batch: int = 256
    max_inflight_per_peer: int = 2
    fetch_timeout: float = 30.0
    fetch_retries: int = 3
    # housekeeping cadence (orphan expiry, deferred fetch scheduling)
    tick_interval: float = 1.0


class _Entry:
    """One seen txid: state + (while useful) the tx and its outputs."""

    __slots__ = ("txid", "wtxid", "state", "tx", "outputs", "origin",
                 "missing", "added", "verdicts")

    def __init__(self, txid: bytes, wtxid: bytes, state: str, tx=None,
                 outputs=None, origin: str = "?"):
        self.txid = txid
        self.wtxid = wtxid
        self.state = state
        self.tx = tx
        # tuple of (value, scriptPubKey) rows: the in-mempool prevout
        # oracle for children (and the orphan-resolvability check)
        self.outputs = outputs
        self.origin = origin  # label of the peer that delivered it
        self.missing: Optional[set[bytes]] = None  # ORPHAN: parent txids
        self.added = time.monotonic()
        self.verdicts: tuple[bool, ...] = ()


class _Want:
    """One announced-but-not-yet-delivered txid."""

    __slots__ = ("announcers", "tried", "inflight", "attempts", "added")

    def __init__(self, announcer: Optional[Peer]):
        self.announcers: list[Peer] = [announcer] if announcer else []
        self.tried: set[Peer] = set()
        self.inflight: Optional[Peer] = None
        self.attempts = 0
        self.added = time.monotonic()


# --- mailbox messages --------------------------------------------------------


@dataclass(frozen=True)
class _TxPush:
    peer: object
    tx: object


@dataclass(frozen=True)
class _Invs:
    peer: object
    txids: tuple


@dataclass(frozen=True)
class _Verdict:
    txid: bytes
    valid: bool
    verdicts: tuple
    error: Optional[str]


@dataclass(frozen=True)
class _Confirmed:
    txids: tuple


@dataclass(frozen=True)
class _ConfirmedBlock:
    block: object


@dataclass(frozen=True)
class _PeerGone:
    peer: object


@dataclass(frozen=True)
class _FetchDone:
    peer: object
    txids: tuple
    ok: bool


class _Tick:
    pass


class _Sched:
    """Deferred scheduling marker: posted to the mailbox tail so a burst
    of inv/fetch-done messages triggers ONE want-list scan after the
    burst drains, not one full scan per message."""


def _label(peer) -> str:
    lab = getattr(peer, "label", None)
    return lab if isinstance(lab, str) else f"<{type(peer).__name__}>"


def _bump_label(counter: "dict[str, int]", label: str, n: int = 1,
                bound: int = 512) -> None:
    """Per-label counter bounded against label churn: past ``bound``
    distinct labels, the smallest count is evicted (flooders keep their
    standing, one-shot labels age out)."""
    counter[label] = counter.get(label, 0) + n
    if len(counter) > bound:
        counter.pop(min(counter, key=counter.get))


class Mempool:
    """The mempool actor handle + query API.

    ``submit(peer, tx)`` is the verify-ingest hook (node.py's
    ``_submit_verify_tx``); ``prevout_lookup`` is the embedder's UTXO
    oracle (NodeConfig.prevout_lookup); ``pressure()`` true defers fetch
    scheduling (ingest backpressure); ``pressure_key(txid)`` true defers
    fetching just THAT txid (ISSUE 19 host-affine backpressure: one
    slow verify host parks only its own keys, the rest keep fetching).
    Like Chain/PeerMgr, constructed by Node and entered inside the node
    bracket."""

    def __init__(
        self,
        cfg: MempoolConfig,
        net: Network,
        submit: Callable[[object, object], None],
        prevout_lookup: Optional[Callable] = None,
        pressure: Optional[Callable[[], bool]] = None,
        pressure_key: Optional[Callable[[bytes], bool]] = None,
        on_failure=None,
    ):
        self.cfg = cfg
        self.net = net
        self._submit = submit
        self._oracle = prevout_lookup
        self._pressure = pressure
        self._pressure_key = pressure_key
        self.mailbox: Mailbox = Mailbox(name="mempool")
        self._tasks = LinkedTasks(name="mempool", on_failure=on_failure)
        # fetch tasks are crash-isolated: one failed getdata RPC must
        # never tear the node down (death is handled via _FetchDone)
        self._fetchers = Supervisor(name="mempool-fetch")
        # seen/verdict LRU (extracted structure: seenlru.py) — keyed by
        # txid with a wtxid alias; PENDING entries are pinned (verdict
        # in flight: a re-push would double-verify) up to the hard 2x
        # ceiling the structure enforces
        self._seen: SeenLru = SeenLru(
            cfg.max_txs, pinned=lambda e: e.state == TxState.PENDING
        )
        self._orphans: "OrderedDict[bytes, _Entry]" = OrderedDict()
        self._waiting: dict[bytes, set[bytes]] = {}  # parent -> orphans
        self._want: "OrderedDict[bytes, _Want]" = OrderedDict()
        self._inflight: dict[Peer, int] = {}
        self._sched_queued = False  # a _Sched marker is in the mailbox
        self._size = 0  # PENDING + VALID entries
        self._announcers: dict[str, int] = {}  # label -> announcements
        self._misbehavior: dict[str, int] = {}  # label -> incidents
        # stats() counters: instance-owned (the metrics registry is
        # process-global and cumulative — a second Node in the same
        # process must not inherit the first one's hit-rate)
        self._admitted = 0
        self._dedup_hits = 0
        self._orphan_resolved = 0
        self._fetched = 0
        self._fetch_failures = 0

    # -- lifecycle ----------------------------------------------------------

    async def __aenter__(self) -> "Mempool":
        self._tasks.link(self._main_loop(), name="mempool-main")
        if self.cfg.tick_interval > 0:
            self._tasks.link(self._tick_loop(), name="mempool-tick")
        return self

    async def __aexit__(self, *exc) -> None:
        await self._fetchers.aclose()
        await self._tasks.__aexit__(*exc)

    async def _main_loop(self) -> None:
        while True:
            msg = await self.mailbox.receive()
            if isinstance(msg, _TxPush):
                with span("mempool.admit"):
                    self._on_push(msg.peer, msg.tx)
            elif isinstance(msg, _Invs):
                self._on_invs(msg.peer, msg.txids)
            elif isinstance(msg, _Verdict):
                self._on_verdict(msg)
            elif isinstance(msg, _Confirmed):
                self._on_confirmed(msg.txids)
            elif isinstance(msg, _ConfirmedBlock):
                self._on_confirmed_block(msg.block)
            elif isinstance(msg, _FetchDone):
                self._on_fetch_done(msg.peer, msg.txids, msg.ok)
            elif isinstance(msg, _PeerGone):
                self._on_peer_gone(msg.peer)
            elif isinstance(msg, _Tick):
                self._on_tick()
            elif isinstance(msg, _Sched):
                self._sched_queued = False
                self._schedule()

    async def _tick_loop(self) -> None:
        while True:
            await asyncio.sleep(self.cfg.tick_interval)
            self.mailbox.send(_Tick())

    # -- handle methods (enqueue only; any-thread unsafe like the other
    #    actors: call from the event loop) -----------------------------------

    def tx_pushed(self, peer, tx) -> None:
        """An unsolicited (or fetched) ``tx`` message arrived from peer."""
        self.mailbox.send(_TxPush(peer, tx))

    def invs(self, peer, txids: "list[bytes]") -> None:
        """Peer announced transactions by txid (``inv``)."""
        if txids:
            self.mailbox.send(_Invs(peer, tuple(txids)))

    def verdict(self, txid: bytes, valid: bool, verdicts: tuple = (),
                error: Optional[str] = None) -> None:
        """The verify pipeline published a TxVerdict for ``txid``."""
        self.mailbox.send(_Verdict(txid, valid, tuple(verdicts), error))

    def confirmed(self, txids: "list[bytes]") -> None:
        """Block connect: these txids are now in a block."""
        if txids:
            self.mailbox.send(_Confirmed(tuple(txids)))

    def block_connected(self, block) -> None:
        """Block connect with only the block in hand (no-verify-engine
        path): txids are extracted inside the actor, guarded."""
        self.mailbox.send(_ConfirmedBlock(block))

    def peer_gone(self, peer) -> None:
        self.mailbox.send(_PeerGone(peer))

    def chain_event(self, _event) -> None:
        """Chain activity (new best block): run housekeeping soon."""
        self.mailbox.send(_Tick())

    # -- query API (lock-free reads of loop-owned state; same contract as
    #    Chain's read queries) ----------------------------------------------

    def contains(self, txid: bytes) -> bool:
        """Is ``txid`` an active (pending or valid) mempool member?"""
        e = self._seen.lookup(txid)
        return e is not None and e.state in (TxState.PENDING, TxState.VALID)

    def get(self, txid: bytes):
        """The tx object for an active member, else None."""
        e = self._seen.get(txid)
        return e.tx if e is not None and e.tx is not None else None

    def state(self, txid: bytes) -> Optional[str]:
        e = self._seen.get(txid)
        return e.state if e is not None else None

    def size(self) -> int:
        return self._size

    def orphan_count(self) -> int:
        return len(self._orphans)

    def orphans(self) -> "list[bytes]":
        return list(self._orphans)

    def lookup_prevout(self, txid: bytes, vout: int):
        """In-mempool prevout oracle: ``(value, scriptPubKey)`` when the
        funding tx is an active member, else None.  Node composes this
        in FRONT of the embedder's oracle so children spending unconfirmed
        parents extract with full prevout data."""
        e = self._seen.get(txid)
        if e is not None and e.outputs is not None and 0 <= vout < len(e.outputs):
            return e.outputs[vout]
        return None

    def stats(self) -> dict:
        """Snapshot for Node.stats() / the debug server."""
        hits = self._dedup_hits
        admitted = self._admitted
        deliveries = hits + admitted
        top = sorted(
            self._announcers.items(), key=lambda kv: -kv[1]
        )[:10]
        return {
            "size": self._size,
            "orphans": len(self._orphans),
            "wanted": len(self._want),
            "inflight_fetches": sum(self._inflight.values()),
            "admitted": admitted,
            "dedup_hits": hits,
            "dedup_hit_rate": round(hits / deliveries, 4) if deliveries else 0.0,
            "orphan_resolved": self._orphan_resolved,
            "fetched": self._fetched,
            "fetch_failures": self._fetch_failures,
            "top_announcers": [
                {"peer": k, "announcements": v} for k, v in top
            ],
            "misbehavior": dict(
                sorted(self._misbehavior.items(), key=lambda kv: -kv[1])[:10]
            ),
        }

    # -- admission ----------------------------------------------------------

    def _on_push(self, peer, tx) -> None:
        admitted = self._admit(peer, tx)
        if not admitted:
            # dedup/orphan/malformed short-circuit: this message's
            # pipeline trace (started in the peer wire loop) ends here,
            # unretained — exactly like the shed path in node.py
            _discard_active_trace()

    def _admit(self, peer, tx, re_entry: bool = False,
               force: bool = False, resolve: bool = True) -> bool:
        """Run one tx through admission.  Returns True iff it was
        submitted to the verify pipeline (False: dedup hit, parked as
        orphan, or rejected as malformed)."""
        origin = _label(peer)
        raw = getattr(tx, "raw", None)
        if raw is not None and not re_entry:
            # fast dedup: one double-SHA over the wire bytes (== wtxid
            # for witness serializations, == txid otherwise), no parse
            k = double_sha256(raw)
            known = self._seen.resolve(k)
            if known in self._seen:
                self._dedup_hit(peer, known)
                return False
        try:
            txid = tx.txid  # parses a LazyTx once (validates the payload)
            wtxid = tx.wtxid if tx.has_witness else txid
            n_out = len(tx.outputs)
        except Exception as e:
            # unparseable push: same contract as the pre-mempool decode
            # path — the relaying peer dies, the node does not
            metrics.inc("mempool.malformed")
            self._misbehave(peer, "malformed-tx")
            events.emit("mempool.reject", peer=origin,
                        error=str(e)[:200])
            kill = getattr(peer, "kill", None)
            if kill is not None:
                kill(CannotDecodePayload(f"mempool tx: {e}"))
            return False
        if not re_entry and txid in self._seen:
            # NO alias insert on this path: a malleated witness gives
            # every re-push of one known tx a fresh wtxid, and recording
            # each would grow _alias without bound (the dedup stays
            # correct — it just re-parses instead of raw-hash matching)
            self._dedup_hit(peer, txid)
            return False
        if wtxid != txid:
            self._seen.alias(wtxid, txid)
        if not force:
            missing = self._missing_parents(tx)
            if missing:
                self._park_orphan(peer, tx, txid, wtxid, missing,
                                  re_entry=re_entry)
                return False
        outputs = tuple(
            (tx.outputs[i].value, tx.outputs[i].script) for i in range(n_out)
        )
        entry = _Entry(txid, wtxid, TxState.PENDING, tx=tx,
                       outputs=outputs, origin=origin)
        self._insert_seen(entry)
        self._size += 1
        self._admitted += 1
        metrics.inc("mempool.admitted")
        metrics.set_gauge("mempool.size", self._size)
        self._drop_want(txid)
        self._submit(peer, tx)
        if resolve:
            # a newly admitted tx may be the parent an orphan waits for
            self._resolve_waiting(txid)
        return True

    def _dedup_hit(self, peer, txid: bytes) -> None:
        self._dedup_hits += 1
        metrics.inc("mempool.dedup_hits")
        e = self._seen.get(txid)
        if e is not None:
            self._seen.touch(txid)  # recently relevant: keep in LRU
            if e.state == TxState.INVALID:
                # a verdict served from cache: zero verify work, and the
                # peer relaying a known-invalid tx is counted against it
                self._misbehave(peer, "relayed-known-invalid")

    def _missing_parents(self, tx) -> "set[bytes]":
        """Parent txids whose absence would degrade this tx's
        verification: only inputs whose digest/classification actually
        consumes prevout data gate admission (txverify.needs_prevout) —
        a legacy input with an unknown prevout verifies fine and must
        not orphan the tx."""
        missing: set[bytes] = set()
        for idx, txin in enumerate(tx.inputs):
            if not needs_prevout(tx, idx):
                continue
            prev = txin.prevout
            e = self._seen.get(prev.txid)
            if e is not None:
                if e.outputs is not None and prev.index < len(e.outputs):
                    continue
                if e.state == TxState.CONFIRMED:
                    continue  # in the chain: the embedder's oracle owns it
            if self._oracle is not None and (
                self._oracle(prev.txid, prev.index) is not None
            ):
                continue
            missing.add(prev.txid)
        return missing

    def _insert_seen(self, entry: _Entry) -> None:
        # eviction policy (PENDING rotation, 2x ceiling) lives in the
        # extracted structure; this actor owns index teardown + metrics
        for old_txid, old in self._seen.insert(entry.txid, entry):
            self._forget(old_txid, old)
            metrics.inc("mempool.evicted")

    def _forget(self, txid: bytes, e: _Entry) -> None:
        """Drop every index entry for a seen txid (LRU eviction)."""
        if e.wtxid != txid:
            self._seen.drop_alias(e.wtxid)
        if e.state in (TxState.PENDING, TxState.VALID):
            self._size -= 1
            metrics.set_gauge("mempool.size", self._size)
        if e.state == TxState.ORPHAN:
            self._unpark(txid, e)

    # -- orphan pool --------------------------------------------------------

    def _park_orphan(self, peer, tx, txid: bytes, wtxid: bytes,
                     missing: "set[bytes]", re_entry: bool = False) -> None:
        entry = _Entry(txid, wtxid, TxState.ORPHAN, tx=tx,
                       origin=_label(peer))
        entry.missing = missing
        self._insert_seen(entry)
        self._orphans[txid] = entry
        for parent in missing:
            self._waiting.setdefault(parent, set()).add(txid)
            # the peer that relayed the child likely has the parent:
            # put the parent on the want-list sourced from that peer
            if isinstance(peer, Peer):
                self._want_tx(parent, peer)
        if not re_entry:
            metrics.inc("mempool.orphaned")
        metrics.set_gauge("mempool.orphans", len(self._orphans))
        events.emit("mempool.orphan", txid=hash_to_hex(txid),
                    missing=len(missing), peer=entry.origin)
        self._drop_want(txid)
        while len(self._orphans) > self.cfg.max_orphans:
            old_txid, old = self._orphans.popitem(last=False)
            self._unpark(old_txid, old, pop=False)
            self._seen.pop(old_txid, None)
            if old.wtxid != old_txid:
                self._seen.drop_alias(old.wtxid)
            metrics.inc("mempool.orphan_evicted")
            # same contract as TTL expiry: the embedder gets a verdict
            # for every ingested tx — size pressure degrades the oldest
            # orphan to verify-what's-extractable, never silent loss
            self._admit(_Origin(old.origin), old.tx, re_entry=True,
                        force=True)
        self._schedule_soon()

    def _unpark(self, txid: bytes, e: _Entry, pop: bool = True) -> None:
        """Remove orphan bookkeeping (the seen entry is the caller's)."""
        if pop:
            self._orphans.pop(txid, None)
        for parent in e.missing or ():
            waiters = self._waiting.get(parent)
            if waiters is not None:
                waiters.discard(txid)
                if not waiters:
                    del self._waiting[parent]
        metrics.set_gauge("mempool.orphans", len(self._orphans))

    def _resolve_waiting(self, parent: bytes) -> None:
        """A parent arrived (admitted or confirmed): re-run admission for
        the orphans that were waiting on it.  Iterative worklist — a
        deep orphan chain resolving parent-by-parent must not recurse
        ``max_orphans`` frames deep."""
        queue = [parent]
        while queue:
            parent = queue.pop()
            waiters = self._waiting.pop(parent, None)
            if not waiters:
                continue
            for child_txid in list(waiters):
                e = self._orphans.get(child_txid)
                if e is None:
                    continue
                e.missing.discard(parent)
                if e.missing:
                    continue  # still waiting on other parents
                self._unpark(child_txid, e)
                self._seen.pop(child_txid, None)
                # re-admission re-checks every prevout: other parents
                # may have been evicted meanwhile -> it re-parks
                if self._admit(_Origin(e.origin), e.tx, re_entry=True,
                               resolve=False):
                    self._orphan_resolved += 1
                    metrics.inc("mempool.orphan_resolved")
                    events.emit(
                        "mempool.orphan_resolved",
                        txid=hash_to_hex(child_txid),
                        parent=hash_to_hex(parent),
                    )
                    queue.append(child_txid)  # may unblock grandchildren

    def _expire_orphans(self) -> None:
        now = time.monotonic()
        while self._orphans:
            txid, e = next(iter(self._orphans.items()))
            if now - e.added <= self.cfg.orphan_ttl:
                break
            self._unpark(txid, e)
            self._seen.pop(txid, None)
            metrics.inc("mempool.orphan_expired")
            events.emit("mempool.orphan_expired", txid=hash_to_hex(txid))
            # degrade to the pre-mempool contract instead of silence:
            # verify what's extractable, the embedder gets a verdict
            self._admit(_Origin(e.origin), e.tx, re_entry=True, force=True)

    # -- verdicts and confirmation ------------------------------------------

    def _on_verdict(self, v: _Verdict) -> None:
        e = self._seen.get(v.txid)
        if e is None or e.state != TxState.PENDING:
            return
        if v.error is not None:
            # indeterminate (engine/extract failure): forget the entry so
            # a later re-push retries instead of serving a bogus verdict
            self._seen.pop(v.txid, None)
            self._forget(v.txid, e)
            return
        e.verdicts = v.verdicts
        if v.valid:
            e.state = TxState.VALID
            metrics.inc("mempool.accepted")
        else:
            e.state = TxState.INVALID
            e.tx = None
            e.outputs = None
            self._size -= 1
            metrics.inc("mempool.rejected")
            metrics.set_gauge("mempool.size", self._size)
            self._misbehave(_Origin(e.origin), "relayed-invalid")

    def _on_confirmed(self, txids: tuple) -> None:
        flipped = 0
        for txid in txids:
            e = self._seen.get(txid)
            if e is None and (txid in self._waiting or txid in self._want):
                # Never seen, but actively tracked: an orphan waits on it
                # or it's on the want-list.  Tombstone it as CONFIRMED so
                # a late inv for it doesn't trigger a pointless fetch.
                # Both sets are bounded, so this can't flood the LRU.
                e = _Entry(txid, txid, TxState.CONFIRMED)
                self._insert_seen(e)
                flipped += 1
            elif e is not None:
                # Only entries we already track flip to CONFIRMED.  Any
                # other never-seen block txid is NOT cached: block sync
                # would otherwise pump thousands of historical txids per
                # block through the LRU, churning out the live mempool
                # state the cache exists to protect.
                if e.state == TxState.ORPHAN:
                    self._unpark(txid, e)
                elif e.state in (TxState.PENDING, TxState.VALID):
                    self._size -= 1
                    metrics.inc("mempool.confirmed_evictions")
                e.state = TxState.CONFIRMED
                e.tx = None
                e.outputs = None
                e.missing = None
                flipped += 1
            self._drop_want(txid)
        metrics.set_gauge("mempool.size", self._size)
        if flipped:
            metrics.inc("mempool.confirmed", flipped)
        # confirmed parents can unblock waiting orphans (their prevouts
        # are now the embedder oracle's/chain's responsibility) — seen
        # or not: an orphan can wait on a parent that was never relayed
        for txid in txids:
            self._resolve_waiting(txid)

    def _on_confirmed_block(self, block) -> None:
        try:
            txids = [tx.txid for tx in block.txs]
        except Exception as e:
            log.debug("[Mempool] unparseable block on connect: %s", e)
            return
        self._on_confirmed(tuple(txids))

    # -- inv relay / fetch scheduler ----------------------------------------

    def _on_invs(self, peer, txids: tuple) -> None:
        _bump_label(self._announcers, _label(peer), len(txids))
        metrics.inc("mempool.announcements", len(txids))
        for txid in txids:
            e_txid = self._seen.resolve(txid)
            if e_txid in self._seen:
                self._dedup_hit(peer, e_txid)
                continue
            self._want_tx(txid, peer)
        self._schedule_soon()

    def _want_tx(self, txid: bytes, peer: Peer) -> None:
        w = self._want.get(txid)
        if w is None:
            if len(self._want) >= self.cfg.max_wanted:
                metrics.inc("mempool.inv_dropped")
                return
            self._want[txid] = w = _Want(None)
            metrics.inc("mempool.announced")
        if peer not in w.announcers and peer not in w.tried:
            w.announcers.append(peer)

    def _drop_want(self, txid: bytes) -> None:
        w = self._want.pop(txid, None)
        if w is not None and w.inflight is not None:
            # delivered by another path while a fetch was in flight: the
            # in-flight accounting is reconciled at _FetchDone
            self._want[txid] = w

    def _schedule_soon(self) -> None:
        """Request a scheduling pass after the current mailbox burst
        drains.  The scan in _schedule is O(want-list); running it per
        inv message makes a flood quadratic — coalescing to one marker
        at the mailbox tail makes it amortized one scan per burst."""
        if not self._sched_queued:
            self._sched_queued = True
            self.mailbox.send(_Sched())

    def _schedule(self) -> None:
        """Assign wanted txids to announcers with capacity, batched."""
        if self._pressure is not None and self._pressure():
            metrics.inc("mempool.fetch_deferred")
            return  # the tick loop re-schedules once pressure clears
        batches: dict[Peer, list[bytes]] = {}
        deferred_txs = 0
        for txid, w in self._want.items():
            if w.inflight is not None:
                continue
            if self._pressure_key is not None and self._pressure_key(txid):
                # host-affine deferral (ISSUE 19): this txid's target
                # verify host is over its feed ceiling — leave it in the
                # want-list for the next pass; other hosts' txids keep
                # fetching below
                deferred_txs += 1
                continue
            for p in w.announcers:
                if p in batches:
                    batch = batches[p]
                    if len(batch) >= self.cfg.fetch_batch:
                        continue  # this announcer's batch is full
                else:
                    # at most ONE new batch per peer per scheduling pass,
                    # and never past the per-peer in-flight cap
                    if self._inflight.get(p, 0) + 1 > (
                        self.cfg.max_inflight_per_peer
                    ):
                        continue
                    batch = batches.setdefault(p, [])
                batch.append(txid)
                w.inflight = p
                break
        if deferred_txs:
            metrics.inc("mempool.fetch_deferred_txs", deferred_txs)
        for p, txids in batches.items():
            self._inflight[p] = self._inflight.get(p, 0) + 1
            metrics.inc("mempool.fetches")
            self._fetchers.add_child(
                self._fetch(p, tuple(txids)), name=f"mempool-fetch-{_label(p)}"
            )

    async def _fetch(self, peer: Peer, txids: tuple) -> None:
        """One getdata batch against one announcer.  The RPC's returned
        txs are NOT admitted here: every served tx also arrives through
        the normal peer-message path (the wire loop publishes it), so
        admission stays single-path and the dedup metric honest.  This
        task only reconciles the want-list."""
        ok = False
        try:
            res = await get_txs(self.net, self.cfg.fetch_timeout, peer, list(txids))
            ok = res is not None
        except Exception as e:
            log.debug("[Mempool] fetch from %s failed: %s", _label(peer), e)
        finally:
            self.mailbox.send(_FetchDone(peer, txids, ok))

    def _on_fetch_done(self, peer, txids: tuple, ok: bool) -> None:
        if ok:
            # counted here, not in the fetcher task: all state mutation
            # (instance counters included) stays in the actor loop
            self._fetched += len(txids)
            metrics.inc("mempool.fetched", len(txids))
        n = self._inflight.get(peer, 0) - 1
        if n > 0:
            self._inflight[peer] = n
        else:
            self._inflight.pop(peer, None)
        for txid in txids:
            w = self._want.get(txid)
            if w is None or w.inflight is not peer:
                continue
            w.inflight = None
            if ok or self._seen.resolve(txid) in self._seen:
                # served (or delivered by another path mid-flight): the
                # push path owns admission from here
                del self._want[txid]
                continue
            w.attempts += 1
            w.tried.add(peer)
            w.announcers = [p for p in w.announcers if p is not peer]
            if w.attempts >= self.cfg.fetch_retries or not w.announcers:
                del self._want[txid]
                self._fetch_failures += 1
                metrics.inc("mempool.fetch_failures")
                events.emit(
                    "mempool.fetch_failed", txid=hash_to_hex(txid),
                    attempts=w.attempts, peer=_label(peer),
                )
            else:
                metrics.inc("mempool.fetch_retries")
        self._schedule_soon()

    def _on_peer_gone(self, peer) -> None:
        self._inflight.pop(peer, None)
        for txid in list(self._want):
            w = self._want[txid]
            if w.inflight is peer:
                w.inflight = None
            if peer in w.announcers:
                w.announcers.remove(peer)
            if w.inflight is None and not w.announcers:
                del self._want[txid]
        self._schedule_soon()

    # -- housekeeping --------------------------------------------------------

    def _on_tick(self) -> None:
        self._expire_orphans()
        self._expire_wants()
        self._schedule()

    def _expire_wants(self) -> None:
        """Reclaim want-list slots that never got fetched: an entry can
        sit with ``inflight=None`` indefinitely when its announcers are
        permanently at their in-flight cap or never answer (the TxRelay
        "stall" shape keeps the peer connected, so _on_peer_gone never
        clears it).  A fresh announcement re-adds the txid."""
        now = time.monotonic()
        expired = 0
        for txid in list(self._want):
            w = self._want[txid]
            if w.inflight is None and now - w.added > self.cfg.want_ttl:
                del self._want[txid]
                expired += 1
        if expired:
            metrics.inc("mempool.want_expired", expired)

    def _misbehave(self, peer, why: str) -> None:
        metrics.inc("mempool.misbehavior")
        lab = _label(peer)
        _bump_label(self._misbehavior, lab)
        events.emit("mempool.misbehavior", peer=lab, reason=why)

    def misbehavior(self, peer) -> int:
        """Misbehavior incidents attributed to ``peer`` (by label)."""
        return self._misbehavior.get(_label(peer), 0)


class _Origin:
    """Stand-in peer for re-admissions (orphan resolution/expiry): the
    original relayer's label for attribution, no live session."""

    __slots__ = ("label",)

    def __init__(self, label: str):
        self.label = label

    def __repr__(self) -> str:
        return f"<Origin {self.label}>"
