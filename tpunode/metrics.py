"""Lightweight counters/gauges registry for observability.

The reference exposes no metrics (SURVEY.md §5: logging only, RTT stats as
the lone performance signal); the benchmark harness and verify engine need
real counters — sigs/sec, batch occupancy, headers/sec, peer count — so this
registry provides them process-wide with zero dependencies.
"""

from __future__ import annotations

import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Optional

__all__ = ["Metrics", "metrics"]


@dataclass
class _Counter:
    value: float = 0.0
    updated: float = 0.0


class Metrics:
    def __init__(self) -> None:
        self._counters: dict[str, _Counter] = defaultdict(_Counter)
        self._gauges: dict[str, float] = {}
        self._created = time.monotonic()

    def inc(self, name: str, amount: float = 1.0) -> None:
        c = self._counters[name]
        c.value += amount
        c.updated = time.monotonic()

    def set_gauge(self, name: str, value: float) -> None:
        self._gauges[name] = value

    def get(self, name: str) -> float:
        if name in self._gauges:
            return self._gauges[name]
        return self._counters[name].value if name in self._counters else 0.0

    def rate(self, name: str) -> float:
        """Average rate of a counter since process start (per second)."""
        c = self._counters.get(name)
        if c is None or c.value == 0:
            return 0.0
        elapsed = max(1e-9, time.monotonic() - self._created)
        return c.value / elapsed

    def snapshot(self) -> dict[str, float]:
        out = {k: c.value for k, c in self._counters.items()}
        out.update(self._gauges)
        return out

    def reset(self) -> None:
        self._counters.clear()
        self._gauges.clear()
        self._created = time.monotonic()


# Process-wide registry (tests may construct their own).
metrics = Metrics()
