"""Telemetry registry: counters, gauges, histograms — labeled, thread-safe.

The reference exposes no metrics (SURVEY.md §5: logging only, RTT stats as
the lone performance signal); the benchmark harness, verify engine and the
network layers need real distributions — dispatch latency, batch occupancy,
per-peer RTT — because averages hide the tail that determines block-relay
latency.  This registry provides them process-wide with zero dependencies.

Conventions (see OBSERVABILITY.md):

* metric names follow ``<layer>.<name>`` (``^[a-z]+(\\.[a-z_]+)+$``),
  enforced by a lint test (tests/test_metrics.py);
* histograms use fixed log-scaled buckets so ``observe()`` is O(log n
  buckets) and shapes never grow with traffic;
* every mutation takes one process-wide lock — the verify engine and
  asyncio executors mutate from worker threads;
* ``TPUNODE_NO_METRICS=1`` disables all recording (hot-loop escape hatch;
  reads still work and report zeros/empties).
"""

from __future__ import annotations

import math
import os
import threading
import time
import weakref
from bisect import bisect_left
from collections import deque
from typing import Callable, Iterable, Optional, Sequence

from . import threadsan

__all__ = [
    "DEFAULT_BUCKETS",
    "Histogram",
    "Metrics",
    "metrics",
    "percentiles",
]

# Log-scaled duration buckets: 1µs .. ~134s, ×2 per bucket (+overflow).
DEFAULT_BUCKETS: tuple[float, ...] = tuple(1e-6 * 2**i for i in range(28))

# Labels normalize to a sorted tuple of (key, value) pairs; the internal
# registry key is (name, label_tuple) with () meaning "unlabeled".
_LabelKey = tuple[tuple[str, str], ...]


def _label_key(labels: Optional[dict]) -> _LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _render_key(name: str, lk: _LabelKey) -> str:
    if not lk:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in lk)
    return f"{name}{{{inner}}}"


def _weak_callable(fn):
    """A weak reference to ``fn`` suitable for callback lists: bound
    methods need WeakMethod (a plain ref to the transient bound-method
    object dies immediately)."""
    if hasattr(fn, "__self__"):
        return weakref.WeakMethod(fn)
    return weakref.ref(fn)


def percentiles(values: Sequence[float], ps: Iterable[float]) -> dict[str, float]:
    """Exact percentiles of a small sample (per-peer RTT lists): linear
    interpolation between order statistics; {} when empty."""
    if not values:
        return {}
    s = sorted(values)
    out = {}
    for p in ps:
        rank = p * (len(s) - 1)
        lo = int(math.floor(rank))
        hi = min(lo + 1, len(s) - 1)
        out[f"p{int(p * 100)}"] = s[lo] + (s[hi] - s[lo]) * (rank - lo)
    return out


class Histogram:
    """Fixed-bucket histogram with quantile estimation.

    Buckets are half-open ``(bounds[i-1], bounds[i]]`` plus an overflow
    bucket.  ``quantile`` returns the geometric midpoint of the target
    bucket clamped to the observed [min, max], so a single-sample (or
    single-valued) histogram reports the exact value.
    """

    __slots__ = ("bounds", "counts", "count", "total", "min", "max")

    def __init__(self, bounds: Sequence[float] = DEFAULT_BUCKETS):
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, value: float) -> None:
        self.counts[bisect_left(self.bounds, value)] += 1
        self.count += 1
        self.total += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value

    def quantile(self, p: float) -> Optional[float]:
        """Estimate the p-quantile (p in [0, 1]); None when empty."""
        if self.count == 0:
            return None
        rank = max(1, math.ceil(p * self.count))
        cum = 0
        idx = len(self.counts) - 1
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank:
                idx = i
                break
        lo = self.bounds[idx - 1] if idx > 0 else 0.0
        hi = self.bounds[idx] if idx < len(self.bounds) else self.max
        if lo > 0 and hi > 0:
            mid = math.sqrt(lo * hi)  # geometric: log-scaled buckets
        else:
            mid = (lo + hi) / 2.0
        return min(max(mid, self.min), self.max)

    @property
    def mean(self) -> Optional[float]:
        return self.total / self.count if self.count else None

    def summary(self) -> dict:
        """Self-describing stats dict (the BENCH ``telemetry`` rows)."""
        out: dict = {"count": self.count}
        if self.count:
            out.update(
                sum=self.total,
                min=self.min,
                max=self.max,
                p50=self.quantile(0.50),
                p90=self.quantile(0.90),
                p99=self.quantile(0.99),
            )
        else:
            # same keys as the populated case: BENCH consumers diff these
            # rows across rounds and a schema flip would break them
            out.update(sum=0.0, min=None, max=None,
                       p50=None, p90=None, p99=None)
        return out

    def count_le(self, bound: float) -> int:
        """Observations ``<= bound`` — exact when ``bound`` is one of the
        bucket boundaries (the SLO evaluator picks its latency thresholds
        on boundaries for exactly this reason); otherwise the cumulative
        count up to the last boundary ``<= bound`` (a lower bound)."""
        if math.isinf(bound) and bound > 0:
            return self.count  # +Inf: everything, incl. the overflow bucket
        idx = bisect_left(self.bounds, bound)
        if idx < len(self.bounds) and self.bounds[idx] == bound:
            idx += 1
        return sum(self.counts[:idx])

    def bucket_counts(self) -> dict[str, int]:
        """Non-empty buckets keyed by upper bound (readable exposition)."""
        out = {}
        for i, c in enumerate(self.counts):
            if c:
                le = self.bounds[i] if i < len(self.bounds) else math.inf
                out[f"{le:.6g}"] = c
        return out


class _Counter:
    __slots__ = ("value", "updated", "samples")

    def __init__(self, now: float):
        self.value = 0.0
        self.updated = now
        # (monotonic, value) checkpoints for windowed rates, ≥1s apart;
        # seeded at 0 so the first window covers the counter's whole life.
        self.samples: deque[tuple[float, float]] = deque(maxlen=720)
        self.samples.append((now, 0.0))


# Minimum spacing between rate checkpoints (keeps inc() allocation-light).
_RATE_RESOLUTION = 1.0


class Metrics:
    """Process-wide registry.  All public methods are thread-safe."""

    def __init__(self, disabled: Optional[bool] = None):
        self.disabled = (
            os.environ.get("TPUNODE_NO_METRICS") == "1"
            if disabled is None
            else disabled
        )
        self._lock = threadsan.lock("metrics.registry")
        self._counters: dict[tuple[str, _LabelKey], _Counter] = {}
        self._gauges: dict[tuple[str, _LabelKey], float] = {}
        self._hists: dict[tuple[str, _LabelKey], Histogram] = {}
        # metric family -> help text (# HELP exposition lines); optional,
        # registered at first use via describe()
        self._help: dict[str, str] = {}
        # drop_label listeners (ISSUE 19 labeled-series lifecycle):
        # weakly-referenced callables invoked OUTSIDE the lock with
        # (key, value) after an eviction, so downstream samplers (the
        # Timeline) retire the same series instead of re-growing them.
        # Weak refs: a churned Timeline must not be kept alive (or
        # called) by the process-global registry.
        self._drop_hooks: list = []
        self._created = time.monotonic()

    def describe(self, name: str, help_: str) -> None:
        """Register a one-line description for a metric family: rendered
        as a ``# HELP`` line by :meth:`render_prometheus`.  Idempotent —
        the first registration wins (call it where the family is first
        recorded).  Works even when recording is disabled (descriptions
        are metadata, not samples)."""
        with self._lock:
            self._help.setdefault(name, help_)

    # -- write path ----------------------------------------------------------

    def _inc_locked(
        self, key: tuple[str, _LabelKey], amount: float, now: float
    ) -> None:
        """Counter update + rate checkpointing; caller holds the lock."""
        c = self._counters.get(key)
        if c is None:
            c = self._counters[key] = _Counter(now)
        c.value += amount
        c.updated = now
        if now - c.samples[-1][0] >= _RATE_RESOLUTION:
            c.samples.append((now, c.value))

    def inc(
        self, name: str, amount: float = 1.0, labels: Optional[dict] = None
    ) -> None:
        if self.disabled:
            return
        now = time.monotonic()
        with self._lock:
            self._inc_locked((name, _label_key(labels)), amount, now)

    def inc_batch(
        self, items: Iterable[tuple[str, float, Optional[dict]]]
    ) -> None:
        """Increment several counters under ONE lock acquisition — the
        per-message hot-loop form (see trace.span's time_span for the
        same pattern): ``items`` is (name, amount, labels-or-None)."""
        if self.disabled:
            return
        now = time.monotonic()
        with self._lock:
            for name, amount, labels in items:
                self._inc_locked((name, _label_key(labels)), amount, now)

    def set_gauge(
        self, name: str, value: float, labels: Optional[dict] = None
    ) -> None:
        if self.disabled:
            return
        with self._lock:
            self._gauges[(name, _label_key(labels))] = value

    def observe(
        self,
        name: str,
        value: float,
        labels: Optional[dict] = None,
        buckets: Optional[Sequence[float]] = None,
    ) -> None:
        """Record ``value`` into the named histogram (created on first use;
        ``buckets`` overrides the default log-scaled bounds then)."""
        if self.disabled:
            return
        key = (name, _label_key(labels))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = Histogram(
                    buckets if buckets is not None else DEFAULT_BUCKETS
                )
            h.observe(value)

    def time_span(self, hist_name: str, seconds_name: str, count_name: str,
                  dt: float) -> None:
        """One-lock fast path for trace.span: histogram observe + the two
        legacy counters (``span.<name>.seconds`` / ``.count``)."""
        if self.disabled:
            return
        now = time.monotonic()
        with self._lock:
            h = self._hists.get((hist_name, ()))
            if h is None:
                h = self._hists[(hist_name, ())] = Histogram()
            h.observe(dt)
            self._inc_locked((seconds_name, ()), dt, now)
            self._inc_locked((count_name, ()), 1.0, now)

    def on_drop(self, hook: Callable[[str, str], None]) -> None:
        """Register a ``(key, value)`` callback fired after every
        :meth:`drop_label` eviction.  Held by WEAK reference — callers
        must keep the callable alive (a bound method of a live object
        does); dead refs are pruned on the next drop."""
        with self._lock:
            self._drop_hooks.append(_weak_callable(hook))

    def drop_label(self, key: str, value: str) -> None:
        """Evict every labeled series carrying ``key=value`` (all names).

        Per-peer labeled series (``peer.msgs{peer=...}``, ``peer.rtt``)
        would otherwise grow the registry without bound on a long-running
        node churning through addresses; the peer manager calls this when
        a session ends — and the verify engine retires its fleet's
        ``host=`` series at teardown (ISSUE 19).  Unlabeled aggregates
        are untouched.  Registered :meth:`on_drop` hooks fire after the
        eviction, outside the lock."""
        pair = (str(key), str(value))
        with self._lock:
            for table in (self._counters, self._gauges, self._hists):
                for k in [k for k in table if pair in k[1]]:
                    del table[k]
            hooks = list(self._drop_hooks)
        live = []
        for ref in hooks:
            fn = ref()
            if fn is None:
                continue
            live.append(ref)
            fn(pair[0], pair[1])
        if len(live) != len(hooks):
            with self._lock:
                self._drop_hooks = [
                    r for r in self._drop_hooks if r() is not None
                ]

    # -- read path -----------------------------------------------------------

    def get(self, name: str, labels: Optional[dict] = None) -> float:
        key = (name, _label_key(labels))
        with self._lock:
            if key in self._gauges:
                return self._gauges[key]
            c = self._counters.get(key)
            return c.value if c is not None else 0.0

    def histogram(
        self, name: str, labels: Optional[dict] = None
    ) -> Optional[Histogram]:
        return self._hists.get((name, _label_key(labels)))

    def series(self, name: str) -> dict[_LabelKey, float]:
        """All labeled values of one counter/gauge name (round-trippable:
        keys are the normalized (key, value) tuples)."""
        out: dict[_LabelKey, float] = {}
        with self._lock:
            for (n, lk), c in self._counters.items():
                if n == name:
                    out[lk] = c.value
            for (n, lk), v in self._gauges.items():
                if n == name:
                    out[lk] = v
        return out

    def rate(self, name: str, window: float = 60.0,
             labels: Optional[dict] = None) -> float:
        """Windowed rate (per second) of a counter over roughly the last
        ``window`` seconds (accurate to the ~1s checkpoint resolution).
        The old since-process-start behavior — which understates rates
        after any idle period — is ``lifetime_rate``."""
        now = time.monotonic()
        with self._lock:
            c = self._counters.get((name, _label_key(labels)))
            if c is None:
                return 0.0
            cutoff = now - window
            if c.updated <= cutoff:
                return 0.0  # idle for the whole window
            base_t, base_v = c.samples[0]
            for t, v in c.samples:
                if t > cutoff:
                    break
                base_t, base_v = t, v
            if base_t <= cutoff:
                # baseline value stands in for the value AT the cutoff
                # (no checkpoint landed between them), so the window is
                # the true denominator — an idle gap before the cutoff
                # must not dilute the current rate
                dt = window
            else:
                # counter younger than the window: rate over its life,
                # floored at the checkpoint resolution so a counter
                # microseconds old cannot report an absurd spike
                dt = max(_RATE_RESOLUTION, now - base_t)
            return (c.value - base_v) / dt

    def lifetime_rate(self, name: str, labels: Optional[dict] = None) -> float:
        """Average rate of a counter since process start (per second)."""
        with self._lock:
            c = self._counters.get((name, _label_key(labels)))
            if c is None or c.value == 0:
                return 0.0
            elapsed = max(1e-9, time.monotonic() - self._created)
            return c.value / elapsed

    def snapshot(self) -> dict[str, float]:
        """Flat counters+gauges dict; labeled series render as
        ``name{k="v",...}`` keys."""
        with self._lock:
            out = {_render_key(n, lk): c.value for (n, lk), c in self._counters.items()}
            out.update(
                {_render_key(n, lk): v for (n, lk), v in self._gauges.items()}
            )
        return out

    def histograms(self) -> dict[str, Histogram]:
        with self._lock:
            return {_render_key(n, lk): h for (n, lk), h in self._hists.items()}

    def flat_sample(self) -> dict[str, float]:
        """One-lock flat sample for the timeline sampler
        (tpunode/timeseries.py): counters + gauges (like :meth:`snapshot`)
        plus each histogram's ``<name>.count``/``<name>.sum`` — the two
        histogram moments that are meaningful as time series (windowed
        deltas give rate and mean; per-bucket rings would be cardinality
        × buckets for no query anyone asks).  A span histogram's
        ``.count`` collides with its legacy shadow counter of the same
        name — they track the same quantity, so the overwrite is a
        no-op."""
        with self._lock:
            out = {
                _render_key(n, lk): c.value
                for (n, lk), c in self._counters.items()
            }
            out.update(
                {_render_key(n, lk): v for (n, lk), v in self._gauges.items()}
            )
            for (n, lk), h in self._hists.items():
                key = _render_key(n, lk)
                out[key + ".count"] = float(h.count)
                out[key + ".sum"] = h.total
        return out

    def render_prometheus(self, prefix: str = "tpunode_") -> str:
        """Prometheus text exposition format (0.0.4).

        The legacy ``span.<name>.seconds``/``.count`` counters are skipped:
        the ``span.<name>`` histogram already exposes ``_sum``/``_count``
        series, and rendering both would emit duplicate sample names
        (``..._count`` twice), which Prometheus rejects."""

        def pname(name: str) -> str:
            return prefix + name.replace(".", "_").replace("-", "_")

        def fmt(v: float) -> str:
            # repr: shortest round-trip text — %g's 6 significant digits
            # would quantize large byte/msg counters between scrapes
            return repr(float(v))

        def is_span_shadow(name: str) -> bool:
            return name.startswith("span.") and (
                name.endswith(".seconds") or name.endswith(".count")
            )

        def esc(v: str) -> str:
            # exposition-format 0.0.4 label-value escaping: backslash,
            # double-quote AND newline (peer addresses and error strings
            # are attacker-influenced; a raw newline would let one forge
            # arbitrary exposition lines)
            return (
                v.replace("\\", "\\\\")
                .replace('"', '\\"')
                .replace("\n", "\\n")
            )

        def plabels(lk: _LabelKey, extra: str = "") -> str:
            parts = [f'{k}="{esc(v)}"' for k, v in lk]
            if extra:
                parts.append(extra)
            return "{" + ",".join(parts) + "}" if parts else ""

        with self._lock:
            counters = {k: c.value for k, c in self._counters.items()}
            gauges = dict(self._gauges)
            hists = dict(self._hists)
            helps = dict(self._help)
        lines: list[str] = []
        typed: set[str] = set()

        def emit_type(name: str, kind: str) -> None:
            if name not in typed:
                typed.add(name)
                help_ = helps.get(name)
                if help_ is not None:
                    # HELP escaping (0.0.4): backslash and newline only
                    text = help_.replace("\\", "\\\\").replace("\n", "\\n")
                    lines.append(f"# HELP {pname(name)} {text}")
                lines.append(f"# TYPE {pname(name)} {kind}")

        for (name, lk), value in sorted(counters.items()):
            if is_span_shadow(name):
                continue
            emit_type(name, "counter")
            lines.append(f"{pname(name)}{plabels(lk)} {fmt(value)}")
        for (name, lk), value in sorted(gauges.items()):
            emit_type(name, "gauge")
            lines.append(f"{pname(name)}{plabels(lk)} {fmt(value)}")
        for (name, lk), h in sorted(hists.items()):
            emit_type(name, "histogram")
            cum = 0
            for i, c in enumerate(h.counts):
                cum += c
                le = (
                    f"{h.bounds[i]:.9g}" if i < len(h.bounds) else "+Inf"
                )
                le_label = 'le="%s"' % le
                lines.append(
                    f"{pname(name)}_bucket{plabels(lk, le_label)} {cum}"
                )
            lines.append(f"{pname(name)}_sum{plabels(lk)} {fmt(h.total)}")
            lines.append(f"{pname(name)}_count{plabels(lk)} {h.count}")
        return "\n".join(lines) + "\n"

    def telemetry(self) -> dict:
        """The BENCH JSON ``telemetry`` section: span percentiles, the
        batch-occupancy histogram, and structured-event counts.  The
        ``verify.dispatch`` and ``verify.occupancy`` rows are always
        present (empty = count 0) so the artifact shape is stable."""
        with self._lock:
            hists = {_render_key(n, lk): h for (n, lk), h in self._hists.items()}
        spans = {
            name[len("span."):]: h.summary()
            for name, h in hists.items()
            if name.startswith("span.") and "{" not in name
        }
        spans.setdefault("verify.dispatch", Histogram().summary())
        occ = hists.get("verify.occupancy") or Histogram()
        out = {
            "spans": spans,
            "occupancy": dict(occ.summary(), buckets=occ.bucket_counts()),
        }
        try:  # events is a sibling module; avoid a hard import cycle
            from .events import events

            out["events"] = events.counts()
        except Exception:
            out["events"] = {}
        return out

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self._created = time.monotonic()


# Process-wide registry (tests may construct their own).
metrics = Metrics()
