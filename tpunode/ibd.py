"""Block-fetch-driven IBD: the fetch planner behind ``NodeConfig.ibd``
(ISSUE 11 / ROADMAP item 5).

The node's block ingest used to be embedder-driven: headers synced through
the chain actor, but block BODIES only arrived when the embedding process
pushed them or drove ``peer.get_blocks`` windows itself (benchmarks/run.py
config3 was the canonical driver).  :class:`BlockFetcher` closes that gap:
a bare ``Node`` now syncs the whole chain by itself, the way the mempool's
inv-driven fetch pipeline already self-drives tx relay.

Shape (deliberately the mempool fetcher's, tpunode/mempool.py):

* the planner walks the **persisted chain from the UTXO watermark** —
  restart resumes exactly where the store says verification stopped, so a
  kill -9 mid-sync re-fetches (and re-verifies) nothing below the
  watermark (the ISSUE 9 crash contract, now end-to-end);
* block hashes come from an incrementally-maintained height->hash view of
  the best chain (one O(1) step per new header, one bounded walk per
  reorg) — never an O(n) ancestor walk per batch;
* ``getdata`` batches (``batch_blocks`` hashes each) are spread across the
  online peer fleet best-RTT-first with a per-peer in-flight cap; a
  failed/timed-out batch retries from another peer (its ``tried`` set
  rotates the fleet), and a dead peer's batches reassign immediately;
* delivered blocks arrive through the NORMAL peer-message path (the wire
  loop publishes them; ``node._peer_events`` routes them into verify
  ingest + UTXO connect) — the planner never touches block bytes, so
  admission stays single-path exactly like mempool fetch;
* scheduling is watermark-gated: at most ``max_lead`` blocks beyond the
  watermark are ever in flight (bounded by the node's out-of-order
  parking), and planning defers while verify-ingest pressure is high —
  the planner can saturate the pipeline but never outrun it into the
  shed path;
* a delivered-but-stuck head batch (its blocks shed, or lost to an engine
  failure) is re-fetched after ``refetch_after`` seconds — the watermark
  can stall but never wedge.

Telemetry: ``ibd.*`` metrics/events (OBSERVABILITY.md).  Engine-side, the
node submits planner-era block batches at the ``ibd`` priority — beneath
live ``block``/``mempool`` traffic in the lane packer — so a backfilling
node still serves fresh verdicts first (tpunode/verify/sched.py).
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from .actors import LinkedTasks, Supervisor
from .events import events
from .metrics import metrics
from .peer import get_blocks

__all__ = ["IbdConfig", "BlockFetcher"]

log = logging.getLogger("tpunode.ibd")


@dataclass
class IbdConfig:
    """Fetch-planner knobs (``NodeConfig.ibd``).  The defaults keep the
    total in-flight block count under the node's verify-pending and
    out-of-order-parking bounds, so a healthy sync never sheds."""

    # blocks per getdata batch (one peer round-trip)
    batch_blocks: int = 16
    # concurrent batches per peer
    max_inflight_per_peer: int = 2
    # per-batch RPC timeout (the trailing-ping sentinel bounds the wait)
    fetch_timeout: float = 45.0
    # max blocks scheduled beyond the UTXO watermark: bounds in-flight
    # memory AND stays inside Node.MAX_VERIFY_PENDING (64 messages) and
    # MAX_UTXO_PENDING (128 parked) so healthy syncs never shed
    max_lead: int = 48
    # a delivered head batch whose blocks still have not connected after
    # this long is re-fetched (heals shed/failed ingest; in a healthy sync
    # this never fires, keeping verdicts exactly-once)
    refetch_after: float = 30.0
    # planner cadence (timeouts/retries are detected on ticks; deliveries
    # and chain events wake it immediately)
    tick_interval: float = 0.5


class _Batch:
    """One scheduled getdata window: heights ``[lo, hi]`` on the best
    chain.  States: queued -> fetching -> delivered (-> dropped once the
    watermark passes ``hi``); failures return it to queued."""

    __slots__ = (
        "lo", "hi", "hashes", "state", "peer", "task", "tried",
        "attempts", "delivered_at",
    )

    def __init__(self, lo: int, hi: int, hashes: list[bytes]):
        self.lo = lo
        self.hi = hi
        self.hashes = hashes
        self.state = "queued"
        self.peer = None
        self.task: Optional[asyncio.Task] = None
        self.tried: set = set()
        self.attempts = 0
        self.delivered_at = 0.0


class BlockFetcher:
    """The IBD fetch planner.  Constructed by ``Node`` (never directly);
    lives inside the node bracket like the other subsystems."""

    def __init__(
        self,
        cfg: IbdConfig,
        net,
        chain,
        peer_mgr,
        utxo,
        pressure: Callable[[], bool],
        pressure_key: Optional[Callable[[bytes], bool]] = None,
        on_failure=None,
    ):
        self.cfg = cfg
        self._net = net
        self._chain = chain
        self._peer_mgr = peer_mgr
        self._utxo = utxo
        self._pressure = pressure
        # host-affine gate (ISSUE 19): true for a BLOCK HASH whose
        # target verify host is over its feed ceiling — _assign skips
        # just that batch instead of deferring the whole plan
        self._pressure_key = pressure_key
        self._tasks = LinkedTasks(name="ibd", on_failure=on_failure)
        # fetch RPCs are crash-isolated: one failed getdata must never
        # tear the node down (failure returns the batch to queued)
        self._fetchers = Supervisor(name="ibd-fetch")
        self._wake = asyncio.Event()
        self._batches: dict[int, _Batch] = {}  # keyed by lo height
        self._inflight: dict[object, int] = {}
        self._hashes: dict[int, bytes] = {}  # best-chain height -> hash
        self._cache_best: Optional[bytes] = None
        self._cache_floor = 1 << 62  # lowest height the view covers
        self._target = 0
        self._announced = False
        self.synced = asyncio.Event()  # wm reached the header tip once
        self._fetched_blocks = 0
        self._refetches = 0
        self._retries = 0

    # -- lifecycle -----------------------------------------------------------

    async def __aenter__(self) -> "BlockFetcher":
        self._tasks.link(self._main_loop(), name="ibd-planner")
        return self

    async def __aexit__(self, *exc) -> None:
        await self._fetchers.aclose()
        await self._tasks.__aexit__(*exc)

    # -- wiring from the node's routers (event-loop only) ---------------------

    def nudge(self) -> None:
        """Chain activity (new best header) or a delivered block: plan."""
        self._wake.set()

    def peer_gone(self, peer) -> None:
        """A peer died: its in-flight batches reassign immediately instead
        of waiting out the RPC timeout."""
        self._inflight.pop(peer, None)
        for b in self._batches.values():
            if b.state == "fetching" and b.peer is peer:
                if b.task is not None and not b.task.done():
                    b.task.cancel()  # -> _fetch's finally requeues it
        self._wake.set()

    # -- introspection --------------------------------------------------------

    @property
    def backfilling(self) -> bool:
        """True while the watermark trails the header tip by more than
        the planner's lead window: the node tags block verify submissions
        ``ibd`` (beneath live traffic) during a genuine backfill and
        ``block`` otherwise.  The margin matters: on a SYNCED node a live
        block's headers land (bumping the target) before its UTXO connect
        advances the watermark, so a trail of a few blocks is the normal
        live-tip state — classifying it ``ibd`` would put fresh blocks
        beneath mempool relay, inverting the block > mempool ordering
        (review finding)."""
        return self._target - self._utxo.height > self.cfg.max_lead

    def stats(self) -> dict:
        return {
            "enabled": True,
            "target": self._target,
            "watermark": self._utxo.height,
            "batches": len(self._batches),
            "inflight": sum(self._inflight.values()),
            "fetched_blocks": self._fetched_blocks,
            "retries": self._retries,
            "refetches": self._refetches,
        }

    # -- planner --------------------------------------------------------------

    async def _main_loop(self) -> None:
        while True:
            try:
                await asyncio.wait_for(
                    self._wake.wait(), self.cfg.tick_interval
                )
            except (asyncio.TimeoutError, TimeoutError):
                pass
            self._wake.clear()
            self._plan()

    def _best(self):
        try:
            return self._chain.get_best()
        except Exception:
            return None  # chain DB not initialized yet

    def _plan(self) -> None:
        best = self._best()
        if best is None:
            return
        self._target = best.height
        wm = self._utxo.height
        metrics.set_gauge("ibd.target", float(self._target))
        if not self._announced and self._target > wm:
            self._announced = True
            events.emit(
                "ibd.start", watermark=wm, target=self._target,
            )
        # connected batches retire; stale cache entries prune
        for lo in [lo for lo, b in self._batches.items() if b.hi <= wm]:
            del self._batches[lo]
        for h in [h for h in self._hashes if h <= wm]:
            del self._hashes[h]
        self._cache_floor = max(self._cache_floor, wm + 1)
        if wm >= self._target:
            if self._target > 0 and not self.synced.is_set():
                self.synced.set()
                events.emit("ibd.synced", height=wm)
                log.info("[IBD] watermark reached header tip %d", wm)
            metrics.set_gauge("ibd.inflight_blocks", 0.0)
            return
        self.synced.clear()
        now = time.monotonic()
        # head-of-line healing: the batch holding wm+1 was delivered but
        # never connected (shed under pressure, or its ingest failed) —
        # after the grace window, fetch it again
        head = next(
            (b for b in self._batches.values() if b.lo <= wm + 1 <= b.hi),
            None,
        )
        if (
            head is not None
            and head.state == "delivered"
            and now - head.delivered_at > self.cfg.refetch_after
        ):
            head.state = "queued"
            head.tried.clear()
            self._refetches += 1
            metrics.inc("ibd.refetches")
            events.emit("ibd.refetch", lo=head.lo, hi=head.hi)
        if self._pressure():
            metrics.inc("ibd.deferred")
            return  # the tick retries once ingest drains
        self._refresh_hashes(best)
        # a reorg may have rewritten heights under planned batches: a
        # batch whose hashes no longer match the best-chain view fetches
        # orphaned blocks nobody can connect — drop it and replan
        for lo in [
            lo for lo, b in self._batches.items()
            if any(
                self._hashes.get(h) != hh
                for h, hh in zip(range(b.lo, b.hi + 1), b.hashes)
                if h > wm  # connected heights are pruned from the view
            )
        ]:
            b = self._batches.pop(lo)
            if b.task is not None and not b.task.done():
                b.state = "dropped"  # _fetch_done ignores it
                b.task.cancel()
            metrics.inc("ibd.reorg_dropped")
        # extend the plan over every uncovered height up to the lead
        # horizon.  Not just past the highest batch: after a reorg unwind
        # the watermark sits BELOW surviving batches, and the gap in
        # front of them is exactly what must be fetched next.
        horizon = min(self._target, wm + self.cfg.max_lead)
        for lo, hi in self._uncovered(max(wm + 1, 1), horizon):
            next_h = lo
            while next_h <= hi:
                b_hi = min(next_h + self.cfg.batch_blocks - 1, hi)
                hashes = [
                    self._hashes.get(h) for h in range(next_h, b_hi + 1)
                ]
                if any(h is None for h in hashes):
                    break  # header gap (mid-reorg): replan on the next tick
                self._batches[next_h] = _Batch(next_h, b_hi, hashes)
                next_h = b_hi + 1
        metrics.set_gauge(
            "ibd.inflight_blocks",
            float(sum(
                b.hi - b.lo + 1
                for b in self._batches.values()
                if b.state == "fetching"
            )),
        )
        self._assign()

    def _uncovered(self, lo: int, hi: int) -> list[tuple[int, int]]:
        """Height ranges in ``[lo, hi]`` not covered by any batch."""
        gaps: list[tuple[int, int]] = []
        cur = lo
        for b_lo, b_hi in sorted(
            (b.lo, b.hi) for b in self._batches.values()
        ):
            if b_lo > cur:
                gaps.append((cur, min(b_lo - 1, hi)))
            cur = max(cur, b_hi + 1)
            if cur > hi:
                break
        if cur <= hi:
            gaps.append((cur, hi))
        return [(a, b) for a, b in gaps if a <= b]

    def _refresh_hashes(self, best) -> None:
        """Maintain the height->hash view of the best chain: O(1) per tip
        extension, one bounded walk down to the first already-agreeing
        entry after a reorg.  The view covers ``[watermark+1, best]`` —
        ``_cache_floor`` tracks its lower edge so a reorg unwind that
        moves the watermark BACKWARD re-fills the newly-needed heights
        (early-stopping on an agreeing entry is only sound when the
        cached range already reaches the floor)."""
        floor = max(self._utxo.height, 0)
        covered = self._cache_floor <= floor + 1
        if best.hash == self._cache_best and covered:
            return
        node = best
        while node is not None and node.height > floor:
            if covered and self._hashes.get(node.height) == node.hash:
                break  # below here the cached view already agrees
            self._hashes[node.height] = node.hash
            node = self._chain.get_block(node.header.prev)
        self._cache_floor = min(self._cache_floor, floor + 1)
        self._cache_best = best.hash
        # a reorg may have shortened the chain: drop orphaned heights
        for h in [h for h in self._hashes if h > best.height]:
            del self._hashes[h]

    def _assign(self) -> None:
        """Hand queued batches to online peers with capacity, lowest
        heights first (the watermark only advances contiguously)."""
        peers = self._peer_mgr.get_peers()  # online, best median RTT first
        if not peers:
            return
        cap = self.cfg.max_inflight_per_peer
        for lo in sorted(self._batches):
            b = self._batches[lo]
            if b.state != "queued":
                continue
            if (
                self._pressure_key is not None
                and b.hashes
                and b.hashes[0] is not None
                and self._pressure_key(b.hashes[0])
            ):
                # this batch's verify host is saturated: defer IT, keep
                # assigning batches bound for other hosts (ISSUE 19)
                metrics.inc("ibd.deferred_batches")
                continue
            pick = next(
                (o.peer for o in peers
                 if self._inflight.get(o.peer, 0) < cap
                 and o.peer not in b.tried),
                None,
            )
            if pick is None:
                # every capable peer already failed this batch: rotate the
                # fleet and let the next pass retry from anyone
                if b.tried and all(
                    o.peer in b.tried for o in peers
                ):
                    b.tried.clear()
                    self._retries += 1
                    metrics.inc("ibd.rotations")
                continue
            b.state = "fetching"
            b.peer = pick
            self._inflight[pick] = self._inflight.get(pick, 0) + 1
            metrics.inc("ibd.fetches")
            b.task = self._fetchers.add_child(
                self._fetch(b, pick), name=f"ibd-fetch-{b.lo}"
            )

    async def _fetch(self, b: _Batch, peer) -> None:
        """One getdata batch.  The returned blocks are DISCARDED here:
        every served block also arrives through the peer-message path
        (the wire loop publishes it), which is where ingest happens —
        this task only acks delivery for the planner's bookkeeping."""
        ok = False
        try:
            res = await get_blocks(
                self._net, self.cfg.fetch_timeout, peer, b.hashes
            )
            ok = res is not None
        except asyncio.CancelledError:
            raise  # finally still runs: the batch requeues
        except Exception as e:
            log.debug("[IBD] fetch [%d,%d] failed: %s", b.lo, b.hi, e)
        finally:
            self._fetch_done(b, peer, ok)

    def _fetch_done(self, b: _Batch, peer, ok: bool) -> None:
        n = self._inflight.get(peer, 0) - 1
        if n > 0:
            self._inflight[peer] = n
        else:
            self._inflight.pop(peer, None)
        if b.state != "fetching" or b.peer is not peer:
            return  # already retired or reassigned (peer_gone raced)
        b.task = None
        if ok:
            b.state = "delivered"
            b.delivered_at = time.monotonic()
            self._fetched_blocks += b.hi - b.lo + 1
            metrics.inc("ibd.blocks", b.hi - b.lo + 1)
        else:
            b.state = "queued"
            b.peer = None
            b.tried.add(peer)
            b.attempts += 1
            metrics.inc("ibd.batch_failures")
            events.emit(
                "ibd.batch_failed", lo=b.lo, hi=b.hi,
                attempts=b.attempts,
                peer=getattr(peer, "label", "?"),
            )
        self._wake.set()
