"""The Pallas/Mosaic batch ECDSA verify kernel: the whole MSM in VMEM.

Same mathematics as :mod:`kernel` (GLV + Shamir over 33 interleaved 4-bit
windows, complete RCB point formulas via :mod:`curve` with the
Mosaic-friendly field ops of :mod:`pallas_field`), but compiled as ONE TPU
program per batch block:

* the per-signature Q/λQ multiple tables live in VMEM scratch;
* the accumulator and every field-op intermediate stay in vector
  registers/VMEM — zero HBM round-trips inside the window loop;
* table entries are selected by 16-way compare-accumulate (no gathers,
  no one-hot einsums);
* the grid walks fixed-size lane blocks of the batch, Pallas
  double-buffering the block DMAs.

Why: under plain XLA the same math is per-op dispatch/HBM bound (~41k
sigs/s ceiling at batch 8k on one v5e chip — measured round 3); in a
single Mosaic program the arithmetic runs from VMEM at VPU rate.

Inputs/outputs match :func:`kernel.verify_core` (same PreparedBatch host
prep, same verdict vector), pinned against the CPU oracle in
tests/test_pallas_kernel.py.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import field as F
from . import pallas_field as PF
from .curve import point_form, pt_add, pt_add_mixed, pt_double
from .kernel import (
    _EULER_DIGITS,
    _PM2_DIGITS,
    BETA,
    G_TABLE,
    G_TABLE_AFF,
    LG_TABLE,
    LG_TABLE_AFF,
    select_mode,
    select_tree16,
    structure_modes,
    window_bits,
    window_tables,
)

__all__ = ["verify_blocked", "verify_blocked_impl", "BLOCK"]

BLOCK = 256  # lanes per grid step: 2 tables x 1.2 MB VMEM + headroom

_BETA_LIMBS = [int(x) for x in F.to_limbs(BETA)]
_SEVEN_LIMBS = [7] + [0] * (F.NLIMBS - 1)

# Constant G / λG tables as host numpy, shape (16, 3, NLIMBS) — and their
# 2-coordinate affine views (16, 2, NLIMBS) for the affine point form:
# broadcast over lanes at trace time (compile-time constants in-kernel).
# The 5-bit window mode fetches its 32-entry tables from
# kernel.window_tables() instead (see _const_table).
_G_NP = np.asarray(G_TABLE)
_LG_NP = np.asarray(LG_TABLE)
_G_AFF_NP = np.asarray(G_TABLE_AFF)
_LG_AFF_NP = np.asarray(LG_TABLE_AFF)


def _const_table(tab_np: np.ndarray, b: int) -> jnp.ndarray:
    """Constant window table operand.  4-bit windows keep the proven r3
    layout: the (16, C, L) table broadcast over all ``b`` lanes.  5-bit
    windows (ISSUE 12) pass ONE shared copy — shape (32, C, L, 1) — and
    let the in-kernel selects broadcast it against the per-lane digit
    rows: the per-lane duplication is pure VMEM waste, and at 32 entries
    it would double a cost that was already ~1.2 MB per table."""
    if window_bits() == 5:
        return jnp.asarray(tab_np[:, :, :, None])
    return jnp.asarray(
        np.broadcast_to(tab_np[:, :, :, None], tab_np.shape + (b,))
    )


def _select16(table, digit_row):
    """Branch-free 16-way select over window-table entries.

    ``table``: (16, C, L, B) value or VMEM ref (C = 3 projective / 2
    affine); ``digit_row``: (1, B).  Two formulations behind the
    TPUNODE_SELECT16 knob (kernel.select_mode(), read at trace time):

    * ``tree`` (default, ISSUE 8 lever 3): balanced 4-level binary
      select tree — 15 wheres, each level resolving one digit bit; half
      the one-hot form's data movement and no accumulate adds.
    * ``onehot``: the r3 compare-accumulate (16 wheres + 15 adds).

    Entry 0 is the infinity point — under the projective form the
    complete RCB formulas make adding it a no-op; the affine window loop
    handles digit 0 with a keep-accumulator select instead.

    Entry count follows the table's leading axis (16 at 4-bit windows,
    32 at 5-bit — ISSUE 12).  A shared constant table with a 1-lane
    trailing axis broadcasts against the digit row inside each where.
    """
    ent_n = int(table.shape[0])
    if select_mode() == "onehot":
        out = None
        for t in range(ent_n):
            m = digit_row == t  # (1, B), broadcasts over (C, L, B)
            contrib = jnp.where(m, table[t], 0)
            out = contrib if out is None else out + contrib
        return out
    # the ONE shared fold (kernel.select_tree16): digit_row (1, B)
    # broadcasts over each (C, L, B) entry exactly like the XLA path's
    return select_tree16([table[t] for t in range(ent_n)], digit_row)


def _signed(entry: jnp.ndarray, neg_row: jnp.ndarray) -> jnp.ndarray:
    """Negate the point iff ``neg_row`` (1, B): -P = (X, -Y[, Z]) — works
    on projective (3, L, B) and affine (2, L, B) entries alike."""
    y = jnp.where(neg_row != 0, -entry[1], entry[1])
    parts = [entry[0:1], y[None]]
    if entry.shape[0] == 3:
        parts.append(entry[2:3])
    return jnp.concatenate(parts, axis=0)


def _kernel(
    g_ref,  # (16, 3, L, B) constant G table, same block every step
    lg_ref,  # (16, 3, L, B) constant λG table
    d1a_ref,
    d1b_ref,
    d2a_ref,
    d2b_ref,
    negs_ref,  # (4, B) int32
    qx_ref,
    qy_ref,
    r1_ref,
    r2_ref,
    flags_ref,  # (4, B) int32: [r2_valid, host_valid, schnorr, bip340]
    # remaining refs depend on the STATIC variant (pallas passes inputs,
    # then outputs, then scratch, positionally):
    #   projective full:         euler_ref, out_ref, qtab, lqtab, powtab
    #   projective schnorr_free: out_ref, qtab, lqtab  (no digits/pow)
    #   affine (either):         euler_ref, out_ref, qtab(2-coord),
    #                            lqtab(2-coord), ztab, ptab, powtab
    #   (affine always carries the digits + pow scratch: the batch
    #   inversion's Fermat ladder needs the _PM2 digit row even when the
    #   acceptance pows are pruned)
    *rest,
    schnorr_free: bool = False,
    point_form: str = "projective",
):
    affine = point_form == "affine"
    if affine:
        (euler_ref, out_ref, qtab_ref, lqtab_ref, ztab_ref, ptab_ref,
         powtab_ref) = rest
    elif schnorr_free:
        euler_ref = powtab_ref = None
        out_ref, qtab_ref, lqtab_ref = rest
    else:
        euler_ref, out_ref, qtab_ref, lqtab_ref, powtab_ref = rest
    b = out_ref.shape[-1]
    # MSM structure from the ref shapes (ISSUE 12): table entries and
    # window width off the Q-table scratch, window rounds off the digit
    # stream — so ONE kernel body serves both widths.
    ent_n = int(qtab_ref.shape[0])
    wbits = (ent_n - 1).bit_length()
    nwin = int(d1a_ref.shape[0])
    L = F.NLIMBS
    zero = jnp.zeros((L, b), jnp.int32)
    one = jnp.concatenate(
        [jnp.ones((1, b), jnp.int32), jnp.zeros((L - 1, b), jnp.int32)], axis=0
    )
    inf = jnp.stack([zero, one, zero], axis=0)

    qx = qx_ref[:]
    qy = qy_ref[:]

    # ---- windowed pow machinery (shared by the affine batch inversion
    # and the jacobi/parity acceptance pows): 16-entry power table of
    # ``t`` in powtab, then 64 4-bit windows with digits from SMEM row
    # ``row`` of euler_ref.  fori_loop bodies (one mul each) instead of
    # unrolled chains: the straight-line form dominated Mosaic compile
    # time (the r3 finding; benchmarks/mosaic_diag.py's ``pow_descan``
    # case probes whether a de-scanned static-digit ladder lowers too).
    def pow_build_table(t):
        powtab_ref[0] = one
        powtab_ref[1] = t

        def pow_build(k, carry):
            powtab_ref[pl.ds(k, 1)] = PF.mul(
                powtab_ref[pl.ds(k - 1, 1)][0], t
            )[None]
            return carry

        lax.fori_loop(2, 16, pow_build, 0)

    def pow_window_for(row):
        def pow_window(w, pacc):
            pacc = PF.sqr(PF.sqr(PF.sqr(PF.sqr(pacc))))
            d = euler_ref[row, w]
            sel = None
            for tv in range(16):
                contrib = jnp.where(d == tv, powtab_ref[tv], 0)
                sel = contrib if sel is None else sel + contrib
            return PF.mul(pacc, sel)

        return pow_window

    # ---- per-signature Q table: [O, Q, 2Q, ..., 15Q] ----------------------
    # fori_loop bodies (one pt_add / one mul) instead of unrolled chains:
    # the straight-line table build dominated Mosaic compile time otherwise.
    # Projective: 3-coordinate entries straight into qtab.  Affine (ISSUE
    # 8): X/Y into the 2-coordinate qtab, Z into ztab, then one
    # Montgomery-trick batch inversion per lane (prefix products in ptab,
    # ONE shared Fermat Z^(p-2) ladder, suffix pass) normalizes every
    # entry to affine in place.
    q1 = jnp.stack([qx, qy, one], axis=0)
    if affine:
        qtab_ref[0] = jnp.stack([zero, one], axis=0)
        qtab_ref[1] = q1[0:2]

        def build_step(k, acc):
            nxt = pt_add(acc, q1, F=PF)
            qtab_ref[pl.ds(k, 1)] = nxt[0:2][None]
            ztab_ref[pl.ds(k, 1)] = nxt[2][None]
            return nxt

        lax.fori_loop(2, ent_n, build_step, q1)

        # prefix products ptab[k] = z_2 * ... * z_k (ptab[1] = 1)
        ptab_ref[1] = one
        ptab_ref[2] = ztab_ref[2]

        def prefix_step(k, carry):
            ptab_ref[pl.ds(k, 1)] = PF.mul(
                ptab_ref[pl.ds(k - 1, 1)][0], ztab_ref[pl.ds(k, 1)][0]
            )[None]
            return carry

        lax.fori_loop(3, ent_n, prefix_step, 0)

        # one shared Fermat ladder: (z_2 ... z_{ent_n-1})^(p-2)
        pow_build_table(ptab_ref[ent_n - 1])
        inv = lax.fori_loop(0, 64, pow_window_for(1), one)

        # suffix pass: entering k, run = (z_2 ... z_k)^-1
        def suffix_step(i, run):
            k = ent_n - 1 - i
            zinv = PF.mul(run, ptab_ref[pl.ds(k - 1, 1)][0])
            e = qtab_ref[pl.ds(k, 1)][0]
            qtab_ref[pl.ds(k, 1)] = jnp.stack(
                [PF.mul(e[0], zinv), PF.mul(e[1], zinv)], axis=0
            )[None]
            return PF.mul(run, ztab_ref[pl.ds(k, 1)][0])

        lax.fori_loop(0, ent_n - 2, suffix_step, inv)
    else:
        qtab_ref[0] = inf
        qtab_ref[1] = q1

        def build_step(k, acc):
            nxt = pt_add(acc, q1, F=PF)
            qtab_ref[pl.ds(k, 1)] = nxt[None]
            return nxt

        lax.fori_loop(2, ent_n, build_step, q1)

    # ---- λQ table: the endomorphism is additive, so scale each X by β ----
    beta = PF.const_col(_BETA_LIMBS, b)

    def lam_step(k, carry):
        e = qtab_ref[pl.ds(k, 1)][0]
        lx = PF.mul(e[0], beta)
        lqtab_ref[pl.ds(k, 1)] = jnp.concatenate([lx[None], e[1:]], axis=0)[
            None
        ]
        return carry

    lax.fori_loop(0, ent_n, lam_step, 0)

    g_tab = g_ref[:]
    lg_tab = lg_ref[:]

    n1a = negs_ref[0:1]
    n1b = negs_ref[1:2]
    n2a = negs_ref[2:3]
    n2b = negs_ref[3:4]

    # ---- Shamir/GLV window loop ------------------------------------------
    if affine:
        # mixed additions against 2-coordinate tables; digit 0 (the
        # infinity entry, unrepresentable in affine) keeps the
        # accumulator through a branch-free select
        def window(w, acc):
            for _ in range(wbits):
                acc = pt_double(acc, F=PF)
            for tab, dref, neg in (
                (g_tab, d1a_ref, n1a),
                (lg_tab, d1b_ref, n1b),
                (qtab_ref, d2a_ref, n2a),
                (lqtab_ref, d2b_ref, n2b),
            ):
                d = dref[pl.ds(w, 1)]
                sel = _signed(_select16(tab, d), neg)
                nxt = pt_add_mixed(acc, sel, F=PF)
                acc = jnp.where(d == 0, acc, nxt)
            return acc

    else:
        def window(w, acc):
            for _ in range(wbits):
                acc = pt_double(acc, F=PF)
            da = d1a_ref[pl.ds(w, 1)]
            db = d1b_ref[pl.ds(w, 1)]
            dc = d2a_ref[pl.ds(w, 1)]
            dd = d2b_ref[pl.ds(w, 1)]
            acc = pt_add(acc, _signed(_select16(g_tab, da), n1a), F=PF)
            acc = pt_add(acc, _signed(_select16(lg_tab, db), n1b), F=PF)
            acc = pt_add(acc, _signed(_select16(qtab_ref, dc), n2a), F=PF)
            acc = pt_add(acc, _signed(_select16(lqtab_ref, dd), n2b), F=PF)
            return acc

    acc = lax.fori_loop(0, nwin, window, inf)

    # ---- projective check x(R) ∈ {r, r+n} and curve membership ------------
    X, Y, Z = acc[0], acc[1], acc[2]
    not_inf = ~PF.is_zero(Z)
    m1 = PF.eq(X, PF.mul(r1_ref[:], Z))
    m2 = PF.eq(X, PF.mul(r2_ref[:], Z)) & (flags_ref[0:1] != 0)
    seven = PF.const_col(_SEVEN_LIMBS, b)
    on_curve = PF.eq(PF.sqr(qy), PF.mul(PF.sqr(qx), qx) + seven)

    # ---- jacobi(y(R)) for Schnorr lanes -----------------------------------
    # y = Y/Z so jacobi(y) = jacobi(Y·Z); Euler pow t^((p-1)/2) == 1 as a
    # windowed 4-bit exponentiation: the digit sequence is a compile-time
    # constant (_EULER_DIGITS), the 16-entry power table lives in VMEM.
    #
    # ``schnorr_free`` (STATIC, set by the dispatcher when no lane in the
    # batch carries a Schnorr/BIP340 flag — the common real shape: BTC
    # mainnet has no BCH Schnorr, IBD-era blocks no taproot, and the
    # ECDSA-only headline bench workload) prunes BOTH acceptance pows at
    # trace time; the placeholders below are never selected by algo_ok.
    if schnorr_free:
        jac_ok = jnp.ones((1, b), dtype=jnp.bool_)
        even_ok = jnp.ones((1, b), dtype=jnp.bool_)
    else:
        # jacobi(Y·Z) via the Euler pow (digit row 0), rebuilding the
        # power table (the affine variant used it for the inversion)
        pow_build_table(PF.mul(Y, Z))
        pacc = lax.fori_loop(0, 64, pow_window_for(0), one)
        jac_ok = PF.eq(pacc, one)

        # BIP340 evenness: affine y = Y/Z via Fermat inverse Z^(p-2)
        # (digit row 1), then the canonical representative's low bit
        pow_build_table(Z)
        zinv = lax.fori_loop(0, 64, pow_window_for(1), one)
        y_aff = PF.mul(Y, zinv)
        even_ok = (PF.canonical(y_aff)[0:1] & 1) == 0

    is_sch = flags_ref[2:3] != 0
    is_b340 = flags_ref[3:4] != 0
    algo_ok = jnp.where(
        is_b340, m1 & even_ok, jnp.where(is_sch, m1 & jac_ok, m1 | m2)
    )
    valid = (flags_ref[1:2] != 0) & on_curve & not_inf & algo_ok
    out_ref[:] = valid.astype(jnp.int32)


def verify_blocked_impl(
    d1a,
    d1b,
    d2a,
    d2b,
    n1a,
    n1b,
    n2a,
    n2b,
    qx,
    qy,
    r1,
    r2,
    r2_valid,
    host_valid,
    schnorr,
    bip340,
    *,
    interpret: bool = False,
    block: int = BLOCK,
    schnorr_free: bool = False,
    point_form: "str | None" = None,
) -> jnp.ndarray:
    """Un-jitted kernel body — reused inside shard_map by multichip.py
    (a jitted callee cannot be shard_mapped).  See :func:`verify_blocked`.

    ``schnorr_free`` statically prunes the jacobi/parity acceptance pows
    (see _kernel) — only set it when NO lane carries a schnorr/bip340
    flag; verdicts are bit-identical for such batches.  ``point_form``
    selects the projective or affine MSM variant (None = the process
    global, curve.point_form()); verdicts are bit-identical across
    forms."""
    if point_form is None:
        point_form = _active_point_form()
    # Trace-time int32 bound audit of the live formulas (ISSUE 12): the
    # Pallas and XLA programs share curve.py's bodies, so the one cached
    # pure-Python replay covers this path too.
    from . import bounds as _bounds

    _bounds.assert_formulas_safe()
    affine = point_form == "affine"
    blk = block
    bsz = qx.shape[-1]
    if bsz % blk != 0:
        raise ValueError(f"batch {bsz} not a multiple of BLOCK={blk}")
    grid = bsz // blk
    nwin = int(d1a.shape[0])
    wb = window_bits()
    ent_n = 1 << wb
    from .kernel import windows as _windows

    # data/mode consistency (same guard as the XLA path): digit rows
    # prepped at one window width under another width's global would
    # produce silently wrong verdicts, not a shape error.
    if nwin != _windows():
        raise RuntimeError(
            f"digit arrays carry {nwin} window rows but the active "
            f"window_bits={wb} needs {_windows()}: re-prepare the "
            "batch under the active mode"
        )
    # Constant G/λG tables for the active width: 4-bit keeps the module
    # constants; 5-bit fetches the 32-entry tables (ONE shared VMEM copy
    # — see _const_table).
    if wb == 4:
        g_np = _G_AFF_NP if affine else _G_NP
        lg_np = _LG_AFF_NP if affine else _LG_NP
    else:
        g_full, lg_full, g_aff, lg_aff = window_tables()
        g_np = np.asarray(g_aff if affine else g_full)
        lg_np = np.asarray(lg_aff if affine else lg_full)
    tab_lanes = 1 if wb == 5 else blk

    negs = jnp.stack(
        [a.astype(jnp.int32) for a in (n1a, n1b, n2a, n2b)], axis=0
    )
    flags = jnp.stack(
        [
            r2_valid.astype(jnp.int32),
            host_valid.astype(jnp.int32),
            schnorr.astype(jnp.int32),
            bip340.astype(jnp.int32),
        ],
        axis=0,
    )

    def col(rows):  # BlockSpec for a (rows, B) input walked along lanes
        return pl.BlockSpec((rows, blk), lambda i: (0, i))

    coords = 2 if affine else 3
    tab_spec = pl.BlockSpec(
        (ent_n, coords, F.NLIMBS, tab_lanes), lambda i: (0, 0, 0, 0)
    )
    in_specs = [
        tab_spec,
        tab_spec,
        col(nwin),
        col(nwin),
        col(nwin),
        col(nwin),
        col(4),
        col(F.NLIMBS),
        col(F.NLIMBS),
        col(F.NLIMBS),
        col(F.NLIMBS),
        col(4),
    ]
    operands = [
        _const_table(g_np, blk),
        _const_table(lg_np, blk),
        d1a.astype(jnp.int32),
        d1b.astype(jnp.int32),
        d2a.astype(jnp.int32),
        d2b.astype(jnp.int32),
        negs,
        qx,
        qy,
        r1,
        r2,
        flags,
    ]
    scratch = [
        pltpu.VMEM((ent_n, coords, F.NLIMBS, blk), jnp.int32),
        pltpu.VMEM((ent_n, coords, F.NLIMBS, blk), jnp.int32),
    ]
    if affine or not schnorr_free:
        # Exponent digits live in SMEM: the kernel reads them with
        # dynamic scalar indices inside the window fori_loop, which is
        # scalar memory's canonical job — a VMEM block read that way
        # is the r5 Mosaic-outage suspect (benchmarks/mosaic_diag.py
        # probes both placements).  The projective schnorr_free variant
        # omits the digits AND the (16, L, blk) pow-table scratch
        # entirely; the affine variants always need both (the batch
        # inversion's Fermat ladder reads the _PM2 digit row).
        in_specs.append(
            pl.BlockSpec((2, 64), lambda i: (0, 0), memory_space=pltpu.SMEM)
        )
        operands.append(
            jnp.stack(
                [jnp.asarray(_EULER_DIGITS), jnp.asarray(_PM2_DIGITS)],
                axis=0,
            )
        )
    if affine:
        # Z column + prefix-product tables for the batch inversion: the
        # 2-coordinate main tables free exactly 2 x (ent, L, blk) planes,
        # so the affine variant's VMEM high-water stays ~level with the
        # projective one's.
        scratch.append(pltpu.VMEM((ent_n, F.NLIMBS, blk), jnp.int32))
        scratch.append(pltpu.VMEM((ent_n, F.NLIMBS, blk), jnp.int32))
    if affine or not schnorr_free:
        # pow-ladder table: ALWAYS 16 entries (the constant-exponent
        # ladders stay 4-bit regardless of the MSM window width)
        scratch.append(pltpu.VMEM((16, F.NLIMBS, blk), jnp.int32))
    out = pl.pallas_call(
        partial(_kernel, schnorr_free=schnorr_free, point_form=point_form),
        out_shape=jax.ShapeDtypeStruct((1, bsz), jnp.int32),
        grid=(grid,),
        in_specs=in_specs,
        out_specs=col(1),
        scratch_shapes=scratch,
        interpret=interpret,
    )(*operands)
    return out[0].astype(jnp.bool_)


def _active_point_form() -> str:
    return point_form()


@partial(
    jax.jit,
    static_argnames=(
        "interpret", "block", "schnorr_free", "point_form", "field_modes",
    ),
)
def _verify_blocked_jit(*args, interpret: bool = False, block: int = BLOCK,
                        schnorr_free: bool = False, point_form=None,
                        field_modes=None):
    # ``field_modes`` is only a jit-cache key (kernel.structure_modes():
    # field formulation + select/ladder shape — the point form rides the
    # EXPLICIT static arg, so including the global form here too would
    # double-encode it): the knobs are process globals read at trace
    # time, so a flip must force a retrace instead of reusing the stale
    # executable.
    del field_modes
    return verify_blocked_impl(*args, interpret=interpret, block=block,
                               schnorr_free=schnorr_free,
                               point_form=point_form)


def verify_blocked(*args, interpret: bool = False, block: int = BLOCK,
                   schnorr_free: bool = False,
                   point_form: "str | None" = None):
    """Drop-in replacement for :func:`kernel.verify_core` (same argument
    order — PreparedBatch.device_args) running the Pallas kernel over
    lane blocks of ``block`` (default BLOCK; tests use small blocks in
    interpret mode).  Batch size must be a multiple of the block size
    (prepare_batch pads to the engine's fixed shape).  ``schnorr_free``
    selects the ECDSA-only program variant (acceptance pows pruned at
    trace time) — callers must only set it when no lane carries a
    schnorr/bip340 flag (kernel._dispatch_prep derives it from the
    prepared batch).  ``point_form`` selects the projective/affine MSM
    (None = the process-global curve.point_form()).  Jit-cached per
    explicit point form + kernel.structure_modes()."""
    if point_form is None:
        point_form = _active_point_form()
    return _verify_blocked_jit(*args, interpret=interpret, block=block,
                               schnorr_free=schnorr_free,
                               point_form=point_form,
                               field_modes=structure_modes())
