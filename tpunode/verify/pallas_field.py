"""Mosaic-friendly secp256k1 field arithmetic for the Pallas verify kernel.

Same radix-11 / 24-limb representation, bounds, and contracts as
:mod:`field` (see its docstrings — they are the load-bearing audit), but
expressed in the subset of jnp that Pallas/Mosaic lowers well inside a TPU
kernel:

* no ``.at[...]`` dynamic-update-slices — limb shifts are static
  ``concatenate`` of row slices (sublane shifts in hardware);
* no broadcast-from-(L, 1) constants — constant columns are built with
  ``jnp.full`` rows (folded at compile time);
* fold constants are Python scalars, not device arrays.

Why it exists (the round-3 performance finding): under plain XLA the
verify kernel is per-op-overhead/HBM bound — a chained field mul costs
~430 us at batch 8192 (~0.5% VPU utilization) because every one of its
~80 small (24, B) ops round-trips through HBM.  Inside one Pallas program
the whole MSM loop runs out of VMEM/registers, so these same formulas
compile to straight-line vector code with no per-op dispatch.

Functions mirror :mod:`field`'s API (``mul``/``mul_t``/``mul_small_red``/
``sqr``/``sqr_t``/``canonical``/``is_zero``/``eq``) so :mod:`curve`'s
audited RCB formulas can be reused unchanged via their ``F=`` parameter —
including the limb-product formulation knobs: :func:`field.mul_mode` /
:func:`field.sqr_mode` select shift-add vs ``dot_general`` and the
dedicated half-product squaring here exactly as in :mod:`field` (the
dispatch reads the same process-global modes at trace time; pallas
programs key their jit caches on ``field.field_modes()``).  Exactness is
pinned against :mod:`field` property-style in tests/test_pallas_kernel.py.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from . import field as F

RADIX = F.RADIX
NLIMBS = F.NLIMBS
MASK = F.MASK

_FOLD = [int(x) for x in F.FOLD]  # 2^264 mod p, 4 limbs, as Python ints
_C = [int(x) for x in F.C_LIMBS]  # 2^256 mod p, 4 limbs
_FN = F._FN
_P_LIMBS = [int(x) for x in F.P_LIMBS[:, 0]]
_BIG_LIMBS = [int(x) for x in F._BIG[:, 0]]  # 25 limbs


def _z(rows: int, b: int) -> jnp.ndarray:
    return jnp.zeros((rows, b), jnp.int32)


def _cat(*parts: jnp.ndarray) -> jnp.ndarray:
    """Sublane concatenate, dropping zero-row segments (Mosaic requires
    positive vector sizes; a (0, B) operand is a lowering error)."""
    live = [p for p in parts if p.shape[0] > 0]
    return live[0] if len(live) == 1 else jnp.concatenate(live, axis=0)


def const_col(ints, b: int) -> jnp.ndarray:
    """Constant limb column broadcast over ``b`` lanes, shape (len, b)."""
    return jnp.concatenate(
        [jnp.full((1, b), int(v), jnp.int32) for v in ints], axis=0
    )


def _carry(x: jnp.ndarray, rounds: int) -> jnp.ndarray:
    """field._carry in concatenate form: exact for negative limbs, top
    limb keeps its overflow in place."""
    b = x.shape[-1]
    for _ in range(rounds):
        lo = x & MASK
        hi = x >> RADIX
        y = lo + _cat(_z(1, b), hi[:-1])
        x = _cat(y[:-1], y[-1:] + (hi[-1:] << RADIX))
    return x


def tighten(x: jnp.ndarray, rounds: int = 1) -> jnp.ndarray:
    return _carry(x, rounds)


def _tree_sum(terms: list) -> jnp.ndarray:
    while len(terms) > 1:  # balanced reduction: short dependency chains
        terms = [
            terms[j] + terms[j + 1] if j + 1 < len(terms) else terms[j]
            for j in range(0, len(terms), 2)
        ]
    return terms[0]


def _conv(a: jnp.ndarray, b_: jnp.ndarray) -> jnp.ndarray:
    """Limb convolution (24, B) x (24, B) -> (47, B) as a tree sum of 24
    sublane-shifted broadcast products (same partials as field._conv)."""
    b = a.shape[-1]
    terms = []
    for i in range(NLIMBS):
        t = a[i : i + 1] * b_  # (NLIMBS, B): row-broadcast multiply
        terms.append(_cat(_z(i, b), t, _z(NLIMBS - 1 - i, b)))
    return _tree_sum(terms)


def _mul_scatter() -> jnp.ndarray:
    """The (47, 576) anti-diagonal scatter matrix (field._MUL_SCATTER),
    built from iota + integer ops INSIDE the traced computation: a pallas
    kernel may not capture non-scalar constants, and this way the Mosaic
    and XLA programs share one construction.  Column c encodes the pair
    (i, j) = (c // 24, c % 24); row k selects i + j == k."""
    shape = (2 * NLIMBS - 1, NLIMBS * NLIMBS)
    k = lax.broadcasted_iota(jnp.int32, shape, 0)
    c = lax.broadcasted_iota(jnp.int32, shape, 1)
    return ((c // NLIMBS + c % NLIMBS) == k).astype(jnp.int32)


def _conv_dot(a: jnp.ndarray, b_: jnp.ndarray) -> jnp.ndarray:
    """field._conv_dot in concatenate form: the (576, B) partial-product
    rows are a sublane concat of 24 row-broadcast multiplies (no gathers),
    contracted against the anti-diagonal scatter matrix with one
    dot_general — the MXU-mapped formulation."""
    p = _cat(*[a[i : i + 1] * b_ for i in range(NLIMBS)])
    return lax.dot_general(
        _mul_scatter(),
        p,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )


def _sqr_conv(a: jnp.ndarray) -> jnp.ndarray:
    """field._sqr_conv in concatenate form: out[i+j] += (2-δij)·a_i·a_j
    over i <= j — ~half the partial products, per-position sums identical
    to _conv(a, a)'s."""
    b = a.shape[-1]
    d = a + a
    terms = []
    for i in range(NLIMBS):
        row = a[i : i + 1]
        t = row * (_cat(row, d[i + 1 :]) if i + 1 < NLIMBS else row)
        terms.append(_cat(_z(2 * i, b), t, _z(NLIMBS - 1 - i, b)))
    return _tree_sum(terms)


def _sqr_dot(a: jnp.ndarray) -> jnp.ndarray:
    """field._sqr_dot in concatenate form: the i <= j partial rows (cross
    terms pre-doubled, j < i positions zero-padded so the pair layout and
    scatter match _conv_dot's) contracted with the shared anti-diagonal
    matrix.  ~Half the real multiplies; the contraction stays 576 wide —
    on a real MXU the matmul cost is shape-bound, so sharing one scatter
    costs nothing there while keeping the kernel free of a second
    constant construction."""
    b = a.shape[-1]
    d = a + a
    rows = []
    for i in range(NLIMBS):
        row = a[i : i + 1]
        t = row * (_cat(row, d[i + 1 :]) if i + 1 < NLIMBS else row)
        rows.append(t if i == 0 else _cat(_z(i, b), t))
    return lax.dot_general(
        _mul_scatter(),
        _cat(*rows),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )


def _convolve(a: jnp.ndarray, b_: jnp.ndarray) -> jnp.ndarray:
    return _conv(a, b_) if F.mul_mode() == "shift_add" else _conv_dot(a, b_)


def _square_conv(a: jnp.ndarray) -> jnp.ndarray:
    if F.sqr_mode() == "mul":
        return _convolve(a, a)
    return _sqr_conv(a) if F.mul_mode() == "shift_add" else _sqr_dot(a)


def _pad(x: jnp.ndarray, n: int) -> jnp.ndarray:
    return _cat(x, _z(n, x.shape[-1]))


def _fold_once(wide: jnp.ndarray) -> jnp.ndarray:
    """field._fold_once with scalar fold constants (same bounds)."""
    b = wide.shape[-1]
    lo = wide[:NLIMBS]
    hi = wide[NLIMBS:]
    k = hi.shape[0]
    width = max(NLIMBS, k + _FN - 1)
    out = _pad(lo, width - NLIMBS)
    for i in range(_FN):
        out = out + _cat(_z(i, b), _FOLD[i] * hi, _z(width - i - k, b))
    if out.shape[0] > NLIMBS:
        out = _carry(_pad(out, 1), 2)
        return _fold_once(out)
    return out


def _fold_top(x: jnp.ndarray) -> jnp.ndarray:
    """field._fold_top: carry into a 25th limb, fold it back via
    2^264 ≡ FOLD (mod p)."""
    b = x.shape[-1]
    x = _carry(_pad(x, 1), 1)
    hi = x[NLIMBS : NLIMBS + 1]  # (1, B)
    x = x[:NLIMBS]
    fold_rows = _cat(*[_FOLD[i] * hi for i in range(_FN)])
    return x + _cat(fold_rows, _z(NLIMBS - _FN, b))


def _tight24(a: jnp.ndarray) -> jnp.ndarray:
    return _carry(_fold_top(a), 1)


def _reduce_wide(wide: jnp.ndarray) -> jnp.ndarray:
    """field._reduce_wide: the shared 47-limb -> 24-limb reduction tail."""
    wide = _carry(_pad(wide, 1), 2)
    x = _fold_once(wide)
    x = _carry(x, 1)
    return _carry(_fold_top(x), 1)


def mul(a: jnp.ndarray, b_: jnp.ndarray) -> jnp.ndarray:
    """Modular multiply — identical contract to field.mul."""
    a = _carry(a, 1)
    b_ = _carry(b_, 1)
    return _reduce_wide(_convolve(a, b_))


def mul_t(a: jnp.ndarray, b_: jnp.ndarray) -> jnp.ndarray:
    """field.mul_t: pre-tight operands (every |limb| <= 2^13)."""
    return _reduce_wide(_convolve(a, b_))


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    """field.sqr: half-product squaring under the default sqr mode."""
    a = _carry(a, 1)
    return _reduce_wide(_square_conv(a))


def sqr_t(a: jnp.ndarray) -> jnp.ndarray:
    """field.sqr_t: squaring for pre-tight operands (mul_t's contract)."""
    return _reduce_wide(_square_conv(a))


def mul_small_red(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """field.mul_small_red: scale by small constant and fold the top."""
    return _fold_top(a * k)


# ---------- lazy-reduction wide-accumulator API (ISSUE 12) ----------------
# Mirrors field.py's wide API in concatenate form so curve.py's lazy
# formula bodies run unchanged inside the Pallas kernel (the same ``F=``
# seam).  Safety: identical op sequences, identical bounds — the ONE
# bound-tracker audit (tpunode.verify.bounds) covers both namespaces.


def mul_wide(a: jnp.ndarray, b_: jnp.ndarray) -> jnp.ndarray:
    """field.mul_wide: mul minus the reduction tail -> (47, B) wide."""
    return _convolve(_carry(a, 1), _carry(b_, 1))


def mul_t_wide(a: jnp.ndarray, b_: jnp.ndarray) -> jnp.ndarray:
    """field.mul_t_wide: pre-tight operands, bare convolution."""
    return _convolve(a, b_)


def sqr_wide(a: jnp.ndarray) -> jnp.ndarray:
    """field.sqr_wide."""
    return _square_conv(_carry(a, 1))


def sqr_t_wide(a: jnp.ndarray) -> jnp.ndarray:
    """field.sqr_t_wide."""
    return _square_conv(a)


def acc_add(*wides: jnp.ndarray) -> jnp.ndarray:
    """field.acc_add: limb-wise sum of unreduced wides."""
    out = wides[0]
    for w in wides[1:]:
        out = out + w
    return out


def reduce_wide(wide: jnp.ndarray) -> jnp.ndarray:
    """field.reduce_wide: the one reduction a lazy expression pays."""
    return _reduce_wide(wide)


def reduce_wide_loose(wide: jnp.ndarray) -> jnp.ndarray:
    """field.reduce_wide_loose: the reduction tail minus its final carry
    round — the lazy pipeline's default reduction."""
    wide = _carry(_pad(wide, 1), 2)
    x = _fold_once(wide)
    x = _carry(x, 1)
    return _fold_top(x)


# ---------- exact canonicalization & comparisons ----------


def canonical(x: jnp.ndarray) -> jnp.ndarray:
    """field.canonical in Mosaic-friendly form (same algorithm/bounds)."""
    b = x.shape[-1]
    x = _tight24(x)
    wide = _pad(x, 1) + const_col(_BIG_LIMBS, b)
    wide = _carry(wide, NLIMBS + 4)
    hi = (wide[NLIMBS - 1 : NLIMBS] >> 3) + (wide[NLIMBS : NLIMBS + 1] << 8)
    top = wide[NLIMBS - 1 : NLIMBS] & 7
    lo = _cat(wide[: NLIMBS - 1], top)
    c_rows = _cat(*[_C[i] * hi for i in range(_FN)])
    lo = lo + _cat(c_rows, _z(NLIMBS - _FN, b))
    lo = _carry(lo, NLIMBS + 2)
    p_col = const_col(_P_LIMBS, b)
    for _ in range(2):
        ge_p = _ge_p(lo)  # (1, B) bool
        lo = lo - jnp.where(ge_p, p_col, 0)
        lo = _carry(lo, NLIMBS + 1)
    return lo


def _ge_p(a: jnp.ndarray) -> jnp.ndarray:
    """Lexicographic a >= p over canonical nonnegative limbs -> (1, B)."""
    gt = jnp.zeros((1, a.shape[-1]), jnp.bool_)
    eq = jnp.ones((1, a.shape[-1]), jnp.bool_)
    for i in range(NLIMBS - 1, -1, -1):
        ai = a[i : i + 1]
        gt = gt | (eq & (ai > _P_LIMBS[i]))
        eq = eq & (ai == _P_LIMBS[i])
    return gt | eq


def is_zero(x: jnp.ndarray) -> jnp.ndarray:
    """value ≡ 0 (mod p)?  Exact.  Returns (1, B) bool."""
    c = canonical(x)
    return jnp.sum(jnp.abs(c), axis=0, keepdims=True) == 0


def eq(a: jnp.ndarray, b_: jnp.ndarray) -> jnp.ndarray:
    return is_zero(a - b_)
