"""Async batch verification engine: the queue between ingest and the TPU.

The north-star integration point (BASELINE.json): block/mempool ingest
submits VerifyItem tuples (ECDSA / BCH Schnorr / BIP340 — see
tpunode/verify/raw.py); the engine accumulates them into
fixed-shape batches (static shapes = no XLA recompilation), dispatches to
the TPU kernel — or the C++ CPU engine for small batches / no device — and
resolves per-item futures.

Streaming pipeline (ISSUE 10): queued submissions are no longer dispatched
FIFO-coalesced — a lane-packing scheduler (:mod:`tpunode.verify.sched`)
bins pending payloads into full ``device_batch`` lanes across submission
boundaries with priority classes (block > mempool > bulk) and a
max-linger deadline, and up to ``VerifyConfig.pipeline_depth`` packed
lanes are in flight at once, each in its own worker thread.  JAX device
dispatch is asynchronous, so lane N+1's host prep and transfer overlap
lane N's kernel; the asyncio event loop (the P2P side) never blocks.
``pipeline_depth=1`` restores strictly serial dispatch for A/B runs.
Small remainders pack with later submissions instead of defaulting to the
CPU rung; ``min_tpu_batch`` is a shed-only floor applied when a lingering
partial lane finally dispatches.  With ``mesh_devices > 1`` the device
rung shards packed lanes over a local device mesh
(:func:`multichip.dispatch_raw_sharded`).

Pod scale (ISSUE 13): ``mesh_hosts >= 2`` promotes the pipeline into a
cross-host fleet — the device set is carved into that many host groups
(a ``(host, chip)`` hybrid mesh, :func:`multichip.make_hybrid_mesh`),
each host runs ``pipeline_depth`` dispatch workers pulling packed lanes
from a work-stealing :class:`sched.FleetDispatcher` (idle hosts steal
whole lanes from the deepest peer queue), and each host carries its OWN
circuit breaker and device sub-mesh so one sick host degrades alone.
Degradation is chip-by-chip: a device loss shrinks that host's sub-mesh
to the largest still-healthy half (re-grown when its breaker's canary
closes); a host partition re-queues the lane onto a healthy peer
(exactly once — the lane delivered nothing), deactivates the host, and
a cooldown-paced canary rejoin re-grows the fleet.  With every host
dark, lanes fall through the local ladder so waiters still resolve.

Device survival discipline (VERDICT r2 item 4 + ISSUE 7): the TPU path is
only used after an off-queue **warmup** (backend init + XLA compile at the
fixed batch shape + a verdict cross-check against the oracle) completes in
a background thread.  Until then batches flow to the CPU engine, so a box
with a broken or slow TPU backend still produces verdicts with nothing
blocked and the decision logged; a failed warmup is re-probed on a timer
(``warmup_retry``), never terminal.  Compiles go through a persistent
compilation cache so a restart reuses earlier work.

Self-healing dispatch (ISSUE 7): a batch that fails on one backend
re-dispatches down the ladder (tpu -> cpu-native -> python oracle), so
waiters get verdicts — not exceptions — for transient faults; only a
batch that fails on EVERY rung fails its waiters (and only its own: the
queue loop survives to serve the next batch).  Device-rung failures feed
a :class:`CircuitBreaker` (``ready -> degraded -> open -> probing ->
ready``): repeated failures inside a window open the breaker and route
all traffic to the CPU, then a periodic half-open canary batch re-probes
the device and restores the fast path when it recovers.  The state
machine is observable as ``verify.breaker`` events, the
``verify.breaker_state`` gauge, engine ``stats()`` and ``/health``.

Mirrors the role the reference's synchronous libsecp256k1 callout plays, but
asynchronous and batched (SURVEY.md §2.3: this IS the data-parallel north
star path).
"""

from __future__ import annotations

import asyncio
import collections
import contextlib
import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from .. import threadsan
from ..actors import spawn_supervised
from ..chaos import ChaosPartition, chaos
from ..events import events
from ..metrics import metrics
from ..trace import span
from ..tracectx import activate as _activate_trace, current as _trace_current
from .ecdsa_cpu import Point, verify_batch_cpu
from .raw import as_raw_batch, concat_raw
from .sched import (
    OCCUPANCY_BUCKETS as _OCCUPANCY_BUCKETS,
    FleetDispatcher,
    LanePacker,
    PackedLane,
    Submission,
)

__all__ = [
    "CircuitBreaker",
    "HostLost",
    "VerifyConfig",
    "VerifyEngine",
    "VerifyItem",
    "enable_compile_cache",
]


class HostLost(RuntimeError):
    """A fleet host is unreachable (ISSUE 13): the dispatch ladder must
    NOT serve the lane locally on this host's behalf — the worker
    re-queues it onto a healthy peer and deactivates the host.  Today
    raised for an injected ``mesh.dispatch:partition``; a real pod's
    RPC/transport failures map here too."""

# (pubkey, z, r, s) for ECDSA; 5-tuples append "schnorr" (BCH) or
# "bip340" (taproot) with the precomputed challenge in the z position.
VerifyItem = tuple  # see raw.pack_items for the per-algorithm rules

log = logging.getLogger("tpunode.verify")

_DEFAULT_CACHE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".jax_cache",
)


def enable_compile_cache(path: Optional[str] = None) -> None:
    """Point JAX's persistent compilation cache at ``path`` (idempotent).

    The kernel's XLA program is large; a cold compile can take minutes on
    some backends.  With the cache enabled, any process on this machine
    (engine warmup, bench.py, tests) reuses the first successful compile.
    """
    import jax

    target = path or os.environ.get("TPUNODE_JAX_CACHE") or _DEFAULT_CACHE
    try:
        if not jax.config.jax_compilation_cache_dir:
            jax.config.update("jax_compilation_cache_dir", target)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # cache is an optimization, never a hard failure
        log.debug("compilation cache unavailable: %s", e)


class BigShapeFailed(RuntimeError):
    """Warmup outcome: the small device shape compiled and cross-checked
    but the steady-state ``device_batch`` shape did not compile.  Carries
    the device kind so the engine can stay on the device path with
    ``device_batch`` degraded to ``batch_size``."""

    def __init__(self, kind: str, error: str):
        super().__init__(error)
        self.kind = kind


def _device_warmup(batch_size: int, device_batch: int = 0) -> str:
    """Default warmup body (runs in a daemon thread): init the backend,
    compile the kernel at the engine's fixed batch shapes (the small
    ``batch_size`` shape first so readiness comes early, then the big
    ``device_batch`` steady-state shape), and cross-check a small batch
    against the oracle.  Returns the device kind string.  Raises on any
    failure — including a verdict mismatch, which must disqualify the
    device path permanently."""
    import jax

    enable_compile_cache()
    devs = [d for d in jax.devices() if d.platform == "tpu"]
    if not devs:
        raise RuntimeError("no TPU device visible")
    from .ecdsa_cpu import (
        CURVE_N,
        GENERATOR,
        bip340_challenge,
        lift_x,
        point_mul,
        schnorr_challenge,
        sign,
        sign_bip340,
        sign_schnorr,
    )
    from .kernel import verify_batch_tpu

    items = []
    expect = []
    for i in range(8):
        priv = (0xA11CE + i) % CURVE_N
        pub = point_mul(priv, GENERATOR)
        z = (0xD00D << i) % CURVE_N
        # every algorithm's lane compiles + cross-checks in the one program
        if i % 4 == 1:
            r, s = sign_schnorr(priv, z, 0xC0FFEE + i)
            if i % 3 == 2:
                z ^= 1
            items.append((pub, schnorr_challenge(r, pub, z), r, s, "schnorr"))
            expect.append(i % 3 != 2)
            continue
        if i % 4 == 3:
            r, s = sign_bip340(priv, z, 0xC0FFEE + i)
            if i % 3 == 2:
                z ^= 1
            items.append(
                (lift_x(pub.x), bip340_challenge(r, pub.x, z), r, s, "bip340")
            )
            expect.append(i % 3 != 2)
            continue
        r, s = sign(priv, z, 0xC0FFEE + i)
        if i % 3 == 2:
            z ^= 1
        items.append((pub, z, r, s))
        expect.append(i % 3 != 2)
    from .kernel import with_mosaic_fallback

    kind = f"{devs[0].platform}:{getattr(devs[0], 'device_kind', '?')}"
    # A Mosaic RUNTIME failure surfaces at collect time inside
    # verify_batch_tpu, past _dispatch_prep's compile-stage catch: mark
    # pallas broken and retry once through the XLA program instead of
    # pinning the engine to CPU for the whole process.
    got = with_mosaic_fallback(
        lambda: verify_batch_tpu(items, pad_to=batch_size),
        "during warmup",
    )
    if got != expect:
        raise RuntimeError("device/oracle verdict mismatch during warmup")
    if device_batch and device_batch != batch_size:
        try:
            got = verify_batch_tpu(items, pad_to=device_batch)
        except Exception as e:  # noqa: BLE001 — verdict errors re-raised below
            # The small shape works but the steady-state shape doesn't
            # compile (e.g. the XLA fallback at 32768 during a Mosaic
            # outage): keep the device path, chunk at the small shape.
            # (A Mosaic error here is unreachable in practice — the
            # small-shape pass above already forced the XLA program —
            # and degrading to the known-good small shape handles it.)
            raise BigShapeFailed(
                kind, f"{type(e).__name__}: {e}"[:300]
            ) from e
        if got != expect:
            raise RuntimeError(
                "device/oracle verdict mismatch at device_batch"
            )
    return kind


class CircuitBreaker:
    """Device-path health state machine (ISSUE 7).

    States (``STATES`` order is the ``verify.breaker_state`` gauge
    encoding):

    * ``ready``    — device path in use, no recent failures.
    * ``degraded`` — failures seen inside the window (< threshold); the
      device is still used, each failed batch already re-ran on the CPU
      rung via the dispatch ladder.
    * ``open``     — threshold reached: all traffic to the CPU, the
      device isn't attempted at all until the cooldown elapses.
    * ``probing``  — cooldown elapsed: exactly one live batch is routed
      to the device as a half-open canary.  Success closes the breaker
      (``ready``, recovery latency observed); failure re-opens it and
      restarts the cooldown.

    Thread-safe: transitions happen on the engine's dispatch worker
    thread (ladder outcomes) and the queue loop (backend picks).  Every
    transition emits one ``verify.breaker`` event and updates the
    ``verify.breaker_state`` gauge.
    """

    STATES = ("ready", "degraded", "open", "probing")

    def __init__(
        self,
        threshold: int = 3,
        window: float = 30.0,
        cooldown: float = 5.0,
        name: str = "",
    ):
        self.threshold = max(1, threshold)
        self.window = window
        self.cooldown = cooldown
        # Fleet host identity (ISSUE 13): named breakers label their
        # gauge/events with host= so one sick host's transitions don't
        # masquerade as engine-wide device health.
        self.name = name
        # Reentrant: _transition emits verify.breaker with the lock held,
        # and a synchronous event observer (the flight recorder freezing
        # a bundle on the open transition) calls back into stats() on the
        # same thread — a plain Lock would self-deadlock there (the PR 14
        # hang, now pinned via threadsan in tests/test_threadsan.py).
        # Per-host breakers register under their own name so the fleet's
        # host->engine acquisition edges don't alias into self-loops.
        self._lock = threadsan.rlock(
            f"verify.breaker.{name}" if name else "verify.breaker"
        )
        self._state = "ready"
        self._failures: collections.deque[float] = collections.deque()
        self._opened_at: Optional[float] = None
        self._last_error: Optional[str] = None
        self.opens = 0
        self.closes = 0

    @property
    def state(self) -> str:
        return self._state

    def allow_device(self) -> bool:
        """May this batch take the device path?  ``open -> probing`` when
        the cooldown has elapsed — the caller's batch becomes the canary
        (exactly one: while ``probing``, everyone else stays on cpu)."""
        with self._lock:
            if self._state in ("ready", "degraded"):
                return True
            if self._state == "probing":
                return False  # a canary is already in flight
            now = time.monotonic()
            if (
                self._opened_at is not None
                and now - self._opened_at >= self.cooldown
            ):
                self._transition("probing")
                return True
            return False

    def record_success(self) -> bool:
        """A device batch completed: close toward ``ready``.  Returns
        True when this success CLOSED an open/probing breaker (the
        fleet's re-grow hook — a successful canary restores the host's
        full sub-mesh)."""
        with self._lock:
            self._failures.clear()
            if self._state == "ready":
                return False
            fields = {}
            if self._opened_at is not None:
                recovery = time.monotonic() - self._opened_at
                metrics.observe("verify.breaker_recovery_seconds", recovery)
                fields["recovery_seconds"] = round(recovery, 3)
            closed = self._state in ("open", "probing")
            if closed:
                self.closes += 1
                metrics.inc("verify.breaker_closes")
            self._opened_at = None
            self._last_error = None
            self._transition("ready", **fields)
            return closed

    def record_failure(self, error: str = "") -> None:
        """A device batch failed (the ladder already re-dispatched it)."""
        with self._lock:
            now = time.monotonic()
            self._failures.append(now)
            while self._failures and now - self._failures[0] > self.window:
                self._failures.popleft()
            self._last_error = error or None
            if (
                self._state == "probing"
                or len(self._failures) >= self.threshold
            ):
                # a failed canary re-opens immediately; repeated failures
                # inside the window open from ready/degraded
                self._opened_at = now
                if self._state != "open":
                    self.opens += 1
                    metrics.inc("verify.breaker_opens")
                    self._transition(
                        "open", failures=len(self._failures), error=error,
                    )
            elif self._state == "ready":
                self._transition(
                    "degraded", failures=len(self._failures), error=error,
                )

    def trip(self, error: str = "") -> None:
        """Force the breaker OPEN immediately (ISSUE 13: a host
        partition is not three strikes — the host is gone NOW; the
        cooldown/canary recovery machinery applies unchanged)."""
        with self._lock:
            now = time.monotonic()
            self._failures.append(now)
            self._last_error = error or None
            self._opened_at = now
            if self._state != "open":
                self.opens += 1
                metrics.inc("verify.breaker_opens")
                self._transition("open", error=error, forced=True)

    def _transition(self, to: str, **fields) -> None:
        # lock held by the caller
        frm, self._state = self._state, to
        metrics.set_gauge(
            "verify.breaker_state",
            float(self.STATES.index(to)),
            labels={"host": self.name} if self.name else None,
        )
        if self.name:
            fields = {"host": self.name, **fields}
        log.warning("[Engine] breaker %s -> %s %s", frm, to, fields or "")
        events.emit("verify.breaker", **{"from": frm, "to": to, **fields})

    def stats(self) -> dict:
        with self._lock:
            out = {
                "state": self._state,
                "failures_in_window": len(self._failures),
                "threshold": self.threshold,
                "opens": self.opens,
                "closes": self.closes,
                "last_error": self._last_error,
            }
            if self._opened_at is not None:
                out["open_age_seconds"] = round(
                    time.monotonic() - self._opened_at, 3
                )
            return out


@dataclass
class VerifyConfig:
    """Knobs (gated behind NodeConfig like the reference's config surface,
    Node.hs:74-96; see BASELINE.json north_star 'gated behind the existing
    NodeConfig hooks')."""

    backend: str = "auto"  # auto | tpu | cpu | oracle
    batch_size: int = 4096  # small device shape / queue coalescing threshold
    # Steady-state device shape: the Pallas kernel's measured sweet spot is
    # 32768 (210.9k sigs/s vs 54.5k at 4096 — PERF.md r3 table; VERDICT r3
    # item 4).  Work under ``batch_size`` pads to the small shape, bigger
    # work is chunked at this size; warmup compiles both shapes.
    device_batch: int = 32768
    max_wait: float = 0.025  # seconds to linger for a fuller batch
    # Streaming pipeline width (ISSUE 10): how many packed lanes may be
    # in flight at once, each in its own dispatch thread.  2 overlaps
    # lane N+1's host prep + transfer with lane N's kernel (JAX async
    # dispatch); 1 restores the serial pre-pipeline dispatch for A/B.
    pipeline_depth: int = 2
    # Mesh-aware device rung (ISSUE 10): >1 shards each packed lane over
    # a mesh of that many local devices (multichip.dispatch_raw_sharded)
    # when they are visible; 0/1 keeps single-chip dispatch.  The mesh
    # program compiles on first dispatch (warmup compiles the single-chip
    # shapes only).
    mesh_devices: int = 0
    # Pod-scale fleet dispatch (ISSUE 13): >= 2 carves the device set
    # into this many host groups (a (host, chip) hybrid mesh —
    # multichip.make_hybrid_mesh; with mesh_devices set, only that many
    # devices are carved) and runs pipeline_depth work-stealing dispatch
    # workers PER HOST (sched.FleetDispatcher), each host with its own
    # circuit breaker and device sub-mesh so one sick host degrades
    # alone.  0 (default) keeps the single-host pipeline.  1 is
    # rejected: a one-host fleet is the single-host pipeline.
    mesh_hosts: int = 0
    # Per-host assigned-lane cap (lanes): how deep the scheduler may
    # pre-assign packed lanes onto one host's queue before waiting.
    # Shallow queues keep late high-priority submissions packing ahead
    # of un-cut work; the work-stealing makes depth mostly latency, not
    # throughput.
    fleet_queue: int = 2
    # Below this, the CPU engine beats a device step padded to batch_size:
    # the device pays one full fixed-shape step regardless of occupancy,
    # while the C++ engine verifies ~4.8k sigs/s — crossover near
    # batch_size/4.  Small remainder chunks also route to CPU.
    min_tpu_batch: int = 1024
    # CPU-fallback verify parallelism: 1 = serial (the measurement-honest
    # default on this 1-core dev box), 0 = all hardware threads, N = N OS
    # threads (secp_verify_batch_mt; each MSM row is independent).
    cpu_threads: int = 1
    # device warmup discipline
    warmup_timeout: float = 600.0  # backend=tpu: max wait for warmup
    warmup: bool = True  # start warmup thread on engine start
    # A failed warmup is re-probed after this many seconds (ISSUE 7:
    # the old terminal `failed` state outlived many a transient outage
    # — the r5 Mosaic remote-compile 500s cleared within the round).
    # 0 disables re-probing (the pre-ISSUE-7 terminal behavior).
    warmup_retry: float = 60.0
    # Circuit breaker on the device dispatch path (ISSUE 7):
    # `breaker_threshold` failures inside `breaker_window` seconds open
    # the breaker (all traffic to cpu); after `breaker_cooldown` seconds
    # one live batch probes the device and, on success, restores the
    # fast path.
    breaker_threshold: int = 3
    breaker_window: float = 30.0
    breaker_cooldown: float = 5.0
    # Field-arithmetic formulation (ISSUE 4): None keeps the process-wide
    # mode (TPUNODE_FIELD_MUL / TPUNODE_FIELD_SQR env knobs, defaults
    # measured in PERF.md's roofline section); "shift_add"/"dot_general"
    # and "half"/"mul" select explicitly.  Applied process-globally at
    # engine construction — every device program keys its jit cache on
    # the modes, so the first dispatch traces the requested formulation.
    field_mul: Optional[str] = None
    field_sqr: Optional[str] = None
    # MSM point form (ISSUE 8): None keeps the process-wide mode
    # (TPUNODE_POINT_FORM env knob); "projective"/"affine" select
    # explicitly.  Applied process-globally at engine construction like
    # the field knobs — every device program keys its jit cache on
    # kernel.kernel_modes(), so the first dispatch traces the requested
    # formulation.  Verdicts are bit-identical across forms.
    point_form: Optional[str] = None
    # Field reduction discipline (ISSUE 12): None keeps the process-wide
    # mode (TPUNODE_FIELD_REDUCE env knob); "eager"/"lazy" select
    # explicitly.  "lazy" accumulates unreduced products in curve.py's
    # formulas and pays one reduction per expression — values differ
    # limb-wise, verdicts are bit-identical; int32 safety is asserted at
    # trace time by tpunode.verify.bounds.
    field_reduce: Optional[str] = None
    # MSM window width (ISSUE 12): None keeps the process-wide mode
    # (TPUNODE_WINDOW_BITS env knob); 4 keeps the 33-round/16-entry r3
    # structure, 5 runs 27 rounds over 32-entry tables (the native prep
    # emits both layouts since ISSUE 13; only a stale libsecp_cpu.so
    # preps w5 batches in Python).
    window_bits: Optional[int] = None

    def __post_init__(self):
        if self.device_batch < self.batch_size:
            self.device_batch = self.batch_size
        if self.pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1")
        if self.mesh_hosts == 1 or self.mesh_hosts < 0:
            raise ValueError(
                "mesh_hosts: 0 disables the fleet, >= 2 enables it"
            )
        if self.fleet_queue < 1:
            raise ValueError("fleet_queue must be >= 1")
        if (
            self.field_mul is not None
            or self.field_sqr is not None
            or self.field_reduce is not None
        ):
            from . import field as _field

            _field.set_field_modes(
                mul=self.field_mul,
                sqr=self.field_sqr,
                reduce=self.field_reduce,
            )
        if self.point_form is not None:
            from . import curve as _curve

            _curve.set_point_form(self.point_form)
        if self.window_bits is not None:
            from . import kernel as _kernel

            _kernel.set_kernel_modes(window_bits=self.window_bits)


class _HostState:
    """Per-host fleet state (ISSUE 13): its breaker, its device
    sub-mesh (with the current healthy width), and the lost/rejoin
    machinery.  Mesh fields are guarded by the engine's ``_mesh_lock``
    (dispatch worker threads race on first build / shrink / re-grow);
    ``lost`` is written on the event loop and in dispatch threads but
    only ever flips through the engine's ``_host_down`` /
    ``_host_rejoin`` which the worker task serializes per host."""

    __slots__ = (
        "name", "index", "breaker", "lost", "lost_at",
        "mesh", "mesh_state", "chips", "full_chips", "shrunk_at", "event",
    )

    def __init__(self, name: str, index: int, cfg: "VerifyConfig"):
        self.name = name
        self.index = index
        self.breaker = CircuitBreaker(
            threshold=cfg.breaker_threshold,
            window=cfg.breaker_window,
            cooldown=cfg.breaker_cooldown,
            name=name,
        )
        self.lost = False
        self.lost_at = 0.0
        self.mesh = None  # lazily-built 1-D sub-mesh over this host's row
        self.mesh_state = "cold"  # cold -> ready | failed (soft: single-chip)
        self.chips = 0  # current healthy sub-mesh width (0 = not built yet)
        self.full_chips = 0  # the full row width (re-grow target)
        self.shrunk_at = 0.0  # last shrink time (paces the re-grow probe)
        self.event: Optional[asyncio.Event] = None  # lane-assigned wakeup


metrics.describe(
    "verify.cost_seconds",
    "wall-clock rung seconds charged to each priority class, pro-rated "
    "by item count",
)


class CostLedger:
    """Per-class cost attribution (ISSUE 17): every dispatched lane's
    wall-clock rung time is charged back to the priority classes of the
    submissions it carried, pro-rated by item count.  The charge is cut
    from the ONE measured ``dt`` around :meth:`VerifyEngine._run_ladder`,
    so conservation holds by construction: summed charged seconds equal
    total rung busy seconds (the pin in tests/test_slo.py allows 5% for
    float accumulation, nothing more).

    Thread-safe — charges arrive from every dispatch worker thread;
    snapshots from stats()/the flight recorder."""

    def __init__(self):
        self._lock = threadsan.lock("verify.ledger")
        # (priority, rung) -> [charged seconds, items]
        self._cells: dict[tuple[str, str], list] = {}
        self._busy = 0.0  # total measured rung busy seconds
        # host -> charged seconds: per-host attribution (ISSUE 19) —
        # charged to the EXECUTING host, so a stolen lane bills the
        # thief and per-host shares stay truthful under heavy stealing
        self._by_host: dict[str, float] = {}
        # tenant -> [charged seconds, items]: serve-layer attribution
        # (ISSUE 20).  Unattributed items bill to the node itself under
        # the "" key, so conservation holds over the tenant axis too.
        self._by_tenant: dict[str, list] = {}

    def charge(
        self,
        class_counts: dict[str, int],
        total: int,
        dt: float,
        rung: str,
        host: Optional[str] = None,
        tenants: Optional[dict] = None,
    ) -> None:
        if total <= 0 or dt < 0:
            return
        shares = [
            (p, n, dt * n / total) for p, n in class_counts.items() if n > 0
        ]
        tenant_shares = []
        if tenants:
            tenant_items = 0
            for t, n in tenants.items():
                if n > 0:
                    tenant_shares.append((t, n, dt * n / total))
                    tenant_items += n
            rest = total - tenant_items
            if rest > 0:
                tenant_shares.append(("", rest, dt * rest / total))
        with self._lock:
            self._busy += dt
            if host is not None:
                self._by_host[host] = self._by_host.get(host, 0.0) + dt
            for p, n, share in shares:
                cell = self._cells.get((p, rung))
                if cell is None:
                    cell = self._cells[(p, rung)] = [0.0, 0]
                cell[0] += share
                cell[1] += n
            for t, n, share in tenant_shares:
                cell = self._by_tenant.get(t)
                if cell is None:
                    cell = self._by_tenant[t] = [0.0, 0]
                cell[0] += share
                cell[1] += n
        host_labels = {} if host is None else {"host": host}
        metrics.inc_batch(
            (
                (
                    "verify.cost_seconds",
                    share,
                    {"priority": p, "rung": rung, **host_labels},
                )
                for p, _, share in shares
            )
        )

    def snapshot(self) -> dict:
        """The ``engine.stats()["ledger"]`` / flight-recorder section:
        per-(class, rung) charged seconds + items, per-class
        items-weighted share of the total, and the busy-seconds pin."""
        with self._lock:
            cells = {k: list(v) for k, v in self._cells.items()}
            busy = self._busy
            by_host = dict(self._by_host)
            by_tenant = {k: list(v) for k, v in self._by_tenant.items()}
        charged = sum(v[0] for v in cells.values())
        by_class: dict[str, dict] = {}
        for (p, rung), (secs, items) in sorted(cells.items()):
            c = by_class.setdefault(
                p, {"seconds": 0.0, "items": 0, "rungs": {}}
            )
            c["seconds"] += secs
            c["items"] += items
            c["rungs"][rung] = {
                "seconds": round(secs, 6), "items": items,
            }
        for c in by_class.values():
            c["seconds"] = round(c["seconds"], 6)
            c["share"] = round(c["seconds"] / charged, 4) if charged else 0.0
        out = {
            "busy_seconds": round(busy, 6),
            "charged_seconds": round(charged, 6),
            "by_class": by_class,
        }
        if by_host:
            # fleet mode only (ISSUE 19): busy seconds by EXECUTING host
            out["by_host"] = {
                h: round(s, 6) for h, s in sorted(by_host.items())
            }
        if by_tenant:
            # serve mode only (ISSUE 20): charged seconds + items by
            # tenant ("" = the node's own share of tenant-mixed lanes)
            out["by_tenant"] = {
                t: {"seconds": round(v[0], 6), "items": v[1]}
                for t, v in sorted(by_tenant.items())
            }
        return out


class VerifyEngine:
    """Submit items, await verdicts.

    Usage::

        engine = VerifyEngine(VerifyConfig())
        async with engine:
            ok = await engine.verify(items)   # list[bool]
    """

    # Test seam: replace to simulate slow/broken device warmup.
    _warmup_fn: Callable[[int], str] = staticmethod(_device_warmup)

    def __init__(self, cfg: Optional[VerifyConfig] = None):
        self.cfg = cfg or VerifyConfig()
        # Lane-packing scheduler (ISSUE 10): submissions (with their
        # futures and trace positions) queue here; the pipeline loop
        # pops packed lanes from it.
        self._packer = LanePacker()
        # Per-inflight dispatch start times keyed by a monotonic token
        # (ISSUE 10 watchdog satellite): with pipeline_depth > 1 a single
        # scalar would misattribute or miss stalls — the watchdog's
        # dispatch-stall signal reports the OLDEST in-flight dispatch.
        # Written by the queue loop and the lane tasks, read by the
        # watchdog thread: guarded by _inflight_lock.
        self._inflight: dict[int, float] = {}
        self._inflight_lock = threadsan.lock("verify.inflight")
        self._inflight_seq = 0
        # Cost-attribution ledger (ISSUE 17) + the per-dispatch-thread
        # slot carrying the lane's class counts into _dispatch_multi
        # (threading.local, not a parameter: tests and subclasses pin
        # _dispatch_multi's (payloads, target) call shape).
        self._ledger = CostLedger()
        self._tls = threading.local()
        self._last_rung = "none"  # rung of the latest served batch
        self._lane_tasks: set[asyncio.Task] = set()
        self._slots: Optional[asyncio.Semaphore] = None
        self._kick: Optional[asyncio.Event] = None
        self._task: Optional[asyncio.Task] = None
        # sharded device rung (cfg.mesh_devices): lazily-built mesh;
        # "failed" means mesh construction was tried and is off for
        # good.  Init races between concurrent dispatch worker threads
        # (pipeline_depth > 1) are serialized by _mesh_lock — without
        # it two lanes would double-build (and double-compile), and a
        # transient loser could pin "failed" over a winner's mesh.
        self._mesh_obj = None
        self._mesh_state = "cold"
        self._mesh_lock = threadsan.lock("verify.mesh")
        # Pod-scale fleet (ISSUE 13, cfg.mesh_hosts >= 2): per-host
        # states + the work-stealing dispatcher, built in __aenter__;
        # the hybrid mesh's device rows are carved lazily on the first
        # device dispatch (guarded by _mesh_lock).
        self._fleet: Optional[FleetDispatcher] = None
        self._hosts: dict[str, _HostState] = {}
        self._fleet_hybrid = None  # the (host, chip) Mesh, carved lazily
        self._fleet_hybrid_state = "cold"
        self._room: Optional[asyncio.Event] = None
        if self.cfg.mesh_hosts >= 2:
            # canonical names from sched.py (ISSUE 19): the affinity
            # map's rendezvous seeds hash these strings, so the naming
            # scheme must be stable across layers
            from .sched import host_names

            self._hosts = {
                name: _HostState(name, i, self.cfg)
                for i, name in enumerate(host_names(self.cfg.mesh_hosts))
            }
            self._fleet = FleetDispatcher(
                list(self._hosts), self._packer,
                max_queue=self.cfg.fleet_queue,
            )
            metrics.set_gauge(
                "mesh.active_hosts", float(len(self._hosts))
            )
        self._cpu = None
        if self.cfg.backend in ("auto", "cpu"):
            from .cpu_native import load_native_verifier

            self._cpu = load_native_verifier()
        # Steady-state device shape actually in use: starts at the config
        # value, degraded to batch_size if the big shape fails to compile
        # (never written back into the caller's cfg).
        self._device_batch = self.cfg.device_batch
        # device readiness state machine: cold -> warming -> ready | failed
        # (failed re-probes on the warmup_retry timer — never terminal)
        self._device_state = "cold"
        self._device_kind = ""
        self._device_error: Optional[str] = None
        self._warmup_started = 0.0
        self._warmup_failed_at = 0.0
        self._warmup_lock = threadsan.lock("verify.warmup")
        self._warmup_done = threading.Event()
        self._slow_logged = False
        # device-dispatch circuit breaker (ISSUE 7): engaged only once
        # the device is warm; open = all traffic on the cpu rungs
        self._breaker = CircuitBreaker(
            threshold=self.cfg.breaker_threshold,
            window=self.cfg.breaker_window,
            cooldown=self.cfg.breaker_cooldown,
        )
        if self.cfg.warmup and self.cfg.backend in ("auto", "tpu"):
            self.start_warmup()

    # -- device warmup -------------------------------------------------------

    def start_warmup(self) -> None:
        """Kick off device warmup in a daemon thread (idempotent).  The
        thread is never joined on the hot path: if compile stalls, dispatch
        simply keeps using the CPU engine; if it eventually succeeds, the
        device path switches on."""
        if self._device_state != "cold":
            return
        self._device_state = "warming"
        self._warmup_started = time.monotonic()

        def run() -> None:
            try:
                if chaos.on:  # injected compile/init failure (ISSUE 7)
                    chaos.maybe_raise("engine.warmup")
                kind = type(self)._warmup_fn(
                    self.cfg.batch_size, self.cfg.device_batch
                )
            except BigShapeFailed as e:
                # Small shape is good; stay on the device path chunked at
                # the small shape instead of losing the device entirely.
                self._device_batch = self.cfg.batch_size
                self._device_kind = e.kind
                self._device_state = "ready"
                log.warning(
                    "[Engine] device ready (%s) but device_batch shape "
                    "failed to compile (%s) — chunking at batch_size=%d",
                    e.kind,
                    e,
                    self.cfg.batch_size,
                )
                events.emit(
                    "verify.device", state="ready", kind=e.kind,
                    degraded_batch=self.cfg.batch_size, error=str(e),
                )
            except Exception as e:  # noqa: BLE001 — any failure disables tpu
                self._device_error = f"{type(e).__name__}: {e}"
                self._warmup_failed_at = time.monotonic()
                self._device_state = "failed"
                log.warning(
                    "[Engine] device warmup failed, using cpu engine"
                    " (re-probe in %.0fs): %s",
                    self.cfg.warmup_retry,
                    self._device_error,
                )
                events.emit(
                    "verify.device", state="failed", error=self._device_error
                )
            else:
                self._device_kind = kind
                self._device_state = "ready"
                dt = time.monotonic() - self._warmup_started
                log.info("[Engine] device ready (%s) after %.1fs", kind, dt)
                events.emit(
                    "verify.device", state="ready", kind=kind,
                    warmup_seconds=round(dt, 3),
                )
            finally:
                self._warmup_done.set()

        threading.Thread(target=run, name="verify-warmup", daemon=True).start()

    def _retry_warmup(self) -> None:
        """Re-probe a failed device warmup (ISSUE 7: `failed` is a
        cooldown, not a verdict).  Called from the dispatch path once the
        retry interval elapses; idempotent and thread-safe — exactly one
        caller flips failed -> cold and relaunches the warmup thread."""
        with self._warmup_lock:
            if self._device_state != "failed":
                return
            if (
                time.monotonic() - self._warmup_failed_at
                < self.cfg.warmup_retry
            ):
                return
            log.info(
                "[Engine] re-probing device warmup after failure: %s",
                self._device_error,
            )
            events.emit("verify.device", state="reprobe",
                        error=self._device_error)
            # fresh latch: forced-tpu waiters must block on THIS attempt
            self._warmup_done = threading.Event()
            self._slow_logged = False
            self._device_state = "cold"
            self.start_warmup()

    @property
    def device_state(self) -> str:
        return self._device_state

    @property
    def breaker(self) -> CircuitBreaker:
        return self._breaker

    @property
    def breaker_state(self) -> str:
        """Device-path breaker state (``/health``): the warmup machine's
        view until the device is warm, the breaker's after."""
        if self._device_state != "ready":
            return self._device_state
        return self._breaker.state

    def queue_depth(self) -> dict:
        """Current backlog: queued submissions, total unclaimed items,
        and the per-priority split (``by_priority`` is itself a dict).
        Fleet mode aggregates the central + per-host packers."""
        if self._fleet is not None:
            return {
                "batches": self._fleet.batches(),
                "items": self._fleet.uncut_pending(),
                "by_priority": self._fleet.depths(),
            }
        return {
            "batches": self._packer.batches(),
            "items": self._packer.pending(),
            "by_priority": self._packer.depths(),
        }

    def dispatch_inflight_seconds(self) -> float:
        """Age of the OLDEST in-flight dispatch across the pipeline
        (0.0 when idle) — the stall watchdog's signal.  A wedged device
        backend pins the oldest entry while younger lanes (and the event
        loop) stay healthy."""
        with self._inflight_lock:
            if not self._inflight:
                return 0.0
            return time.monotonic() - min(self._inflight.values())

    def dispatch_inflight(self) -> int:
        """How many packed lanes are currently in dispatch threads."""
        with self._inflight_lock:
            return len(self._inflight)

    def ledger(self) -> dict:
        """Cost-attribution snapshot (ISSUE 17): per-class charged rung
        seconds + the conservation pin — also under stats()["ledger"]."""
        return self._ledger.snapshot()

    @property
    def last_rung(self) -> str:
        """The ladder rung that served the most recent batch ("none"
        before any dispatch) — what a verdict receipt binds (ISSUE 20)."""
        return self._last_rung

    def stats(self) -> dict:
        """Telemetry snapshot for Node.stats()/health()."""
        out = {
            "backend": self.cfg.backend,
            "device_state": self._device_state,
            "device_kind": self._device_kind or None,
            "device_error": self._device_error,
            "device_batch": self._device_batch,
            "backlog": self.queue_depth(),
            "dispatch_inflight_seconds": round(
                self.dispatch_inflight_seconds(), 3
            ),
            "dispatch_inflight": self.dispatch_inflight(),
            "pipeline_depth": self.cfg.pipeline_depth,
            "lanes": metrics.get("sched.lanes"),
            "batches": metrics.get("verify.batches"),
            "items": metrics.get("verify.items"),
            "errors": metrics.get("verify.dispatch_errors"),
            "failovers": metrics.get("verify.failovers"),
            "breaker": self._breaker.stats(),
        }
        if self._fleet is not None:
            out["fleet"] = {
                "hosts": len(self._hosts),
                "active": self._fleet.active_hosts(),
                "depths": self._fleet.host_depths(),
                "steals": self._fleet.steals,
                "host_steals": dict(self._fleet.host_steals),
                "requeued": self._fleet.requeued,
                "queued_lanes": self._fleet.queued_lanes(),
                "breakers": {
                    name: hs.breaker.state
                    for name, hs in self._hosts.items()
                },
                "chips": {
                    name: hs.chips for name, hs in self._hosts.items()
                },
                # host-affine feed surface (ISSUE 19)
                "feed_depths": self._fleet.feed_depths(),
                "feed_idle": {
                    h: round(v, 4)
                    for h, v in self._fleet.feed_idle().items()
                },
                "affinity": {
                    "routed": self._fleet.affinity_routed,
                    "spilled": self._fleet.affinity_spilled,
                },
            }
        occ = metrics.histogram("verify.occupancy")
        if occ is not None:
            out["occupancy"] = occ.summary()
        pack = metrics.histogram("sched.pack_efficiency")
        if pack is not None:
            out["pack_efficiency"] = pack.summary()
        disp = metrics.histogram("span.verify.dispatch")
        if disp is not None:
            out["dispatch_seconds"] = disp.summary()
        out["ledger"] = self._ledger.snapshot()
        return out

    # -- lifecycle -----------------------------------------------------------

    async def __aenter__(self) -> "VerifyEngine":
        self._kick = asyncio.Event()
        self._slots = asyncio.Semaphore(self.cfg.pipeline_depth)
        self._closing = False  # task-registry owner convention (actors.py)
        if self._fleet is not None:
            self._room = asyncio.Event()
            for hs in self._hosts.values():
                hs.event = asyncio.Event()
                for _ in range(self.cfg.pipeline_depth):
                    t = spawn_supervised(
                        self._host_worker(hs),
                        name=f"verify-host-{hs.name}",
                        owner=self,
                    )
                    self._lane_tasks.add(t)
                    t.add_done_callback(self._lane_tasks.discard)
        # ISSUE 3 satellite: the queue loop was a bare create_task handle —
        # registry-supervised now, cancelled+awaited in __aexit__ below
        self._task = spawn_supervised(
            self._run(), name="verify-engine", owner=self
        )
        return self

    async def __aexit__(self, *exc) -> None:
        self._closing = True
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
        # in-flight lanes + fleet workers: cancel + await (their dispatch
        # threads finish behind the cancelled await; verdicts for
        # cancelled lanes are dropped with the futures below)
        for t in list(self._lane_tasks):
            t.cancel()
        for t in list(self._lane_tasks):
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await t
        self._lane_tasks.clear()
        # lanes still assigned to host queues (cut from the packer but
        # never taken — incl. lanes re-queued mid-steal by a dying host):
        # cancel their carried futures exactly like queued submissions;
        # Submission.deliver tolerates a done/cancelled future, so a
        # concurrent late delivery cannot double-resolve (ISSUE 13
        # lane-requeue hardening).
        if self._fleet is not None:
            for lane in self._fleet.drain_lanes():
                for sub, _, _ in lane.slices:
                    if not sub.fut.done():
                        sub.fut.cancel()
            # stragglers across the central AND per-host packers
            for sub in self._fleet.drain_submissions():
                if not sub.fut.done():
                    sub.fut.cancel()
            # Permanent host retirement (ISSUE 19 labeled-series
            # lifecycle): engine teardown is the one point a fleet's
            # hosts deactivate for good — drop their host= series from
            # the registry (and, via the registry's drop hooks, from
            # any Timeline sampler) so fleet churn across engine
            # lifetimes can't grow label cardinality unboundedly.
            for name in self._hosts:
                metrics.drop_label("host", name)
        else:
            # fail any stragglers still queued (or partially claimed)
            for sub in self._packer.drain():
                if not sub.fut.done():
                    sub.fut.cancel()

    # -- API -----------------------------------------------------------------

    async def verify(
        self,
        items: Sequence[VerifyItem],
        priority: str = "bulk",
        affinity: Optional[int] = None,
        tenant: Optional[str] = None,
    ) -> list[bool]:
        """Queue items; resolves when their lanes have been verified.
        ``priority``: ``block`` > ``mempool`` > ``bulk`` (sched.py) — the
        class whose lanes pack and dispatch first under saturation.
        ``affinity`` (fleet mode, ISSUE 19): a ``sched.affinity_key``
        routing this submission to its home host's packer — a placement
        hint only, never a correctness input.  ``tenant`` (serve mode,
        ISSUE 20): the registered tenant this submission's rung time
        bills to in the cost ledger."""
        return await self._enqueue(list(items), priority, affinity, tenant)

    async def verify_raw(
        self,
        raw,
        priority: str = "bulk",
        affinity: Optional[int] = None,
        tenant: Optional[str] = None,
    ) -> list[bool]:
        """Queue a packed batch (RawBatch, or anything `as_raw_batch`
        coerces, e.g. txextract.RawSigItems): the native-extract fast path —
        no per-item Python objects anywhere between wire bytes and device."""
        return await self._enqueue(as_raw_batch(raw), priority, affinity,
                                   tenant)

    async def _enqueue(
        self,
        payload,
        priority: str = "bulk",
        affinity: Optional[int] = None,
        tenant: Optional[str] = None,
    ) -> list[bool]:
        if not len(payload):
            return []
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        act = _trace_current()
        if act is not None:
            # queue-wait + dispatch as one span in the submitter's trace:
            # closed when the submission's future resolves, however it
            # resolves — per payload even when the packer slices it
            # across several lanes (ISSUE 10 trace satellite)
            tr = act[0]
            rec = tr.begin("verify.queue", act[1], items=len(payload))
            fut.add_done_callback(lambda _f, tr=tr, rec=rec: tr.end(rec))
        sub = Submission(payload, fut, act, priority, affinity=affinity,
                         tenant=tenant)
        if self._fleet is not None:
            # host-affine route (ISSUE 19): keyed submissions land in
            # their home host's packer; keyless work stays central
            self._fleet.push(sub)
        else:
            self._packer.push(sub)
        assert self._kick is not None, "engine not started"
        self._kick.set()
        return await fut

    # -- host-affine feed surface (ISSUE 19) ----------------------------------

    def route_host(self, key: int) -> Optional[str]:
        """The ACTIVE host an affinity key routes to right now (None
        without a fleet, or with every host dark) — upstream ingest
        sharding partitions parse/prep work by this."""
        if self._fleet is None:
            return None
        return self._fleet.affinity.route(key, self._fleet.active_hosts())

    def _feed_limit(self) -> int:
        """Per-host feed-depth ceiling for intake gating: the host's
        queue allowance plus one lane of headroom, in items."""
        return (self.cfg.fleet_queue + 1) * self._lane_target()

    def host_pressured(self, key: int) -> bool:
        """Is the TARGET host of ``key`` over its feed ceiling?  The
        per-host backpressure signal (ISSUE 19): intake for one slow
        host's keys defers without stalling the rest of the fleet.
        False without a fleet or with every host dark — callers fall
        back to their global gates."""
        if self._fleet is None:
            return False
        host = self._fleet.affinity.route(key, self._fleet.active_hosts())
        if host is None:
            return False
        return self._fleet.feed_depth(host) >= self._feed_limit()

    def hosts_all_pressured(self) -> bool:
        """Every ACTIVE host over its feed ceiling (the fleet-wide
        intake gate: one slow host alone must never trip it)."""
        if self._fleet is None:
            return False
        active = self._fleet.active_hosts()
        if not active:
            return False
        limit = self._feed_limit()
        return all(self._fleet.feed_depth(h) >= limit for h in active)

    def verify_sync(self, items: Sequence[VerifyItem]) -> list[bool]:
        """Blocking verification (benchmarks, scripts): no queueing."""
        return self._dispatch(list(items))

    def verify_raw_sync(self, raw) -> list[bool]:
        """Blocking raw-batch verification (benchmarks, scripts)."""
        return self._dispatch(as_raw_batch(raw))

    # -- internals -----------------------------------------------------------

    def _lane_target(self) -> int:
        """Pack/fill goal: the steady-state device shape once the device
        is up, the small shape before."""
        return (
            self._device_batch
            if self._device_state == "ready"
            else self.cfg.batch_size
        )

    def _uncut_pending(self) -> int:
        """Unclaimed queued items across every packer (fleet mode sums
        the central + per-host packers — ISSUE 19)."""
        if self._fleet is not None:
            return self._fleet.uncut_pending()
        return self._packer.pending()

    def _uncut_oldest(self) -> Optional[float]:
        if self._fleet is not None:
            return self._fleet.oldest_enqueued()
        return self._packer.oldest_enqueued()

    async def _run(self) -> None:
        """Pipeline scheduler loop: linger toward full lanes, then keep up
        to ``pipeline_depth`` packed lanes in flight (each in its own
        dispatch thread — lane N+1's host prep and transfer overlap lane
        N's kernel under JAX async dispatch).  In fleet mode the same
        linger feeds the work-stealing dispatcher instead: each cut lane
        is assigned to the shallowest active host queue, and the per-host
        workers (not this loop) own dispatch."""
        assert self._kick is not None and self._slots is not None
        while True:
            # wait for work
            while not self._uncut_pending():
                await self._kick.wait()
                self._kick.clear()
            target = self._lane_target()
            # Event-driven fill (VERDICT r4 weak #6 — the former 2 ms poll
            # burned ≤500 wakes/s per linger window): sleep until either a
            # new enqueue kicks, or the linger deadline passes.  The
            # deadline anchors on the OLDEST queued submission, so a
            # remainder lingers for later submissions to pack with only
            # while its submitter is younger than max_wait (ISSUE 10:
            # max-linger — a lone small batch still dispatches promptly).
            while self._uncut_pending() < target:
                oldest = self._uncut_oldest()
                if oldest is None:
                    break
                remain = oldest + self.cfg.max_wait - time.monotonic()
                if remain <= 0:
                    break
                try:
                    await asyncio.wait_for(self._kick.wait(), timeout=remain)
                except asyncio.TimeoutError:
                    break
                self._kick.clear()
            if not self._uncut_pending():
                continue
            if self._fleet is not None:
                await self._feed_fleet()
                continue
            # admission: a free pipeline slot (more work keeps queueing —
            # and packing fuller lanes — while every slot is busy)
            await self._slots.acquire()
            lane = self._packer.pop_lane(self._lane_target())
            if lane is None:
                self._slots.release()
                continue
            self._spawn_lane_task(lane)

    def _spawn_lane_task(self, lane: PackedLane) -> None:
        """Spawn one locally-dispatched lane task (the caller holds a
        pipeline slot; _dispatch_lane releases it)."""
        task = spawn_supervised(
            self._dispatch_lane(lane), name="verify-lane", owner=self
        )
        self._lane_tasks.add(task)
        task.add_done_callback(self._lane_tasks.discard)

    async def _feed_fleet(self) -> None:
        """Cut ONE lane and hand it to the fleet (ISSUE 13, host-affine
        since ISSUE 19).  ``cut_next`` picks the globally most-urgent
        feedable source — an active host's HOME packer (lane lands on
        that host's own queue) or the central packer (lane lands on the
        shallowest queue) — so per-host packing preserves the global
        priority order.  Admission is a feedable source (shallow queues
        keep late high-priority submissions packing ahead of un-cut
        work); with every host lost, lanes are served through the LOCAL
        ladder under the ordinary pipeline slots — a fully-dark fleet
        still produces verdicts."""
        assert self._fleet is not None and self._room is not None
        assert self._slots is not None
        while not self._fleet.feedable() and self._fleet.active_hosts():
            self._room.clear()
            await self._room.wait()
        if not self._fleet.active_hosts():
            # no active host at all: local fallback, traffic never stops
            lane = self._fleet.pop_any(self._lane_target())
            if lane is None:
                return
            await self._slots.acquire()
            self._spawn_lane_task(lane)
            return
        lane, host = self._fleet.cut_next(self._lane_target())
        if lane is None:
            return
        if host is None:
            # cut from the central packer but no queue had room (raced
            # with other cuts): serve locally rather than re-queueing —
            # the lane exists now and must resolve exactly once
            await self._slots.acquire()
            self._spawn_lane_task(lane)
            return
        self._wake_fleet()

    def _wake_fleet(self) -> None:
        """Wake every host worker (a new/re-queued lane may be stolen by
        ANY idle host, not just the one it was assigned to)."""
        for hs in self._hosts.values():
            if hs.event is not None:
                hs.event.set()

    async def _host_worker(self, hs: _HostState) -> None:
        """One host's dispatch worker (``pipeline_depth`` of these run
        per host): pull lanes — own queue first, then steal from the
        deepest peer — and dispatch them over this host's sub-mesh with
        this host's breaker.  A lost host's workers pace the canary
        rejoin instead of pulling work."""
        assert self._fleet is not None and self._room is not None
        while True:
            if hs.lost:
                # cooldown-paced rejoin, anchored on the LOSS time (not
                # on when this worker noticed — several workers share
                # one host): after breaker_cooldown the host re-enters
                # the active set with its breaker open — the next lane
                # a worker takes is the half-open canary, and a
                # still-dead host just gets deactivated again.
                remain = (
                    hs.lost_at + self.cfg.breaker_cooldown
                    - time.monotonic()
                )
                await asyncio.sleep(max(0.01, remain))
                if hs.lost:
                    self._host_rejoin(hs)
                continue
            lane = self._fleet.take(hs.name)
            if lane is None:
                self._room.set()
                assert hs.event is not None
                await hs.event.wait()
                hs.event.clear()
                continue
            self._room.set()
            await self._dispatch_lane(lane, host=hs, slot=False)

    async def _dispatch_lane(
        self,
        lane: PackedLane,
        host: Optional[_HostState] = None,
        slot: bool = True,
    ) -> None:
        """Run one packed lane end to end: dispatch in a worker thread
        (the ladder/breaker/failover semantics of :meth:`_run_ladder`
        apply per in-flight lane), then deliver each slice's verdicts to
        its submission.  A lane that fails on every rung fails exactly
        the submissions it carries slices of.

        Fleet mode (``host`` set): the lane runs with that host's
        breaker and sub-mesh; a :class:`HostLost` deactivates the host
        and RE-QUEUES the lane onto a healthy peer — exactly once, since
        nothing was delivered and the lane now lives in exactly one peer
        queue.  A lane that has already bounced through every host (or
        finds no healthy peer) falls through the LOCAL cpu ladder so its
        waiters still resolve."""
        assert self._kick is not None and self._slots is not None
        payloads = lane.payloads()
        total = lane.total
        metrics.inc("verify.batches")
        metrics.inc("verify.items", total)
        metrics.set_gauge("verify.batch_occupancy", lane.occupancy)
        with self._inflight_lock:
            self._inflight_seq += 1
            token = self._inflight_seq
            self._inflight[token] = time.monotonic()
        try:
            classes = lane.class_counts()
            tenants = lane.tenant_counts()
            try:
                results = await asyncio.to_thread(
                    self._dispatch_traced, payloads, lane.target, lane.act0,
                    host, None, classes, tenants,
                )
            except HostLost as e:
                assert host is not None and self._fleet is not None
                self._host_down(host, str(e))
                if (
                    lane.requeues < len(self._hosts)
                    and self._fleet.requeue(host.name, lane) is not None
                ):
                    self._wake_fleet()
                    return
                # no healthy peer (or the lane is orbiting dying hosts):
                # serve it locally, skipping the device rungs entirely
                results = await asyncio.to_thread(
                    self._dispatch_traced, payloads, lane.target, lane.act0,
                    None, "cpu" if self._cpu is not None else "oracle",
                    classes, tenants,
                )
        except asyncio.CancelledError:
            # engine teardown mid-dispatch: waiters must not hang on a
            # future nobody will resolve
            for sub, _, _ in lane.slices:
                if not sub.fut.done():
                    sub.fut.cancel()
            raise
        except Exception as e:  # all rungs failed: the waiters learn it
            log.error("[Engine] lane of %d failed: %s", total, e)
            for sub, _, _ in lane.slices:
                sub.fail(e)
            return
        finally:
            with self._inflight_lock:
                self._inflight.pop(token, None)
            if slot:
                self._slots.release()
            if self._room is not None:
                self._room.set()
            self._kick.set()  # a freed slot may unblock the scheduler
        pos = 0
        for sub, lo, hi in lane.slices:
            sub.deliver(lo, results[pos : pos + (hi - lo)])
            pos += hi - lo

    def _dispatch(self, payload) -> list[bool]:
        """Pick an execution engine and run one payload (worker thread)."""
        return self._dispatch_multi([payload])

    def _dispatch_traced(
        self,
        payloads: list,
        target: Optional[int],
        act: Optional[tuple],
        host: Optional[_HostState] = None,
        backend: Optional[str] = None,
        classes: Optional[dict] = None,
        tenants: Optional[dict] = None,
    ) -> list[bool]:
        """Worker-thread entry: re-activate the submitting item's trace
        (contextvars do not cross ``to_thread`` from the queue loop — the
        loop's own context has no trace) so the dispatch/prepare/transfer/
        kernel/readback spans land in the item's pipeline tree.
        ``classes`` (the lane's per-priority item counts) and ``tenants``
        (per-tenant counts, serve mode) ride a thread-local into
        _dispatch_multi's ledger charge — this IS the dispatch thread."""
        self._tls.classes = classes
        self._tls.tenants = tenants
        try:
            with _activate_trace(act):
                if host is None and backend is None:
                    # keep the 2-arg call shape: tests (and subclasses)
                    # spy on _dispatch_multi with (payloads, target)
                    # signatures
                    return self._dispatch_multi(payloads, target)
                return self._dispatch_multi(
                    payloads, target, host=host, backend=backend
                )
        finally:
            self._tls.classes = None
            self._tls.tenants = None

    def _pick(self, n: int, host: Optional[_HostState] = None) -> str:
        """Resolve the starting backend rung for one batch.  Never blocks
        except for the forced-tpu backend, which waits (bounded) for
        warmup.  The device path additionally passes through the circuit
        breaker — the HOST's own breaker in fleet mode, so one sick
        host degrades alone: open = cpu, one canary batch while probing."""
        backend = self.cfg.backend
        if (
            backend in ("auto", "tpu")
            and self._device_state == "failed"
            and self.cfg.warmup_retry > 0
        ):
            self._retry_warmup()  # no-op until the retry interval elapses
        if backend == "tpu":
            if self._device_state == "cold":  # cfg.warmup=False: warm lazily
                self.start_warmup()
            if self._device_state == "warming":
                remain = self.cfg.warmup_timeout - (
                    time.monotonic() - self._warmup_started
                )
                self._warmup_done.wait(timeout=max(0.0, remain))
            if self._device_state != "ready":
                raise RuntimeError(
                    "tpu backend unavailable: "
                    + (self._device_error or "warmup timed out")
                )
            return "tpu"
        if backend != "auto":
            return backend
        breaker = host.breaker if host is not None else self._breaker
        if (
            n >= self.cfg.min_tpu_batch
            and self._device_state == "ready"
            and breaker.allow_device()
        ):
            return "tpu"
        if (
            self._device_state == "warming"
            and not self._slow_logged
            and time.monotonic() - self._warmup_started > 30.0
        ):
            self._slow_logged = True
            log.info("[Engine] device warmup still running; batches on cpu")
        return "cpu" if self._cpu is not None else "oracle"

    # Linear occupancy buckets (0.05 steps) shared with the packer's
    # sched.pack_efficiency histogram so the two stay comparable.
    OCCUPANCY_BUCKETS = _OCCUPANCY_BUCKETS

    def _dispatch_multi(
        self,
        payloads: list,
        target: Optional[int] = None,
        host: Optional[_HostState] = None,
        backend: Optional[str] = None,
    ) -> list[bool]:
        """Verify a coalesced batch of payloads (tuple lists and/or raw
        batches) on one backend; results are in payload order.  ``target``
        is the fill goal the queue lingered for (None on the synchronous
        paths) — it sizes the occupancy observation.  ``host`` routes the
        batch through that fleet host's breaker and sub-mesh (ISSUE 13);
        ``backend`` forces the starting rung (the fleet's local-fallback
        path pins "cpu" so a dark fleet never re-enters device picks)."""
        with span("verify.dispatch"):
            total = sum(len(p) for p in payloads)
            occupancy = total / target if target else None
            if occupancy is not None:
                metrics.observe(
                    "verify.occupancy",
                    min(1.0, occupancy),
                    buckets=self.OCCUPANCY_BUCKETS,
                )
            picked = backend or self._pick(total, host)
            t0 = time.perf_counter()
            out, served = self._run_ladder(picked, payloads, total, host)
            dt = time.perf_counter() - t0
            metrics.inc("verify.seconds", dt)
            # Ledger charge (ISSUE 17): the ONE measured rung time is cut
            # across the lane's carried classes; the sync/no-lane paths
            # (verify_sync, warmup canaries) have no class counts and
            # charge to "bulk".
            classes = getattr(self._tls, "classes", None)
            self._ledger.charge(
                classes if classes else {"bulk": total}, total, dt, served,
                host=host.name if host is not None else None,
                tenants=getattr(self._tls, "tenants", None),
            )
            # the rung that actually served the latest batch: what a
            # verdict receipt binds (ISSUE 20) — best-effort under
            # concurrency, exact in the serve bench's cpu-proxy shape
            self._last_rung = served
            events.emit(
                "verify.dispatch", backend=served, size=total,
                occupancy=round(occupancy, 4) if occupancy is not None else None,
                seconds=round(dt, 6),
                **({"host": host.name} if host is not None else {}),
            )
            return out

    # Failover order (ISSUE 7): each rung is strictly more available and
    # strictly slower than the one above it; the python oracle cannot
    # fail for device/native reasons, so transient faults never surface
    # to waiters as exceptions.
    _LADDER = ("tpu", "cpu", "oracle")

    def _run_ladder(
        self,
        backend: str,
        payloads: list,
        total: int,
        host: Optional[_HostState] = None,
    ) -> tuple[list[bool], str]:
        """Run one coalesced batch starting at ``backend``, re-dispatching
        the SAME batch down the ladder on failure.  Device-rung outcomes
        feed the circuit breaker (the HOST's in fleet mode).  Returns
        (results, rung that served).  Only a batch that fails on every
        rung raises — and then fails just this batch's waiters; the
        queue loop survives (pinned by tests/test_engine.py).

        Fleet specifics (ISSUE 13): a host partition
        (:class:`HostLost` / injected ``mesh.dispatch:partition``)
        escapes the ladder immediately — the host's CPU is as gone as
        its chips, so laddering down locally would serve a dead host's
        lane; the worker re-queues it instead.  A device LOSS on a
        multi-chip host additionally shrinks its sub-mesh to the largest
        still-healthy half before the ladder re-serves the batch on cpu;
        a successful canary re-grows it."""
        breaker = host.breaker if host is not None else self._breaker
        start = self._LADDER.index(backend) if backend in self._LADDER else 0
        rungs = [
            r
            for r in self._LADDER[start:]
            if r != "cpu" or self._cpu is not None
        ]
        for i, rung in enumerate(rungs):
            try:
                if chaos.on:  # injected batch/device failure (ISSUE 7/13)
                    if host is not None:
                        chaos.maybe_raise(
                            "mesh.dispatch",
                            f"{host.name}:{rung}:chips{host.chips}",
                        )
                    chaos.maybe_raise("engine.dispatch", rung)
                # 3-arg call shape kept when hostless: tests (and
                # subclasses) wrap _run_backend with (rung, payloads,
                # total) signatures
                out = (
                    self._run_backend(rung, payloads, total)
                    if host is None
                    else self._run_backend(rung, payloads, total, host)
                )
            except HostLost:
                raise
            except ChaosPartition as e:
                raise HostLost(str(e)) from e
            except Exception as e:
                err = f"{type(e).__name__}: {e}"[:300]
                metrics.inc("verify.dispatch_errors")
                events.emit(
                    "verify.failure", where="dispatch", backend=rung,
                    size=total, error=err,
                    **({"host": host.name} if host is not None else {}),
                )
                if rung == "tpu":
                    breaker.record_failure(err)
                    if host is not None:
                        # ANY device-rung failure on a multi-chip fleet
                        # host probes the smaller sub-mesh — real device
                        # losses surface as assorted XLA runtime errors
                        # that cannot be reliably classified (review
                        # r13: keying on ChaosDeviceLoss alone left real
                        # hardware pinned at CPU speed).  A wrong shrink
                        # self-heals via the cooldown-paced re-grow; a
                        # missed one parks the host on the cpu rung.
                        self._host_shrink(host)
                if i + 1 >= len(rungs):
                    raise  # every rung failed: the waiters learn it
                metrics.inc("verify.failovers")
                events.emit(
                    "verify.failover", source=rung, target=rungs[i + 1],
                    size=total, error=err,
                )
                log.warning(
                    "[Engine] batch of %d failed on %s, retrying on %s: %s",
                    total, rung, rungs[i + 1], err,
                )
                continue
            if rung == "tpu":
                closed = breaker.record_success()
                if host is not None and (
                    closed
                    or (
                        # Re-grow is NOT gated on a full breaker
                        # open/close cycle (review r13: a single device
                        # loss shrinks from 'degraded', which closes
                        # with closed=False — the host would run at
                        # half capacity forever): any device success on
                        # a shrunken host re-probes the full row once
                        # per breaker cooldown; a repeat loss just
                        # shrinks again.
                        0 < host.chips < host.full_chips
                        and time.monotonic() - host.shrunk_at
                        >= self.cfg.breaker_cooldown
                    )
                ):
                    self._host_regrow(host)
            return out, rung
        raise RuntimeError("no verify backend available")  # unreachable

    def _run_backend(
        self,
        rung: str,
        payloads: list,
        total: int,
        host: Optional[_HostState] = None,
    ) -> list[bool]:
        """Execute one ladder rung over the coalesced payloads."""
        if rung == "tpu":
            # counts tpu/cpu items per chunk
            return self._run_tpu(payloads, host)
        if rung == "cpu" and self._cpu is not None:
            out = self._cpu.verify_raw(
                concat_raw([as_raw_batch(p) for p in payloads]),
                nthreads=self.cfg.cpu_threads,
            )
            metrics.inc("verify.cpu_items", total)
            return out
        out = []
        for p in payloads:
            out.extend(
                verify_batch_cpu(
                    p if isinstance(p, list) else as_raw_batch(p).to_tuples()
                )
            )
        metrics.inc("verify.oracle_items", total)
        return out

    def _mesh(self):
        """Lazily-built device mesh for the sharded tpu rung (ISSUE 10):
        None when ``mesh_devices`` is off, fewer than 2 devices are
        visible, or mesh construction already failed (tried once).
        Thread-safe: concurrent lanes race to be the first dispatch."""
        if self.cfg.mesh_devices < 2 or self._mesh_state == "failed":
            return None
        with self._mesh_lock:
            if self._mesh_state == "failed":
                return None
            if self._mesh_obj is None:
                try:
                    import jax

                    from .multichip import make_mesh

                    n = min(self.cfg.mesh_devices, len(jax.devices()))
                    if n < 2:
                        raise RuntimeError(
                            f"mesh_devices={self.cfg.mesh_devices} but "
                            f"only {n} device(s) visible"
                        )
                    self._mesh_obj = make_mesh(n)
                    self._mesh_state = "ready"
                    events.emit("verify.mesh", state="ready", devices=n)
                except Exception as e:  # mesh is an upgrade, never a gate
                    self._mesh_state = "failed"
                    log.warning(
                        "[Engine] sharded dispatch unavailable, "
                        "single-chip rung: %s", e,
                    )
                    events.emit(
                        "verify.mesh", state="failed", error=str(e)[:300]
                    )
                    return None
            return self._mesh_obj

    # -- fleet host health / sub-meshes (ISSUE 13) ---------------------------

    def _host_down(self, hs: _HostState, error: str) -> None:
        """Deactivate a lost host: trip its breaker (instant open — the
        cooldown/canary recovery machinery applies unchanged), move its
        queued lanes to active peers, and wake the fleet.  Idempotent —
        concurrent lanes observing the same partition deactivate once."""
        assert self._fleet is not None
        if hs.lost:
            return
        hs.lost = True
        hs.lost_at = time.monotonic()
        hs.breaker.trip(error[:300])
        moved = self._fleet.deactivate(hs.name)
        active = len(self._fleet.active_hosts())
        metrics.inc("mesh.host_losses")
        metrics.set_gauge("mesh.active_hosts", float(active))
        events.emit(
            "mesh.host_down", host=hs.name, error=error[:200],
            requeued_lanes=moved, active_hosts=active,
        )
        log.warning(
            "[Engine] fleet host %s lost (%d active): %s",
            hs.name, active, error,
        )
        self._wake_fleet()
        if self._room is not None:
            self._room.set()

    def _host_rejoin(self, hs: _HostState) -> None:
        """Cooldown elapsed: the host re-enters the active set with its
        breaker open — the first lane it takes is the half-open canary
        (success closes the breaker and re-grows the sub-mesh; a
        still-dead host is deactivated again by the next HostLost)."""
        assert self._fleet is not None
        hs.lost = False
        self._fleet.activate(hs.name)
        active = len(self._fleet.active_hosts())
        metrics.set_gauge("mesh.active_hosts", float(active))
        events.emit("mesh.host_up", host=hs.name, active_hosts=active,
                    probing=True)
        self._wake_fleet()
        if self._room is not None:
            self._room.set()

    def _host_shrink(self, hs: _HostState) -> None:
        """Device loss on a multi-chip host: rebuild its sub-mesh as the
        largest still-healthy half (8→4→2→1 chips) instead of failing
        soft to single-chip in one step.  The failed batch itself is
        re-served by the ladder's cpu rung; later lanes use the smaller
        mesh."""
        with self._mesh_lock:
            if not hs.full_chips:
                # the loss can precede the first sub-mesh build (chips
                # still 0): resolve this host's row width so there is a
                # known-good whole to halve
                hybrid = self._fleet_hybrid_mesh()
                if hybrid is not None:
                    hs.full_chips = int(hybrid.devices.shape[-1])
                    hs.chips = hs.full_chips
            if hs.chips <= 1:
                return
            hs.chips //= 2
            hs.shrunk_at = time.monotonic()
            hs.mesh = None  # rebuilt lazily at the new width
            hs.mesh_state = "cold"
            chips = hs.chips
        metrics.inc("mesh.shrinks")
        self._chips_gauge(hs.name, chips)
        events.emit("mesh.shrink", host=hs.name, chips=chips)
        log.warning(
            "[Engine] host %s sub-mesh shrunk to %d chip(s)", hs.name, chips
        )

    def _host_regrow(self, hs: _HostState) -> None:
        """Restore the host's full device row — on a breaker canary
        close, or (review r13) on any device success once a breaker
        cooldown has passed since the shrink, so a loss that never
        opened the breaker (degraded at the default threshold) cannot
        pin the host at reduced width forever.  The chips that caused
        the shrink get re-probed by ordinary traffic — a repeat loss
        just shrinks again, at most once per cooldown."""
        with self._mesh_lock:
            if not hs.full_chips or hs.chips >= hs.full_chips:
                return
            hs.chips = hs.full_chips
            hs.mesh = None
            hs.mesh_state = "cold"
            chips = hs.chips
        metrics.inc("mesh.regrows")
        self._chips_gauge(hs.name, chips)
        events.emit("mesh.regrow", host=hs.name, chips=chips)
        log.info(
            "[Engine] host %s sub-mesh re-grown to %d chip(s)", hs.name, chips
        )

    @staticmethod
    def _chips_gauge(host: str, chips: int) -> None:
        # per-host sub-mesh width as a labeled gauge: the fleet timeline
        # (tpunode/timeseries.py) samples it, so an 8→4→8 shrink/regrow
        # is reconstructible after the fact
        metrics.set_gauge(
            "mesh.host_chips", float(chips), labels={"host": host}
        )

    def _fleet_hybrid_mesh(self):
        """The fleet's (host, chip) hybrid mesh, carved lazily on first
        device dispatch.  Caller holds ``_mesh_lock``.  None = hybrid
        construction failed (hosts fall back to single-chip
        default-device dispatch — the mesh is an upgrade, never a
        gate)."""
        if self._fleet_hybrid_state == "failed":
            return None
        if self._fleet_hybrid is None:
            try:
                import jax

                from .multichip import make_hybrid_mesh

                n = len(jax.devices())
                if self.cfg.mesh_devices:
                    n = min(n, self.cfg.mesh_devices)
                hosts = self.cfg.mesh_hosts
                chips = max(1, n // hosts)
                self._fleet_hybrid = make_hybrid_mesh(hosts, chips)
                self._fleet_hybrid_state = "ready"
                events.emit(
                    "verify.mesh", state="ready", hosts=hosts,
                    chips_per_host=chips,
                )
            except Exception as e:  # mesh is an upgrade, never a gate
                self._fleet_hybrid_state = "failed"
                log.warning(
                    "[Engine] hybrid fleet mesh unavailable, per-host "
                    "single-chip dispatch: %s", e,
                )
                events.emit(
                    "verify.mesh", state="failed", error=str(e)[:300]
                )
                return None
        return self._fleet_hybrid

    def _host_mesh(self, hs: _HostState):
        """This host's 1-D device sub-mesh at its current healthy width
        (its hybrid-mesh row via :func:`multichip.host_submesh`; None =
        single-chip dispatch).  Thread-safe: dispatch worker threads
        race on first build and after shrink/re-grow."""
        if hs.mesh_state == "ready":
            return hs.mesh
        if hs.mesh_state == "failed":
            return None
        with self._mesh_lock:
            if hs.mesh_state != "cold":
                return hs.mesh if hs.mesh_state == "ready" else None
            hybrid = self._fleet_hybrid_mesh()
            if hybrid is None:
                hs.mesh_state = "failed"
                return None
            try:
                from .multichip import host_submesh

                if not hs.full_chips:
                    hs.full_chips = int(hybrid.devices.shape[-1])
                    hs.chips = hs.full_chips
                hs.mesh = host_submesh(hybrid, hs.index, chips=hs.chips)
                hs.mesh_state = "ready"
                self._chips_gauge(hs.name, hs.chips)
                return hs.mesh
            except Exception as e:
                hs.mesh_state = "failed"
                events.emit(
                    "verify.mesh", state="failed", host=hs.name,
                    error=str(e)[:300],
                )
                return None

    def _dispatch_chunk(self, chunk, pad_to: int,
                        host: Optional[_HostState] = None):
        """Async device dispatch of one fixed-shape chunk: sharded over
        the host's sub-mesh in fleet mode, the local mesh when
        configured, single-chip otherwise.  Returns the (device array,
        count) handle for :func:`collect_verdicts`."""
        mesh = self._host_mesh(host) if host is not None else self._mesh()
        if mesh is not None:
            from .multichip import dispatch_raw_sharded

            return dispatch_raw_sharded(chunk, mesh, pad_to=pad_to)
        from .kernel import dispatch_batch_tpu_raw

        return dispatch_batch_tpu_raw(chunk, pad_to=pad_to)

    def _run_tpu(
        self, payloads: list, host: Optional[_HostState] = None
    ) -> list[bool]:
        """Device dispatch in fixed-size chunks: every call is one of the
        two shapes the warmup compiled (``device_batch`` steady-state,
        ``batch_size`` for small tails) — no surprise recompiles on the hot
        path.  Dispatch is pipelined at two levels: chunk N+1 is
        host-prepped while chunk N runs on the device (JAX async
        dispatch), and whole lanes overlap via ``pipeline_depth`` worker
        threads.  The packer keeps remainders queued for later
        submissions; ``min_tpu_batch`` is the shed-only floor applied
        when a lingered partial lane finally lands here (forced-tpu
        backend excepted)."""
        from .kernel import collect_verdicts, mark_pallas_broken_if_mosaic

        raw = concat_raw([as_raw_batch(p) for p in payloads])
        B = self._device_batch
        # (chunk | None, pad, (device array, count) | list[bool])
        pending: list = []
        for i in range(0, len(raw), B):
            chunk = raw.slice(i, i + B)
            if (
                len(chunk) < self.cfg.min_tpu_batch
                and self.cfg.backend != "tpu"
                and self._cpu is not None
            ):
                pending.append((None, 0, self._cpu.verify_raw(chunk)))
                metrics.inc("verify.cpu_items", len(chunk))
            else:
                # small tails take the small compiled shape, not a mostly
                # empty device_batch step
                pad = B if len(chunk) > self.cfg.batch_size else self.cfg.batch_size
                pending.append(
                    (chunk, pad, self._dispatch_chunk(chunk, pad_to=pad,
                                                      host=host))
                )
                metrics.inc("verify.tpu_items", len(chunk))
        out: list[bool] = []
        for chunk, pad, p in pending:
            if isinstance(p, list):
                out.extend(p)
                continue
            try:
                out.extend(collect_verdicts(*p))
            except Exception as e:  # noqa: BLE001 — only Mosaic recovered
                # JAX async dispatch: a Mosaic RUNTIME failure surfaces
                # here, not at the dispatch call.  Mark pallas broken and
                # re-run this chunk once through the (now XLA) program.
                if not mark_pallas_broken_if_mosaic(e):
                    raise
                out.extend(
                    collect_verdicts(
                        *self._dispatch_chunk(chunk, pad_to=pad, host=host)
                    )
                )
        return out
