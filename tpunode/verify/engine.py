"""Async batch verification engine: the queue between ingest and the TPU.

The north-star integration point (BASELINE.json): block/mempool ingest
submits (pubkey, z, r, s) items; the engine accumulates them into
fixed-shape batches (static shapes = no XLA recompilation), dispatches to
the TPU kernel — or the C++ CPU engine for small batches / no device — and
resolves per-item futures.  Double-buffered by construction: device dispatch
runs in a worker thread so the asyncio event loop (the P2P side) never
blocks, and the next batch accumulates while the previous one runs.

Mirrors the role the reference's synchronous libsecp256k1 callout plays, but
asynchronous and batched (SURVEY.md §2.3: this IS the data-parallel north
star path).
"""

from __future__ import annotations

import asyncio
import collections
import contextlib
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

from ..metrics import metrics
from ..trace import span
from .ecdsa_cpu import Point, verify_batch_cpu

__all__ = ["VerifyConfig", "VerifyEngine", "VerifyItem"]

VerifyItem = tuple[Optional[Point], int, int, int]  # (pubkey, z, r, s)


@dataclass
class VerifyConfig:
    """Knobs (gated behind NodeConfig like the reference's config surface,
    Node.hs:74-96; see BASELINE.json north_star 'gated behind the existing
    NodeConfig hooks')."""

    backend: str = "auto"  # auto | tpu | cpu | oracle
    batch_size: int = 4096  # fixed device batch shape
    max_wait: float = 0.025  # seconds to linger for a fuller batch
    min_tpu_batch: int = 128  # below this, CPU fallback is faster
    cpu_threads: int = 1


def _have_tpu() -> bool:
    try:
        import jax

        return any(d.platform == "tpu" for d in jax.devices())
    except Exception:
        return False


class VerifyEngine:
    """Submit items, await verdicts.

    Usage::

        engine = VerifyEngine(VerifyConfig())
        async with engine:
            ok = await engine.verify(items)   # list[bool]
    """

    def __init__(self, cfg: Optional[VerifyConfig] = None):
        self.cfg = cfg or VerifyConfig()
        self._queue: collections.deque[tuple[list[VerifyItem], asyncio.Future]] = (
            collections.deque()
        )
        self._kick: Optional[asyncio.Event] = None
        self._task: Optional[asyncio.Task] = None
        self._backend = self._pick_backend()
        self._cpu = None
        if self._backend in ("auto", "cpu"):
            from .cpu_native import load_native_verifier

            self._cpu = load_native_verifier()

    def _pick_backend(self) -> str:
        if self.cfg.backend != "auto":
            return self.cfg.backend
        return "auto"  # decide per batch: tpu when big enough & available

    # -- lifecycle -----------------------------------------------------------

    async def __aenter__(self) -> "VerifyEngine":
        self._kick = asyncio.Event()
        self._task = asyncio.get_running_loop().create_task(
            self._run(), name="verify-engine"
        )
        return self

    async def __aexit__(self, *exc) -> None:
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
        # fail any stragglers
        for _, fut in self._queue:
            if not fut.done():
                fut.cancel()
        self._queue.clear()

    # -- API -----------------------------------------------------------------

    async def verify(self, items: Sequence[VerifyItem]) -> list[bool]:
        """Queue items; resolves when their batch has been verified."""
        if not items:
            return []
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._queue.append((list(items), fut))
        assert self._kick is not None, "engine not started"
        self._kick.set()
        return await fut

    def verify_sync(self, items: Sequence[VerifyItem]) -> list[bool]:
        """Blocking verification (benchmarks, scripts): no queueing."""
        return self._dispatch(list(items))

    # -- internals -----------------------------------------------------------

    async def _run(self) -> None:
        assert self._kick is not None
        while True:
            await self._kick.wait()
            self._kick.clear()
            # linger briefly to let a fuller batch accumulate
            deadline = time.monotonic() + self.cfg.max_wait
            while (
                sum(len(i) for i, _ in self._queue) < self.cfg.batch_size
                and time.monotonic() < deadline
            ):
                await asyncio.sleep(0.002)
            while self._queue:
                batch: list[tuple[list[VerifyItem], asyncio.Future]] = []
                total = 0
                while self._queue and total < self.cfg.batch_size:
                    items, fut = self._queue.popleft()
                    batch.append((items, fut))
                    total += len(items)
                flat = [it for items, _ in batch for it in items]
                metrics.inc("verify.batches")
                metrics.inc("verify.items", len(flat))
                metrics.set_gauge(
                    "verify.batch_occupancy", total / self.cfg.batch_size
                )
                try:
                    results = await asyncio.to_thread(self._dispatch, flat)
                except Exception as e:  # engine errors fail the waiters
                    for _, fut in batch:
                        if not fut.done():
                            fut.set_exception(e)
                    continue
                pos = 0
                for items, fut in batch:
                    if not fut.done():
                        fut.set_result(results[pos : pos + len(items)])
                    pos += len(items)

    def _dispatch(self, items: list[VerifyItem]) -> list[bool]:
        """Pick an execution engine and run the batch (worker thread)."""
        with span("verify.dispatch"):
            return self._dispatch_inner(items)

    def _dispatch_inner(self, items: list[VerifyItem]) -> list[bool]:
        backend = self.cfg.backend
        if backend == "auto":
            if len(items) >= self.cfg.min_tpu_batch and _have_tpu():
                backend = "tpu"
            elif self._cpu is not None:
                backend = "cpu"
            else:
                backend = "oracle"
        t0 = time.perf_counter()
        if backend == "tpu":
            from .kernel import verify_batch_tpu

            out = verify_batch_tpu(items, pad_to=self._pad_size(len(items)))
            metrics.inc("verify.tpu_items", len(items))
        elif backend == "cpu" and self._cpu is not None:
            out = self._cpu.verify_batch(items)
            metrics.inc("verify.cpu_items", len(items))
        else:
            out = verify_batch_cpu(items)
            metrics.inc("verify.oracle_items", len(items))
        dt = time.perf_counter() - t0
        metrics.inc("verify.seconds", dt)
        return out

    def _pad_size(self, n: int) -> int:
        """Static shapes for XLA: pad to the fixed batch size (or the next
        power of two below it for small batches)."""
        size = 128
        while size < n:
            size *= 2
        return min(max(size, 128), max(self.cfg.batch_size, n))
