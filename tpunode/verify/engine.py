"""Async batch verification engine: the queue between ingest and the TPU.

The north-star integration point (BASELINE.json): block/mempool ingest
submits VerifyItem tuples (ECDSA / BCH Schnorr / BIP340 — see
tpunode/verify/raw.py); the engine accumulates them into
fixed-shape batches (static shapes = no XLA recompilation), dispatches to
the TPU kernel — or the C++ CPU engine for small batches / no device — and
resolves per-item futures.

Streaming pipeline (ISSUE 10): queued submissions are no longer dispatched
FIFO-coalesced — a lane-packing scheduler (:mod:`tpunode.verify.sched`)
bins pending payloads into full ``device_batch`` lanes across submission
boundaries with priority classes (block > mempool > bulk) and a
max-linger deadline, and up to ``VerifyConfig.pipeline_depth`` packed
lanes are in flight at once, each in its own worker thread.  JAX device
dispatch is asynchronous, so lane N+1's host prep and transfer overlap
lane N's kernel; the asyncio event loop (the P2P side) never blocks.
``pipeline_depth=1`` restores strictly serial dispatch for A/B runs.
Small remainders pack with later submissions instead of defaulting to the
CPU rung; ``min_tpu_batch`` is a shed-only floor applied when a lingering
partial lane finally dispatches.  With ``mesh_devices > 1`` the device
rung shards packed lanes over a local device mesh
(:func:`multichip.dispatch_raw_sharded`).

Device survival discipline (VERDICT r2 item 4 + ISSUE 7): the TPU path is
only used after an off-queue **warmup** (backend init + XLA compile at the
fixed batch shape + a verdict cross-check against the oracle) completes in
a background thread.  Until then batches flow to the CPU engine, so a box
with a broken or slow TPU backend still produces verdicts with nothing
blocked and the decision logged; a failed warmup is re-probed on a timer
(``warmup_retry``), never terminal.  Compiles go through a persistent
compilation cache so a restart reuses earlier work.

Self-healing dispatch (ISSUE 7): a batch that fails on one backend
re-dispatches down the ladder (tpu -> cpu-native -> python oracle), so
waiters get verdicts — not exceptions — for transient faults; only a
batch that fails on EVERY rung fails its waiters (and only its own: the
queue loop survives to serve the next batch).  Device-rung failures feed
a :class:`CircuitBreaker` (``ready -> degraded -> open -> probing ->
ready``): repeated failures inside a window open the breaker and route
all traffic to the CPU, then a periodic half-open canary batch re-probes
the device and restores the fast path when it recovers.  The state
machine is observable as ``verify.breaker`` events, the
``verify.breaker_state`` gauge, engine ``stats()`` and ``/health``.

Mirrors the role the reference's synchronous libsecp256k1 callout plays, but
asynchronous and batched (SURVEY.md §2.3: this IS the data-parallel north
star path).
"""

from __future__ import annotations

import asyncio
import collections
import contextlib
import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from ..actors import spawn_supervised
from ..chaos import chaos
from ..events import events
from ..metrics import metrics
from ..trace import span
from ..tracectx import activate as _activate_trace, current as _trace_current
from .ecdsa_cpu import Point, verify_batch_cpu
from .raw import as_raw_batch, concat_raw
from .sched import (
    OCCUPANCY_BUCKETS as _OCCUPANCY_BUCKETS,
    LanePacker,
    PackedLane,
    Submission,
)

__all__ = [
    "CircuitBreaker",
    "VerifyConfig",
    "VerifyEngine",
    "VerifyItem",
    "enable_compile_cache",
]

# (pubkey, z, r, s) for ECDSA; 5-tuples append "schnorr" (BCH) or
# "bip340" (taproot) with the precomputed challenge in the z position.
VerifyItem = tuple  # see raw.pack_items for the per-algorithm rules

log = logging.getLogger("tpunode.verify")

_DEFAULT_CACHE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".jax_cache",
)


def enable_compile_cache(path: Optional[str] = None) -> None:
    """Point JAX's persistent compilation cache at ``path`` (idempotent).

    The kernel's XLA program is large; a cold compile can take minutes on
    some backends.  With the cache enabled, any process on this machine
    (engine warmup, bench.py, tests) reuses the first successful compile.
    """
    import jax

    target = path or os.environ.get("TPUNODE_JAX_CACHE") or _DEFAULT_CACHE
    try:
        if not jax.config.jax_compilation_cache_dir:
            jax.config.update("jax_compilation_cache_dir", target)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # cache is an optimization, never a hard failure
        log.debug("compilation cache unavailable: %s", e)


class BigShapeFailed(RuntimeError):
    """Warmup outcome: the small device shape compiled and cross-checked
    but the steady-state ``device_batch`` shape did not compile.  Carries
    the device kind so the engine can stay on the device path with
    ``device_batch`` degraded to ``batch_size``."""

    def __init__(self, kind: str, error: str):
        super().__init__(error)
        self.kind = kind


def _device_warmup(batch_size: int, device_batch: int = 0) -> str:
    """Default warmup body (runs in a daemon thread): init the backend,
    compile the kernel at the engine's fixed batch shapes (the small
    ``batch_size`` shape first so readiness comes early, then the big
    ``device_batch`` steady-state shape), and cross-check a small batch
    against the oracle.  Returns the device kind string.  Raises on any
    failure — including a verdict mismatch, which must disqualify the
    device path permanently."""
    import jax

    enable_compile_cache()
    devs = [d for d in jax.devices() if d.platform == "tpu"]
    if not devs:
        raise RuntimeError("no TPU device visible")
    from .ecdsa_cpu import (
        CURVE_N,
        GENERATOR,
        bip340_challenge,
        lift_x,
        point_mul,
        schnorr_challenge,
        sign,
        sign_bip340,
        sign_schnorr,
    )
    from .kernel import verify_batch_tpu

    items = []
    expect = []
    for i in range(8):
        priv = (0xA11CE + i) % CURVE_N
        pub = point_mul(priv, GENERATOR)
        z = (0xD00D << i) % CURVE_N
        # every algorithm's lane compiles + cross-checks in the one program
        if i % 4 == 1:
            r, s = sign_schnorr(priv, z, 0xC0FFEE + i)
            if i % 3 == 2:
                z ^= 1
            items.append((pub, schnorr_challenge(r, pub, z), r, s, "schnorr"))
            expect.append(i % 3 != 2)
            continue
        if i % 4 == 3:
            r, s = sign_bip340(priv, z, 0xC0FFEE + i)
            if i % 3 == 2:
                z ^= 1
            items.append(
                (lift_x(pub.x), bip340_challenge(r, pub.x, z), r, s, "bip340")
            )
            expect.append(i % 3 != 2)
            continue
        r, s = sign(priv, z, 0xC0FFEE + i)
        if i % 3 == 2:
            z ^= 1
        items.append((pub, z, r, s))
        expect.append(i % 3 != 2)
    from .kernel import with_mosaic_fallback

    kind = f"{devs[0].platform}:{getattr(devs[0], 'device_kind', '?')}"
    # A Mosaic RUNTIME failure surfaces at collect time inside
    # verify_batch_tpu, past _dispatch_prep's compile-stage catch: mark
    # pallas broken and retry once through the XLA program instead of
    # pinning the engine to CPU for the whole process.
    got = with_mosaic_fallback(
        lambda: verify_batch_tpu(items, pad_to=batch_size),
        "during warmup",
    )
    if got != expect:
        raise RuntimeError("device/oracle verdict mismatch during warmup")
    if device_batch and device_batch != batch_size:
        try:
            got = verify_batch_tpu(items, pad_to=device_batch)
        except Exception as e:  # noqa: BLE001 — verdict errors re-raised below
            # The small shape works but the steady-state shape doesn't
            # compile (e.g. the XLA fallback at 32768 during a Mosaic
            # outage): keep the device path, chunk at the small shape.
            # (A Mosaic error here is unreachable in practice — the
            # small-shape pass above already forced the XLA program —
            # and degrading to the known-good small shape handles it.)
            raise BigShapeFailed(
                kind, f"{type(e).__name__}: {e}"[:300]
            ) from e
        if got != expect:
            raise RuntimeError(
                "device/oracle verdict mismatch at device_batch"
            )
    return kind


class CircuitBreaker:
    """Device-path health state machine (ISSUE 7).

    States (``STATES`` order is the ``verify.breaker_state`` gauge
    encoding):

    * ``ready``    — device path in use, no recent failures.
    * ``degraded`` — failures seen inside the window (< threshold); the
      device is still used, each failed batch already re-ran on the CPU
      rung via the dispatch ladder.
    * ``open``     — threshold reached: all traffic to the CPU, the
      device isn't attempted at all until the cooldown elapses.
    * ``probing``  — cooldown elapsed: exactly one live batch is routed
      to the device as a half-open canary.  Success closes the breaker
      (``ready``, recovery latency observed); failure re-opens it and
      restarts the cooldown.

    Thread-safe: transitions happen on the engine's dispatch worker
    thread (ladder outcomes) and the queue loop (backend picks).  Every
    transition emits one ``verify.breaker`` event and updates the
    ``verify.breaker_state`` gauge.
    """

    STATES = ("ready", "degraded", "open", "probing")

    def __init__(
        self, threshold: int = 3, window: float = 30.0, cooldown: float = 5.0
    ):
        self.threshold = max(1, threshold)
        self.window = window
        self.cooldown = cooldown
        self._lock = threading.Lock()
        self._state = "ready"
        self._failures: collections.deque[float] = collections.deque()
        self._opened_at: Optional[float] = None
        self._last_error: Optional[str] = None
        self.opens = 0
        self.closes = 0

    @property
    def state(self) -> str:
        return self._state

    def allow_device(self) -> bool:
        """May this batch take the device path?  ``open -> probing`` when
        the cooldown has elapsed — the caller's batch becomes the canary
        (exactly one: while ``probing``, everyone else stays on cpu)."""
        with self._lock:
            if self._state in ("ready", "degraded"):
                return True
            if self._state == "probing":
                return False  # a canary is already in flight
            now = time.monotonic()
            if (
                self._opened_at is not None
                and now - self._opened_at >= self.cooldown
            ):
                self._transition("probing")
                return True
            return False

    def record_success(self) -> None:
        """A device batch completed: close toward ``ready``."""
        with self._lock:
            self._failures.clear()
            if self._state == "ready":
                return
            fields = {}
            if self._opened_at is not None:
                recovery = time.monotonic() - self._opened_at
                metrics.observe("verify.breaker_recovery_seconds", recovery)
                fields["recovery_seconds"] = round(recovery, 3)
            if self._state in ("open", "probing"):
                self.closes += 1
                metrics.inc("verify.breaker_closes")
            self._opened_at = None
            self._last_error = None
            self._transition("ready", **fields)

    def record_failure(self, error: str = "") -> None:
        """A device batch failed (the ladder already re-dispatched it)."""
        with self._lock:
            now = time.monotonic()
            self._failures.append(now)
            while self._failures and now - self._failures[0] > self.window:
                self._failures.popleft()
            self._last_error = error or None
            if (
                self._state == "probing"
                or len(self._failures) >= self.threshold
            ):
                # a failed canary re-opens immediately; repeated failures
                # inside the window open from ready/degraded
                self._opened_at = now
                if self._state != "open":
                    self.opens += 1
                    metrics.inc("verify.breaker_opens")
                    self._transition(
                        "open", failures=len(self._failures), error=error,
                    )
            elif self._state == "ready":
                self._transition(
                    "degraded", failures=len(self._failures), error=error,
                )

    def _transition(self, to: str, **fields) -> None:
        # lock held by the caller
        frm, self._state = self._state, to
        metrics.set_gauge(
            "verify.breaker_state", float(self.STATES.index(to))
        )
        log.warning("[Engine] breaker %s -> %s %s", frm, to, fields or "")
        events.emit("verify.breaker", **{"from": frm, "to": to, **fields})

    def stats(self) -> dict:
        with self._lock:
            out = {
                "state": self._state,
                "failures_in_window": len(self._failures),
                "threshold": self.threshold,
                "opens": self.opens,
                "closes": self.closes,
                "last_error": self._last_error,
            }
            if self._opened_at is not None:
                out["open_age_seconds"] = round(
                    time.monotonic() - self._opened_at, 3
                )
            return out


@dataclass
class VerifyConfig:
    """Knobs (gated behind NodeConfig like the reference's config surface,
    Node.hs:74-96; see BASELINE.json north_star 'gated behind the existing
    NodeConfig hooks')."""

    backend: str = "auto"  # auto | tpu | cpu | oracle
    batch_size: int = 4096  # small device shape / queue coalescing threshold
    # Steady-state device shape: the Pallas kernel's measured sweet spot is
    # 32768 (210.9k sigs/s vs 54.5k at 4096 — PERF.md r3 table; VERDICT r3
    # item 4).  Work under ``batch_size`` pads to the small shape, bigger
    # work is chunked at this size; warmup compiles both shapes.
    device_batch: int = 32768
    max_wait: float = 0.025  # seconds to linger for a fuller batch
    # Streaming pipeline width (ISSUE 10): how many packed lanes may be
    # in flight at once, each in its own dispatch thread.  2 overlaps
    # lane N+1's host prep + transfer with lane N's kernel (JAX async
    # dispatch); 1 restores the serial pre-pipeline dispatch for A/B.
    pipeline_depth: int = 2
    # Mesh-aware device rung (ISSUE 10): >1 shards each packed lane over
    # a mesh of that many local devices (multichip.dispatch_raw_sharded)
    # when they are visible; 0/1 keeps single-chip dispatch.  The mesh
    # program compiles on first dispatch (warmup compiles the single-chip
    # shapes only).
    mesh_devices: int = 0
    # Below this, the CPU engine beats a device step padded to batch_size:
    # the device pays one full fixed-shape step regardless of occupancy,
    # while the C++ engine verifies ~4.8k sigs/s — crossover near
    # batch_size/4.  Small remainder chunks also route to CPU.
    min_tpu_batch: int = 1024
    # CPU-fallback verify parallelism: 1 = serial (the measurement-honest
    # default on this 1-core dev box), 0 = all hardware threads, N = N OS
    # threads (secp_verify_batch_mt; each MSM row is independent).
    cpu_threads: int = 1
    # device warmup discipline
    warmup_timeout: float = 600.0  # backend=tpu: max wait for warmup
    warmup: bool = True  # start warmup thread on engine start
    # A failed warmup is re-probed after this many seconds (ISSUE 7:
    # the old terminal `failed` state outlived many a transient outage
    # — the r5 Mosaic remote-compile 500s cleared within the round).
    # 0 disables re-probing (the pre-ISSUE-7 terminal behavior).
    warmup_retry: float = 60.0
    # Circuit breaker on the device dispatch path (ISSUE 7):
    # `breaker_threshold` failures inside `breaker_window` seconds open
    # the breaker (all traffic to cpu); after `breaker_cooldown` seconds
    # one live batch probes the device and, on success, restores the
    # fast path.
    breaker_threshold: int = 3
    breaker_window: float = 30.0
    breaker_cooldown: float = 5.0
    # Field-arithmetic formulation (ISSUE 4): None keeps the process-wide
    # mode (TPUNODE_FIELD_MUL / TPUNODE_FIELD_SQR env knobs, defaults
    # measured in PERF.md's roofline section); "shift_add"/"dot_general"
    # and "half"/"mul" select explicitly.  Applied process-globally at
    # engine construction — every device program keys its jit cache on
    # the modes, so the first dispatch traces the requested formulation.
    field_mul: Optional[str] = None
    field_sqr: Optional[str] = None
    # MSM point form (ISSUE 8): None keeps the process-wide mode
    # (TPUNODE_POINT_FORM env knob); "projective"/"affine" select
    # explicitly.  Applied process-globally at engine construction like
    # the field knobs — every device program keys its jit cache on
    # kernel.kernel_modes(), so the first dispatch traces the requested
    # formulation.  Verdicts are bit-identical across forms.
    point_form: Optional[str] = None
    # Field reduction discipline (ISSUE 12): None keeps the process-wide
    # mode (TPUNODE_FIELD_REDUCE env knob); "eager"/"lazy" select
    # explicitly.  "lazy" accumulates unreduced products in curve.py's
    # formulas and pays one reduction per expression — values differ
    # limb-wise, verdicts are bit-identical; int32 safety is asserted at
    # trace time by tpunode.verify.bounds.
    field_reduce: Optional[str] = None
    # MSM window width (ISSUE 12): None keeps the process-wide mode
    # (TPUNODE_WINDOW_BITS env knob); 4 keeps the 33-round/16-entry r3
    # structure, 5 runs 27 rounds over 32-entry tables (host prep falls
    # back to the Python path — the native layout is 4-bit).
    window_bits: Optional[int] = None

    def __post_init__(self):
        if self.device_batch < self.batch_size:
            self.device_batch = self.batch_size
        if self.pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1")
        if (
            self.field_mul is not None
            or self.field_sqr is not None
            or self.field_reduce is not None
        ):
            from . import field as _field

            _field.set_field_modes(
                mul=self.field_mul,
                sqr=self.field_sqr,
                reduce=self.field_reduce,
            )
        if self.point_form is not None:
            from . import curve as _curve

            _curve.set_point_form(self.point_form)
        if self.window_bits is not None:
            from . import kernel as _kernel

            _kernel.set_kernel_modes(window_bits=self.window_bits)


class VerifyEngine:
    """Submit items, await verdicts.

    Usage::

        engine = VerifyEngine(VerifyConfig())
        async with engine:
            ok = await engine.verify(items)   # list[bool]
    """

    # Test seam: replace to simulate slow/broken device warmup.
    _warmup_fn: Callable[[int], str] = staticmethod(_device_warmup)

    def __init__(self, cfg: Optional[VerifyConfig] = None):
        self.cfg = cfg or VerifyConfig()
        # Lane-packing scheduler (ISSUE 10): submissions (with their
        # futures and trace positions) queue here; the pipeline loop
        # pops packed lanes from it.
        self._packer = LanePacker()
        # Per-inflight dispatch start times keyed by a monotonic token
        # (ISSUE 10 watchdog satellite): with pipeline_depth > 1 a single
        # scalar would misattribute or miss stalls — the watchdog's
        # dispatch-stall signal reports the OLDEST in-flight dispatch.
        # Written by the queue loop and the lane tasks, read by the
        # watchdog thread: guarded by _inflight_lock.
        self._inflight: dict[int, float] = {}
        self._inflight_lock = threading.Lock()
        self._inflight_seq = 0
        self._lane_tasks: set[asyncio.Task] = set()
        self._slots: Optional[asyncio.Semaphore] = None
        self._kick: Optional[asyncio.Event] = None
        self._task: Optional[asyncio.Task] = None
        # sharded device rung (cfg.mesh_devices): lazily-built mesh;
        # "failed" means mesh construction was tried and is off for
        # good.  Init races between concurrent dispatch worker threads
        # (pipeline_depth > 1) are serialized by _mesh_lock — without
        # it two lanes would double-build (and double-compile), and a
        # transient loser could pin "failed" over a winner's mesh.
        self._mesh_obj = None
        self._mesh_state = "cold"
        self._mesh_lock = threading.Lock()
        self._cpu = None
        if self.cfg.backend in ("auto", "cpu"):
            from .cpu_native import load_native_verifier

            self._cpu = load_native_verifier()
        # Steady-state device shape actually in use: starts at the config
        # value, degraded to batch_size if the big shape fails to compile
        # (never written back into the caller's cfg).
        self._device_batch = self.cfg.device_batch
        # device readiness state machine: cold -> warming -> ready | failed
        # (failed re-probes on the warmup_retry timer — never terminal)
        self._device_state = "cold"
        self._device_kind = ""
        self._device_error: Optional[str] = None
        self._warmup_started = 0.0
        self._warmup_failed_at = 0.0
        self._warmup_lock = threading.Lock()
        self._warmup_done = threading.Event()
        self._slow_logged = False
        # device-dispatch circuit breaker (ISSUE 7): engaged only once
        # the device is warm; open = all traffic on the cpu rungs
        self._breaker = CircuitBreaker(
            threshold=self.cfg.breaker_threshold,
            window=self.cfg.breaker_window,
            cooldown=self.cfg.breaker_cooldown,
        )
        if self.cfg.warmup and self.cfg.backend in ("auto", "tpu"):
            self.start_warmup()

    # -- device warmup -------------------------------------------------------

    def start_warmup(self) -> None:
        """Kick off device warmup in a daemon thread (idempotent).  The
        thread is never joined on the hot path: if compile stalls, dispatch
        simply keeps using the CPU engine; if it eventually succeeds, the
        device path switches on."""
        if self._device_state != "cold":
            return
        self._device_state = "warming"
        self._warmup_started = time.monotonic()

        def run() -> None:
            try:
                if chaos.on:  # injected compile/init failure (ISSUE 7)
                    chaos.maybe_raise("engine.warmup")
                kind = type(self)._warmup_fn(
                    self.cfg.batch_size, self.cfg.device_batch
                )
            except BigShapeFailed as e:
                # Small shape is good; stay on the device path chunked at
                # the small shape instead of losing the device entirely.
                self._device_batch = self.cfg.batch_size
                self._device_kind = e.kind
                self._device_state = "ready"
                log.warning(
                    "[Engine] device ready (%s) but device_batch shape "
                    "failed to compile (%s) — chunking at batch_size=%d",
                    e.kind,
                    e,
                    self.cfg.batch_size,
                )
                events.emit(
                    "verify.device", state="ready", kind=e.kind,
                    degraded_batch=self.cfg.batch_size, error=str(e),
                )
            except Exception as e:  # noqa: BLE001 — any failure disables tpu
                self._device_error = f"{type(e).__name__}: {e}"
                self._warmup_failed_at = time.monotonic()
                self._device_state = "failed"
                log.warning(
                    "[Engine] device warmup failed, using cpu engine"
                    " (re-probe in %.0fs): %s",
                    self.cfg.warmup_retry,
                    self._device_error,
                )
                events.emit(
                    "verify.device", state="failed", error=self._device_error
                )
            else:
                self._device_kind = kind
                self._device_state = "ready"
                dt = time.monotonic() - self._warmup_started
                log.info("[Engine] device ready (%s) after %.1fs", kind, dt)
                events.emit(
                    "verify.device", state="ready", kind=kind,
                    warmup_seconds=round(dt, 3),
                )
            finally:
                self._warmup_done.set()

        threading.Thread(target=run, name="verify-warmup", daemon=True).start()

    def _retry_warmup(self) -> None:
        """Re-probe a failed device warmup (ISSUE 7: `failed` is a
        cooldown, not a verdict).  Called from the dispatch path once the
        retry interval elapses; idempotent and thread-safe — exactly one
        caller flips failed -> cold and relaunches the warmup thread."""
        with self._warmup_lock:
            if self._device_state != "failed":
                return
            if (
                time.monotonic() - self._warmup_failed_at
                < self.cfg.warmup_retry
            ):
                return
            log.info(
                "[Engine] re-probing device warmup after failure: %s",
                self._device_error,
            )
            events.emit("verify.device", state="reprobe",
                        error=self._device_error)
            # fresh latch: forced-tpu waiters must block on THIS attempt
            self._warmup_done = threading.Event()
            self._slow_logged = False
            self._device_state = "cold"
            self.start_warmup()

    @property
    def device_state(self) -> str:
        return self._device_state

    @property
    def breaker(self) -> CircuitBreaker:
        return self._breaker

    @property
    def breaker_state(self) -> str:
        """Device-path breaker state (``/health``): the warmup machine's
        view until the device is warm, the breaker's after."""
        if self._device_state != "ready":
            return self._device_state
        return self._breaker.state

    def queue_depth(self) -> dict:
        """Current backlog: queued submissions, total unclaimed items,
        and the per-priority split (``by_priority`` is itself a dict)."""
        return {
            "batches": self._packer.batches(),
            "items": self._packer.pending(),
            "by_priority": self._packer.depths(),
        }

    def dispatch_inflight_seconds(self) -> float:
        """Age of the OLDEST in-flight dispatch across the pipeline
        (0.0 when idle) — the stall watchdog's signal.  A wedged device
        backend pins the oldest entry while younger lanes (and the event
        loop) stay healthy."""
        with self._inflight_lock:
            if not self._inflight:
                return 0.0
            return time.monotonic() - min(self._inflight.values())

    def dispatch_inflight(self) -> int:
        """How many packed lanes are currently in dispatch threads."""
        with self._inflight_lock:
            return len(self._inflight)

    def stats(self) -> dict:
        """Telemetry snapshot for Node.stats()/health()."""
        out = {
            "backend": self.cfg.backend,
            "device_state": self._device_state,
            "device_kind": self._device_kind or None,
            "device_error": self._device_error,
            "device_batch": self._device_batch,
            "backlog": self.queue_depth(),
            "dispatch_inflight_seconds": round(
                self.dispatch_inflight_seconds(), 3
            ),
            "dispatch_inflight": self.dispatch_inflight(),
            "pipeline_depth": self.cfg.pipeline_depth,
            "lanes": metrics.get("sched.lanes"),
            "batches": metrics.get("verify.batches"),
            "items": metrics.get("verify.items"),
            "errors": metrics.get("verify.dispatch_errors"),
            "failovers": metrics.get("verify.failovers"),
            "breaker": self._breaker.stats(),
        }
        occ = metrics.histogram("verify.occupancy")
        if occ is not None:
            out["occupancy"] = occ.summary()
        pack = metrics.histogram("sched.pack_efficiency")
        if pack is not None:
            out["pack_efficiency"] = pack.summary()
        disp = metrics.histogram("span.verify.dispatch")
        if disp is not None:
            out["dispatch_seconds"] = disp.summary()
        return out

    # -- lifecycle -----------------------------------------------------------

    async def __aenter__(self) -> "VerifyEngine":
        self._kick = asyncio.Event()
        self._slots = asyncio.Semaphore(self.cfg.pipeline_depth)
        self._closing = False  # task-registry owner convention (actors.py)
        # ISSUE 3 satellite: the queue loop was a bare create_task handle —
        # registry-supervised now, cancelled+awaited in __aexit__ below
        self._task = spawn_supervised(
            self._run(), name="verify-engine", owner=self
        )
        return self

    async def __aexit__(self, *exc) -> None:
        self._closing = True
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
        # in-flight lanes: cancel + await (their dispatch threads finish
        # behind the cancelled await; verdicts for cancelled lanes are
        # dropped with the futures below)
        for t in list(self._lane_tasks):
            t.cancel()
        for t in list(self._lane_tasks):
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await t
        self._lane_tasks.clear()
        # fail any stragglers still queued (or partially claimed)
        for sub in self._packer.drain():
            if not sub.fut.done():
                sub.fut.cancel()

    # -- API -----------------------------------------------------------------

    async def verify(
        self, items: Sequence[VerifyItem], priority: str = "bulk"
    ) -> list[bool]:
        """Queue items; resolves when their lanes have been verified.
        ``priority``: ``block`` > ``mempool`` > ``bulk`` (sched.py) — the
        class whose lanes pack and dispatch first under saturation."""
        return await self._enqueue(list(items), priority)

    async def verify_raw(self, raw, priority: str = "bulk") -> list[bool]:
        """Queue a packed batch (RawBatch, or anything `as_raw_batch`
        coerces, e.g. txextract.RawSigItems): the native-extract fast path —
        no per-item Python objects anywhere between wire bytes and device."""
        return await self._enqueue(as_raw_batch(raw), priority)

    async def _enqueue(self, payload, priority: str = "bulk") -> list[bool]:
        if not len(payload):
            return []
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        act = _trace_current()
        if act is not None:
            # queue-wait + dispatch as one span in the submitter's trace:
            # closed when the submission's future resolves, however it
            # resolves — per payload even when the packer slices it
            # across several lanes (ISSUE 10 trace satellite)
            tr = act[0]
            rec = tr.begin("verify.queue", act[1], items=len(payload))
            fut.add_done_callback(lambda _f, tr=tr, rec=rec: tr.end(rec))
        self._packer.push(Submission(payload, fut, act, priority))
        assert self._kick is not None, "engine not started"
        self._kick.set()
        return await fut

    def verify_sync(self, items: Sequence[VerifyItem]) -> list[bool]:
        """Blocking verification (benchmarks, scripts): no queueing."""
        return self._dispatch(list(items))

    def verify_raw_sync(self, raw) -> list[bool]:
        """Blocking raw-batch verification (benchmarks, scripts)."""
        return self._dispatch(as_raw_batch(raw))

    # -- internals -----------------------------------------------------------

    def _lane_target(self) -> int:
        """Pack/fill goal: the steady-state device shape once the device
        is up, the small shape before."""
        return (
            self._device_batch
            if self._device_state == "ready"
            else self.cfg.batch_size
        )

    async def _run(self) -> None:
        """Pipeline scheduler loop: linger toward full lanes, then keep up
        to ``pipeline_depth`` packed lanes in flight (each in its own
        dispatch thread — lane N+1's host prep and transfer overlap lane
        N's kernel under JAX async dispatch)."""
        assert self._kick is not None and self._slots is not None
        while True:
            # wait for work
            while not self._packer.pending():
                await self._kick.wait()
                self._kick.clear()
            target = self._lane_target()
            # Event-driven fill (VERDICT r4 weak #6 — the former 2 ms poll
            # burned ≤500 wakes/s per linger window): sleep until either a
            # new enqueue kicks, or the linger deadline passes.  The
            # deadline anchors on the OLDEST queued submission, so a
            # remainder lingers for later submissions to pack with only
            # while its submitter is younger than max_wait (ISSUE 10:
            # max-linger — a lone small batch still dispatches promptly).
            while self._packer.pending() < target:
                oldest = self._packer.oldest_enqueued()
                if oldest is None:
                    break
                remain = oldest + self.cfg.max_wait - time.monotonic()
                if remain <= 0:
                    break
                try:
                    await asyncio.wait_for(self._kick.wait(), timeout=remain)
                except asyncio.TimeoutError:
                    break
                self._kick.clear()
            if not self._packer.pending():
                continue
            # admission: a free pipeline slot (more work keeps queueing —
            # and packing fuller lanes — while every slot is busy)
            await self._slots.acquire()
            lane = self._packer.pop_lane(self._lane_target())
            if lane is None:
                self._slots.release()
                continue
            task = spawn_supervised(
                self._dispatch_lane(lane), name="verify-lane", owner=self
            )
            self._lane_tasks.add(task)
            task.add_done_callback(self._lane_tasks.discard)

    async def _dispatch_lane(self, lane: PackedLane) -> None:
        """Run one packed lane end to end: dispatch in a worker thread
        (the ladder/breaker/failover semantics of :meth:`_run_ladder`
        apply per in-flight lane), then deliver each slice's verdicts to
        its submission.  A lane that fails on every rung fails exactly
        the submissions it carries slices of."""
        assert self._kick is not None and self._slots is not None
        payloads = lane.payloads()
        total = lane.total
        metrics.inc("verify.batches")
        metrics.inc("verify.items", total)
        metrics.set_gauge("verify.batch_occupancy", lane.occupancy)
        with self._inflight_lock:
            self._inflight_seq += 1
            token = self._inflight_seq
            self._inflight[token] = time.monotonic()
        try:
            results = await asyncio.to_thread(
                self._dispatch_traced, payloads, lane.target, lane.act0
            )
        except asyncio.CancelledError:
            # engine teardown mid-dispatch: waiters must not hang on a
            # future nobody will resolve
            for sub, _, _ in lane.slices:
                if not sub.fut.done():
                    sub.fut.cancel()
            raise
        except Exception as e:  # all rungs failed: the waiters learn it
            log.error("[Engine] lane of %d failed: %s", total, e)
            for sub, _, _ in lane.slices:
                sub.fail(e)
            return
        finally:
            with self._inflight_lock:
                self._inflight.pop(token, None)
            self._slots.release()
            self._kick.set()  # a freed slot may unblock the scheduler
        pos = 0
        for sub, lo, hi in lane.slices:
            sub.deliver(lo, results[pos : pos + (hi - lo)])
            pos += hi - lo

    def _dispatch(self, payload) -> list[bool]:
        """Pick an execution engine and run one payload (worker thread)."""
        return self._dispatch_multi([payload])

    def _dispatch_traced(
        self, payloads: list, target: Optional[int], act: Optional[tuple]
    ) -> list[bool]:
        """Worker-thread entry: re-activate the submitting item's trace
        (contextvars do not cross ``to_thread`` from the queue loop — the
        loop's own context has no trace) so the dispatch/prepare/transfer/
        kernel/readback spans land in the item's pipeline tree."""
        with _activate_trace(act):
            return self._dispatch_multi(payloads, target)

    def _pick(self, n: int) -> str:
        """Resolve the starting backend rung for one batch.  Never blocks
        except for the forced-tpu backend, which waits (bounded) for
        warmup.  The device path additionally passes through the circuit
        breaker: open = cpu, one canary batch while probing."""
        backend = self.cfg.backend
        if (
            backend in ("auto", "tpu")
            and self._device_state == "failed"
            and self.cfg.warmup_retry > 0
        ):
            self._retry_warmup()  # no-op until the retry interval elapses
        if backend == "tpu":
            if self._device_state == "cold":  # cfg.warmup=False: warm lazily
                self.start_warmup()
            if self._device_state == "warming":
                remain = self.cfg.warmup_timeout - (
                    time.monotonic() - self._warmup_started
                )
                self._warmup_done.wait(timeout=max(0.0, remain))
            if self._device_state != "ready":
                raise RuntimeError(
                    "tpu backend unavailable: "
                    + (self._device_error or "warmup timed out")
                )
            return "tpu"
        if backend != "auto":
            return backend
        if (
            n >= self.cfg.min_tpu_batch
            and self._device_state == "ready"
            and self._breaker.allow_device()
        ):
            return "tpu"
        if (
            self._device_state == "warming"
            and not self._slow_logged
            and time.monotonic() - self._warmup_started > 30.0
        ):
            self._slow_logged = True
            log.info("[Engine] device warmup still running; batches on cpu")
        return "cpu" if self._cpu is not None else "oracle"

    # Linear occupancy buckets (0.05 steps) shared with the packer's
    # sched.pack_efficiency histogram so the two stay comparable.
    OCCUPANCY_BUCKETS = _OCCUPANCY_BUCKETS

    def _dispatch_multi(
        self, payloads: list, target: Optional[int] = None
    ) -> list[bool]:
        """Verify a coalesced batch of payloads (tuple lists and/or raw
        batches) on one backend; results are in payload order.  ``target``
        is the fill goal the queue lingered for (None on the synchronous
        paths) — it sizes the occupancy observation."""
        with span("verify.dispatch"):
            total = sum(len(p) for p in payloads)
            occupancy = total / target if target else None
            if occupancy is not None:
                metrics.observe(
                    "verify.occupancy",
                    min(1.0, occupancy),
                    buckets=self.OCCUPANCY_BUCKETS,
                )
            backend = self._pick(total)
            t0 = time.perf_counter()
            out, backend = self._run_ladder(backend, payloads, total)
            dt = time.perf_counter() - t0
            metrics.inc("verify.seconds", dt)
            events.emit(
                "verify.dispatch", backend=backend, size=total,
                occupancy=round(occupancy, 4) if occupancy is not None else None,
                seconds=round(dt, 6),
            )
            return out

    # Failover order (ISSUE 7): each rung is strictly more available and
    # strictly slower than the one above it; the python oracle cannot
    # fail for device/native reasons, so transient faults never surface
    # to waiters as exceptions.
    _LADDER = ("tpu", "cpu", "oracle")

    def _run_ladder(
        self, backend: str, payloads: list, total: int
    ) -> tuple[list[bool], str]:
        """Run one coalesced batch starting at ``backend``, re-dispatching
        the SAME batch down the ladder on failure.  Device-rung outcomes
        feed the circuit breaker.  Returns (results, rung that served).
        Only a batch that fails on every rung raises — and then fails
        just this batch's waiters; the queue loop survives (pinned by
        tests/test_engine.py)."""
        start = self._LADDER.index(backend) if backend in self._LADDER else 0
        rungs = [
            r
            for r in self._LADDER[start:]
            if r != "cpu" or self._cpu is not None
        ]
        for i, rung in enumerate(rungs):
            try:
                if chaos.on:  # injected batch/device failure (ISSUE 7)
                    chaos.maybe_raise("engine.dispatch", rung)
                out = self._run_backend(rung, payloads, total)
            except Exception as e:
                err = f"{type(e).__name__}: {e}"[:300]
                metrics.inc("verify.dispatch_errors")
                events.emit(
                    "verify.failure", where="dispatch", backend=rung,
                    size=total, error=err,
                )
                if rung == "tpu":
                    self._breaker.record_failure(err)
                if i + 1 >= len(rungs):
                    raise  # every rung failed: the waiters learn it
                metrics.inc("verify.failovers")
                events.emit(
                    "verify.failover", source=rung, target=rungs[i + 1],
                    size=total, error=err,
                )
                log.warning(
                    "[Engine] batch of %d failed on %s, retrying on %s: %s",
                    total, rung, rungs[i + 1], err,
                )
                continue
            if rung == "tpu":
                self._breaker.record_success()
            return out, rung
        raise RuntimeError("no verify backend available")  # unreachable

    def _run_backend(self, rung: str, payloads: list, total: int) -> list[bool]:
        """Execute one ladder rung over the coalesced payloads."""
        if rung == "tpu":
            return self._run_tpu(payloads)  # counts tpu/cpu items per chunk
        if rung == "cpu" and self._cpu is not None:
            out = self._cpu.verify_raw(
                concat_raw([as_raw_batch(p) for p in payloads]),
                nthreads=self.cfg.cpu_threads,
            )
            metrics.inc("verify.cpu_items", total)
            return out
        out = []
        for p in payloads:
            out.extend(
                verify_batch_cpu(
                    p if isinstance(p, list) else as_raw_batch(p).to_tuples()
                )
            )
        metrics.inc("verify.oracle_items", total)
        return out

    def _mesh(self):
        """Lazily-built device mesh for the sharded tpu rung (ISSUE 10):
        None when ``mesh_devices`` is off, fewer than 2 devices are
        visible, or mesh construction already failed (tried once).
        Thread-safe: concurrent lanes race to be the first dispatch."""
        if self.cfg.mesh_devices < 2 or self._mesh_state == "failed":
            return None
        with self._mesh_lock:
            if self._mesh_state == "failed":
                return None
            if self._mesh_obj is None:
                try:
                    import jax

                    from .multichip import make_mesh

                    n = min(self.cfg.mesh_devices, len(jax.devices()))
                    if n < 2:
                        raise RuntimeError(
                            f"mesh_devices={self.cfg.mesh_devices} but "
                            f"only {n} device(s) visible"
                        )
                    self._mesh_obj = make_mesh(n)
                    self._mesh_state = "ready"
                    events.emit("verify.mesh", state="ready", devices=n)
                except Exception as e:  # mesh is an upgrade, never a gate
                    self._mesh_state = "failed"
                    log.warning(
                        "[Engine] sharded dispatch unavailable, "
                        "single-chip rung: %s", e,
                    )
                    events.emit(
                        "verify.mesh", state="failed", error=str(e)[:300]
                    )
                    return None
            return self._mesh_obj

    def _dispatch_chunk(self, chunk, pad_to: int):
        """Async device dispatch of one fixed-shape chunk: sharded over
        the mesh when configured, single-chip otherwise.  Returns the
        (device array, count) handle for :func:`collect_verdicts`."""
        mesh = self._mesh()
        if mesh is not None:
            from .multichip import dispatch_raw_sharded

            return dispatch_raw_sharded(chunk, mesh, pad_to=pad_to)
        from .kernel import dispatch_batch_tpu_raw

        return dispatch_batch_tpu_raw(chunk, pad_to=pad_to)

    def _run_tpu(self, payloads: list) -> list[bool]:
        """Device dispatch in fixed-size chunks: every call is one of the
        two shapes the warmup compiled (``device_batch`` steady-state,
        ``batch_size`` for small tails) — no surprise recompiles on the hot
        path.  Dispatch is pipelined at two levels: chunk N+1 is
        host-prepped while chunk N runs on the device (JAX async
        dispatch), and whole lanes overlap via ``pipeline_depth`` worker
        threads.  The packer keeps remainders queued for later
        submissions; ``min_tpu_batch`` is the shed-only floor applied
        when a lingered partial lane finally lands here (forced-tpu
        backend excepted)."""
        from .kernel import collect_verdicts, mark_pallas_broken_if_mosaic

        raw = concat_raw([as_raw_batch(p) for p in payloads])
        B = self._device_batch
        # (chunk | None, pad, (device array, count) | list[bool])
        pending: list = []
        for i in range(0, len(raw), B):
            chunk = raw.slice(i, i + B)
            if (
                len(chunk) < self.cfg.min_tpu_batch
                and self.cfg.backend != "tpu"
                and self._cpu is not None
            ):
                pending.append((None, 0, self._cpu.verify_raw(chunk)))
                metrics.inc("verify.cpu_items", len(chunk))
            else:
                # small tails take the small compiled shape, not a mostly
                # empty device_batch step
                pad = B if len(chunk) > self.cfg.batch_size else self.cfg.batch_size
                pending.append(
                    (chunk, pad, self._dispatch_chunk(chunk, pad_to=pad))
                )
                metrics.inc("verify.tpu_items", len(chunk))
        out: list[bool] = []
        for chunk, pad, p in pending:
            if isinstance(p, list):
                out.extend(p)
                continue
            try:
                out.extend(collect_verdicts(*p))
            except Exception as e:  # noqa: BLE001 — only Mosaic recovered
                # JAX async dispatch: a Mosaic RUNTIME failure surfaces
                # here, not at the dispatch call.  Mark pallas broken and
                # re-run this chunk once through the (now XLA) program.
                if not mark_pallas_broken_if_mosaic(e):
                    raise
                out.extend(
                    collect_verdicts(
                        *self._dispatch_chunk(chunk, pad_to=pad)
                    )
                )
        return out
