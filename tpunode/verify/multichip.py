"""Multi-chip batch ECDSA verification: shard_map over a device mesh.

The BCH 32 MB-block stress config (BASELINE.json configs[4], ~150k sigs in
one block) wants more than one chip.  Signature verification has no
cross-item dependencies (SURVEY.md §2.3: data parallelism IS the north-star
axis; ring/Ulysses-style sequence parallelism is deliberately unnecessary
here and documented as such), so the multi-chip design is pure DP:

* a 1-D ``Mesh`` over all chips, axis ``"batch"``;
* every input array sharded along its batch dimension — the minor-most
  axis for limb-major arrays (see field.py), the only axis for the masks —
  so host→device transfer is split per chip;
* ``shard_map`` runs the same single-chip program :func:`kernel.verify_core`
  on each shard — zero inter-chip traffic in the hot loop;
* one ``psum`` over ICI reduces the per-shard valid-counts so every chip
  (and the host, reading one scalar) agrees on the batch verdict count —
  the only collective the algorithm needs.

Pod scale (ISSUE 13): :func:`make_hybrid_mesh` generalizes the 1-D local
mesh to a ``(host, chip)`` grid following the t5x
``create_hybrid_device_mesh`` exemplar (SNIPPETS.md [1]) — data-parallel
lane sharding across hosts with the per-host axis kept local, so the
slow DCN hop only ever carries the batch split and the one verdict-count
psum, never table traffic.  ``sharded_verify_fn`` / ``dispatch_raw_sharded``
accept either mesh shape (the batch axis shards over ALL mesh axes
jointly); :func:`host_submesh` slices one host's device row back out as
a 1-D mesh — the fleet dispatcher's per-host device rung
(engine ``mesh_hosts``).  The CPU dryrun path (conftest's 8 virtual host
devices) pins every spec without TPU hardware.

Replaces the capability of the reference's process-parallel verification
(one libsecp256k1 call per tx input across peer threads) at chip scale.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

from ..trace import span
from .ecdsa_cpu import Point
# Canonical fleet host names: owned by sched.py (next to the AffinityMap
# that seeds rendezvous scores from them, ISSUE 19), re-exported here so
# topology callers keep one import site.
from .sched import host_names
from .kernel import (
    ARG_IS_2D,
    kernel_modes,
    pallas_broken,
    prepare_batch,
    prepare_batch_raw,
    verify_core,
    with_mosaic_fallback,
)

__all__ = [
    "HYBRID_AXES",
    "make_mesh",
    "make_hybrid_mesh",
    "host_names",
    "host_submesh",
    "sharded_verify_fn",
    "verify_batch_sharded",
    "dispatch_raw_sharded",
]


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` local devices (all, if None)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), ("batch",))


#: Axis names of a hybrid (multi-host) mesh: ``host`` is the slow
#: (DCN/cross-host) axis, ``chip`` the fast per-host (ICI/local) axis.
HYBRID_AXES = ("host", "chip")


def make_hybrid_mesh(
    hosts: Optional[int] = None, chips_per_host: Optional[int] = None
) -> Mesh:
    """A ``(hosts, chips_per_host)`` mesh with the per-host axis kept
    local (the t5x ``create_hybrid_device_mesh`` shape).

    On a real multi-host pod (``jax.process_count() > 1``) the grid comes
    from ``mesh_utils.create_hybrid_device_mesh`` so the ``host`` axis
    follows DCN connectivity and each row holds exactly one process's
    local chips.  In a single process — the CPU dryrun, or a virtual
    topology carved out of one host's chips — local devices are reshaped
    into the requested grid instead (tests pin the 2x4 virtual topology
    on the conftest 8-device CPU mesh).

    Defaults: ``hosts`` = the process count (single-process: one host
    per device), ``chips_per_host`` = the per-host device count.  Raises
    when the requested grid needs more devices than are visible — a pod
    that silently shrank must not masquerade as the requested topology
    (the engine's fleet layer handles shrinking explicitly).
    """
    devs = jax.devices()
    nproc = getattr(jax, "process_count", lambda: 1)()
    if nproc > 1:  # pragma: no cover - real pod only (no CI multi-host)
        hosts = nproc if hosts is None else hosts
        if chips_per_host is None:
            chips_per_host = max(1, len(devs) // nproc)
        from jax.experimental import mesh_utils

        grid = mesh_utils.create_hybrid_device_mesh(
            (1, chips_per_host), (hosts, 1), devices=devs
        )
        return Mesh(grid, HYBRID_AXES)
    n = len(devs)
    if hosts is None and chips_per_host is None:
        hosts, chips_per_host = n, 1
    elif hosts is None:
        hosts = max(1, n // chips_per_host)
    elif chips_per_host is None:
        chips_per_host = max(1, n // hosts)
    need = hosts * chips_per_host
    if need > n:
        raise ValueError(
            f"hybrid mesh {hosts}x{chips_per_host} needs {need} devices, "
            f"only {n} visible"
        )
    grid = np.array(devs[:need]).reshape(hosts, chips_per_host)
    return Mesh(grid, HYBRID_AXES)




def host_submesh(
    mesh: Mesh, host_index: int, chips: Optional[int] = None
) -> Mesh:
    """One host's device row of a hybrid mesh as a 1-D local mesh — the
    fleet dispatcher's per-host device rung dispatches whole lanes over
    this (zero cross-host traffic per lane).  ``chips`` keeps only the
    leading that-many devices of the row (the engine's chip-by-chip
    degradation rebuilds here at the largest still-healthy width).  A
    1-D mesh is its own (only) full-width row."""
    if mesh.devices.ndim == 1 and chips is None:
        return mesh
    row = mesh.devices if mesh.devices.ndim == 1 else mesh.devices[host_index]
    devs = list(row.flat)
    if chips is not None:
        devs = devs[:chips]
    return Mesh(np.array(devs), ("batch",))


def _batch_axes(mesh: Mesh):
    """The axis-name spec entry sharding the batch dimension: the single
    name on a 1-D mesh, the name tuple on a hybrid mesh (the batch axis
    shards over host AND chip jointly — pure DP, ISSUE 13)."""
    names = tuple(mesh.axis_names)
    return names if len(names) > 1 else names[0]


_FN_CACHE: dict = {}


def _mesh_is_tpu(mesh: Mesh) -> bool:
    return all(d.platform == "tpu" for d in mesh.devices.flat)


def sharded_verify_fn(
    mesh: Mesh,
    kernel: str = "auto",
    *,
    interpret: bool = False,
    block: Optional[int] = None,
    schnorr_free: bool = False,
):
    """Jitted verify step sharded over ``mesh``: same signature as
    :func:`kernel.verify_core`, returns ``(ok: (B,) bool, total: int32)``.

    ``kernel``: "auto" picks the Pallas program per shard on an all-TPU
    mesh (per-shard batch must then be BLOCK-aligned — callers pad), the
    portable XLA program otherwise; "xla" forces the latter (the CPU-mesh
    dryrun path); "pallas" forces the Mosaic program — with
    ``interpret=True`` and a small ``block`` it runs on a CPU mesh, which
    is how tests pin the Pallas-inside-shard_map specs without TPU
    hardware (VERDICT r3 item 7).

    ``schnorr_free`` (ADVICE r5 #3): an ECDSA-only batch may select the
    pallas program variant with the jacobi/parity acceptance pows pruned
    at trace time, exactly like the single-chip dispatcher — callers must
    derive it from ``PreparedBatch.schnorr_free`` (a wrong True would
    accept jacobi/parity forgeries).  The XLA program needs no static
    flag: its runtime lax.cond gating sheds the pows per shard already.

    ``B`` must be a multiple of the mesh size (callers pad; static shapes
    also keep XLA from recompiling across batches).  Cached per mesh,
    program variant, and formulation-mode tuple (kernel.kernel_modes():
    field formulation + point form + select/ladder shape — all baked in
    at trace time) so repeated batches reuse the compiled executable.
    """
    if kernel not in ("auto", "pallas", "xla"):
        raise ValueError(f"unknown kernel {kernel!r}: auto|pallas|xla")
    use_pallas = kernel == "pallas" or (
        kernel == "auto" and _mesh_is_tpu(mesh) and not pallas_broken()
    )
    schnorr_free = bool(schnorr_free) and use_pallas
    # kernel_modes() carries the field formulation AND the point-form/
    # select/ladder knobs (ISSUE 8) — all read at trace time, so all part
    # of the cache key.  The pallas branch additionally pins point_form
    # explicitly so the impl can't drift from the keyed mode.
    key = (mesh, use_pallas, interpret, block, schnorr_free, kernel_modes())
    cached = _FN_CACHE.get(key)
    if cached is not None:
        return cached
    # limb-major layout: batch is the trailing axis of the 2-D arrays.
    # On a hybrid mesh the batch dimension shards over host AND chip
    # jointly (axis-name tuple) — same program, wider denominator.
    axes = _batch_axes(mesh)
    spec_2d = P(None, axes)
    spec_1d = P(axes)
    in_specs = tuple(spec_2d if is2d else spec_1d for is2d in ARG_IS_2D)

    if use_pallas:
        from functools import partial

        from .pallas_kernel import verify_blocked_impl

        from .curve import point_form

        kw = {"point_form": point_form()}
        if interpret:
            kw["interpret"] = True
        if block is not None:
            kw["block"] = block
        if schnorr_free:
            kw["schnorr_free"] = True
        _core = partial(verify_blocked_impl, **kw)
    else:
        _core = verify_core

    def step(*args):
        ok = _core(*args)
        total = lax.psum(jnp.sum(ok.astype(jnp.int32)), axes)
        return ok, total

    # check_vma off: verify_core's scan carry starts from a broadcast
    # constant (INFINITY), which the varying-manual-axes analysis rejects
    # even though the program is shard-correct (pure DP + one psum).
    try:
        sharded = _shard_map(
            step,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=(spec_1d, P()),
            check_vma=False,
        )
    except TypeError:  # pragma: no cover - older jax spells it check_rep
        sharded = _shard_map(
            step,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=(spec_1d, P()),
            check_rep=False,
        )
    fn = jax.jit(sharded)
    _FN_CACHE[key] = fn
    return fn


def _mesh_quantum(mesh: Mesh) -> int:
    """Per-batch size quantum: Pallas shards need BLOCK-aligned per-shard
    batches; the XLA program just needs a multiple of the mesh size."""
    n = mesh.devices.size
    if _mesh_is_tpu(mesh) and not pallas_broken():
        from .pallas_kernel import BLOCK

        return n * BLOCK
    return n


def dispatch_raw_sharded(
    raw, mesh: Mesh, pad_to: Optional[int] = None, kernel: str = "auto"
) -> tuple:
    """ASYNC sharded dispatch of a packed RawBatch (ISSUE 10): host prep
    at a mesh-aligned shape, per-chip ``device_put`` (the host→device
    transfer is split per chip), sharded program enqueue.  Returns the
    ``(ok device array, count)`` handle — collect with
    :func:`kernel.collect_verdicts`; JAX async dispatch means the caller
    can prep the next lane while this one computes, exactly like the
    single-chip :func:`kernel.dispatch_batch_tpu_raw`.

    This is the engine's mesh rung (``VerifyConfig.mesh_devices``): a
    packed full lane shards across chips with zero inter-chip traffic in
    the hot loop.  The CPU-mesh dryrun path (conftest's 8 virtual host
    devices) pins it without TPU hardware; the device verdict is banked
    by the watcher when a TPU window opens.
    """
    from .raw import as_raw_batch

    raw = as_raw_batch(raw)
    quantum = _mesh_quantum(mesh)
    size = max(pad_to or 0, len(raw), 1)
    size = (size + quantum - 1) // quantum * quantum
    with span("verify.prepare"):
        prep = prepare_batch_raw(raw, pad_to=size)
    axes = _batch_axes(mesh)
    shard_2d = NamedSharding(mesh, P(None, axes))
    shard_1d = NamedSharding(mesh, P(axes))
    with span("verify.transfer"):
        args = [
            jax.device_put(np.asarray(a), shard_2d if is2d else shard_1d)
            for a, is2d in zip(prep.device_args, ARG_IS_2D)
        ]
    fn = sharded_verify_fn(mesh, kernel, schnorr_free=prep.schnorr_free)
    with span("verify.kernel"):
        ok, _total = fn(*args)
    return ok, prep.count


def verify_batch_sharded(
    items: Sequence[tuple[Optional[Point], int, int, int]],
    mesh: Optional[Mesh] = None,
    pad_to: Optional[int] = None,
) -> list[bool]:
    """End-to-end multi-chip verify: host prep, shard over the mesh, run.

    Pads the batch to a multiple of the mesh size (lanes padded with
    ``host_valid=False`` are rejected for free).
    """
    if not items:
        return []
    mesh = mesh or make_mesh()
    quantum = _mesh_quantum(mesh)
    size = pad_to or len(items)
    size = max(size, len(items))
    size = (size + quantum - 1) // quantum * quantum
    prep = prepare_batch(items, pad_to=size)

    axes = _batch_axes(mesh)
    shard_2d = NamedSharding(mesh, P(None, axes))
    shard_1d = NamedSharding(mesh, P(axes))
    args = [
        jax.device_put(np.asarray(a), shard_2d if is2d else shard_1d)
        for a, is2d in zip(prep.device_args, ARG_IS_2D)
    ]

    def run():
        # resolved inside the retry: after a Mosaic failure marks pallas
        # broken, the auto selection yields the XLA variant (cached
        # separately per use_pallas).  schnorr_free comes from the host
        # prep flags (the ONE safe derivation — kernel.PreparedBatch):
        # an ECDSA-only sharded batch sheds the acceptance pows exactly
        # like the single-chip dispatcher.
        ok, _total = sharded_verify_fn(
            mesh, schnorr_free=prep.schnorr_free
        )(*args)
        return [bool(b) for b in np.asarray(ok)[: prep.count]]

    return with_mosaic_fallback(run, "in shard_map")
