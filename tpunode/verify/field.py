"""256-bit modular arithmetic for the secp256k1 field on TPU.

TPUs have no wide integers, so field elements are vectors of NLIMBS=24 limbs
of RADIX=11 bits in int32 lanes.  **Layout is limb-major**: an element batch
has shape ``(NLIMBS, B)`` — the limb axis is axis 0 (sublanes: 24 = 3x8,
zero padding) and the batch axis is minor-most (lanes: B a multiple of 128
tiles perfectly).  The transposed layout ``(B, NLIMBS)`` would pad the
24-limb minor dim to 128 lanes (~19% utilization); limb-major is the single
biggest throughput lever on this kernel.

Everything is a fixed-shape, branch-free jnp program — what XLA fuses and
tiles best.  Constants are shape ``(NLIMBS, 1)`` so they broadcast over the
trailing batch axis.

Key design points (bounds are load-bearing):

* **Loose limbs.** Between operations limbs may be loose — up to the
  per-function input contracts (``mul`` admits |non-top limb| <= 2**19,
  |top limb| <= 2**15; ``mul_t`` requires every |limb| <= 2**13 — see their
  docstrings, which are the load-bearing bounds) — and possibly negative:
  two's-complement ``& MASK`` / arithmetic ``>> RADIX`` keep carry rounds
  exact for negatives, which makes subtraction free (no borrow chains).
* **Multiplication** internally tightens both inputs with one carry round
  (bringing limbs to ``< 2**12``), then does the 24x24 limb convolution in
  direct shift-add form (partials < 2**24, anti-diagonal sums of <= 24 terms
  < 2**28.6 — far inside int32), then folds limbs >= 24 back using the
  sparse prime: 2^264 ≡ 256*(2^32+977) (mod p).
* **No value is ever dropped**: carry rounds preserve the top limb's
  overflow in place instead of discarding it, and every buffer that carries a
  fat top limb is padded first.
* **Canonicalization** (exact value in [0, p)) is only needed at equality
  checks — once per verification, not per operation.

Host<->device speaks Python ints via ``to_limbs``/``from_limbs``.

This replaces the capability the reference gets from libsecp256k1's field
module (reference stack.yaml:5,9; SURVEY.md C9), redesigned for vector/matrix
units rather than translated from the C.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

__all__ = [
    "RADIX",
    "NLIMBS",
    "P",
    "N",
    "to_limbs",
    "from_limbs",
    "mul",
    "mul_t",
    "sqr",
    "mul_small_red",
    "tighten",
    "canonical",
    "is_zero",
    "eq",
    "select",
    "ZERO",
    "ONE",
]

RADIX = 11
NLIMBS = 24
MASK = (1 << RADIX) - 1
TOTAL_BITS = RADIX * NLIMBS  # 264

P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141

FOLD_INT = (1 << TOTAL_BITS) % P  # 2^264 mod p = 256*(2^32+977), 4 limbs
C_INT = (1 << 256) % P  # 2^32 + 977
_FN = 4  # limb count of the fold constant


def _limbs_list(v: int, n: int) -> list[int]:
    return [(v >> (RADIX * i)) & MASK for i in range(n)]


def to_limbs(v: int, n: int = NLIMBS) -> np.ndarray:
    """Host: Python int -> little-endian limb vector (int32), shape (n,)."""
    return np.array(_limbs_list(v, n), dtype=np.int32)


def from_limbs(limbs) -> int:
    """Host: limb vector (loose/negative limbs fine) -> Python int.

    Accepts shape (L,) or (L, 1); the limb axis must be axis 0.
    """
    out = 0
    for i, l in enumerate(np.asarray(limbs).reshape(-1).tolist()):
        out += int(l) << (RADIX * i)
    return out


FOLD = jnp.array(_limbs_list(FOLD_INT, _FN), dtype=jnp.int32)
C_LIMBS = jnp.array(_limbs_list(C_INT, _FN), dtype=jnp.int32)
P_LIMBS = jnp.array(_limbs_list(P, NLIMBS), dtype=jnp.int32)[:, None]
ZERO = jnp.zeros((NLIMBS, 1), dtype=jnp.int32)
ONE = jnp.zeros((NLIMBS, 1), dtype=jnp.int32).at[0].set(1)


def _conv(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Limb convolution: (24, B) x (24, B) -> (47, B).

    Direct shift-add form (24 broadcast multiplies + static slice-adds):
    exactly the 24*24 partial products, nothing more — XLA fuses the
    whole chain into vector code with no materialized outer product.
    """
    out = jnp.zeros((2 * NLIMBS - 1,) + a.shape[1:], dtype=jnp.int32)
    for i in range(NLIMBS):
        out = out.at[i : i + NLIMBS].add(a[i] * b)
    return out


def _carry(x: jnp.ndarray, rounds: int) -> jnp.ndarray:
    """Carry-save rounds.  Exact for negative limbs (arithmetic shift), and
    the top limb keeps its overflow in place — no value is ever dropped."""
    for _ in range(rounds):
        lo = x & MASK
        hi = x >> RADIX
        y = lo.at[1:].add(hi[:-1])
        x = y.at[-1].add(hi[-1] << RADIX)
    return x


def _pad(x: jnp.ndarray, n: int) -> jnp.ndarray:
    return jnp.concatenate(
        [x, jnp.zeros((n,) + x.shape[1:], dtype=jnp.int32)], axis=0
    )


def tighten(x: jnp.ndarray, rounds: int = 1) -> jnp.ndarray:
    """Re-tighten loose limbs (|limb| <= 2^17 -> < 2^12 after one round)."""
    return _carry(x, rounds)


def _fold_once(wide: jnp.ndarray) -> jnp.ndarray:
    """Fold limbs >= NLIMBS back via 2^264 ≡ FOLD (mod p).

    Contract: |limb| <= 2^15 (so partials hi*FOLD <= 2^26, 4-term sums
    <= 2^28).  Output: (NLIMBS, ...) with |limb| <= 2^28-ish (loose; callers
    carry right after).
    """
    lo = wide[:NLIMBS]
    hi = wide[NLIMBS:]
    k = hi.shape[0]
    out = _pad(lo, max(0, k + _FN - 1 - NLIMBS))
    for i in range(_FN):
        out = out.at[i : i + k].add(FOLD[i] * hi)
    if out.shape[0] > NLIMBS:
        out = _carry(_pad(out, 1), 2)
        return _fold_once(out)
    return out


def _fold_top(x: jnp.ndarray) -> jnp.ndarray:
    """Carry into a 25th limb, then fold it back via 2^264 ≡ FOLD (mod p):
    (NLIMBS, ...) in, (NLIMBS, ...) out with the top limb's overflow folded
    into the low _FN limbs.  The shared tail of _tight24 / mul /
    mul_small_red — the most bound-sensitive snippet in the module, so it
    lives in exactly one place."""
    x = _carry(_pad(x, 1), 1)
    hi = x[NLIMBS]
    x = x[:NLIMBS]
    return x.at[:_FN].add(FOLD[:, None] * hi[None])


def _tight24(a: jnp.ndarray) -> jnp.ndarray:
    """Bring EVERY limb (including the top one) under ~2^12 without losing
    value.  Needed because plain carry rounds preserve (never shrink) the
    top limb."""
    return _carry(_fold_top(a), 1)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Modular multiply mod p (general loose inputs; see mul_t for the
    pre-tight fast path).

    Input contract (audited at every call site in curve.py/kernel.py):
    |non-top limbs| <= 2^19, |top limb| <= 2^15, and for the PAIR
    top(a)*top(b) <= 2^30.  One internal carry round then brings non-top
    limbs under 2^11.3 while preserving each top limb, so every
    anti-diagonal convolution sum stays below 2^31 (int32-exact):
    mid diagonals <= 24*2^22.6, the single top*top term <= 2^30, mixed
    top terms <= 2*2^15*2^11.3.  Output loose with |limb| <= 2^12, non-top
    <= 2^11.2, and value magnitude < 2^265.  Exact modulo p, sign-correct.

    (Operands that are sums of a few mul outputs satisfy this trivially:
    mul outputs have every limb <= 2^12.  The B3/8 scalings are the only
    spots that need care — see mul_small_red and the audit notes in
    curve.py.)
    """
    a = _carry(a, 1)
    b = _carry(b, 1)
    wide = _conv(a, b)  # 47 limbs, anti-diagonal sums < 2^28.6
    wide = _carry(_pad(wide, 1), 2)  # 48 limbs, |v| <= 2^12 (top <= 2^15)
    x = _fold_once(wide)  # 24 limbs, loose <= 2^28
    x = _carry(x, 1)  # <= 2^12, top <= 2^17-ish
    return _carry(_fold_top(x), 1)  # fold residual top overflow; <= 2^12


def mul_t(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """``mul`` for pre-tight operands: skips the two input carry rounds.

    Contract (stricter than mul's, audited per call site in curve.py):
    EVERY limb of both inputs |<= 2^13| — raw mul outputs (<= 2^12) and
    single point coordinates (sums of <= 2 mul outputs) qualify; wider sums
    and mul_small_red outputs do NOT.  Convolution bound: 24 * 2^13 * 2^13
    = 2^30.6 < 2^31.  Output identical contract to mul's.
    """
    wide = _conv(a, b)
    wide = _carry(_pad(wide, 1), 2)
    x = _fold_once(wide)
    x = _carry(x, 1)
    return _carry(_fold_top(x), 1)


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def mul_small_red(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Scale by a small constant AND reduce so the result is a valid
    ``mul`` input even though |value| grows past 2^268: carry into a 25th
    limb, fold it back via 2^264 ≡ FOLD (mod p).

    Contract: |a limbs| <= 2^15, |k| <= 32.  Output: value < 2^265 and
    |top limb| <= 2^12 always; non-top limbs <= 2^11 + 2^11*(value(a*k)>>264).
    At the actual call sites (a is a mul output: every limb <= 2^12; k = B3
    = 21) that is <= 2^16.6 — so 3-term sums of such outputs (<= 2^18.3)
    still sit inside mul's |non-top| <= 2^19 input contract (the pt_double
    audit relies on this).
    """
    return _fold_top(a * k)


# ---------- exact canonicalization & comparisons ----------

# A comfortably large multiple of p added before canonicalizing so negative
# values become positive: loose values are bounded by |v| < 2^266.
_BIG_INT = ((1 << 267) // P + 1) * P
_BIG = jnp.array(_limbs_list(_BIG_INT, NLIMBS + 1), dtype=jnp.int32)[:, None]


def canonical(x: jnp.ndarray) -> jnp.ndarray:
    """Exact canonical representative in [0, p), as nonnegative limbs.

    Input: loose limbs (|limb| <= 2^13 -> |value| < 2^266).  Used only at
    equality checks (once per verification), so the long carry chains here
    are off the hot path.
    """
    x = _tight24(x)  # all limbs < ~2^12 -> |value| < 2^266
    wide = _pad(x, 1) + _BIG  # nonnegative, < 2^268
    wide = _carry(wide, NLIMBS + 4)  # canonical limbs (top limb <= 2^16)
    # fold value at the 2^256 boundary: bits 256+ are limb23>>3 and limb24
    hi = (wide[NLIMBS - 1] >> 3) + (wide[NLIMBS] << 8)
    lo = wide[:NLIMBS].at[NLIMBS - 1].set(wide[NLIMBS - 1] & 7)
    lo = lo.at[:_FN].add(C_LIMBS[:, None] * hi[None])  # += hi * (2^256 mod p)
    lo = _carry(lo, NLIMBS + 2)  # canonical, value < 2^256 + 2^47 < 2p
    for _ in range(2):
        ge_p = _ge(lo, P_LIMBS)
        lo = lo - jnp.where(ge_p, P_LIMBS, 0)
        lo = _carry(lo, NLIMBS + 1)  # resolve borrows (result nonnegative)
    return lo


def _ge(a: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """Lexicographic >= over canonical (nonnegative, in-range) limb vectors."""
    diff = a - m
    nz = diff != 0
    idx = (NLIMBS - 1) - jnp.argmax(nz[::-1], axis=0)
    top = jnp.take_along_axis(diff, idx[None], axis=0)[0]
    return jnp.where(jnp.any(nz, axis=0), top > 0, True)


def is_zero(x: jnp.ndarray) -> jnp.ndarray:
    """value ≡ 0 (mod p)?  Exact."""
    return jnp.all(canonical(x) == 0, axis=0)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a ≡ b (mod p)?  Exact."""
    return is_zero(a - b)


def select(mask: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Branch-free ``mask ? a : b`` (mask (B,) broadcasts over the leading
    limb axis)."""
    return jnp.where(mask, a, b)
