"""256-bit modular arithmetic for the secp256k1 field on TPU.

TPUs have no wide integers, so field elements are vectors of NLIMBS=24 limbs
of RADIX=11 bits in int32 lanes (shape ``(..., 24)``).  Everything is a
fixed-shape, branch-free jnp program — what XLA fuses and tiles best — and
batches via leading dimensions.

Key design points (bounds are load-bearing):

* **Loose limbs.** Between operations limbs may be loose — any int32 with
  ``|limb| <= 2**17`` — and possibly negative: two's-complement ``& MASK`` /
  arithmetic ``>> RADIX`` keep carry rounds exact for negatives, which makes
  subtraction free (no borrow chains).
* **Multiplication** internally tightens both inputs with one carry round
  (bringing limbs to ``< 2**12``), then does the 24x24 limb convolution
  (partials < 2**24, anti-diagonal sums of <= 24 terms < 2**28.6 — far inside
  int32), then folds limbs >= 24 back using the sparse prime:
  2^264 ≡ 256*(2^32+977) (mod p).
* **No value is ever dropped**: carry rounds preserve the top limb's
  overflow in place instead of discarding it, and every buffer that carries a
  fat top limb is padded first.
* **Canonicalization** (exact value in [0, p)) is only needed at equality
  checks — once per verification, not per operation.

Host<->device speaks Python ints via ``to_limbs``/``from_limbs``.

This replaces the capability the reference gets from libsecp256k1's field
module (reference stack.yaml:5,9; SURVEY.md C9), redesigned for vector/matrix
units rather than translated from the C.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

__all__ = [
    "RADIX",
    "NLIMBS",
    "P",
    "N",
    "to_limbs",
    "from_limbs",
    "mul",
    "sqr",
    "mul_small",
    "tighten",
    "canonical",
    "is_zero",
    "eq",
    "select",
    "ZERO",
    "ONE",
]

RADIX = 11
NLIMBS = 24
MASK = (1 << RADIX) - 1
TOTAL_BITS = RADIX * NLIMBS  # 264

P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141

FOLD_INT = (1 << TOTAL_BITS) % P  # 2^264 mod p = 256*(2^32+977), 4 limbs
C_INT = (1 << 256) % P  # 2^32 + 977
_FN = 4  # limb count of the fold constant


def _limbs_list(v: int, n: int) -> list[int]:
    return [(v >> (RADIX * i)) & MASK for i in range(n)]


def to_limbs(v: int, n: int = NLIMBS) -> np.ndarray:
    """Host: Python int -> little-endian limb vector (int32)."""
    return np.array(_limbs_list(v, n), dtype=np.int32)


def from_limbs(limbs) -> int:
    """Host: limb vector (loose/negative limbs fine) -> Python int."""
    out = 0
    for i, l in enumerate(np.asarray(limbs).reshape(-1).tolist()):
        out += int(l) << (RADIX * i)
    return out


FOLD = jnp.array(_limbs_list(FOLD_INT, _FN), dtype=jnp.int32)
C_LIMBS = jnp.array(_limbs_list(C_INT, _FN), dtype=jnp.int32)
P_LIMBS = jnp.array(_limbs_list(P, NLIMBS), dtype=jnp.int32)
ZERO = jnp.zeros((NLIMBS,), dtype=jnp.int32)
ONE = jnp.zeros((NLIMBS,), dtype=jnp.int32).at[0].set(1)

# anti-diagonal one-hot: S[i, j, k] = [i + j == k], for the limb convolution
_S = np.zeros((NLIMBS, NLIMBS, 2 * NLIMBS - 1), dtype=np.int32)
for _i in range(NLIMBS):
    for _j in range(NLIMBS):
        _S[_i, _j, _i + _j] = 1
S_CONV = jnp.array(_S)


def _carry(x: jnp.ndarray, rounds: int) -> jnp.ndarray:
    """Carry-save rounds.  Exact for negative limbs (arithmetic shift), and
    the top limb keeps its overflow in place — no value is ever dropped."""
    for _ in range(rounds):
        lo = x & MASK
        hi = x >> RADIX
        y = lo.at[..., 1:].add(hi[..., :-1])
        x = y.at[..., -1].add(hi[..., -1] << RADIX)
    return x


def _pad(x: jnp.ndarray, n: int) -> jnp.ndarray:
    return jnp.concatenate(
        [x, jnp.zeros(x.shape[:-1] + (n,), dtype=jnp.int32)], axis=-1
    )


def tighten(x: jnp.ndarray, rounds: int = 1) -> jnp.ndarray:
    """Re-tighten loose limbs (|limb| <= 2^17 -> < 2^12 after one round)."""
    return _carry(x, rounds)


def _fold_once(wide: jnp.ndarray) -> jnp.ndarray:
    """Fold limbs >= NLIMBS back via 2^264 ≡ FOLD (mod p).

    Contract: |limb| <= 2^15 (so partials hi*FOLD <= 2^26, 4-term sums
    <= 2^28).  Output: (..., NLIMBS) with |limb| <= 2^28-ish (loose; callers
    carry right after).
    """
    lo = wide[..., :NLIMBS]
    hi = wide[..., NLIMBS:]
    k = hi.shape[-1]
    out = _pad(lo, max(0, k + _FN - 1 - NLIMBS))
    for i in range(_FN):
        out = out.at[..., i : i + k].add(FOLD[i] * hi)
    if out.shape[-1] > NLIMBS:
        out = _carry(_pad(out, 1), 2)
        return _fold_once(out)
    return out


def _tight24(a: jnp.ndarray) -> jnp.ndarray:
    """Bring EVERY limb (including the top one) under ~2^12 without losing
    value: carry into a 25th limb, fold it back via 2^264 ≡ FOLD, carry once
    more.  Needed because plain carry rounds preserve (never shrink) the top
    limb."""
    a = _carry(_pad(a, 1), 1)
    hi = a[..., NLIMBS]
    a = a[..., :NLIMBS]
    a = a.at[..., :_FN].add(FOLD * hi[..., None])
    return _carry(a, 1)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Modular multiply mod p.

    Inputs loose (|limb| <= 2^18); output loose with |limb| <= 2^12 and
    value magnitude < 2^265.  Exact modulo p, sign-correct.
    """
    a = _tight24(a)  # all limbs < ~2^12
    b = _tight24(b)
    prod = a[..., :, None] * b[..., None, :]  # (..., 24, 24), |v| < 2^24
    wide = jnp.einsum("...ij,ijk->...k", prod, S_CONV)  # 47 limbs, < 2^28.6
    wide = _carry(_pad(wide, 1), 2)  # 48 limbs, |v| <= 2^12 (top <= 2^15)
    x = _fold_once(wide)  # 24 limbs, loose <= 2^28
    x = _carry(_pad(x, 1), 2)  # 25 limbs, <= 2^12, top small
    # fold the residual 25th limb (value * 2^264)
    hi = x[..., NLIMBS]
    x = x[..., :NLIMBS]
    x = x.at[..., :_FN].add(FOLD * hi[..., None])
    return _carry(x, 1)


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Scale by a small constant (|k| <= 32); result loose (needs |a| <= 2^12
    to stay within the 2^17 loose contract)."""
    return a * k


# ---------- exact canonicalization & comparisons ----------

# A comfortably large multiple of p added before canonicalizing so negative
# values become positive: loose values are bounded by |v| < 2^266.
_BIG_INT = ((1 << 267) // P + 1) * P
_BIG = jnp.array(_limbs_list(_BIG_INT, NLIMBS + 1), dtype=jnp.int32)


def canonical(x: jnp.ndarray) -> jnp.ndarray:
    """Exact canonical representative in [0, p), as nonnegative limbs.

    Input: loose limbs (|limb| <= 2^13 -> |value| < 2^266).  Used only at
    equality checks (once per verification), so the long carry chains here
    are off the hot path.
    """
    x = _tight24(x)  # all limbs < ~2^12 -> |value| < 2^266
    wide = _pad(x, 1) + _BIG  # nonnegative, < 2^268
    wide = _carry(wide, NLIMBS + 4)  # canonical limbs (top limb <= 2^16)
    # fold value at the 2^256 boundary: bits 256+ are limb23>>3 and limb24
    hi = (wide[..., NLIMBS - 1] >> 3) + (wide[..., NLIMBS] << 8)
    lo = wide[..., :NLIMBS].at[..., NLIMBS - 1].set(wide[..., NLIMBS - 1] & 7)
    lo = lo.at[..., :_FN].add(C_LIMBS * hi[..., None])  # += hi * (2^256 mod p)
    lo = _carry(lo, NLIMBS + 2)  # canonical, value < 2^256 + 2^47 < 2p
    for _ in range(2):
        ge_p = _ge(lo, P_LIMBS)
        lo = lo - jnp.where(ge_p[..., None], P_LIMBS, 0)
        lo = _carry(lo, NLIMBS + 1)  # resolve borrows (result nonnegative)
    return lo


def _ge(a: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """Lexicographic >= over canonical (nonnegative, in-range) limb vectors."""
    diff = a - m
    nz = diff != 0
    idx = (NLIMBS - 1) - jnp.argmax(nz[..., ::-1], axis=-1)
    top = jnp.take_along_axis(diff, idx[..., None], axis=-1)[..., 0]
    return jnp.where(jnp.any(nz, axis=-1), top > 0, True)


def is_zero(x: jnp.ndarray) -> jnp.ndarray:
    """value ≡ 0 (mod p)?  Exact."""
    return jnp.all(canonical(x) == 0, axis=-1)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a ≡ b (mod p)?  Exact."""
    return is_zero(a - b)


def select(mask: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Branch-free ``mask ? a : b`` (mask broadcasts over the limb dim)."""
    return jnp.where(mask[..., None], a, b)
