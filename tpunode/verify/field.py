"""256-bit modular arithmetic for the secp256k1 field on TPU.

TPUs have no wide integers, so field elements are vectors of NLIMBS=24 limbs
of RADIX=11 bits in int32 lanes.  **Layout is limb-major**: an element batch
has shape ``(NLIMBS, B)`` — the limb axis is axis 0 (sublanes: 24 = 3x8,
zero padding) and the batch axis is minor-most (lanes: B a multiple of 128
tiles perfectly).  The transposed layout ``(B, NLIMBS)`` would pad the
24-limb minor dim to 128 lanes (~19% utilization); limb-major is the single
biggest throughput lever on this kernel.

Everything is a fixed-shape, branch-free jnp program — what XLA fuses and
tiles best.  Constants are shape ``(NLIMBS, 1)`` so they broadcast over the
trailing batch axis.

Key design points (bounds are load-bearing):

* **Loose limbs.** Between operations limbs may be loose — up to the
  per-function input contracts (``mul`` admits |non-top limb| <= 2**19,
  |top limb| <= 2**15; ``mul_t`` requires every |limb| <= 2**13 — see their
  docstrings, which are the load-bearing bounds) — and possibly negative:
  two's-complement ``& MASK`` / arithmetic ``>> RADIX`` keep carry rounds
  exact for negatives, which makes subtraction free (no borrow chains).
* **Multiplication** internally tightens both inputs with one carry round
  (bringing limbs to ``< 2**12``), then does the 24x24 limb convolution in
  direct shift-add form (partials < 2**24, anti-diagonal sums of <= 24 terms
  < 2**28.6 — far inside int32), then folds limbs >= 24 back using the
  sparse prime: 2^264 ≡ 256*(2^32+977) (mod p).
* **No value is ever dropped**: carry rounds preserve the top limb's
  overflow in place instead of discarding it, and every buffer that carries a
  fat top limb is padded first.
* **Canonicalization** (exact value in [0, p)) is only needed at equality
  checks — once per verification, not per operation.

Host<->device speaks Python ints via ``to_limbs``/``from_limbs``.

**Two limb-product formulations** (ISSUE 4): the classic shift-add
convolution (``shift_add``, the default) keeps everything on the VPU;
``dot_general`` materializes the 24x24 partial-product rows and contracts
them against a constant anti-diagonal scatter matrix with one
``lax.dot_general`` — the formulation that maps onto the MXU (the TPU's
wide-MAC unit, the analogue of the FPGA batch-ECDSA engines' DSP arrays).
Squaring additionally has a **dedicated half-product path** (~300 partial
products instead of 576, exploiting a_i*a_j symmetry) used by the pow
ladders and doubling formulas.  Both knobs are process-global, selectable
via ``TPUNODE_FIELD_MUL`` / ``TPUNODE_FIELD_SQR`` (see
:func:`set_field_modes`); every jit cache keyed on :func:`field_modes`
retraces on a flip.  All formulations compute IDENTICAL anti-diagonal
sums, so the int32 overflow audit below applies verbatim to each.

This replaces the capability the reference gets from libsecp256k1's field
module (reference stack.yaml:5,9; SURVEY.md C9), redesigned for vector/matrix
units rather than translated from the C.
"""

from __future__ import annotations

import os

import numpy as np

import jax.numpy as jnp
from jax import lax

__all__ = [
    "RADIX",
    "NLIMBS",
    "P",
    "N",
    "to_limbs",
    "from_limbs",
    "mul",
    "mul_t",
    "sqr",
    "sqr_t",
    "mul_small_red",
    "mul_wide",
    "mul_t_wide",
    "sqr_wide",
    "sqr_t_wide",
    "acc_add",
    "reduce_wide",
    "reduce_wide_loose",
    "tighten",
    "canonical",
    "is_zero",
    "eq",
    "select",
    "ZERO",
    "ONE",
    "MUL_MODES",
    "SQR_MODES",
    "REDUCE_MODES",
    "field_modes",
    "mul_mode",
    "sqr_mode",
    "reduce_mode",
    "set_field_modes",
]

RADIX = 11
NLIMBS = 24
MASK = (1 << RADIX) - 1
TOTAL_BITS = RADIX * NLIMBS  # 264

P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141

FOLD_INT = (1 << TOTAL_BITS) % P  # 2^264 mod p = 256*(2^32+977), 4 limbs
C_INT = (1 << 256) % P  # 2^32 + 977
_FN = 4  # limb count of the fold constant


def _limbs_list(v: int, n: int) -> list[int]:
    return [(v >> (RADIX * i)) & MASK for i in range(n)]


def to_limbs(v: int, n: int = NLIMBS) -> np.ndarray:
    """Host: Python int -> little-endian limb vector (int32), shape (n,)."""
    return np.array(_limbs_list(v, n), dtype=np.int32)


def from_limbs(limbs) -> int:
    """Host: limb vector (loose/negative limbs fine) -> Python int.

    Accepts shape (L,) or (L, 1); the limb axis must be axis 0.
    """
    out = 0
    for i, l in enumerate(np.asarray(limbs).reshape(-1).tolist()):
        out += int(l) << (RADIX * i)
    return out


FOLD = jnp.array(_limbs_list(FOLD_INT, _FN), dtype=jnp.int32)
C_LIMBS = jnp.array(_limbs_list(C_INT, _FN), dtype=jnp.int32)
P_LIMBS = jnp.array(_limbs_list(P, NLIMBS), dtype=jnp.int32)[:, None]
ZERO = jnp.zeros((NLIMBS, 1), dtype=jnp.int32)
ONE = jnp.zeros((NLIMBS, 1), dtype=jnp.int32).at[0].set(1)


# ---------- limb-product formulation knobs (ISSUE 4) ----------------------
#
# Process-global, read at TRACE time: every jitted program that embeds
# field ops keys its jit cache on field_modes() (kernel.verify_device,
# pallas_kernel.verify_blocked, multichip._FN_CACHE), so flipping a mode
# retraces instead of silently keeping the old formulation.
#
# Defaults chosen by measurement (PERF.md roofline section): on cpu-jax
# the fused shift-add chain beats the materialized dot_general outer
# product, and the half-product sqr wins everywhere.

MUL_MODES = ("shift_add", "dot_general")
SQR_MODES = ("half", "mul")
# Reduction discipline (ISSUE 12): "eager" reduces every product to 24
# limbs on the spot (the r3-r11 behavior); "lazy" lets curve.py's RCB
# formulas accumulate unreduced 47-limb convolutions (mul_wide/acc_add
# below) and pay ONE _reduce_wide per accumulated expression, with
# shared-operand carry rounds hoisted — the fused carry/fold rounds
# ROADMAP item 1 names.  Values differ limb-wise between modes but are
# equal mod p (pinned in tests/test_field.py); verdicts are
# bit-identical.  int32 safety of every lazy chain is CHECKED at trace
# time by tpunode.verify.bounds (not argued in comments).  "lazy" is
# the default since round 12: −27% carry/fold vector ops in the op
# model and a −9.5% measured step on the cpu-jax proxy @1024 (PERF.md;
# campaign-clean on XLA and pallas-interpret, device verdict pending
# the watcher's kind="lazy" rungs).
REDUCE_MODES = ("eager", "lazy")


def _env_mode(var: str, allowed: tuple, default: str) -> str:
    v = os.environ.get(var, "").strip().lower()
    if not v:
        return default
    if v not in allowed:
        # Fail fast: this is a measurement knob — silently falling back
        # to the default would make an A/B run measure the wrong
        # formulation and label it with the requested one.
        raise ValueError(f"{var}={v!r} not in {allowed}")
    return v


_MUL_MODE = _env_mode("TPUNODE_FIELD_MUL", MUL_MODES, "shift_add")
_SQR_MODE = _env_mode("TPUNODE_FIELD_SQR", SQR_MODES, "half")
_REDUCE_MODE = _env_mode("TPUNODE_FIELD_REDUCE", REDUCE_MODES, "lazy")


def mul_mode() -> str:
    """Active limb-product formulation: "shift_add" | "dot_general"."""
    return _MUL_MODE


def sqr_mode() -> str:
    """Active squaring path: "half" (dedicated ~half-product) | "mul"."""
    return _SQR_MODE


def reduce_mode() -> str:
    """Active reduction discipline: "eager" | "lazy" (ISSUE 12)."""
    return _REDUCE_MODE


def field_modes() -> tuple:
    """Hashable (mul_mode, sqr_mode, reduce_mode) — THE jit-cache key for
    every program that embeds field ops (a trace bakes the formulation
    in; the reduce mode changes curve.py's traced formula bodies)."""
    return (_MUL_MODE, _SQR_MODE, _REDUCE_MODE)


def set_field_modes(
    mul: str | None = None,
    sqr: str | None = None,
    reduce: str | None = None,
) -> tuple:
    """Select the limb-product / squaring / reduction formulation
    process-wide.

    Returns the previous (mul_mode, sqr_mode, reduce_mode) so callers can
    restore.  Programs traced BEFORE the flip keep their formulation until
    their owner re-traces — which every in-repo dispatch site does,
    because all of them key on :func:`field_modes`.
    """
    global _MUL_MODE, _SQR_MODE, _REDUCE_MODE
    # Validate ALL before mutating any: a caller that catches the
    # ValueError must find the process-global modes untouched, not
    # half-flipped (which would silently mislabel every later trace).
    if mul is not None and mul not in MUL_MODES:
        raise ValueError(f"mul mode {mul!r} not in {MUL_MODES}")
    if sqr is not None and sqr not in SQR_MODES:
        raise ValueError(f"sqr mode {sqr!r} not in {SQR_MODES}")
    if reduce is not None and reduce not in REDUCE_MODES:
        raise ValueError(f"reduce mode {reduce!r} not in {REDUCE_MODES}")
    prev = (_MUL_MODE, _SQR_MODE, _REDUCE_MODE)
    if mul is not None:
        _MUL_MODE = mul
    if sqr is not None:
        _SQR_MODE = sqr
    if reduce is not None:
        _REDUCE_MODE = reduce
    return prev


def _conv(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Limb convolution: (24, B) x (24, B) -> (47, B).

    Direct shift-add form (24 broadcast multiplies + static slice-adds):
    exactly the 24*24 partial products, nothing more — XLA fuses the
    whole chain into vector code with no materialized outer product.
    """
    out = jnp.zeros((2 * NLIMBS - 1,) + a.shape[1:], dtype=jnp.int32)
    for i in range(NLIMBS):
        out = out.at[i : i + NLIMBS].add(a[i] * b)
    return out


# Constant scatter matrices for the dot_general formulation.  MUL: row k
# of (47, 576) selects the partial products a_i*b_j with i+j == k — the
# anti-diagonal sum becomes ONE contraction over 576, which is what
# lax.dot_general maps onto the MXU.  SQR: only the 300 i <= j pairs are
# materialized; off-diagonal entries carry weight 2 (a_i*a_j appears
# twice in the square), so the contraction output is bit-identical to
# the full convolution of a with itself.
_MUL_PAIRS = [(i, j) for i in range(NLIMBS) for j in range(NLIMBS)]
_SQR_PAIRS = [(i, j) for i in range(NLIMBS) for j in range(i, NLIMBS)]


def _scatter(pairs, weighted: bool) -> np.ndarray:
    m = np.zeros((2 * NLIMBS - 1, len(pairs)), dtype=np.int32)
    for col, (i, j) in enumerate(pairs):
        m[i + j, col] = 2 if (weighted and i != j) else 1
    return m


_MUL_SCATTER = jnp.asarray(_scatter(_MUL_PAIRS, weighted=False))
_SQR_SCATTER = jnp.asarray(_scatter(_SQR_PAIRS, weighted=True))
_SQR_I = np.array([i for i, _ in _SQR_PAIRS])
_SQR_J = np.array([j for _, j in _SQR_PAIRS])


def _contract(scatter: jnp.ndarray, partials: jnp.ndarray,
              rest: tuple) -> jnp.ndarray:
    """(47, NPAIRS) @ (NPAIRS, prod(rest)) -> (47,) + rest, int32-exact.

    ``preferred_element_type=int32``: the accumulator must be exactly the
    int32 carry-save arithmetic of the shift-add form (every anti-diagonal
    sum is bounded inside int32 by the callers' contracts, so accumulation
    order is irrelevant)."""
    out = lax.dot_general(
        scatter,
        partials.reshape((partials.shape[0], -1)),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return out.reshape((2 * NLIMBS - 1,) + rest)


def _conv_dot(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """_conv as outer-product + one dot_general (same partials, same
    anti-diagonal sums — bit-identical output)."""
    p = (a[:, None] * b[None, :]).reshape((NLIMBS * NLIMBS,) + a.shape[1:])
    return _contract(_MUL_SCATTER, p, a.shape[1:])


def _sqr_conv(a: jnp.ndarray) -> jnp.ndarray:
    """Half-product squaring, shift-add form: out[i+j] += (2-δij)·a_i·a_j
    over i <= j — ~300 partial products instead of 576.  Per-position sums
    equal _conv(a, a)'s exactly (same value, same bounds: the doubling
    only rebrackets 2 identical cross terms into one)."""
    out = jnp.zeros((2 * NLIMBS - 1,) + a.shape[1:], dtype=jnp.int32)
    d = a + a
    for i in range(NLIMBS):
        out = out.at[2 * i].add(a[i] * a[i])
        if i + 1 < NLIMBS:
            out = out.at[2 * i + 1 : i + NLIMBS].add(a[i] * d[i + 1 :])
    return out


def _sqr_dot(a: jnp.ndarray) -> jnp.ndarray:
    """Half-product squaring, dot_general form: gather the 300 i <= j
    partial rows, contract with the 2-weighted scatter matrix."""
    p = a[_SQR_I] * a[_SQR_J]
    return _contract(_SQR_SCATTER, p, a.shape[1:])


def _convolve(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _conv(a, b) if _MUL_MODE == "shift_add" else _conv_dot(a, b)


def _square_conv(a: jnp.ndarray) -> jnp.ndarray:
    if _SQR_MODE == "mul":
        return _convolve(a, a)
    return _sqr_conv(a) if _MUL_MODE == "shift_add" else _sqr_dot(a)


def _carry(x: jnp.ndarray, rounds: int) -> jnp.ndarray:
    """Carry-save rounds.  Exact for negative limbs (arithmetic shift), and
    the top limb keeps its overflow in place — no value is ever dropped."""
    for _ in range(rounds):
        lo = x & MASK
        hi = x >> RADIX
        y = lo.at[1:].add(hi[:-1])
        x = y.at[-1].add(hi[-1] << RADIX)
    return x


def _pad(x: jnp.ndarray, n: int) -> jnp.ndarray:
    return jnp.concatenate(
        [x, jnp.zeros((n,) + x.shape[1:], dtype=jnp.int32)], axis=0
    )


def tighten(x: jnp.ndarray, rounds: int = 1) -> jnp.ndarray:
    """Re-tighten loose limbs (|limb| <= 2^17 -> < 2^12 after one round)."""
    return _carry(x, rounds)


def _fold_once(wide: jnp.ndarray) -> jnp.ndarray:
    """Fold limbs >= NLIMBS back via 2^264 ≡ FOLD (mod p).

    Contract: |limb| <= 2^15 (so partials hi*FOLD <= 2^26, 4-term sums
    <= 2^28).  Output: (NLIMBS, ...) with |limb| <= 2^28-ish (loose; callers
    carry right after).
    """
    lo = wide[:NLIMBS]
    hi = wide[NLIMBS:]
    k = hi.shape[0]
    out = _pad(lo, max(0, k + _FN - 1 - NLIMBS))
    for i in range(_FN):
        out = out.at[i : i + k].add(FOLD[i] * hi)
    if out.shape[0] > NLIMBS:
        out = _carry(_pad(out, 1), 2)
        return _fold_once(out)
    return out


def _fold_top(x: jnp.ndarray) -> jnp.ndarray:
    """Carry into a 25th limb, then fold it back via 2^264 ≡ FOLD (mod p):
    (NLIMBS, ...) in, (NLIMBS, ...) out with the top limb's overflow folded
    into the low _FN limbs.  The shared tail of _tight24 / mul /
    mul_small_red — the most bound-sensitive snippet in the module, so it
    lives in exactly one place."""
    x = _carry(_pad(x, 1), 1)
    hi = x[NLIMBS]
    x = x[:NLIMBS]
    return x.at[:_FN].add(FOLD[:, None] * hi[None])


def _tight24(a: jnp.ndarray) -> jnp.ndarray:
    """Bring EVERY limb (including the top one) under ~2^12 without losing
    value.  Needed because plain carry rounds preserve (never shrink) the
    top limb."""
    return _carry(_fold_top(a), 1)


def _reduce_wide(wide: jnp.ndarray) -> jnp.ndarray:
    """The shared reduction tail of every product: 47 loose product limbs
    -> 24 limbs, every |limb| <= 2^12.  Bounds as audited in mul's
    docstring (this is the exact op sequence the original mul inlined)."""
    wide = _carry(_pad(wide, 1), 2)  # 48 limbs, |v| <= 2^12 (top <= 2^15)
    x = _fold_once(wide)  # 24 limbs, loose <= 2^28
    x = _carry(x, 1)  # <= 2^12, top <= 2^17-ish
    return _carry(_fold_top(x), 1)  # fold residual top overflow; <= 2^12


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Modular multiply mod p (general loose inputs; see mul_t for the
    pre-tight fast path).

    Input contract (audited at every call site in curve.py/kernel.py):
    |non-top limbs| <= 2^19, |top limb| <= 2^15, and for the PAIR
    top(a)*top(b) <= 2^30.  One internal carry round then brings non-top
    limbs under 2^11.3 while preserving each top limb, so every
    anti-diagonal convolution sum stays below 2^31 (int32-exact):
    mid diagonals <= 24*2^22.6, the single top*top term <= 2^30, mixed
    top terms <= 2*2^15*2^11.3.  Output loose with |limb| <= 2^12, non-top
    <= 2^11.2, and value magnitude < 2^265.  Exact modulo p, sign-correct.

    (Operands that are sums of a few mul outputs satisfy this trivially:
    mul outputs have every limb <= 2^12.  The B3/8 scalings are the only
    spots that need care — see mul_small_red and the audit notes in
    curve.py.)
    """
    a = _carry(a, 1)
    b = _carry(b, 1)
    return _reduce_wide(_convolve(a, b))  # sums < 2^28.6 (see contract)


def mul_t(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """``mul`` for pre-tight operands: skips the two input carry rounds.

    Contract (stricter than mul's, audited per call site in curve.py):
    EVERY limb of both inputs |<= 2^13| — raw mul outputs (<= 2^12) and
    single point coordinates (sums of <= 2 mul outputs) qualify; wider sums
    and mul_small_red outputs do NOT.  Convolution bound: 24 * 2^13 * 2^13
    = 2^30.6 < 2^31.  Output identical contract to mul's.
    """
    return _reduce_wide(_convolve(a, b))


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    """Modular square — mul(a, a)'s contract, via the dedicated
    half-product path when ``sqr_mode() == "half"`` (the default: the pow
    ladders spend most of their muls here).  The pairwise top*top <= 2^30
    condition reduces to |top limb| <= 2^15, which mul's contract already
    requires.  Bit-identical output to mul(a, a) in every mode."""
    a = _carry(a, 1)
    return _reduce_wide(_square_conv(a))


def sqr_t(a: jnp.ndarray) -> jnp.ndarray:
    """``sqr`` for pre-tight operands — mul_t's contract (every |limb|
    <= 2^13).  The doubled cross partials 2*a_i*a_j <= 2^27 and the
    per-position sums equal mul_t's convolution sums (< 2^30.6)."""
    return _reduce_wide(_square_conv(a))


def mul_small_red(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Scale by a small constant AND reduce so the result is a valid
    ``mul`` input even though |value| grows past 2^268: carry into a 25th
    limb, fold it back via 2^264 ≡ FOLD (mod p).

    Contract: |a limbs| <= 2^15, |k| <= 32.  Output: value < 2^265 and
    |top limb| <= 2^12 always; non-top limbs <= 2^11 + 2^11*(value(a*k)>>264).
    At the actual call sites (a is a mul output: every limb <= 2^12; k = B3
    = 21) that is <= 2^16.6 — so 3-term sums of such outputs (<= 2^18.3)
    still sit inside mul's |non-top| <= 2^19 input contract (the pt_double
    audit relies on this).
    """
    return _fold_top(a * k)


# ---------- lazy-reduction wide-accumulator API (ISSUE 12) ----------------
#
# A "wide" value is the unreduced 47-limb convolution of one product —
# exactly what _reduce_wide consumes.  Wides of the SAME expression may be
# summed limb-wise (acc_add) before the one shared reduction, eliminating
# the interior carry/fold rounds the eager formulas pay per product.
# Wides are plain (47, ...) int32 arrays: negation and subtraction are
# ordinary elementwise arithmetic (value-exact, sign-correct).
#
# int32-safety of every accumulation chain is NOT argued here: the static
# bound tracker (tpunode.verify.bounds) replays each live formula over
# exact per-limb magnitude bounds and hard-fails at trace time if any
# anti-diagonal sum, accumulated wide, or reduction intermediate can
# exceed int32.  That audit — not these docstrings — is the contract.


def mul_wide(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """``mul`` minus the reduction tail: one carry round per input, then
    the limb convolution.  Input contract identical to :func:`mul`'s;
    output is the (47, ...) wide for :func:`acc_add`/:func:`reduce_wide`.
    ``reduce_wide(mul_wide(a, b))`` is bit-identical to ``mul(a, b)``."""
    return _convolve(_carry(a, 1), _carry(b, 1))


def mul_t_wide(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """``mul_t`` minus the reduction tail (pre-tight operands, every
    |limb| <= 2^13 — :func:`mul_t`'s contract)."""
    return _convolve(a, b)


def sqr_wide(a: jnp.ndarray) -> jnp.ndarray:
    """``sqr`` minus the reduction tail (mul's input contract)."""
    return _square_conv(_carry(a, 1))


def sqr_t_wide(a: jnp.ndarray) -> jnp.ndarray:
    """``sqr_t`` minus the reduction tail (mul_t's contract)."""
    return _square_conv(a)


def acc_add(*wides: jnp.ndarray) -> jnp.ndarray:
    """Sum unreduced wides limb-wise — the lazy accumulator.  Value-exact
    (int adds); the per-limb magnitude bound is the SUM of the operands'
    bounds, which the bound tracker checks against int32 at trace time."""
    out = wides[0]
    for w in wides[1:]:
        out = out + w
    return out


def reduce_wide(wide: jnp.ndarray) -> jnp.ndarray:
    """Public reduction tail: 47 loose product limbs (or an acc_add of a
    few) -> 24 limbs, every |limb| <= 2^12.  The one reduction a lazy
    expression pays."""
    return _reduce_wide(wide)


def reduce_wide_loose(wide: jnp.ndarray) -> jnp.ndarray:
    """``reduce_wide`` minus the final carry round (4 carry rounds + 2
    folds instead of 5 + 2): output limbs are LOOSE — |limb| <= ~2^12.3
    (bound-tracker-checked <= 2^13) instead of <= 2^12 — but that still
    satisfies every consumer the lazy formulas have (coordinate sums,
    mul_t_wide convolutions, mul_small_red).  The default reduction of
    the lazy pipeline: one carry round saved per product."""
    wide = _carry(_pad(wide, 1), 2)
    x = _fold_once(wide)
    x = _carry(x, 1)
    return _fold_top(x)


# ---------- exact canonicalization & comparisons ----------

# A comfortably large multiple of p added before canonicalizing so negative
# values become positive: loose values are bounded by |v| < 2^266.
_BIG_INT = ((1 << 267) // P + 1) * P
_BIG = jnp.array(_limbs_list(_BIG_INT, NLIMBS + 1), dtype=jnp.int32)[:, None]


def canonical(x: jnp.ndarray) -> jnp.ndarray:
    """Exact canonical representative in [0, p), as nonnegative limbs.

    Input: loose limbs (|limb| <= 2^13 -> |value| < 2^266).  Used only at
    equality checks (once per verification), so the long carry chains here
    are off the hot path.
    """
    x = _tight24(x)  # all limbs < ~2^12 -> |value| < 2^266
    wide = _pad(x, 1) + _BIG  # nonnegative, < 2^268
    wide = _carry(wide, NLIMBS + 4)  # canonical limbs (top limb <= 2^16)
    # fold value at the 2^256 boundary: bits 256+ are limb23>>3 and limb24
    hi = (wide[NLIMBS - 1] >> 3) + (wide[NLIMBS] << 8)
    lo = wide[:NLIMBS].at[NLIMBS - 1].set(wide[NLIMBS - 1] & 7)
    lo = lo.at[:_FN].add(C_LIMBS[:, None] * hi[None])  # += hi * (2^256 mod p)
    lo = _carry(lo, NLIMBS + 2)  # canonical, value < 2^256 + 2^47 < 2p
    for _ in range(2):
        ge_p = _ge(lo, P_LIMBS)
        lo = lo - jnp.where(ge_p, P_LIMBS, 0)
        lo = _carry(lo, NLIMBS + 1)  # resolve borrows (result nonnegative)
    return lo


def _ge(a: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """Lexicographic >= over canonical (nonnegative, in-range) limb vectors."""
    diff = a - m
    nz = diff != 0
    idx = (NLIMBS - 1) - jnp.argmax(nz[::-1], axis=0)
    top = jnp.take_along_axis(diff, idx[None], axis=0)[0]
    return jnp.where(jnp.any(nz, axis=0), top > 0, True)


def is_zero(x: jnp.ndarray) -> jnp.ndarray:
    """value ≡ 0 (mod p)?  Exact."""
    return jnp.all(canonical(x) == 0, axis=0)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a ≡ b (mod p)?  Exact."""
    return is_zero(a - b)


def select(mask: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Branch-free ``mask ? a : b`` (mask (B,) broadcasts over the leading
    limb axis)."""
    return jnp.where(mask, a, b)
