"""Batch secp256k1 ECDSA signature verification.

The reference consumes libsecp256k1 (C) transitively through haskoin-core
(reference stack.yaml:5,9; SURVEY.md C9).  This package is the TPU-native
replacement of that capability — the north star of BASELINE.json:

* :mod:`tpunode.verify.ecdsa_cpu` — pure-Python reference implementation
  (the correctness oracle, cross-checked against OpenSSL via ``cryptography``).
* ``native/secp256k1`` + :mod:`tpunode.verify.cpu_native` — C++ single-core
  verifier: the CPU baseline and small-batch fallback.
* :mod:`tpunode.verify.field` / :mod:`tpunode.verify.curve` /
  :mod:`tpunode.verify.kernel` — the JAX batch kernel: 256-bit limb
  arithmetic, Jacobian point ops and interleaved fixed-window double-and-add
  (Shamir) for u1*G + u2*Q, vmapped over the batch and shardable over chips.
* :mod:`tpunode.verify.engine` — async batch queue with CPU fallback, hooked
  into the node's block/mempool ingest path.
"""

from .ecdsa_cpu import (
    CURVE_N,
    CURVE_P,
    GENERATOR,
    Point,
    decode_pubkey,
    parse_der_signature,
    verify,
    verify_batch_cpu,
)
