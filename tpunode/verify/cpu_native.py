"""ctypes binding to the native C++ secp256k1 verifier.

The CPU baseline / fallback engine (native/secp256k1/secp256k1.cpp) — the
framework's equivalent of the reference's libsecp256k1 dependency
(reference stack.yaml:5,9; SURVEY.md C9).  Builds on demand with ``make -C
native`` when the shared library is missing.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np
from typing import Optional, Sequence

from .ecdsa_cpu import Point

__all__ = ["NativeVerifier", "load_native_verifier"]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_LIB_PATH = os.path.join(_REPO_ROOT, "native", "build", "libsecp_cpu.so")


def _ensure_built() -> str:
    from ..native import ensure_native_lib

    return ensure_native_lib(_LIB_PATH, "secp256k1")


class NativeVerifier:
    """Batch ECDSA verification through the C++ engine."""

    def __init__(self, lib_path: Optional[str] = None):
        path = lib_path or _ensure_built()
        self._lib = ctypes.CDLL(path)
        self._lib.secp_verify_batch.restype = ctypes.c_int
        self._lib.secp_verify_batch.argtypes = [
            ctypes.c_char_p,  # px
            ctypes.c_char_p,  # py
            ctypes.c_char_p,  # z (digest or schnorr challenge)
            ctypes.c_char_p,  # r
            ctypes.c_char_p,  # s
            ctypes.c_char_p,  # present/algo (None = all ecdsa)
            ctypes.c_int,  # count
            ctypes.c_char_p,  # out
        ]
        self._lib.secp_verify_batch_mt.restype = ctypes.c_int
        self._lib.secp_verify_batch_mt.argtypes = (
            self._lib.secp_verify_batch.argtypes + [ctypes.c_int]  # nthreads
        )
        import numpy as _np
        from numpy.ctypeslib import ndpointer

        i32 = ndpointer(_np.int32, flags="C_CONTIGUOUS")
        u8 = ndpointer(_np.uint8, flags="C_CONTIGUOUS")
        self._lib.secp_prepare_batch.restype = ctypes.c_int
        self._lib.secp_prepare_batch.argtypes = [
            ctypes.c_char_p,  # px
            ctypes.c_char_p,  # py
            ctypes.c_char_p,  # z
            ctypes.c_char_p,  # r
            ctypes.c_char_p,  # s
            ctypes.c_char_p,  # present
            ctypes.c_int,  # count
            ctypes.c_int,  # size
            i32,  # d1a
            i32,  # d1b
            i32,  # d2a
            i32,  # d2b
            u8,  # negs
            i32,  # qx
            i32,  # qy
            i32,  # r1
            i32,  # r2
            u8,  # r2_valid
            u8,  # host_valid
            u8,  # schnorr
            u8,  # bip340
            ctypes.c_int,  # nthreads
        ]
        # Width-aware prep (ISSUE 13 satellite: 5-bit digit layout).
        # Probe rather than require — a stale libsecp_cpu.so without the
        # symbol keeps the 4-bit fast path, and kernel.py falls back to
        # Python prep at w5.
        try:
            prep_w = self._lib.secp_prepare_batch_w
        except AttributeError:
            prep_w = None
        self._prep_w = prep_w
        if prep_w is not None:
            prep_w.restype = ctypes.c_int
            prep_w.argtypes = (
                self._lib.secp_prepare_batch.argtypes
                + [ctypes.c_int]  # window_bits
            )

    #: windows per supported window width (mirrors kernel.py's table)
    _WINDOWS_BY_BITS = {4: 33, 5: 27}

    def supports_window_bits(self, window_bits: int) -> bool:
        """Can this library emit the given digit layout?  4-bit always;
        5-bit needs the ``secp_prepare_batch_w`` symbol (ISSUE 13 — a
        stale .so predating it preps w5 batches in Python instead)."""
        if window_bits == 4:
            return True
        return window_bits in self._WINDOWS_BY_BITS and (
            self._prep_w is not None
        )

    def prepare_batch_arrays(
        self,
        px: bytes,
        py: bytes,
        z: bytes,
        r: bytes,
        s: bytes,
        present: bytes,
        count: int,
        size: int,
        nthreads: int = 0,
        window_bits: int = 4,
    ):
        """Fill PreparedBatch arrays natively (see kernel.prepare_batch's
        fast path).  Returns the dict of limb-major numpy arrays.  Raises
        on a GLV bound violation (structurally impossible for in-range
        scalars; nonzero means a bug, never a bad signature) and on an
        unsupported ``window_bits`` (callers gate on
        :meth:`supports_window_bits`)."""
        import numpy as np

        if not self.supports_window_bits(window_bits):
            raise RuntimeError(
                f"native prep does not support window_bits={window_bits} "
                "(stale native/build/libsecp_cpu.so? run `make -C native`)"
            )
        nwin = self._WINDOWS_BY_BITS[window_bits]
        out = {
            "d1a": np.zeros((nwin, size), np.int32),
            "d1b": np.zeros((nwin, size), np.int32),
            "d2a": np.zeros((nwin, size), np.int32),
            "d2b": np.zeros((nwin, size), np.int32),
            "negs": np.zeros((4, size), np.uint8),
            "qx": np.zeros((24, size), np.int32),
            "qy": np.zeros((24, size), np.int32),
            "r1": np.zeros((24, size), np.int32),
            "r2": np.zeros((24, size), np.int32),
            "r2_valid": np.zeros(size, np.uint8),
            "host_valid": np.zeros(size, np.uint8),
            "schnorr": np.zeros(size, np.uint8),
            "bip340": np.zeros(size, np.uint8),
        }
        args = (
            px, py, z, r, s, present, count, size,
            out["d1a"], out["d1b"], out["d2a"], out["d2b"], out["negs"],
            out["qx"], out["qy"], out["r1"], out["r2"],
            out["r2_valid"], out["host_valid"], out["schnorr"],
            out["bip340"], nthreads,
        )
        if self._prep_w is not None:
            bad = self._prep_w(*args, window_bits)
        else:
            bad = self._lib.secp_prepare_batch(*args)
        if bad:
            raise ValueError(
                f"native prep: {bad} GLV half-scalars out of range"
                if bad > 0
                else f"native prep rejected window_bits={window_bits}"
            )
        return out

    def verify_batch(self, items: Sequence[tuple]) -> list[bool]:
        """items: (pubkey|None, z, r, s) ECDSA tuples or 5-tuples tagged
        "schnorr" (z = precomputed challenge) — same shape as the oracle's
        ``verify_batch_cpu``.  ``None`` pubkeys are auto-invalid (matching
        the oracle and kernel.prepare_batch's host_valid mask)."""
        n = len(items)
        if n == 0:
            return []
        # Range checks on the ORIGINAL ints happen in pack_items: r/s from
        # lax DER can exceed 2^256, and truncating them mod 2^256 could
        # alias a hostile value onto a valid one — the oracle/TPU paths
        # reject such items, so this backend must too (never pack-then-
        # check).  pack_items zeroes those rows with present=0.
        from .raw import pack_items

        return self.verify_raw(pack_items(items))

    def verify_raw(self, raw, nthreads: int = 1) -> list[bool]:
        """Verify a packed :class:`tpunode.verify.raw.RawBatch` — the
        zero-copy path from the native extractor.  ``present`` carries the
        per-row algorithm (0 absent, 1 ecdsa, 2 schnorr) straight into the
        C engine.  ``nthreads`` != 1 splits rows across OS threads (0 =
        hardware concurrency) — the engine passes VerifyConfig.cpu_threads
        so multi-core hosts scale the fallback path."""
        n = len(raw)
        if n == 0:
            return []
        out = ctypes.create_string_buffer(n)
        present = np.ascontiguousarray(raw.present, dtype=np.uint8)
        if nthreads == 1:
            self._lib.secp_verify_batch(
                raw.px.tobytes(), raw.py.tobytes(), raw.z.tobytes(),
                raw.r.tobytes(), raw.s.tobytes(), present.tobytes(), n, out,
            )
        else:
            self._lib.secp_verify_batch_mt(
                raw.px.tobytes(), raw.py.tobytes(), raw.z.tobytes(),
                raw.r.tobytes(), raw.s.tobytes(), present.tobytes(), n, out,
                nthreads,
            )
        return [bool(raw.present[i]) and out.raw[i] == 1 for i in range(n)]


_cached: Optional[NativeVerifier] = None
_load_failed = False


def load_native_verifier() -> Optional[NativeVerifier]:
    """Build+load the native verifier; None if the toolchain is unavailable.
    Failure is cached so a broken toolchain costs one ``make`` attempt per
    process, not one per batch on the hot prep path."""
    global _cached, _load_failed
    if _cached is None and not _load_failed:
        try:
            _cached = NativeVerifier()
        except Exception:
            _load_failed = True
    return _cached
