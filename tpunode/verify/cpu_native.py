"""ctypes binding to the native C++ secp256k1 verifier.

The CPU baseline / fallback engine (native/secp256k1/secp256k1.cpp) — the
framework's equivalent of the reference's libsecp256k1 dependency
(reference stack.yaml:5,9; SURVEY.md C9).  Builds on demand with ``make -C
native`` when the shared library is missing.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Sequence

from .ecdsa_cpu import Point

__all__ = ["NativeVerifier", "load_native_verifier"]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_LIB_PATH = os.path.join(_REPO_ROOT, "native", "build", "libsecp_cpu.so")


def _ensure_built() -> str:
    if not os.path.exists(_LIB_PATH):
        subprocess.run(
            ["make", "-C", os.path.join(_REPO_ROOT, "native"), "build/libsecp_cpu.so"],
            check=True,
            capture_output=True,
        )
    return _LIB_PATH


class NativeVerifier:
    """Batch ECDSA verification through the C++ engine."""

    def __init__(self, lib_path: Optional[str] = None):
        path = lib_path or _ensure_built()
        self._lib = ctypes.CDLL(path)
        self._lib.secp_verify_batch.restype = ctypes.c_int
        self._lib.secp_verify_batch.argtypes = [
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.c_char_p,
        ]

    def verify_batch(
        self, items: Sequence[tuple[Optional[Point], int, int, int]]
    ) -> list[bool]:
        """items: (pubkey|None, z, r, s) tuples — same shape as the oracle's
        ``verify_batch_cpu``.  ``None`` pubkeys are auto-invalid (matching
        the oracle and kernel.prepare_batch's host_valid mask)."""
        n = len(items)
        if n == 0:
            return []
        px = bytearray()
        py = bytearray()
        zs = bytearray()
        rs = bytearray()
        ss = bytearray()
        degenerate = [False] * n
        for i, (q, z, r, s) in enumerate(items):
            if q is None or q.infinity:
                degenerate[i] = True
                px += b"\x00" * 32
                py += b"\x00" * 32
            else:
                px += q.x.to_bytes(32, "big")
                py += q.y.to_bytes(32, "big")
            zs += (z % (1 << 256)).to_bytes(32, "big")
            rs += (r % (1 << 256)).to_bytes(32, "big")
            ss += (s % (1 << 256)).to_bytes(32, "big")
        out = ctypes.create_string_buffer(n)
        self._lib.secp_verify_batch(
            bytes(px), bytes(py), bytes(zs), bytes(rs), bytes(ss), n, out
        )
        return [
            (not degenerate[i]) and out.raw[i] == 1 for i in range(n)
        ]


_cached: Optional[NativeVerifier] = None


def load_native_verifier() -> Optional[NativeVerifier]:
    """Build+load the native verifier; None if the toolchain is unavailable."""
    global _cached
    if _cached is None:
        try:
            _cached = NativeVerifier()
        except Exception:
            return None
    return _cached
