"""The TPU batch ECDSA verification kernel.

Verifies B signatures at once: for each signature ``(Q, z, r, s)`` compute
``R = u1*G + u2*Q`` (``u1 = z/s``, ``u2 = r/s`` mod n) and accept iff
``R != O`` and ``x(R) ≡ r (mod n)`` — the capability of libsecp256k1's
``secp256k1_ecdsa_verify`` (SURVEY.md C9), redesigned TPU-first:

* **Host prep** (cheap, Python ints): range checks, pubkey decode, one
  Montgomery batch inversion of every ``s`` in the batch, **GLV scalar
  decomposition** (secp256k1's cube-root endomorphism ``φ(x,y) = (βx, y)
  = λ·(x,y)``): each 256-bit scalar splits into two signed ~128-bit
  halves, so the device loop runs 33 windows instead of 64 — a ~1.4x cut
  in point operations for the cost of two extra table selects per window.
* **Device MSM** (the FLOPs): Shamir's trick over 33 interleaved 4-bit
  windows of the four half-scalars — ``lax.scan`` over windows, each step
  4 complete doublings + 4 complete additions with one-hot table selects
  (no gathers with data-dependent control flow, no recompilation: shapes
  are static).  Scalar signs are folded in by conditionally negating the
  selected table entry's Y (branch-free select).  Per-signature 16-entry
  tables of Q and λQ multiples are built on device (λQ's table is Q's
  with X scaled by β — the endomorphism is additive); the G and λG tables
  are compile-time constants.
* **Layout**: limb-major / batch-minor everywhere (see field.py) so the
  batch dim lands in TPU lanes with zero padding.
* **No inversions on device**: the affine check ``x(R) = r`` is done
  projectively as ``X ≡ r_cand * Z (mod p)`` for the (at most two) valid
  candidates ``r`` and ``r + n``.

Everything is exact integer math; results are bit-identical to the CPU
oracle (tested property-style in tests/test_kernel.py).
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..trace import span
from . import bounds as _bounds
from . import field as F
from .curve import (
    B3,
    INFINITY,
    make_point,
    point_form,
    pt_add,
    pt_add_mixed,
    pt_double,
    pt_select,
)
from .ecdsa_cpu import CURVE_N, CURVE_P, GENERATOR, Point

log = logging.getLogger("tpunode.verify")

__all__ = [
    "WINDOWS",
    "WINDOW_BITS",
    "WINDOW_BITS_MODES",
    "window_bits",
    "windows",
    "window_tables",
    "set_kernel_modes",
    "LAMBDA",
    "BETA",
    "glv_split",
    "kernel_modes",
    "prepare_batch",
    "verify_core",
    "verify_device",
    "verify_batch_tpu",
    "dispatch_batch_tpu",
    "collect_verdicts",
    "PreparedBatch",
]


# ---------- kernel-structure knobs (ISSUE 8) -------------------------------
#
# Same discipline as field.py's formulation knobs: process-global, read at
# TRACE time, every jit cache keyed on kernel_modes() below.
#
# TPUNODE_SELECT16: how a 4-bit digit picks its window-table entry.
#   "tree"   (default) — balanced 4-level binary select tree: 15 wheres,
#            half the data movement of the one-hot form and no integer
#            multiplies.
#   "onehot" — the r3 original: one-hot einsum (XLA) / 16-way
#            compare-accumulate (Pallas).
# TPUNODE_POW_LADDER: the shape of the constant-exponent pow ladders and
# the on-device table builds.
#   "scan"   (default) — the r3 lax.scan ladders.  Default by MEASUREMENT
#            (PERF.md ISSUE 8 section): the de-scanned programs explode
#            XLA-CPU compile time (81 s -> >500 s at batch 8 on this
#            box) for a step-time question that only a TPU can answer
#            (compiles there are server-side; benchmarks/mosaic_diag.py
#            carries a ``pow_descan`` case for the Mosaic verdict).
#   "unroll" — de-scanned (ISSUE 8 lever 2): the 64 4-bit windows unroll
#            with STATIC digits (table entries picked by static index —
#            the per-digit one-hot selects vanish entirely), and the
#            16-entry power/Q tables build through log-depth
#            square/double chains instead of a 14-step sequential scan,
#            cutting the latency-bound critical path PERF r5 measured.

SELECT_MODES = ("tree", "onehot")
POW_LADDER_MODES = ("scan", "unroll")
# MSM window width (ISSUE 12): 4-bit keeps the r3 33-round / 16-entry
# structure; 5-bit cuts the window rounds to 27 (4 fewer of everything
# per half-scalar: doublings, selects, adds) at the cost of 32-entry
# tables — the larger-VMEM-tables lever ROADMAP item 1 names.  The
# constant-exponent pow ladders stay 4-bit regardless (their 64-digit
# exponents are compile-time constants unrelated to the GLV windows).
WINDOW_BITS_MODES = (4, 5)
_WINDOWS_BY_BITS = {4: 33, 5: 27}  # ceil(~2^129 GLV halves / width) + slack

_SELECT_MODE = F._env_mode("TPUNODE_SELECT16", SELECT_MODES, "tree")
_POW_LADDER_MODE = F._env_mode(
    "TPUNODE_POW_LADDER", POW_LADDER_MODES, "scan"
)
_WINDOW_BITS = int(
    F._env_mode("TPUNODE_WINDOW_BITS", ("4", "5"), "4")
)


def select_mode() -> str:
    """Active table-select formulation: "tree" | "onehot"."""
    return _SELECT_MODE


def pow_ladder_mode() -> str:
    """Active pow-ladder/table-build shape: "unroll" | "scan"."""
    return _POW_LADDER_MODE


def window_bits() -> int:
    """Active MSM window width in bits: 4 | 5 (ISSUE 12)."""
    return _WINDOW_BITS


def windows() -> int:
    """Window rounds for the active width (33 at 4-bit, 27 at 5-bit)."""
    return _WINDOWS_BY_BITS[_WINDOW_BITS]


def set_kernel_modes(
    select: Optional[str] = None,
    pow_ladder: Optional[str] = None,
    window_bits: Optional[int] = None,
) -> tuple:
    """Select the kernel-structure formulations process-wide; returns the
    previous (select_mode, pow_ladder_mode, window_bits).  Validates ALL
    before mutating any (field.set_field_modes's contract)."""
    global _SELECT_MODE, _POW_LADDER_MODE, _WINDOW_BITS
    if select is not None and select not in SELECT_MODES:
        raise ValueError(f"select mode {select!r} not in {SELECT_MODES}")
    if pow_ladder is not None and pow_ladder not in POW_LADDER_MODES:
        raise ValueError(
            f"pow ladder mode {pow_ladder!r} not in {POW_LADDER_MODES}"
        )
    if window_bits is not None and window_bits not in WINDOW_BITS_MODES:
        raise ValueError(
            f"window bits {window_bits!r} not in {WINDOW_BITS_MODES}"
        )
    prev = (_SELECT_MODE, _POW_LADDER_MODE, _WINDOW_BITS)
    if select is not None:
        _SELECT_MODE = select
    if pow_ladder is not None:
        _POW_LADDER_MODE = pow_ladder
    if window_bits is not None:
        _WINDOW_BITS = window_bits
    return prev


def kernel_modes() -> tuple:
    """Hashable static jit-cache key for EVERY program that embeds the
    MSM: the field formulation (field.field_modes(), which carries the
    ISSUE 12 reduce mode), the point form (curve.point_form()), and the
    select/ladder/window-width shapes above — all process globals read
    at trace time, so they must force a retrace."""
    return F.field_modes() + (
        point_form(), _SELECT_MODE, _POW_LADDER_MODE, _WINDOW_BITS,
    )


def structure_modes() -> tuple:
    """:func:`kernel_modes` MINUS the point form — the cache key for jit
    sites that already carry ``point_form`` as an explicit static
    argument (pallas ``verify_blocked``): including the global form
    there too would double-encode it and retrace the identical program
    under a second key whenever the explicit argument and the global
    disagree (review r8)."""
    return F.field_modes() + (_SELECT_MODE, _POW_LADDER_MODE, _WINDOW_BITS)

# Default (4-bit) structure constants: the pow ladders' window width is
# ALWAYS 4 (compile-time 64-digit exponents); the MSM follows the
# window_bits()/windows() accessors above.
WINDOW_BITS = 4
# GLV half-scalars are bounded by ~2^129 (asserted per-item in
# prepare_batch): 33 windows cover 132 bits at 4-bit width.
WINDOWS = 33

# --- the secp256k1 endomorphism (standard public constants) ---------------
# φ(x, y) = (β·x, y) equals scalar multiplication by λ; λ³ ≡ 1 (mod n),
# β³ ≡ 1 (mod p).  The lattice basis (a1, b1), (a2, b2) below spans the
# kernel of (k1, k2) -> k1 + k2·λ (mod n) and has ~128-bit entries.
LAMBDA = 0x5363AD4CC05C30E0A5261C028812645A122E22EA20816678DF02967C1B23BD72
BETA = 0x7AE96A2B657C07106E64479EAC3434E99CF0497512F58995C1396C28719501EE
_A1 = 0x3086D221A7D46BCDE86C90E49284EB15
_B1 = -0xE4437ED6010E88286F547FA90ABFE4C3
_A2 = 0x114CA50F7A8E2F3F657C1108D9D44CFD8
_B2 = _A1

assert pow(LAMBDA, 3, CURVE_N) == 1
assert pow(BETA, 3, CURVE_P) == 1
assert (_A1 + _B1 * LAMBDA) % CURVE_N == 0
assert (_A2 + _B2 * LAMBDA) % CURVE_N == 0

_SEVEN = jnp.array(F.to_limbs(7))[:, None]
_BETA_L = jnp.array(F.to_limbs(BETA))[:, None]


# Barrett reciprocals: round(2^384 * b2 / n) and round(2^384 * |b1| / n).
# c_i = round(k * G_i / 2^384) equals the exact round((b*k + n/2) / n) in
# practice (and ANY c rounding keeps the decomposition exact: k1 + λ·k2 ≡ k
# holds structurally); the native prep (secp_prepare_batch) uses the same
# formula so both paths emit bit-identical digits.
_G1 = ((_B2 << 384) + CURVE_N // 2) // CURVE_N
_G2 = ((-_B1 << 384) + CURVE_N // 2) // CURVE_N


def glv_split(k: int) -> tuple[int, int]:
    """Decompose ``k`` (mod n) as ``k1 + k2·λ`` with |k1|, |k2| < ~2^129."""
    k %= CURVE_N
    c1 = (k * _G1 + (1 << 383)) >> 384
    c2 = (k * _G2 + (1 << 383)) >> 384
    k1 = k - c1 * _A1 - c2 * _A2
    k2 = -c1 * _B1 - c2 * _B2
    return k1, k2


def _table_np(base: Point, entries: int = 16) -> np.ndarray:
    """Constant table [O, P, 2P, ..., (entries-1)P] as projective limb
    points."""
    from .ecdsa_cpu import INFINITY as OINF, point_add

    table = np.zeros((entries, 3, F.NLIMBS), dtype=np.int32)
    table[0, 1, 0] = 1  # (0 : 1 : 0)
    acc = OINF
    for k in range(1, entries):
        acc = point_add(acc, base)
        table[k, 0] = F.to_limbs(acc.x)
        table[k, 1] = F.to_limbs(acc.y)
        table[k, 2, 0] = 1
    return table


G_TABLE = jnp.array(_table_np(GENERATOR))  # (16, 3, NLIMBS)
LG_TABLE = jnp.array(
    _table_np(Point(BETA * GENERATOR.x % CURVE_P, GENERATOR.y))
)  # table of λG = φ(G)

# Affine (2-coordinate) views for the affine point form (ISSUE 8): every
# finite constant-table entry already has Z = 1, so dropping the Z plane
# IS the normalization.  Entry 0 keeps (0, 1) from (0 : 1 : 0) — a
# placeholder the window loop never adds (digit-0 keeps the accumulator
# through a branch-free select instead).
G_TABLE_AFF = G_TABLE[:, :2]  # (16, 2, NLIMBS)
LG_TABLE_AFF = LG_TABLE[:, :2]

# Per-window-width constant tables (ISSUE 12), cached as PURE NUMPY:
# the first fetch can happen inside a jit trace, where any jnp value
# created (even from constants) is that trace's tracer — caching one
# would poison every later trace.  Numpy constants lift cleanly into
# whichever trace uses them.
_WINDOW_TABLES: dict = {}


def window_tables() -> tuple:
    """(G, λG, G_affine, λG_affine) constant tables for the ACTIVE
    window width — numpy, (2^wb, 3|2, NLIMBS) each."""
    got = _WINDOW_TABLES.get(_WINDOW_BITS)
    if got is None:
        ent = 1 << _WINDOW_BITS
        g = _table_np(GENERATOR, ent)
        lg = _table_np(Point(BETA * GENERATOR.x % CURVE_P, GENERATOR.y), ent)
        got = (g, lg, g[:, :2], lg[:, :2])
        _WINDOW_TABLES[_WINDOW_BITS] = got
    return got


# One annotated list drives PreparedBatch.__slots__, the device_args order
# (== verify_core's signature order), and the 2-D/1-D split shard_map
# callers need — so the three can't drift apart.
_DEVICE_FIELDS = (
    ("d1a", 2),
    ("d1b", 2),
    ("d2a", 2),
    ("d2b", 2),
    ("n1a", 1),
    ("n1b", 1),
    ("n2a", 1),
    ("n2b", 1),
    ("qx", 2),
    ("qy", 2),
    ("r1", 2),
    ("r2", 2),
    ("r2_valid", 1),
    ("host_valid", 1),
    ("schnorr", 1),  # per-lane algorithm: BCH Schnorr instead of ECDSA
    ("bip340", 1),  # per-lane algorithm: BIP340 (taproot) Schnorr
)

# For shard_map callers: which device_args are 2-D (batch trailing) vs 1-D.
ARG_IS_2D = tuple(nd == 2 for _, nd in _DEVICE_FIELDS)


class PreparedBatch:
    """Host-prepared device inputs for one batch of signatures.

    Limb-major layout: digit arrays ``(WINDOWS, B)``, limb arrays
    ``(NLIMBS, B)``, masks ``(B,)``.  ``device_args`` yields the arrays in
    :func:`verify_core` argument order so callers stay decoupled from it.
    """

    __slots__ = tuple(name for name, _ in _DEVICE_FIELDS) + ("count",)

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)

    @property
    def device_args(self) -> tuple:
        return tuple(getattr(self, name) for name, _ in _DEVICE_FIELDS)

    @property
    def schnorr_free(self) -> bool:
        """No lane carries a Schnorr/BIP340 flag: the batch may use the
        program variants with the jacobi/parity acceptance pows pruned.
        The ONE derivation every dispatch site must use — a wrong True
        would accept jacobi/parity forgeries."""
        return not (np.any(self.schnorr) or np.any(self.bip340))


def _batch_inverse_mod_n(values: list[int]) -> list[int]:
    """Montgomery batch inversion mod n: one pow() for the whole batch.

    B == 1 short-circuits to the bare pow (ISSUE 8 bugfix sweep): the
    general path builds the prefix/suffix machinery around the same
    single pow, which is pure overhead for the singleton batches the
    mempool's per-tx admission path submits."""
    if not values:
        return []
    if len(values) == 1:
        return [pow(values[0], -1, CURVE_N)]
    prefix = []
    run = 1
    for v in values:
        run = run * v % CURVE_N
        prefix.append(run)
    inv = pow(run, -1, CURVE_N)
    out = [0] * len(values)
    for i in range(len(values) - 1, -1, -1):
        before = prefix[i - 1] if i > 0 else 1
        out[i] = inv * before % CURVE_N
        inv = inv * values[i] % CURVE_N
    return out


def _digits_base16(v: int) -> list[int]:
    """windows() base-2^wb digits of a nonnegative int, most significant
    first (historical name: base-16 under the default 4-bit width)."""
    wb, nwin = _WINDOW_BITS, windows()
    mask = (1 << wb) - 1
    return [(v >> (wb * (nwin - 1 - i))) & mask for i in range(nwin)]


def _ints_to_limbs_np(vals: list[int]) -> np.ndarray:
    """Vectorized ``F.to_limbs``: 256-bit ints -> (len, NLIMBS) int32.

    Python-loop limb extraction dominates host prep at batch 4096 (~15 ms
    per array x 4 arrays); this does one ``to_bytes`` per int and then
    numpy uint64 shifts — ~10x faster.  Bit-identical to F.to_limbs
    (tested in tests/test_kernel.py::test_np_conversions_match_scalar).
    """
    n = len(vals)
    buf = b"".join(v.to_bytes(32, "little") for v in vals)
    words = np.frombuffer(buf, dtype="<u8").reshape(n, 4)
    out = np.zeros((n, F.NLIMBS), dtype=np.int32)
    for i in range(F.NLIMBS):
        w, off = divmod(F.RADIX * i, 64)
        lo = words[:, w] >> np.uint64(off)
        if off > 64 - F.RADIX and w + 1 < 4:  # limb straddles a word edge
            lo = lo | (words[:, w + 1] << np.uint64(64 - off))
        out[:, i] = (lo & np.uint64(F.MASK)).astype(np.int32)
    return out


def _ints_to_digits_np(vals: list[int]) -> np.ndarray:
    """Vectorized ``_digits_base16``: ints < 2^(wb*windows()) ->
    (len, windows()) int32, MSB-first.  4-bit digits never straddle
    64-bit word edges; 5-bit digits can, so the straddle path ORs in the
    next word's low bits (same trick as ``_ints_to_limbs_np``)."""
    wb, nwin = _WINDOW_BITS, windows()
    mask = (1 << wb) - 1
    n = len(vals)
    buf = b"".join(v.to_bytes(24, "little") for v in vals)
    words = np.frombuffer(buf, dtype="<u8").reshape(n, 3)
    out = np.zeros((n, nwin), dtype=np.int32)
    for j in range(nwin):
        w, off = divmod(wb * (nwin - 1 - j), 64)
        lo = words[:, w] >> np.uint64(off)
        if off > 64 - wb and w + 1 < 3:  # digit straddles a word edge
            lo = lo | (words[:, w + 1] << np.uint64(64 - off))
        out[:, j] = (lo & np.uint64(mask)).astype(np.int32)
    return out


def _item_algo(item: tuple) -> Optional[str]:
    """The VerifyItem tuple's algorithm tag ("schnorr" / "bip340") or None
    for plain ECDSA."""
    if len(item) >= 5 and item[4] in ("schnorr", "bip340"):
        return item[4]
    return None


def prepare_batch(
    items: Sequence[tuple],
    pad_to: Optional[int] = None,
    native: Optional[bool] = None,
) -> PreparedBatch:
    """Host-side preparation: (pubkey|None, z, r, s[, "schnorr"]) -> device
    arrays.  ECDSA items carry the sighash in ``z``; Schnorr items carry
    the PRECOMPUTED challenge ``e`` (u1 = s, u2 = n - e — no inversion).

    Invalid-by-inspection entries (bad ranges, missing/infinite pubkey) are
    masked out host-side (``host_valid``); their lanes carry dummy values so
    shapes stay static.  ``pad_to`` pads the batch to a fixed size to avoid
    recompilation across batches.

    ``native=None`` auto-selects the C++ fast path (secp_prepare_batch_w
    in native/secp256k1 — batch inversion, GLV split, digit/limb
    conversion; bit-identical outputs, ~10x the Python rate) when the
    library loads AND supports the active window width (ISSUE 13 closed
    the PR 12 gap: the native layer now emits the 5-bit word-straddling
    digit layout too; only a stale pre-w5 .so falls back to Python);
    ``native=False`` forces the pure-Python reference path.
    """
    if native is not False and _WINDOW_BITS != 4:
        from .cpu_native import load_native_verifier

        nv = load_native_verifier()
        if nv is None or not nv.supports_window_bits(_WINDOW_BITS):
            if native is True:
                raise RuntimeError(
                    "native prep does not support window_bits="
                    f"{_WINDOW_BITS} (stale libsecp_cpu.so? run "
                    "`make -C native`) — the Python path handles it"
                )
            native = False
    if native is not False:
        prep = _prepare_batch_native(items, pad_to)
        if prep is not None or native is True:
            if prep is None:
                raise RuntimeError("native prep requested but unavailable")
            return prep
    count = len(items)
    size = pad_to or count
    assert size >= count
    nwin = windows()
    d1a = np.zeros((size, nwin), dtype=np.int32)
    d1b = np.zeros((size, nwin), dtype=np.int32)
    d2a = np.zeros((size, nwin), dtype=np.int32)
    d2b = np.zeros((size, nwin), dtype=np.int32)
    negs = np.zeros((4, size), dtype=bool)
    qx = np.zeros((size, F.NLIMBS), dtype=np.int32)
    qy = np.zeros((size, F.NLIMBS), dtype=np.int32)
    r1 = np.zeros((size, F.NLIMBS), dtype=np.int32)
    r2 = np.zeros((size, F.NLIMBS), dtype=np.int32)
    r2v = np.zeros((size,), dtype=bool)
    hv = np.zeros((size,), dtype=bool)
    sch = np.zeros((size,), dtype=bool)
    b340 = np.zeros((size,), dtype=bool)

    s_vals = []
    s_idx = []
    for i, item in enumerate(items):
        q, z, r, s = item[:4]
        if q is None or q.infinity:
            continue
        tag = _item_algo(item)
        if tag is not None:
            if not (0 <= r < CURVE_P and 0 <= s < CURVE_N):
                continue
            hv[i] = True
            (sch if tag == "schnorr" else b340)[i] = True
        else:
            if not (0 < r < CURVE_N and 0 < s < CURVE_N):
                continue
            hv[i] = True
            s_vals.append(s)
            s_idx.append(i)
    with span("verify.batch_inv"):
        s_inv = _batch_inverse_mod_n(s_vals) if s_vals else []
    inv_by_idx = dict(zip(s_idx, s_inv))

    digit_arrays = (d1a, d1b, d2a, d2b)
    bound = 1 << (_WINDOW_BITS * nwin)
    # Gather per-valid-lane scalars, then convert in bulk with numpy
    # (the per-int Python limb/digit loops dominate prep otherwise).
    idxs: list[int] = []
    half_abs: tuple[list[int], ...] = ([], [], [], [])
    gx: list[int] = []
    gy: list[int] = []
    gr1: list[int] = []
    r2_idx: list[int] = []
    gr2: list[int] = []
    for i, item in enumerate(items):
        if not hv[i]:
            continue
        q, z, r, s = item[:4]
        idxs.append(i)
        if sch[i] or b340[i]:
            u1 = s % CURVE_N
            u2 = (CURVE_N - z % CURVE_N) % CURVE_N
        else:
            w = inv_by_idx[i]
            u1 = (z % CURVE_N) * w % CURVE_N
            u2 = r * w % CURVE_N
        halves = glv_split(u1) + glv_split(u2)
        for j, k in enumerate(halves):
            if abs(k) >= bound:  # not assert: -O must not strip a consensus guard
                raise ValueError(
                    f"GLV half-scalar out of window range: |{k}| >= 2^"
                    f"{_WINDOW_BITS * nwin} (item {i}, half {j})"
                )
            negs[j, i] = k < 0
            half_abs[j].append(abs(k))
        gx.append(q.x)
        gy.append(q.y)
        gr1.append(r)
        if not (sch[i] or b340[i]) and r + CURVE_N < CURVE_P:
            r2_idx.append(i)
            gr2.append(r + CURVE_N)
    if idxs:
        ii = np.array(idxs)
        for j, dst in enumerate(digit_arrays):
            dst[ii] = _ints_to_digits_np(half_abs[j])
        qx[ii] = _ints_to_limbs_np(gx)
        qy[ii] = _ints_to_limbs_np(gy)
        r1[ii] = _ints_to_limbs_np(gr1)
    if r2_idx:
        jj = np.array(r2_idx)
        r2[jj] = _ints_to_limbs_np(gr2)
        r2v[jj] = True

    t = np.ascontiguousarray
    return PreparedBatch(
        d1a=t(d1a.T),
        d1b=t(d1b.T),
        d2a=t(d2a.T),
        d2b=t(d2b.T),
        n1a=t(negs[0]),
        n1b=t(negs[1]),
        n2a=t(negs[2]),
        n2b=t(negs[3]),
        qx=t(qx.T),
        qy=t(qy.T),
        r1=t(r1.T),
        r2=t(r2.T),
        r2_valid=r2v,
        host_valid=hv,
        schnorr=sch,
        bip340=b340,
        count=count,
    )


def _prepare_batch_native(
    items: Sequence[tuple[Optional[Point], int, int, int]],
    pad_to: Optional[int],
) -> Optional[PreparedBatch]:
    """C++ fast path for prepare_batch (None if the library is missing).

    Python packs fixed-width byte columns and prechecks ranges (so every
    packed int fits 32 bytes); the native side redoes the r/s range checks,
    then does the heavy big-int work per item.  Output arrays are written
    directly in limb-major layout — no transposes.
    """
    from .cpu_native import load_native_verifier

    nv = load_native_verifier()
    if nv is None or not nv.supports_window_bits(_WINDOW_BITS):
        return None
    count = len(items)
    size = pad_to or count
    assert size >= count
    zero32 = b"\x00" * 32
    px, py, zs, rs, ss, present = [], [], [], [], [], bytearray(count)
    for i, item in enumerate(items):
        q, z, r, s = item[:4]
        tag = _item_algo(item)
        if q is not None and not q.infinity and (
            (0 <= r < CURVE_P and 0 <= s < CURVE_N)
            if tag is not None
            else (0 < r < CURVE_N and 0 < s < CURVE_N)
        ):
            present[i] = 1 if tag is None else (2 if tag == "schnorr" else 3)
            px.append(q.x.to_bytes(32, "big"))
            py.append(q.y.to_bytes(32, "big"))
            zs.append((z % CURVE_N).to_bytes(32, "big"))
            rs.append(r.to_bytes(32, "big"))
            ss.append(s.to_bytes(32, "big"))
        else:
            px.append(zero32)
            py.append(zero32)
            zs.append(zero32)
            rs.append(zero32)
            ss.append(zero32)
    out = nv.prepare_batch_arrays(
        b"".join(px),
        b"".join(py),
        b"".join(zs),
        b"".join(rs),
        b"".join(ss),
        bytes(present),
        count,
        size,
        window_bits=_WINDOW_BITS,
    )
    return PreparedBatch(
        d1a=out["d1a"],
        d1b=out["d1b"],
        d2a=out["d2a"],
        d2b=out["d2b"],
        n1a=out["negs"][0].astype(bool),
        n1b=out["negs"][1].astype(bool),
        n2a=out["negs"][2].astype(bool),
        n2b=out["negs"][3].astype(bool),
        qx=out["qx"],
        qy=out["qy"],
        r1=out["r1"],
        r2=out["r2"],
        r2_valid=out["r2_valid"].astype(bool),
        host_valid=out["host_valid"].astype(bool),
        schnorr=out["schnorr"].astype(bool),
        bip340=out["bip340"].astype(bool),
        count=count,
    )


def prepare_batch_raw(raw, pad_to: Optional[int] = None) -> PreparedBatch:
    """Host prep from a packed :class:`tpunode.verify.raw.RawBatch` — the
    zero-Python-int path from the native extractor straight into
    ``secp_prepare_batch`` (which redoes all range checks on the raw rows).
    Falls back to the tuple path when the native library is unavailable
    or too old to emit the active window width's digit layout (ISSUE 13:
    a current build handles both 4- and 5-bit)."""
    from .cpu_native import load_native_verifier

    nv = load_native_verifier()
    if nv is None or not nv.supports_window_bits(_WINDOW_BITS):
        return prepare_batch(raw.to_tuples(), pad_to=pad_to, native=False)
    count = len(raw)
    size = pad_to or count
    assert size >= count
    out = nv.prepare_batch_arrays(
        raw.px.tobytes(),
        raw.py.tobytes(),
        raw.z.tobytes(),
        raw.r.tobytes(),
        raw.s.tobytes(),
        raw.present.tobytes(),
        count,
        size,
        window_bits=_WINDOW_BITS,
    )
    return PreparedBatch(
        d1a=out["d1a"],
        d1b=out["d1b"],
        d2a=out["d2a"],
        d2b=out["d2b"],
        n1a=out["negs"][0].astype(bool),
        n1b=out["negs"][1].astype(bool),
        n2a=out["negs"][2].astype(bool),
        n2b=out["negs"][3].astype(bool),
        qx=out["qx"],
        qy=out["qy"],
        r1=out["r1"],
        r2=out["r2"],
        r2_valid=out["r2_valid"].astype(bool),
        host_valid=out["host_valid"].astype(bool),
        schnorr=out["schnorr"].astype(bool),
        bip340=out["bip340"].astype(bool),
        count=count,
    )


def _build_q_table(qx: jnp.ndarray, qy: jnp.ndarray) -> jnp.ndarray:
    """Per-signature table [O, Q, 2Q, ..., (2^wb - 1)Q], shape
    (2^wb, 3, L, B) — 16 entries at the default 4-bit width, 32 at 5-bit
    (ISSUE 12).

    Under the ``unroll`` ladder mode the build is a de-scanned log-depth
    double-and-add chain (ISSUE 8 lever 2): complete doublings + complete
    additions (vs the scan's sequential adds — fewer field muls AND a
    much shorter critical path).  ``scan`` (the default — see the knob
    comment for the measured why) keeps the r3 sequential form.  Both
    are exact, so verdicts are bit-identical either way."""
    ent_n = 1 << _WINDOW_BITS
    q1 = make_point(qx, qy, jnp.broadcast_to(F.ONE, qx.shape))
    inf = jnp.broadcast_to(INFINITY, q1.shape)
    if _POW_LADDER_MODE == "scan":
        def step(acc, _):
            nxt = pt_add(acc, q1)
            return nxt, nxt

        _, multiples = lax.scan(step, q1, None, length=ent_n - 2)  # 2Q..
        return jnp.concatenate([inf[None], q1[None], multiples], axis=0)
    ent: list = [None] * ent_n
    ent[0], ent[1] = inf, q1
    for k in range(2, ent_n):
        ent[k] = pt_double(ent[k // 2]) if k % 2 == 0 else pt_add(ent[k - 1], q1)
    return jnp.stack(ent, axis=0)


def _lambda_table(q_table: jnp.ndarray) -> jnp.ndarray:
    """Table of λQ multiples from the Q table: the endomorphism is additive
    (φ(kQ) = k·φ(Q)), so scaling each entry's X by β is all it takes —
    16 field muls instead of another 14 point additions."""
    xs = q_table[:, 0]  # (16, L, B)
    lxs = jax.vmap(lambda x: F.mul(x, _BETA_L))(xs)
    return q_table.at[:, 0].set(lxs)


def _select_entry_onehot(table: jnp.ndarray, digits: jnp.ndarray) -> jnp.ndarray:
    """One-hot select: table (T, C, L, B) or (T, C, L), digits (B,) ->
    (C, L, B); T = 2^window_bits entries."""
    onehot = jax.nn.one_hot(
        digits, int(table.shape[0]), dtype=jnp.int32
    ).T  # (T, B)
    if table.ndim == 3:
        return jnp.einsum("tb,tcl->clb", onehot, table)
    return jnp.einsum("tb,tclb->clb", onehot, table)


def select_tree16(entries: list, digits: jnp.ndarray) -> jnp.ndarray:
    """THE balanced binary select-tree fold (ISSUE 8 lever 3): T-1
    wheres over T entries (a power of two — 16 at 4-bit windows, 32 at
    5-bit), level ``i`` resolving digit bit ``i``.  ``entries`` are the
    table entries (arrays or VMEM-ref reads), ``digits`` any digit array
    that broadcasts against them under ``jnp.where``.  Shared by the XLA
    select below AND the Pallas ``_select16`` tree branch so the two
    device paths cannot diverge (one fold, the same way curve.py's
    formulas are shared via the ``F=`` namespace)."""
    level = list(entries)
    depth = (len(level) - 1).bit_length()
    assert len(level) == 1 << depth, "select tree needs 2^k entries"
    for i in range(depth):
        bit = ((digits >> i) & 1) == 1
        level = [
            jnp.where(bit, level[2 * j + 1], level[2 * j])
            for j in range(len(level) // 2)
        ]
    return level[0]


def _select_entry_tree(table: jnp.ndarray, digits: jnp.ndarray) -> jnp.ndarray:
    """Balanced select tree over a stacked table: T-1 wheres moving T-1
    entry-volumes of data vs the one-hot form's T multiplies + T-1 adds
    over the whole table — and no integer multiplies at all.  Identical
    output to the one-hot select for digits in [0, T)."""
    if table.ndim == 3:  # constant (T, C, L) table: broadcast over lanes
        table = table[..., None]
    # digits (B,) broadcasts over each (C, L, B) entry
    return select_tree16(
        [table[t] for t in range(int(table.shape[0]))], digits
    )


def _select_entry(table: jnp.ndarray, digits: jnp.ndarray) -> jnp.ndarray:
    """Digit-indexed window-table select, per the active select mode."""
    if _SELECT_MODE == "onehot":
        return _select_entry_onehot(table, digits)
    return _select_entry_tree(table, digits)


def _signed(entry: jnp.ndarray, neg: jnp.ndarray) -> jnp.ndarray:
    """Negate the point iff ``neg`` (per-lane): -P = (X, -Y[, Z]) — works
    on projective (3, L, B) and affine (2, L, B) entries alike."""
    return entry.at[1].set(jnp.where(neg, -entry[1], entry[1]))


def _normalize_q_table(
    q_table: jnp.ndarray, F=F, pow_const=None
) -> jnp.ndarray:
    """Projective Q table (16, 3, L, B) -> affine (16, 2, L, B) via one
    Montgomery-trick batch inversion per lane (ISSUE 8 lever 1).

    Entries 2..15 carry arbitrary Z; entry 1 is (qx, qy, 1) and entry 0
    is infinity (gets the (0, 1) placeholder — the window loop's digit-0
    select never adds it).  One shared Fermat ``Z^(p-2)`` ladder inverts
    the 14-entry Z product (amortized over the whole table), prefix/
    suffix products recover each entry's inverse with 2 muls, and 2 more
    muls normalize (X, Y).  Cost: 13 prefix + 1 ladder + 26 suffix + 28
    normalize muls ≈ ladder + 67 vs the 14 x 1-full-mul-per-add saving
    plus a third less select traffic in the window loop (the measured
    trade is in PERF.md).

    A lane whose table hits Z ≡ 0 beyond entry 0 (impossible for a valid
    on-curve Q on a prime-order curve; reachable only for garbage/
    off-curve host inputs) zeroes that LANE's products and produces
    garbage affine entries — harmless, because such lanes are already
    masked by host_valid/on_curve in the verdict.

    ``F``/``pow_const`` parameterized like curve.py's formulas so the
    roofline can count this function by executing it.  Entry count
    follows the table's leading axis (16 at 4-bit windows, 32 at
    5-bit)."""
    if pow_const is None:
        pow_const = _pow_const
    ent_n = int(q_table.shape[0])
    zs = [q_table[k, 2] for k in range(2, ent_n)]  # (L, B) each
    prefix = [zs[0]]  # prefix[i] = z_2 * ... * z_{i+2}
    for z in zs[1:]:
        prefix.append(F.mul(prefix[-1], z))
    inv = pow_const(prefix[-1], _PM2_DIGITS)  # ONE ladder for the table
    ent: list = [None] * ent_n
    shape = q_table.shape[-2:]
    ent[0] = jnp.stack(
        [jnp.broadcast_to(F.ZERO, shape), jnp.broadcast_to(F.ONE, shape)],
        axis=0,
    )
    ent[1] = q_table[1, :2]  # (qx, qy): affine by construction
    run = inv  # invariant entering entry k: run = (z_2 ... z_k)^-1
    for k in range(ent_n - 1, 1, -1):
        zinv = F.mul(run, prefix[k - 3]) if k > 2 else run
        ent[k] = jnp.stack(
            [F.mul(q_table[k, 0], zinv), F.mul(q_table[k, 1], zinv)], axis=0
        )
        if k > 2:
            run = F.mul(run, zs[k - 2])
    return jnp.stack(ent, axis=0)


# Constant-exponent digit tables (64 MSB-first 4-bit digits each) for the
# two fixed powers the acceptance tests need — compile-time constants, so
# the windowed pow needs no data-dependent digit extraction.
_EULER_DIGITS = np.array(
    [((CURVE_P - 1) // 2 >> (4 * (63 - i))) & 0xF for i in range(64)],
    dtype=np.int32,
)  # Euler's criterion: jacobi via t^((p-1)/2)
_PM2_DIGITS = np.array(
    [((CURVE_P - 2) >> (4 * (63 - i))) & 0xF for i in range(64)],
    dtype=np.int32,
)  # Fermat inverse: z^(p-2)


def _pow_table(t: jnp.ndarray) -> list:
    """[1, t, t^2, ..., t^15] via a log-depth square/multiply chain: same
    14 muls as the sequential chain (squares where possible — cheaper
    under the dedicated sqr path) but critical depth 4 instead of 14."""
    table: list = [None] * 16
    table[0] = jnp.broadcast_to(F.ONE, t.shape)
    table[1] = t
    for k in range(2, 16):
        table[k] = (
            F.sqr(table[k // 2]) if k % 2 == 0 else F.mul(table[k - 1], t)
        )
    return table


def _pow_const(t: jnp.ndarray, digits: np.ndarray) -> jnp.ndarray:
    """Windowed 4-bit pow by a COMPILE-TIME exponent for a (L, B) limb
    column, paid once per batch for every lane uniformly (branch-free
    SPMD).

    ``unroll`` mode (ISSUE 8 lever 2): the 64 windows unroll with
    STATIC digits, so each window's table entry is picked by a plain
    static index — the scan's 64 one-hot selects (16 muls + 15 adds
    over the whole table, each) vanish, zero-digit windows skip their
    mul outright, and the first window seeds the accumulator directly
    (4 squarings + 1 mul saved).  ``scan`` (the default — the unrolled
    program's XLA-CPU compile cost is the measured blocker, see the
    knob comment) keeps the r3 sequential lax.scan ladder
    (latency-bound, PERF r5).  Exact either way."""
    if _POW_LADDER_MODE == "scan":
        one = jnp.broadcast_to(F.ONE, t.shape)

        def tstep(acc, _):
            nxt = F.mul(acc, t)
            return nxt, nxt

        _, mults = lax.scan(tstep, t, None, length=14)  # t^2 .. t^15
        table = jnp.concatenate([one[None], t[None], mults], axis=0)

        def step(acc, d):
            acc = F.sqr(F.sqr(F.sqr(F.sqr(acc))))
            sel = jnp.einsum(
                "t,tlb->lb", jax.nn.one_hot(d, 16, dtype=jnp.int32), table
            )
            return F.mul(acc, sel), None

        acc, _ = lax.scan(step, one, jnp.asarray(digits))
        return acc
    table = _pow_table(t)
    ds = [int(d) for d in np.asarray(digits)]
    acc = table[ds[0]]  # MSB window: skip the leading squarings of 1
    for d in ds[1:]:
        acc = F.sqr(F.sqr(F.sqr(F.sqr(acc))))
        if d:
            acc = F.mul(acc, table[d])
    return acc


def _euler_is_one(t: jnp.ndarray) -> jnp.ndarray:
    """Legendre symbol check ``t^((p-1)/2) ≡ 1 (mod p)`` — the jacobi(y)
    acceptance test of BCH Schnorr."""
    return F.eq(_pow_const(t, _EULER_DIGITS), jnp.broadcast_to(F.ONE, t.shape))


def verify_core(
    d1a: jnp.ndarray,  # (33, B) int32, MSB-first base-16 digits of |u1a|
    d1b: jnp.ndarray,  # (33, B)  |u1b|  (λ half of u1)
    d2a: jnp.ndarray,  # (33, B)  |u2a|
    d2b: jnp.ndarray,  # (33, B)  |u2b|  (λ half of u2)
    n1a: jnp.ndarray,  # (B,) bool: u1a < 0
    n1b: jnp.ndarray,  # (B,) bool
    n2a: jnp.ndarray,  # (B,) bool
    n2b: jnp.ndarray,  # (B,) bool
    qx: jnp.ndarray,  # (L, B)
    qy: jnp.ndarray,  # (L, B)
    r1: jnp.ndarray,  # (L, B)
    r2: jnp.ndarray,  # (L, B)
    r2_valid: jnp.ndarray,  # (B,) bool
    host_valid: jnp.ndarray,  # (B,) bool
    schnorr: jnp.ndarray,  # (B,) bool: lane verifies BCH Schnorr
    bip340: jnp.ndarray,  # (B,) bool: lane verifies BIP340 (taproot)
) -> jnp.ndarray:
    """The device program (un-jitted: reused by the shard_map multi-chip
    wrapper in multichip.py): returns a (B,) bool validity vector.

    One program, three signature algorithms (same dual-scalar MSM):
    per-lane flags select the acceptance test — ECDSA checks
    ``x(R) ∈ {r, r+n} (mod p)``; BCH Schnorr checks ``x(R) = r`` AND
    ``jacobi(y(R)) = 1``; BIP340 checks ``x(R) = r`` AND ``y(R)`` even
    (host prep already folded ``u1 = s``, ``u2 = n - e`` into the digit
    arrays for both Schnorr variants).

    The MSM's point form is read from ``curve.point_form()`` at TRACE
    time (ISSUE 8): "projective" keeps 3-coordinate tables + the full
    RCB add; "affine" batch-normalizes the Q/λQ tables with one
    Montgomery-trick inversion per lane and runs the window loop on
    2-coordinate tables with the 11-mul complete MIXED add (digit 0 —
    the infinity entry, unrepresentable in affine — keeps the
    accumulator through a branch-free select).  The MSM's window width
    and reduction discipline follow ``window_bits()`` and
    ``field.reduce_mode()`` (ISSUE 12) — per-window doublings equal the
    width, table/select sizes equal 2^width.  Verdicts are bit-identical
    across forms/widths/disciplines (everything downstream is exact
    mod p).
    """
    # Trace-time int32 safety audit of the live formulas under the
    # active reduce mode (ISSUE 12): cached pure-Python bound replay —
    # a formula edit that breaks headroom fails HERE, not on device.
    _bounds.assert_formulas_safe()

    # Trace-time data/mode consistency (the shape is static in a trace):
    # digit rows prepped at one window width driven by another width's
    # doubling count would be silently wrong verdicts, not an error.
    if d1a.shape[0] != windows():
        raise RuntimeError(
            f"digit arrays carry {d1a.shape[0]} window rows but the "
            f"active window_bits={_WINDOW_BITS} needs {windows()}: "
            "re-prepare the batch under the active mode"
        )

    wb = _WINDOW_BITS
    g_tab, lg_tab, g_aff, lg_aff = window_tables()
    q_table = _build_q_table(qx, qy)  # (2^wb, 3, L, B)

    acc0 = jnp.broadcast_to(INFINITY, (3, F.NLIMBS, qx.shape[1]))

    if point_form() == "affine":
        q_aff = _normalize_q_table(q_table)  # (2^wb, 2, L, B)
        lq_aff = _lambda_table(q_aff)  # β-scaled X, same trick

        def window_step(acc, digits):
            da, db, dc, dd = digits
            for _ in range(wb):
                acc = pt_double(acc)
            for table, d, neg in (
                (g_aff, da, n1a),
                (lg_aff, db, n1b),
                (q_aff, dc, n2a),
                (lq_aff, dd, n2b),
            ):
                sel = _signed(_select_entry(table, d), neg)
                acc = pt_select(d == 0, acc, pt_add_mixed(acc, sel))
            return acc, None

    else:
        lq_table = _lambda_table(q_table)

        def window_step(acc, digits):
            da, db, dc, dd = digits
            for _ in range(wb):
                acc = pt_double(acc)
            acc = pt_add(acc, _signed(_select_entry(g_tab, da), n1a))
            acc = pt_add(acc, _signed(_select_entry(lg_tab, db), n1b))
            acc = pt_add(acc, _signed(_select_entry(q_table, dc), n2a))
            acc = pt_add(acc, _signed(_select_entry(lq_table, dd), n2b))
            return acc, None

    acc, _ = lax.scan(window_step, acc0, (d1a, d1b, d2a, d2b))

    X, Y, Z = acc[0], acc[1], acc[2]
    not_inf = ~F.is_zero(Z)
    m1 = F.eq(X, F.mul(r1, Z))
    m2 = F.eq(X, F.mul(r2, Z)) & r2_valid
    # The two acceptance pows below are ~19% of the program's field-mul
    # budget (2 × ~335 muls vs ~3500 total) but only matter to lanes of
    # their algorithm — and real batches are often single-algorithm (BTC
    # mainnet carries no BCH Schnorr; IBD-era blocks carry no taproot).
    # Gate each on a batch-level any() with lax.cond: XLA compiles both
    # branches once, runtime executes one, and the placeholder lanes are
    # never selected by the algo_ok where() below, so results are
    # bit-identical to the ungated program.
    true_col = jnp.ones(qx.shape[1], dtype=bool)
    # jacobi(y(R)) for the BCH Schnorr lanes: y = Y/Z, and jacobi(Y/Z) =
    # jacobi(Y·Z) since the symbol is multiplicative and squares vanish
    jac_ok = lax.cond(
        jnp.any(schnorr),
        lambda: _euler_is_one(F.mul(Y, Z)),
        lambda: true_col,
    )
    # y(R) parity for the BIP340 lanes: affine y via a Fermat inverse
    # (z^(p-2)), then the canonical representative's low bit
    even_ok = lax.cond(
        jnp.any(bip340),
        lambda: (
            F.canonical(F.mul(Y, _pow_const(Z, _PM2_DIGITS)))[0] & 1
        ) == 0,
        lambda: true_col,
    )
    # pubkey must satisfy the curve equation: qy^2 = qx^3 + 7
    on_curve = F.eq(F.sqr(qy), F.mul(F.sqr(qx), qx) + _SEVEN)
    algo_ok = jnp.where(
        bip340, m1 & even_ok, jnp.where(schnorr, m1 & jac_ok, m1 | m2)
    )
    return host_valid & on_curve & not_inf & algo_ok


# Jitted verify_core, one executable per formulation-mode tuple
# (TPUNODE_FIELD_MUL / TPUNODE_FIELD_SQR from ISSUE 4, plus ISSUE 8's
# TPUNODE_POINT_FORM / TPUNODE_SELECT16 / TPUNODE_POW_LADDER): every
# formulation is read from process globals at TRACE time, so the full
# kernel_modes() tuple must be part of the jit cache key — as a static
# argument.  (Distinct ``jax.jit(verify_core)`` wrapper objects share
# one underlying trace cache keyed on the wrapped function, so a
# per-mode dict of wrappers does NOT retrace — measured the hard way.)
from functools import partial as _partial


@_partial(jax.jit, static_argnames=("field_modes",))
def _verify_device_jit(*args, field_modes=None):
    # cache key only (the full kernel_modes() tuple rides in under the
    # historical "field_modes" name): forces a retrace per formulation
    del field_modes
    return verify_core(*args)


def verify_device(*args) -> jnp.ndarray:
    """Jitted :func:`verify_core` under the ACTIVE formulation modes
    (:func:`kernel_modes` — field + point form + select/ladder shape) —
    a drop-in for the former module-level ``jax.jit(verify_core)``."""
    return _verify_device_jit(*args, field_modes=kernel_modes())


# Sticky per-process flag: set when a pallas compile fails with a
# Mosaic/remote-compile error (observed r5: the axon compile helper 500s
# on every pallas program while plain XLA compiles and runs).  Dispatch
# then stays on the XLA program so the engine keeps a device path instead
# of failing warmup and pinning itself to the CPU fallback.
#
# TPUNODE_VERIFY_KERNEL=xla seeds the flag at import: a parent that has
# already diagnosed the outage (the round-long watcher) can force fresh
# subprocesses straight to the XLA program.  The r5 outage's hang mode
# makes this necessary — a pallas compile that HANGS (rather than
# erroring) cannot be caught in-process, so warmup in an engine-bearing
# config run would otherwise burn the whole subprocess watchdog.
_PALLAS_BROKEN = (
    os.environ.get("TPUNODE_VERIFY_KERNEL", "").strip().lower() == "xla"
)


def pallas_broken() -> bool:
    """Has a pallas compile failed with a Mosaic error this process?"""
    return _PALLAS_BROKEN


def _is_mosaic_error(e: Exception) -> bool:
    s = f"{type(e).__name__}: {e}"
    return "Mosaic" in s or "remote_compile" in s


def mark_pallas_broken_if_mosaic(e: Exception, where: str = "at collect") -> bool:
    """If ``e`` is a Mosaic/remote-compile failure, set the sticky
    process-wide pallas-broken flag and return True; else return False.
    ``where`` names the stage for the operator log (compile errors raise
    at the dispatch call; JAX async dispatch surfaces runtime failures
    when the result is read)."""
    global _PALLAS_BROKEN
    if not _is_mosaic_error(e):
        return False
    if not _PALLAS_BROKEN:
        _PALLAS_BROKEN = True
        log.warning(
            "pallas failed %s (%s: %s) — falling back to the "
            "XLA program for this process",
            where,
            type(e).__name__,
            str(e)[:200],
        )
    return True


def with_mosaic_fallback(fn, where: str):
    """Call ``fn()``; on a Mosaic/remote-compile failure, mark pallas
    broken process-wide and call it once more (dispatch then selects the
    XLA program).  Non-Mosaic errors propagate.  The shared shape of the
    outage recovery at every simple call site (engine warmup, shard_map,
    benchmark configs); the engine's pipelined collect loop re-dispatches
    per chunk instead and stays bespoke."""
    try:
        return fn()
    except Exception as e:  # noqa: BLE001 — only Mosaic retried
        if not mark_pallas_broken_if_mosaic(e, where=where):
            raise
        return fn()


def _pallas_usable(batch: int) -> bool:
    """The Pallas/Mosaic kernel (pallas_kernel.py) is ~3-6x faster than the
    XLA program but TPU-only and fixed-block: use it when the padded batch
    tiles into its lane blocks and the first device is a TPU.  Platform
    comes from jax.devices()[0] — jax.default_backend() can report a stale
    value under this box's axon shim (VERDICT r3 weak #1)."""
    if _PALLAS_BROKEN:
        return False
    try:
        from .pallas_kernel import BLOCK

        if batch % BLOCK != 0:
            return False
        import jax as _jax

        return getattr(_jax.devices()[0], "platform", "") == "tpu"
    except Exception:
        return False


def _dispatch_prep(prep: PreparedBatch) -> tuple[jnp.ndarray, int]:
    # window_bits is the one mode knob that changes HOST DATA layout
    # (digit row count), not just the traced program: a batch prepped at
    # one width then dispatched after the process-global flipped would
    # run the wrong doubling count over the wrong digits — silently
    # wrong verdicts, no shape error (the window loop takes its trip
    # count from the data, the doubling count from the global).  Not
    # assert: -O must not strip a consensus guard.
    if prep.d1a.shape[0] != windows():
        raise RuntimeError(
            f"PreparedBatch has {prep.d1a.shape[0]} digit rows but the "
            f"active window_bits={_WINDOW_BITS} needs {windows()}: the "
            "window-width mode flipped between prep and dispatch — "
            "re-prepare the batch under the active mode"
        )
    # host->device transfer and kernel enqueue are separate spans so the
    # telemetry section can tell a slow tunnel from a slow program (both
    # are async under JAX dispatch: these time the enqueue, the blocking
    # tail shows up in verify.readback)
    with span("verify.transfer"):
        args = tuple(jnp.asarray(a) for a in prep.device_args)
    if _pallas_usable(args[8].shape[-1]):
        from .pallas_kernel import verify_blocked

        # STATIC program choice from the host-side flags: an ECDSA-only
        # batch (the common real shape) selects the variant with the
        # jacobi/parity acceptance pows pruned at trace time.  The XLA
        # program below gets the same effect at runtime via lax.cond.
        schnorr_free = prep.schnorr_free
        try:
            with span("verify.kernel"):
                return (
                    verify_blocked(*args, schnorr_free=schnorr_free),
                    prep.count,
                )
        except Exception as e:  # noqa: BLE001 — only Mosaic errors handled
            if not mark_pallas_broken_if_mosaic(e, where="at compile"):
                raise
    with span("verify.kernel"):
        return verify_device(*args), prep.count


def dispatch_batch_tpu(
    items: Sequence[tuple[Optional[Point], int, int, int]],
    pad_to: Optional[int] = None,
) -> tuple[jnp.ndarray, int]:
    """Host prep + ASYNC device dispatch: returns (device verdict array,
    item count) without blocking on the result.  JAX dispatch is
    asynchronous, so the caller can prep the next chunk while this one
    computes — the overlap that keeps the device saturated during IBD
    (SURVEY.md §7 hard part 5).  Collect with :func:`collect_verdicts`."""
    with span("verify.prepare"):
        prep = prepare_batch(items, pad_to=pad_to)
    return _dispatch_prep(prep)


def dispatch_batch_tpu_raw(raw, pad_to: Optional[int] = None) -> tuple[jnp.ndarray, int]:
    """:func:`dispatch_batch_tpu` over a packed RawBatch (native-extract
    fast path): same async dispatch, no Python-int round trip."""
    with span("verify.prepare"):
        prep = prepare_batch_raw(raw, pad_to=pad_to)
    return _dispatch_prep(prep)


def collect_verdicts(out: jnp.ndarray, count: int) -> list[bool]:
    """Block on a :func:`dispatch_batch_tpu` result and return verdicts."""
    with span("verify.readback"):
        return [bool(b) for b in np.asarray(out)[:count]]


def verify_batch_tpu(
    items: Sequence[tuple[Optional[Point], int, int, int]],
    pad_to: Optional[int] = None,
) -> list[bool]:
    """End-to-end: host prep + device verify.  Same item shape as the CPU
    engines: (pubkey, z, r, s).  Dispatches to the Pallas kernel on TPU
    (block-aligned batches), else the portable XLA program."""
    if not items:
        return []
    return collect_verdicts(*dispatch_batch_tpu(items, pad_to=pad_to))
