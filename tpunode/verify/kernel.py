"""The TPU batch ECDSA verification kernel.

Verifies B signatures at once: for each signature ``(Q, z, r, s)`` compute
``R = u1*G + u2*Q`` (``u1 = z/s``, ``u2 = r/s`` mod n) and accept iff
``R != O`` and ``x(R) ≡ r (mod n)`` — the capability of libsecp256k1's
``secp256k1_ecdsa_verify`` (SURVEY.md C9), redesigned TPU-first:

* **Host prep** (cheap, Python ints): range checks, pubkey decode, one
  Montgomery batch inversion of every ``s`` in the batch, base-16 window
  digits of ``u1``/``u2``.
* **Device MSM** (the FLOPs): Shamir's trick over 64 interleaved 4-bit
  windows — ``lax.scan`` over windows, each step 4 complete doublings + 2
  complete additions with one-hot table selects (no gathers with
  data-dependent control flow, no recompilation: shapes are static).
  A per-signature 16-entry table of Q multiples is built on device; the G
  table is a compile-time constant.
* **No inversions on device**: the affine check ``x(R) = r`` is done
  projectively as ``X ≡ r_cand * Z (mod p)`` for the (at most two) valid
  candidates ``r`` and ``r + n``.

Everything is exact integer math; results are bit-identical to the CPU
oracle (tested property-style in tests/test_kernel.py).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import field as F
from .curve import B3, INFINITY, make_point, pt_add, pt_double
from .ecdsa_cpu import CURVE_N, CURVE_P, GENERATOR, Point

__all__ = [
    "WINDOWS",
    "WINDOW_BITS",
    "prepare_batch",
    "verify_core",
    "verify_device",
    "verify_batch_tpu",
    "PreparedBatch",
]

WINDOW_BITS = 4
WINDOWS = 64  # 256 / 4

_SEVEN = jnp.array(F.to_limbs(7))


def _g_table_np() -> np.ndarray:
    """Constant table [0*G, 1*G, ..., 15*G] as projective limb points."""
    from .ecdsa_cpu import INFINITY as OINF, point_add

    table = np.zeros((16, 3, F.NLIMBS), dtype=np.int32)
    table[0, 1, 0] = 1  # (0 : 1 : 0)
    acc = OINF
    for k in range(1, 16):
        acc = point_add(acc, GENERATOR)
        table[k, 0] = F.to_limbs(acc.x)
        table[k, 1] = F.to_limbs(acc.y)
        table[k, 2, 0] = 1
    return table


G_TABLE = jnp.array(_g_table_np())  # (16, 3, NLIMBS)


class PreparedBatch:
    """Host-prepared device inputs for one batch of signatures."""

    __slots__ = (
        "u1_digits",
        "u2_digits",
        "qx",
        "qy",
        "r1",
        "r2",
        "r2_valid",
        "host_valid",
        "count",
    )

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


def _batch_inverse_mod_n(values: list[int]) -> list[int]:
    """Montgomery batch inversion mod n: one pow() for the whole batch."""
    prefix = []
    run = 1
    for v in values:
        run = run * v % CURVE_N
        prefix.append(run)
    inv = pow(run, -1, CURVE_N)
    out = [0] * len(values)
    for i in range(len(values) - 1, -1, -1):
        before = prefix[i - 1] if i > 0 else 1
        out[i] = inv * before % CURVE_N
        inv = inv * values[i] % CURVE_N
    return out


def _digits_base16(v: int) -> np.ndarray:
    """64 base-16 digits, most significant first."""
    return np.array(
        [(v >> (WINDOW_BITS * (WINDOWS - 1 - i))) & 0xF for i in range(WINDOWS)],
        dtype=np.int32,
    )


def prepare_batch(
    items: Sequence[tuple[Optional[Point], int, int, int]], pad_to: Optional[int] = None
) -> PreparedBatch:
    """Host-side preparation: (pubkey|None, z, r, s) -> device arrays.

    Invalid-by-inspection entries (bad ranges, missing/infinite pubkey) are
    masked out host-side (``host_valid``); their lanes carry dummy values so
    shapes stay static.  ``pad_to`` pads the batch to a fixed size to avoid
    recompilation across batches.
    """
    count = len(items)
    size = pad_to or count
    assert size >= count
    u1d = np.zeros((size, WINDOWS), dtype=np.int32)
    u2d = np.zeros((size, WINDOWS), dtype=np.int32)
    qx = np.zeros((size, F.NLIMBS), dtype=np.int32)
    qy = np.zeros((size, F.NLIMBS), dtype=np.int32)
    r1 = np.zeros((size, F.NLIMBS), dtype=np.int32)
    r2 = np.zeros((size, F.NLIMBS), dtype=np.int32)
    r2v = np.zeros((size,), dtype=bool)
    hv = np.zeros((size,), dtype=bool)

    s_vals = []
    s_idx = []
    for i, (q, z, r, s) in enumerate(items):
        if q is None or q.infinity:
            continue
        if not (0 < r < CURVE_N and 0 < s < CURVE_N):
            continue
        hv[i] = True
        s_vals.append(s)
        s_idx.append(i)
    s_inv = _batch_inverse_mod_n(s_vals) if s_vals else []
    inv_by_idx = dict(zip(s_idx, s_inv))

    for i, (q, z, r, s) in enumerate(items):
        if not hv[i]:
            continue
        w = inv_by_idx[i]
        u1 = (z % CURVE_N) * w % CURVE_N
        u2 = r * w % CURVE_N
        u1d[i] = _digits_base16(u1)
        u2d[i] = _digits_base16(u2)
        qx[i] = F.to_limbs(q.x)
        qy[i] = F.to_limbs(q.y)
        r1[i] = F.to_limbs(r)
        if r + CURVE_N < CURVE_P:
            r2[i] = F.to_limbs(r + CURVE_N)
            r2v[i] = True

    return PreparedBatch(
        u1_digits=u1d,
        u2_digits=u2d,
        qx=qx,
        qy=qy,
        r1=r1,
        r2=r2,
        r2_valid=r2v,
        host_valid=hv,
        count=count,
    )


def _build_q_table(qx: jnp.ndarray, qy: jnp.ndarray) -> jnp.ndarray:
    """Per-signature table [O, Q, 2Q, ..., 15Q], shape (B, 16, 3, L)."""
    B = qx.shape[0]
    q1 = make_point(qx, qy, jnp.broadcast_to(F.ONE, qx.shape))
    inf = jnp.broadcast_to(INFINITY, q1.shape)

    def step(acc, _):
        nxt = pt_add(acc, q1)
        return nxt, nxt

    _, multiples = lax.scan(step, q1, None, length=14)  # 2Q..15Q, (14, B, 3, L)
    table = jnp.concatenate(
        [inf[None], q1[None], jnp.moveaxis(multiples, 0, 0)], axis=0
    )  # (16, B, 3, L)
    return jnp.moveaxis(table, 0, 1)  # (B, 16, 3, L)


def _select_entry(table: jnp.ndarray, digits: jnp.ndarray) -> jnp.ndarray:
    """One-hot select: table (B, 16, 3, L) or (16, 3, L), digits (B,) -> (B, 3, L)."""
    onehot = jax.nn.one_hot(digits, 16, dtype=jnp.int32)  # (B, 16)
    if table.ndim == 3:
        return jnp.einsum("bt,tcl->bcl", onehot, table)
    return jnp.einsum("bt,btcl->bcl", onehot, table)


def verify_core(
    u1_digits: jnp.ndarray,  # (B, 64) int32, MSB-first base-16
    u2_digits: jnp.ndarray,  # (B, 64)
    qx: jnp.ndarray,  # (B, L)
    qy: jnp.ndarray,  # (B, L)
    r1: jnp.ndarray,  # (B, L)
    r2: jnp.ndarray,  # (B, L)
    r2_valid: jnp.ndarray,  # (B,) bool
    host_valid: jnp.ndarray,  # (B,) bool
) -> jnp.ndarray:
    """The device program (un-jitted: reused by the shard_map multi-chip
    wrapper in multichip.py): returns a (B,) bool validity vector."""
    q_table = _build_q_table(qx, qy)  # (B, 16, 3, L)

    acc0 = jnp.broadcast_to(INFINITY, (qx.shape[0], 3, F.NLIMBS))

    def window_step(acc, digits):
        d1, d2 = digits
        acc = pt_double(pt_double(pt_double(pt_double(acc))))
        acc = pt_add(acc, _select_entry(q_table, d2))
        acc = pt_add(acc, _select_entry(G_TABLE, d1))
        return acc, None

    digit_seq = (
        jnp.moveaxis(u1_digits, 1, 0),  # (64, B)
        jnp.moveaxis(u2_digits, 1, 0),
    )
    acc, _ = lax.scan(window_step, acc0, digit_seq)

    X, Z = acc[..., 0, :], acc[..., 2, :]
    not_inf = ~F.is_zero(Z)
    m1 = F.eq(X, F.mul(r1, Z))
    m2 = F.eq(X, F.mul(r2, Z)) & r2_valid
    # pubkey must satisfy the curve equation: qy^2 = qx^3 + 7
    on_curve = F.eq(F.sqr(qy), F.mul(F.sqr(qx), qx) + _SEVEN)
    return host_valid & on_curve & not_inf & (m1 | m2)


verify_device = jax.jit(verify_core)


def verify_batch_tpu(
    items: Sequence[tuple[Optional[Point], int, int, int]],
    pad_to: Optional[int] = None,
) -> list[bool]:
    """End-to-end: host prep + device verify.  Same item shape as the CPU
    engines: (pubkey, z, r, s)."""
    if not items:
        return []
    prep = prepare_batch(items, pad_to=pad_to)
    out = verify_device(
        jnp.asarray(prep.u1_digits),
        jnp.asarray(prep.u2_digits),
        jnp.asarray(prep.qx),
        jnp.asarray(prep.qy),
        jnp.asarray(prep.r1),
        jnp.asarray(prep.r2),
        jnp.asarray(prep.r2_valid),
        jnp.asarray(prep.host_valid),
    )
    return [bool(b) for b in np.asarray(out)[: prep.count]]
