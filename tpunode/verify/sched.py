"""Lane-packing verify scheduler (ISSUE 10).

The engine used to dispatch FIFO-coalesced submissions: whole payloads
were popped until the fill target was crossed, and a sub-``min_tpu_batch``
remainder was shunted to the CPU rung.  Under many-tenant traffic (Flow's
consensus/compute separation, arXiv:1909.05832: one verify service fed by
many light ingest sources) that wastes device occupancy twice — lanes
dispatch part-empty, and small tails pay a CPU step that the *next*
submission's items could have filled.

This module owns the queue instead:

* **Priority classes** — ``block`` > ``mempool`` > ``ibd`` > ``bulk``.
  Live block-ingest items always pack (and therefore dispatch) ahead of
  mempool relay, which packs ahead of IBD backfill (ISSUE 11: the fetch
  planner's historical blocks must not starve fresh traffic), which packs
  ahead of bulk/re-index traffic.  Within a class, FIFO.
* **Cross-submission packing** — :meth:`LanePacker.pop_lane` slices
  queued payloads so every lane is exactly ``target`` items (the
  compiled device shape) regardless of how the work arrived.  One
  submission may span several lanes; several submissions may share one.
  Per-item futures still resolve exactly once with exactly their items'
  verdicts (verdict conservation — the chaos SOAK invariant).
* **Max-linger deadline** — a lone small submission is dispatched as a
  partial lane once its linger expires; ``min_tpu_batch`` degrades from
  a routing rule to a shed-only floor applied at dispatch time.

The packer is plain data + arithmetic on the event loop; the engine's
pipeline (``VerifyConfig.pipeline_depth``) pulls lanes from it.

Pod scale (ISSUE 13): :class:`FleetDispatcher` promotes the packer into
a cross-host work-stealing dispatcher — one lane queue per mesh host,
fed from the shared packer in global priority order (block > mempool >
ibd > bulk is preserved because lanes are CUT in priority order and
every per-host queue is FIFO), with idle hosts stealing whole packed
lanes from the deepest peer queue.  Steals move the OLDEST lane (queue
head): verification lanes have no cache locality worth protecting, so
unlike classic tail-stealing the head steal strictly improves the
highest-priority lane's latency.  Lane granularity keeps verdict
conservation intact — a stolen or re-queued lane still resolves its
carried submissions exactly once, because a lane lives in exactly one
queue (or exactly one host's in-flight set) at a time and
:class:`Submission` bookkeeping is slice-indexed, not host-indexed.

Host-affine feeds (ISSUE 19): at pod scale the single shared packer is
the feed bottleneck — every tx funnels through one queue before a lane
ships to the host that verifies it.  :class:`AffinityMap` gives every
submission key a stable home host via rendezvous (highest-random-weight)
hashing: removing a host remaps ONLY that host's keys, and a rejoin
restores exactly the old placement, so a rebalance never re-shuffles
the steady state.  :class:`FleetDispatcher` grows one
:class:`LanePacker` PER HOST fed by :meth:`FleetDispatcher.push`;
lanes are cut per-host but in GLOBAL priority order (the feed loop
compares per-packer head classes before cutting), and head-steal stays
as the anti-starvation fallback — affinity is a placement hint, never
a starvation source.

Telemetry: ``sched.queue_depth{priority=}`` gauges, the
``sched.pack_efficiency`` histogram (lane occupancy at dispatch),
``sched.lanes`` / ``sched.packed_submissions`` counters, and the fleet
surface — ``sched.host_depth{host=}`` gauges, ``sched.steals`` /
``sched.requeued`` counters, ``sched.steal`` events, plus the affine
feed surface: ``sched.affinity_routed{host=}`` / ``sched.affinity_spilled``
counters and ``sched.feed_idle{host=}`` gauges (queue-idle fraction —
the per-host feed-starvation metric; OBSERVABILITY.md).
"""

from __future__ import annotations

import asyncio
import collections
import hashlib
import time
from typing import Optional, Sequence

from ..events import events
from ..metrics import metrics

__all__ = [
    "OCCUPANCY_BUCKETS",
    "PRIORITIES",
    "affinity_key",
    "host_names",
    "AffinityMap",
    "Submission",
    "PackedLane",
    "LanePacker",
    "FleetDispatcher",
]

# Dispatch order under saturation: live block ingest outranks mempool
# relay, which outranks IBD backfill (planner-fetched historical blocks,
# ISSUE 11 — a syncing node keeps serving fresh verdicts first), which
# outranks bulk (API default / re-index) traffic.
PRIORITIES = ("block", "mempool", "ibd", "bulk")

# Linear occupancy buckets (0.05 steps): lane occupancy lives in [0, 1],
# which the duration-shaped default bounds would quantize uselessly.
OCCUPANCY_BUCKETS = tuple(i / 20 for i in range(1, 21))

metrics.describe(
    "node.verdict_latency",
    "submit->verdict-publish latency per priority class (seconds)",
)


def slice_payload(payload, lo: int, hi: int):
    """A view/copy of ``payload[lo:hi]`` in dispatchable form: list
    payloads slice natively, raw-batch payloads through
    :func:`raw.as_raw_batch` (numpy views, no copies)."""
    if lo == 0 and hi >= len(payload):
        return payload
    if isinstance(payload, list):
        return payload[lo:hi]
    from .raw import as_raw_batch

    return as_raw_batch(payload).slice(lo, hi)


_MASK64 = (1 << 64) - 1


def _mix64(x: int) -> int:
    """splitmix64 finalizer: a cheap, well-distributed 64-bit mixer —
    rendezvous hashing only needs per-(key, host) scores that are
    independent across hosts, not cryptographic strength."""
    x &= _MASK64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK64
    return x ^ (x >> 31)


def affinity_key(txid: bytes) -> int:
    """The affinity key for a txid / block hash: its first 8 bytes as a
    little-endian integer.  Hash digests are already uniform, so no
    extra mixing is needed here — :class:`AffinityMap` mixes the key
    against each host's seed anyway."""
    return int.from_bytes(txid[:8], "little")


def host_names(n: int) -> list:
    """Canonical fleet host names (``h0`` .. ``h{n-1}``).  Owned HERE —
    next to :class:`AffinityMap`, which seeds per-host rendezvous
    scores from these strings: a renamed host is a re-shuffled steady
    state, so the engine fleet, the topology module, the bench proxy,
    and the timeline's host-series parsing must agree on one naming
    scheme.  Jax-free on purpose (multichip re-exports it): the
    analyzer's label-cardinality rule allowlists this as the bounded
    source for ``host=`` label values, so jax-free workers must be able
    to import it too."""
    return [f"h{i}" for i in range(n)]


class AffinityMap:
    """Stable key→host placement via rendezvous (HRW) hashing.

    Every ``(key, host)`` pair gets an independent score
    ``_mix64(key ^ seed(host))``; a key's home is the highest-scoring
    host.  The property ISSUE 19 needs falls out directly: removing a
    host remaps ONLY the keys that host owned (every other key's argmax
    is unchanged), and re-adding it restores exactly the old placement —
    a shrink/rejoin cycle never re-shuffles the steady state, unlike
    modulo placement where every key moves.

    Pure arithmetic, no mutable state beyond the fixed seed table:
    safe to call from any thread.
    """

    def __init__(self, hosts: Sequence[str]):
        hosts = list(hosts)
        if not hosts:
            raise ValueError("AffinityMap needs at least one host")
        self.hosts = hosts
        self._seed = {
            h: _mix64(
                int.from_bytes(
                    hashlib.blake2b(h.encode(), digest_size=8).digest(),
                    "big",
                )
            )
            for h in hosts
        }

    def prefer(self, key: int) -> str:
        """The key's home host over the FULL host set (ignores health —
        the steady-state placement a rejoin restores)."""
        return self._argmax(key, self.hosts)

    def route(self, key: int, active: Sequence[str]) -> Optional[str]:
        """The key's home host over ``active`` — the live routing
        decision.  None when no host is active (dark fleet: the caller
        falls back to the central path)."""
        if not active:
            return None
        return self._argmax(key, active)

    def _argmax(self, key: int, hosts: Sequence[str]) -> str:
        key &= _MASK64
        best = None
        best_score = -1
        for h in hosts:
            score = _mix64(key ^ self._seed[h])
            if score > best_score:
                best, best_score = h, score
        return best


class Submission:
    """One queued verify request: a payload plus the future its caller
    awaits.  ``results`` fills in slices as the lanes carrying this
    submission complete (in any order); the future resolves when the
    last slice lands, or fails on the FIRST lane failure (later slices
    of a failed submission are delivered into a dead buffer)."""

    __slots__ = (
        "payload", "n", "fut", "act", "priority", "enqueued",
        "taken", "results", "remaining", "failed", "affinity", "tenant",
    )

    def __init__(
        self,
        payload,
        fut: asyncio.Future,
        act: Optional[tuple],
        priority: str,
        enqueued: Optional[float] = None,
        affinity: Optional[int] = None,
        tenant: Optional[str] = None,
    ):
        if priority not in PRIORITIES:
            raise ValueError(
                f"unknown priority {priority!r}: one of {PRIORITIES}"
            )
        self.payload = payload
        self.n = len(payload)
        self.fut = fut
        self.act = act
        self.priority = priority
        self.affinity = affinity
        # serve-layer attribution (ISSUE 20): the registered tenant this
        # submission bills to, None for the node's own traffic
        self.tenant = tenant
        self.enqueued = time.monotonic() if enqueued is None else enqueued
        self.taken = 0  # items already claimed into lanes
        self.results: list = [None] * self.n
        self.remaining = self.n
        self.failed = False

    def deliver(self, lo: int, verdicts: Sequence[bool]) -> None:
        """Fill ``results[lo:lo+len(verdicts)]``; resolve the future when
        the submission is complete.  Idempotent against a prior failure."""
        self.results[lo : lo + len(verdicts)] = verdicts
        self.remaining -= len(verdicts)
        if self.remaining <= 0 and not self.failed and not self.fut.done():
            # Per-class e2e latency (ISSUE 17): admission stamp -> last
            # slice delivered.  Observed HERE — submission-side, not
            # lane-side — so packed/sliced/stolen/requeued lanes still
            # attribute the latency to the originating priority class.
            metrics.observe(
                "node.verdict_latency",
                time.monotonic() - self.enqueued,
                labels={"priority": self.priority},
            )
            self.fut.set_result(self.results)

    def fail(self, exc: BaseException) -> None:
        """A lane carrying part of this submission failed on every rung:
        the whole submission's waiter learns it (partial verdict lists
        are never surfaced — all-or-nothing per submission)."""
        self.failed = True
        if not self.fut.done():
            self.fut.set_exception(exc)


class PackedLane:
    """One dispatchable lane: ``(submission, lo, hi)`` slices summing to
    ``total`` items (≤ the pack target).  ``requeues`` counts fleet
    re-queues after a host loss (ISSUE 13) — the engine bounds it so a
    lane bouncing between dying hosts eventually falls through the
    local ladder instead of orbiting forever."""

    __slots__ = ("slices", "total", "target", "requeues")

    def __init__(
        self, slices: list[tuple[Submission, int, int]], target: int
    ):
        self.slices = slices
        self.total = sum(hi - lo for _, lo, hi in slices)
        self.target = target
        self.requeues = 0

    @property
    def occupancy(self) -> float:
        return self.total / self.target if self.target else 1.0

    @property
    def act0(self) -> Optional[tuple]:
        """First traced submitter's trace position — the tree the
        dispatch-phase spans are recorded into (exact for the
        one-block-per-lane common case)."""
        for sub, _, _ in self.slices:
            if sub.act is not None:
                return sub.act
        return None

    def payloads(self) -> list:
        """Sliced payloads in lane order (what the dispatch rungs run)."""
        return [
            slice_payload(sub.payload, lo, hi) for sub, lo, hi in self.slices
        ]

    def class_counts(self) -> dict[str, int]:
        """Items per priority class carried by this lane — the cost
        ledger's attribution input (ISSUE 17): the engine pro-rates the
        lane's wall-clock rung time across these counts."""
        out: dict[str, int] = {}
        for sub, lo, hi in self.slices:
            out[sub.priority] = out.get(sub.priority, 0) + (hi - lo)
        return out

    def tenant_counts(self) -> dict[str, int]:
        """Items per serve-layer tenant carried by this lane (ISSUE 20)
        — empty for pure node traffic, so the ledger's tenant table only
        exists when the serve subsystem is live."""
        out: dict[str, int] = {}
        for sub, lo, hi in self.slices:
            if sub.tenant is not None:
                out[sub.tenant] = out.get(sub.tenant, 0) + (hi - lo)
        return out


class LanePacker:
    """Priority-binned submission queue with cross-boundary lane packing.

    Not thread-safe by design: every method runs on the event loop (the
    engine's queue loop and ``_enqueue``).

    ``gauge=False`` silences the ``sched.queue_depth{priority=}``
    gauges: the fleet's per-host packers (ISSUE 19) would otherwise
    last-writer-win the same gauge keys as the central packer.  The
    counters/histogram stay on — they are process totals and sum
    correctly across packers.
    """

    def __init__(self, gauge: bool = True):
        self._gauge_on = gauge
        self._q: dict[str, collections.deque[Submission]] = {
            p: collections.deque() for p in PRIORITIES
        }
        # Running unclaimed-item counts (global + per priority): push and
        # pop_lane maintain them in O(1) — recomputing by summing the
        # deque would make a burst of n enqueues O(n^2) on the event
        # loop (review finding).
        self._pending_items = 0
        self._depth: dict[str, int] = {p: 0 for p in PRIORITIES}

    # -- intake ---------------------------------------------------------------

    def push(self, sub: Submission) -> None:
        # Unclaimed remainder, not sub.n: a host deactivation re-routes
        # its packer's queue through push(), and a partially-claimed
        # submission must not inflate the depth by items already cut
        # into lanes (ISSUE 19).
        rem = sub.n - sub.taken
        self._q[sub.priority].append(sub)
        self._pending_items += rem
        self._depth[sub.priority] += rem
        if self._gauge_on:
            metrics.set_gauge(
                "sched.queue_depth",
                float(self._depth[sub.priority]),
                labels={"priority": sub.priority},
            )

    # -- introspection --------------------------------------------------------

    def pending(self) -> int:
        """Unclaimed items across every priority class."""
        return self._pending_items

    def batches(self) -> int:
        return sum(len(q) for q in self._q.values())

    def depths(self) -> dict[str, int]:
        """Unclaimed items per priority (stats/debug endpoints)."""
        return dict(self._depth)

    def oldest_enqueued(self) -> Optional[float]:
        """Enqueue time of the oldest queued submission (any class) —
        the linger deadline anchors on it so a lone low-priority
        submission still dispatches promptly."""
        heads = [q[0].enqueued for q in self._q.values() if q]
        return min(heads) if heads else None

    def head_class(self) -> Optional[int]:
        """Index into PRIORITIES of the highest class with unclaimed
        items (None when empty) — the fleet feed loop compares per-host
        packers by this before cutting, so per-host packing preserves
        GLOBAL priority order (ISSUE 19)."""
        for i, p in enumerate(PRIORITIES):
            if self._depth[p] > 0:
                return i
        return None

    # -- packing --------------------------------------------------------------

    def pop_lane(self, target: int) -> Optional[PackedLane]:
        """Claim up to ``target`` items into one lane, draining priority
        classes in order and slicing across submission boundaries.
        Returns None when the queue is empty."""
        slices: list[tuple[Submission, int, int]] = []
        room = target
        for p in PRIORITIES:
            q = self._q[p]
            while q and room > 0:
                sub = q[0]
                if sub.failed:
                    # an earlier lane already failed this submission's
                    # waiter: dispatching its remainder would burn whole
                    # device lanes on verdicts nobody can observe
                    rem = sub.n - sub.taken
                    sub.taken = sub.n
                    self._pending_items -= rem
                    self._depth[p] -= rem
                    metrics.inc("sched.failed_skipped", rem)
                    q.popleft()
                    continue
                take = min(room, sub.n - sub.taken)
                slices.append((sub, sub.taken, sub.taken + take))
                sub.taken += take
                room -= take
                self._pending_items -= take
                self._depth[p] -= take
                if sub.taken >= sub.n:
                    q.popleft()
            if self._gauge_on:
                metrics.set_gauge(
                    "sched.queue_depth",
                    float(self._depth[p]),
                    labels={"priority": p},
                )
            if room <= 0:
                break
        if not slices:
            return None
        lane = PackedLane(slices, target)
        metrics.inc("sched.lanes")
        metrics.inc("sched.packed_submissions", len(slices))
        metrics.observe(
            "sched.pack_efficiency", lane.occupancy, buckets=OCCUPANCY_BUCKETS
        )
        return lane

    # -- shutdown -------------------------------------------------------------

    def drain(self) -> list[Submission]:
        """Remove and return every queued submission (engine teardown:
        their futures are cancelled by the caller).  Partially-claimed
        submissions are included — their in-flight slices resolve or
        fail through the lane that claimed them."""
        out: list[Submission] = []
        for p, q in self._q.items():
            out.extend(q)
            q.clear()
            self._depth[p] = 0
            if self._gauge_on:
                metrics.set_gauge(
                    "sched.queue_depth", 0.0, labels={"priority": p}
                )
        self._pending_items = 0
        return out


class FleetDispatcher:
    """Cross-host work-stealing lane dispatcher (ISSUE 13).

    One FIFO lane queue per mesh host, fed from a shared
    :class:`LanePacker` in global priority order; idle hosts steal the
    OLDEST lane from the deepest peer queue.  Lane granularity preserves
    verdict conservation: a lane lives in exactly one queue at a time,
    so a steal or a host-loss re-queue moves the whole resolution
    responsibility with it — its carried submissions still resolve
    exactly once.

    Host health is the ENGINE's business (per-host circuit breakers,
    canary re-probes); this class only tracks the active set so
    assignment and re-queueing skip lost hosts.  Not thread-safe by
    design: every method runs on the event loop, like the packer.
    """

    def __init__(
        self,
        hosts,
        packer: Optional[LanePacker] = None,
        max_queue: int = 2,
    ):
        hosts = list(hosts)
        if not hosts:
            raise ValueError("FleetDispatcher needs at least one host")
        if len(set(hosts)) != len(hosts):
            raise ValueError(f"duplicate host names: {hosts}")
        self.hosts = hosts
        self.packer = packer if packer is not None else LanePacker()
        self.max_queue = max(1, max_queue)
        self._queues: dict = {h: collections.deque() for h in hosts}
        self._active: dict = {h: True for h in hosts}
        self.steals = 0
        self.requeued = 0
        # per-thief steal totals: the fleet timeline's per-host steal
        # series (tpunode/timeseries.py) — bounded by the fixed host set
        self.host_steals: dict = {h: 0 for h in hosts}
        # Host-affine feeds (ISSUE 19): one packer per host, routed by
        # rendezvous hashing.  The shared self.packer stays as the
        # central path for affinity-less submissions and the dark-fleet
        # fallback; per-host packers run gauge-silenced so they don't
        # stomp the central sched.queue_depth series.
        self.affinity = AffinityMap(hosts)
        self._packers: dict = {h: LanePacker(gauge=False) for h in hosts}
        self.affinity_routed = 0
        self.affinity_spilled = 0
        # feed starvation: take attempts that found the host's own
        # queue dry, over all take attempts — the queue-idle fraction
        self._takes: dict = {h: 0 for h in hosts}
        self._idle_takes: dict = {h: 0 for h in hosts}

    # -- intake ---------------------------------------------------------------

    def push(self, sub: Submission) -> None:
        """Route a submission to its packer.  Affinity-keyed work goes
        to its home host's packer over the ACTIVE set — a lost host's
        keys spill to their rendezvous runner-up (counted as a spill),
        and a rejoin restores the steady-state placement for new work.
        Affinity-less submissions and dark-fleet traffic take the
        central packer."""
        if sub.affinity is None:
            self.packer.push(sub)
            return
        host = self.affinity.route(sub.affinity, self.active_hosts())
        if host is None:
            self.packer.push(sub)
            return
        self._packers[host].push(sub)
        if host == self.affinity.prefer(sub.affinity):
            self.affinity_routed += 1
            metrics.inc("sched.affinity_routed", labels={"host": host})
        else:
            self.affinity_spilled += 1
            metrics.inc("sched.affinity_spilled")

    # -- introspection --------------------------------------------------------

    def is_active(self, host: str) -> bool:
        return self._active[host]

    def active_hosts(self) -> list:
        return [h for h in self.hosts if self._active[h]]

    def host_depth(self, host: str) -> int:
        """Queued ITEMS on one host (the steal victim metric)."""
        return sum(lane.total for lane in self._queues[host])

    def host_lanes(self, host: str) -> int:
        return len(self._queues[host])

    def host_depths(self) -> dict:
        return {h: self.host_depth(h) for h in self.hosts}

    def queued_lanes(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def uncut_pending(self) -> int:
        """Unclaimed items across the central AND every per-host packer
        (what the engine's linger loop measures)."""
        return self.packer.pending() + sum(
            p.pending() for p in self._packers.values()
        )

    def pending(self) -> int:
        """Unclaimed packer items + items already cut into host lanes."""
        return self.uncut_pending() + sum(
            lane.total for q in self._queues.values() for lane in q
        )

    def batches(self) -> int:
        return self.packer.batches() + sum(
            p.batches() for p in self._packers.values()
        )

    def depths(self) -> dict[str, int]:
        """Unclaimed items per priority, summed over every packer."""
        out = self.packer.depths()
        for p in self._packers.values():
            for k, v in p.depths().items():
                out[k] += v
        return out

    def oldest_enqueued(self) -> Optional[float]:
        heads = [self.packer.oldest_enqueued()] + [
            p.oldest_enqueued() for p in self._packers.values()
        ]
        heads = [h for h in heads if h is not None]
        return min(heads) if heads else None

    def feed_depth(self, host: str) -> int:
        """Uncut items homed to ``host`` plus items already cut into
        its queue — the per-host backpressure signal (ISSUE 19):
        node/mempool intake gates on the TARGET host's feed depth, not
        a global counter, so one slow host can't stall fleet intake."""
        return self._packers[host].pending() + self.host_depth(host)

    def feed_depths(self) -> dict:
        return {h: self.feed_depth(h) for h in self.hosts}

    def feed_idle(self) -> dict:
        """Per-host queue-idle fraction of take attempts (the feed
        starvation metric: 0.0 = always fed, → 1.0 = starved)."""
        return {
            h: (self._idle_takes[h] / self._takes[h])
            if self._takes[h]
            else 0.0
            for h in self.hosts
        }

    def has_room(self) -> bool:
        """May the scheduler cut + assign another lane?  (Backpressure:
        keeping assignment shallow lets late high-priority submissions
        pack ahead of work that hasn't been cut into lanes yet.)"""
        return any(
            self._active[h] and len(self._queues[h]) < self.max_queue
            for h in self.hosts
        )

    def feedable(self) -> bool:
        """Is there a lane the feed loop could cut + place right now?
        True when an active host with queue room has a nonempty home
        packer, or the central packer has work and any active queue has
        room."""
        central = self.packer.pending() > 0
        for h in self.hosts:
            if not self._active[h]:
                continue
            if len(self._queues[h]) >= self.max_queue:
                continue
            if central or self._packers[h].pending() > 0:
                return True
        return False

    def _gauge(self, host: str) -> None:
        metrics.set_gauge(
            "sched.host_depth",
            float(self.host_depth(host)),
            labels={"host": host},
        )

    # -- assignment / consumption ---------------------------------------------

    def _shallowest(
        self, exclude: Optional[str] = None, respect_cap: bool = False
    ) -> Optional[str]:
        """The shallowest-by-items ACTIVE host (ties -> first in host
        order), optionally excluding one host and/or skipping queues at
        ``max_queue`` — the one selection policy behind assignment AND
        re-queueing (review r13: two hand-rolled copies would fork)."""
        best = None
        for h in self.hosts:
            if h == exclude or not self._active[h]:
                continue
            if respect_cap and len(self._queues[h]) >= self.max_queue:
                continue
            if best is None or self.host_depth(h) < self.host_depth(best):
                best = h
        return best

    def assign(self, lane: PackedLane) -> Optional[str]:
        """Queue ``lane`` on the shallowest active host with room; None
        when every active queue is full (caller waits) or no host is
        active (caller must dispatch locally — traffic never stops)."""
        best = self._shallowest(respect_cap=True)
        if best is None:
            return None
        self._queues[best].append(lane)
        self._gauge(best)
        return best

    def cut_next(
        self, target: int
    ) -> tuple[Optional[PackedLane], Optional[str]]:
        """Cut the globally most-urgent feedable lane and place it.

        Candidate sources: each active host's home packer (the lane
        lands on that host's OWN queue — host-local feed, no cross-host
        placement decision) and the central packer (the lane lands on
        the shallowest active queue).  The winner is the source whose
        head is highest-class, ties broken by oldest enqueue — per-host
        packing thus preserves the GLOBAL block > mempool > ibd > bulk
        order (ISSUE 19).  Returns ``(lane, host)``; ``(None, None)``
        when nothing was cut; ``(lane, None)`` when a central lane was
        cut but no queue had room (caller dispatches it locally —
        traffic never stops)."""
        best_key = None
        best_host: Optional[str] = None
        for h in self.hosts:
            if not self._active[h]:
                continue
            if len(self._queues[h]) >= self.max_queue:
                continue
            cls = self._packers[h].head_class()
            if cls is None:
                continue
            key = (cls, self._packers[h].oldest_enqueued() or 0.0)
            if best_key is None or key < best_key:
                best_key, best_host = key, h
        central_cls = self.packer.head_class()
        if central_cls is not None and self.has_room():
            key = (central_cls, self.packer.oldest_enqueued() or 0.0)
            if best_key is None or key < best_key:
                best_key, best_host = key, None
        if best_key is None:
            return None, None
        if best_host is not None:
            lane = self._packers[best_host].pop_lane(target)
            if lane is None:  # only failed-submission residue queued
                return None, None
            self._queues[best_host].append(lane)
            self._gauge(best_host)
            return lane, best_host
        lane = self.packer.pop_lane(target)
        if lane is None:
            return None, None
        return lane, self.assign(lane)

    def pop_any(self, target: int) -> Optional[PackedLane]:
        """Cut a lane from ANY packer, priority-first (dark fleet: the
        engine's local-CPU fallback drains the affine packers too, so
        affinity never strands work when every host is down)."""
        best_key = None
        best_packer = None
        for p in (self.packer, *self._packers.values()):
            cls = p.head_class()
            if cls is None:
                continue
            key = (cls, p.oldest_enqueued() or 0.0)
            if best_key is None or key < best_key:
                best_key, best_packer = key, p
        if best_packer is None:
            return None
        return best_packer.pop_lane(target)

    def take(self, host: str, steal: bool = True) -> Optional[PackedLane]:
        """Next lane for ``host``: its own queue head, else (``steal``)
        the OLDEST lane of the deepest peer queue.  The deque pop is the
        atomic hand-off — once taken, no other host can reach this lane."""
        q = self._queues[host]
        # Feed starvation accounting (ISSUE 19): a take that finds the
        # host's own queue dry is a feed miss, counted BEFORE stealing —
        # a steal hides compute starvation but not feed starvation.
        self._takes[host] += 1
        if not q:
            self._idle_takes[host] += 1
        metrics.set_gauge(
            "sched.feed_idle",
            self._idle_takes[host] / self._takes[host],
            labels={"host": host},
        )
        if q:
            lane = q.popleft()
            self._gauge(host)
            return lane
        if not steal:
            return None
        return self._steal_for(host)

    def _steal_for(self, thief: str) -> Optional[PackedLane]:
        # Deepest queue by ITEMS, scanned over every host (a lost host's
        # orphaned lanes are legitimate loot too).  Head steal: lanes
        # were cut in global priority order, so the victim's oldest lane
        # is the whole fleet's most urgent queued work.
        victim = None
        depth = 0
        for h in self.hosts:
            if h == thief or not self._queues[h]:
                continue
            d = self.host_depth(h)
            if d > depth:
                victim, depth = h, d
        if victim is None:
            return None
        lane = self._queues[victim].popleft()
        self.steals += 1
        self.host_steals[thief] += 1
        metrics.inc("sched.steals")
        metrics.inc("sched.host_steals", labels={"host": thief})
        events.emit(
            "sched.steal", thief=thief, victim=victim, items=lane.total,
        )
        self._gauge(victim)
        return lane

    # -- degradation (ISSUE 13: one sick host degrades alone) -----------------

    def requeue(self, host: str, lane: PackedLane) -> Optional[str]:
        """Give a lost host's IN-FLIGHT lane to a peer (FRONT of the
        shallowest active queue — it is older than anything queued).
        Returns the host it landed on, or None WITHOUT queueing (and
        without counting — review r13: a refused requeue placed
        nothing) when no peer is active: ownership stays with the
        caller, which must resolve the lane itself (queueing it here
        too would leave two live copies — the double-resolution hazard
        the ISSUE 13 requeue audit exists to rule out).  Only THESE
        in-flight bounces consume ``lane.requeues`` (the engine's orbit
        bound); queued-lane redistribution at deactivation does not."""
        best = self._shallowest(exclude=host)
        if best is None:
            return None
        lane.requeues += 1
        self.requeued += 1
        metrics.inc("sched.requeued")
        self._queues[best].appendleft(lane)
        self._gauge(best)
        return best

    def deactivate(self, host: str) -> int:
        """Mark ``host`` lost and redistribute its queued lanes to the
        active peers (order preserved, each to the FRONT of the
        shallowest peer — they are older than anything queued; with no
        active peer they stay put for steals / the engine's local
        fallback).  A redistribution is NOT an in-flight bounce: it
        counts in ``sched.requeued`` telemetry but never consumes
        ``lane.requeues`` — a lane that merely sat queued on dying
        hosts must arrive at its first real dispatch with its full
        orbit budget (review r13).  Returns how many lanes moved.
        Idempotent."""
        if not self._active[host]:
            return 0
        self._active[host] = False
        moved = 0
        lanes = list(self._queues[host])
        self._queues[host].clear()
        self._gauge(host)
        for lane in reversed(lanes):
            target = self._shallowest(exclude=host)
            if target is None:
                self._queues[host].appendleft(lane)
                continue
            self._queues[target].appendleft(lane)
            self._gauge(target)
            self.requeued += 1
            metrics.inc("sched.requeued")
            moved += 1
        self._gauge(host)
        # Re-route the lost host's UNCUT feed through push(): rendezvous
        # re-homes each key over the remaining active set (counted as
        # spills), affinity-less work falls back to the central packer.
        # Runs after the active flag flipped so route() skips this host;
        # push()'s remainder accounting keeps partially-claimed
        # submissions' depths truthful.
        for sub in self._packers[host].drain():
            self.push(sub)
        return moved

    def activate(self, host: str) -> None:
        self._active[host] = True

    # -- shutdown -------------------------------------------------------------

    def drain_lanes(self) -> list[PackedLane]:
        """Remove and return every queued lane (engine teardown: the
        caller cancels their carried futures)."""
        out: list[PackedLane] = []
        for h, q in self._queues.items():
            out.extend(q)
            q.clear()
            self._gauge(h)
        return out

    def drain_submissions(self) -> list[Submission]:
        """Remove and return every queued submission across the central
        and per-host packers (engine teardown: the caller cancels their
        futures)."""
        out = self.packer.drain()
        for p in self._packers.values():
            out.extend(p.drain())
        return out
