"""Lane-packing verify scheduler (ISSUE 10).

The engine used to dispatch FIFO-coalesced submissions: whole payloads
were popped until the fill target was crossed, and a sub-``min_tpu_batch``
remainder was shunted to the CPU rung.  Under many-tenant traffic (Flow's
consensus/compute separation, arXiv:1909.05832: one verify service fed by
many light ingest sources) that wastes device occupancy twice — lanes
dispatch part-empty, and small tails pay a CPU step that the *next*
submission's items could have filled.

This module owns the queue instead:

* **Priority classes** — ``block`` > ``mempool`` > ``ibd`` > ``bulk``.
  Live block-ingest items always pack (and therefore dispatch) ahead of
  mempool relay, which packs ahead of IBD backfill (ISSUE 11: the fetch
  planner's historical blocks must not starve fresh traffic), which packs
  ahead of bulk/re-index traffic.  Within a class, FIFO.
* **Cross-submission packing** — :meth:`LanePacker.pop_lane` slices
  queued payloads so every lane is exactly ``target`` items (the
  compiled device shape) regardless of how the work arrived.  One
  submission may span several lanes; several submissions may share one.
  Per-item futures still resolve exactly once with exactly their items'
  verdicts (verdict conservation — the chaos SOAK invariant).
* **Max-linger deadline** — a lone small submission is dispatched as a
  partial lane once its linger expires; ``min_tpu_batch`` degrades from
  a routing rule to a shed-only floor applied at dispatch time.

The packer is plain data + arithmetic on the event loop; the engine's
pipeline (``VerifyConfig.pipeline_depth``) pulls lanes from it.

Telemetry: ``sched.queue_depth{priority=}`` gauges, the
``sched.pack_efficiency`` histogram (lane occupancy at dispatch), and
``sched.lanes`` / ``sched.packed_submissions`` counters
(OBSERVABILITY.md).
"""

from __future__ import annotations

import asyncio
import collections
import time
from typing import Optional, Sequence

from ..metrics import metrics

__all__ = [
    "OCCUPANCY_BUCKETS",
    "PRIORITIES",
    "Submission",
    "PackedLane",
    "LanePacker",
]

# Dispatch order under saturation: live block ingest outranks mempool
# relay, which outranks IBD backfill (planner-fetched historical blocks,
# ISSUE 11 — a syncing node keeps serving fresh verdicts first), which
# outranks bulk (API default / re-index) traffic.
PRIORITIES = ("block", "mempool", "ibd", "bulk")

# Linear occupancy buckets (0.05 steps): lane occupancy lives in [0, 1],
# which the duration-shaped default bounds would quantize uselessly.
OCCUPANCY_BUCKETS = tuple(i / 20 for i in range(1, 21))


def slice_payload(payload, lo: int, hi: int):
    """A view/copy of ``payload[lo:hi]`` in dispatchable form: list
    payloads slice natively, raw-batch payloads through
    :func:`raw.as_raw_batch` (numpy views, no copies)."""
    if lo == 0 and hi >= len(payload):
        return payload
    if isinstance(payload, list):
        return payload[lo:hi]
    from .raw import as_raw_batch

    return as_raw_batch(payload).slice(lo, hi)


class Submission:
    """One queued verify request: a payload plus the future its caller
    awaits.  ``results`` fills in slices as the lanes carrying this
    submission complete (in any order); the future resolves when the
    last slice lands, or fails on the FIRST lane failure (later slices
    of a failed submission are delivered into a dead buffer)."""

    __slots__ = (
        "payload", "n", "fut", "act", "priority", "enqueued",
        "taken", "results", "remaining", "failed",
    )

    def __init__(
        self,
        payload,
        fut: asyncio.Future,
        act: Optional[tuple],
        priority: str,
        enqueued: Optional[float] = None,
    ):
        if priority not in PRIORITIES:
            raise ValueError(
                f"unknown priority {priority!r}: one of {PRIORITIES}"
            )
        self.payload = payload
        self.n = len(payload)
        self.fut = fut
        self.act = act
        self.priority = priority
        self.enqueued = time.monotonic() if enqueued is None else enqueued
        self.taken = 0  # items already claimed into lanes
        self.results: list = [None] * self.n
        self.remaining = self.n
        self.failed = False

    def deliver(self, lo: int, verdicts: Sequence[bool]) -> None:
        """Fill ``results[lo:lo+len(verdicts)]``; resolve the future when
        the submission is complete.  Idempotent against a prior failure."""
        self.results[lo : lo + len(verdicts)] = verdicts
        self.remaining -= len(verdicts)
        if self.remaining <= 0 and not self.failed and not self.fut.done():
            self.fut.set_result(self.results)

    def fail(self, exc: BaseException) -> None:
        """A lane carrying part of this submission failed on every rung:
        the whole submission's waiter learns it (partial verdict lists
        are never surfaced — all-or-nothing per submission)."""
        self.failed = True
        if not self.fut.done():
            self.fut.set_exception(exc)


class PackedLane:
    """One dispatchable lane: ``(submission, lo, hi)`` slices summing to
    ``total`` items (≤ the pack target)."""

    __slots__ = ("slices", "total", "target")

    def __init__(
        self, slices: list[tuple[Submission, int, int]], target: int
    ):
        self.slices = slices
        self.total = sum(hi - lo for _, lo, hi in slices)
        self.target = target

    @property
    def occupancy(self) -> float:
        return self.total / self.target if self.target else 1.0

    @property
    def act0(self) -> Optional[tuple]:
        """First traced submitter's trace position — the tree the
        dispatch-phase spans are recorded into (exact for the
        one-block-per-lane common case)."""
        for sub, _, _ in self.slices:
            if sub.act is not None:
                return sub.act
        return None

    def payloads(self) -> list:
        """Sliced payloads in lane order (what the dispatch rungs run)."""
        return [
            slice_payload(sub.payload, lo, hi) for sub, lo, hi in self.slices
        ]


class LanePacker:
    """Priority-binned submission queue with cross-boundary lane packing.

    Not thread-safe by design: every method runs on the event loop (the
    engine's queue loop and ``_enqueue``).
    """

    def __init__(self):
        self._q: dict[str, collections.deque[Submission]] = {
            p: collections.deque() for p in PRIORITIES
        }
        # Running unclaimed-item counts (global + per priority): push and
        # pop_lane maintain them in O(1) — recomputing by summing the
        # deque would make a burst of n enqueues O(n^2) on the event
        # loop (review finding).
        self._pending_items = 0
        self._depth: dict[str, int] = {p: 0 for p in PRIORITIES}

    # -- intake ---------------------------------------------------------------

    def push(self, sub: Submission) -> None:
        self._q[sub.priority].append(sub)
        self._pending_items += sub.n
        self._depth[sub.priority] += sub.n
        metrics.set_gauge(
            "sched.queue_depth",
            float(self._depth[sub.priority]),
            labels={"priority": sub.priority},
        )

    # -- introspection --------------------------------------------------------

    def pending(self) -> int:
        """Unclaimed items across every priority class."""
        return self._pending_items

    def batches(self) -> int:
        return sum(len(q) for q in self._q.values())

    def depths(self) -> dict[str, int]:
        """Unclaimed items per priority (stats/debug endpoints)."""
        return dict(self._depth)

    def oldest_enqueued(self) -> Optional[float]:
        """Enqueue time of the oldest queued submission (any class) —
        the linger deadline anchors on it so a lone low-priority
        submission still dispatches promptly."""
        heads = [q[0].enqueued for q in self._q.values() if q]
        return min(heads) if heads else None

    # -- packing --------------------------------------------------------------

    def pop_lane(self, target: int) -> Optional[PackedLane]:
        """Claim up to ``target`` items into one lane, draining priority
        classes in order and slicing across submission boundaries.
        Returns None when the queue is empty."""
        slices: list[tuple[Submission, int, int]] = []
        room = target
        for p in PRIORITIES:
            q = self._q[p]
            while q and room > 0:
                sub = q[0]
                if sub.failed:
                    # an earlier lane already failed this submission's
                    # waiter: dispatching its remainder would burn whole
                    # device lanes on verdicts nobody can observe
                    rem = sub.n - sub.taken
                    sub.taken = sub.n
                    self._pending_items -= rem
                    self._depth[p] -= rem
                    metrics.inc("sched.failed_skipped", rem)
                    q.popleft()
                    continue
                take = min(room, sub.n - sub.taken)
                slices.append((sub, sub.taken, sub.taken + take))
                sub.taken += take
                room -= take
                self._pending_items -= take
                self._depth[p] -= take
                if sub.taken >= sub.n:
                    q.popleft()
            metrics.set_gauge(
                "sched.queue_depth",
                float(self._depth[p]),
                labels={"priority": p},
            )
            if room <= 0:
                break
        if not slices:
            return None
        lane = PackedLane(slices, target)
        metrics.inc("sched.lanes")
        metrics.inc("sched.packed_submissions", len(slices))
        metrics.observe(
            "sched.pack_efficiency", lane.occupancy, buckets=OCCUPANCY_BUCKETS
        )
        return lane

    # -- shutdown -------------------------------------------------------------

    def drain(self) -> list[Submission]:
        """Remove and return every queued submission (engine teardown:
        their futures are cancelled by the caller).  Partially-claimed
        submissions are included — their in-flight slices resolve or
        fail through the lane that claimed them."""
        out: list[Submission] = []
        for p, q in self._q.items():
            out.extend(q)
            q.clear()
            self._depth[p] = 0
            metrics.set_gauge(
                "sched.queue_depth", 0.0, labels={"priority": p}
            )
        self._pending_items = 0
        return out
