"""Static per-limb bound tracker for the field pipeline (ISSUE 12).

field.py's int32-safety story used to live in docstrings ("every
anti-diagonal sum stays below 2^31", "|non-top limb| <= 2^19", ...) and
hand audits in curve.py.  This module turns that argument into CHECKED
code: :class:`BVal` carries an exact worst-case per-limb magnitude bound
(Python ints — no device work), :class:`BoundField` mirrors every field
op's real op sequence in bound space (the same carries, folds, and
convolutions, including the lazy wide-accumulator API), and every
multiply/accumulate asserts int32 headroom as it happens.

:func:`audit_formulas` replays the live RCB formulas (curve.pt_add /
pt_double / pt_add_mixed — via their ``F=`` namespace parameter, the same
seam the Pallas kernel and the roofline counter use) from the window
loop's input bounds and additionally checks CLOSURE: output coordinate
bounds must fit back inside the input contract, because the MSM feeds
them back in every window.  :func:`assert_formulas_safe` is the
trace-time hook — kernel.verify_core and the Pallas kernel call it (it
is cached per reduce mode and costs microseconds), so a formula edit
that violates int32 headroom fails the very first trace with a
:class:`BoundOverflow` naming the op, not a silent wrong verdict on
device.

Bound semantics: a bound B means |value| <= B for every program input
allowed by the contracts.  Magnitudes only (signs are free in this
representation — subtraction is addition of magnitudes), interval steps
are conservative but exact integer arithmetic:

* ``x & MASK``   -> bound MASK (a negative x masks to up to MASK);
* ``x >> RADIX`` -> bound (B + MASK) >> RADIX (arithmetic shift of a
  negative rounds toward -inf);
* convolution    -> exact anti-diagonal sums of pairwise bound products
  (identical for the shift_add / dot_general / half-product sqr
  formulations — they compute the same sums, so ONE audit covers all).
"""

from __future__ import annotations

import numpy as np

from . import field as F

__all__ = [
    "BoundOverflow",
    "BVal",
    "BoundField",
    "audit_formulas",
    "assert_formulas_safe",
    "COORD_BOUND",
]

_INT32_MAX = (1 << 31) - 1
_MASK = F.MASK
_RADIX = F.RADIX
_NLIMBS = F.NLIMBS
_FOLD = np.asarray(F.FOLD).tolist()  # numpy: importable inside a trace
_FN = F._FN

# The window loop's input contract (audited in curve.py's docstrings and
# now CHECKED here): accumulator/table point coordinates are sums of at
# most two reduced products — every |limb| <= 2^13.
COORD_BOUND = 1 << 13


class BoundOverflow(AssertionError):
    """A tracked chain can exceed int32 (or a documented output contract)
    for some contract-legal input."""


def _ck(v: int, what: str) -> int:
    if v > _INT32_MAX:
        raise BoundOverflow(
            f"{what}: worst-case |value| {v} = 2^{v.bit_length() - 1}.x "
            f"exceeds int32 (2^31 - 1)"
        )
    return v


class BVal:
    """A field value known only by per-limb magnitude bounds."""

    __slots__ = ("b",)

    def __init__(self, bounds):
        self.b = tuple(int(x) for x in bounds)

    @classmethod
    def uniform(cls, bound: int, n: int = _NLIMBS) -> "BVal":
        return cls((bound,) * n)

    @property
    def width(self) -> int:
        return len(self.b)

    def max(self) -> int:
        return max(self.b)

    # -- arithmetic the formulas use directly on values/wides ------------
    def __add__(self, other: "BVal") -> "BVal":
        if not isinstance(other, BVal):
            return NotImplemented
        assert len(self.b) == len(other.b), "width mismatch in add"
        return BVal(_ck(a + c, "add") for a, c in zip(self.b, other.b))

    __radd__ = __add__

    def __sub__(self, other: "BVal") -> "BVal":
        return self.__add__(other)  # magnitudes: |a - b| <= |a| + |b|

    __rsub__ = __sub__

    def __neg__(self) -> "BVal":
        return self

    def __mul__(self, k: int) -> "BVal":
        if not isinstance(k, int):
            return NotImplemented
        return BVal(_ck(x * abs(k), "scale") for x in self.b)

    __rmul__ = __mul__


def _carry(x: BVal, rounds: int) -> BVal:
    """field._carry in bound space: lo = x & MASK, hi = x >> RADIX, the
    top limb keeps its overflow in place."""
    b = list(x.b)
    for _ in range(rounds):
        lo = [_MASK if v else 0 for v in b]
        hi = [(v + _MASK) >> _RADIX for v in b]
        y = [lo[0]] + [
            _ck(lo[i] + hi[i - 1], "carry add") for i in range(1, len(b))
        ]
        # top limb: lo[-1] + (hi[-1] << RADIX) reconstructs the old top
        # EXACTLY ((x & MASK) + (x >> R << R) == x), so its bound is the
        # old bound itself — only the neighbor's carry-in adds.
        y[-1] = _ck(b[-1] + (hi[-2] if len(b) > 1 else 0), "carry top")
        b = y
    return BVal(b)


def _pad(x: BVal, n: int) -> BVal:
    return BVal(x.b + (0,) * n)


def _conv(a: BVal, b: BVal, sqr: bool = False) -> BVal:
    """Anti-diagonal sums of pairwise bound products — the bound of every
    limb-product formulation (they all compute these sums).  ``sqr``
    additionally checks the half-product path's DOUBLED cross partials
    (2*a_i*a_j must fit int32 individually, not just the sums)."""
    n = len(a.b)
    out = [0] * (2 * n - 1)
    for i in range(n):
        for j in range(n):
            p = _ck(a.b[i] * b.b[j], "conv partial")
            if sqr and i != j:
                _ck(2 * p, "sqr doubled partial")
            out[i + j] = _ck(out[i + j] + p, "conv sum")
    return BVal(out)


def _fold_once(wide: BVal) -> BVal:
    lo = BVal(wide.b[:_NLIMBS])
    hi = wide.b[_NLIMBS:]
    k = len(hi)
    out = list(_pad(lo, max(0, k + _FN - 1 - _NLIMBS)).b)
    for i in range(_FN):
        for j in range(k):
            out[i + j] = _ck(
                out[i + j] + _ck(_FOLD[i] * hi[j], "fold partial"),
                "fold sum",
            )
    o = BVal(out)
    if o.width > _NLIMBS:
        return _fold_once(_carry(_pad(o, 1), 2))
    return o


def _fold_top(x: BVal) -> BVal:
    x = _carry(_pad(x, 1), 1)
    hi = x.b[_NLIMBS]
    b = list(x.b[:_NLIMBS])
    for i in range(_FN):
        b[i] = _ck(b[i] + _ck(_FOLD[i] * hi, "fold_top partial"), "fold_top")
    return BVal(b)


def _reduce_wide(wide: BVal) -> BVal:
    """field._reduce_wide in bound space, asserting its DOCUMENTED output
    contract (every |limb| <= 2^12) — the bound comment at
    field.py's _reduce_wide, now enforced."""
    w = _carry(_pad(wide, 1), 2)
    x = _fold_once(w)
    x = _carry(x, 1)
    out = _carry(_fold_top(x), 1)
    if out.max() > (1 << 12):
        raise BoundOverflow(
            f"reduce_wide output bound {out.max()} exceeds the documented "
            f"|limb| <= 2^12 contract"
        )
    return out


class BoundField:
    """field.py's namespace API over :class:`BVal` — drop-in for the
    ``F=`` parameter of curve.py's formulas.  Every op replays the real
    implementation's op sequence on bounds and int32-checks each step."""

    RADIX = _RADIX
    NLIMBS = _NLIMBS
    MASK = _MASK

    def mul(self, a: BVal, b: BVal) -> BVal:
        return _reduce_wide(_conv(_carry(a, 1), _carry(b, 1)))

    def mul_t(self, a: BVal, b: BVal) -> BVal:
        return _reduce_wide(_conv(a, b))

    def sqr(self, a: BVal) -> BVal:
        a = _carry(a, 1)
        return _reduce_wide(_conv(a, a, sqr=True))

    def sqr_t(self, a: BVal) -> BVal:
        return _reduce_wide(_conv(a, a, sqr=True))

    def mul_small_red(self, a: BVal, k: int) -> BVal:
        return _fold_top(a * k)

    def mul_wide(self, a: BVal, b: BVal) -> BVal:
        return _conv(_carry(a, 1), _carry(b, 1))

    def mul_t_wide(self, a: BVal, b: BVal) -> BVal:
        return _conv(a, b)

    def sqr_wide(self, a: BVal) -> BVal:
        a = _carry(a, 1)
        return _conv(a, a, sqr=True)

    def sqr_t_wide(self, a: BVal) -> BVal:
        return _conv(a, a, sqr=True)

    def acc_add(self, *wides: BVal) -> BVal:
        out = wides[0]
        for w in wides[1:]:
            out = out + w
        return out

    def reduce_wide(self, w: BVal) -> BVal:
        return _reduce_wide(w)

    def reduce_wide_loose(self, w: BVal) -> BVal:
        """field.reduce_wide_loose: same tail minus the final carry;
        output must stay under the COORD closure bound."""
        x = _carry(_pad(w, 1), 2)
        x = _fold_once(x)
        x = _carry(x, 1)
        out = _fold_top(x)
        if out.max() > COORD_BOUND:
            raise BoundOverflow(
                f"reduce_wide_loose output bound {out.max()} exceeds the "
                f"documented loose |limb| <= 2^13 contract"
            )
        return out

    def tighten(self, x: BVal, rounds: int = 1) -> BVal:
        return _carry(x, rounds)

    # points stay plain lists so formula bodies can build/index them
    # without jnp (curve.py fetches make_point off the namespace when
    # the namespace provides one)
    def make_point(self, x: BVal, y: BVal, z: BVal) -> list:
        return [x, y, z]


def _coord_point(bound: int = COORD_BOUND) -> list:
    c = BVal.uniform(bound)
    return [c, c, c]


def audit_formulas(reduce: "str | None" = None) -> dict:
    """Replay the live pt_add / pt_double / pt_add_mixed bodies (the
    ACTIVE reduce mode, or ``reduce`` explicitly) from the window loop's
    input bounds; raise :class:`BoundOverflow` if any step can exceed
    int32 or an output coordinate bound escapes the COORD_BOUND closure
    the MSM relies on.  Returns the per-formula peak output bounds."""
    from .curve import pt_add, pt_add_mixed, pt_double

    bf = BoundField()
    p = _coord_point()
    # mixed q: canonical table entries (<= 2^11), possibly negated — but
    # lazy tables are reduce outputs (<= 2^12); take the looser bound
    q_aff = [BVal.uniform(1 << 12), BVal.uniform(1 << 12)]
    out = {}
    for name, res in (
        ("pt_add", pt_add(p, p, F=bf, reduce=reduce)),
        ("pt_double", pt_double(p, F=bf, reduce=reduce)),
        ("pt_add_mixed", pt_add_mixed(p, q_aff, F=bf, reduce=reduce)),
    ):
        peak = max(c.max() for c in res)
        if peak > COORD_BOUND:
            raise BoundOverflow(
                f"{name} output coordinate bound {peak} escapes the "
                f"window loop's |limb| <= 2^13 closure"
            )
        out[name] = peak
    return out


_AUDITED: dict = {}


def assert_formulas_safe(reduce: "str | None" = None) -> None:
    """Trace-time hook: audit the live formulas once per reduce mode (a
    cached no-op after the first call).  Raises BoundOverflow — failing
    the trace — when a formula edit breaks int32 headroom."""
    mode = reduce or F.reduce_mode()
    if mode not in _AUDITED:
        _AUDITED[mode] = audit_formulas(mode)
