"""Pure-Python secp256k1 ECDSA: the correctness oracle.

Implements the verification capability the reference obtains from
libsecp256k1 (via haskoin-core -> secp256k1-haskell; reference
stack.yaml:5,9).  This module favors clarity over speed — it is the ground
truth the C++ baseline and the JAX TPU kernel are validated against, and is
itself cross-checked against OpenSSL (the ``cryptography`` package) in tests.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

__all__ = [
    "CURVE_P",
    "CURVE_N",
    "CURVE_B",
    "GENERATOR",
    "Point",
    "decode_pubkey",
    "parse_der_signature",
    "sign",
    "verify",
    "verify_batch_cpu",
    "jacobi",
    "schnorr_challenge",
    "sign_schnorr",
    "verify_schnorr",
    "verify_schnorr_e",
    "tagged_hash",
    "lift_x",
    "bip340_challenge",
    "sign_bip340",
    "verify_bip340",
    "verify_bip340_e",
]

# Curve: y^2 = x^3 + 7 over F_p
CURVE_P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
CURVE_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
CURVE_B = 7
_GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
_GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8


@dataclass(frozen=True)
class Point:
    """Affine point; ``None`` coordinates encode the point at infinity."""

    x: Optional[int]
    y: Optional[int]

    @property
    def infinity(self) -> bool:
        return self.x is None

    def on_curve(self) -> bool:
        if self.infinity:
            return True
        return (self.y * self.y - (self.x * self.x * self.x + CURVE_B)) % CURVE_P == 0


INFINITY = Point(None, None)
GENERATOR = Point(_GX, _GY)


def _inv(a: int, m: int) -> int:
    return pow(a, -1, m)


def point_add(p: Point, q: Point) -> Point:
    if p.infinity:
        return q
    if q.infinity:
        return p
    if p.x == q.x:
        if (p.y + q.y) % CURVE_P == 0:
            return INFINITY
        return point_double(p)
    lam = (q.y - p.y) * _inv(q.x - p.x, CURVE_P) % CURVE_P
    x = (lam * lam - p.x - q.x) % CURVE_P
    y = (lam * (p.x - x) - p.y) % CURVE_P
    return Point(x, y)


def point_double(p: Point) -> Point:
    if p.infinity or p.y == 0:
        return INFINITY
    lam = 3 * p.x * p.x * _inv(2 * p.y, CURVE_P) % CURVE_P
    x = (lam * lam - 2 * p.x) % CURVE_P
    y = (lam * (p.x - x) - p.y) % CURVE_P
    return Point(x, y)


# Fixed-base window table for G, built lazily: table[w][d] = d * 16^w * G.
# The oracle favors clarity, but G-multiplies dominate test signing and
# benchmark workload generation (hours of wall over a round); the windowed
# path is ~6x faster and bit-identical (cross-checked against the generic
# ladder in tests and against OpenSSL).  Built under a lock and published
# atomically: engine warmup (a daemon thread) and oracle batches (worker
# threads) can race to first use.
_G_TABLE: tuple[tuple[Point, ...], ...] | None = None
_G_TABLE_LOCK = __import__("tpunode.threadsan", fromlist=["lock"]).lock(
    "verify.ecdsa_table"
)


def _g_table() -> tuple[tuple[Point, ...], ...]:
    # Lock-free read relies only on a single reference assignment being
    # atomic (true by the language model, not just the GIL — a partially
    # visible list via extend() would not be, ADVICE r4).
    global _G_TABLE
    table = _G_TABLE
    if table is not None:
        return table
    with _G_TABLE_LOCK:
        if _G_TABLE is not None:
            return _G_TABLE
        rows: list[tuple[Point, ...]] = []
        base = GENERATOR
        for _ in range(64):
            row = [INFINITY]
            for _d in range(15):
                row.append(point_add(row[-1], base))
            rows.append(tuple(row))
            base = point_double(point_double(point_double(point_double(base))))
        table = tuple(rows)
        _G_TABLE = table  # publish fully built, atomically
    return table


def point_mul(k: int, p: Point) -> Point:
    k %= CURVE_N
    if p == GENERATOR:
        table = _g_table()
        acc = INFINITY
        for w in range(64):
            acc = point_add(acc, table[w][(k >> (4 * w)) & 0xF])
        return acc
    acc = INFINITY
    addend = p
    while k:
        if k & 1:
            acc = point_add(acc, addend)
        addend = point_double(addend)
        k >>= 1
    return acc


def decode_pubkey(data: bytes) -> Optional[Point]:
    """SEC1 public key: compressed (33B, 02/03) or uncompressed (65B, 04).

    Returns None for malformed keys or points not on the curve.
    """
    if len(data) == 33 and data[0] in (2, 3):
        x = int.from_bytes(data[1:], "big")
        if x >= CURVE_P:
            return None
        y2 = (x * x * x + CURVE_B) % CURVE_P
        y = pow(y2, (CURVE_P + 1) // 4, CURVE_P)
        if y * y % CURVE_P != y2:
            return None
        if (y & 1) != (data[0] & 1):
            y = CURVE_P - y
        return Point(x, y)
    if len(data) == 65 and data[0] == 4:
        x = int.from_bytes(data[1:33], "big")
        y = int.from_bytes(data[33:], "big")
        p = Point(x, y)
        if x >= CURVE_P or y >= CURVE_P or not p.on_curve():
            return None
        return p
    return None


def parse_der_signature(sig: bytes) -> Optional[tuple[int, int]]:
    """Parse a DER ECDSA signature into (r, s).

    Accepts the (lax, pre-BIP66-ish) shapes found in historical Bitcoin
    transactions as long as the basic TLV structure holds.
    """
    try:
        if len(sig) < 8 or sig[0] != 0x30:
            return None
        if sig[1] != len(sig) - 2:
            return None
        if sig[2] != 0x02:
            return None
        rlen = sig[3]
        r = int.from_bytes(sig[4 : 4 + rlen], "big")
        pos = 4 + rlen
        if sig[pos] != 0x02:
            return None
        slen = sig[pos + 1]
        s = int.from_bytes(sig[pos + 2 : pos + 2 + slen], "big")
        if pos + 2 + slen != len(sig):
            return None
        return r, s
    except IndexError:
        return None


def sign(priv: int, z: int, nonce: int) -> tuple[int, int]:
    """Deterministic-nonce test signing helper (NOT for production use)."""
    k = nonce % CURVE_N
    if k == 0:
        k = 1
    R = point_mul(k, GENERATOR)
    r = R.x % CURVE_N
    s = _inv(k, CURVE_N) * (z + r * priv) % CURVE_N
    if r == 0 or s == 0:
        return sign(priv, z, nonce + 1)
    return r, s


def verify(pubkey: Optional[Point], z: int, r: int, s: int) -> bool:
    """Standard ECDSA verification: R = u1*G + u2*Q, accept iff R.x ≡ r (mod n).

    ``pubkey=None`` (undecodable key, see txverify.extract_sig_items) is
    auto-invalid — all three backends agree on this (kernel.prepare_batch
    masks None host-side the same way).
    """
    if not (0 < r < CURVE_N and 0 < s < CURVE_N):
        return False
    if pubkey is None or pubkey.infinity or not pubkey.on_curve():
        return False
    w = _inv(s, CURVE_N)
    u1 = z * w % CURVE_N
    u2 = r * w % CURVE_N
    R = point_add(point_mul(u1, GENERATOR), point_mul(u2, pubkey))
    if R.infinity:
        return False
    return R.x % CURVE_N == r


# --- BCH Schnorr (2019-05 upgrade spec) ------------------------------------
#
# Signature is 64 bytes r ∥ s (r an Fp x-coordinate, s a scalar).  Verify:
# with e = SHA256(ser256(r) ∥ ser_compressed(P) ∥ ser256(m)) mod n, compute
# R' = s·G − e·P and accept iff R' is finite, jacobi(y(R')) = 1, and
# x(R') = r.  Same dual-scalar MSM shape as ECDSA (u1 = s, u2 = n − e), so
# the batch kernel verifies both algorithms with one program.  The
# reference's libsecp256k1 grew this capability for BCH the same year
# (stack.yaml:5,9 pulls the BCH-era library).


def jacobi(a: int) -> int:
    """Legendre/Jacobi symbol of ``a`` mod p via Euler's criterion."""
    if a % CURVE_P == 0:
        return 0
    return 1 if pow(a, (CURVE_P - 1) // 2, CURVE_P) == 1 else -1


def _compress(p: Point) -> bytes:
    return bytes([2 + (p.y & 1)]) + p.x.to_bytes(32, "big")


def schnorr_challenge(r: int, pubkey: Point, m: int) -> int:
    """e = SHA256(r ∥ P_compressed ∥ m) mod n (single SHA256 per the BCH
    2019 schnorr spec — not BIP340's tagged hash)."""
    import hashlib

    digest = hashlib.sha256(
        r.to_bytes(32, "big") + _compress(pubkey) + m.to_bytes(32, "big")
    ).digest()
    return int.from_bytes(digest, "big") % CURVE_N


def sign_schnorr(priv: int, m: int, nonce: int) -> tuple[int, int]:
    """Deterministic-nonce test signing helper (NOT for production use)."""
    k = nonce % CURVE_N or 1
    R = point_mul(k, GENERATOR)
    if jacobi(R.y) != 1:
        k = CURVE_N - k
        R = Point(R.x, CURVE_P - R.y)
    r = R.x
    pub = point_mul(priv, GENERATOR)
    e = schnorr_challenge(r, pub, m)
    s = (k + e * priv) % CURVE_N
    return r, s


def verify_schnorr_e(
    pubkey: Optional[Point], e: int, r: int, s: int
) -> bool:
    """Schnorr verification from a precomputed challenge ``e`` — the form
    batch items carry (extraction computes e, so no hashing downstream)."""
    if not (0 <= r < CURVE_P and 0 <= s < CURVE_N):
        return False
    if pubkey is None or pubkey.infinity or not pubkey.on_curve():
        return False
    R = point_add(
        point_mul(s, GENERATOR), point_mul(CURVE_N - e % CURVE_N, pubkey)
    )
    if R.infinity:
        return False
    return jacobi(R.y) == 1 and R.x == r


def verify_schnorr(pubkey: Optional[Point], m: int, r: int, s: int) -> bool:
    """Full Schnorr verification over the message hash ``m``."""
    if pubkey is None or pubkey.infinity:
        return False
    return verify_schnorr_e(pubkey, schnorr_challenge(r, pubkey, m), r, s)


# --- BIP340 Schnorr (taproot, BTC 2021) ------------------------------------
#
# Same R' = s·G − e·P shape again; differences from the BCH variant: x-only
# public keys lifted to the EVEN-y point, a tagged challenge hash, and the
# acceptance test requires y(R') even (not jacobi = 1).  Exposed as a
# verify primitive (engine items tagged "bip340"); extraction does not
# emit these because a taproot keypath spend carries no pubkey on the
# wire — it lives in the prevout scriptPubKey, i.e. behind the embedder's
# UTXO set, and the BIP341 sighash needs every input's amount and script.


def tagged_hash(tag: bytes, data: bytes) -> bytes:
    import hashlib

    th = hashlib.sha256(tag).digest()
    return hashlib.sha256(th + th + data).digest()


def lift_x(x: int) -> Optional[Point]:
    """The even-y point with x-coordinate ``x`` (BIP340 lift_x); None if
    ``x`` is out of range or not on the curve."""
    if not (0 <= x < CURVE_P):
        return None
    y2 = (x * x * x + CURVE_B) % CURVE_P
    y = pow(y2, (CURVE_P + 1) // 4, CURVE_P)
    if y * y % CURVE_P != y2:
        return None
    return Point(x, y if y % 2 == 0 else CURVE_P - y)


def bip340_challenge(r: int, pubkey_x: int, m: int) -> int:
    e = tagged_hash(
        b"BIP0340/challenge",
        r.to_bytes(32, "big") + pubkey_x.to_bytes(32, "big")
        + m.to_bytes(32, "big"),
    )
    return int.from_bytes(e, "big") % CURVE_N


def sign_bip340(priv: int, m: int, nonce: int) -> tuple[int, int]:
    """Deterministic-nonce test signing helper (NOT for production use; the
    BIP's aux-rand nonce derivation is skipped, signatures are still
    spec-verifiable)."""
    P = point_mul(priv, GENERATOR)
    d = priv if P.y % 2 == 0 else CURVE_N - priv
    k = nonce % CURVE_N or 1
    R = point_mul(k, GENERATOR)
    if R.y % 2 != 0:
        k = CURVE_N - k
        R = Point(R.x, CURVE_P - R.y)
    r = R.x
    e = bip340_challenge(r, P.x, m)
    s = (k + e * d) % CURVE_N
    return r, s


def verify_bip340_e(
    pubkey: Optional[Point], e: int, r: int, s: int
) -> bool:
    """BIP340 verification from a precomputed challenge.  ``pubkey`` must
    be the lift_x'd (even-y) point."""
    if not (0 <= r < CURVE_P and 0 <= s < CURVE_N):
        return False
    if pubkey is None or pubkey.infinity or not pubkey.on_curve():
        return False
    R = point_add(
        point_mul(s, GENERATOR), point_mul(CURVE_N - e % CURVE_N, pubkey)
    )
    if R.infinity:
        return False
    return R.y % 2 == 0 and R.x == r


def verify_bip340(pubkey_x: int, m: int, r: int, s: int) -> bool:
    """Full BIP340 verification over an x-only public key."""
    P = lift_x(pubkey_x)
    if P is None:
        return False
    return verify_bip340_e(P, bip340_challenge(r, pubkey_x, m), r, s)


def verify_batch_cpu(
    items: Sequence[tuple],
) -> list[bool]:
    """Sequential batch verify.  Items are ``(pubkey|None, z, r, s)`` for
    ECDSA, or 5-tuples tagged ``"schnorr"`` (BCH) / ``"bip340"`` (taproot)
    with the precomputed challenge in the z position."""
    out = []
    for item in items:
        if len(item) >= 5 and item[4] == "schnorr":
            out.append(verify_schnorr_e(item[0], item[1], item[2], item[3]))
        elif len(item) >= 5 and item[4] == "bip340":
            out.append(verify_bip340_e(item[0], item[1], item[2], item[3]))
        else:
            q, z, r, s = item[:4]
            out.append(verify(q, z, r, s))
    return out
