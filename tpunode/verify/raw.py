"""Canonical raw representation of a verify batch: packed byte rows.

``RawBatch`` is the zero-Python-int interchange format between the native
extractor (tpunode/txextract.py), the C++ CPU verifier (``secp_verify_batch``)
and the TPU prep (``secp_prepare_batch``): five ``(N, 32)`` uint8 arrays of
big-endian values plus a per-item ``present`` flag carrying the algorithm:

* ``present == 0``: auto-invalid row (zeros elsewhere) — verifies False on
  every backend;
* ``present == 1``: ECDSA — ``z`` is the sighash digest, ``r``/``s`` the
  DER scalars;
* ``present == 2``: BCH Schnorr — ``z`` is the PRECOMPUTED challenge ``e``
  (extraction hashes it once; no backend re-hashes), ``r`` the Fp
  x-coordinate, ``s`` the scalar.
* ``present == 3``: BIP340 (taproot) Schnorr — same row layout as BCH
  Schnorr with the tagged challenge in ``z``; the pubkey columns hold the
  lift_x'd even-y point.

Tuple items (the engine's ``VerifyItem``) pack into it with the same
degenerate-item rules the CPU backend always applied (None/infinity pubkey,
out-of-range r/s — checked on the ORIGINAL ints, so oversized lax-DER
values can't alias).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from .ecdsa_cpu import CURVE_N, CURVE_P, Point

__all__ = ["RawBatch", "pack_items", "as_raw_batch", "concat_raw"]


@dataclass
class RawBatch:
    """Packed verify items: ``(N, 32)`` big-endian uint8 rows."""

    px: np.ndarray
    py: np.ndarray
    z: np.ndarray
    r: np.ndarray
    s: np.ndarray
    present: np.ndarray  # (N,) uint8; 0 absent, 1 ecdsa, 2 bch-schnorr, 3 bip340

    def __len__(self) -> int:
        return len(self.present)

    def slice(self, lo: int, hi: int) -> "RawBatch":
        return RawBatch(
            px=self.px[lo:hi],
            py=self.py[lo:hi],
            z=self.z[lo:hi],
            r=self.r[lo:hi],
            s=self.s[lo:hi],
            present=self.present[lo:hi],
        )

    def to_tuples(self) -> list[tuple]:
        """VerifyItem tuples (oracle backend / cross-checks).  Rows with
        ``present == 0`` become ``(None, 0, 0, 0)`` — same verdict (False)
        as whatever degenerate original they packed from; ``present == 2``
        rows come back as 5-tuples tagged ``"schnorr"``."""
        out = []
        for i in range(len(self)):
            if not self.present[i]:
                out.append((None, 0, 0, 0))
                continue
            tup = (
                Point(
                    int.from_bytes(self.px[i].tobytes(), "big"),
                    int.from_bytes(self.py[i].tobytes(), "big"),
                ),
                int.from_bytes(self.z[i].tobytes(), "big"),
                int.from_bytes(self.r[i].tobytes(), "big"),
                int.from_bytes(self.s[i].tobytes(), "big"),
            )
            if self.present[i] == 2:
                tup = tup + ("schnorr",)
            elif self.present[i] == 3:
                tup = tup + ("bip340",)
            out.append(tup)
        return out


def pack_items(items: Sequence[tuple]) -> RawBatch:
    """Pack VerifyItem tuples (4-tuples ECDSA, 5-tuples tagged "schnorr"),
    applying the degenerate-row rules on the original ints (mirrors
    NativeVerifier.verify_batch's packing)."""
    n = len(items)
    px = np.zeros((n, 32), np.uint8)
    py = np.zeros((n, 32), np.uint8)
    z = np.zeros((n, 32), np.uint8)
    r = np.zeros((n, 32), np.uint8)
    s = np.zeros((n, 32), np.uint8)
    present = np.zeros(n, np.uint8)
    for i, item in enumerate(items):
        q, zi, ri, si = item[:4]
        tag = item[4] if len(item) >= 5 else None
        if q is None or q.infinity:
            continue
        if tag in ("schnorr", "bip340"):
            # spec ranges: r an Fp element, s a scalar; zero allowed
            if not (0 <= ri < CURVE_P and 0 <= si < CURVE_N):
                continue
            present[i] = 2 if tag == "schnorr" else 3
        else:
            if not (0 < ri < CURVE_N and 0 < si < CURVE_N):
                continue
            present[i] = 1
        px[i] = np.frombuffer(q.x.to_bytes(32, "big"), np.uint8)
        py[i] = np.frombuffer(q.y.to_bytes(32, "big"), np.uint8)
        z[i] = np.frombuffer((zi % CURVE_N).to_bytes(32, "big"), np.uint8)
        r[i] = np.frombuffer(ri.to_bytes(32, "big"), np.uint8)
        s[i] = np.frombuffer(si.to_bytes(32, "big"), np.uint8)
    return RawBatch(px=px, py=py, z=z, r=r, s=s, present=present)


def as_raw_batch(obj) -> RawBatch:
    """Coerce to RawBatch: pass-through, duck-typed arrays (e.g.
    txextract.RawSigItems), or a VerifyItem sequence."""
    if isinstance(obj, RawBatch):
        return obj
    if hasattr(obj, "px") and hasattr(obj, "present"):
        return RawBatch(
            px=obj.px, py=obj.py, z=obj.z, r=obj.r, s=obj.s,
            present=np.asarray(obj.present, np.uint8),
        )
    return pack_items(obj)


def concat_raw(batches: Sequence[RawBatch]) -> RawBatch:
    if len(batches) == 1:
        return batches[0]
    return RawBatch(
        px=np.concatenate([b.px for b in batches]),
        py=np.concatenate([b.py for b in batches]),
        z=np.concatenate([b.z for b in batches]),
        r=np.concatenate([b.r for b in batches]),
        s=np.concatenate([b.s for b in batches]),
        present=np.concatenate([b.present for b in batches]),
    )
