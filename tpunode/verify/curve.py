"""secp256k1 group operations on TPU: complete projective formulas.

Points are projective ``(X : Y : Z)`` triples of limb vectors, stored as one
array of shape ``(3, NLIMBS, B)`` — limb-major layout (see field.py): the
batch axis is minor-most so it lands in TPU lanes.  Infinity is
``(0 : 1 : 0)``, shape ``(3, NLIMBS, 1)``, broadcasting over the batch.

We use the Renes–Costello–Batina *complete* addition/doubling formulas for
prime-order short-Weierstrass curves with a = 0 (RCB'16, Algorithms 7 and 9,
b3 = 3*b = 21 for secp256k1).  Complete formulas are branch-free and correct
for EVERY input pair — including infinity and P = ±Q — which is exactly what
a jit-compiled, batched, consensus-critical verifier wants: no data-dependent
control flow, no exceptional-case equality tests in the hot loop, bit-exact
results.

This replaces the group layer of libsecp256k1 (SURVEY.md C9) with a design
chosen for XLA rather than a port: libsecp256k1 uses branchy Jacobian
formulas + constant-time tricks; here completeness does that job for free.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import field as F

__all__ = [
    "B3",
    "INFINITY",
    "pt_add",
    "pt_add_mixed",
    "pt_double",
    "pt_select",
    "make_point",
    "is_infinity",
    "POINT_FORMS",
    "point_form",
    "set_point_form",
]

B3 = 21  # 3 * b for y^2 = x^3 + 7


# ---------- point-form knob (ISSUE 8) --------------------------------------
#
# Like field.py's limb-product formulation knobs: process-global, read at
# TRACE time, so every jitted program that embeds the MSM keys its jit
# cache on kernel.kernel_modes() (which includes point_form()) and a flip
# retraces instead of silently keeping the old formulation.
#
# "projective" (default): per-signature Q/λQ window tables stay projective
# (3 coords), window additions use the full 12M+2 RCB complete add.
# "affine": the tables are batch-normalized to affine (2 coords) with one
# Montgomery-trick inversion per lane (kernel._affine_tables), window
# additions use the cheaper 11M+2 complete MIXED add below, and table
# selects move a third less data.

POINT_FORMS = ("projective", "affine")

_POINT_FORM = F._env_mode("TPUNODE_POINT_FORM", POINT_FORMS, "projective")


def point_form() -> str:
    """Active MSM point formulation: "projective" | "affine"."""
    return _POINT_FORM


def set_point_form(form: "str | None") -> str:
    """Select the MSM point form process-wide; returns the previous form
    (None is a no-op, mirroring field.set_field_modes).  Programs traced
    before the flip keep their form until their owner re-traces — which
    every in-repo dispatch site does, because all of them key on
    :func:`tpunode.verify.kernel.kernel_modes`."""
    global _POINT_FORM
    if form is None:
        return _POINT_FORM
    if form not in POINT_FORMS:
        raise ValueError(f"point form {form!r} not in {POINT_FORMS}")
    prev = _POINT_FORM
    _POINT_FORM = form
    return prev


def make_point(x: jnp.ndarray, y: jnp.ndarray, z: jnp.ndarray) -> jnp.ndarray:
    return jnp.stack([x, y, z], axis=0)


# The formulas read the process-global reduction discipline (ISSUE 12)
# at trace time unless the caller pins it via their ``reduce=`` kwarg;
# module-level binding because the ``F`` name is shadowed by the
# namespace parameter inside the formula bodies.
_active_reduce = F.reduce_mode


def _mk(F_ns):
    """The point constructor for a formula's namespace: the namespace's
    own ``make_point`` when it has one (the bound tracker builds plain
    lists), :func:`make_point` otherwise (jnp stacking for the real
    field namespaces)."""
    return getattr(F_ns, "make_point", make_point)


INFINITY = make_point(F.ZERO, F.ONE, F.ZERO)


def is_infinity(p: jnp.ndarray) -> jnp.ndarray:
    """Z ≡ 0 (mod p) — exact; a finite point can never have Z ≡ 0."""
    return F.is_zero(p[2])


def pt_select(mask: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Branch-free ``mask ? a : b`` over whole points."""
    return jnp.where(mask, a, b)


def pt_add(p: jnp.ndarray, q: jnp.ndarray, F=F, reduce=None) -> jnp.ndarray:
    """Complete addition (RCB'16 Algorithm 7, a = 0): 12 muls, no exceptions.

    ``F`` is the field-arithmetic namespace (mul/mul_t/mul_small_red with
    field.py's contracts); the Pallas kernel passes its Mosaic-friendly
    implementation so both device paths share these audited formulas.
    ``reduce`` pins the reduction discipline ("eager"/"lazy", ISSUE 12) —
    None reads the process-global :func:`field.reduce_mode` at trace
    time.  The two bodies produce different limb representations but
    identical values mod p (pinned in tests/test_field.py); int32 safety
    of BOTH is checked by tpunode.verify.bounds at trace time.

    Limb-bound audit against field.mul's contract (|non-top limb| <= 2^19,
    |top limb| <= 2^15, pairwise top(a)*top(b) <= 2^30): every mul operand
    below is a mul output (every limb <= 2^12), a 2-3-term sum of mul
    outputs (<= 2^13.6, top included), or a mul_small_red result (non-top
    <= 2^19, top <= 2^12) — the raw B3 scalings that used to exceed the
    top-limb bound now go through mul_small_red.
    """
    if (reduce or _active_reduce()) == "lazy":
        return _pt_add_lazy(p, q, F)
    X1, Y1, Z1 = p[0], p[1], p[2]
    X2, Y2, Z2 = q[0], q[1], q[2]
    mul = F.mul

    # coords are <= 2^13 (sums of <= 2 mul outputs): inside mul_t's contract
    t0 = F.mul_t(X1, X2)
    t1 = F.mul_t(Y1, Y2)
    t2 = F.mul_t(Z1, Z2)
    t3 = mul(X1 + Y1, X2 + Y2)
    t3 = t3 - (t0 + t1)
    t4 = mul(Y1 + Z1, Y2 + Z2)
    t4 = t4 - (t1 + t2)
    t5 = mul(X1 + Z1, X2 + Z2)
    t5 = t5 - (t0 + t2)  # = X1*Z2 + X2*Z1
    t0_3 = t0 + t0 + t0  # 3*X1*X2
    t2_b3 = F.mul_small_red(t2, B3)  # reduced: keeps z3/t1m inside mul's contract
    z3 = t1 + t2_b3
    t1m = t1 - t2_b3
    y3 = F.mul_small_red(t5, B3)  # reduced: y3 feeds two muls below
    x3 = mul(t4, y3)
    t2b = mul(t3, t1m)
    x3 = t2b - x3
    y3 = mul(y3, t0_3)
    t1b = mul(t1m, z3)
    y3 = t1b + y3
    t0b = mul(t0_3, t3)
    z3 = mul(z3, t4)
    z3 = z3 + t0b
    return _mk(F)(x3, y3, z3)


def _pt_add_lazy(p: jnp.ndarray, q: jnp.ndarray, F=F) -> jnp.ndarray:
    """The lazy-reduction body of :func:`pt_add` (ISSUE 12): same RCB
    algebra, three fused carry/fold levers —

    * the three output coordinates, each a ±-sum of two products,
      accumulate as unreduced 47-limb wides and pay ONE reduction each
      (3 reductions saved);
    * every reduction is the LOOSE tail (``reduce_wide_loose``: one
      carry round cheaper; outputs <= ~2^12.3, inside every consumer's
      contract);
    * shared tail operands get ONE hoisted carry round each instead of
      a fresh pair inside every full mul (6 rounds instead of 12).

    Values differ limb-wise from the eager body's but are equal mod p;
    the window loop's verdicts are bit-identical.  int32 safety and the
    2^13 coordinate closure are checked by tpunode.verify.bounds."""
    X1, Y1, Z1 = p[0], p[1], p[2]
    X2, Y2, Z2 = q[0], q[1], q[2]
    rw = F.reduce_wide_loose

    t0 = rw(F.mul_t_wide(X1, X2))
    t1 = rw(F.mul_t_wide(Y1, Y2))
    t2 = rw(F.mul_t_wide(Z1, Z2))
    t3 = rw(F.mul_wide(X1 + Y1, X2 + Y2))
    t3 = t3 - (t0 + t1)  # = X1*Y2 + X2*Y1
    t4 = rw(F.mul_wide(Y1 + Z1, Y2 + Z2))
    t4 = t4 - (t1 + t2)
    t5 = rw(F.mul_wide(X1 + Z1, X2 + Z2))
    t5 = t5 - (t0 + t2)  # = X1*Z2 + X2*Z1
    t2_b3 = F.mul_small_red(t2, B3)
    # hoisted carry rounds: each shared operand tightens ONCE, then
    # every product below is a bare convolution (mul_t_wide)
    t3 = F.tighten(t3)
    t4 = F.tighten(t4)
    t0_3 = F.tighten(t0 + t0 + t0)  # 3*X1*X2
    z3s = F.tighten(t1 + t2_b3)
    t1m = F.tighten(t1 - t2_b3)
    y3r = F.tighten(F.mul_small_red(t5, B3))  # b3*(X1*Z2 + X2*Z1)
    x3 = rw(F.mul_t_wide(t3, t1m) - F.mul_t_wide(t4, y3r))
    y3 = rw(F.acc_add(F.mul_t_wide(t1m, z3s), F.mul_t_wide(y3r, t0_3)))
    z3 = rw(F.acc_add(F.mul_t_wide(z3s, t4), F.mul_t_wide(t0_3, t3)))
    return _mk(F)(x3, y3, z3)


def pt_add_mixed(p: jnp.ndarray, q: jnp.ndarray, F=F, reduce=None) -> jnp.ndarray:
    """Complete MIXED addition (RCB'16 Algorithm 8, a = 0): 11 muls + 2
    reduced scalings — one full mul cheaper than :func:`pt_add` because
    ``q`` is affine: a 2-coordinate ``(x2, y2)`` stack with Z2 = 1
    implicit (the ISSUE 8 affine window tables), so t2 = Z1*Z2
    degenerates to Z1 and the X1*Z2/Y1*Z2 cross terms to X1/Y1.

    Complete in ``p`` (infinity, p = ±q all exact) but ``q`` CANNOT be
    the point at infinity — affine coordinates can't represent it.  The
    window loops handle the digit-0 (infinity) table entry by keeping
    the accumulator unchanged via a branch-free select instead
    (kernel.py / pallas_kernel.py), so the formula never sees it.

    Limb-bound audit (same contracts as pt_add's): p's coords are <= 2^13
    (sums of <= 2 mul outputs), q's are mul outputs or canonical table
    constants (<= 2^12, possibly negated — sign-safe throughout).
    mul_t legs: X1*x2, Y1*y2, y2*Z1, x2*Z1 all <= 2^13 x 2^12.  The
    mul legs take sums <= 2^14 (non-top <= 2^19 trivially; pairwise
    top*top <= 2^27 < 2^30).  mul_small_red on Z1 (limbs <= 2^13):
    value*21 < 2^271 so non-top <= 2^11 + 2^11*2^7 <= 2^18.1 — z3/t1m
    sums stay inside mul's |non-top| <= 2^19 input contract.

    ``reduce`` as in :func:`pt_add`: the lazy body fuses the same three
    output accumulations and hoists the shared-operand carry rounds.
    """
    if (reduce or _active_reduce()) == "lazy":
        return _pt_add_mixed_lazy(p, q, F)
    X1, Y1, Z1 = p[0], p[1], p[2]
    x2, y2 = q[0], q[1]
    mul = F.mul

    t0 = F.mul_t(X1, x2)
    t1 = F.mul_t(Y1, y2)
    t3 = mul(X1 + Y1, x2 + y2)
    t3 = t3 - (t0 + t1)  # = X1*y2 + x2*Y1
    t4 = F.mul_t(y2, Z1)
    t4 = t4 + Y1  # = Y1*Z2 + Y2*Z1 with Z2 = 1
    t5 = F.mul_t(x2, Z1)
    t5 = t5 + X1  # = X1*Z2 + X2*Z1 with Z2 = 1
    t0_3 = t0 + t0 + t0  # 3*X1*X2
    t2_b3 = F.mul_small_red(Z1, B3)  # b3*Z1*Z2 with Z2 = 1
    z3 = t1 + t2_b3
    t1m = t1 - t2_b3
    y3 = F.mul_small_red(t5, B3)
    x3 = mul(t4, y3)
    t2b = mul(t3, t1m)
    x3 = t2b - x3
    y3 = mul(y3, t0_3)
    t1b = mul(t1m, z3)
    y3 = t1b + y3
    t0b = mul(t0_3, t3)
    z3 = mul(z3, t4)
    z3 = z3 + t0b
    return _mk(F)(x3, y3, z3)


def _pt_add_mixed_lazy(p: jnp.ndarray, q: jnp.ndarray, F=F) -> jnp.ndarray:
    """The lazy-reduction body of :func:`pt_add_mixed` (ISSUE 12): the
    same fused-tail / loose-reduce / hoisted-carry levers as
    :func:`_pt_add_lazy` over the mixed-add algebra (Z2 = 1)."""
    X1, Y1, Z1 = p[0], p[1], p[2]
    x2, y2 = q[0], q[1]
    rw = F.reduce_wide_loose

    t0 = rw(F.mul_t_wide(X1, x2))
    t1 = rw(F.mul_t_wide(Y1, y2))
    t3 = rw(F.mul_wide(X1 + Y1, x2 + y2))
    t3 = t3 - (t0 + t1)  # = X1*y2 + x2*Y1
    t4 = rw(F.mul_t_wide(y2, Z1))
    t4 = t4 + Y1  # = Y1*Z2 + Y2*Z1 with Z2 = 1
    t5 = rw(F.mul_t_wide(x2, Z1))
    t5 = t5 + X1  # = X1*Z2 + X2*Z1 with Z2 = 1
    t2_b3 = F.mul_small_red(Z1, B3)  # b3*Z1*Z2 with Z2 = 1
    # hoisted carry rounds, one per shared operand (see _pt_add_lazy)
    t3 = F.tighten(t3)
    t4 = F.tighten(t4)
    t0_3 = F.tighten(t0 + t0 + t0)  # 3*X1*X2
    z3s = F.tighten(t1 + t2_b3)
    t1m = F.tighten(t1 - t2_b3)
    y3r = F.tighten(F.mul_small_red(t5, B3))
    x3 = rw(F.mul_t_wide(t3, t1m) - F.mul_t_wide(t4, y3r))
    y3 = rw(F.acc_add(F.mul_t_wide(t1m, z3s), F.mul_t_wide(y3r, t0_3)))
    z3 = rw(F.acc_add(F.mul_t_wide(z3s, t4), F.mul_t_wide(t0_3, t3)))
    return _mk(F)(x3, y3, z3)


def pt_double(p: jnp.ndarray, F=F, reduce=None) -> jnp.ndarray:
    """Complete doubling (RCB'16 Algorithm 9, a = 0): 6 muls + 2 squarings.

    ``F`` as in :func:`pt_add`.  The two squarings (Y^2, Z^2) go through
    ``F.sqr_t`` — the dedicated half-product path (~300 partials vs 576)
    under the default sqr mode; same contract as ``mul_t`` and
    bit-identical output.  ``reduce`` as in :func:`pt_add`."""
    if (reduce or _active_reduce()) == "lazy":
        return _pt_double_lazy(p, F)
    X, Y, Z = p[0], p[1], p[2]
    mul = F.mul

    # coords are <= 2^13: inside mul_t's (== sqr_t's) contract
    t0 = F.sqr_t(Y)
    z3 = t0 * 8  # 8Y^2, |limb| <= 2^15
    t1 = F.mul_t(Y, Z)
    t2 = F.sqr_t(Z)
    t2 = F.mul_small_red(t2, B3)  # b3*Z^2: non-top <= 2^16.6, top <= 2^12
    x3 = mul(t2, z3)
    y3 = t0 + t2
    z3 = mul(t1, z3)
    t2_3 = t2 + t2 + t2  # 3*b3*Z^2: <= 3*2^16.6 = 2^18.3 (mul-input safe)
    t0 = t0 - t2_3
    y3 = mul(t0, y3)
    y3 = x3 + y3
    t1 = F.mul_t(X, Y)
    x3 = mul(t0, t1)
    x3 = x3 + x3
    return _mk(F)(x3, y3, z3)


def _pt_double_lazy(p: jnp.ndarray, F=F) -> jnp.ndarray:
    """The lazy-reduction body of :func:`pt_double` (ISSUE 12): the
    eager body's interior ``x3 = b3·Z²·8Y²`` product never materializes
    reduced — it fuses into y3's accumulation (one reduction saved) —
    and the three shared operands (8Y², the b3·Z² scaling, and the
    t0 - 3·t2 difference) each get ONE hoisted carry round instead of
    per-mul input carries."""
    X, Y, Z = p[0], p[1], p[2]
    rw = F.reduce_wide_loose

    t0 = rw(F.sqr_t_wide(Y))
    z8 = F.tighten(t0 * 8)  # 8Y^2: tightened once, feeds two products
    t1 = rw(F.mul_t_wide(Y, Z))
    t2 = F.tighten(F.mul_small_red(rw(F.sqr_t_wide(Z)), B3))  # b3*Z^2
    y3s = t0 + t2
    t0m = F.tighten(t0 - (t2 + t2 + t2))
    z3 = rw(F.mul_t_wide(t1, z8))
    y3 = rw(F.acc_add(F.mul_t_wide(t2, z8), F.mul_t_wide(t0m, y3s)))
    t1b = rw(F.mul_t_wide(X, Y))
    x3 = rw(F.mul_t_wide(t0m, t1b))
    x3 = x3 + x3
    return _mk(F)(x3, y3, z3)
