"""Zero-dependency debug HTTP server: live node introspection.

A minimal asyncio HTTP/1.1 server (stdlib only — no framework) exposing
the telemetry that already exists in-process:

* ``GET /metrics``  — Prometheus text exposition (``render_prometheus``)
* ``GET /health``   — the embedder-supplied health snapshot as JSON
* ``GET /stats``    — the full stats snapshot as JSON (when supplied)
* ``GET /events?n=100&type=watchdog.stall`` — recent structured events;
  ``?since=<seq>`` returns only events newer than that sequence number
  (pollers keep a cursor instead of re-downloading the ring)
* ``GET /traces?n=8`` — recent + slowest finished trace trees (tracectx)
* ``GET /mempool`` — mempool snapshot (size, orphans, dedup hit-rate,
  top announcers) when the node runs one (``NodeConfig.mempool``)
* ``GET /timeseries?name=&tier=&since=`` — the metrics timeline
  (tpunode/timeseries.py): series index, or one series' ring
* ``GET /fleet`` — per-host fleet state now + its sampled history
* ``GET /flightrecords?n=`` — the flight recorder's post-mortem bundles
  (tpunode/blackbox.py)
* ``GET /slo`` — the SLO evaluator's snapshot (tpunode/slo.py):
  definitions, burn rates, remaining budgets, burn history, cost ledger
* ``GET /serve`` — the serve layer's tenant/quota/cache snapshot
  (tpunode/serve.py, ISSUE 20): per-tenant frames/items/shed/throttle
  counters, verdict-cache occupancy, per-tenant spend attribution
* ``GET /receipts?start=&n=`` — verdict receipt records by sequence
  number from the hash-chained log (tpunode/receipts.py) + chain tip
* ``GET /`` — the endpoint catalog itself as JSON (machine-discoverable:
  an operator with just the port can enumerate everything above)

Off by default: enable with ``NodeConfig.debug_port`` (0 binds an
ephemeral port — read it back from ``DebugServer.port``).  Binds
``127.0.0.1`` only; this is an operator/debug surface, not a public API.
Every response closes the connection (``Connection: close``) — curl-able,
scrape-able, nothing more.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
from typing import Callable, Optional
from urllib.parse import parse_qs, urlsplit

from .events import EventLog, events
from .metrics import Metrics, metrics
from .tracectx import Tracer, tracer

__all__ = ["DebugServer"]

log = logging.getLogger("tpunode.debugsrv")

_MAX_REQUEST_LINE = 8192
_HEADER_TIMEOUT = 5.0

# The endpoint catalog: served by ``GET /`` and echoed (keys only) in the
# 404 body.  One source of truth — adding a route means adding a row here.
ENDPOINTS: dict[str, str] = {
    "/": "this endpoint catalog",
    "/metrics": "Prometheus text exposition",
    "/health": "health snapshot (JSON)",
    "/stats": "full stats snapshot (JSON)",
    "/events?n=&type=&since=": "recent structured events / seq cursor",
    "/traces?n=": "recent + slowest finished trace trees",
    "/mempool": "mempool snapshot",
    "/timeseries?name=&tier=&since=": "metrics timeline rings",
    "/fleet": "per-host fleet state now + sampled history",
    "/flightrecords?n=": "flight recorder post-mortem bundles",
    "/slo": "SLO burn rates, budgets, burn history, cost ledger",
    "/serve": "serve-layer tenant/quota/cache snapshot",
    "/receipts?start=&n=": "hash-chained verdict receipt records",
}


class DebugServer:
    """Serve the debug endpoints until the scope closes::

        async with DebugServer(port=0, health=node.health) as srv:
            ...  # GET http://127.0.0.1:{srv.port}/metrics
    """

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        health: Optional[Callable[[], dict]] = None,
        stats: Optional[Callable[[], dict]] = None,
        mempool: Optional[Callable[[], dict]] = None,
        registry: Optional[Metrics] = None,
        log_: Optional[EventLog] = None,
        tracer_: Optional[Tracer] = None,
        timeline=None,  # tpunode.timeseries.Timeline (or None)
        blackbox=None,  # tpunode.blackbox.FlightRecorder (or None)
        fleet: Optional[Callable[[], dict]] = None,  # live fleet state
        slo: Optional[Callable[[], dict]] = None,  # SloEvaluator.snapshot
        serve: Optional[Callable[[], dict]] = None,  # ServeServer.stats
        receipts=None,  # tpunode.receipts.ReceiptLog (or None)
    ):
        self._want_port = port
        self.host = host
        self.health = health
        self.stats = stats
        self.mempool = mempool
        self.registry = registry if registry is not None else metrics
        self.log = log_ if log_ is not None else events
        self.tracer = tracer_ if tracer_ is not None else tracer
        self.timeline = timeline
        self.blackbox = blackbox
        self.fleet = fleet
        self.slo = slo
        self.serve = serve
        self.receipts = receipts
        self._server: Optional[asyncio.base_events.Server] = None
        self.port: Optional[int] = None  # actual bound port once started

    async def start(self) -> "DebugServer":
        self._server = await asyncio.start_server(
            self._handle, self.host, self._want_port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("[DebugSrv] listening on %s:%d", self.host, self.port)
        return self

    async def aclose(self) -> None:
        if self._server is not None:
            self._server.close()
            with contextlib.suppress(Exception):
                await self._server.wait_closed()
            self._server = None

    async def __aenter__(self) -> "DebugServer":
        return await self.start()

    async def __aexit__(self, *exc) -> None:
        await self.aclose()

    # -- request handling -----------------------------------------------------

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            line = await asyncio.wait_for(
                reader.readline(), timeout=_HEADER_TIMEOUT
            )
            if not line or len(line) > _MAX_REQUEST_LINE:
                return
            parts = line.decode("latin-1").split()
            if len(parts) < 2:
                return
            method, target = parts[0], parts[1]
            # drain request headers (ignored; no bodies on GET)
            while True:
                hdr = await asyncio.wait_for(
                    reader.readline(), timeout=_HEADER_TIMEOUT
                )
                if hdr in (b"\r\n", b"\n", b""):
                    break
            if method != "GET":
                self._respond(writer, 405, {"error": "method not allowed"})
            else:
                self._route(writer, target)
            with contextlib.suppress(Exception):
                await writer.drain()
        except (asyncio.TimeoutError, ConnectionError, OSError):
            pass
        except Exception:  # a handler bug must not kill the server
            log.exception("[DebugSrv] request failed")
            with contextlib.suppress(Exception):
                self._respond(writer, 500, {"error": "internal error"})
        finally:
            with contextlib.suppress(Exception):
                writer.close()

    def _route(self, writer: asyncio.StreamWriter, target: str) -> None:
        url = urlsplit(target)
        path = url.path
        params = parse_qs(url.query)

        def qint(name: str, default: int, cap: int = 4096) -> int:
            try:
                return max(0, min(cap, int(params[name][0])))
            except (KeyError, ValueError, IndexError):
                return default

        if path == "/":
            self._respond(
                writer, 200,
                {"server": "tpunode-debugsrv", "endpoints": ENDPOINTS},
            )
        elif path == "/metrics":
            self._respond_text(writer, 200, self.registry.render_prometheus())
        elif path == "/health":
            body = self.health() if self.health is not None else {"ok": True}
            self._respond(writer, 200, body)
        elif path == "/stats" and self.stats is not None:
            self._respond(writer, 200, self.stats())
        elif path == "/events":
            typ = params.get("type", [None])[0]
            since = qint("since", -1, cap=(1 << 62))
            if since >= 0:
                # cursor mode: only events with seq > since (the poller
                # remembers the newest seq it saw); ?type= filtering is
                # a ring-tail view, not a cursor — they do not combine
                evs = self.log.tail_since(since, qint("n", 100))
            else:
                evs = self.log.tail(qint("n", 100), type=typ)
            self._respond(
                writer,
                200,
                {
                    "events": evs,
                    "counts": self.log.counts(),
                    "seq": self.log.seq(),
                },
            )
        elif path == "/traces":
            n = qint("n", 16, cap=256)
            self._respond(
                writer,
                200,
                {
                    "recent": self.tracer.recent_traces(n),
                    "slowest": self.tracer.slowest(n),
                },
            )
        elif path == "/mempool":
            if self.mempool is not None:
                self._respond(writer, 200, self.mempool())
            else:
                self._respond(writer, 200, {"enabled": False})
        elif path == "/timeseries":
            if self.timeline is None:
                self._respond(writer, 200, {"enabled": False})
            else:
                name = params.get("name", [None])[0]
                if name is None:
                    body = dict(self.timeline.stats())
                    body["series_names"] = self.timeline.names()
                    self._respond(writer, 200, body)
                else:
                    tier = qint("tier", 0, cap=16)
                    since = qint("since", 0, cap=(1 << 62))
                    self._respond(
                        writer,
                        200,
                        {
                            "name": name,
                            "tier": tier,
                            "points": self.timeline.series(
                                name, tier=tier, since=float(since)
                            ),
                        },
                    )
        elif path == "/fleet":
            now = self.fleet() if self.fleet is not None else None
            history = (
                self.timeline.fleet_history()
                if self.timeline is not None
                else {}
            )
            self._respond(writer, 200, {"now": now, "history": history})
        elif path == "/flightrecords":
            if self.blackbox is None:
                self._respond(writer, 200, {"enabled": False})
            else:
                self._respond(
                    writer,
                    200,
                    {
                        "records": self.blackbox.records(qint("n", 16)),
                        "stats": self.blackbox.stats(),
                    },
                )
        elif path == "/slo":
            if self.slo is not None:
                self._respond(writer, 200, self.slo())
            else:
                self._respond(writer, 200, {"enabled": False})
        elif path == "/serve":
            if self.serve is not None:
                self._respond(writer, 200, self.serve())
            else:
                self._respond(writer, 200, {"enabled": False})
        elif path == "/receipts":
            if self.receipts is None:
                self._respond(writer, 200, {"enabled": False})
            else:
                start = qint("start", 0, cap=(1 << 62))
                self._respond(
                    writer,
                    200,
                    {
                        "records": self.receipts.records(
                            start=start, limit=qint("n", 64, cap=1024)
                        ),
                        "stats": self.receipts.stats(),
                    },
                )
        else:
            self._respond(
                writer,
                404,
                {
                    "error": f"no such endpoint: {path}",
                    "endpoints": list(ENDPOINTS),
                },
            )

    _STATUS = {
        200: "OK",
        404: "Not Found",
        405: "Method Not Allowed",
        500: "Internal Server Error",
    }

    def _respond_text(
        self,
        writer: asyncio.StreamWriter,
        status: int,
        body: str,
        ctype: str = "text/plain; version=0.0.4; charset=utf-8",
    ) -> None:
        data = body.encode("utf-8")
        head = (
            f"HTTP/1.1 {status} {self._STATUS.get(status, '?')}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(data)}\r\n"
            "Connection: close\r\n\r\n"
        )
        writer.write(head.encode("latin-1") + data)

    def _respond(
        self, writer: asyncio.StreamWriter, status: int, body: dict
    ) -> None:
        self._respond_text(
            writer,
            status,
            json.dumps(body, default=str),
            ctype="application/json; charset=utf-8",
        )
