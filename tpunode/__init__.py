"""tpunode — a TPU-native peer-to-peer node framework.

A from-scratch framework with the capabilities of ``haskoin/haskoin-node``
(reference mounted read-only at /root/reference; design blueprint in
SURVEY.md): a Bitcoin / Bitcoin Cash P2P library that maintains a validated
block-header chain in a persistent key-value store, manages a fleet of peers
(handshake, discovery, health, supervised lifecycle) and offers a
request/response API for fetching blocks and transactions — plus a batch
secp256k1 ECDSA verification engine on the block/mempool ingest path whose
hot path runs on TPU (``tpunode.verify``, landing with the verify milestone;
see SURVEY.md §7 step 7).

Public surface mirrors the reference's single exposed module
(``Haskoin.Node`` re-exporting Peer/PeerMgr/Chain; reference
src/Haskoin/Node.hs:10-19).
"""

from .actors import LinkedTasks, Mailbox, Publisher, Supervisor
from .debugsrv import DebugServer
from .events import EventLog, StatsReporter, events
from .metrics import Histogram, Metrics, metrics
from .tracectx import Trace, Tracer, start_trace, tracer
from .watchdog import Watchdog, WatchdogConfig
from .chain import (
    Chain,
    ChainBestBlock,
    ChainConfig,
    ChainEvent,
    ChainSynced,
)
from .headers import (
    BadHeaders,
    BlockNode,
    block_locator,
    connect_blocks,
    genesis_node,
    get_ancestor,
    get_parents,
    median_time_past,
    next_work_required,
    split_point,
)
from .node import (
    IbdConfig,
    Node,
    NodeConfig,
    TxVerdict,
    VerifyShed,
    tcp_connect,
)
from .params import (
    BCH,
    BCH_REGTEST,
    BCH_TEST,
    BTC,
    BTC_REGTEST,
    BTC_TEST,
    NETWORKS,
    Network,
)
from .peer import (
    Peer,
    PeerConfig,
    PeerConnected,
    PeerDisconnected,
    PeerError,
    PeerEvent,
    PeerMessage,
    get_blocks,
    get_data,
    get_txs,
    ping_peer,
)
from .peermgr import (
    OnlinePeer,
    PeerMgr,
    PeerMgrConfig,
    build_version,
    to_host_service,
    to_sock_addr,
)
from .store import (
    LogKV,
    MemoryKV,
    Namespaced,
    StoreVersionError,
    open_store,
)
from .utxo import UtxoStore
from .sighash import bip143_sighash, bip341_sighash, legacy_sighash
from .txverify import (
    ExtractStats,
    SigItem,
    combine_verdicts,
    extract_sig_items,
    intra_block_prevouts,
    is_p2tr,
    msig_match,
    wants_amount,
)
from .wire import (
    Block,
    BlockHeader,
    InvType,
    InvVector,
    LazyBlock,
    LazyTx,
    NetworkAddress,
    Tx,
    build_merkle_root,
)

__version__ = "0.1.0"
