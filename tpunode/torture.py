"""Kill-torture harness: prove the storage layer's crash invariants
(ISSUE 9).

A *writer child* process runs a deterministic workload against a
:class:`~tpunode.store.LogKV` (fsync on) + :class:`~tpunode.utxo.UtxoStore`
and records every **acked** write — a write is acked only after
``write_batch`` returned, i.e. after the fsync — to a sidecar ack log.
A seeded chaos plan (``TPUNODE_CHAOS``) kills the child with
``os._exit`` at one precise injection point (``store.append`` /
``store.rotate`` / ``store.compact`` × ``after=N``), or damages the
bytes in flight (``torn_write``, ``bit_flip``).  The parent then reopens
the store and asserts the recovery invariants:

* **acked ⇒ durable** — every acked write is present with its exact
  value (crash mode; a ``bit_flip`` run simulates media corruption, the
  one case where acked bytes may be legitimately lost — *detected and
  quarantined*, below);
* **no corrupt bytes as data** — every value the reopened store returns
  parses and digest-validates; injected corruption must raise the
  ``store.corruption`` count, never leak through ``get``;
* **watermark monotone** — the UTXO watermark after reopen is at least
  the last acked height and never moves backward across reopens;
* a clean kill (no byte damage) must replay **silently**: a crash that
  produces a ``store.corruption`` event is itself a violation (a torn
  tail is not corruption).

The sweep walks ``after=0,1,2,...`` per point until a child run
completes without crashing (the point's hit space is exhausted), giving
a dense set of *distinct* seeded kill points across the append, rotate
and compact paths.  tests/test_store_recovery.py runs the acceptance
sweep (≥200 kill points, slow tier) and a smoke subset in tier-1;
``bench.py --recovery`` reports the pass-rate as a tracked number.

Child entry point::

    python -m tpunode.torture --child --dir D --ops N --seg-bytes B \
        --compact-every C --seed S     # plan via TPUNODE_CHAOS
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import random
import struct
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Optional

from .chaos import CRASH_EXIT, chaos
from .metrics import metrics
from .store import LogKV, Namespaced, put_op
from .utxo import UtxoStore

__all__ = [
    "CRASH_EXIT",
    "TortureResult",
    "child_workload",
    "run_child",
    "sweep",
    "verify_dir",
]

_DATA_NS = b"d/"
_UTXO_NS = b"u/"
_ACK_FILE = "acks.log"
_STORE_FILE = "kv.log"
_DIGEST_LEN = 12
_VER = struct.Struct("<I")


# ---------------------------------------------------------------------------
# deterministic workload values

def make_value(key: bytes, ver: int) -> bytes:
    """Self-validating value: version + keyed digest + deterministic pad.
    Any byte damage that survives into a returned value fails
    :func:`check_value` — 'never corrupt bytes as data' is checkable."""
    d = hashlib.sha256(key + _VER.pack(ver)).digest()
    pad = (ver * 7919 + len(key)) % 160
    return _VER.pack(ver) + d[:_DIGEST_LEN] + (d * 6)[:pad]


def check_value(key: bytes, raw: bytes) -> Optional[int]:
    """The version ``raw`` encodes for ``key``, or None if it is not a
    value this workload could ever have written (i.e. corrupt)."""
    if len(raw) < _VER.size + _DIGEST_LEN:
        return None
    ver = _VER.unpack_from(raw)[0]
    return ver if raw == make_value(key, ver) else None


def _fake_txid(height: int) -> bytes:
    return hashlib.sha256(b"blk" + _VER.pack(height)).digest()


# ---------------------------------------------------------------------------
# the writer child

def child_workload(
    dirpath: str,
    ops: int = 60,
    seg_bytes: int = 1600,
    compact_every: int = 25,
    seed: int = 1,
) -> dict:
    """The deterministic writer: puts/overwrites/deletes on a small key
    set (dead bytes accrue → compaction is real), periodic explicit
    compactions, and UTXO block applies with an advancing watermark.
    Every completed (= fsynced) write is acked to the sidecar log BEFORE
    the next operation, so the parent knows exactly what the store
    promised.  Returns a summary dict (only reached when no fault
    killed the process)."""
    store = LogKV(
        os.path.join(dirpath, _STORE_FILE),
        fsync=True,
        segment_bytes=seg_bytes,
    )
    utxo = UtxoStore(Namespaced(store, _UTXO_NS))
    data = Namespaced(store, _DATA_NS)
    ack_fd = os.open(
        os.path.join(dirpath, _ACK_FILE),
        os.O_WRONLY | os.O_CREAT | os.O_APPEND,
        0o644,
    )

    def ack(line: str) -> None:
        # one write syscall per line: survives os._exit (page cache), and
        # a torn final line is ignored by the parser
        os.write(ack_fd, (line + "\n").encode())

    rng = random.Random(seed)
    versions: dict[bytes, int] = {}
    height = utxo.height
    acked = 0
    for n in range(ops):
        roll = rng.random()
        if roll < 0.10 and versions:
            key = rng.choice(sorted(versions))
            ver = versions[key] + 1
            versions[key] = ver
            data.delete(key)
            ack(f"D {key.decode()} {ver}")
        elif roll < 0.25:
            height += 1
            txid = _fake_txid(height)
            utxo.apply(
                height,
                txid,
                spends=[(_fake_txid(height - 1), 0)] if height > 0 else [],
                creates=[(txid, 0, 5000 + height, b"\x51" * 4)],
            )
            ack(f"W {height}")
        else:
            key = f"k{rng.randrange(12)}".encode()
            ver = versions.get(key, 0) + 1
            versions[key] = ver
            data.put(key, make_value(key, ver))
            ack(f"P {key.decode()} {ver}")
        acked += 1
        if compact_every and (n + 1) % compact_every == 0:
            store.compact()
            ack("C")
    store.close()
    os.close(ack_fd)
    return {"acked": acked, "chaos": chaos.stats()["faults"]}


def parse_acks(dirpath: str) -> dict:
    """Parse the ack log (ignoring a torn final line): per-key last acked
    (op, version), plus the last acked UTXO height."""
    last: dict[bytes, tuple[str, int]] = {}
    wm = -1
    path = os.path.join(dirpath, _ACK_FILE)
    if not os.path.exists(path):
        return {"keys": last, "watermark": wm}
    with open(path, "rb") as f:
        raw = f.read()
    for line in raw.split(b"\n")[:-1]:  # last element: torn or empty
        parts = line.decode("latin-1").split()
        if not parts:
            continue
        if parts[0] in ("P", "D") and len(parts) == 3:
            last[parts[1].encode()] = (parts[0], int(parts[2]))
        elif parts[0] == "W" and len(parts) == 2:
            wm = int(parts[1])
    return {"keys": last, "watermark": wm}


# ---------------------------------------------------------------------------
# the verifying parent

def verify_dir(dirpath: str, mode: str = "crash") -> list[str]:
    """Reopen the store and check every invariant; returns violations
    (empty = pass).  ``mode='crash'`` (kill only, bytes intact) demands
    acked ⇒ present and a silent replay; ``mode='bitflip'`` (simulated
    media corruption) demands detection instead of presence."""
    violations: list[str] = []
    acks = parse_acks(dirpath)
    corrupt0 = metrics.get("store.corruption")
    try:
        store = LogKV(os.path.join(dirpath, _STORE_FILE))
    except Exception as e:  # a reopen that cannot complete is a violation
        return [f"reopen failed: {type(e).__name__}: {e}"]
    corrupt_delta = metrics.get("store.corruption") - corrupt0
    try:
        data = Namespaced(store, _DATA_NS)
        utxo = UtxoStore(Namespaced(store, _UTXO_NS))
        # 1) no corrupt bytes as data — every surviving value validates
        for key, raw in data.scan_prefix(b"k"):
            if check_value(key, raw) is None:
                violations.append(f"corrupt value surfaced for {key!r}")
        # 2) acked ⇒ durable (crash mode only: bit_flip may legitimately
        #    lose acked records — but loudly, see 4)
        if mode == "crash":
            for key, (op, ver) in acks["keys"].items():
                raw = data.get(key)
                if raw is not None:
                    got = check_value(key, raw)
                    if got is None:
                        violations.append(f"corrupt value for {key!r}")
                        continue
                if op == "P":
                    if raw is None:
                        violations.append(
                            f"acked put lost: {key!r} v{ver}"
                        )
                    elif got < ver:
                        violations.append(
                            f"stale value for {key!r}: v{got} < acked v{ver}"
                        )
                elif op == "D" and raw is not None and got <= ver:
                    violations.append(
                        f"acked delete lost: {key!r} resurfaced v{got}"
                    )
            if corrupt_delta:
                violations.append(
                    "clean kill replayed as corruption "
                    f"({int(corrupt_delta)} store.corruption events)"
                )
        # 3) watermark monotone and never behind the ack
        if mode == "crash" and utxo.height < acks["watermark"]:
            violations.append(
                f"watermark {utxo.height} < acked {acks['watermark']}"
            )
        wm_first = utxo.height
        store.close()
        store = LogKV(os.path.join(dirpath, _STORE_FILE))
        utxo2 = UtxoStore(Namespaced(store, _UTXO_NS))
        if utxo2.height < wm_first:
            violations.append(
                f"watermark moved backward: {wm_first} -> {utxo2.height}"
            )
    finally:
        store.close()
    return violations


@dataclass
class TortureResult:
    points: int = 0  # distinct seeded kill points that actually fired
    completed: int = 0  # runs where the fault space was exhausted
    corruption_detected: int = 0  # bit_flip runs caught by the CRC
    violations: list[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations


def run_child(
    dirpath: str,
    plan: str,
    *,
    ops: int = 60,
    seg_bytes: int = 1600,
    compact_every: int = 25,
    seed: int = 1,
    timeout: float = 120.0,
) -> "subprocess.CompletedProcess":
    """One writer-child run under ``plan`` (a real subprocess: the kill is
    a real process death, the reopen a real cold start)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["TPUNODE_CHAOS"] = plan
    env.pop("TPUNODE_EVENTS", None)  # no event-sink files from children
    return subprocess.run(
        [
            sys.executable, "-m", "tpunode.torture", "--child",
            "--dir", dirpath, "--ops", str(ops),
            "--seg-bytes", str(seg_bytes),
            "--compact-every", str(compact_every), "--seed", str(seed),
        ],
        cwd=repo,
        env=env,
        capture_output=True,
        timeout=timeout,
    )


def sweep(
    base_dir: str,
    *,
    seeds=(1,),
    points=("store.append", "store.rotate", "store.compact"),
    max_after: int = 10_000,
    ops: int = 60,
    seg_bytes: int = 1600,
    compact_every: int = 25,
    budget_s: Optional[float] = None,
    bit_flips: int = 2,
) -> TortureResult:
    """The full torture sweep: for every (seed, point), kill the child at
    ``after=0,1,2,...`` until a run survives (fault space exhausted),
    verifying the reopened store after EVERY run; then ``bit_flips``
    byte-damage runs per seed that must be *detected*.  ``budget_s``
    bounds wall clock (the bench worker's watchdog discipline) — the
    result reports how far it got, never silently caps coverage."""
    res = TortureResult()
    t0 = time.monotonic()
    run_i = 0

    def out_of_budget() -> bool:
        return budget_s is not None and time.monotonic() - t0 > budget_s

    for seed in seeds:
        # bit-flip detection FIRST: under a wall-clock budget, breadth of
        # evidence (corruption is detected at all) beats depth of the
        # kill-point walk — the walk reports how far it got either way
        for i in range(bit_flips):
            if out_of_budget():
                return res
            run_i += 1
            d = os.path.join(base_dir, f"run{run_i:05d}")
            os.makedirs(d, exist_ok=True)
            # Early flip + NO compaction: the damaged segment must still
            # be on disk at reopen (compaction would rewrite it from the
            # intact in-memory index), and must be SEALED by later
            # rotations — damage behind the active tail is always loud
            # (the tail itself is the one spot physically indistinguishable
            # from a torn write, which replay drops quietly by design).
            after = max(1, ops // 6) + i * max(1, ops // 8)
            plan = f"seed={seed};store.append:bit_flip:after={after},n=1"
            proc = run_child(
                d, plan, ops=ops, seg_bytes=seg_bytes,
                compact_every=0, seed=seed,
            )
            if proc.returncode != 0:
                res.violations.append(
                    f"[{plan}] bit_flip child rc={proc.returncode}"
                )
                continue
            fired = any(
                f["fired"] for f in json.loads(proc.stdout)["chaos"]
            )
            c0 = metrics.get("store.corruption")
            vs = verify_dir(d, "bitflip")
            detected = metrics.get("store.corruption") - c0
            res.violations.extend(f"[{plan}] {v}" for v in vs)
            if fired and not detected:
                res.violations.append(
                    f"[{plan}] flipped bit NOT detected on reopen"
                )
            if fired and detected:
                res.corruption_detected += 1
        for point in points:
            for after in range(max_after):
                if out_of_budget():
                    return res
                run_i += 1
                d = os.path.join(base_dir, f"run{run_i:05d}")
                os.makedirs(d, exist_ok=True)
                plan = f"seed={seed};{point}:crash:after={after}"
                proc = run_child(
                    d, plan, ops=ops, seg_bytes=seg_bytes,
                    compact_every=compact_every, seed=seed,
                )
                if proc.returncode == CRASH_EXIT:
                    res.points += 1
                    res.violations.extend(
                        f"[{plan}] {v}" for v in verify_dir(d, "crash")
                    )
                elif proc.returncode == 0:
                    res.completed += 1
                    res.violations.extend(
                        f"[{plan}] {v}" for v in verify_dir(d, "crash")
                    )
                    break  # point exhausted for this seed
                else:
                    res.violations.append(
                        f"[{plan}] child died rc={proc.returncode}: "
                        f"{proc.stderr.decode(errors='replace')[-300:]}"
                    )
                    break
    return res


# ---------------------------------------------------------------------------
# child entry point

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--child", action="store_true", required=True)
    ap.add_argument("--dir", required=True)
    ap.add_argument("--ops", type=int, default=60)
    ap.add_argument("--seg-bytes", type=int, default=1600)
    ap.add_argument("--compact-every", type=int, default=25)
    ap.add_argument("--seed", type=int, default=1)
    args = ap.parse_args(argv)
    summary = child_workload(
        args.dir,
        ops=args.ops,
        seg_bytes=args.seg_bytes,
        compact_every=args.compact_every,
        seed=args.seed,
    )
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
