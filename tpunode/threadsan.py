"""threadsan: the thread-side twin of asyncsan (ISSUE 18).

asyncsan (PR 3) watches the event loop; this module watches the ~18
``threading.Lock``/``RLock`` instances the node has grown across 12
modules — the group-commit writer, extract pool workers, dispatch
workers, fleet host workers, the flight recorder's synchronous
observers.  PR 14 proved the gap: the ``CircuitBreaker._lock``
self-deadlock (breaker emits ``verify.breaker`` holding its lock, the
recorder's observer re-enters ``stats()`` on the same thread) was only
found because a bench worker *hung*.  threadsan finds that class of bug
before anything hangs:

* **Lock-order cycle detection** — every instrumented acquire while
  other locks are held adds name-level edges to a global lock-order
  graph; the first edge that closes a cycle records a
  ``threadsan.lock_cycle`` finding (both witness stacks attached) the
  moment the *potential* deadlock is created, not when two threads
  finally interleave badly.
* **Reentry detection** — a blocking re-acquire of a non-reentrant lock
  by the thread that already holds it is a guaranteed self-deadlock;
  threadsan records a ``threadsan.lock_reentry`` finding and raises
  :class:`ThreadSanError` instead of hanging (the exact PR 14 bug,
  pinned in tests/test_threadsan.py with the RLock fix reverted).
* **Hold-time + loop-blocking telemetry** — per-lock
  ``threadsan.hold_seconds{lock=}`` histograms, a max-hold watermark for
  bench.py's sanitizers section, and detection of a *blocking* acquire
  that stalls a registered event-loop thread (``threadsan.loop_block``),
  complementing asyncsan's slow-callback attribution.

Off path (the default) an instrumented acquire is two attribute reads
ahead of the raw ``lock.acquire`` — micro-benched <5µs per
acquire/release pair in tests/test_threadsan.py.  Arm it with
``TPUNODE_THREADSAN=1`` (wired into ``Node.__aenter__`` and the test
conftest exactly like asyncsan).

Reporting never happens synchronously under user locks: findings and
counters update in place (guarded by the registry's one sanctioned bare
lock), while events/metrics emission — which would re-enter the very
locks being watched — runs on a short-lived daemon reporter thread with
the per-thread ``busy`` flag set so threadsan never instruments itself.

Import discipline: stdlib-only at module scope (``tpunode.metrics`` and
``tpunode.events`` construct registry locks at import time, so threadsan
must not import them back except lazily inside reporting paths).
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
import traceback
from typing import Optional, Union

__all__ = [
    "enabled",
    "install",
    "lock",
    "rlock",
    "registry",
    "LockRegistry",
    "SanLock",
    "ThreadSanError",
]

log = logging.getLogger("tpunode.threadsan")

#: Default loop-thread blocking-acquire threshold in seconds
#: (``TPUNODE_THREADSAN_BLOCK`` overrides).
LOOP_BLOCK_THRESHOLD = 0.05

#: Frames kept per witness stack.
_MAX_FRAMES = 16

#: Findings kept in the registry (counters keep counting past this).
_MAX_FINDINGS = 64


def enabled() -> bool:
    """True iff the opt-in ``TPUNODE_THREADSAN`` env var is set truthy."""
    return os.environ.get("TPUNODE_THREADSAN", "") not in ("", "0", "false", "no")


def loop_block_threshold() -> float:
    raw = os.environ.get("TPUNODE_THREADSAN_BLOCK", "")
    try:
        return float(raw) if raw else LOOP_BLOCK_THRESHOLD
    except ValueError:
        return LOOP_BLOCK_THRESHOLD


class ThreadSanError(RuntimeError):
    """A guaranteed self-deadlock: blocking acquire of a non-reentrant
    lock by the thread that already holds it.  Raised *instead of*
    hanging, so the bug surfaces as a stack trace, not a stuck worker."""


def _capture_stack(skip: int = 2) -> list[str]:
    """Innermost-first formatted frames of the caller, threadsan frames
    skipped.  Cheap enough for first-witness capture (once per lock
    pair), never on the steady-state path."""
    try:
        frame = sys._getframe(skip)
    except ValueError:  # pragma: no cover - shallow stacks
        return []
    out = []
    for fs in reversed(traceback.extract_stack(frame)):
        out.append(
            f"{os.path.basename(fs.filename)}:{fs.lineno} in {fs.name}"
        )
        if len(out) >= _MAX_FRAMES:
            break
    return out


class _Held:
    """One entry in a thread's held-lock stack."""

    __slots__ = ("lock", "name", "t0", "depth")

    def __init__(self, san: "SanLock", t0: float):
        self.lock = san
        self.name = san.name
        self.t0 = t0
        self.depth = 1


class SanLock:
    """Named instrumented wrapper over ``threading.Lock``/``RLock``.

    Supports the full subset of the lock protocol the tree uses:
    ``acquire(blocking, timeout)``, ``release()``, context manager, and
    ``locked()``.  Disarmed, ``acquire`` is two attribute reads ahead of
    the raw primitive.
    """

    __slots__ = ("_raw", "_reg", "name", "reentrant")

    def __init__(self, name: str, reg: "LockRegistry", reentrant: bool):
        self.name = name
        self._reg = reg
        self.reentrant = reentrant
        self._raw = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not self._reg._armed:
            return self._raw.acquire(blocking, timeout)
        return self._reg._acquire(self, blocking, timeout)

    def release(self) -> None:
        if not self._reg._armed:
            self._raw.release()
            return
        self._reg._release(self)

    def __enter__(self) -> "SanLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        fn = getattr(self._raw, "locked", None)
        if fn is not None:
            return bool(fn())
        return bool(self._raw._is_owned())  # RLock before py3.12

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        kind = "rlock" if self.reentrant else "lock"
        return f"<SanLock {self.name!r} ({kind})>"


class LockRegistry:
    """Global registry of named instrumented locks + the lock-order
    graph and per-thread lockset state that power the detectors."""

    def __init__(self):
        # The ONE sanctioned bare lock in the tree outside test fixtures:
        # it guards threadsan's own graph/finding state and must never be
        # instrumented (it would watch itself).
        self._meta = threading.Lock()
        self._armed = False
        self._epoch = 0
        self._tls = threading.local()
        self._loop_threads: set[int] = set()
        # name -> number of instances constructed under that name
        self._names: dict[str, int] = {}
        # name-level order graph: edge a -> b when b was acquired with a
        # held.  _edge_seen makes the steady-state re-walk O(held) set
        # probes with no witness-stack capture.
        self._edges: dict[str, set[str]] = {}
        self._edge_seen: set[tuple[str, str]] = set()
        self._edge_witness: dict[tuple[str, str], dict] = {}
        self._reported_cycles: set[frozenset] = set()
        self._reported_reentries: set[str] = set()
        self.findings: list[dict] = []
        self.lock_cycles = 0
        self.lock_reentries = 0
        self.loop_blocks = 0
        self.max_hold_seconds = 0.0
        self.last_loop_block: Optional[dict] = None

    # ------------------------------------------------------------------
    # construction / lifecycle

    def lock(self, name: str) -> SanLock:
        """A named non-reentrant lock (wraps ``threading.Lock``)."""
        return self._new(name, reentrant=False)

    def rlock(self, name: str) -> SanLock:
        """A named reentrant lock (wraps ``threading.RLock``)."""
        return self._new(name, reentrant=True)

    def _new(self, name: str, reentrant: bool) -> SanLock:
        with self._meta:
            self._names[name] = self._names.get(name, 0) + 1
        return SanLock(name, self, reentrant)

    def arm(self) -> None:
        """Turn instrumentation on.  Bumps the epoch so held-stack state
        from a previous arming window is discarded per thread."""
        with self._meta:
            self._epoch += 1
            self._armed = True

    def disarm(self) -> None:
        self._armed = False

    def register_loop_thread(self, ident: Optional[int] = None) -> None:
        """Mark a thread (default: current) as an event-loop thread so
        blocking acquires that stall it are reported."""
        self._loop_threads.add(
            threading.get_ident() if ident is None else ident
        )

    def reset(self) -> None:
        """Drop graph + findings + counters (tests)."""
        with self._meta:
            self._epoch += 1
            self._loop_threads.clear()
            self._edges.clear()
            self._edge_seen.clear()
            self._edge_witness.clear()
            self._reported_cycles.clear()
            self._reported_reentries.clear()
            self.findings = []
            self.lock_cycles = 0
            self.lock_reentries = 0
            self.loop_blocks = 0
            self.max_hold_seconds = 0.0
            self.last_loop_block = None

    def snapshot(self) -> dict:
        """Cheap state dump for bench.py's sanitizers section and the
        flight recorder's ``threadsan`` source."""
        with self._meta:
            return {
                "armed": self._armed,
                "locks": len(self._names),
                "edges": len(self._edge_seen),
                "lock_cycles": self.lock_cycles,
                "lock_reentries": self.lock_reentries,
                "loop_blocks": self.loop_blocks,
                "max_hold_ms": round(self.max_hold_seconds * 1000.0, 3),
                "findings": list(self.findings[-8:]),
            }

    # ------------------------------------------------------------------
    # instrumented acquire / release

    def _state(self):
        tls = self._tls
        if getattr(tls, "epoch", None) != self._epoch:
            tls.epoch = self._epoch
            tls.held = []
            tls.busy = False
        return tls

    def _acquire(self, san: SanLock, blocking: bool, timeout: float) -> bool:
        tls = self._state()
        if tls.busy:  # threadsan's own reporting path: stay raw
            return san._raw.acquire(blocking, timeout)
        held = tls.held
        for h in held:
            if h.lock is san:
                if san.reentrant:
                    ok = san._raw.acquire(blocking, timeout)
                    if ok:
                        h.depth += 1
                    return ok
                # Non-reentrant re-acquire by the holding thread: a
                # blocking call can never return.  Report, then raise
                # rather than hang (timeout'd/non-blocking calls are
                # left to fail on their own).
                self._report_reentry(san, tls)
                if blocking and timeout < 0:
                    raise ThreadSanError(
                        f"thread {threading.current_thread().name!r} "
                        f"re-acquired non-reentrant lock {san.name!r} it "
                        "already holds (guaranteed self-deadlock; use "
                        "threadsan.rlock() if reentry is intended)"
                    )
                return san._raw.acquire(blocking, timeout)
        if held:
            self._note_edges(held, san, tls)
        waited = None
        ok = san._raw.acquire(False)
        if not ok:
            if not blocking:
                return False
            t0 = time.perf_counter()
            ok = san._raw.acquire(True, timeout)
            waited = time.perf_counter() - t0
        if not ok:
            return False
        if (
            waited is not None
            and threading.get_ident() in self._loop_threads
            and waited >= loop_block_threshold()
        ):
            self._report_loop_block(san, waited, tls)
        held.append(_Held(san, time.perf_counter()))
        return True

    def _release(self, san: SanLock) -> None:
        tls = self._state()
        if tls.busy:
            san._raw.release()
            return
        held = tls.held
        for i in range(len(held) - 1, -1, -1):
            h = held[i]
            if h.lock is san:
                if h.depth > 1:
                    h.depth -= 1
                    san._raw.release()
                    return
                del held[i]
                dt = time.perf_counter() - h.t0
                san._raw.release()
                self._note_hold(san, dt, tls)
                return
        # Acquired before arming (or on another thread — already a bug
        # the raw primitive will raise on): pass through.
        san._raw.release()

    # ------------------------------------------------------------------
    # lock-order graph

    def _note_edges(self, held: list, san: SanLock, tls) -> None:
        name_b = san.name
        fresh: list[tuple[str, str]] = []
        with self._meta:
            for h in held:
                if h.name == name_b:
                    continue  # same-name siblings (e.g. per-host breakers)
                pair = (h.name, name_b)
                if pair not in self._edge_seen:
                    self._edge_seen.add(pair)
                    fresh.append(pair)
        if not fresh:
            return  # steady state: no witness capture, no graph walk
        stack = _capture_stack(skip=3)
        thread = threading.current_thread().name
        cycles: list[dict] = []
        with self._meta:
            for a, b in fresh:
                # A path b ->* a through existing edges means adding
                # a -> b closes a cycle: two threads CAN deadlock.
                path = self._find_path(b, a)
                self._edges.setdefault(a, set()).add(b)
                self._edge_witness[(a, b)] = {
                    "thread": thread,
                    "stack": stack,
                }
                if path is None:
                    continue
                chain = [a] + path  # a -> b -> ... -> a
                key = frozenset(chain)
                if key in self._reported_cycles:
                    continue
                self._reported_cycles.add(key)
                witnesses = {}
                for x, y in zip(path, path[1:]):
                    w = self._edge_witness.get((x, y))
                    if w is not None:
                        witnesses[f"{x}->{y}"] = w
                finding = {
                    "kind": "cycle",
                    "chain": chain,
                    "edge": f"{a}->{b}",
                    "thread": thread,
                    "stack": stack,
                    "witnesses": witnesses,
                }
                self.lock_cycles += 1
                if len(self.findings) < _MAX_FINDINGS:
                    self.findings.append(finding)
                cycles.append(finding)
        for finding in cycles:
            log.error(
                "threadsan: lock-order cycle %s (first witness: %s)",
                " -> ".join(finding["chain"]),
                finding["thread"],
            )
            self._emit(
                "threadsan.lock_cycle",
                {
                    "chain": finding["chain"],
                    "edge": finding["edge"],
                    "thread": finding["thread"],
                    "stack": finding["stack"][:8],
                    "witnesses": {
                        k: w["stack"][:8]
                        for k, w in finding["witnesses"].items()
                    },
                },
                "threadsan.lock_cycles",
            )

    def _find_path(self, src: str, dst: str) -> Optional[list[str]]:
        """DFS: a path src -> ... -> dst through the order graph, or
        None.  Returned list starts at src and ends at dst."""
        if src == dst:
            return [src]
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            for nxt in self._edges.get(node, ()):
                if nxt == dst:
                    return path + [nxt]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # ------------------------------------------------------------------
    # findings + telemetry

    def _report_reentry(self, san: SanLock, tls) -> None:
        stack = _capture_stack(skip=3)
        thread = threading.current_thread().name
        with self._meta:
            self.lock_reentries += 1
            first = san.name not in self._reported_reentries
            if first:
                self._reported_reentries.add(san.name)
                if len(self.findings) < _MAX_FINDINGS:
                    self.findings.append(
                        {
                            "kind": "reentry",
                            "lock": san.name,
                            "thread": thread,
                            "stack": stack,
                        }
                    )
        log.error(
            "threadsan: non-reentrant lock %r re-acquired by holding "
            "thread %r",
            san.name,
            thread,
        )
        if first:
            self._emit(
                "threadsan.lock_reentry",
                {"lock": san.name, "thread": thread, "stack": stack[:8]},
                "threadsan.lock_reentries",
            )

    def _report_loop_block(self, san: SanLock, waited: float, tls) -> None:
        info = {
            "lock": san.name,
            "waited_seconds": round(waited, 4),
            "thread": threading.current_thread().name,
            "stack": _capture_stack(skip=3)[:8],
        }
        with self._meta:
            self.loop_blocks += 1
            self.last_loop_block = info
        log.warning(
            "threadsan: blocking acquire of %r stalled loop thread for "
            "%.1fms",
            san.name,
            waited * 1000.0,
        )
        self._emit("threadsan.loop_block", info, "threadsan.loop_blocks")

    def _note_hold(self, san: SanLock, dt: float, tls) -> None:
        if dt > self.max_hold_seconds:
            self.max_hold_seconds = dt
        tls.busy = True
        try:
            from .metrics import metrics

            metrics.observe(
                "threadsan.hold_seconds", dt, labels={"lock": san.name}
            )
        except Exception:  # pragma: no cover - metrics must never break locks
            pass
        finally:
            tls.busy = False

    def _emit(self, event_type: str, fields: dict, counter: str) -> None:
        """Emit the finding's event + metric from a one-shot daemon
        thread.  Synchronous emission would run the flight recorder's
        observers (which re-enter engine/metrics locks) while the caller
        may be holding the very locks being reported — the exact shape
        of bug threadsan exists to catch."""

        def run() -> None:
            tls = self._state()
            tls.busy = True
            try:
                from .events import events
                from .metrics import metrics

                metrics.inc(counter)
                events.emit(event_type, **fields)
            except Exception:  # pragma: no cover
                log.debug("threadsan: report emission failed", exc_info=True)

        threading.Thread(
            target=run, name="threadsan-report", daemon=True
        ).start()


#: Process-wide registry.  Module-level so every subsystem's locks share
#: one order graph regardless of construction order.
registry = LockRegistry()


def lock(name: str) -> SanLock:
    """A named non-reentrant lock on the global registry."""
    return registry.lock(name)


def rlock(name: str) -> SanLock:
    """A named reentrant lock on the global registry."""
    return registry.rlock(name)


def install() -> None:
    """Arm the global registry and register the calling thread as an
    event-loop thread.  Called from ``Node.__aenter__`` and the test
    conftest when :func:`enabled` — idempotent."""
    registry.arm()
    registry.register_loop_thread()
    log.info(
        "threadsan armed: %d named locks, loop-block threshold %.0fms",
        len(registry._names),
        loop_block_threshold() * 1000.0,
    )
