"""servesrv — multi-tenant verification-as-a-service (ISSUE 20).

ROADMAP item 3: the node's batch verify engine, leased over the network
to registered **tenants** — the permissioned-blockchain shape of
PAPERS.md's arXiv:2112.02229, where one shared ECDSA verify pipeline
serves many validators.  This module is the traffic-facing layer on top
of substrates that already exist separately:

* **Wire API** — a zero-dep asyncio TCP server (debugsrv-style;
  ``NodeConfig.serve_port``, default off) speaking length-prefixed JSON
  frames.  A frame authenticates a registered tenant (name + shared
  token) and submits either pre-extracted signature rows
  (``(digest, pubkey, sig)``) or raw transaction bytes; every frame
  gets exactly one explicit reply — verdicts, a throttle, or a shed
  error.  Nothing is ever silently dropped (the mempool's verdict
  contract, applied to the network edge).
* **Quota admission** — per-tenant token bucket (sigs/sec + burst) and
  max-inflight-items cap, both from :class:`TenantConfig`.  An
  over-quota frame is answered with ``error=throttled`` (+
  ``retry_after``) and costs zero verify work.
* **QoS shedding** — when the node's own SLO evaluator reports a
  fast-window burn (slo.py), admission sheds the lowest
  priority-class tenants first (never ``block``-class), with explicit
  per-frame error verdicts and ``serve.shed{tenant=,reason=}``
  accounting — the verify engine's headroom goes to the classes whose
  SLOs are burning.
* **Shared verdict-cache tier** — the mempool's extracted seen/verdict
  LRU (seenlru.py) mounted service-wide: Zipf-skewed duplicate
  submissions across tenants hit the cache (or coalesce onto the
  in-flight future of the first submitter) and cost zero TPU work,
  with per-tenant hit accounting (``serve.cache_hits{tenant=}``).
* **Cost attribution** — submissions carry ``tenant=`` through the
  packer into the engine's :class:`~tpunode.verify.engine.CostLedger`,
  so ``stats()["serve"]`` reports per-tenant charged rung seconds under
  the same conservation pin as the per-class ledger (ISSUE 17).
* **Verdict receipts** — every dispatched batch appends a hash-chained
  receipt (receipts.py) binding the batch digest, verdict digest,
  kernel mode tuple and serving rung, so tenants can audit the service
  offline without re-verifying.

The tenant registry is bounded (``MAX_TENANTS``) and
:func:`tenant_names` is the canonical — analyzer-allowlisted — source
of ``tenant=`` label values, exactly like ``sched.host_names`` for
``host=`` (PR 19's label-cardinality rule).

Single-threaded: all state lives on the event loop (the asyncio server
callbacks); nothing here takes locks.
"""

from __future__ import annotations

import asyncio
import contextlib
import hashlib
import hmac
import json
import logging
import sys
import time
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from .events import events
from .metrics import metrics
from .seenlru import SeenLru
from .txverify import extract_sig_items
from .util import double_sha256
from .verify.ecdsa_cpu import decode_pubkey
from .verify.sched import PRIORITIES
from .wire import Reader, Tx

__all__ = ["TenantConfig", "ServeServer", "tenant_names", "MAX_TENANTS"]

log = logging.getLogger("tpunode.serve")

#: Hard bound on the tenant registry: the ``tenant=`` label set (and the
#: per-tenant state table) must stay small by construction.
MAX_TENANTS = 64

_MAX_FRAME = 8 << 20  # wire frame byte cap (pre-parse bound)
_MAX_ITEMS = 8192  # items per frame (one packer lane's worth of slack)

#: Default service-wide verdict-cache entries.
DEFAULT_CACHE = 65536

metrics.describe("serve.frames", "wire frames received per tenant")
metrics.describe("serve.items", "signature items submitted per tenant")
metrics.describe(
    "serve.cache_hits",
    "items served from the shared verdict cache (zero verify work)",
)
metrics.describe("serve.shed", "items shed under SLO burn per tenant")
metrics.describe("serve.throttled", "items refused by quota admission")
metrics.describe("serve.verified", "items dispatched to the verify engine")
metrics.describe(
    "serve.latency", "frame admission->reply latency per tenant (seconds)"
)


def tenant_names(tenants) -> list:
    """Canonical tenant-name list for a registry (configs or plain
    names), validating the bound.  Owned HERE — next to the server that
    keys its state tables and its ``tenant=`` metric labels by these
    strings: the analyzer's label-cardinality rule allowlists this as
    the bounded source for ``tenant=`` label values (exactly like
    ``sched.host_names`` for ``host=``), which is only sound because
    every name must pass this validator to be registered at all."""
    names: list = []
    for t in tenants:
        name = t if isinstance(t, str) else t.name
        if (
            not name
            or len(name) > 32
            or not all(c.isalnum() or c in "_-" for c in name)
        ):
            raise ValueError(f"invalid tenant name {name!r}")
        if name in names:
            raise ValueError(f"duplicate tenant name {name!r}")
        names.append(name)
    if len(names) > MAX_TENANTS:
        raise ValueError(
            f"{len(names)} tenants exceeds MAX_TENANTS={MAX_TENANTS}"
        )
    return names


@dataclass(frozen=True)
class TenantConfig:
    """One registered tenant: identity, lane mapping, and quota."""

    name: str
    token: str  # shared-secret auth token (compared constant-time)
    priority: str = "bulk"  # packer lane: block > mempool > ibd > bulk
    rate: float = 5000.0  # token-bucket refill, signature items / second
    burst: float = 10000.0  # token-bucket depth, items
    max_inflight: int = 8192  # items in the engine at once

    def __post_init__(self):
        if self.priority not in PRIORITIES:
            raise ValueError(
                f"tenant {self.name!r}: unknown priority "
                f"{self.priority!r}: one of {PRIORITIES}"
            )


class _TokenBucket:
    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.stamp = time.monotonic()

    def take(self, n: int, now: Optional[float] = None) -> float:
        """Try to spend ``n`` tokens.  Returns 0.0 on success, else the
        seconds until ``n`` tokens will have refilled (the throttle
        reply's ``retry_after``) — nothing is spent on refusal."""
        if now is None:
            now = time.monotonic()
        self.tokens = min(self.burst, self.tokens + (now - self.stamp) * self.rate)
        self.stamp = now
        if n <= self.tokens:
            self.tokens -= n
            return 0.0
        if self.rate <= 0:
            return float("inf")
        return (n - self.tokens) / self.rate


class _TenantState:
    __slots__ = (
        "cfg", "bucket", "inflight", "frames", "items", "cache_hits",
        "verified", "shed", "throttled",
    )

    def __init__(self, cfg: TenantConfig):
        self.cfg = cfg
        self.bucket = _TokenBucket(cfg.rate, cfg.burst)
        self.inflight = 0  # items currently in the engine
        self.frames = 0
        self.items = 0
        self.cache_hits = 0
        self.verified = 0
        self.shed = 0
        self.throttled = 0

    def snapshot(self) -> dict:
        return {
            "priority": self.cfg.priority,
            "frames": self.frames,
            "items": self.items,
            "cache_hits": self.cache_hits,
            "verified": self.verified,
            "shed": self.shed,
            "throttled": self.throttled,
            "inflight": self.inflight,
            "tokens": round(self.bucket.tokens, 1),
        }


def _kernel_modes_now() -> tuple:
    """The device kernel's mode tuple when the device kernel is actually
    in play, else a marker.  Gated on the module being imported — the
    cpu/oracle rungs never touch it, and importing it pulls in jax
    (which the serve bench's cpu-proxy worker must never do)."""
    k = sys.modules.get("tpunode.verify.kernel")
    if k is None:
        return ("no-device-kernel",)
    try:
        return tuple(k.kernel_modes())
    except Exception:  # modes must never fail a verify reply
        return ("kernel-modes-error",)


def _parse_row(row) -> tuple:
    """One pre-extracted wire row ``[digest_hex, pubkey_hex, sig_hex]``
    (sig = 64-byte compact r||s) to a VerifyItem tuple.  Malformed rows
    become the degenerate ``(None, 0, 0, 0)`` item — an explicit False
    verdict, never a dropped one (the engine's own contract for
    undecodable keys)."""
    try:
        digest = bytes.fromhex(row[0])
        pub = bytes.fromhex(row[1])
        sig = bytes.fromhex(row[2])
        if len(digest) != 32 or len(sig) != 64:
            return (None, 0, 0, 0)
        q = decode_pubkey(pub)
        if q is None:
            return (None, 0, 0, 0)
        return (
            q,
            int.from_bytes(digest, "big"),
            int.from_bytes(sig[:32], "big"),
            int.from_bytes(sig[32:], "big"),
        )
    except (ValueError, TypeError, IndexError):
        return (None, 0, 0, 0)


class ServeServer:
    """The verification service: TCP front, quota admission, shared
    verdict cache, receipts.  Lifecycle mirrors DebugServer::

        async with ServeServer(engine, tenants, port=0) as srv:
            ...  # connect to 127.0.0.1:{srv.port}

    ``slo_burning`` is the shed signal — a callable returning the list
    of SLOs burning in the fast window (``SloEvaluator.burning``); None
    disables shedding.  ``receipts`` is an optional
    :class:`~tpunode.receipts.ReceiptLog`.
    """

    def __init__(
        self,
        engine,
        tenants: Sequence[TenantConfig],
        port: int = 0,
        host: str = "127.0.0.1",
        slo_burning: Optional[Callable[[], list]] = None,
        receipts=None,
        cache_entries: int = DEFAULT_CACHE,
    ):
        self._engine = engine
        self._slo_burning = slo_burning
        self._receipts = receipts
        self._want_port = port
        self.host = host
        self.port: Optional[int] = None
        self._server: Optional[asyncio.base_events.Server] = None
        # registry keys come from the ONE bounded source of tenant=
        # label values (tenant_names — analyzer-pinned); cfg order is
        # registration order
        self._tenants: "dict[str, _TenantState]" = {}
        cfgs = list(tenants)
        for tname in tenant_names(cfgs):
            for cfg in cfgs:
                if cfg.name == tname:
                    self._tenants[tname] = _TenantState(cfg)
        # shared verdict-cache tier: key -> asyncio.Future[bool].  An
        # unresolved future IS the in-flight marker — duplicates
        # coalesce on it (exactly one verify per unique item), and the
        # LRU pins it against eviction exactly like the mempool pins
        # PENDING entries (same extracted structure, same 2x ceiling).
        self._cache: SeenLru = SeenLru(
            max(1, cache_entries), pinned=lambda f: not f.done()
        )
        self._conns = 0

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> "ServeServer":
        self._server = await asyncio.start_server(
            self._handle, self.host, self._want_port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("[Serve] listening on %s:%d (%d tenants)",
                 self.host, self.port, len(self._tenants))
        return self

    async def aclose(self) -> None:
        if self._server is not None:
            self._server.close()
            with contextlib.suppress(Exception):
                await self._server.wait_closed()
            self._server = None
        # labeled-series lifecycle (ISSUE 19): retire this service's
        # tenant= series so a churned registry can't grow the registry
        for tname in tenant_names(st.cfg for st in self._tenants.values()):
            metrics.drop_label("tenant", tname)

    async def __aenter__(self) -> "ServeServer":
        return await self.start()

    async def __aexit__(self, *exc) -> None:
        await self.aclose()

    # -- wire ----------------------------------------------------------------

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self._conns += 1
        try:
            while True:
                try:
                    hdr = await reader.readexactly(4)
                except (asyncio.IncompleteReadError, ConnectionError):
                    return
                size = int.from_bytes(hdr, "big")
                if size > _MAX_FRAME:
                    self._send(writer, {"ok": False, "error": "frame-too-large"})
                    return
                body = await reader.readexactly(size)
                try:
                    frame = json.loads(body)
                    if not isinstance(frame, dict):
                        raise ValueError("frame must be an object")
                except ValueError as e:
                    self._send(writer, {
                        "ok": False, "error": f"bad-frame: {str(e)[:100]}",
                    })
                    return
                reply = await self._handle_frame(frame)
                reply["id"] = frame.get("id")
                self._send(writer, reply)
                await writer.drain()
        except (ConnectionError, OSError, asyncio.IncompleteReadError):
            pass
        except asyncio.CancelledError:
            raise
        except Exception:  # one frame's bug must not kill the service
            log.exception("[Serve] frame handling failed")
            with contextlib.suppress(Exception):
                self._send(writer, {"ok": False, "error": "internal"})
        finally:
            self._conns -= 1
            with contextlib.suppress(Exception):
                writer.close()

    @staticmethod
    def _send(writer: asyncio.StreamWriter, obj: dict) -> None:
        data = json.dumps(obj, separators=(",", ":")).encode()
        writer.write(len(data).to_bytes(4, "big") + data)

    # -- admission + dispatch ------------------------------------------------

    def _shed_class(self) -> Optional[str]:
        """The priority class admission sheds under burn: the LOWEST
        class any registered tenant occupies — but never ``block``
        (live-ingest-equivalent traffic is what shedding protects)."""
        present = {st.cfg.priority for st in self._tenants.values()}
        for p in reversed(PRIORITIES):
            if p in present:
                return p if p != "block" else None
        return None

    async def _handle_frame(self, frame: dict) -> dict:
        t0 = time.monotonic()
        tname = frame.get("tenant")
        st = self._tenants.get(tname) if isinstance(tname, str) else None
        if st is None or not hmac.compare_digest(
            str(frame.get("token", "")), st.cfg.token
        ):
            metrics.inc("serve.auth_failures")
            return {"ok": False, "error": "auth"}
        st.frames += 1
        metrics.inc("serve.frames", labels={"tenant": tname})

        # decode the submission (either pre-extracted rows or raw txs)
        rows = frame.get("items")
        raws = frame.get("raw")
        try:
            keys, items, per_tx = self._decode(rows, raws)
        except ValueError as e:
            return {"ok": False, "error": str(e)[:200]}
        n = len(keys)
        st.items += n
        metrics.inc("serve.items", n, labels={"tenant": tname})
        if n == 0:
            return {"ok": True, "verdicts": []}

        # QoS shed (before any quota spend): under fast-window SLO burn
        # the lowest-class tenants are refused with explicit error
        # verdicts — the mempool's verdict contract at the network edge
        if self._slo_burning is not None:
            burning = self._slo_burning()
            if burning and st.cfg.priority == self._shed_class():
                st.shed += n
                metrics.inc(
                    "serve.shed", n,
                    labels={"tenant": tname, "reason": "slo-burn"},
                )
                events.emit(
                    "serve.shed", tenant=tname, reason="slo-burn",
                    items=n, burning=burning[:4],
                )
                return {
                    "ok": False, "error": "shed", "reason": "slo-burn",
                    "verdicts": [None] * len(per_tx if raws else keys),
                }

        # quota admission: token bucket, then the inflight-items cap —
        # a refusal is an explicit throttle reply, never a silent drop
        retry = st.bucket.take(n, t0)
        if retry > 0.0:
            st.throttled += n
            metrics.inc(
                "serve.throttled", n,
                labels={"tenant": tname, "reason": "rate"},
            )
            return {
                "ok": False, "error": "throttled", "reason": "rate",
                "retry_after": round(min(retry, 3600.0), 4),
            }
        if st.inflight + n > st.cfg.max_inflight:
            st.throttled += n
            metrics.inc(
                "serve.throttled", n,
                labels={"tenant": tname, "reason": "inflight"},
            )
            return {"ok": False, "error": "throttled", "reason": "inflight"}

        # shared verdict-cache pass: resolved futures are free hits,
        # unresolved ones coalesce this frame onto the first submitter's
        # in-flight verify; misses become OUR futures to resolve
        futs: list = []
        fresh_futs: list = []
        fresh_keys: list = []
        fresh_items: list = []
        hits = 0
        for key, item in zip(keys, items):
            fut = self._cache.get(key)
            if fut is not None:
                self._cache.touch(key)
                hits += 1
                futs.append(fut)
                continue
            fut = asyncio.get_running_loop().create_future()
            self._cache.insert(key, fut)
            futs.append(fut)
            fresh_futs.append(fut)
            fresh_keys.append(key)
            fresh_items.append((key, item))
        if hits:
            st.cache_hits += hits
            metrics.inc("serve.cache_hits", hits, labels={"tenant": tname})

        if fresh_items:
            st.inflight += len(fresh_items)
            st.verified += len(fresh_items)
            metrics.inc(
                "serve.verified", len(fresh_items), labels={"tenant": tname}
            )
            try:
                verdicts = await self._engine.verify(
                    [it for _, it in fresh_items],
                    priority=st.cfg.priority,
                    tenant=tname,
                )
            except Exception as e:
                # engine failure: un-cache the keys this frame owns (a
                # retry must re-verify, not inherit a dead future), fail
                # only OUR futures (coalescers on them learn the error;
                # futures owned by other in-flight frames are theirs to
                # resolve) and answer with an explicit error
                for key, _ in fresh_items:
                    self._cache.pop(key)
                err = f"verify-failed: {type(e).__name__}: {e}"[:200]
                for fut in fresh_futs:
                    if not fut.done():
                        fut.set_exception(RuntimeError(err))
                        fut.add_done_callback(lambda f: f.exception())
                return {"ok": False, "error": err}
            finally:
                st.inflight -= len(fresh_items)
            for fut, verdict in zip(fresh_futs, verdicts):
                if not fut.done():
                    fut.set_result(bool(verdict))
            self._append_receipt(fresh_keys, verdicts)

        # gather (ours resolve immediately; coalesced may still wait)
        try:
            flat = [bool(await f) for f in futs]
        except Exception as e:
            return {"ok": False, "error": f"verify-failed: {e}"[:200]}

        if raws:
            # raw-tx form: one verdict per submitted transaction — all
            # of its extracted signatures must pass (inputs that extract
            # nothing contribute nothing, same as the node's own path)
            out = []
            pos = 0
            for count in per_tx:
                # all() over the tx's extracted items — vacuously True
                # for zero extractable signatures, same as the node's
                # own verify-what's-extractable contract
                out.append(all(flat[pos : pos + count]))
                pos += count
        else:
            out = flat
        dt = time.monotonic() - t0
        metrics.observe("serve.latency", dt, labels={"tenant": tname})
        return {"ok": True, "verdicts": out, "cached": hits}

    def _decode(self, rows, raws) -> tuple:
        """Wire submission -> (cache keys, VerifyItem tuples, per-tx item
        counts).  ``per_tx`` is only meaningful for the raw form."""
        if (rows is None) == (raws is None):
            raise ValueError("frame needs exactly one of items=/raw=")
        keys: list = []
        items: list = []
        per_tx: list = []
        if rows is not None:
            if not isinstance(rows, list) or len(rows) > _MAX_ITEMS:
                raise ValueError(f"items must be a list of <= {_MAX_ITEMS}")
            for row in rows:
                if not isinstance(row, (list, tuple)) or len(row) != 3:
                    raise ValueError("item rows are [digest, pubkey, sig]")
                keys.append(
                    hashlib.sha256(
                        "|".join(str(c) for c in row).encode()
                    ).digest()
                )
                items.append(_parse_row(row))
            return keys, items, per_tx
        if not isinstance(raws, list) or len(raws) > _MAX_ITEMS:
            raise ValueError(f"raw must be a list of <= {_MAX_ITEMS}")
        for txhex in raws:
            try:
                raw = bytes.fromhex(txhex)
                tx = Tx.deserialize(Reader(raw))
                sig_items, _stats = extract_sig_items(tx)
            except Exception as e:
                raise ValueError(f"bad raw tx: {str(e)[:100]}")
            base = double_sha256(raw)
            per_tx.append(len(sig_items))
            for i, si in enumerate(sig_items):
                keys.append(hashlib.sha256(base + i.to_bytes(4, "big")).digest())
                items.append(si.verify_item)
        if len(keys) > _MAX_ITEMS:
            raise ValueError(f"raw txs expand past {_MAX_ITEMS} items")
        return keys, items, per_tx

    def _append_receipt(self, fresh_keys: list, verdicts: list) -> None:
        if self._receipts is None:
            return
        batch = hashlib.sha256(b"".join(fresh_keys)).digest()
        vdig = hashlib.sha256(
            bytes(1 if v else 0 for v in verdicts)
        ).digest()
        try:
            self._receipts.append(
                batch, vdig, _kernel_modes_now(),
                getattr(self._engine, "last_rung", "none"),
            )
        except Exception:
            # the receipt log failing must not fail verify replies —
            # but it must be LOUD (a quiet receipt gap is exactly what
            # the chain exists to rule out)
            log.exception("[Serve] receipt append failed")
            events.emit("serve.receipt_error")

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        """The ``stats()["serve"]`` / ``/serve`` endpoint snapshot."""
        ledger = {}
        led = getattr(self._engine, "ledger", None)
        if callable(led):
            snap = led()
            ledger = {
                "busy_seconds": snap.get("busy_seconds", 0.0),
                "charged_seconds": snap.get("charged_seconds", 0.0),
                "by_tenant": snap.get("by_tenant", {}),
            }
        return {
            "port": self.port,
            "connections": self._conns,
            "tenants": {
                tname: st.snapshot() for tname, st in self._tenants.items()
            },
            "cache": {
                "entries": len(self._cache),
                "max_entries": self._cache.max_entries,
            },
            "spend": ledger,
            "receipts": (
                self._receipts.stats() if self._receipts is not None else None
            ),
        }
