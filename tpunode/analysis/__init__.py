"""asyncsan — AST concurrency lint for the actor/TPU pipeline.

The reference node inherits its concurrency discipline from nqe actor
mailboxes + STM; this port re-creates it with asyncio tasks, threads and a
device-dispatch worker — a combination where one blocking call or orphaned
task silently stalls block relay (the hang class PR 2's watchdog can only
observe after the fact).  This package prevents those defects at lint
time:

* :mod:`tpunode.analysis.core` — the engine: a rule registry, per-file AST
  contexts with import/name resolution, per-line suppression
  (``# asyncsan: disable=RULE``), and an :class:`Analyzer` front-end.
* :mod:`tpunode.analysis.rules` — the rule set, targeting this codebase's
  real hazard classes (blocking calls inside ``async def``, dropped task
  handles, raw spawns bypassing the supervision registry, locks held
  across ``await``, unawaited coroutines, ``CancelledError`` swallowing,
  cross-thread mutation of loop-owned state, metric/event name schema).
* ``python -m tpunode.analysis [--json] [paths]`` — the CLI
  (:mod:`tpunode.analysis.__main__`); exit code 1 iff findings.

The paired *runtime* sanitizers (``TPUNODE_ASYNCSAN`` debug mode, the
task-supervision registry, the blocked-loop attributor) live in
:mod:`tpunode.asyncsan` — see ANALYSIS.md for the full catalog.

Tier-1 tests (tests/test_analysis.py) run the analyzer over the whole
``tpunode`` tree and pin ZERO findings, so every rule added here must
either hold across the codebase or carry an explicit suppression at the
deliberate call site.
"""

from __future__ import annotations

from .core import Analyzer, FileContext, Finding, Rule, RULES, rule
from . import rules as _rules  # noqa: F401  (registers the rule set)

__all__ = [
    "Analyzer",
    "FileContext",
    "Finding",
    "Rule",
    "RULES",
    "rule",
    "analyze_paths",
    "analyze_source",
]


def analyze_source(source: str, path: str = "<memory>") -> "list[Finding]":
    """One-shot convenience: lint a source string with every rule."""
    return Analyzer().check_source(source, path)


def analyze_paths(paths) -> "list[Finding]":
    """One-shot convenience: lint files/directories with every rule."""
    return Analyzer().check_paths(paths)
