"""asyncsan rule set: the hazard classes this codebase has actually hit.

Every rule id doubles as its suppression token
(``# asyncsan: disable=<id>``); ANALYSIS.md is the user-facing catalog.
The selection is deliberately grounded in this node's architecture —
actor mailboxes drained by linked loops on ONE event loop, a verify
engine whose dispatch runs in a worker thread, and a telemetry layer with
a pinned ``<layer>.<name>`` naming schema.
"""

from __future__ import annotations

import ast
import os
import re

from .core import FileContext, Finding, NAME_SCHEMA_RE, rule

# --- blocking-call -----------------------------------------------------------

# Qualified call names that block the calling thread.  Inside an
# ``async def`` these freeze the event loop: every mailbox, timer, peer
# session and watchdog shares that one thread (actors.py's substrate).
_BLOCKING_CALLS = {
    "time.sleep",
    "os.system",
    "os.popen",
    "os.wait",
    "os.waitpid",
    # durable-storage syscalls (ISSUE 9): an fsync is milliseconds on a
    # good day and unbounded on a bad one, and a cross-filesystem replace
    # degrades to a copy — the chain actor's durable commits route them
    # through LogKV's group-commit writer thread instead
    "os.fsync",
    "os.fdatasync",
    "os.replace",
    "os.rename",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.Popen",
    "socket.create_connection",
    "socket.getaddrinfo",
    "socket.gethostbyname",
    "socket.gethostbyaddr",
    "urllib.request.urlopen",
    "requests.get",
    "requests.post",
    "requests.put",
    "requests.head",
    "requests.delete",
    "requests.request",
    "open",
    "input",
}

# Methods that block regardless of receiver when NOT awaited:
# ``fut.result()`` (concurrent.futures) and jax's ``block_until_ready()``
# synchronize on work that may never finish while the loop is frozen.
_BLOCKING_METHODS = {"result", "block_until_ready"}

# Methods that block only in their no-positional-arg form — distinguishes
# ``thread.join()`` / ``event.wait()`` / ``lock.acquire()`` from
# ``sep.join(parts)`` (always one positional arg).  A NON-awaited bare
# ``.wait()``/``.acquire()`` inside ``async def`` is either a threading
# primitive (blocks the loop) or a missed ``await`` on the asyncio one —
# a hazard either way.
_BLOCKING_METHODS_NOARG = {"join", "wait", "acquire"}


@rule(
    "blocking-call",
    "blocking call inside `async def` freezes the event loop "
    "(wrap in asyncio.to_thread, or use the async equivalent)",
)
def _blocking_call(ctx: FileContext) -> None:
    for call, awaited in ctx.async_scope_calls():
        if awaited:
            continue
        qual = ctx.resolve(call.func)
        if qual in _BLOCKING_CALLS:
            ctx.report(
                "blocking-call", call,
                f"blocking call {qual}() inside async def",
            )
            continue
        if isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            if attr in _BLOCKING_METHODS or (
                attr in _BLOCKING_METHODS_NOARG and not call.args
            ):
                ctx.report(
                    "blocking-call", call,
                    f"potentially blocking .{attr}() inside async def "
                    "(not awaited)",
                )


# --- dropped-task ------------------------------------------------------------

_SPAWN_QUALS = {"asyncio.create_task", "asyncio.ensure_future"}
_SPAWN_ATTRS = {"create_task", "ensure_future"}
_SPAWN_NAMES = {"spawn_supervised"}


def _is_spawn(ctx: FileContext, call: ast.Call) -> bool:
    qual = ctx.resolve(call.func)
    if qual in _SPAWN_QUALS:
        return True
    if qual is not None and qual.split(".")[-1] in _SPAWN_NAMES:
        return True
    return (
        isinstance(call.func, ast.Attribute) and call.func.attr in _SPAWN_ATTRS
    )


@rule(
    "dropped-task",
    "task handle discarded at spawn: the task can be garbage-collected "
    "mid-flight and its exception is never observed (keep the handle, or "
    "hand it to a supervisor)",
)
def _dropped_task(ctx: FileContext) -> None:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Expr):
            continue
        call = node.value
        if isinstance(call, ast.Call) and _is_spawn(ctx, call):
            name = ctx.resolve(call.func) or ast.unparse(call.func)
            ctx.report(
                "dropped-task", node,
                f"fire-and-forget {name}(...): task handle dropped",
            )


# --- raw-spawn ---------------------------------------------------------------


@rule(
    "raw-spawn",
    "direct create_task/ensure_future bypasses the supervision registry "
    "(use actors.spawn_supervised so leaks are reported at shutdown)",
)
def _raw_spawn(ctx: FileContext) -> None:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        qual = ctx.resolve(node.func)
        is_raw = qual in _SPAWN_QUALS or (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _SPAWN_ATTRS
        )
        if is_raw:
            name = qual or f".{node.func.attr}"  # type: ignore[union-attr]
            ctx.report(
                "raw-spawn", node,
                f"{name}(...) outside the supervision registry: route "
                "through actors.spawn_supervised",
            )


# --- lock-across-await -------------------------------------------------------


def _mentions_lock(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and "lock" in sub.id.lower():
            return True
        if isinstance(sub, ast.Attribute) and "lock" in sub.attr.lower():
            return True
    return False


@rule(
    "lock-across-await",
    "synchronous lock held across `await`: other tasks (and the metrics/"
    "event emitters on worker threads) deadlock against the frozen holder",
)
def _lock_across_await(ctx: FileContext) -> None:
    # Only sync ``with`` blocks: ``async with asyncio.Lock()`` awaits by
    # design.  A threading/`_lock`-style guard whose body awaits keeps the
    # lock held while OTHER code runs on this thread — the cross-thread
    # emitters then block a worker thread against a loop that may be
    # awaiting that very worker (the verify-engine dispatch boundary).
    def walk(node: ast.AST, in_async: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.AsyncFunctionDef):
                walk(child, True)
                continue
            if isinstance(child, (ast.FunctionDef, ast.Lambda)):
                walk(child, False)
                continue
            if (
                in_async
                and isinstance(child, ast.With)
                and any(_mentions_lock(item.context_expr) for item in child.items)
                and any(isinstance(n, ast.Await) for n in ast.walk(child))
            ):
                ctx.report(
                    "lock-across-await", child,
                    "sync lock held across await inside async def",
                )
            walk(child, in_async)

    walk(ctx.tree, False)


# --- unawaited-coro ----------------------------------------------------------


@rule(
    "unawaited-coro",
    "call to a locally-defined `async def` whose coroutine is discarded: "
    "the body never runs (RuntimeWarning at GC, silently dropped work)",
)
def _unawaited_coro(ctx: FileContext) -> None:
    names = ctx.async_defs
    if not names:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Expr):
            continue
        call = node.value
        if not isinstance(call, ast.Call):
            continue
        func = call.func
        called = None
        if isinstance(func, ast.Name) and func.id in names:
            called = func.id
        elif (
            isinstance(func, ast.Attribute)
            and func.attr in names
            # only `self.<name>` receivers: a deeper chain (e.g.
            # `self._writer.write`) usually reaches an unrelated object
            # that merely shares a method name with a local async def
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
        ):
            called = func.attr
        if called is not None:
            ctx.report(
                "unawaited-coro", node,
                f"coroutine {called}(...) is never awaited",
            )


# --- cancel-swallow ----------------------------------------------------------

_CANCEL_NAMES = {
    "asyncio.CancelledError",
    "CancelledError",
    "concurrent.futures.CancelledError",
    "BaseException",
}


def _catches_cancelled(ctx: FileContext, handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare except
        return True
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for node in types:
        qual = ctx.resolve(node)
        if qual in _CANCEL_NAMES or (
            qual is not None and qual.split(".")[-1] == "CancelledError"
        ):
            return True
    return False


@rule(
    "cancel-swallow",
    "except clause swallows CancelledError: shutdown cancellation never "
    "propagates and the task loops forever (re-raise it)",
)
def _cancel_swallow(ctx: FileContext) -> None:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _catches_cancelled(ctx, node):
            continue
        if any(isinstance(n, ast.Raise) for n in ast.walk(node)):
            continue
        what = "bare except" if node.type is None else (
            ctx.resolve(node.type)
            if not isinstance(node.type, ast.Tuple)
            else "except (...)"
        )
        ctx.report(
            "cancel-swallow", node,
            f"{what} catches CancelledError without re-raising",
        )


# --- thread-loop-affinity ----------------------------------------------------

# Loop-affine calls: mutating these from a non-loop thread corrupts
# asyncio internals or races the consumer (asyncio.Queue.put_nowait and
# Mailbox.send are NOT thread-safe).  The verify-engine dispatch-worker
# boundary is exactly this seam: results cross back via the future the
# *loop* resolves, never via direct mutation from the worker.
_LOOP_AFFINE_ATTRS = {
    "set_result",
    "set_exception",
    "call_soon",
    "call_later",
    "call_at",
    "create_task",
    "ensure_future",
    "put_nowait",
    "send",
}


def _thread_target_names(ctx: FileContext) -> set[str]:
    """Names of local defs handed to worker threads: Thread(target=f),
    asyncio.to_thread(f, ...), loop.run_in_executor(None, f, ...)."""
    targets: set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        qual = ctx.resolve(node.func)
        is_thread = qual == "threading.Thread" or (
            isinstance(node.func, ast.Attribute) and node.func.attr == "Thread"
        )
        if is_thread:
            for kw in node.keywords:
                if kw.arg == "target" and isinstance(kw.value, ast.Name):
                    targets.add(kw.value.id)
            continue
        if qual == "asyncio.to_thread" and node.args:
            if isinstance(node.args[0], ast.Name):
                targets.add(node.args[0].id)
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "run_in_executor"
            and len(node.args) >= 2
            and isinstance(node.args[1], ast.Name)
        ):
            targets.add(node.args[1].id)
    return targets


@rule(
    "thread-loop-affinity",
    "worker-thread code mutates loop-owned state directly (futures, "
    "mailboxes, task spawns): marshal through loop.call_soon_threadsafe",
)
def _thread_loop_affinity(ctx: FileContext) -> None:
    targets = _thread_target_names(ctx)
    if not targets:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.FunctionDef) or node.name not in targets:
            continue
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in _LOOP_AFFINE_ATTRS
            ):
                ctx.report(
                    "thread-loop-affinity", sub,
                    f".{sub.func.attr}(...) called from thread-target "
                    f"{node.name}() without call_soon_threadsafe",
                )


# --- pool-shutdown -----------------------------------------------------------

# Worker-pool constructors whose threads/processes outlive their owner
# unless someone shuts them down: a pool created per-request (or per
# node restart) without a shutdown path leaks OS threads until the
# process dies — invisible to the asyncio task-leak sweep, which only
# sees loop tasks.  ISSUE 10's parallel-extraction pool is the in-tree
# instance (Node.__aexit__ shuts it down).
_POOL_QUALS = {
    "concurrent.futures.ThreadPoolExecutor",
    "concurrent.futures.ProcessPoolExecutor",
    "multiprocessing.Pool",
    "multiprocessing.pool.ThreadPool",
}
_POOL_ATTRS = {"ThreadPoolExecutor", "ProcessPoolExecutor", "ThreadPool"}


def _is_pool_call(ctx: FileContext, call: ast.Call) -> bool:
    qual = ctx.resolve(call.func)
    return qual in _POOL_QUALS or (
        qual is not None and qual.split(".")[-1] in _POOL_ATTRS
    )


@rule(
    "pool-shutdown",
    "executor/worker pool created without a shutdown path in this file: "
    "its threads outlive the owner and leak per restart (call .shutdown()/"
    ".terminate()/.close()+.join(), or create it in a `with` block)",
)
def _pool_shutdown(ctx: FileContext) -> None:
    # A `with ThreadPoolExecutor(...) as p:` item manages its own
    # lifetime; so does entering a STORED pool later (`pool = ...;
    # with pool:`) — but only names actually assigned from a pool
    # constructor count, or any `with lock:` in the file would
    # suppress the rule (review finding: near-vacuous heuristics).
    managed: set[int] = set()
    pool_names: set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Call
        ) and _is_pool_call(ctx, node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    pool_names.add(t.id)
                elif isinstance(t, ast.Attribute):
                    pool_names.add(t.attr)  # self.pool = ...
    with_pool_context = False
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                ce = item.context_expr
                if isinstance(ce, ast.Call):
                    managed.add(id(ce))
                elif isinstance(ce, ast.Name) and ce.id in pool_names:
                    with_pool_context = True
                elif (
                    isinstance(ce, ast.Attribute) and ce.attr in pool_names
                ):
                    with_pool_context = True
    # File-scope teardown (like thread-loop-affinity's heuristic):
    # .shutdown()/.terminate() anywhere; a bare .close() only counts
    # alongside a .join() (multiprocessing's canonical close()+join() —
    # an unrelated file.close() alone must not suppress the rule).
    attrs = {
        n.func.attr
        for n in ast.walk(ctx.tree)
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
        # `sep.join(parts)` always takes a positional arg; a pool's
        # join() never does — don't let string plumbing count
        and (n.func.attr != "join" or not n.args)
    }
    has_shutdown = (
        with_pool_context
        or "shutdown" in attrs
        or "terminate" in attrs
        or ("close" in attrs and "join" in attrs)
    )
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or id(node) in managed:
            continue
        if _is_pool_call(ctx, node) and not has_shutdown:
            qual = ctx.resolve(node.func)
            name = (qual or "").split(".")[-1] or "pool"
            ctx.report(
                "pool-shutdown", node,
                f"{name}(...) created but this file never calls "
                ".shutdown()/.terminate()/.close()+.join() (and it is "
                "not a `with` target)",
            )


# --- metric-name / event-name ------------------------------------------------

_METRIC_ATTRS = {"inc", "observe", "set_gauge"}

# Registered telemetry layers: the `<layer>` half of every
# `<layer>.<name>` metric/span/event literal must come from this set, so
# a new subsystem's names are REGISTERED (here + OBSERVABILITY.md), not
# invented ad hoc — a typo'd or unregistered layer ("mempol.size") would
# otherwise ship a parallel namespace no dashboard ever reads.  ISSUE 5
# adds `mempool` (the mempool subsystem's metric/event/span names).
KNOWN_LAYERS = frozenset({
    "asyncsan",   # runtime sanitizers (tpunode/asyncsan.py)
    "bench",      # driver bench traces (bench.py; incl. the watcher's
                  # cross-round regression detector, ISSUE 16)
    "blackbox",   # flight recorder (tpunode/blackbox.py, ISSUE 16)
    "bus",        # Publisher/user bus (tpunode/actors.py)
    "chain",      # header-chain actor (tpunode/chain.py)
    "chaos",      # fault injection (tpunode/chaos.py, ISSUE 7)
    "events",     # event-log self-metrics (tpunode/events.py)
    "ibd",        # block-fetch-driven IBD planner (tpunode/ibd.py, ISSUE 11)
    "mempool",    # mempool subsystem (tpunode/mempool.py)
    "mesh",       # pod-scale fleet: host health, sub-mesh shrink/regrow
                  # (tpunode/verify/engine.py, ISSUE 13; also the
                  # chaos mesh.dispatch injection point)
    "node",       # node composition/ingest (tpunode/node.py)
    "peer",       # wire sessions (tpunode/peer.py)
    "peermgr",    # fleet manager (tpunode/peermgr.py)
    "receipts",   # hash-chained verdict receipt log (tpunode/receipts.py,
                  # ISSUE 20)
    "sched",      # lane-packing verify scheduler (tpunode/verify/sched.py,
                  # ISSUE 10; incl. the node-side extract ring gauges)
    "serve",      # multi-tenant verification-as-a-service front-end
                  # (tpunode/serve.py, ISSUE 20)
    "slo",        # SLO engine: burn rates + budgets (tpunode/slo.py,
                  # ISSUE 17)
    "store",      # KV store (tpunode/store.py)
    "threadsan",  # lock-order/lockset sanitizer (tpunode/threadsan.py,
                  # ISSUE 18)
    "trace",      # tracing internals (tpunode/tracectx.py)
    "tsdb",       # metrics timeline sampler (tpunode/timeseries.py,
                  # ISSUE 16)
    "utxo",       # persistent UTXO store (tpunode/utxo.py, ISSUE 9)
    "verify",     # batch verify engine (tpunode/verify/)
    "watchdog",   # stall watchdog (tpunode/watchdog.py)
})


def _name_violation(name: str) -> "str | None":
    """Schema complaint for a metric/span/event name literal, or None."""
    if not NAME_SCHEMA_RE.match(name):
        return f"{name!r} violates <layer>.<name> schema"
    layer = name.split(".", 1)[0]
    if layer not in KNOWN_LAYERS:
        return (
            f"{name!r} uses unregistered layer {layer!r} (register in "
            "analysis.rules.KNOWN_LAYERS + OBSERVABILITY.md)"
        )
    return None


def _literal(node: ast.AST) -> "str | None":
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


@rule(
    "metric-name",
    "metric/span name literal violates the `<layer>.<name>` schema "
    "(^[a-z]+(\\.[a-z_]+)+$ with a registered layer, OBSERVABILITY.md)",
)
def _metric_name(ctx: FileContext) -> None:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        lit = _literal(node.args[0]) if node.args else None
        hit = None
        if isinstance(func, ast.Attribute) and func.attr in _METRIC_ATTRS:
            hit = lit
        elif isinstance(func, ast.Name) and func.id == "span":
            hit = lit
        elif isinstance(func, ast.Attribute) and func.attr == "span":
            hit = lit  # module-qualified form: trace.span("...")
        elif isinstance(func, ast.Attribute) and func.attr == "inc_batch":
            # inc_batch takes ((name, delta, labels), ...): lint every
            # literal tuple's literal first element (the old regex lint
            # never saw these)
            for arg in node.args:
                if isinstance(arg, (ast.Tuple, ast.List)):
                    for el in arg.elts:
                        if isinstance(el, (ast.Tuple, ast.List)) and el.elts:
                            name = _literal(el.elts[0])
                            why = (
                                _name_violation(name)
                                if name is not None else None
                            )
                            if why is not None:
                                ctx.report(
                                    "metric-name", el,
                                    f"metric name {why}",
                                )
            continue
        if hit is not None:
            why = _name_violation(hit)
            if why is not None:
                ctx.report("metric-name", node, f"metric name {why}")


@rule(
    "event-name",
    "event-type literal at .emit() violates the `<layer>.<name>` schema "
    "(registered layer required, no grandfathered names)",
)
def _event_name(ctx: FileContext) -> None:
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "emit"
            and node.args
        ):
            lit = _literal(node.args[0])
            why = _name_violation(lit) if lit is not None else None
            if why is not None:
                ctx.report("event-name", node, f"event type {why}")


# --- label-cardinality -------------------------------------------------------

# Registered bounded label-value sources: helpers whose return set is
# fixed and small by construction, so a label value drawn from one
# cannot grow series cardinality.  ``multichip.host_names`` is the
# canonical fleet-name source (ISSUE 19): AffinityMap seeds hash the
# name strings, so every layer that labels by host must already route
# through it — which is exactly what makes it safe to allowlist.
# ``serve.tenant_names`` (ISSUE 20) is its tenant-registry twin: it
# validates and bounds the tenant set (<= serve.MAX_TENANTS, pinned name
# charset), so a ``tenant=`` value drawn from it cannot grow series.
_BOUNDED_LABEL_SOURCES = frozenset({"host_names", "tenant_names"})


def _dynamic_format(expr: ast.AST) -> bool:
    """Is this expression a dynamically-formatted string — an f-string
    with interpolation, a ``.format(...)`` call, or a ``%`` format?"""
    if isinstance(expr, ast.JoinedStr):
        return any(
            isinstance(v, ast.FormattedValue) for v in expr.values
        )
    if (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Attribute)
        and expr.func.attr == "format"
    ):
        return True
    return (
        isinstance(expr, ast.BinOp)
        and isinstance(expr.op, ast.Mod)
        and isinstance(expr.left, ast.Constant)
        and isinstance(expr.left.value, str)
    )


def _has_bounded_call(ctx: FileContext, expr: ast.AST) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            qual = ctx.resolve(n.func) or ""
            if qual.split(".")[-1] in _BOUNDED_LABEL_SOURCES:
                return True
    return False


def _binding_index(ctx: FileContext) -> dict:
    """name -> every expression bound to it anywhere in the file
    (assignments, loop targets, comprehension targets).  File-wide on
    purpose: for a lint, over-approximation beats scope bookkeeping —
    an unbounded formatted binding ANYWHERE taints the name unless a
    bounded source also feeds it."""
    out: dict = {}

    def bind(target: ast.AST, expr: ast.AST) -> None:
        if isinstance(target, ast.Name):
            out.setdefault(target.id, []).append(expr)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                bind(el, expr)
        elif isinstance(target, ast.Starred):
            bind(target.value, expr)

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                bind(t, node.value)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            bind(node.target, node.value)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            bind(node.target, node.iter)
        elif isinstance(node, ast.comprehension):
            bind(node.target, node.iter)
    return out


def _labeled_metric_calls(ctx: FileContext):
    """Yield ``(report_node, labels_expr)`` for every labeled metric
    call: the ``labels=`` keyword of inc/observe/set_gauge, and the
    third element of each literal inc_batch tuple."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not isinstance(
            node.func, ast.Attribute
        ):
            continue
        if node.func.attr in _METRIC_ATTRS:
            for kw in node.keywords:
                if kw.arg == "labels" and kw.value is not None:
                    yield node, kw.value
        elif node.func.attr == "inc_batch":
            for arg in node.args:
                if isinstance(arg, (ast.Tuple, ast.List)):
                    for el in arg.elts:
                        if (
                            isinstance(el, (ast.Tuple, ast.List))
                            and len(el.elts) >= 3
                        ):
                            yield el, el.elts[2]


@rule(
    "label-cardinality",
    "dynamically-formatted label value on a metric without a bounded "
    "source (series cardinality = label-value cardinality: route fleet "
    "names through multichip.host_names, or pin the value set)",
)
def _label_cardinality(ctx: FileContext) -> None:
    """ISSUE 19 satellite: a labeled series is born per distinct label
    value, and the registry/Timeline only stay bounded when every label
    value comes from a bounded set (fixed hosts, declared SLOs, enum
    classes).  An f-string/``.format``/``%``-formatted value is the
    canonical unbounded-source smell — flag it unless the formatted
    input demonstrably comes from a registered bounded helper
    (``_BOUNDED_LABEL_SOURCES``).

    ISSUE 20 extension: the ``tenant=`` label key additionally gets a
    POSITIVE check — its value must be a string literal, or visibly
    trace to the bounded tenant registry (``serve.tenant_names``),
    because tenant names arrive from config/wire input where a merely
    not-formatted value is no evidence of boundedness."""
    bindings: "dict | None" = None

    def get_bindings() -> dict:
        nonlocal bindings
        if bindings is None:
            bindings = _binding_index(ctx)
        return bindings

    def taint(expr: ast.AST) -> "str | None":
        if _dynamic_format(expr):
            return "is dynamically formatted inline"
        if isinstance(expr, ast.Name):
            bound = get_bindings().get(expr.id, [])
            if any(_has_bounded_call(ctx, e) for e in bound):
                return None
            if any(_dynamic_format(e) for e in bound):
                return (
                    f"is bound to a dynamically formatted value "
                    f"({expr.id!r})"
                )
        return None

    def unbounded_tenant(v: ast.AST) -> bool:
        """True when a ``tenant=`` value shows no bounded provenance:
        not a literal, no inline ``tenant_names(...)`` call, and no
        file-wide binding of the name routed through one."""
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            return False
        if _has_bounded_call(ctx, v):
            return False
        if isinstance(v, ast.Name):
            bound = get_bindings().get(v.id, [])
            if any(_has_bounded_call(ctx, e) for e in bound):
                return False
        return True

    for node, labels in _labeled_metric_calls(ctx):
        dicts = []
        if isinstance(labels, ast.Dict):
            dicts.append(labels)
        elif isinstance(labels, ast.Name):
            # labels passed by name: lint the dict literal(s) the name
            # was assigned, but report at the metric call (that is
            # where the pragma belongs)
            dicts.extend(
                e for e in get_bindings().get(labels.id, [])
                if isinstance(e, ast.Dict)
            )
        for d in dicts:
            for k_node, v in zip(d.keys, d.values):
                key = _literal(k_node) if k_node is not None else None
                why = taint(v)
                if why is None and key == "tenant" and unbounded_tenant(v):
                    why = (
                        "does not visibly trace to the bounded tenant "
                        "registry (serve.tenant_names)"
                    )
                if why is not None:
                    ctx.report(
                        "label-cardinality", node,
                        f"label {key or '?'!r} value {why} — label "
                        "values must come from a bounded source "
                        "(register one in _BOUNDED_LABEL_SOURCES, or "
                        "pin the set)",
                    )


# --- doc-drift ---------------------------------------------------------------

# OBSERVABILITY.md relative to this file (tpunode/analysis/ -> repo
# root).  Loaded once per process; a missing doc disables the rule (an
# installed copy of the package without the repo docs must lint clean).
_OBS_DOC_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", "..",
    "OBSERVABILITY.md",
)
_obs_doc_cache: list = []  # [str] once loaded, [None] when absent


def _observability_text() -> "str | None":
    if not _obs_doc_cache:
        try:
            with open(_OBS_DOC_PATH, encoding="utf-8") as f:
                _obs_doc_cache.append(f.read())
        except OSError:
            _obs_doc_cache.append(None)
    return _obs_doc_cache[0]


def _telemetry_name_literals(ctx: FileContext):
    """Yield ``(node, name)`` for every literal metric/span/event name in
    the file — the exact call sites metric-name/event-name lint."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        first = _literal(node.args[0]) if node.args else None
        if isinstance(func, ast.Attribute):
            if func.attr in _METRIC_ATTRS or func.attr in ("span", "emit"):
                if first is not None:
                    yield node, first
            elif func.attr == "inc_batch":
                for arg in node.args:
                    if isinstance(arg, (ast.Tuple, ast.List)):
                        for el in arg.elts:
                            if isinstance(
                                el, (ast.Tuple, ast.List)
                            ) and el.elts:
                                name = _literal(el.elts[0])
                                if name is not None:
                                    yield el, name
        elif (
            isinstance(func, ast.Name)
            and func.id == "span"
            and first is not None
        ):
            yield node, first


@rule(
    "doc-drift",
    "schema-valid telemetry name literal is absent from OBSERVABILITY.md "
    "(every shipped metric/span/event name needs an inventory row)",
)
def _doc_drift(ctx: FileContext) -> None:
    """ISSUE 16 satellite: the names inventory in OBSERVABILITY.md is
    load-bearing (dashboards and the flight-recorder postmortems are
    read against it), so a name shipped without a row is drift, caught
    at lint time.  Only names that PASS the schema+layer checks are
    considered — a malformed name is metric-name/event-name's finding,
    not two findings for one mistake."""
    doc = _observability_text()
    if doc is None:
        return
    for node, name in _telemetry_name_literals(ctx):
        if _name_violation(name) is not None:
            continue
        if name not in doc:
            ctx.report(
                "doc-drift", node,
                f"telemetry name {name!r} is not documented in "
                "OBSERVABILITY.md (add an inventory row)",
            )


# --- stale-doc ---------------------------------------------------------------

# doc-drift's reverse pass (ISSUE 17): an OBSERVABILITY.md inventory row
# whose name no code literal ships anymore is a lie dashboards are still
# being read against.  The scan is scoped to the regions that CLAIM to be
# an inventory — the "Current inventory by layer" bullet list and the
# pipe-table rows whose first cell is backticked (the events/pieces
# tables) — so prose elsewhere in the doc cannot false-positive.

_DOC_TOKEN_RE = re.compile(r"`([^`]+)`")
# Same pragma as core's, re-parsed here for MARKDOWN rows: the doc form
# lives in an HTML comment (`<!-- # asyncsan: disable=stale-doc -->`),
# so the token list must stop at whitespace rather than swallowing the
# comment terminator's hyphens.
_DOC_PRAGMA_RE = re.compile(r"#\s*asyncsan:\s*disable=([A-Za-z0-9_\-,]+)")

# Repo root relative to this file; the code corpus the doc is checked
# against is every .py under tpunode/ and benchmarks/ plus the driver.
_REPO_ROOT = os.path.normpath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)
_corpus_cache: list = []  # [str] once loaded (concatenated sources)


def _code_corpus() -> str:
    if not _corpus_cache:
        paths = [os.path.join(_REPO_ROOT, "bench.py")]
        for top in ("tpunode", "benchmarks"):
            for root, dirs, names in os.walk(os.path.join(_REPO_ROOT, top)):
                dirs[:] = sorted(
                    d for d in dirs
                    if d != "__pycache__" and not d.startswith(".")
                )
                paths.extend(
                    os.path.join(root, f)
                    for f in sorted(names)
                    if f.endswith(".py")
                )
        chunks = []
        for path in paths:
            try:
                with open(path, encoding="utf-8") as f:
                    chunks.append(f.read())
            except OSError:
                pass
        _corpus_cache.append("\n".join(chunks))
    return _corpus_cache[0]


def _doc_documented_names(doc: str):
    """Yield ``(lineno, line, name)`` for every schema-valid telemetry
    name the doc's inventory regions commit to.  Labeled forms are
    stripped at ``{`` (``peer.msgs{peer=,cmd=}`` documents ``peer.msgs``)
    and ``.py`` path tokens are skipped (module tables, not telemetry)."""
    inventory = False
    for lineno, line in enumerate(doc.splitlines(), start=1):
        stripped = line.strip()
        if stripped.startswith("Current inventory by layer"):
            inventory = True
            continue
        if inventory and stripped.startswith("## "):
            inventory = False
        if not inventory and not stripped.startswith("| `"):
            continue
        for token in _DOC_TOKEN_RE.findall(line):
            name = token.split("{", 1)[0]
            if name.endswith(".py") or not NAME_SCHEMA_RE.match(name):
                continue
            yield lineno, line, name


@rule(
    "stale-doc",
    "OBSERVABILITY.md inventory row names a telemetry series no code "
    "literal ships anymore (delete the row, or suppress the row with "
    "`<!-- # asyncsan: disable=stale-doc -->` if it is intentional)",
)
def _stale_doc(ctx: FileContext) -> None:
    """Runs once per analysis (anchored on this file, which every full
    tree sweep includes) rather than per analyzed file.  Findings carry
    the DOC's path+line, so they are appended directly instead of going
    through ctx.report — per-row suppression is the pragma on the doc
    row itself, not on any Python line."""
    if not ctx.path.replace(os.sep, "/").endswith("analysis/rules.py"):
        return
    doc = _observability_text()
    if doc is None:
        return
    corpus = _code_corpus()
    seen: set[str] = set()
    for lineno, line, name in _doc_documented_names(doc):
        if name in seen:
            continue
        seen.add(name)
        m = _DOC_PRAGMA_RE.search(line)
        if m is not None:
            ids = {t.strip().rstrip("-") for t in m.group(1).split(",")}
            if "all" in ids or "stale-doc" in ids:
                continue
        # span-histogram rows document the landed name; the literal at
        # the call site is the bare span("<layer>.<name>") argument
        bare = name[len("span."):] if name.startswith("span.") else name
        if name in corpus or bare in corpus:
            continue
        ctx.findings.append(
            Finding(
                rule="stale-doc",
                path=os.path.normpath(_OBS_DOC_PATH),
                line=lineno,
                col=0,
                message=(
                    f"documented telemetry name {name!r} no longer "
                    "appears as a code literal (stale inventory row)"
                ),
            )
        )


# --- raw-lock (ISSUE 18) ------------------------------------------------------


@rule(
    "raw-lock",
    "bare threading.Lock()/RLock() construction bypasses the threadsan "
    "registry (use tpunode.threadsan.lock()/rlock() so the lock is "
    "named, hold-timed, and deadlock-checked)",
)
def _raw_lock(ctx: FileContext) -> None:
    """Every lock in the tree goes through threadsan's LockRegistry —
    that is what makes the lock-order graph complete.  threadsan.py
    itself is exempt (its wrappers and the registry's one meta lock are
    the raw primitives everything else is built on)."""
    base = os.path.basename(ctx.path.replace(os.sep, "/"))
    if base == "threadsan.py":
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        qual = ctx.resolve(func)
        hit = qual in ("threading.Lock", "threading.RLock")
        if (
            not hit
            and qual is None
            and isinstance(func, ast.Attribute)
            and func.attr in ("Lock", "RLock")
            and isinstance(func.value, ast.Call)
        ):
            # dynamic receiver, e.g. __import__("threading").Lock()
            hit = True
        if hit:
            kind = (func.attr if isinstance(func, ast.Attribute)
                    else qual.rsplit(".", 1)[-1])
            ctx.report(
                "raw-lock", node,
                f"bare threading.{kind}() outside the threadsan registry "
                "(construct via tpunode.threadsan."
                f"{'rlock' if kind == 'RLock' else 'lock'}('<layer>.<name>') "
                "so it joins the lock-order graph)",
            )


# --- jit-cache-key (ISSUE 18) -------------------------------------------------

# The formulation-mode accessors (tpunode/verify/modes.py): any compiled
# wrapper whose behaviour depends on the active modes must key on one of
# these — PR 4's shared-trace-cache bug was a jit cache that silently
# served one mode's trace to another.
_MODE_FNS = frozenset({"kernel_modes", "field_modes", "structure_modes"})


def _static_argnames_have_modes(call: ast.Call) -> "bool | None":
    """True/False when the call carries static_argnames (do they include
    a mode tuple?); None when neither static kwarg is present."""
    saw = None
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            return True  # positional static key — accepted as-is
        if kw.arg == "static_argnames":
            saw = False
            names: list = []
            if isinstance(kw.value, (ast.Tuple, ast.List)):
                names = [_literal(el) for el in kw.value.elts]
            else:
                names = [_literal(kw.value)]
            if any(n is not None and "modes" in n for n in names):
                saw = True
    return saw


def _scope_calls_mode_fn(ctx: FileContext, fstack: list) -> bool:
    for f in fstack:
        for sub in ast.walk(f):
            if isinstance(sub, ast.Call):
                q = ctx.resolve(sub.func)
                if q is not None and q.rsplit(".", 1)[-1] in _MODE_FNS:
                    return True
    return False


@rule(
    "jit-cache-key",
    "jax.jit wrapper in tpunode/verify/ is not keyed on the formulation "
    "modes (thread kernel_modes()/field_modes()/structure_modes() "
    "through static_argnums/static_argnames, or key the surrounding "
    "cache dict on it)",
)
def _jit_cache_key(ctx: FileContext) -> None:
    """PR 4's discovery, enforced: two formulations tracing through one
    jit cache silently serve each other's compilations.  Every
    ``jax.jit(...)`` (or ``partial(jax.jit, ...)``) in the verify layer
    must either carry the mode tuple as a static argument or live in a
    scope that computes its cache key from a mode accessor."""
    path = ctx.path.replace(os.sep, "/")
    if "verify" not in path.split("/") and not path.startswith("<"):
        return  # in-memory sources ("<...>") stay lintable for tests

    def visit(node: ast.AST, fstack: list) -> None:
        for child in ast.iter_child_nodes(node):
            stack = fstack
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                stack = fstack + [child]
            elif isinstance(child, ast.Call):
                check(child, fstack)
            visit(child, stack)

    def check(call: ast.Call, fstack: list) -> None:
        qual = ctx.resolve(call.func)
        if qual == "jax.jit":
            jit = call
        elif (
            qual is not None
            and qual.rsplit(".", 1)[-1] == "partial"
            and call.args
            and ctx.resolve(call.args[0]) == "jax.jit"
        ):
            jit = call
        else:
            return
        static = _static_argnames_have_modes(jit)
        if static is True:
            return
        if static is None and _scope_calls_mode_fn(ctx, fstack):
            return
        ctx.report(
            "jit-cache-key", jit,
            "jax.jit wrapper is not keyed on the formulation modes "
            "(add the mode tuple to static_argnames/static_argnums or "
            "key the enclosing cache on kernel_modes()/field_modes()/"
            "structure_modes())",
        )

    visit(ctx.tree, [])


# --- env-knob-doc (ISSUE 18) --------------------------------------------------

_ENV_KNOB_RE = re.compile(r"^TPUNODE_[A-Z0-9_]+$")


@rule(
    "env-knob-doc",
    "TPUNODE_* env knob literal is missing from OBSERVABILITY.md's "
    "env-var inventory (every shipped knob needs an inventory row)",
)
def _env_knob_doc(ctx: FileContext) -> None:
    """Same doc-drift contract as the telemetry inventory, for config
    knobs: an operator reading OBSERVABILITY.md must see every env var
    the tree actually reads.  Containment is whole-doc (a prose mention
    counts), so one inventory row per knob is the cheap fix."""
    doc = _observability_text()
    if doc is None:
        return
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and _ENV_KNOB_RE.match(node.value)
            and node.value not in doc
        ):
            ctx.report(
                "env-knob-doc", node,
                f"env knob {node.value!r} is not documented in "
                "OBSERVABILITY.md (add an env-var inventory row)",
            )
