"""asyncsan CLI: ``python -m tpunode.analysis [--json] [paths...]``.

With no paths, lints the ``tpunode`` package, the repo-root
``bench.py``, and the ``benchmarks/`` harness package (the same closure
the tier-1 test pins at zero findings — ISSUE 8 extended it over
benchmarks/, whose async harness scripts carry the same hazard classes).
Exit status: 0 = clean, 1 = findings, 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .core import Analyzer, RULES


def default_paths() -> list[str]:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [pkg]
    repo = os.path.dirname(pkg)
    bench = os.path.join(repo, "bench.py")
    if os.path.isfile(bench):
        paths.append(bench)
    marks = os.path.join(repo, "benchmarks")
    if os.path.isdir(marks):
        paths.append(marks)
    return paths


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpunode.analysis",
        description="asyncsan: AST concurrency lint for the actor/TPU "
        "pipeline (rule catalog in ANALYSIS.md)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: the tpunode package, "
        "bench.py, and benchmarks/)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="machine-readable output (one JSON object)",
    )
    parser.add_argument(
        "--rules", metavar="ID[,ID...]",
        help="comma-separated rule subset (default: all)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for r in RULES.values():
            print(f"{r.id}: {r.summary}")
        return 0

    try:
        select = (
            [s.strip() for s in args.rules.split(",") if s.strip()]
            if args.rules
            else None
        )
        analyzer = Analyzer(select=select)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2

    paths = args.paths or default_paths()
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"no such path: {', '.join(missing)}", file=sys.stderr)
        return 2
    findings = analyzer.check_paths(paths)

    if args.json:
        print(
            json.dumps(
                {
                    "paths": paths,
                    "rules": [r.id for r in analyzer.rules],
                    "findings": [f.to_dict() for f in findings],
                }
            )
        )
    else:
        for f in findings:
            print(f.render())
        if findings:
            print(f"{len(findings)} finding(s)", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # e.g. `... | head`: not an analyzer failure
        sys.exit(0)
