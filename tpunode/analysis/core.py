"""asyncsan engine: findings, rule registry, file contexts, suppression.

Rules are plain functions registered with the :func:`rule` decorator; each
receives one :class:`FileContext` per analyzed file and reports through
:meth:`FileContext.report`, which applies per-line suppression
(``# asyncsan: disable=RULE[,RULE2]`` or ``disable=all`` on the finding's
first line) before a :class:`Finding` is recorded.  The context carries
the shared per-file indexes every rule needs — an import-alias resolver
(``resolve`` maps ``t.sleep`` back to ``time.sleep`` under
``import time as t``), the set of locally-defined ``async def`` names,
and a scope-aware walker that yields calls made while inside an
``async def`` body (nested *sync* defs and lambdas are excluded: code in
them does not run on the awaiting scope's event-loop turn).

Everything here is stdlib-only (ast/tokenize): the analyzer must run in
CI boxes and pre-commit hooks without jax or the node's runtime deps.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Callable, Iterable, Iterator, Optional

__all__ = [
    "Finding",
    "Rule",
    "RULES",
    "rule",
    "FileContext",
    "Analyzer",
]

# One suppression pragma per line: ``# asyncsan: disable=rule-a,rule-b``
# (or ``all``).  The pragma applies to findings whose *first* line is the
# pragma's line — for a multi-line statement, put it on the opening line.
_PRAGMA_RE = re.compile(r"#\s*asyncsan:\s*disable=([A-Za-z0-9_\-, ]+)")

# ``<layer>.<name>`` schema shared by metric, span and event-type
# literals (OBSERVABILITY.md); formerly enforced by two ad-hoc regex
# lints in tests/test_metrics.py, now by the metric-name/event-name rules.
NAME_SCHEMA_RE = re.compile(r"^[a-z]+(\.[a-z_]+)+$")


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"


@dataclass(frozen=True)
class Rule:
    """A registered rule: id (the suppression token), summary, checker."""

    id: str
    summary: str
    check: Callable[["FileContext"], None]


# Registry: rule id -> Rule.  Populated by the @rule decorator at import
# of tpunode.analysis.rules; tests may register extra rules (ids must be
# unique — re-registration is a programming error, not a merge).
RULES: dict[str, Rule] = {}

_RULE_ID_RE = re.compile(r"^[a-z][a-z0-9\-]*$")


def rule(id: str, summary: str) -> Callable:
    """Decorator registering a rule function in :data:`RULES`."""
    if not _RULE_ID_RE.match(id):
        raise ValueError(f"rule id must be kebab-case, got {id!r}")

    def deco(fn: Callable[["FileContext"], None]) -> Callable:
        if id in RULES:
            raise ValueError(f"duplicate rule id {id!r}")
        RULES[id] = Rule(id=id, summary=summary, check=fn)
        return fn

    return deco


def _suppressions(lines: list[str]) -> dict[int, set[str]]:
    """Map 1-based line number -> set of suppressed rule ids ('all' ok)."""
    out: dict[int, set[str]] = {}
    for i, line in enumerate(lines, start=1):
        m = _PRAGMA_RE.search(line)
        if m is None:
            continue
        ids = {t.strip() for t in m.group(1).split(",") if t.strip()}
        if ids:
            out[i] = ids
    return out


class FileContext:
    """Everything a rule needs to analyze one file."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.findings: list[Finding] = []
        self._suppress = _suppressions(self.lines)
        self._aliases: Optional[dict[str, str]] = None
        self._async_defs: Optional[set[str]] = None

    # -- reporting -----------------------------------------------------------

    def report(self, rule_id: str, node: ast.AST, message: str) -> None:
        """Record a finding unless the line carries a suppression pragma."""
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        sup = self._suppress.get(line)
        if sup is not None and ("all" in sup or rule_id in sup):
            return
        self.findings.append(
            Finding(rule=rule_id, path=self.path, line=line, col=col,
                    message=message)
        )

    # -- shared indexes ------------------------------------------------------

    @property
    def aliases(self) -> dict[str, str]:
        """Local name -> imported qualified name (``t`` -> ``time``,
        ``snooze`` -> ``time.sleep``, ``urlopen`` ->
        ``urllib.request.urlopen``)."""
        if self._aliases is None:
            amap: dict[str, str] = {}
            for node in ast.walk(self.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        amap[a.asname or a.name.split(".")[0]] = (
                            a.name if a.asname else a.name.split(".")[0]
                        )
                elif isinstance(node, ast.ImportFrom) and node.module:
                    for a in node.names:
                        amap[a.asname or a.name] = f"{node.module}.{a.name}"
            self._aliases = amap
        return self._aliases

    @property
    def async_defs(self) -> set[str]:
        """Names of every ``async def`` in this file (incl. methods)."""
        if self._async_defs is None:
            self._async_defs = {
                n.name
                for n in ast.walk(self.tree)
                if isinstance(n, ast.AsyncFunctionDef)
            }
        return self._async_defs

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted name of a Name/Attribute chain with import aliases
        unfolded, or None for dynamic expressions (calls, subscripts)."""
        parts: list[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        head = self.aliases.get(cur.id, cur.id)
        parts.append(head)
        return ".".join(reversed(parts))

    def async_scope_calls(self) -> Iterator[tuple[ast.Call, bool]]:
        """Yield ``(call, awaited)`` for every call made while running on
        an ``async def``'s event-loop turn (nested sync defs/lambdas are
        other scopes and are skipped; nested async defs recurse)."""

        def awaited(call: ast.Call) -> Iterator[tuple[ast.Call, bool]]:
            # The awaited call itself, plus its direct Call arguments —
            # (almost always) coroutine construction the wrapper consumes,
            # ``await wait_for(e.wait())`` — count as awaited.  asyncio
            # combinators pass awaitedness one level further, so
            # ``await wait_for(shield(e.wait()), 5)`` is clean too; a
            # non-asyncio wrapper does NOT (``await f(g(open(p)))`` keeps
            # flagging the nested blocker).
            yield call, True
            for sub in ast.iter_child_nodes(call):
                if isinstance(sub, ast.Call):
                    qual = self.resolve(sub.func) or ""
                    if qual.startswith("asyncio."):
                        yield from awaited(sub)
                    else:
                        yield sub, True
                        yield from walk(sub, True)
                else:
                    yield from walk(sub, True)

        def walk(node: ast.AST, in_async: bool) -> Iterator[tuple[ast.Call, bool]]:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.AsyncFunctionDef):
                    yield from walk(child, True)
                elif isinstance(child, (ast.FunctionDef, ast.Lambda)):
                    yield from walk(child, False)
                elif isinstance(child, ast.Await):
                    if in_async and isinstance(child.value, ast.Call):
                        yield from awaited(child.value)
                    else:
                        yield from walk(child, in_async)
                else:
                    if in_async and isinstance(child, ast.Call):
                        yield child, False
                    yield from walk(child, in_async)

        yield from walk(self.tree, False)


class Analyzer:
    """Front-end: run (a selection of) the registered rules over sources,
    files or directory trees."""

    def __init__(self, select: Optional[Iterable[str]] = None):
        ids = list(RULES) if select is None else list(select)
        unknown = [i for i in ids if i not in RULES]
        if unknown:
            raise ValueError(f"unknown rule ids: {unknown}")
        self.rules = [RULES[i] for i in ids]

    # -- entry points --------------------------------------------------------

    def check_source(self, source: str, path: str = "<memory>") -> list[Finding]:
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            return [
                Finding(
                    rule="syntax-error", path=path, line=e.lineno or 1,
                    col=e.offset or 0, message=f"could not parse: {e.msg}",
                )
            ]
        ctx = FileContext(path, source, tree)
        for r in self.rules:
            r.check(ctx)
        ctx.findings.sort(key=lambda f: (f.line, f.col, f.rule))
        return ctx.findings

    def check_file(self, path: str) -> list[Finding]:
        with open(path, encoding="utf-8") as f:
            return self.check_source(f.read(), path)

    def check_paths(self, paths: Iterable[str]) -> list[Finding]:
        """Lint every ``.py`` under the given files/directories (sorted
        walk: deterministic output ordering for CI diffs)."""
        findings: list[Finding] = []
        for path in paths:
            if os.path.isdir(path):
                for root, dirs, files in os.walk(path):
                    dirs[:] = sorted(
                        d for d in dirs
                        if d != "__pycache__" and not d.startswith(".")
                    )
                    for f in sorted(files):
                        if f.endswith(".py"):
                            findings.extend(
                                self.check_file(os.path.join(root, f))
                            )
            else:
                findings.extend(self.check_file(path))
        return findings
