"""Request-scoped causal tracing: per-block/tx pipeline trace trees.

PR 1's aggregates (metrics.py histograms, events.py) answer "what is slow";
this module answers "why was THIS one slow": one block or tx message yields
a single :class:`Trace` — a tree of timed spans with one trace id — that
follows the item through the actor pipeline:

    peer.payload -> peer.decode -> [mailbox hops] -> node.extract ->
    verify.queue -> verify.dispatch -> verify.prepare/transfer/kernel/
    readback -> node.commit

Propagation is ``contextvars``-based and implicit:

* ``_ACTIVE`` holds ``(trace, span_id)`` for the current task/thread;
* :class:`tpunode.actors.Mailbox` captures it on ``send`` and re-activates
  it on ``receive`` (actor hops);
* ``asyncio.ensure_future``/``to_thread`` copy the context into child
  tasks; the verify engine re-activates it explicitly in its dispatch
  worker thread (the one boundary ``contextvars`` cannot cross alone);
* :class:`tpunode.trace.span` records into the active trace when one
  exists — and costs nothing extra when none does (the <5µs pin in
  tests/test_bench.py covers the no-trace fast path).

The process-wide :data:`tracer` retains the N slowest finished traces (the
BENCH JSON ``slowest_traces`` section) plus a ring of recent ones (the
debug server's ``/traces``), and exports each finished trace as Chrome
trace-event JSON when ``TPUNODE_TRACE_DIR`` is set (load the file in
``chrome://tracing`` or Perfetto).  ``TPUNODE_NO_TRACE=1`` disables trace
creation entirely; span/metrics recording is unaffected.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Iterator, Optional

from . import threadsan
from .metrics import metrics

__all__ = [
    "SpanRec",
    "Trace",
    "Tracer",
    "tracer",
    "current",
    "activate",
    "start_trace",
    "finish_active",
    "discard_active",
    "clear_active",
]

log = logging.getLogger("tpunode.tracectx")

# The active trace position: None, or a (Trace, parent_span_id) pair.
_ACTIVE: contextvars.ContextVar[Optional[tuple["Trace", int]]] = (
    contextvars.ContextVar("tpunode_trace", default=None)
)

# Trace ids: a per-process random prefix + a counter — unique across the
# processes that may share one TPUNODE_TRACE_DIR, cheap per trace.
_ID_PREFIX = os.urandom(4).hex()
_ids = itertools.count(1)


class SpanRec:
    """One timed region inside a trace (flat record; the tree is encoded
    by ``parent`` span ids)."""

    __slots__ = ("id", "parent", "name", "t", "dur", "tid", "fields")

    def __init__(self, id: int, parent: Optional[int], name: str, t: float):
        self.id = id
        self.parent = parent
        self.name = name
        self.t = t  # seconds since trace start
        self.dur: Optional[float] = None  # seconds; None while open
        self.tid = threading.get_ident()
        self.fields: Optional[dict] = None

    def as_dict(self) -> dict:
        out = {
            "id": self.id,
            "parent": self.parent,
            "name": self.name,
            "t": round(self.t, 6),
            "dur": round(self.dur, 6) if self.dur is not None else None,
        }
        if self.fields:
            out["fields"] = self.fields
        return out


class Trace:
    """One item's lifecycle: a span tree under a single trace id.

    ``begin``/``end`` are thread-safe — the verify engine records phases
    from its dispatch worker thread while the event loop records actor
    spans into the same trace.
    """

    __slots__ = (
        "trace_id",
        "name",
        "t0",
        "wall0",
        "spans",
        "root",
        "finished",
        "_lock",
        "_next",
    )

    def __init__(self, name: str, trace_id: Optional[str] = None, **fields):
        self.trace_id = trace_id or f"{_ID_PREFIX}-{next(_ids):x}"
        self.name = name
        self.t0 = time.perf_counter()
        self.wall0 = time.time()
        self.finished = False
        self._lock = threadsan.lock("tracectx.trace")
        self._next = itertools.count(2)
        root = SpanRec(1, None, name, 0.0)
        if fields:
            root.fields = fields
        self.root = root
        self.spans: list[SpanRec] = [root]

    def begin(
        self, name: str, parent: Optional[int] = None, **fields
    ) -> SpanRec:
        """Open a child span; returns its record (close with :meth:`end`
        or by setting ``rec.dur`` directly)."""
        with self._lock:
            rec = SpanRec(
                next(self._next),
                parent if parent is not None else self.root.id,
                name,
                time.perf_counter() - self.t0,
            )
            if fields:
                rec.fields = fields
            self.spans.append(rec)
        return rec

    def end(self, rec: SpanRec, dur: Optional[float] = None) -> None:
        rec.dur = (
            dur if dur is not None else (time.perf_counter() - self.t0) - rec.t
        )

    @property
    def duration(self) -> float:
        """Root duration once finished; live extent of the tree until then."""
        if self.root.dur is not None:
            return self.root.dur
        with self._lock:
            return max((s.t + (s.dur or 0.0)) for s in self.spans)

    def as_dict(self) -> dict:
        with self._lock:
            spans = [s.as_dict() for s in self.spans]
        return {
            "trace_id": self.trace_id,
            "name": self.name,
            "start_ts": round(self.wall0, 6),
            "duration": round(self.duration, 6),
            "spans": spans,
        }

    def to_chrome(self) -> dict:
        """Chrome trace-event / Perfetto JSON (``ph: "X"`` complete events,
        µs timestamps on the wall clock)."""
        pid = os.getpid()
        evs = []
        with self._lock:
            spans = list(self.spans)
        for s in spans:
            args = {"trace_id": self.trace_id, "span_id": s.id}
            if s.parent is not None:
                args["parent"] = s.parent
            if s.fields:
                args.update(s.fields)
            evs.append(
                {
                    "name": s.name,
                    "cat": "tpunode",
                    "ph": "X",
                    "pid": pid,
                    "tid": s.tid,
                    "ts": (self.wall0 + s.t) * 1e6,
                    "dur": (s.dur or 0.0) * 1e6,
                    "args": args,
                }
            )
        return {
            "displayTimeUnit": "ms",
            "otherData": {"trace_id": self.trace_id, "name": self.name},
            "traceEvents": evs,
        }


class Tracer:
    """Process-wide trace collector: start/finish, slowest-N retention,
    recent ring, optional Chrome-JSON export directory."""

    def __init__(
        self,
        trace_dir: Optional[str] = None,
        ring: int = 8,
        recent: int = 32,
        enabled: Optional[bool] = None,
    ):
        self.trace_dir = (
            trace_dir
            if trace_dir is not None
            else os.environ.get("TPUNODE_TRACE_DIR")
        )
        self.enabled = (
            os.environ.get("TPUNODE_NO_TRACE") != "1"
            if enabled is None
            else enabled
        )
        self.ring = ring
        self._lock = threadsan.lock("tracectx.tracer")
        self._slowest: list[Trace] = []  # kept sorted, slowest first
        self._recent: deque[Trace] = deque(maxlen=recent)

    def start(self, name: str, **fields) -> Trace:
        """New trace with an open root span (finish with :meth:`finish`)."""
        metrics.inc("trace.started")
        return Trace(name, **fields)

    def finish(self, trace: Trace) -> None:
        """Close the root span and retain the trace (idempotent — a trace
        may reach more than one finish site on coalesced paths)."""
        if trace.finished:
            return
        trace.finished = True
        if trace.root.dur is None:
            trace.end(trace.root)
        metrics.inc("trace.finished")
        with self._lock:
            self._recent.append(trace)
            self._slowest.append(trace)
            self._slowest.sort(key=lambda t: t.root.dur or 0.0, reverse=True)
            del self._slowest[self.ring :]
        if self.trace_dir:
            self._export(trace)

    def _export(self, trace: Trace) -> None:
        try:
            os.makedirs(self.trace_dir, exist_ok=True)
            fname = f"{trace.name.replace('.', '_')}-{trace.trace_id}.json"
            path = os.path.join(self.trace_dir, fname)
            with open(path, "w", encoding="utf-8") as f:
                json.dump(trace.to_chrome(), f)
        except OSError as e:  # export is best-effort, never a hot-path error
            log.warning("trace export to %s failed, disabling: %s",
                        self.trace_dir, e)
            self.trace_dir = None

    def discard(self, trace: Trace) -> None:
        """Close a trace WITHOUT retaining or exporting it — the overload
        paths (verify shed/drop) end traces they will never attribute, and
        flooding the rings with shed stubs would evict the traces that
        matter.  Counted separately so started == finished + discarded."""
        if trace.finished:
            return
        trace.finished = True
        if trace.root.dur is None:
            trace.end(trace.root)
        metrics.inc("trace.discarded")

    def slowest(self, n: Optional[int] = None, name: Optional[str] = None
                ) -> list[dict]:
        """The slowest finished traces (dicts), slowest first."""
        with self._lock:
            traces = list(self._slowest)
        if name is not None:
            traces = [t for t in traces if t.name == name]
        return [t.as_dict() for t in traces[: n if n is not None else self.ring]]

    def recent_traces(self, n: int = 32) -> list[dict]:
        """The most recently finished traces (dicts), newest first."""
        if n <= 0:
            return []  # list[-0:] would be the WHOLE ring
        with self._lock:
            traces = list(self._recent)[-n:]
        return [t.as_dict() for t in reversed(traces)]

    def reset(self) -> None:
        with self._lock:
            self._slowest.clear()
            self._recent.clear()


# Process-wide tracer (tests may construct their own).
tracer = Tracer()


def current() -> Optional[tuple[Trace, int]]:
    """The active ``(trace, span_id)`` position, or None."""
    return _ACTIVE.get()


@contextlib.contextmanager
def activate(act: Optional[tuple[Trace, int]]) -> Iterator[None]:
    """Make ``act`` the active trace position for the enclosed region
    (no-op when None).  Works in worker threads too — this is how the
    verify engine carries a trace across the thread-pool boundary."""
    if act is None:
        yield
        return
    tok = _ACTIVE.set(act)
    try:
        yield
    finally:
        _ACTIVE.reset(tok)


@contextlib.contextmanager
def start_trace(
    name: str, tracer_: Optional[Tracer] = None, **fields
) -> Iterator[Optional[Trace]]:
    """Start a trace, activate its root for the enclosed region, finish on
    exit.  Yields None (and does nothing) when the tracer is disabled."""
    col = tracer_ if tracer_ is not None else tracer
    if not col.enabled:
        yield None
        return
    tr = col.start(name, **fields)
    tok = _ACTIVE.set((tr, tr.root.id))
    try:
        yield tr
    finally:
        _ACTIVE.reset(tok)
        col.finish(tr)


def finish_active(tracer_: Optional[Tracer] = None) -> None:
    """Finish the active trace (if any) and clear the context — the end
    of an item's pipeline (verdicts published, headers imported)."""
    act = _ACTIVE.get()
    if act is not None:
        (tracer_ if tracer_ is not None else tracer).finish(act[0])
        _ACTIVE.set(None)


def discard_active(tracer_: Optional[Tracer] = None) -> None:
    """Close and drop the active trace (if any) without retention — the
    shed/overload paths, where the item's pipeline ends by design."""
    act = _ACTIVE.get()
    if act is not None:
        (tracer_ if tracer_ is not None else tracer).discard(act[0])
        _ACTIVE.set(None)


def clear_active() -> None:
    """Detach the current context from any trace without ending it — for
    long-lived tasks that inherited a request context at creation."""
    if _ACTIVE.get() is not None:
        _ACTIVE.set(None)
