"""Metrics timeline: fixed-interval ring-buffer history of the registry.

Every metric in tpunode/metrics.py is a point-in-time value; the moment
an incident is noticed, the shape that led up to it is gone.  This module
is the retrospective half: a sampler task snapshots the registry
(:meth:`Metrics.flat_sample` — counters, gauges, histogram
``.count``/``.sum`` moments) into per-series ring buffers on a fixed
interval, with **downsampling tiers** so recent history is fine-grained
and older history is cheap:

* tier 0 — every sample (default 1s × 600 = 10 minutes),
* tier 1 — every 15th sample (default 15s × 480 = 2 hours).

Decimation (keep the Nth sample) rather than averaging: counters are
monotonic so any retained sample is exact, and a gauge's decimated value
is a real observed value, not a synthetic mean.

Cardinality discipline: unlabeled series are always captured; **labeled**
series are captured only for families in ``label_families`` (default:
the per-host fleet series — ``sched.host_depth``, ``sched.host_steals``,
``verify.breaker_state``, ``mesh.host_chips`` — whose label set is fixed
at engine construction, plus ``slo.burn_rate`` whose label set is fixed
by the declared SLOs).
Per-peer families never reach the rings (address churn would grow them
without bound), and a hard ``max_series`` cap drops anything beyond it
(counted in ``tsdb.dropped_series``).

Query surface: :meth:`series`, :meth:`names`, :meth:`window` (the flight
recorder's bundle input), :meth:`fleet_history` (per-host view for
``Node.stats()["fleet_history"]`` and the ``/fleet`` endpoint).

Like span(): there is an off-switch — ``TPUNODE_NO_TSDB=1`` (or
``Timeline(disabled=True)``) makes :meth:`tick` one attribute read —
and the enabled per-tick cost is micro-benched (tests/test_timeseries.py
pins it well under 1% of a bench step).  Stdlib-only, never imports jax.
"""

from __future__ import annotations

import asyncio
import os
import threading
import time
from collections import deque
from typing import Callable, Iterable, Optional

from . import threadsan
from .metrics import Metrics, metrics

__all__ = ["Timeline", "DEFAULT_TIERS", "DEFAULT_LABEL_FAMILIES"]

# (decimation factor vs. the base sampling interval, ring capacity).
# With the default 1s base interval: 1s x 600 = 10min, 15s x 480 = 2h.
DEFAULT_TIERS: tuple[tuple[int, int], ...] = ((1, 600), (15, 480))

# Labeled families worth a ring per label value: the per-host fleet
# gauges (bounded label set — hosts are fixed at engine construction)
# and the per-SLO burn rates (bounded by the declared SLO set).
DEFAULT_LABEL_FAMILIES: tuple[str, ...] = (
    "sched.host_depth",
    "sched.host_steals",
    "verify.breaker_state",
    "mesh.host_chips",
    "slo.burn_rate",
    # host-affine feed surface (ISSUE 19): bounded by the fixed host set
    "sched.feed_idle",
    "sched.affinity_routed",
)


class Timeline:
    """Ring-buffered metrics history with downsampling tiers."""

    def __init__(
        self,
        interval: float = 1.0,
        tiers: tuple[tuple[int, int], ...] = DEFAULT_TIERS,
        registry: Optional[Metrics] = None,
        extra: Optional[Callable[[], dict]] = None,
        label_families: Iterable[str] = DEFAULT_LABEL_FAMILIES,
        max_series: int = 512,
        disabled: Optional[bool] = None,
    ):
        if disabled is None:
            disabled = os.environ.get("TPUNODE_NO_TSDB") == "1"
        self.disabled = disabled
        self.interval = interval
        self.tiers = tuple(tiers)
        self.registry = registry if registry is not None else metrics
        self.extra = extra  # node hook: series the registry does not carry
        self.label_families = tuple(label_families)
        self.max_series = max_series
        # series name -> per-tier deque[(ts, value)].  One lock: tick()
        # writes from the sampler task, window() reads from whatever
        # thread the flight recorder fires on (engine dispatch workers).
        self._lock = threadsan.lock("timeseries.rings")
        self._rings: dict[str, tuple[deque, ...]] = {}
        self._ticks = 0
        self._dropped: set[str] = set()
        # Labeled-series lifecycle (ISSUE 19): when the registry evicts
        # a label pair (host retirement at engine teardown, peer-session
        # end), retire the matching rings too — otherwise fleet churn
        # regrows them from the drop cap forever.  on_drop holds the
        # hook weakly; the bound method dies with this Timeline.
        self.registry.on_drop(self.drop_label)

    # -- capture --------------------------------------------------------------

    def _keep(self, key: str) -> bool:
        if "{" not in key:
            return True
        family = key.split("{", 1)[0]
        # histogram moments of a labeled family: strip the moment suffix
        if family.endswith(".count") or family.endswith(".sum"):
            return False
        return family in self.label_families

    def tick(self, now: Optional[float] = None) -> int:
        """Capture one sample of every kept series; returns the number of
        series written (0 when disabled)."""
        if self.disabled:
            return 0
        ts = time.time() if now is None else now
        sample = self.registry.flat_sample()
        if self.extra is not None:
            try:
                sample.update(self.extra())
            except Exception:
                self.registry.inc("tsdb.extra_errors")
        with self._lock:
            self._ticks += 1
            # which tiers take this sample (tier 0 takes every one)
            live = tuple(
                i for i, (decim, _) in enumerate(self.tiers)
                if self._ticks % decim == 0
            )
            written = 0
            for key, value in sample.items():
                if not self._keep(key):
                    continue
                rings = self._rings.get(key)
                if rings is None:
                    if len(self._rings) >= self.max_series:
                        if key not in self._dropped:
                            self._dropped.add(key)
                            self.registry.inc("tsdb.dropped_series")
                        continue
                    rings = self._rings[key] = tuple(
                        deque(maxlen=cap) for _, cap in self.tiers
                    )
                point = (ts, value)
                for i in live:
                    rings[i].append(point)
                written += 1
        self.registry.inc("tsdb.samples")
        self.registry.set_gauge("tsdb.series", float(len(self._rings)))
        return written

    def drop_label(self, key: str, value: str) -> None:
        """Retire every ring whose rendered series key carries
        ``key="value"`` — the Timeline half of the registry's
        :meth:`Metrics.drop_label` eviction (wired via ``on_drop`` at
        construction).  Matching keys leave the ``_dropped`` set too:
        a host name REUSED by a future fleet gets a fresh ring instead
        of being silently discarded against the old cap entry."""
        needle = f'{key}="{value}"'
        with self._lock:
            for name in [n for n in self._rings if needle in n]:
                del self._rings[name]
            for name in [n for n in self._dropped if needle in n]:
                self._dropped.discard(name)

    # -- query ----------------------------------------------------------------

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._rings)

    def series(
        self, name: str, tier: int = 0, since: float = 0.0
    ) -> list[tuple[float, float]]:
        """Points ``[(ts, value), ...]`` (oldest first) for one series.
        Unknown series (or a disabled timeline) -> empty list."""
        with self._lock:
            rings = self._rings.get(name)
            if rings is None or not 0 <= tier < len(rings):
                return []
            pts = list(rings[tier])
        if since:
            pts = [p for p in pts if p[0] >= since]
        return pts

    def window(
        self, start: float, end: float, tier: int = 0
    ) -> dict[str, list[tuple[float, float]]]:
        """Every series' points with ``start <= ts <= end`` — the flight
        recorder's "timeline around the trigger" bundle section.  Series
        with no points in the window are omitted."""
        with self._lock:
            snap = {
                name: list(rings[tier])
                for name, rings in self._rings.items()
                if tier < len(rings)
            }
        out: dict[str, list[tuple[float, float]]] = {}
        for name, pts in snap.items():
            kept = [p for p in pts if start <= p[0] <= end]
            if kept:
                out[name] = kept
        return out

    def fleet_history(self, tier: int = 0) -> dict[str, dict[str, list]]:
        """Per-host view of the labeled fleet series:
        ``{host: {family: [(ts, value), ...]}}`` — how an 8→1→8 shrink
        looked, reconstructible after the fact."""
        with self._lock:
            snap = {
                name: list(rings[tier])
                for name, rings in self._rings.items()
                if "{" in name and tier < len(rings)
            }
        out: dict[str, dict[str, list]] = {}
        for name, pts in snap.items():
            family, _, labels = name.partition("{")
            host = None
            for part in labels.rstrip("}").split(","):
                k, _, v = part.partition("=")
                if k == "host":
                    host = v.strip('"')
                    break
            if host is None:
                continue
            out.setdefault(host, {})[family] = pts
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": not self.disabled,
                "interval": self.interval,
                "tiers": [
                    {"interval": self.interval * decim, "capacity": cap}
                    for decim, cap in self.tiers
                ],
                "series": len(self._rings),
                "ticks": self._ticks,
                "dropped_series": len(self._dropped),
            }

    # -- loop -----------------------------------------------------------------

    async def run(self) -> None:
        """Linked sampler loop (``NodeConfig.timeline_interval``)."""
        while True:
            await asyncio.sleep(self.interval)
            self.tick()
