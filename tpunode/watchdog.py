"""Stall watchdog: localize hangs instead of discovering them post-mortem.

The BENCH_r05 outage mode — ``jax.devices`` blocking for an entire
watchdog budget with nothing in the logs but a timeout — is exactly the
failure this actor exists for.  It watches three stall surfaces:

* **event-loop lag** — the gap between when a timer should have fired and
  when it did.  A blocked loop (sync I/O, a long pure-Python section)
  shows up here before anything else does.  Exposed as the
  ``watchdog.loop_lag_seconds`` gauge + ``watchdog.loop_lag`` histogram.
* **mailbox head age** — per-:class:`tpunode.actors.Mailbox` oldest-message
  age.  A healthy actor drains its queue; a head message older than the
  threshold means the consumer is stuck, even when qsize looks plausible.
* **verify dispatch in-flight time** — how long the engine's current
  device dispatch has been running in its worker thread.  A wedged
  backend (the r05 hang) pins this while the event loop stays healthy.

Each stall emits ONE ``watchdog.stall`` event per episode (re-armed when
the condition clears) so a persistent hang cannot flood the event log.
The node links a :class:`Watchdog` like its other loops
(``NodeConfig.watchdog_interval``; 0 disables).
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from typing import Iterable, Optional

from .actors import Mailbox
from .events import EventLog, events
from .metrics import metrics

__all__ = ["WatchdogConfig", "Watchdog"]

log = logging.getLogger("tpunode.watchdog")

metrics.describe(
    "watchdog.stalled",
    "stall surfaces currently in an episode (0 = healthy)",
)


@dataclass
class WatchdogConfig:
    interval: float = 1.0  # seconds between checks
    lag_threshold: float = 0.5  # event-loop lag that counts as a stall
    mailbox_age_threshold: float = 30.0  # head-message age that counts
    dispatch_stall_threshold: float = 60.0  # verify dispatch in-flight time


class Watchdog:
    """Periodic stall checker (``tick``-style, like StatsReporter: the
    ``run`` loop and tests both drive :meth:`check`)."""

    def __init__(
        self,
        cfg: Optional[WatchdogConfig] = None,
        mailboxes: Iterable[Mailbox] = (),
        engine=None,  # anything with dispatch_inflight_seconds() -> float
        log_: Optional[EventLog] = None,
        attributor=None,  # asyncsan.LoopAttributor (or None): names the
        # frame that froze the loop, merged into event_loop stall events
    ):
        self.cfg = cfg or WatchdogConfig()
        self.mailboxes = list(mailboxes)
        self.engine = engine
        self.log = log_ if log_ is not None else events
        self.attributor = attributor
        # stall keys currently in an episode: emit once, re-arm on clear
        self._stalled: set[str] = set()
        self._last_lag = 0.0  # newest measured loop lag (snapshot())

    def add_mailbox(self, mb: Mailbox) -> None:
        self.mailboxes.append(mb)

    # -- checks ---------------------------------------------------------------

    def check(self, lag: float = 0.0) -> list[dict]:
        """One pass over every stall surface; returns the ``watchdog.stall``
        events emitted this pass (empty on a healthy node)."""
        emitted: list[dict] = []
        self._last_lag = lag
        metrics.set_gauge("watchdog.loop_lag_seconds", lag)
        metrics.observe("watchdog.loop_lag", lag)
        if lag > self.cfg.lag_threshold:
            fields = dict(
                kind="event_loop", lag_seconds=round(lag, 4),
                threshold=self.cfg.lag_threshold,
            )
            # asyncsan attribution: the stack captured DURING the freeze
            # upgrades "the loop stalled" to "the loop stalled here".
            # max_age scopes the capture to THIS episode — the freeze just
            # measured plus a couple of intervals of slack — so a stale
            # capture from an earlier stall never blames the wrong code.
            if self.attributor is not None:
                blocked = self.attributor.last_blocked(
                    max_age=lag + 2 * self.cfg.interval
                )
                if blocked is not None:
                    fields["blocked_frames"] = blocked["frames"]
                    fields["blocked_age_seconds"] = blocked["age_seconds"]
            emitted += self._stall("event_loop", **fields)
        else:
            self._clear("event_loop")
        now = time.monotonic()
        for mb in self.mailboxes:
            age = mb.oldest_age(now)
            key = f"mailbox:{mb.name or id(mb)}"
            if age > self.cfg.mailbox_age_threshold:
                emitted += self._stall(
                    key, kind="mailbox", mailbox=mb.name,
                    age_seconds=round(age, 3), depth=mb.qsize(),
                    threshold=self.cfg.mailbox_age_threshold,
                )
            else:
                self._clear(key)
        if self.engine is not None:
            # Oldest-inflight age (ISSUE 10): with a dispatch pipeline
            # the engine tracks per-lane start times and reports the
            # OLDEST — a single wedged lane is visible even while
            # younger lanes keep completing.  The contract is unchanged:
            # 0.0 when idle, one stall event per episode.
            age = self.engine.dispatch_inflight_seconds()
            if age > self.cfg.dispatch_stall_threshold:
                fields = dict(
                    kind="verify_dispatch",
                    age_seconds=round(age, 3),
                    threshold=self.cfg.dispatch_stall_threshold,
                )
                depth = getattr(self.engine, "dispatch_inflight", None)
                if depth is not None:
                    fields["inflight"] = depth()
                emitted += self._stall("verify_dispatch", **fields)
            else:
                self._clear("verify_dispatch")
        # Level signal for the SLO evaluator (ISSUE 17): episodes emit one
        # event each, but burn-rate accounting needs "are we stalled NOW".
        metrics.set_gauge("watchdog.stalled", float(len(self._stalled)))
        return emitted

    def snapshot(self) -> dict:
        """Current state of every stall surface — the flight recorder's
        ``watchdog`` bundle section (what was stuck, and how stuck, at
        the moment of the trigger)."""
        now = time.monotonic()
        out: dict = {
            "last_lag_seconds": round(self._last_lag, 4),
            "stalled": sorted(self._stalled),
            "mailboxes": [
                {
                    "mailbox": mb.name,
                    "oldest_age_seconds": round(mb.oldest_age(now), 3),
                    "depth": mb.qsize(),
                }
                for mb in self.mailboxes
            ],
            "thresholds": {
                "lag": self.cfg.lag_threshold,
                "mailbox_age": self.cfg.mailbox_age_threshold,
                "dispatch_stall": self.cfg.dispatch_stall_threshold,
            },
        }
        if self.engine is not None:
            out["dispatch_inflight_seconds"] = round(
                self.engine.dispatch_inflight_seconds(), 3
            )
            depth = getattr(self.engine, "dispatch_inflight", None)
            if depth is not None:
                out["dispatch_inflight"] = depth()
        return out

    def _stall(self, key: str, **fields) -> list[dict]:
        if key in self._stalled:
            return []  # already reported this episode
        self._stalled.add(key)
        metrics.inc("watchdog.stalls")
        log.warning("[Watchdog] stall detected: %s %r", key, fields)
        return [self.log.emit("watchdog.stall", **fields)]

    def _clear(self, key: str) -> None:
        if key in self._stalled:
            self._stalled.discard(key)
            log.info("[Watchdog] stall cleared: %s", key)

    # -- loop -----------------------------------------------------------------

    async def run(self) -> None:
        """Linked watchdog loop: measures its own wakeup lag as the
        event-loop health signal, then sweeps the other surfaces."""
        last = time.monotonic()
        while True:
            await asyncio.sleep(self.cfg.interval)
            now = time.monotonic()
            lag = max(0.0, now - last - self.cfg.interval)
            self.check(lag)
            last = time.monotonic()
