"""Network parameter tables for Bitcoin and Bitcoin Cash chains.

The reference consumes these as haskoin-core's ``Network`` constants object
(reference: package.yaml:25; used at src/Haskoin/Node/PeerMgr.hs:282,584-585
and src/Haskoin/Node/Chain.hs:330).  Each network bundles the wire magic, DNS
seeds, default port, genesis block header, and the difficulty rules the header
consensus code (tpunode/headers.py) needs.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .util import bits_to_target

__all__ = [
    "Network",
    "BTC",
    "BTC_TEST",
    "BTC_REGTEST",
    "BCH",
    "BCH_TEST",
    "BCH_REGTEST",
    "NETWORKS",
]

# Service bits (protocol: version message `services` field)
NODE_NETWORK = 1 << 0
NODE_WITNESS = 1 << 3

# P2P protocol version we advertise (reference PeerMgr.hs:866-867).
PROTOCOL_VERSION = 70012


@dataclass(frozen=True)
class Genesis:
    version: int
    merkle: bytes  # internal byte order
    timestamp: int
    bits: int
    nonce: int


# Coinbase merkle root shared by every Bitcoin-lineage genesis block
# (display order 4a5e1e4b...; stored internal/little-endian).
_GENESIS_MERKLE = bytes.fromhex(
    "4a5e1e4baab89f3a32518a88c31bc87f618f76673e2cc77ab2127b7afdeda33b"
)[::-1]


@dataclass(frozen=True)
class Network:
    """Static consensus + wire constants for one chain."""

    name: str
    magic: int  # wire magic, serialized big-endian (4 bytes)
    default_port: int
    seeds: tuple[str, ...]
    user_agent: str
    segwit: bool
    genesis: Genesis
    pow_limit: int  # maximum (easiest) target
    pow_target_timespan: int = 14 * 24 * 3600  # two weeks
    pow_target_spacing: int = 600
    # testnet3/regtest: allow min-difficulty blocks after 2*spacing idle
    allow_min_difficulty: bool = False
    # regtest: no retargeting at all
    no_retargeting: bool = False
    # Bitcoin Cash difficulty hard forks (mainnet/testnet heights; None on BTC
    # and on regtest where they never activate via height).
    bch: bool = False
    eda_height: int | None = None  # UAHF emergency difficulty adjustment
    daa_height: int | None = None  # Nov 2017 cw-144 DAA
    asert_height: int | None = None  # Nov 2020 aserti3-2d
    asert_anchor: tuple[int, int, int] | None = None  # (height, bits, prev timestamp)

    @property
    def retarget_interval(self) -> int:
        return self.pow_target_timespan // self.pow_target_spacing  # 2016

    @property
    def pow_limit_bits(self) -> int:
        from .util import target_to_bits

        return target_to_bits(self.pow_limit)


_MAINNET_POW_LIMIT = bits_to_target(0x1D00FFFF)
_REGTEST_POW_LIMIT = bits_to_target(0x207FFFFF)

BTC = Network(
    name="btc",
    magic=0xF9BEB4D9,
    default_port=8333,
    seeds=(
        "seed.bitcoin.sipa.be",
        "dnsseed.bluematt.me",
        "dnsseed.bitcoin.dashjr.org",
        "seed.bitcoinstats.com",
        "seed.bitcoin.jonasschnelli.ch",
        "seed.btc.petertodd.org",
    ),
    user_agent="/tpunode:0.1.0/",
    segwit=True,
    genesis=Genesis(1, _GENESIS_MERKLE, 1231006505, 0x1D00FFFF, 2083236893),
    pow_limit=_MAINNET_POW_LIMIT,
)

BTC_TEST = Network(
    name="btctest",
    magic=0x0B110907,
    default_port=18333,
    seeds=(
        "testnet-seed.bitcoin.jonasschnelli.ch",
        "seed.tbtc.petertodd.org",
        "seed.testnet.bitcoin.sprovoost.nl",
        "testnet-seed.bluematt.me",
    ),
    user_agent="/tpunode:0.1.0/",
    segwit=True,
    genesis=Genesis(1, _GENESIS_MERKLE, 1296688602, 0x1D00FFFF, 414098458),
    pow_limit=_MAINNET_POW_LIMIT,
    allow_min_difficulty=True,
)

BTC_REGTEST = Network(
    name="btcreg",
    magic=0xFABFB5DA,
    default_port=18444,
    seeds=(),
    user_agent="/tpunode:0.1.0/",
    segwit=True,
    genesis=Genesis(1, _GENESIS_MERKLE, 1296688602, 0x207FFFFF, 2),
    pow_limit=_REGTEST_POW_LIMIT,
    allow_min_difficulty=True,
    no_retargeting=True,
)

BCH = Network(
    name="bch",
    magic=0xE3E1F3E8,
    default_port=8333,
    seeds=(
        "seed.bitcoinabc.org",
        "seed.bchd.cash",
        "btccash-seeder.bitcoinunlimited.info",
        "seed.flowee.cash",
    ),
    user_agent="/tpunode:0.1.0/",
    segwit=False,
    genesis=Genesis(1, _GENESIS_MERKLE, 1231006505, 0x1D00FFFF, 2083236893),
    pow_limit=_MAINNET_POW_LIMIT,
    bch=True,
    eda_height=478558,
    daa_height=504031,
    asert_height=661647,
    # ASERT anchor: height, anchor block nBits, parent-of-anchor timestamp
    # (BCH mainnet activation block 661647 per the aserti3-2d spec).
    asert_anchor=(661647, 0x1804DAFE, 1605447844),
)

BCH_TEST = Network(
    name="bchtest",
    magic=0xF4E5F3F4,
    default_port=18333,
    seeds=(
        "testnet-seed.bitcoinabc.org",
        "testnet-seed.bchd.cash",
    ),
    user_agent="/tpunode:0.1.0/",
    segwit=False,
    genesis=Genesis(1, _GENESIS_MERKLE, 1296688602, 0x1D00FFFF, 414098458),
    pow_limit=_MAINNET_POW_LIMIT,
    allow_min_difficulty=True,
    bch=True,
    eda_height=1155875,
    daa_height=1188697,
    asert_height=1421481,
    asert_anchor=(1421481, 0x1D00FFFF, 1605445400),
)

BCH_REGTEST = Network(
    name="bchreg",
    magic=0xDAB5BFFA,
    default_port=18444,
    seeds=(),
    user_agent="/tpunode:0.1.0/",
    segwit=False,
    genesis=Genesis(1, _GENESIS_MERKLE, 1296688602, 0x207FFFFF, 2),
    pow_limit=_REGTEST_POW_LIMIT,
    allow_min_difficulty=True,
    no_retargeting=True,
    bch=True,
)

NETWORKS: dict[str, Network] = {
    n.name: n for n in (BTC, BTC_TEST, BTC_REGTEST, BCH, BCH_TEST, BCH_REGTEST)
}
