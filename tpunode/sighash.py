"""Transaction signature hashes (what ECDSA actually signs).

The reference doesn't compute sighashes itself (haskoin-core does, for its
wallet side); the verify engine needs them to turn raw transactions into
(pubkey, digest, signature) triples.  Implements:

* the legacy (pre-segwit) sighash algorithm, including the historical
  SIGHASH_SINGLE out-of-range "hash = 1" quirk,
* BIP143 (segwit v0) digests, given the input amount,
* the BCH variant (BIP143-style with FORKID, used by Bitcoin Cash),
* BIP341 (taproot, segwit v1) digests, given EVERY input's prevout
  amount and scriptPubKey (keypath spends sign over the whole prevout
  set — the structural reason taproot extraction needs the extended
  prevout oracle).

Script handling is deliberately minimal: ``script_code`` is supplied by the
caller (tpunode/txverify.py derives it for the standard templates).
"""

from __future__ import annotations

import hashlib
from typing import Optional, Sequence

from .util import double_sha256, write_varint, write_varstr
from .wire import OutPoint, Tx, TxIn, TxOut

__all__ = [
    "SIGHASH_ALL",
    "SIGHASH_NONE",
    "SIGHASH_SINGLE",
    "SIGHASH_ANYONECANPAY",
    "SIGHASH_FORKID",
    "SIGHASH_DEFAULT",
    "legacy_sighash",
    "bip143_sighash",
    "bip341_sighash",
    "tapleaf_hash",
    "valid_taproot_hashtype",
]

SIGHASH_ALL = 0x01
SIGHASH_NONE = 0x02
SIGHASH_SINGLE = 0x03
SIGHASH_FORKID = 0x40  # BCH
SIGHASH_ANYONECANPAY = 0x80
SIGHASH_DEFAULT = 0x00  # BIP341: 64-byte signature, ALL semantics


def legacy_sighash(tx: Tx, index: int, script_code: bytes, hashtype: int) -> int:
    """Pre-segwit digest, as an integer (big-endian interpretation of the
    double-SHA256), matching what goes into ECDSA as ``z``."""
    base = hashtype & 0x1F
    if base == SIGHASH_SINGLE and index >= len(tx.outputs):
        # Historical quirk: out-of-range SIGHASH_SINGLE signs the digest "1".
        return 1

    inputs = []
    if hashtype & SIGHASH_ANYONECANPAY:
        src = [tx.inputs[index]]
        inputs = [TxIn(src[0].prevout, script_code, src[0].sequence)]
    else:
        for i, txin in enumerate(tx.inputs):
            script = script_code if i == index else b""
            seq = txin.sequence
            if i != index and base in (SIGHASH_NONE, SIGHASH_SINGLE):
                seq = 0
            inputs.append(TxIn(txin.prevout, script, seq))

    if base == SIGHASH_NONE:
        outputs: tuple[TxOut, ...] = ()
    elif base == SIGHASH_SINGLE:
        outputs = tuple(
            TxOut(-1 & 0xFFFFFFFFFFFFFFFF, b"") if i < index else tx.outputs[i]
            for i in range(index + 1)
        )
    else:
        outputs = tx.outputs

    stripped = Tx(
        version=tx.version,
        inputs=tuple(inputs),
        outputs=outputs,
        locktime=tx.locktime,
    )
    preimage = stripped.serialize(include_witness=False) + hashtype.to_bytes(
        4, "little"
    )
    return int.from_bytes(double_sha256(preimage), "big")


def bip143_sighash(
    tx: Tx,
    index: int,
    script_code: bytes,
    amount: int,
    hashtype: int,
) -> int:
    """Segwit v0 digest (BIP143); also the BCH replay-protected algorithm
    when ``hashtype`` carries SIGHASH_FORKID."""
    base = hashtype & 0x1F
    anyonecanpay = bool(hashtype & SIGHASH_ANYONECANPAY)

    if anyonecanpay:
        hash_prevouts = b"\x00" * 32
    else:
        hash_prevouts = double_sha256(
            b"".join(i.prevout.serialize() for i in tx.inputs)
        )
    if anyonecanpay or base in (SIGHASH_NONE, SIGHASH_SINGLE):
        hash_sequence = b"\x00" * 32
    else:
        hash_sequence = double_sha256(
            b"".join(i.sequence.to_bytes(4, "little") for i in tx.inputs)
        )
    if base not in (SIGHASH_NONE, SIGHASH_SINGLE):
        hash_outputs = double_sha256(b"".join(o.serialize() for o in tx.outputs))
    elif base == SIGHASH_SINGLE and index < len(tx.outputs):
        hash_outputs = double_sha256(tx.outputs[index].serialize())
    else:
        hash_outputs = b"\x00" * 32

    txin = tx.inputs[index]
    preimage = (
        tx.version.to_bytes(4, "little")
        + hash_prevouts
        + hash_sequence
        + txin.prevout.serialize()
        + write_varstr(script_code)
        + amount.to_bytes(8, "little")
        + txin.sequence.to_bytes(4, "little")
        + hash_outputs
        + tx.locktime.to_bytes(4, "little")
        + hashtype.to_bytes(4, "little")
    )
    return int.from_bytes(double_sha256(preimage), "big")


def _tagged_hash(tag: bytes, data: bytes) -> bytes:
    th = hashlib.sha256(tag).digest()
    return hashlib.sha256(th + th + data).digest()


def valid_taproot_hashtype(hashtype: int) -> bool:
    """BIP341's valid hash_type set: 0x00 (default) or base 1..3, with or
    without ANYONECANPAY.  Anything else makes the spend invalid."""
    return hashtype in (0x00, 0x01, 0x02, 0x03, 0x81, 0x82, 0x83)


def tapleaf_hash(script: bytes, leaf_version: int = 0xC0) -> bytes:
    """BIP341 TapLeaf hash: tagged_hash("TapLeaf", version ∥ varstr(script))
    — the script-path sighash (BIP342) commits to the executed leaf."""
    return _tagged_hash(
        b"TapLeaf", bytes([leaf_version]) + write_varstr(script)
    )


def bip341_sighash(
    tx: Tx,
    index: int,
    amounts: Sequence[int],
    scripts: Sequence[bytes],
    hashtype: int = SIGHASH_DEFAULT,
    annex: Optional[bytes] = None,
    leaf_hash: Optional[bytes] = None,
) -> Optional[int]:
    """Taproot (segwit v1) signature message, per BIP341's SigMsg:
    KEYPATH (``ext_flag = 0``) when ``leaf_hash`` is None, SCRIPT-path
    (``ext_flag = 1``, BIP342 extension: tapleaf hash ∥ key_version 0 ∥
    codesep position 0xFFFFFFFF) when the executed leaf's
    :func:`tapleaf_hash` is supplied.

    ``amounts``/``scripts`` are the spent outputs' values and
    scriptPubKeys for ALL of ``tx``'s inputs, in input order (with
    ANYONECANPAY only entry ``index`` is consulted).  ``annex`` is the
    raw annex WITHOUT its 0x50 prefix stripped (i.e. the full witness
    element), or None.  All hashes are single SHA-256 (unlike
    legacy/BIP143's double).

    Returns the digest as an int, or None when the spend is structurally
    invalid under BIP341 (invalid hash_type, or SIGHASH_SINGLE with no
    matching output) — the caller turns None into an auto-invalid item,
    matching consensus "validation failure", not "unsupported".
    """
    if not valid_taproot_hashtype(hashtype):
        return None
    base = hashtype & 3
    anyonecanpay = bool(hashtype & SIGHASH_ANYONECANPAY)
    if base == SIGHASH_SINGLE and index >= len(tx.outputs):
        return None  # BIP341: invalid (no legacy "hash = 1" quirk)

    msg = bytearray()
    msg.append(hashtype)
    msg += tx.version.to_bytes(4, "little")
    msg += tx.locktime.to_bytes(4, "little")
    if not anyonecanpay:
        msg += hashlib.sha256(
            b"".join(i.prevout.serialize() for i in tx.inputs)
        ).digest()
        msg += hashlib.sha256(
            b"".join(int(a).to_bytes(8, "little") for a in amounts)
        ).digest()
        msg += hashlib.sha256(
            b"".join(write_varstr(s) for s in scripts)
        ).digest()
        msg += hashlib.sha256(
            b"".join(i.sequence.to_bytes(4, "little") for i in tx.inputs)
        ).digest()
    if base not in (SIGHASH_NONE, SIGHASH_SINGLE):
        msg += hashlib.sha256(
            b"".join(o.serialize() for o in tx.outputs)
        ).digest()
    ext_flag = 0 if leaf_hash is None else 1
    msg.append(ext_flag * 2 + (1 if annex is not None else 0))  # spend_type
    txin = tx.inputs[index]
    if anyonecanpay:
        msg += txin.prevout.serialize()
        msg += int(amounts[index]).to_bytes(8, "little")
        msg += write_varstr(scripts[index])
        msg += txin.sequence.to_bytes(4, "little")
    else:
        msg += index.to_bytes(4, "little")
    if annex is not None:
        msg += hashlib.sha256(write_varstr(annex)).digest()
    if base == SIGHASH_SINGLE:
        msg += hashlib.sha256(tx.outputs[index].serialize()).digest()
    if leaf_hash is not None:
        # BIP342 sighash extension (key_version 0; no OP_CODESEPARATOR in
        # the templates this engine extracts, so the position is the
        # "none executed" sentinel)
        msg += leaf_hash
        msg.append(0x00)
        msg += (0xFFFFFFFF).to_bytes(4, "little")
    return int.from_bytes(
        _tagged_hash(b"TapSighash", b"\x00" + bytes(msg)), "big"
    )
