"""Transaction signature hashes (what ECDSA actually signs).

The reference doesn't compute sighashes itself (haskoin-core does, for its
wallet side); the verify engine needs them to turn raw transactions into
(pubkey, digest, signature) triples.  Implements:

* the legacy (pre-segwit) sighash algorithm, including the historical
  SIGHASH_SINGLE out-of-range "hash = 1" quirk,
* BIP143 (segwit v0) digests, given the input amount,
* the BCH variant (BIP143-style with FORKID, used by Bitcoin Cash).

Script handling is deliberately minimal: ``script_code`` is supplied by the
caller (tpunode/txverify.py derives it for the standard templates).
"""

from __future__ import annotations

from .util import double_sha256, write_varint, write_varstr
from .wire import OutPoint, Tx, TxIn, TxOut

__all__ = [
    "SIGHASH_ALL",
    "SIGHASH_NONE",
    "SIGHASH_SINGLE",
    "SIGHASH_ANYONECANPAY",
    "SIGHASH_FORKID",
    "legacy_sighash",
    "bip143_sighash",
]

SIGHASH_ALL = 0x01
SIGHASH_NONE = 0x02
SIGHASH_SINGLE = 0x03
SIGHASH_FORKID = 0x40  # BCH
SIGHASH_ANYONECANPAY = 0x80


def legacy_sighash(tx: Tx, index: int, script_code: bytes, hashtype: int) -> int:
    """Pre-segwit digest, as an integer (big-endian interpretation of the
    double-SHA256), matching what goes into ECDSA as ``z``."""
    base = hashtype & 0x1F
    if base == SIGHASH_SINGLE and index >= len(tx.outputs):
        # Historical quirk: out-of-range SIGHASH_SINGLE signs the digest "1".
        return 1

    inputs = []
    if hashtype & SIGHASH_ANYONECANPAY:
        src = [tx.inputs[index]]
        inputs = [TxIn(src[0].prevout, script_code, src[0].sequence)]
    else:
        for i, txin in enumerate(tx.inputs):
            script = script_code if i == index else b""
            seq = txin.sequence
            if i != index and base in (SIGHASH_NONE, SIGHASH_SINGLE):
                seq = 0
            inputs.append(TxIn(txin.prevout, script, seq))

    if base == SIGHASH_NONE:
        outputs: tuple[TxOut, ...] = ()
    elif base == SIGHASH_SINGLE:
        outputs = tuple(
            TxOut(-1 & 0xFFFFFFFFFFFFFFFF, b"") if i < index else tx.outputs[i]
            for i in range(index + 1)
        )
    else:
        outputs = tx.outputs

    stripped = Tx(
        version=tx.version,
        inputs=tuple(inputs),
        outputs=outputs,
        locktime=tx.locktime,
    )
    preimage = stripped.serialize(include_witness=False) + hashtype.to_bytes(
        4, "little"
    )
    return int.from_bytes(double_sha256(preimage), "big")


def bip143_sighash(
    tx: Tx,
    index: int,
    script_code: bytes,
    amount: int,
    hashtype: int,
) -> int:
    """Segwit v0 digest (BIP143); also the BCH replay-protected algorithm
    when ``hashtype`` carries SIGHASH_FORKID."""
    base = hashtype & 0x1F
    anyonecanpay = bool(hashtype & SIGHASH_ANYONECANPAY)

    if anyonecanpay:
        hash_prevouts = b"\x00" * 32
    else:
        hash_prevouts = double_sha256(
            b"".join(i.prevout.serialize() for i in tx.inputs)
        )
    if anyonecanpay or base in (SIGHASH_NONE, SIGHASH_SINGLE):
        hash_sequence = b"\x00" * 32
    else:
        hash_sequence = double_sha256(
            b"".join(i.sequence.to_bytes(4, "little") for i in tx.inputs)
        )
    if base not in (SIGHASH_NONE, SIGHASH_SINGLE):
        hash_outputs = double_sha256(b"".join(o.serialize() for o in tx.outputs))
    elif base == SIGHASH_SINGLE and index < len(tx.outputs):
        hash_outputs = double_sha256(tx.outputs[index].serialize())
    else:
        hash_outputs = b"\x00" * 32

    txin = tx.inputs[index]
    preimage = (
        tx.version.to_bytes(4, "little")
        + hash_prevouts
        + hash_sequence
        + txin.prevout.serialize()
        + write_varstr(script_code)
        + amount.to_bytes(8, "little")
        + txin.sequence.to_bytes(4, "little")
        + hash_outputs
        + tx.locktime.to_bytes(4, "little")
        + hashtype.to_bytes(4, "little")
    )
    return int.from_bytes(double_sha256(preimage), "big")
