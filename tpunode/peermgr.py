"""Peer manager: fleet lifecycle actor.

Mirror of /root/reference/src/Haskoin/Node/PeerMgr.hs: a connect loop keeps
``max_peers`` sessions alive from an address book (static peers + DNS seeds +
``addr`` gossip), every session runs under a supervisor whose death
notifications become ``PeerDied`` handling, the version/verack handshake state
machine marks peers online (``online = version AND verack``), pings track RTT
(last 11, median ranks peers), and jittered health checks evict stale or old
peers.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import random
import statistics
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional, Union

from .actors import (
    LinkedTasks,
    Mailbox,
    Publisher,
    Supervisor,
    spawn_supervised,
)
from .events import events
from .metrics import metrics
from .params import NODE_NETWORK, PROTOCOL_VERSION, Network
from .peer import (
    CannotDecodePayload,
    DecodeHeaderError,
    DuplicateVersion,
    Peer,
    PeerConfig,
    PeerConnected,
    PeerDisconnected,
    PeerError,
    PeerIsMyself,
    PeerMisbehaving,
    PeerSentBadHeaders,
    PeerTimeout,
    PeerTooOld,
    NotNetworkPeer,
    PayloadTooLarge,
    UnknownPeer,
    WithConnection,
    run_peer,
)
from .wire import MsgPing, MsgPong, MsgVerAck, MsgVersion, NetworkAddress

__all__ = [
    "PeerMgrConfig",
    "OnlinePeer",
    "PeerMgr",
    "PROTOCOL_VERSION",
    "build_version",
    "to_host_service",
    "to_sock_addr",
]

log = logging.getLogger("tpunode.peermgr")

SockAddr = tuple[str, int]  # (host, port)

# Session-death causes that indicate peer misbehavior (vs. ordinary churn):
# these emit a ``peer.ban`` event so embedders doing reputation tracking
# see the protocol violation, not just a disconnect.
_BAN_ERRORS = (
    PeerMisbehaving,
    PeerSentBadHeaders,
    NotNetworkPeer,
    DuplicateVersion,
    PeerIsMyself,
    CannotDecodePayload,
    DecodeHeaderError,
    PayloadTooLarge,
)


@dataclass
class PeerMgrConfig:
    """Reference PeerMgr.hs:149-159."""

    max_peers: int
    peers: list[str]
    discover: bool
    address: NetworkAddress
    net: Network
    pub: Publisher
    timeout: float
    max_peer_life: float
    # injectable transport: SockAddr -> WithConnection (reference Node.hs:95)
    connect: Callable[[SockAddr], WithConnection]
    # -- fleet hardening (ISSUE 7) ------------------------------------------
    # Per-address dial backoff: decorrelated jitter
    # (next = min(cap, uniform(base, 3 * prev))), reset on a completed
    # handshake — a dead or flapping address cannot monopolize dial slots.
    dial_backoff_base: float = 0.5
    dial_backoff_cap: float = 30.0
    # Misbehavior-score escalation: each protocol-violation death (the
    # _BAN_ERRORS classes) bumps the address's score and bans it for
    # min(ban_cap, ban_base * 2**(score-1)) seconds — timed bans, not
    # one-shot kills, so a garbage-spewing peer stays gone for a while
    # but a once-glitchy one gets another chance.
    ban_base: float = 10.0
    ban_cap: float = 600.0
    # Reconnect-storm cap: at most `reconnect_burst` dials per
    # `reconnect_window` seconds; excess dials are deferred back into the
    # address book.  0 = auto (max(8, 2 * max_peers)); negative disables.
    reconnect_burst: int = 0
    reconnect_window: float = 1.0


@dataclass
class _AddrState:
    """Per-address dial/ban bookkeeping (ISSUE 7 fleet hardening).  The
    reference evicts misbehavers one-shot (PeerMgr.hs kills and forgets);
    here an address carries its dial backoff and misbehavior score across
    sessions so churn and garbage degrade that address's slot, not the
    fleet's."""

    backoff: float = 0.0  # current decorrelated-jitter backoff (seconds)
    not_before: float = 0.0  # monotonic: no dial before this
    failures: int = 0  # consecutive session deaths (reset on handshake)
    score: int = 0  # misbehavior incidents (never auto-reset)
    banned_until: float = 0.0  # monotonic: timed ban horizon


@dataclass
class OnlinePeer:
    """Book-keeping for one connected peer (reference PeerMgr.hs:183-195)."""

    address: SockAddr
    peer: Peer
    task: asyncio.Task
    nonce: int
    connected: float
    tickled: float
    verack: bool = False
    online: bool = False
    version: Optional[MsgVersion] = None
    ping: Optional[tuple[float, int]] = None  # (sent monotonic, nonce)
    pings: list[float] = field(default_factory=list)

    def median_ping(self) -> float:
        """Peers are ranked by median RTT; unknown = 60s
        (reference PeerMgr.hs:202-205,833-843)."""
        if not self.pings:
            return 60.0
        return statistics.median(self.pings)


# internal mailbox messages (reference PeerMgrMessage PeerMgr.hs:170-180)
@dataclass(frozen=True)
class _Connect:
    addr: SockAddr


@dataclass(frozen=True)
class _CheckPeer:
    peer: Peer


@dataclass(frozen=True)
class _PeerDied:
    task: asyncio.Task
    error: Optional[BaseException]


@dataclass(frozen=True)
class _ManagerBest:
    height: int


@dataclass(frozen=True)
class _PeerVersion:
    peer: Peer
    version: MsgVersion


@dataclass(frozen=True)
class _PeerVerAck:
    peer: Peer


@dataclass(frozen=True)
class _PeerPing:
    peer: Peer
    nonce: int


@dataclass(frozen=True)
class _PeerPong:
    peer: Peer
    nonce: int


@dataclass(frozen=True)
class _PeerAddrs:
    peer: Peer
    addrs: list[NetworkAddress]


@dataclass(frozen=True)
class _PeerTickle:
    peer: Peer


class PeerMgr:
    """The peer-manager actor handle (reference ``PeerMgr`` PeerMgr.hs:161-168
    + ``withPeerMgr`` PeerMgr.hs:207-234)."""

    def __init__(self, cfg: PeerMgrConfig, on_failure=None):
        self.cfg = cfg
        self.mailbox: Mailbox = Mailbox(name="peermgr")
        self.supervisor = Supervisor(on_death=self._peer_died, name="peers")
        self._best_height = 0
        self._addresses: set[SockAddr] = set()
        self._peers: list[OnlinePeer] = []
        # ISSUE 7: per-address backoff/ban state + the dial-rate window
        self._addr_state: dict[SockAddr, _AddrState] = {}
        self._dial_times: deque[float] = deque()
        self._burst: Optional[int] = (
            None
            if cfg.reconnect_burst < 0
            else (cfg.reconnect_burst or max(8, 2 * cfg.max_peers))
        )
        self._tasks = LinkedTasks(name="peermgr", on_failure=on_failure)
        self._started = asyncio.Event()

    # -- lifecycle ----------------------------------------------------------

    async def __aenter__(self) -> "PeerMgr":
        self._tasks.link(self._main_loop(), name="peermgr-main")
        self._tasks.link(self._connect_loop(), name="peermgr-connect")
        return self

    async def __aexit__(self, *exc) -> None:
        await self.supervisor.aclose()
        await self._tasks.__aexit__(*exc)

    def _peer_died(self, task: asyncio.Task, exc: Optional[BaseException]) -> None:
        # supervisor Notify -> PeerDied message (reference PeerMgr.hs:230)
        self.mailbox.send(_PeerDied(task, exc))

    async def _main_loop(self) -> None:
        # Block until the chain's initial best height arrives — the startup
        # ordering constraint of the reference (PeerMgr.hs:244-247).
        height = await self.mailbox.receive_match(
            lambda m: m.height if isinstance(m, _ManagerBest) else None
        )
        self._best_height = height
        self._started.set()
        while True:
            msg = await self.mailbox.receive()
            await self._dispatch(msg)

    async def _connect_loop(self) -> None:
        """Jittered top-up loop (reference ``withConnectLoop``
        PeerMgr.hs:606-625)."""
        await self._started.wait()
        while True:
            if len(self._peers) < self.cfg.max_peers:
                sa = await self._get_new_peer()
                if sa is not None:
                    self.mailbox.send(_Connect(sa))
            await asyncio.sleep(random.uniform(0.1, 5.0))

    # -- dispatch (reference PeerMgr.hs:304-396) -----------------------------

    async def _dispatch(self, msg) -> None:
        if isinstance(msg, _PeerVersion):
            self._on_version(msg.peer, msg.version)
        elif isinstance(msg, _PeerVerAck):
            self._on_verack(msg.peer)
        elif isinstance(msg, _PeerAddrs):
            self._on_addrs(msg.addrs)
        elif isinstance(msg, _PeerPong):
            self._on_pong(msg.peer, msg.nonce)
        elif isinstance(msg, _PeerPing):
            msg.peer.send_message(MsgPong(msg.nonce))
        elif isinstance(msg, _ManagerBest):
            self._best_height = msg.height
        elif isinstance(msg, _Connect):
            self._connect_peer(msg.addr)
        elif isinstance(msg, _PeerDied):
            self._process_peer_offline(msg.task)
        elif isinstance(msg, _CheckPeer):
            self._check_peer(msg.peer)
        elif isinstance(msg, _PeerTickle):
            o = self._find_peer(msg.peer)
            if o is not None:
                o.tickled = time.monotonic()

    def _on_version(self, p: Peer, v: MsgVersion) -> None:
        """Handshake step 1 (reference ``dispatch (PeerVersion ...)``
        PeerMgr.hs:311-329 + ``setPeerVersion`` :654-674)."""
        if v.services & NODE_NETWORK == 0:
            log.warning(
                "[PeerMgr] peer %s lacks network service bit; killing", p.label
            )
            events.emit(
                "peer.handshake", peer=p.label, ok=False,
                reason="not-network-peer",
            )
            p.kill(NotNetworkPeer(p.label))
            return
        if any(o.nonce == v.nonce for o in self._peers):
            log.warning("[PeerMgr] peer %s is myself (nonce match); killing", p.label)
            events.emit(
                "peer.handshake", peer=p.label, ok=False, reason="is-myself"
            )
            p.kill(PeerIsMyself(p.label))
            return
        o = self._find_peer(p)
        if o is None:
            p.kill(UnknownPeer(p.label))
            return
        log.debug(
            "[PeerMgr] version from %s: %d %s height=%d",
            p.label,
            v.version,
            v.user_agent.decode("latin-1"),
            v.start_height,
        )
        o.version = v
        o.online = o.verack
        p.send_message(MsgVerAck())
        if o.online:
            self._announce_peer(o)

    def _on_verack(self, p: Peer) -> None:
        """Handshake step 2 (reference PeerMgr.hs:330-343 + ``setPeerVerAck``
        :676-685)."""
        o = self._find_peer(p)
        if o is None:
            p.kill(UnknownPeer(p.label))
            return
        o.verack = True
        o.online = o.version is not None
        if o.online:
            self._announce_peer(o)

    def _announce_peer(self, o: OnlinePeer) -> None:
        # reference logConnectedPeers (PeerMgr.hs:285-290)
        st = self._addr_state.get(o.address)
        if st is not None:
            # success reset (ISSUE 7): a completed handshake clears the
            # dial backoff — misbehavior score deliberately persists
            st.backoff = 0.0
            st.not_before = 0.0
            st.failures = 0
        n_online = sum(1 for x in self._peers if x.online)
        log.info(
            "[PeerMgr] connected to peer %s (%d online)", o.peer.label, n_online
        )
        dial = time.monotonic() - o.connected
        metrics.observe("peermgr.dial_seconds", dial)
        metrics.set_gauge("peermgr.peers_online", n_online)
        v = o.version
        events.emit(
            "peer.handshake", peer=o.peer.label, ok=True,
            version=v.version if v else None,
            user_agent=v.user_agent.decode("latin-1") if v else None,
            height=v.start_height if v else None,
            dial_seconds=round(dial, 6),
        )
        events.emit("peer.connect", peer=o.peer.label, online=n_online)
        self.cfg.pub.publish(PeerConnected(o.peer))

    def _on_addrs(self, addrs: list[NetworkAddress]) -> None:
        """``addr`` gossip ingestion when discovery is on
        (reference PeerMgr.hs:344-360)."""
        if not self.cfg.discover:
            return
        log.debug("[PeerMgr] received %d addresses via gossip", len(addrs))
        for na in addrs:
            self._new_peer(na.to_host_port())

    def _on_pong(self, p: Peer, nonce: int) -> None:
        """RTT sample (reference ``gotPong`` PeerMgr.hs:636-648)."""
        o = self._find_peer(p)
        if o is None or o.ping is None:
            return
        sent, expected = o.ping
        if nonce != expected:
            return
        o.ping = None
        rtt = time.monotonic() - sent
        metrics.observe("peer.rtt", rtt)
        metrics.observe("peer.rtt", rtt, labels={"peer": o.peer.label})
        # newest 11 samples (reference keeps `take 11 $ diff : pings`)
        o.pings = ([rtt] + o.pings)[:11]

    def _check_peer(self, p: Peer) -> None:
        """Health check: lifetime eviction + tickle/ping staleness
        (reference ``checkPeer`` PeerMgr.hs:398-425)."""
        o = self._find_peer(p)
        if o is None:
            return
        now = time.monotonic()
        if now > o.connected + self.cfg.max_peer_life:
            log.info("[PeerMgr] peer %s exceeded max life; evicting", p.label)
            p.kill(PeerTooOld(p.label))
            return
        if now > o.tickled + self.cfg.timeout:
            if o.ping is None:
                log.debug("[PeerMgr] peer %s quiet; pinging", p.label)
                self._send_ping(o)
            else:
                log.warning("[PeerMgr] peer %s unresponsive; killing", p.label)
                p.kill(PeerTimeout(p.label))

    def _send_ping(self, o: OnlinePeer) -> None:
        if not o.online:
            return
        nonce = random.getrandbits(64)
        o.ping = (time.monotonic(), nonce)
        o.peer.send_message(MsgPing(nonce))

    def _process_peer_offline(self, task: asyncio.Task) -> None:
        """Peer task ended (reference ``processPeerOffline``
        PeerMgr.hs:447-487)."""
        o = next((x for x in self._peers if x.task is task), None)
        if o is None:
            return
        exc = task.exception() if task.done() and not task.cancelled() else None
        log.info(
            "[PeerMgr] peer %s offline%s (%d online)",
            o.peer.label,
            f": {exc}" if exc else "",
            sum(1 for x in self._peers if x.online) - (1 if o.online else 0),
        )
        metrics.inc("peermgr.disconnects")
        if not o.online:
            # died before completing the handshake: a failed dial
            metrics.inc("peermgr.connect_failures")
        events.emit(
            "peer.disconnect", peer=o.peer.label, online=o.online,
            error=repr(exc) if exc else None,
        )
        now = time.monotonic()
        st = self._addr_state.setdefault(o.address, _AddrState())
        # Dial backoff with decorrelated jitter (ISSUE 7): every session
        # death backs the address off; repeated failures grow the window
        # up to the cap, a completed handshake resets it (_announce_peer).
        st.failures += 1
        st.backoff = min(
            self.cfg.dial_backoff_cap,
            random.uniform(
                self.cfg.dial_backoff_base,
                max(self.cfg.dial_backoff_base, 3.0 * st.backoff),
            ),
        )
        st.not_before = now + st.backoff
        metrics.inc("peermgr.backoffs")
        metrics.observe("peermgr.backoff_seconds", st.backoff)
        events.emit(
            "peermgr.backoff", peer=o.peer.label,
            seconds=round(st.backoff, 3), failures=st.failures,
        )
        if isinstance(exc, _BAN_ERRORS):
            # Misbehavior-score escalation to a TIMED ban (ISSUE 7): the
            # address sits out min(cap, base * 2**(score-1)) seconds —
            # repeat offenders sit out exponentially longer.
            st.score += 1
            ban = min(
                self.cfg.ban_cap,
                self.cfg.ban_base * (2.0 ** min(st.score - 1, 16)),
            )
            st.banned_until = now + ban
            metrics.inc("peermgr.bans")
            metrics.inc("peermgr.timed_bans")
            events.emit(
                "peer.ban", peer=o.peer.label,
                reason=type(exc).__name__, error=str(exc),
                ban_seconds=round(ban, 1), score=st.score,
            )
        if o.online:
            self.cfg.pub.publish(PeerDisconnected(o.peer))
        self._peers.remove(o)
        # the address returns to the book behind its backoff/ban horizon
        # (gossip addresses used to vanish on death; static peers were
        # re-resolved anyway)
        self._addresses.add(o.address)
        # evict the dead peer's labeled series (peer.msgs{peer=},
        # peer.rtt{peer=}): churn through thousands of addresses must not
        # grow the registry without bound
        metrics.drop_label("peer", o.peer.label)
        metrics.set_gauge("peermgr.peers", len(self._peers))
        metrics.set_gauge(
            "peermgr.peers_online", sum(1 for x in self._peers if x.online)
        )

    # -- address book & connecting ------------------------------------------

    async def _load_peers(self) -> None:
        """Static peers + DNS seeds (reference PeerMgr.hs:266-283)."""
        for s in self.cfg.peers:
            for sa in await to_sock_addr(self.cfg.net, s):
                self._new_peer(sa)
        if self.cfg.discover:
            for seed in self.cfg.net.seeds:
                for sa in await to_sock_addr(self.cfg.net, seed):
                    self._new_peer(sa)

    def _new_peer(self, sa: SockAddr) -> None:
        """Add a candidate address unless already connected
        (reference ``newPeer`` PeerMgr.hs:627-634)."""
        if any(o.address == sa for o in self._peers):
            return
        self._addresses.add(sa)

    def _dialable(self, sa: SockAddr, now: float) -> bool:
        """Is this address past its backoff and ban horizons (ISSUE 7)?"""
        st = self._addr_state.get(sa)
        return st is None or (now >= st.not_before and now >= st.banned_until)

    async def _get_new_peer(self) -> Optional[SockAddr]:
        """Random unconnected candidate (reference ``getNewPeer``
        PeerMgr.hs:505-520), skipping addresses still backing off or
        serving a timed ban — those stay in the book for later."""
        await self._load_peers()
        now = time.monotonic()
        eligible = [sa for sa in self._addresses if self._dialable(sa, now)]
        while eligible:
            sa = random.choice(eligible)
            eligible.remove(sa)
            self._addresses.discard(sa)
            if not any(o.address == sa for o in self._peers):
                return sa
        return None

    # Address-state pruning bound: churn through thousands of gossip
    # addresses must not grow _addr_state without limit (the same
    # discipline as metrics.drop_label on peer churn).
    _ADDR_STATE_MAX = 4096

    def _prune_addr_state(self, now: float) -> None:
        if len(self._addr_state) <= self._ADDR_STATE_MAX:
            return
        for sa in [
            sa
            for sa, st in self._addr_state.items()
            if now >= st.not_before and now >= st.banned_until
            and st.score == 0
        ]:
            del self._addr_state[sa]

    def _connect_peer(self, sa: SockAddr) -> None:
        """Launch one supervised peer session (reference ``connectPeer``
        PeerMgr.hs:522-589)."""
        if any(o.address == sa for o in self._peers):
            return
        now = time.monotonic()
        if self._burst is not None:
            # Reconnect-storm cap (ISSUE 7): a mass disconnect (network
            # blip, remote restart) must not translate into an immediate
            # synchronized dial storm.  Excess dials defer back into the
            # address book behind a one-window not_before.
            while (
                self._dial_times
                and now - self._dial_times[0] > self.cfg.reconnect_window
            ):
                self._dial_times.popleft()
            if len(self._dial_times) >= self._burst:
                metrics.inc("peermgr.reconnects_capped")
                events.emit(
                    "peermgr.reconnect_capped",
                    address=f"{sa[0]}:{sa[1]}",
                    burst=self._burst,
                    window=self.cfg.reconnect_window,
                )
                st = self._addr_state.setdefault(sa, _AddrState())
                st.not_before = max(
                    st.not_before, now + self.cfg.reconnect_window
                )
                self._addresses.add(sa)
                return
            self._dial_times.append(now)
        self._prune_addr_state(now)
        label = f"[{sa[0]}]:{sa[1]}" if ":" in sa[0] else f"{sa[0]}:{sa[1]}"
        log.debug("[PeerMgr] connecting to %s", label)
        metrics.inc("peermgr.connect_attempts")
        nonce = random.getrandbits(64)
        inbox: Mailbox = Mailbox(name=f"peer-{label}")
        pc = PeerConfig(
            pub=self.cfg.pub,
            net=self.cfg.net,
            label=label,
            connect=self.cfg.connect(sa),
        )
        p = Peer(inbox, self.cfg.pub, label)
        task = self.supervisor.add_child(
            self._launch_peer(pc, p, inbox), name=f"peer-{label}"
        )
        # We speak first (reference PeerMgr.hs:564).
        ver = build_version(
            self.cfg.net,
            nonce,
            self._best_height,
            self.cfg.address,
            NetworkAddress.from_host_port(sa[0], sa[1], services=_srv(self.cfg.net)),
        )
        p.send_message(ver)
        now = time.monotonic()
        self._peers.append(
            OnlinePeer(
                address=sa,
                peer=p,
                task=task,
                nonce=nonce,
                connected=now,
                tickled=now,
            )
        )
        metrics.set_gauge("peermgr.peers", len(self._peers))

    async def _launch_peer(self, pc: PeerConfig, p: Peer, inbox: Mailbox) -> None:
        """Child body: the session linked with its jittered check timer
        (reference ``launch``/``withPeerLoop`` PeerMgr.hs:586-604)."""

        async def check_loop():
            while True:
                await asyncio.sleep(
                    random.uniform(0.75, 1.0) * self.cfg.timeout
                )
                self.mailbox.send(_CheckPeer(p))

        # ISSUE 3 satellite: the jittered check timer was a bare
        # create_task handle — registry-supervised now, still
        # cancelled+awaited on session exit
        timer = spawn_supervised(
            check_loop(), name=f"peer-check-{p.label}", owner=self.supervisor
        )
        try:
            await run_peer(pc, p, inbox)
        finally:
            timer.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await timer

    # -- event injectors (reference PeerMgr.hs:738-796) ----------------------

    def set_best(self, height: int) -> None:
        self.mailbox.send(_ManagerBest(height))

    def version(self, p: Peer, v: MsgVersion) -> None:
        self.mailbox.send(_PeerVersion(p, v))

    def verack(self, p: Peer) -> None:
        self.mailbox.send(_PeerVerAck(p))

    def ping(self, p: Peer, nonce: int) -> None:
        self.mailbox.send(_PeerPing(p, nonce))

    def pong(self, p: Peer, nonce: int) -> None:
        self.mailbox.send(_PeerPong(p, nonce))

    def addrs(self, p: Peer, addrs: list[NetworkAddress]) -> None:
        self.mailbox.send(_PeerAddrs(p, addrs))

    def tickle(self, p: Peer) -> None:
        self.mailbox.send(_PeerTickle(p))

    def connect(self, sa: SockAddr) -> None:
        self.mailbox.send(_Connect(sa))

    # -- queries (reference PeerMgr.hs:727-736) ------------------------------

    def _find_peer(self, p: Peer) -> Optional[OnlinePeer]:
        return next((o for o in self._peers if o.peer is p), None)

    def get_peers(self) -> list[OnlinePeer]:
        """Connected peers, best (lowest median RTT) first."""
        return sorted(
            (o for o in self._peers if o.online), key=OnlinePeer.median_ping
        )

    def fleet(self) -> list[OnlinePeer]:
        """Every tracked peer, online or mid-handshake (telemetry view)."""
        return list(self._peers)

    def get_online_peer(self, p: Peer) -> Optional[OnlinePeer]:
        return self._find_peer(p)

    def backoff_stats(self) -> dict:
        """Fleet-hardening snapshot (ISSUE 7) for Node.stats(): how many
        addresses are backing off or banned right now, plus the lifetime
        escalation counters."""
        now = time.monotonic()
        sts = self._addr_state.values()
        return {
            "addresses": len(self._addresses),
            "tracked": len(self._addr_state),
            "backing_off": sum(1 for s in sts if s.not_before > now),
            "banned": sum(1 for s in sts if s.banned_until > now),
            "backoffs": metrics.get("peermgr.backoffs"),
            "timed_bans": metrics.get("peermgr.timed_bans"),
            "capped_dials": metrics.get("peermgr.reconnects_capped"),
        }


def _srv(net: Network) -> int:
    # segwit service bit on networks that have it (reference PeerMgr.hs:583-585)
    return 8 if net.segwit else 0


def build_version(
    net: Network,
    nonce: int,
    height: int,
    local: NetworkAddress,
    remote: NetworkAddress,
    timestamp: Optional[int] = None,
) -> MsgVersion:
    """Build our ``version`` message (reference ``buildVersion``
    PeerMgr.hs:845-864)."""
    return MsgVersion(
        version=PROTOCOL_VERSION,
        services=local.services,
        timestamp=int(time.time()) if timestamp is None else timestamp,
        addr_recv=remote,
        addr_from=local,
        nonce=nonce,
        user_agent=net.user_agent.encode(),
        start_height=height,
        relay=True,
    )


def to_host_service(s: str) -> tuple[Optional[str], Optional[str]]:
    """Split "host", "host:port", "[v6]", "[v6]:port" (reference
    ``toHostService`` PeerMgr.hs:798-820)."""
    host: Optional[str]
    srv: Optional[str]
    if s.startswith("["):
        end = s.find("]")
        if end == -1:
            return None, None
        host = s[1:end] or None
        rest = s[end + 1 :]
        srv = rest[1:] if rest.startswith(":") else None
        return host, srv or None
    if s.startswith(":"):
        # leading colon: an IPv6 literal like "::1" (reference PeerMgr.hs:817)
        return s, None
    if ":" in s and s.count(":") > 1:
        # raw IPv6 literal without brackets
        return s, None
    head, sep, tail = s.partition(":")
    host = head or None
    srv = tail if sep else None
    return host, srv or None


async def to_sock_addr(net: Network, s: str) -> list[SockAddr]:
    """Resolve a peer string to socket addresses, filling the network default
    port (reference ``toSockAddr`` PeerMgr.hs:822-831)."""
    host, srv = to_host_service(s)
    if host is None:
        return []
    port = int(srv) if srv and srv.isdigit() else None
    if port is None:
        port = net.default_port
    try:
        loop = asyncio.get_running_loop()
        infos = await loop.getaddrinfo(host, port)
        out = []
        for _, _, _, _, sockaddr in infos:
            sa = (sockaddr[0], sockaddr[1])
            if sa not in out:
                out.append(sa)
        return out
    except OSError:
        return []
