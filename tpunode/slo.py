"""SLO engine: declarative objectives + multi-window burn-rate alerts.

The rest of the observability stack is retrospective — metrics (PR 1),
traces (PR 2), the timeline + flight recorder (PR 14) all answer "what
happened".  Nothing states an *objective*: ROADMAP item 3's
verification-as-a-service needs per-tenant quotas and QoS-aware
shedding, which presuppose a layer that can say "block-class verdict
latency is meeting its target, and we are burning error budget at rate
R".  This module is that layer:

* :class:`SloDef` — one declarative objective.  Three kinds:

  - ``latency`` — fraction of ``node.verdict_latency{priority=}``
    observations under ``threshold`` seconds.  Good/bad counts come
    straight from the live histogram's cumulative buckets
    (:meth:`tpunode.metrics.Histogram.count_le`); thresholds sit on
    bucket boundaries so the counts are exact, not interpolated.
  - ``stall`` — fraction of evaluator ticks with no watchdog stall
    episode active (the ``watchdog.stalled`` gauge).
  - ``breaker`` — fraction of ticks with the verify circuit breaker not
    open (the ``verify.breaker_state`` gauge).

* :class:`SloEvaluator` — a small linked task that samples each SLO's
  cumulative (good, bad) counts into two ring tiers scaled to the
  timeline's (tpunode/timeseries.py) 1s/15s tiers, and computes
  **multi-window burn rates**: burn = (bad fraction in window) / (1 −
  objective), over a fast 5-minute and a slow 1-hour window.  Burn ≥
  14.4 on the fast window (or ≥ 6 on the slow) means the error budget
  is being consumed at least that many times faster than the objective
  allows — the classic SRE two-window page condition.  A breach emits
  ONE ``slo.burn{slo=,window=}`` event per episode (re-armed when the
  burn drops below threshold, same latching as ``watchdog.stall``),
  which the flight recorder treats as a trigger: the bundle gains an
  ``slo`` section (definitions, budgets, burn history, and the verify
  cost ledger snapshot).

Like span()/the timeline, there is an off-switch — ``TPUNODE_NO_SLO=1``
or ``NodeConfig.slos=None`` — and the disabled :meth:`SloEvaluator.tick`
is one attribute read (micro-benched in tests/test_slo.py).  Stdlib
only, never imports jax; reads the registry, owns no locks beyond one.
"""

from __future__ import annotations

import asyncio
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Iterable, Optional

from . import threadsan
from .events import EventLog, events
from .metrics import Metrics, metrics

__all__ = [
    "DEFAULT_SLOS",
    "FAST_WINDOW",
    "SLOW_WINDOW",
    "SloDef",
    "SloEvaluator",
]

# Window sizes + page thresholds (Google SRE workbook's 2-window tiers),
# scaled to the timeline's 1s/15s ring tiers: the fast window reads the
# 1s ring (600 samples = 10 min capacity), the slow window the 15s ring
# (480 samples = 2 h capacity).
FAST_WINDOW = 300.0  # seconds
SLOW_WINDOW = 3600.0
FAST_BURN = 14.4  # burn-rate page thresholds per window
SLOW_BURN = 6.0

# verify.breaker_state gauge encoding (engine.CircuitBreaker.STATES):
# ready=0, degraded=1, open=2, probing=3.  Only "open" spends breaker
# budget — probing is the half-open recovery and degraded still serves.
_BREAKER_OPEN = 2.0


@dataclass(frozen=True)
class SloDef:
    """One declarative objective (``NodeConfig.slos`` row).

    ``objective`` is the target good fraction (0.99 = 1% error budget);
    ``threshold`` is the latency cut in seconds (``latency`` kind only —
    pick a :data:`tpunode.metrics.DEFAULT_BUCKETS` boundary so the
    histogram counts are exact); ``priority`` selects the
    ``node.verdict_latency`` label (``latency`` kind only)."""

    name: str
    kind: str  # "latency" | "stall" | "breaker"
    objective: float = 0.99
    threshold: float = 0.0
    priority: str = ""
    description: str = ""

    def __post_init__(self):
        if self.kind not in ("latency", "stall", "breaker"):
            raise ValueError(f"unknown SLO kind {self.kind!r}")
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"SLO {self.name}: objective must be in (0, 1)"
            )
        if self.kind == "latency" and (
            self.threshold <= 0 or not self.priority
        ):
            raise ValueError(
                f"SLO {self.name}: latency kind needs threshold+priority"
            )

    def describe(self) -> dict:
        out = {
            "name": self.name,
            "kind": self.kind,
            "objective": self.objective,
            "description": self.description,
        }
        if self.kind == "latency":
            out["threshold_seconds"] = self.threshold
            out["priority"] = self.priority
        return out


# Shipped defaults: per-class verdict-latency targets (thresholds on the
# log-scaled bucket boundaries 2**n µs — exact cumulative counts), a
# dispatch-stall budget and a breaker-open budget.  Tighter target for
# live block ingest, looser down the priority ladder.
DEFAULT_SLOS: tuple[SloDef, ...] = (
    SloDef(
        "verdict-latency-block", "latency", objective=0.99,
        threshold=1e-6 * 2**19, priority="block",  # ~0.524 s
        description="block-class submit->verdict latency",
    ),
    SloDef(
        "verdict-latency-mempool", "latency", objective=0.99,
        threshold=1e-6 * 2**21, priority="mempool",  # ~2.10 s
        description="mempool-class submit->verdict latency",
    ),
    SloDef(
        "verdict-latency-ibd", "latency", objective=0.95,
        threshold=1e-6 * 2**23, priority="ibd",  # ~8.39 s
        description="ibd-class submit->verdict latency",
    ),
    SloDef(
        "verdict-latency-bulk", "latency", objective=0.95,
        threshold=1e-6 * 2**24, priority="bulk",  # ~16.8 s
        description="bulk-class submit->verdict latency",
    ),
    SloDef(
        "dispatch-stall", "stall", objective=0.99,
        description="evaluator ticks with no watchdog stall active",
    ),
    SloDef(
        "breaker-open", "breaker", objective=0.99,
        description="evaluator ticks with the verify breaker not open",
    ),
)


class _SloState:
    """Per-SLO ring storage: cumulative (ts, good, bad) samples in two
    decimated tiers, mirroring the timeline's shape."""

    __slots__ = ("d", "rings", "good", "bad", "burn")

    def __init__(self, d: SloDef, tiers):
        self.d = d
        self.rings = tuple(deque(maxlen=cap) for _, cap in tiers)
        self.good = 0  # cumulative counters (stall/breaker kinds own
        self.bad = 0  # them; latency kinds mirror the histogram)
        self.burn = {"fast": 0.0, "slow": 0.0}


class SloEvaluator:
    """Evaluate a set of :class:`SloDef` against the live registry.

    ``tick``-style like StatsReporter/Timeline: the linked ``run`` loop
    and tests both drive :meth:`tick` (tests with explicit ``now=`` so
    burn scenarios need no wall-clock sleeps)."""

    # (decimation, capacity) per ring tier — scaled to the timeline's.
    TIERS: tuple[tuple[int, int], ...] = ((1, 600), (15, 480))

    def __init__(
        self,
        defs: Optional[Iterable[SloDef]] = DEFAULT_SLOS,
        registry: Optional[Metrics] = None,
        log_: Optional[EventLog] = None,
        interval: float = 1.0,
        ledger: Optional[Callable[[], dict]] = None,
        disabled: Optional[bool] = None,
    ):
        if disabled is None:
            disabled = os.environ.get("TPUNODE_NO_SLO") == "1"
        if defs is None:
            disabled = True
            defs = ()
        self.disabled = disabled
        self.defs = tuple(defs)
        names = [d.name for d in self.defs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self.registry = registry if registry is not None else metrics
        self.log = log_ if log_ is not None else events
        self.interval = interval
        self.ledger = ledger  # zero-arg -> engine ledger snapshot
        # one lock: tick() runs on the sampler task, snapshot() from
        # whatever thread the flight recorder fires on
        self._lock = threadsan.lock("slo.evaluator")
        self._states = {d.name: _SloState(d, self.TIERS) for d in self.defs}
        self._ticks = 0
        # (slo, window) pairs currently in a burn episode: emit once,
        # re-arm when the burn drops below the window's threshold
        self._burning: set[tuple[str, str]] = set()
        self._burn_history: deque[dict] = deque(maxlen=32)
        self.registry.describe(
            "slo.burn_rate",
            "error-budget burn rate per SLO and window (1.0 = budget "
            "consumed exactly at the objective's allowance)",
        )
        self.registry.describe(
            "slo.budget_remaining",
            "fraction of the slow-window error budget left per SLO",
        )

    # -- sampling -------------------------------------------------------------

    def _counts(self, st: _SloState) -> tuple[int, int]:
        """Cumulative (good, bad) for one SLO right now."""
        d = st.d
        if d.kind == "latency":
            h = self.registry.histogram(
                "node.verdict_latency", labels={"priority": d.priority}
            )
            if h is None:
                return 0, 0
            good = h.count_le(d.threshold)
            return good, h.count - good
        if d.kind == "stall":
            level = self.registry.get("watchdog.stalled") > 0.0
        else:  # breaker
            level = (
                self.registry.get("verify.breaker_state") == _BREAKER_OPEN
            )
        if level:
            st.bad += 1
        else:
            st.good += 1
        return st.good, st.bad

    @staticmethod
    def _window_delta(
        ring: deque, now: float, window: float, good: int, bad: int
    ) -> tuple[int, int]:
        """(good, bad) accrued inside the trailing window: current
        cumulative counts minus the newest ring sample at or before the
        window start (falling back to the ring's oldest — a young
        process burns against what it has)."""
        cutoff = now - window
        base_g = base_b = 0
        for ts, g, b in ring:
            if ts > cutoff:
                break
            base_g, base_b = g, b
        return good - base_g, bad - base_b

    def tick(self, now: Optional[float] = None) -> int:
        """Evaluate every SLO once; returns how many were evaluated
        (0 when disabled — the off path is this one attribute read)."""
        if self.disabled:
            return 0
        ts = time.time() if now is None else now
        with self._lock:
            self._ticks += 1
            live = tuple(
                i for i, (decim, _) in enumerate(self.TIERS)
                if self._ticks % decim == 0
            )
            burns: list[dict] = []
            for st in self._states.values():
                good, bad = self._counts(st)
                for i in live:
                    st.rings[i].append((ts, good, bad))
                budget = 1.0 - st.d.objective
                for window, ring_idx, span_s, limit in (
                    ("fast", 0, FAST_WINDOW, FAST_BURN),
                    ("slow", 1, SLOW_WINDOW, SLOW_BURN),
                ):
                    wg, wb = self._window_delta(
                        st.rings[ring_idx], ts, span_s, good, bad
                    )
                    total = wg + wb
                    burn = (wb / total) / budget if total else 0.0
                    st.burn[window] = burn
                    key = (st.d.name, window)
                    if burn >= limit and wb > 0:
                        if key not in self._burning:
                            self._burning.add(key)
                            burns.append(
                                dict(
                                    slo=st.d.name, window=window,
                                    burn=round(burn, 3),
                                    threshold=limit,
                                    bad=wb, total=total,
                                    objective=st.d.objective, ts=ts,
                                )
                            )
                    else:
                        self._burning.discard(key)
            evaluated = len(self._states)
        # gauges + events OUTSIDE the lock: the event log fans out to
        # subscribers (the flight recorder builds a bundle inline) and
        # a snapshot() from that path must not deadlock
        for st in self._states.values():
            for window, burn in st.burn.items():
                self.registry.set_gauge(
                    "slo.burn_rate", round(burn, 4),
                    labels={"slo": st.d.name, "window": window},
                )
            self.registry.set_gauge(
                "slo.budget_remaining",
                self._budget_remaining(st),
                labels={"slo": st.d.name},
            )
        for b in burns:
            self._burn_history.append(b)
            self.registry.inc(
                "slo.burns", labels={"slo": b["slo"], "window": b["window"]}
            )
            self.log.emit(
                "slo.burn",
                **{k: v for k, v in b.items() if k != "ts"},
            )
        return evaluated

    def _budget_remaining(self, st: _SloState) -> float:
        """Fraction of the slow-window error budget left (1.0 with no
        traffic): 1 − slow-window burn, clamped to [0, 1]."""
        return max(0.0, min(1.0, 1.0 - st.burn["slow"]))

    # -- query ----------------------------------------------------------------

    def burning(self, window: str = "fast") -> list[str]:
        """Names of SLOs currently in a burn episode on ``window`` — the
        health() degraded signal."""
        with self._lock:
            return sorted(s for s, w in self._burning if w == window)

    def snapshot(self) -> dict:
        """The ``Node.stats()["slo"]`` / ``/slo`` / flight-recorder
        section: definitions, per-SLO budgets + burn state, the burn
        episode history, and the verify cost-ledger snapshot."""
        with self._lock:
            slos = []
            for st in self._states.values():
                ring = st.rings[0]
                good, bad = (ring[-1][1], ring[-1][2]) if ring else (0, 0)
                slos.append(
                    {
                        "definition": st.d.describe(),
                        "good": good,
                        "bad": bad,
                        "budget_remaining": round(
                            self._budget_remaining(st), 4
                        ),
                        "burn": {
                            w: round(b, 4) for w, b in st.burn.items()
                        },
                        "burning": sorted(
                            w
                            for s, w in self._burning
                            if s == st.d.name
                        ),
                    }
                )
            out = {
                "enabled": not self.disabled,
                "interval": self.interval,
                "ticks": self._ticks,
                "windows": {
                    "fast": {"seconds": FAST_WINDOW, "burn": FAST_BURN},
                    "slow": {"seconds": SLOW_WINDOW, "burn": SLOW_BURN},
                },
                "slos": slos,
                "burn_history": list(self._burn_history),
            }
        if self.ledger is not None:
            try:
                out["ledger"] = self.ledger()
            except Exception as e:
                out["ledger"] = {"error": repr(e)}
        else:
            out["ledger"] = None
        return out

    # -- loop -----------------------------------------------------------------

    async def run(self) -> None:
        """Linked evaluator loop (paced like the timeline sampler)."""
        while True:
            await asyncio.sleep(self.interval)
            self.tick()
