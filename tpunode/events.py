"""Structured event log: append-only JSONL sink + StatsReporter actor.

The reference's only observability is ad-hoc textual logging; this module
records *typed* events — peer connect/disconnect/ban, handshake results,
chain reorgs, header-batch imports, verify-batch dispatches and failures —
into an in-memory ring buffer, optionally mirrored to a JSONL file
(``TPUNODE_EVENTS=<path>``).  Every event is one JSON object::

    {"ts": <unix seconds>, "type": "<layer>.<name>", ...fields, "seq": <n>}

``seq`` is a per-log monotonic sequence number (assigned under the ring
lock) — the ``/events?since=<seq>`` cursor and the flight recorder's
ordering both key off it.

so a session's history can be replayed, grepped, or diffed (the schema is
pinned by tests/test_events.py).  Emission is thread-safe (the verify
engine emits from its dispatch worker thread) and cheap enough for the
per-batch path; it is NOT wired into per-message hot loops.

:class:`StatsReporter` is the periodic telemetry actor: it snapshots the
metrics registry on an interval, computes *windowed* rates by diffing
successive snapshots (fixing the since-process-start ``rate()``), and
emits a ``node.stats`` event — the node links it like its other loops
(tpunode/actors.py substrate).
"""

from __future__ import annotations

import asyncio
import io
import json
import logging
import os
import threading
import time
from collections import Counter, deque
from typing import Callable, Optional

from . import threadsan
from .metrics import metrics

__all__ = ["EventLog", "events", "StatsReporter"]

log = logging.getLogger("tpunode.events")


class _Observer:
    """One subscriber: its callback plus a consecutive-failure count (the
    auto-unsubscribe bookkeeping — see ``EventLog.emit``)."""

    __slots__ = ("cb", "failures")

    def __init__(self, cb: Callable[[dict], None]):
        self.cb = cb
        self.failures = 0


class EventLog:
    """Ring buffer of typed events with an optional JSONL file sink."""

    # Consecutive callback failures before a subscriber is dropped: a
    # persistently-broken observer must not keep burning the emitters'
    # hot path (each failure pays exception handling + a counter).
    MAX_SUBSCRIBER_FAILURES = 10

    def __init__(self, maxlen: int = 4096, path: Optional[str] = None):
        self._lock = threadsan.lock("events.ring")
        # Monotonic per-log sequence number, assigned under the ring lock:
        # the /events?since=<seq> cursor (pollers fetch only what they
        # have not seen) and the flight recorder's bundle ordering both
        # key off it.  Never reset — a restart starts a new JSONL file
        # anyway, and within one process seq strictly increases.
        self._seq = 0
        # Separate sink lock: TextIOWrapper is NOT thread-safe, so file
        # writes must serialize — but behind their own lock, so a slow
        # disk stalls only writers, never ring readers/counters.
        self._sink_lock = threadsan.lock("events.sink")
        self._ring: deque[dict] = deque(maxlen=maxlen)
        self._counts: Counter[str] = Counter()
        self._file: Optional[io.TextIOBase] = None
        self._path = path if path is not None else os.environ.get("TPUNODE_EVENTS")
        # observers get every event dict (node republishes to its bus)
        self._observers: list[_Observer] = []

    def emit(self, type: str, **fields) -> dict:
        """Record one event; returns the event dict (with ``ts`` set)."""
        ev = {"ts": round(time.time(), 6), "type": type}
        ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._ring.append(ev)
            self._counts[type] += 1
            if self._path is not None and self._file is None:
                try:
                    # line-buffered: every event line reaches the OS
                    # without an explicit flush() per emit
                    self._file = open(
                        self._path, "a", encoding="utf-8", buffering=1
                    )
                except OSError:
                    self._path = None  # sink broken: memory ring only
            sink = self._file
            observers = tuple(self._observers)
        if sink is not None:
            line = json.dumps(ev, default=str) + "\n"
            try:
                with self._sink_lock:
                    sink.write(line)
            except (OSError, ValueError):
                with self._lock:
                    self._file = None
                    self._path = None
        for ob in observers:
            # a raised callback must not propagate into the emitter's hot
            # path: count it, and drop the subscriber after enough
            # CONSECUTIVE failures (one success re-arms the budget)
            try:
                ob.cb(ev)
                ob.failures = 0
            except Exception as e:
                metrics.inc("events.subscriber_errors")
                ob.failures += 1
                if ob.failures >= self.MAX_SUBSCRIBER_FAILURES:
                    with self._lock:
                        if ob in self._observers:
                            self._observers.remove(ob)
                    log.warning(
                        "event subscriber %r dropped after %d consecutive "
                        "failures (last: %r)", ob.cb, ob.failures, e,
                    )
        return ev

    def tail(self, n: int = 100, type: Optional[str] = None) -> list[dict]:
        """Newest ``n`` events (oldest first), optionally one type only."""
        with self._lock:
            evs = list(self._ring)
        if type is not None:
            evs = [e for e in evs if e["type"] == type]
        return evs[-n:]

    def tail_since(self, seq: int, n: int = 100) -> list[dict]:
        """Events with ``seq > seq`` (oldest first), capped at ``n`` —
        the /events cursor: a poller remembers the last seq it saw and
        never re-downloads the whole ring."""
        with self._lock:
            evs = [e for e in self._ring if e["seq"] > seq]
        return evs[-n:]

    def seq(self) -> int:
        """The seq of the newest event (0 before the first emit)."""
        with self._lock:
            return self._seq

    def counts(self) -> dict[str, int]:
        """Total events per type since start (survives ring eviction)."""
        with self._lock:
            return dict(self._counts)

    def subscribe(self, cb: Callable[[dict], None]) -> Callable[[], None]:
        """Register an observer; returns an unsubscribe callable.

        Observer exceptions never reach emitters: they are counted in the
        ``events.subscriber_errors`` metric, and a subscriber that fails
        :data:`MAX_SUBSCRIBER_FAILURES` times in a row is dropped."""
        ob = _Observer(cb)
        with self._lock:
            self._observers.append(ob)

        def unsubscribe() -> None:
            with self._lock:
                if ob in self._observers:
                    self._observers.remove(ob)

        return unsubscribe

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._counts.clear()

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                finally:
                    self._file = None


# Process-wide event log (tests may construct their own).
events = EventLog()


# Counters surfaced as windowed rates in every stats event (the headline
# node signals; anything else can be read from the snapshot itself).
_RATED = (
    "chain.headers",
    "node.verify_txs",
    "node.verify_inputs",
    "verify.items",
    "peer.msgs_in",
    "peer.bytes_in",
    "peer.bytes_out",
)

# Labeled families summarized into every node.stats event as bounded-cardinality
# aggregates: family name -> the label key to sum by.  The raw per-peer
# series stay out of the persisted event (unbounded cardinality — they
# belong to Node.stats()/render_prometheus() pulls); summing ``peer.msgs``
# by ``cmd`` keeps the message-mix signal without the peer dimension.
_LABEL_AGG: dict[str, str] = {"peer.msgs": "cmd"}


class StatsReporter:
    """Periodic registry snapshot -> windowed rates -> ``node.stats`` events.

    Rates are computed by diffing successive snapshots over the actual
    elapsed interval, so an idle hour does not dilute the current
    throughput the way ``lifetime_rate`` does.  Run it linked like any
    node loop::

        reporter = StatsReporter(interval=30.0)
        tasks.link(reporter.run(), name="stats")
    """

    def __init__(
        self,
        interval: float = 30.0,
        log: Optional[EventLog] = None,
        extra: Optional[Callable[[], dict]] = None,
        label_agg: Optional[dict[str, str]] = None,
    ):
        self.interval = interval
        self.log = log if log is not None else events
        self.extra = extra  # node hook: chain height, fleet size, backlog
        self.label_agg = _LABEL_AGG if label_agg is None else label_agg
        self._last: Optional[tuple[float, dict[str, float]]] = None

    def tick(self) -> dict:
        """One report (synchronous; the loop and tests both use it)."""
        now = time.monotonic()
        # unlabeled series only: the labeled families (per-peer msgs/RTT)
        # are unbounded-cardinality and belong to Node.stats()/
        # render_prometheus() pulls, not to an event persisted every tick
        snap = {
            k: v for k, v in metrics.snapshot().items() if "{" not in k
        }
        rates: dict[str, float] = {}
        if self._last is not None:
            t0, prev = self._last
            dt = max(1e-9, now - t0)
            for name in _RATED:
                cur = snap.get(name, 0.0)
                if cur or prev.get(name):
                    rates[name] = round((cur - prev.get(name, 0.0)) / dt, 3)
        self._last = (now, snap)
        # labeled-series aggregates (see _LABEL_AGG): bounded by the label
        # key's value space (e.g. wire commands), never by peer count
        labeled: dict[str, dict[str, float]] = {}
        for family, key in self.label_agg.items():
            agg: dict[str, float] = {}
            for lk, v in metrics.series(family).items():
                value = dict(lk).get(key)
                if value is not None:
                    agg[value] = agg.get(value, 0.0) + v
            if agg:
                labeled[family] = agg
        fields: dict = {"rates": rates, "counters": snap, "labeled": labeled}
        if self.extra is not None:
            try:
                fields.update(self.extra())
            except Exception as e:
                fields["extra_error"] = repr(e)
        # "node.stats" (ISSUE 3 satellite): the event type followed the
        # <layer>.<name> schema everywhere else; the old grandfathered
        # bare "stats" name is gone.
        return self.log.emit("node.stats", **fields)

    async def run(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            self.tick()
