"""Low-level primitives shared across the framework.

Hashing, variable-length integers, compact difficulty bits and byte-order
helpers.  These mirror the primitives the reference gets from ``haskoin-core``
(see /root/reference SURVEY C6): double-SHA256 block/tx hashing, Bitcoin wire
varints and the compact target encoding used in block headers.
"""

from __future__ import annotations

import hashlib
from io import BytesIO

__all__ = [
    "sha256",
    "double_sha256",
    "read_varint",
    "write_varint",
    "read_varstr",
    "write_varstr",
    "hash_to_hex",
    "hex_to_hash",
    "bits_to_target",
    "target_to_bits",
    "Reader",
]


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def double_sha256(data: bytes) -> bytes:
    """The ubiquitous Bitcoin hash: SHA256(SHA256(data))."""
    return hashlib.sha256(hashlib.sha256(data).digest()).digest()


def hash_to_hex(h: bytes) -> str:
    """Internal byte order -> RPC display order (reversed hex)."""
    return h[::-1].hex()


def hex_to_hash(s: str) -> bytes:
    """RPC display order (reversed hex) -> internal byte order."""
    return bytes.fromhex(s)[::-1]


def write_varint(n: int) -> bytes:
    if n < 0xFD:
        return n.to_bytes(1, "little")
    if n <= 0xFFFF:
        return b"\xfd" + n.to_bytes(2, "little")
    if n <= 0xFFFFFFFF:
        return b"\xfe" + n.to_bytes(4, "little")
    return b"\xff" + n.to_bytes(8, "little")


def write_varstr(b: bytes) -> bytes:
    return write_varint(len(b)) + b


class Reader:
    """Cursor over a byte buffer with exact-read semantics.

    Raises ``ValueError`` on truncated input, which message decoders surface
    as decode errors (the analog of cereal parse failures in the reference).
    """

    __slots__ = ("_buf", "_pos")

    def __init__(self, data: bytes, pos: int = 0):
        self._buf = data
        self._pos = pos

    @property
    def pos(self) -> int:
        return self._pos

    def remaining(self) -> int:
        return len(self._buf) - self._pos

    def slice_from(self, start: int) -> bytes:
        """Bytes consumed since ``start`` (a previously read ``pos``)."""
        return self._buf[start : self._pos]

    def peek(self, n: int) -> bytes:
        return self._buf[self._pos : self._pos + n]

    def read(self, n: int) -> bytes:
        end = self._pos + n
        if end > len(self._buf):
            raise ValueError(f"truncated read: wanted {n}, have {self.remaining()}")
        out = self._buf[self._pos : end]
        self._pos = end
        return out

    def u8(self) -> int:
        return self.read(1)[0]

    def u16(self) -> int:
        return int.from_bytes(self.read(2), "little")

    def u32(self) -> int:
        return int.from_bytes(self.read(4), "little")

    def u64(self) -> int:
        return int.from_bytes(self.read(8), "little")

    def i32(self) -> int:
        return int.from_bytes(self.read(4), "little", signed=True)

    def i64(self) -> int:
        return int.from_bytes(self.read(8), "little", signed=True)

    def u16be(self) -> int:
        return int.from_bytes(self.read(2), "big")

    def varint(self) -> int:
        # Non-minimal encodings are rejected (Bitcoin Core ReadCompactSize):
        # a hostile peer encoding e.g. an input count as fd 01 00 would
        # otherwise produce a different txid/sighash on paths that hash raw
        # spans than on paths that re-serialize canonically.
        first = self.u8()
        if first < 0xFD:
            return first
        if first == 0xFD:
            v = self.u16()
            lo = 0xFD
        elif first == 0xFE:
            v = self.u32()
            lo = 0x10000
        else:
            v = self.u64()
            lo = 0x100000000
        if v < lo:
            raise ValueError(f"non-minimal varint: {v} encoded with 0x{first:02x}")
        return v

    def varstr(self) -> bytes:
        return self.read(self.varint())


def read_varint(data: bytes, pos: int = 0) -> tuple[int, int]:
    r = Reader(data, pos)
    return r.varint(), r.pos


def read_varstr(data: bytes, pos: int = 0) -> tuple[bytes, int]:
    r = Reader(data, pos)
    return r.varstr(), r.pos


# --- compact difficulty encoding ------------------------------------------
#
# Block headers carry the proof-of-work target as a 32-bit base-256 floating
# point number ("nBits").  Encoding matches Bitcoin Core's arith_uint256
# SetCompact/GetCompact.


def bits_to_target(bits: int) -> int:
    """Decode compact bits to the 256-bit integer target.

    Returns 0 for encodings that are negative or overflow 256 bits (such a
    target can never be met, so callers treat the header as invalid).
    """
    exponent = bits >> 24
    mantissa = bits & 0x007FFFFF
    if bits & 0x00800000:  # sign bit: negative target is invalid
        return 0
    if exponent <= 3:
        target = mantissa >> (8 * (3 - exponent))
    else:
        target = mantissa << (8 * (exponent - 3))
    if target.bit_length() > 256:
        return 0
    return target


def target_to_bits(target: int) -> int:
    """Encode a 256-bit integer target into compact bits (canonical form)."""
    if target <= 0:
        return 0
    size = (target.bit_length() + 7) // 8
    if size <= 3:
        compact = target << (8 * (3 - size))
    else:
        compact = target >> (8 * (size - 3))
    # If the mantissa's top bit is set it would read as negative: renormalize.
    if compact & 0x00800000:
        compact >>= 8
        size += 1
    return compact | (size << 24)


def header_work(bits: int) -> int:
    """Expected work for a header: 2^256 / (target + 1).

    Same quantity Bitcoin Core accumulates as chain work; used to compare
    competing chains (reference: haskoin-core BlockNode chain-work field,
    surveyed at SURVEY.md C6).
    """
    target = bits_to_target(bits)
    if target <= 0:
        return 0
    return (1 << 256) // (target + 1)
