"""Turn raw transactions into batch-verifiable signature items.

The ingest side of the north star (BASELINE.json): block and mempool
transactions are scanned for the standard spend templates whose signatures
can be checked without a UTXO set, yielding ``(pubkey, sighash, r, s)``
tuples for the batch verify engine:

* **P2PKH** — scriptSig is ``<DER-sig> <pubkey>``; the prevout's script is
  by construction ``DUP HASH160 <h160(pubkey)> EQUALVERIFY CHECKSIG``, fully
  derivable from the pubkey itself, so the legacy sighash is computable
  standalone.
* **P2WPKH** — witness is ``[DER-sig, pubkey]``; BIP143 needs the input
  amount, so these become items only when the caller can supply amounts
  (``prevout_amounts``).

Inputs that don't match a computable template are counted, not verified —
this engine is a streaming signature pre-verifier (the reference node doesn't
validate scripts at all; SURVEY.md §3.3 "this is where the north star plugs
in"), not a full script interpreter.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Optional

from .sighash import SIGHASH_FORKID, bip143_sighash, legacy_sighash
from .verify.ecdsa_cpu import Point, decode_pubkey, parse_der_signature
from .wire import Tx

__all__ = [
    "SigItem",
    "extract_sig_items",
    "ExtractStats",
    "intra_block_amounts",
    "wants_amount",
]


def wants_amount(tx: Tx, idx: int, bch: bool) -> bool:
    """Could input ``idx`` consume a BIP143 prevout amount?  True for the
    P2WPKH witness shape and for any input on a FORKID (BCH) network;
    legacy inputs elsewhere never use amounts, so callers can skip their
    (possibly expensive) amount lookups."""
    if bch:
        return True
    wit = tx.witnesses[idx] if idx < len(tx.witnesses) else ()
    return not tx.inputs[idx].script and len(wit) == 2


def intra_block_amounts(txs) -> dict[tuple[bytes, int], int]:
    """(txid, vout) -> satoshi amount for every output in ``txs`` — the
    intra-block prevout map that lets BIP143 digests be computed for
    in-block spends without a UTXO set (used by node block ingest and the
    IBD benchmark so both resolve amounts identically)."""
    outs: dict[tuple[bytes, int], int] = {}
    for tx in txs:
        for vout, o in enumerate(tx.outputs):
            outs[(tx.txid, vout)] = o.value
    return outs


def _hash160(b: bytes) -> bytes:
    return hashlib.new("ripemd160", hashlib.sha256(b).digest()).digest()


@dataclass(frozen=True)
class SigItem:
    """One verifiable signature: inputs to ECDSA verify."""

    pubkey: Optional[Point]  # None = undecodable key (auto-invalid)
    z: int  # sighash digest
    r: int
    s: int
    txid: bytes
    input_index: int


@dataclass
class ExtractStats:
    total_inputs: int = 0
    extracted: int = 0
    coinbase: int = 0
    unsupported: int = 0


def _parse_pushes(script: bytes) -> Optional[list[bytes]]:
    """Parse a script consisting only of plain data pushes (opcodes 1-75 and
    PUSHDATA1/2); returns None if anything else appears."""
    out = []
    i = 0
    n = len(script)
    while i < n:
        op = script[i]
        i += 1
        if 1 <= op <= 75:
            ln = op
        elif op == 76 and i < n:  # OP_PUSHDATA1
            ln = script[i]
            i += 1
        elif op == 77 and i + 1 < n:  # OP_PUSHDATA2
            ln = int.from_bytes(script[i : i + 2], "little")
            i += 2
        else:
            return None
        if i + ln > n:
            return None
        out.append(script[i : i + ln])
        i += ln
    return out


def _p2pkh_script_code(pubkey: bytes) -> bytes:
    return b"\x76\xa9\x14" + _hash160(pubkey) + b"\x88\xac"


def extract_sig_items(
    tx: Tx,
    prevout_amounts: Optional[dict[int, int]] = None,
    bch: bool = False,
) -> tuple[list[SigItem], ExtractStats]:
    """Extract batch-verifiable signatures from one transaction.

    ``prevout_amounts`` maps input index -> satoshi amount (enables P2WPKH).
    ``bch`` selects the FORKID (BIP143-style) digest for legacy templates.
    """
    items: list[SigItem] = []
    stats = ExtractStats()
    txid = tx.txid
    for idx, txin in enumerate(tx.inputs):
        stats.total_inputs += 1
        if txin.prevout.txid == b"\x00" * 32:
            stats.coinbase += 1
            continue
        # P2WPKH: empty scriptSig, two-element witness
        wit = tx.witnesses[idx] if idx < len(tx.witnesses) else ()
        if not txin.script and len(wit) == 2:
            sig_blob, pub_blob = wit
            parsed = _try_item(tx, idx, sig_blob, pub_blob, prevout_amounts, bch, segwit=True)
            if parsed is not None:
                items.append(parsed)
                stats.extracted += 1
                continue
            stats.unsupported += 1
            continue
        # P2PKH: scriptSig = <sig> <pubkey>
        pushes = _parse_pushes(txin.script)
        if pushes and len(pushes) == 2 and len(pushes[1]) in (33, 65):
            parsed = _try_item(tx, idx, pushes[0], pushes[1], prevout_amounts, bch, segwit=False)
            if parsed is not None:
                items.append(parsed)
                stats.extracted += 1
                continue
        stats.unsupported += 1
    return items, stats


def _try_item(
    tx: Tx,
    idx: int,
    sig_blob: bytes,
    pub_blob: bytes,
    prevout_amounts: Optional[dict[int, int]],
    bch: bool,
    segwit: bool,
) -> Optional[SigItem]:
    if len(sig_blob) < 9:
        return None
    hashtype = sig_blob[-1]
    rs = parse_der_signature(sig_blob[:-1])
    if rs is None:
        return None
    r, s = rs
    script_code = _p2pkh_script_code(pub_blob)
    if segwit or (bch and hashtype & SIGHASH_FORKID):
        if prevout_amounts is None or idx not in prevout_amounts:
            return None
        z = bip143_sighash(tx, idx, script_code, prevout_amounts[idx], hashtype)
    else:
        z = legacy_sighash(tx, idx, script_code, hashtype)
    pub = decode_pubkey(pub_blob)
    return SigItem(pubkey=pub, z=z, r=r, s=s, txid=tx.txid, input_index=idx)
