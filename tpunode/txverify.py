"""Turn raw transactions into batch-verifiable signature items.

The ingest side of the north star (BASELINE.json): block and mempool
transactions are scanned for the standard spend templates whose signatures
can be checked without a UTXO set, yielding ``(pubkey, sighash, r, s)``
tuples for the batch verify engine:

* **P2PKH** — scriptSig is ``<DER-sig> <pubkey>``; the prevout's script is
  by construction ``DUP HASH160 <h160(pubkey)> EQUALVERIFY CHECKSIG``, fully
  derivable from the pubkey itself, so the legacy sighash is computable
  standalone.
* **P2WPKH** — witness is ``[DER-sig, pubkey]``; BIP143 needs the input
  amount, so these become items only when the caller can supply amounts
  (``prevout_amounts``).
* **P2SH-P2WPKH** — scriptSig is one push of the ``0x0014<h160>`` redeem
  script, witness ``[DER-sig, pubkey]``; same BIP143 digest as P2WPKH.
* **P2SH multisig** — scriptSig is ``OP_0 <sig>*m <redeemScript>`` where
  the redeem script is ``OP_m <key>*n OP_n OP_CHECKMULTISIG``; each sig is
  dispatched as up to ``n-m+1`` candidate (sig, key) pairs, and per-sig
  validity comes out of the consensus matching walk (:func:`combine_verdicts`)
  over the batch verdicts — the matching that OP_CHECKMULTISIG does serially,
  done data-parallel.
* **P2WSH multisig** (and **P2SH-P2WSH**) — witness is
  ``[<empty>, <sig>*m, witnessScript]`` with the same multisig template;
  BIP143 digests, so amounts are required.

Inputs that don't match a computable template are counted, not verified —
this engine is a streaming signature pre-verifier (the reference node doesn't
validate scripts at all; SURVEY.md §3.3 "this is where the north star plugs
in"), not a full script interpreter.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from .sighash import (
    SIGHASH_FORKID,
    bip143_sighash,
    bip341_sighash,
    legacy_sighash,
    tapleaf_hash,
)
from .verify.ecdsa_cpu import (
    Point,
    bip340_challenge,
    decode_pubkey,
    lift_x,
    parse_der_signature,
    schnorr_challenge,
)
from .wire import Tx

__all__ = [
    "SigItem",
    "extract_sig_items",
    "ExtractStats",
    "intra_block_amounts",
    "intra_block_prevouts",
    "wants_amount",
    "needs_prevout",
    "is_p2tr",
    "is_p2pk",
    "is_single_key_tapscript",
    "combine_verdicts",
    "msig_match",
]


def _is_single_push_sig(script: bytes) -> bool:
    """One direct push of a plausible DER/Schnorr sig blob — the bare-P2PK
    spend shape.  Shared by the wants gate and the extractor dispatch so
    the two can never drift (mirrored by the native
    single_push_script_sig)."""
    return len(script) >= 10 and len(script) == script[0] + 1


def wants_amount(tx: Tx, idx: int, bch: bool) -> bool:
    """Could input ``idx``'s prevout data (BIP143 amount or BIP341
    amount+script) be consumed by SOME digest in this tx?  True for every
    input of any tx that carries a witness: segwit-v0 templates digest
    their own input's amount, and a taproot keypath input (1-element
    witness — only the prevout script, which only the oracle knows,
    decides) digests EVERY input's amount and script, including legacy
    no-witness siblings — so the gate is tx-level, not per-input
    (review r5: a per-input gate silently downgraded taproot spends in
    mixed legacy+taproot txs to unsupported).  Also True for any input on
    a FORKID (BCH) network, and for single-push scriptSig inputs (the
    bare-P2PK spend shape: the prevout script both identifies the
    template and carries its key).  Other witness-free non-FORKID inputs
    never use prevout data, so callers skip their (possibly expensive)
    lookups."""
    if bch or tx.has_witness:
        return True
    return _is_single_push_sig(tx.inputs[idx].script)


def needs_prevout(tx: Tx, idx: int) -> bool:
    """Would verification of this tx be DEGRADED without input ``idx``'s
    prevout data?  The mempool's orphan gate (tpunode/mempool.py).

    Stricter than :func:`wants_amount`: a witness-carrying tx digests
    prevout amounts/scripts (BIP143 per-input; BIP341 every-input, so the
    gate is tx-level when any witness is present), and a single-push
    scriptSig (bare P2PK) needs the prevout script to identify the
    template — but the blanket FORKID clause is dropped: a legacy BCH
    spend extracts and verifies fine without the oracle (pinned by the
    fakenet ingest tests), so an unknown legacy prevout must not park
    the tx as an orphan."""
    if tx.has_witness:
        return True
    return _is_single_push_sig(tx.inputs[idx].script)


def intra_block_amounts(txs) -> dict[tuple[bytes, int], int]:
    """(txid, vout) -> satoshi amount for every output in ``txs`` — the
    intra-block prevout map that lets BIP143 digests be computed for
    in-block spends without a UTXO set (used by node block ingest and the
    IBD benchmark so both resolve amounts identically)."""
    outs: dict[tuple[bytes, int], int] = {}
    for tx in txs:
        for vout, o in enumerate(tx.outputs):
            outs[(tx.txid, vout)] = o.value
    return outs


def intra_block_prevouts(txs) -> dict[tuple[bytes, int], tuple[int, bytes]]:
    """(txid, vout) -> (amount, scriptPubKey) for every output in ``txs``
    — the extended intra-block map BIP341 digests need (taproot keypath
    spends sign over every input's amount AND script)."""
    outs: dict[tuple[bytes, int], tuple[int, bytes]] = {}
    for tx in txs:
        for vout, o in enumerate(tx.outputs):
            outs[(tx.txid, vout)] = (o.value, o.script)
    return outs


def is_p2tr(script: bytes) -> bool:
    """Taproot output template: OP_1 <32-byte x-only key>."""
    return len(script) == 34 and script[0] == 0x51 and script[1] == 0x20


def _hash160(b: bytes) -> bytes:
    return hashlib.new("ripemd160", hashlib.sha256(b).digest()).digest()


@dataclass(frozen=True)
class SigItem:
    """One device verify candidate: inputs to ECDSA verify.

    Single-sig templates produce exactly one item per signature.  Multisig
    inputs produce one item per candidate (signature, key) pair —
    ``sig_index``/``key_index`` locate the pair, ``num_sigs``/``num_keys``
    are the input's (m, n) — and :func:`combine_verdicts` collapses the
    candidates back to per-signature verdicts via the consensus walk.
    """

    pubkey: Optional[Point]  # None = undecodable key (auto-invalid)
    z: int  # sighash digest (ECDSA) or precomputed challenge e (Schnorr)
    r: int
    s: int
    txid: bytes
    input_index: int
    sig_index: int = 0
    key_index: int = 0
    num_sigs: int = 1
    num_keys: int = 1
    # "ecdsa" | "schnorr" | "bip340" — BCH interprets any 65-byte signature
    # blob as Schnorr (2019-05 upgrade); single-sig templates only (Schnorr
    # in CHECKMULTISIG was consensus-invalid in the 2019 rules this mirrors,
    # so 65-byte multisig sigs stay auto-invalid candidates).  "bip340" is
    # the taproot keypath spend (BTC 2021): x-only key lifted from the
    # prevout scriptPubKey, BIP341 sighash, even-y acceptance.
    algo: str = "ecdsa"

    @property
    def verify_item(self) -> tuple:
        """The engine's VerifyItem tuple form (5-tuple when Schnorr-family:
        the 5th element names the algorithm)."""
        t = (self.pubkey, self.z, self.r, self.s)
        return t if self.algo == "ecdsa" else t + (self.algo,)


@dataclass
class ExtractStats:
    total_inputs: int = 0
    extracted: int = 0  # inputs whose signatures became verify items
    coinbase: int = 0
    unsupported: int = 0
    sigs: int = 0  # actual signatures extracted (m per multisig input)
    candidates: int = 0  # device items (> sigs when multisig windows fan out)

    @property
    def coverage(self) -> float:
        """Extracted fraction of the signature-bearing inputs."""
        denom = self.total_inputs - self.coinbase
        return self.extracted / denom if denom else 1.0


def _parse_pushes(script: bytes) -> Optional[list[bytes]]:
    """Parse a script consisting only of plain data pushes (OP_0, opcodes
    1-75 and PUSHDATA1/2); returns None if anything else appears.  OP_0
    parses as an empty push (the CHECKMULTISIG dummy)."""
    out = []
    i = 0
    n = len(script)
    while i < n:
        op = script[i]
        i += 1
        if op == 0:  # OP_0: empty push (multisig dummy element)
            ln = 0
        elif 1 <= op <= 75:
            ln = op
        elif op == 76 and i < n:  # OP_PUSHDATA1
            ln = script[i]
            i += 1
        elif op == 77 and i + 1 < n:  # OP_PUSHDATA2
            ln = int.from_bytes(script[i : i + 2], "little")
            i += 2
        else:
            return None
        if i + ln > n:
            return None
        out.append(script[i : i + ln])
        i += ln
    return out


def _parse_multisig(script: bytes) -> Optional[tuple[int, list[bytes]]]:
    """Parse the bare multisig template ``OP_m <key>*n OP_n OP_CHECKMULTISIG``
    (keys 33 or 65 bytes); returns (m, keys) or None."""
    if len(script) < 3 or script[-1] != 0xAE:  # OP_CHECKMULTISIG
        return None
    n_op, m_op = script[-2], script[0]
    if not (0x51 <= n_op <= 0x60 and 0x51 <= m_op <= 0x60):
        return None
    n, m = n_op - 0x50, m_op - 0x50
    if m > n:
        return None
    keys = []
    i, end = 1, len(script) - 2
    while i < end:
        ln = script[i]
        i += 1
        if ln not in (33, 65) or i + ln > end:
            return None
        keys.append(script[i : i + ln])
        i += ln
    if len(keys) != n:
        return None
    return m, keys


def _p2pkh_script_code(pubkey: bytes) -> bytes:
    return b"\x76\xa9\x14" + _hash160(pubkey) + b"\x88\xac"


def _is_multisig_witness(wit: tuple) -> Optional[tuple[int, list[bytes]]]:
    """P2WSH multisig witness shape: [<empty dummy>, <sig>*m, script]."""
    if len(wit) < 3 or wit[0] != b"":
        return None
    ms = _parse_multisig(wit[-1])
    if ms is None or len(wit) - 2 != ms[0]:
        return None
    return ms


def extract_sig_items(
    tx: Tx,
    prevout_amounts: Optional[dict[int, int]] = None,
    bch: bool = False,
    prevout_scripts: Optional[dict[int, bytes]] = None,
) -> tuple[list[SigItem], ExtractStats]:
    """Extract batch-verifiable signatures from one transaction.

    ``prevout_amounts`` maps input index -> satoshi amount (enables the
    BIP143 templates: P2WPKH, P2SH-P2WPKH, P2WSH).  ``bch`` selects the
    FORKID (BIP143-style) digest for legacy templates.
    ``prevout_scripts`` maps input index -> prevout scriptPubKey; when an
    input's prevout script is P2TR (and ``bch`` is False), its keypath
    spend becomes a "bip340" item — the BIP341 digest additionally
    requires amounts AND scripts for every input (the extended oracle,
    VERDICT r4 item 3).  Taproot script-path spends are counted
    unsupported.
    """
    items: list[SigItem] = []
    stats = ExtractStats()
    for idx, txin in enumerate(tx.inputs):
        stats.total_inputs += 1
        if txin.prevout.txid == b"\x00" * 32:
            stats.coinbase += 1
            continue
        wit = tx.witnesses[idx] if idx < len(tx.witnesses) else ()
        new: Optional[list[SigItem]] = None
        pscript = (
            prevout_scripts.get(idx) if prevout_scripts is not None else None
        )
        if not bch and pscript is not None and is_p2tr(pscript):
            new = _taproot_item(
                tx, idx, wit, pscript, prevout_amounts, prevout_scripts
            )
        elif (
            pscript is not None
            and (pk := is_p2pk(pscript)) is not None
            and not wit
            and _is_single_push_sig(txin.script)
        ):
            # bare P2PK: scriptSig = one direct push of <sig>, key lives
            # in the prevout script (extractable only via the script
            # oracle)
            new = _single_item(tx, idx, txin.script[1:], pk, prevout_amounts,
                               bch, segwit=False, script_code=pscript)
        elif not txin.script and len(wit) == 2:
            if len(wit[1]) in (33, 65):
                # P2WPKH: empty scriptSig, [sig, pubkey] witness
                new = _single_item(tx, idx, wit[0], wit[1], prevout_amounts,
                                   bch, segwit=True)
            elif (pk := is_p2pk(wit[1])) is not None:
                # P2WSH single-key: [sig, <key> OP_CHECKSIG] witness; the
                # witness script is the BIP143 script_code.  (Without this
                # template the P2WPKH shape check used to mis-emit these
                # as auto-invalid ECDSA items — review r5.)
                new = _single_item(tx, idx, wit[0], pk, prevout_amounts,
                                   bch, segwit=True, script_code=wit[1])
            # other 2-element witnesses: unsupported, NOT auto-invalid
        elif not txin.script and (ms := _is_multisig_witness(wit)):
            # P2WSH multisig
            new = _msig_items(tx, idx, list(wit[1:-1]), ms[0], ms[1], wit[-1],
                              prevout_amounts, bch, segwit=True)
        else:
            pushes = _parse_pushes(txin.script)
            if pushes is None:
                pass
            elif len(pushes) == 2 and len(pushes[1]) in (33, 65):
                # P2PKH: scriptSig = <sig> <pubkey>
                new = _single_item(tx, idx, pushes[0], pushes[1],
                                   prevout_amounts, bch, segwit=False)
            elif (
                len(pushes) == 1
                and len(pushes[0]) == 22
                and pushes[0][:2] == b"\x00\x14"
                and len(wit) == 2
            ):
                # P2SH-P2WPKH: redeem = v0 keyhash program, witness as P2WPKH
                new = _single_item(tx, idx, wit[0], wit[1], prevout_amounts,
                                   bch, segwit=True)
            elif (
                len(pushes) == 1
                and len(pushes[0]) == 34
                and pushes[0][:2] == b"\x00\x20"
                and (ms := _is_multisig_witness(wit))
            ):
                # P2SH-P2WSH multisig
                new = _msig_items(tx, idx, list(wit[1:-1]), ms[0], ms[1],
                                  wit[-1], prevout_amounts, bch, segwit=True)
            elif (
                len(pushes) == 1
                and len(pushes[0]) == 34
                and pushes[0][:2] == b"\x00\x20"
                and len(wit) == 2
                and (pk := is_p2pk(wit[1])) is not None
            ):
                # P2SH-P2WSH single-key
                new = _single_item(tx, idx, wit[0], pk, prevout_amounts,
                                   bch, segwit=True, script_code=wit[1])
            elif (
                len(pushes) >= 2
                and pushes[0] == b""
                and (ms := _parse_multisig(pushes[-1])) is not None
                and len(pushes) - 2 == ms[0]
            ):
                # P2SH multisig: OP_0 <sig>*m <redeemScript>
                new = _msig_items(tx, idx, pushes[1:-1], ms[0], ms[1],
                                  pushes[-1], prevout_amounts, bch,
                                  segwit=False)
        if new is None:
            stats.unsupported += 1
        else:
            items.extend(new)
            stats.extracted += 1
            stats.sigs += new[0].num_sigs if new else 0
            stats.candidates += len(new)
    return items, stats


def is_single_key_tapscript(script: bytes) -> bool:
    """The canonical single-key tapscript: ``<32-byte x-only key>
    OP_CHECKSIG`` (the standard script-path leaf shape)."""
    return len(script) == 34 and script[0] == 0x20 and script[33] == 0xAC


def is_p2pk(script: bytes) -> Optional[bytes]:
    """Bare P2PK output template ``<33/65-byte pubkey> OP_CHECKSIG``;
    returns the pubkey blob or None."""
    if len(script) == 35 and script[0] == 33 and script[34] == 0xAC:
        return script[1:34]
    if len(script) == 67 and script[0] == 65 and script[66] == 0xAC:
        return script[1:66]
    return None


def _valid_control_block(cb: bytes) -> bool:
    """BIP341 control block: leaf version 0xC0 (the only defined tapscript
    version), internal key, 0-128 merkle path nodes."""
    return (
        33 <= len(cb) <= 33 + 128 * 32
        and (len(cb) - 33) % 32 == 0
        and (cb[0] & 0xFE) == 0xC0
    )


def _taproot_item(
    tx: Tx,
    idx: int,
    wit: tuple,
    pscript: bytes,
    prevout_amounts: Optional[dict[int, int]],
    prevout_scripts: Optional[dict[int, bytes]],
) -> Optional[list[SigItem]]:
    """One "bip340" item for a taproot spend, or None when the input
    can't be handled (unsupported tapscript, or missing prevout info).

    KEYPATH (after peeling the optional annex, exactly one witness
    element): a 64-byte (SIGHASH_DEFAULT) or 65-byte (explicit hash_type)
    BIP340 signature over the BIP341 digest, key = the output key from
    the prevout script.  SCRIPT path with the canonical single-key
    tapscript (witness ``[sig, <32B-key> OP_CHECKSIG, control]``): the
    BIP342 digest (ext_flag 1, tapleaf hash), key = the leaf's x-only
    key.  Like every template here, signatures are verified — script
    EXECUTION and the merkle commitment of the leaf to the output key
    are not (same scope as P2SH, where the redeem-script hash is not
    checked; this is a signature pre-verifier).  Other tapscripts are
    unsupported.

    Consensus-invalid shapes (bad sig length, invalid hash_type,
    SIGHASH_SINGLE with no matching output, off-curve key) yield an
    AUTO-INVALID item — the spend is invalid, not unsupported."""
    annex: Optional[bytes] = None
    if len(wit) >= 2 and len(wit[-1]) >= 1 and wit[-1][0] == 0x50:
        annex = wit[-1]
        wit = wit[:-1]
    txid = tx.txid
    leaf_hash: Optional[bytes] = None
    if len(wit) == 1:
        key_x = int.from_bytes(pscript[2:34], "big")  # keypath: output key
    elif (
        len(wit) == 3
        and is_single_key_tapscript(wit[1])
        and _valid_control_block(wit[2])
    ):
        key_x = int.from_bytes(wit[1][1:33], "big")  # leaf key
        leaf_hash = tapleaf_hash(wit[1], wit[2][0] & 0xFE)
    else:
        return None  # other tapscript shapes: unsupported
    sig_blob = wit[0]

    def invalid(r: int = 0, s: int = 0) -> list[SigItem]:
        return [SigItem(None, 0, r, s, txid, idx, algo="bip340")]

    if len(sig_blob) == 64:
        hashtype = 0x00
    elif len(sig_blob) == 65:
        hashtype = sig_blob[64]
        if hashtype == 0x00:
            return invalid()  # 65-byte sig must carry an explicit type
    else:
        return invalid()
    r = int.from_bytes(sig_blob[0:32], "big")
    s = int.from_bytes(sig_blob[32:64], "big")
    # BIP341 signs over every input's (amount, script) — ANYONECANPAY
    # needs only this input's
    need = [idx] if hashtype & 0x80 else range(len(tx.inputs))
    if prevout_amounts is None or prevout_scripts is None:
        return None
    if any(i not in prevout_amounts or i not in prevout_scripts for i in need):
        return None
    n_in = len(tx.inputs)
    amounts = [prevout_amounts.get(i, 0) for i in range(n_in)]
    scripts = [prevout_scripts.get(i, b"") for i in range(n_in)]
    digest = bip341_sighash(
        tx, idx, amounts, scripts, hashtype, annex, leaf_hash
    )
    if digest is None:
        return invalid(r, s)
    pub = lift_x(key_x)
    if pub is None:
        return invalid(r, s)  # off-curve key: invalid spend
    e = bip340_challenge(r, pub.x, digest)
    return [SigItem(pub, e, r, s, txid, idx, algo="bip340")]


def _single_item(
    tx: Tx,
    idx: int,
    sig_blob: bytes,
    pub_blob: bytes,
    prevout_amounts: Optional[dict[int, int]],
    bch: bool,
    segwit: bool,
    script_code: Optional[bytes] = None,
) -> Optional[list[SigItem]]:
    """One ECDSA/Schnorr item for a single-key spend.  ``script_code``
    defaults to the P2PKH template over ``pub_blob`` (P2PKH/P2WPKH);
    bare P2PK passes the prevout script, P2WSH single-key the witness
    script."""
    if len(sig_blob) < 9:
        return None
    hashtype = sig_blob[-1]
    # BCH consensus: a 65-byte signature blob (64 + hashtype) IS Schnorr.
    schnorr = bch and len(sig_blob) == 65
    if schnorr:
        r = int.from_bytes(sig_blob[0:32], "big")
        s = int.from_bytes(sig_blob[32:64], "big")
    else:
        rs = parse_der_signature(sig_blob[:-1])
        if rs is None:
            return None
        r, s = rs
    if script_code is None:
        script_code = _p2pkh_script_code(pub_blob)
    if segwit or (bch and hashtype & SIGHASH_FORKID):
        if prevout_amounts is None or idx not in prevout_amounts:
            return None
        z = bip143_sighash(tx, idx, script_code, prevout_amounts[idx], hashtype)
    else:
        z = legacy_sighash(tx, idx, script_code, hashtype)
    pub = decode_pubkey(pub_blob)
    if schnorr:
        if pub is None:
            return [SigItem(None, 0, r, s, tx.txid, idx, algo="schnorr")]
        e = schnorr_challenge(r, pub, z)
        return [SigItem(pub, e, r, s, tx.txid, idx, algo="schnorr")]
    return [SigItem(pubkey=pub, z=z, r=r, s=s, txid=tx.txid, input_index=idx)]


def _msig_items(
    tx: Tx,
    idx: int,
    sigs: list[bytes],
    m: int,
    keys: list[bytes],
    script_code: bytes,
    prevout_amounts: Optional[dict[int, int]],
    bch: bool,
    segwit: bool,
) -> Optional[list[SigItem]]:
    """Candidate items for one m-of-n input: sig i against keys
    ``i..n-m+i`` (the only keys the order-preserving consensus walk can
    pair it with).  A DER-unparseable sig yields auto-invalid candidates
    (it matches no key, exactly as in the interpreter).  Returns None —
    whole input unsupported — only when a required amount is missing."""
    n = len(keys)
    txid = tx.txid
    out: list[SigItem] = []
    decoded = [None] * n  # decode each key once, lazily
    for i, sig_blob in enumerate(sigs):
        rs = None
        z = 0
        if len(sig_blob) >= 9:
            hashtype = sig_blob[-1]
            rs = parse_der_signature(sig_blob[:-1])
            if rs is not None:
                if segwit or (bch and hashtype & SIGHASH_FORKID):
                    if prevout_amounts is None or idx not in prevout_amounts:
                        return None
                    z = bip143_sighash(
                        tx, idx, script_code, prevout_amounts[idx], hashtype
                    )
                else:
                    z = legacy_sighash(tx, idx, script_code, hashtype)
        for j in range(i, n - m + i + 1):
            if rs is None:
                item = SigItem(None, 0, 0, 0, txid, idx, i, j, m, n)
            else:
                if decoded[j] is None:
                    decoded[j] = decode_pubkey(keys[j])
                item = SigItem(
                    decoded[j], z, rs[0], rs[1], txid, idx, i, j, m, n
                )
            out.append(item)
    return out


def msig_match(m: int, n: int, ok: Callable[[int, int], bool]) -> list[bool]:
    """The consensus CHECKMULTISIG matching walk (Bitcoin Core
    interpreter.cpp OP_CHECKMULTISIG): compare from the top of the stack —
    last signature against last key — discarding a key on mismatch, and
    fail once the signatures left outnumber the keys left.  ``ok(i, j)``
    is the verify verdict for (sig i, key j); returns per-sig matched
    flags (the input is valid iff all are True)."""
    matched = [False] * m
    i, j = m - 1, n - 1
    while i >= 0 and j >= i:
        if ok(i, j):
            matched[i] = True
            i -= 1
        j -= 1
    return matched


def combine_verdicts(
    items: Sequence[SigItem], verdicts: Sequence[bool]
) -> list[bool]:
    """Collapse per-candidate device verdicts to per-SIGNATURE verdicts, in
    item order: single-sig items pass through; each multisig input's
    candidate block runs the consensus walk.  ``len(result)`` equals the
    extraction's ``stats.sigs``."""
    out: list[bool] = []
    k = 0
    N = len(items)
    while k < N:
        it = items[k]
        if it.num_sigs == 1 and it.num_keys == 1:
            out.append(bool(verdicts[k]))
            k += 1
            continue
        M: dict[tuple[int, int], bool] = {}
        end = k
        while (
            end < N
            and items[end].input_index == it.input_index
            and items[end].txid == it.txid
        ):
            M[(items[end].sig_index, items[end].key_index)] = bool(
                verdicts[end]
            )
            end += 1
        out.extend(
            msig_match(it.num_sigs, it.num_keys, lambda i, j: M.get((i, j), False))
        )
        k = end
    return out
