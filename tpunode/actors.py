"""Actor substrate: typed mailboxes, pub/sub, supervision.

The reference builds on the NQE actor library (reference: package.yaml:29;
``Inbox``/``Mailbox``/``Publisher``/``Supervisor`` imported at
src/Haskoin/Node.hs:49-56, src/Haskoin/Node/PeerMgr.hs:98-115, etc.).  This is
the asyncio-native equivalent:

* :class:`Mailbox` — a typed queue; ``send`` never blocks (NQE's
  ``send``/``sendSTM``), ``receive`` awaits the next message.  Optionally
  bounded with a counted drop-oldest policy.
* :class:`Publisher` — broadcast pub/sub where every subscriber owns a private
  queue (NQE ``withPublisher``/``withSubscription``); subscribing is an async
  context manager so subscriptions are always scoped.  Subscriber queues are
  bounded by default (drop-oldest) — one stalled embedder must not grow
  memory without bound.
* :class:`Supervisor` — owns child tasks and delivers death notifications to a
  callback, the analog of NQE's ``withSupervisor (Notify ...)`` + ``addChild``
  (reference: PeerMgr.hs:215,230,562-563).
* :class:`LinkedTasks` — the ``withAsync``+``link`` pattern: background loops
  whose failure must take the whole enclosing scope down
  (reference: Node.hs:191-192, Chain.hs:296, PeerMgr.hs:234).
* :class:`TaskRegistry` / :func:`spawn_supervised` — the asyncsan
  task-supervision registry: EVERY task tpunode spawns goes through here
  (the ``raw-spawn`` lint in tpunode/analysis enforces it), so an
  orphaned task — pending, with no live open owner — is reported at node
  shutdown as an ``asyncsan.task_leak`` event with its spawn site,
  instead of being garbage-collected mid-flight in silence.

Everything runs on one event loop; like the reference's STM-guarded actors,
state transitions are race-free because they never yield mid-update.
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import sys
import time
import weakref
from collections import deque

from .chaos import chaos
from .compat import timeout as _timeout
from .events import events
from .metrics import metrics
from .tracectx import _ACTIVE as _active_trace
from typing import (
    AsyncIterator,
    Awaitable,
    Callable,
    Generic,
    Optional,
    TypeVar,
)

__all__ = [
    "Mailbox",
    "Publisher",
    "Supervisor",
    "LinkedTasks",
    "TaskRegistry",
    "task_registry",
    "spawn_supervised",
    "receive_match",
]

T = TypeVar("T")
U = TypeVar("U")


class _TaskRecord:
    """Registry bookkeeping for one spawned task: display name, spawn
    site (file:line outside actors.py), and a weakref to the owning
    supervisor-ish object (None = caller promised to await/cancel)."""

    __slots__ = ("name", "where", "owner")

    def __init__(self, name: str, where: str, owner: Optional[object]):
        self.name = name
        self.where = where
        self.owner = weakref.ref(owner) if owner is not None else None


class TaskRegistry:
    """Process-wide supervision registry (asyncsan runtime sanitizer).

    Every task spawned through :func:`spawn_supervised` is tracked until
    it completes.  :meth:`report_leaks` — called at node shutdown —
    emits one ``asyncsan.task_leak`` event (+ ``asyncsan.task_leaks``
    metric) per task that is still pending with no live, open owner:
    exactly the fire-and-forget orphans whose dropped handle the static
    ``dropped-task`` rule catches at lint time when the spawn is literal,
    and only this registry can catch when it is not.

    An *owner* scopes the leak check: a task whose owner is alive and not
    closing (``_closing`` false — the Supervisor/LinkedTasks convention)
    is supervised, not leaked, even while another node in the same
    process shuts down.  All mutation happens on the event-loop thread.
    """

    def __init__(self):
        self._records: dict[asyncio.Task, _TaskRecord] = {}

    def spawn(
        self,
        coro: Awaitable,
        name: str = "",
        owner: Optional[object] = None,
    ) -> asyncio.Task:
        task = asyncio.ensure_future(coro)  # asyncsan: disable=raw-spawn
        if name:
            task.set_name(name)
        self._records[task] = _TaskRecord(
            name or task.get_name(), _spawn_site(), owner
        )
        task.add_done_callback(self._task_done)
        return task

    def _task_done(self, task: asyncio.Task) -> None:
        self._records.pop(task, None)

    def live(self) -> "list[asyncio.Task]":
        """Tracked tasks still pending (telemetry/debug view)."""
        return [t for t in self._records if not t.done()]

    def report_leaks(self, log_=None) -> "list[dict]":
        """Emit one ``asyncsan.task_leak`` event per orphaned pending
        task; returns the events.  Each leak is reported exactly once:
        its record is dropped from the registry on report (the task
        itself stays alive — cancelling it is the caller's call)."""
        sink = log_ if log_ is not None else events
        out: list[dict] = []
        for task, rec in list(self._records.items()):
            if task.done():
                continue
            if rec.owner is not None:
                owner = rec.owner()
                if owner is not None and not getattr(owner, "_closing", False):
                    continue  # supervised by a live, open owner
            del self._records[task]
            task.remove_done_callback(self._task_done)
            metrics.inc("asyncsan.task_leaks")
            out.append(
                sink.emit(
                    "asyncsan.task_leak", task=rec.name, where=rec.where,
                )
            )
        return out


# This module's own filename, for skipping registry-internal frames in
# _spawn_site (code objects compiled from this module carry exactly this
# string, so no per-spawn abspath work is needed).
_HERE = __file__


def _spawn_site() -> str:
    """file:line of the first caller frame outside this module — the
    attribution that makes a task-leak report actionable."""
    fr = sys._getframe(1)
    while fr is not None and fr.f_code.co_filename == _HERE:
        fr = fr.f_back
    if fr is None:
        return "?"
    return f"{os.path.basename(fr.f_code.co_filename)}:{fr.f_lineno}"


#: The process-wide registry (tests may construct private ones).
task_registry = TaskRegistry()


def spawn_supervised(
    coro: Awaitable, name: str = "", owner: Optional[object] = None
) -> asyncio.Task:
    """Spawn a task through the supervision registry — the only sanctioned
    way to create a task inside tpunode (lint rule ``raw-spawn``).

    ``owner`` is the supervising object (Supervisor, LinkedTasks, engine,
    peer handle...) responsible for cancelling/awaiting the task; pass
    None only when the spawning code itself awaits the handle before its
    scope exits.  Pending tasks with no live open owner are reported as
    ``asyncsan.task_leak`` at node shutdown."""
    return task_registry.spawn(coro, name=name, owner=owner)


class _Traced:
    """Queue envelope carrying a message's trace position (tracectx): the
    sender's active ``(trace, span_id)`` rides along so the receiving
    actor's processing lands in the same per-item trace."""

    __slots__ = ("item", "act")

    def __init__(self, item, act):
        self.item = item
        self.act = act


class Mailbox(Generic[T]):
    """Typed actor queue (NQE ``Inbox``/``Mailbox``).

    Unbounded by default (actor-internal mailboxes are drained by linked
    loops whose death tears the node down — crash-only, never silently
    lossy).  With ``maxsize`` set, ``send`` on a full queue evicts the
    OLDEST queued item instead of blocking or raising (drop-oldest), and
    counts the eviction in ``dropped`` + the process-wide
    ``bus.dropped`` metric — the policy for user-facing subscriptions,
    where one stalled embedder must not grow memory without bound
    (reference analog: bounded NQE/STM mailboxes, SURVEY.md C5).
    """

    def __init__(self, name: str = "", maxsize: Optional[int] = None):
        if maxsize is not None and maxsize < 1:
            raise ValueError(f"maxsize must be >= 1 or None, got {maxsize}")
        self._queue: asyncio.Queue = asyncio.Queue()
        # enqueue monotonic timestamps, parallel to _queue: the watchdog's
        # oldest-message-age signal (a growing head age localizes a stuck
        # consumer even when qsize alone looks plausible)
        self._times: deque[float] = deque()
        self.name = name
        self.maxsize = maxsize
        self.dropped = 0

    def send(self, item: T) -> None:
        """Enqueue without blocking (NQE ``send``); see drop-oldest above.
        Captures the sender's active trace position (tracectx) so causal
        traces flow across actor hops."""
        act = _active_trace.get()
        if act is not None:
            item = _Traced(item, act)  # type: ignore[assignment]
        if chaos.on:  # injected delivery faults (tpunode/chaos.py)
            spec = chaos.decide("mailbox.send", self.name)
            if spec is not None and self._chaos_deliver(spec, item):
                return
        self._put(item)

    def _put(self, item) -> None:
        """Enqueue a (possibly trace-wrapped) item: the delivery core."""
        if self.maxsize is not None and self._queue.qsize() >= self.maxsize:
            try:
                self._queue.get_nowait()
                if self._times:
                    self._times.popleft()
            except asyncio.QueueEmpty:
                pass
            self.dropped += 1
            metrics.inc("bus.dropped")
        self._queue.put_nowait(item)
        self._times.append(time.monotonic())

    def _chaos_deliver(self, spec, item) -> bool:
        """Apply an injected delivery fault; True = chaos owns delivery.
        ``delay`` re-enqueues after ``dur`` seconds via the running loop;
        ``reorder`` jumps this message ahead of the current queue head.
        Both preserve at-least-once delivery — chaos perturbs timing and
        order, never drops actor mail (mailboxes are the crash-only
        control plane; loss belongs to the socket points)."""
        if spec.action == "delay":
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                return False  # no loop to schedule on: deliver normally
            loop.call_later(spec.dur, self._put, item)
            return True
        if spec.action == "reorder":
            try:
                prev = self._queue.get_nowait()
            except asyncio.QueueEmpty:
                return False  # nothing to swap with
            if self._times:
                self._times.popleft()
            self._put(item)  # the newcomer jumps the head
            self._put(prev)
            return True
        return False

    def _unwrap(self, item) -> T:
        """Pop-side of the trace envelope: re-activate the carried trace
        position for the receiving task (or clear a stale one)."""
        if type(item) is _Traced:
            _active_trace.set(item.act)
            return item.item
        if _active_trace.get() is not None:
            _active_trace.set(None)
        return item

    async def receive(self) -> T:
        item = await self._queue.get()
        if self._times:
            self._times.popleft()
        return self._unwrap(item)

    async def receive_match(self, select: Callable[[T], Optional[U]]) -> U:
        """Await the first message for which ``select`` returns non-None;
        non-matching messages are discarded (NQE ``receiveMatch`` as used on
        event subscriptions, e.g. NodeSpec.hs:202-205)."""
        while True:
            item = await self._queue.get()
            if self._times:
                self._times.popleft()
            out = select(self._unwrap(item))
            if out is not None:
                return out

    def drain_nowait(self) -> list[T]:
        """Pop every queued message without waiting (test/shutdown helper;
        unwraps trace envelopes like ``receive``)."""
        out: list[T] = []
        while True:
            try:
                item = self._queue.get_nowait()
            except asyncio.QueueEmpty:
                return out
            if self._times:
                self._times.popleft()
            out.append(self._unwrap(item))

    def qsize(self) -> int:
        return self._queue.qsize()

    def oldest_age(self, now: Optional[float] = None) -> float:
        """Seconds the head message has been waiting (0.0 when empty) —
        the watchdog's per-mailbox stall signal."""
        if not self._times:
            return 0.0
        return (time.monotonic() if now is None else now) - self._times[0]

    def __repr__(self) -> str:
        return f"<Mailbox {self.name or hex(id(self))} n={self._queue.qsize()}>"


async def receive_match(
    mailbox: Mailbox[T],
    select: Callable[[T], Optional[U]],
    timeout: float | None = None,
) -> U:
    """``receive_match`` with an optional timeout (NQE ``receiveMatchS``)."""
    if timeout is None:
        return await mailbox.receive_match(select)
    async with _timeout(timeout):
        return await mailbox.receive_match(select)


class Publisher(Generic[T]):
    """Broadcast bus with per-subscriber queues (NQE ``Publisher``).

    ``maxsize`` bounds every subscriber's private queue (drop-oldest,
    counted — see :class:`Mailbox`).  The default bounds the user event
    bus: the node republishes every peer message there (node.py
    ``_peer_events``), so a subscriber that stalls during a 150k-sig
    block or a mempool flood would otherwise grow memory without bound
    (VERDICT r4 weak #3).  Pass ``maxsize=None`` for the internal
    always-drained glue buses.
    """

    DEFAULT_MAXSIZE = 10_000

    def __init__(self, name: str = "", maxsize: Optional[int] = DEFAULT_MAXSIZE):
        self._subscribers: set[Mailbox[T]] = set()
        self.name = name
        self.maxsize = maxsize

    def publish(self, event: T) -> None:
        for sub in tuple(self._subscribers):
            sub.send(event)

    @property
    def dropped(self) -> int:
        """Total events evicted across current subscribers."""
        return sum(sub.dropped for sub in self._subscribers)

    @contextlib.asynccontextmanager
    async def subscription(self) -> AsyncIterator[Mailbox[T]]:
        """Scoped subscription (NQE ``withSubscription``)."""
        mb: Mailbox[T] = Mailbox(name=f"{self.name}-sub", maxsize=self.maxsize)
        self._subscribers.add(mb)
        try:
            yield mb
        finally:
            self._subscribers.discard(mb)


DeathCallback = Callable[[asyncio.Task, Optional[BaseException]], None]


class Supervisor:
    """Parent of crash-isolated child tasks with death notification.

    Equivalent of NQE's ``withSupervisor (Notify cb)``: any child ending — by
    crash, cancellation or normal return — invokes ``on_death(task, exc)``
    instead of propagating, exactly how the reference turns peer-thread deaths
    into ``PeerDied`` manager messages (PeerMgr.hs:230).
    """

    def __init__(self, on_death: Optional[DeathCallback] = None, name: str = ""):
        self._children: set[asyncio.Task] = set()
        self._on_death = on_death
        self._closing = False
        self.name = name

    def add_child(self, coro: Awaitable, name: str = "") -> asyncio.Task:
        task = spawn_supervised(coro, name=name, owner=self)
        self._children.add(task)
        task.add_done_callback(self._child_done)
        return task

    def _child_done(self, task: asyncio.Task) -> None:
        self._children.discard(task)
        if self._closing:
            return
        if task.cancelled():
            exc: Optional[BaseException] = asyncio.CancelledError()
        else:
            exc = task.exception()
        if self._on_death is not None:
            self._on_death(task, exc)

    @property
    def children(self) -> set[asyncio.Task]:
        return set(self._children)

    async def aclose(self) -> None:
        """Cancel and await every child (end of the supervisor bracket)."""
        self._closing = True
        children = tuple(self._children)
        for t in children:
            t.cancel()
        for t in children:
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await t
        self._children.clear()

    async def __aenter__(self) -> "Supervisor":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.aclose()


class LinkedTasks:
    """Background loops whose crash must abort the owning scope.

    The reference ``link``s its glue loops and actor main loops so an internal
    crash tears down the whole node bracket (crash-only design, SURVEY.md §5).
    Here: the first exception from any linked task cancels all of them, is
    reported to ``on_failure`` (the hook the node uses to abort the embedding
    scope) and re-raised when the scope closes.
    """

    def __init__(
        self,
        name: str = "",
        on_failure: Optional[Callable[[BaseException], None]] = None,
    ):
        self._tasks: set[asyncio.Task] = set()
        self._failure: Optional[BaseException] = None
        self._closing = False
        self.name = name
        self.on_failure = on_failure

    def link(self, coro: Awaitable, name: str = "") -> asyncio.Task:
        task = spawn_supervised(coro, name=name, owner=self)
        self._tasks.add(task)
        task.add_done_callback(self._task_done)
        return task

    def _task_done(self, task: asyncio.Task) -> None:
        self._tasks.discard(task)
        if self._closing or task.cancelled():
            return
        exc = task.exception()
        if exc is not None and self._failure is None:
            self._failure = exc
            for t in tuple(self._tasks):
                t.cancel()
            if self.on_failure is not None:
                self.on_failure(exc)

    def check(self) -> None:
        if self._failure is not None:
            raise self._failure

    async def aclose(self) -> None:
        self._closing = True
        tasks = tuple(self._tasks)
        for t in tasks:
            t.cancel()
        for t in tasks:
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await t
        self._tasks.clear()
        if self._failure is not None:
            raise self._failure

    async def __aenter__(self) -> "LinkedTasks":
        return self

    async def __aexit__(self, exc_type, exc, tb) -> None:
        self._closing = True
        tasks = tuple(self._tasks)
        for t in tasks:
            t.cancel()
        for t in tasks:
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await t
        self._tasks.clear()
        # don't mask an exception already unwinding the scope
        if exc is None and self._failure is not None:
            raise self._failure
