"""Chain manager: header synchronization actor + persistent header store.

Mirror of /root/reference/src/Haskoin/Node/Chain.hs.  One actor owns the
header chain: it picks one sync peer at a time (locked through the peer's
busy flag), requests headers with block locators, validates and persists
2000-header batches with a continuation signal, emits ``ChainBestBlock`` /
``ChainSynced`` events, and serves read queries straight from the store.

Storage schema (reference Chain.hs:180-231,448-491): key ``0x90 + hash`` ->
serialized BlockNode, ``0x91`` -> best BlockNode, ``0x92`` -> schema version;
on version mismatch all 0x90/0x91 keys are purged and the chain re-syncs.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Union

from .actors import LinkedTasks, Mailbox, Publisher
from .headers import (
    BadHeaders,
    BlockNode,
    block_locator,
    connect_blocks,
    genesis_node,
    get_ancestor,
    get_parents,
    split_point,
)
from .params import Network, PROTOCOL_VERSION
from .peer import Peer, PeerSentBadHeaders, PeerTimeout
from .events import events
from .metrics import metrics
from .store import KVStore, put_op
from .trace import span
from .tracectx import finish_active as _finish_active_trace
from .wire import BlockHeader, MsgGetHeaders, MsgSendHeaders

__all__ = [
    "ChainConfig",
    "ChainEvent",
    "ChainBestBlock",
    "ChainSynced",
    "Chain",
    "ChainDB",
    "DATA_VERSION",
]

# Schema version (reference Chain.hs:449-450).
DATA_VERSION = 1

_KEY_HEADER = b"\x90"
_KEY_BEST = b"\x91"
_KEY_VERSION = b"\x92"

ZERO_HASH = b"\x00" * 32

log = logging.getLogger("tpunode.chain")


@dataclass(frozen=True)
class ChainBestBlock:
    node: BlockNode


@dataclass(frozen=True)
class ChainSynced:
    node: BlockNode


ChainEvent = Union[ChainBestBlock, ChainSynced]


@dataclass
class ChainConfig:
    """Reference Chain.hs:138-149."""

    store: KVStore
    net: Network
    pub: Publisher
    timeout: float = 120.0
    # ChainSynced gating.  Default (None): report synced the first time the
    # sync queue drains with no locked peer — works on live chains AND stale
    # fixtures.  Set to e.g. 7200.0 for the reference's exact behavior
    # (Chain.hs:533-537: only report synced when the best header is MORE
    # than 2h old — suits its old regtest fixture, but on a live chain the
    # event would wait for a 2h tip stall; divergence is deliberate).
    synced_min_age: Optional[float] = None
    # Wire continuation threshold (reference hardcodes 2000, Chain.hs:513);
    # configurable so tests can exercise continuation with small fixtures.
    headers_batch: int = 2000
    # Injectable wall clock (consensus timestamp checks + the synced_min_age
    # gate); tests override instead of patching the stdlib time module.
    now: Callable[[], float] = time.time


class ChainDB:
    """Typed header-store layer over the KV store: the ``BlockHeaders``
    instance of the reference (Chain.hs:233-263)."""

    def __init__(self, store: KVStore):
        self._kv = store

    def get_header(self, block_hash: bytes) -> Optional[BlockNode]:
        raw = self._kv.get(_KEY_HEADER + block_hash)
        return None if raw is None else BlockNode.deserialize(raw)

    def get_best(self) -> BlockNode:
        raw = self._kv.get(_KEY_BEST)
        if raw is None:
            raise RuntimeError("could not get best block from database")
        return BlockNode.deserialize(raw)

    def put_headers(self, nodes: list[BlockNode], best: Optional[BlockNode]) -> None:
        """Atomic batch write of nodes (+ best pointer), the analog of
        ``addBlockHeaders``/``writeBatch`` (Chain.hs:256-263)."""
        self._kv.write_batch(self._header_ops(nodes, best))

    async def put_headers_durable(
        self, nodes: list[BlockNode], best: Optional[BlockNode]
    ) -> None:
        """:meth:`put_headers` with the fsync off the event loop: stores
        exposing ``write_batch_async`` (LogKV's group-commit writer thread)
        do the physical append + fsync there, and this coroutine resumes
        only once the batch is durable — the chain actor keeps its
        acked ⇒ durable contract (the continuation ``getheaders`` is only
        sent after this returns) without ever blocking the loop inside
        ``os.fsync`` (asyncsan blocking-call clean, ISSUE 9)."""
        ops = self._header_ops(nodes, best)
        submit = getattr(self._kv, "write_batch_async", None)
        if submit is None:
            self._kv.write_batch(ops)  # memory/native engines: no fsync cost
            return
        await asyncio.wrap_future(submit(ops))

    @staticmethod
    def _header_ops(nodes: list[BlockNode], best: Optional[BlockNode]):
        ops = [put_op(_KEY_HEADER + n.hash, n.serialize()) for n in nodes]
        if best is not None:
            ops.append(put_op(_KEY_BEST, best.serialize()))
        return ops

    def get_version(self) -> Optional[int]:
        raw = self._kv.get(_KEY_VERSION)
        return None if raw is None else int.from_bytes(raw, "little")

    def init(self, net: Network) -> None:
        """Version-gated init: purge header keys on schema mismatch, write the
        genesis node if the store is empty (reference ``initChainDB``
        Chain.hs:454-468)."""
        ver = self.get_version()
        if ver != DATA_VERSION:
            if ver is not None:
                log.info(
                    "[Chain] schema version %s != %s: purging header store",
                    ver,
                    DATA_VERSION,
                )
            self.purge()
        self._kv.put(_KEY_VERSION, DATA_VERSION.to_bytes(4, "little"))
        if self._kv.get(_KEY_BEST) is None:
            g = genesis_node(net)
            self.put_headers([g], g)

    def purge(self) -> None:
        """Delete every 0x90/0x91 key (reference ``purgeChainDB``
        Chain.hs:472-491)."""
        ops = []
        for k, _ in self._kv.scan_prefix(_KEY_HEADER):
            ops.append(("del", k, b""))
        for k, _ in self._kv.scan_prefix(_KEY_BEST):
            ops.append(("del", k, b""))
        if ops:
            self._kv.write_batch(ops)


@dataclass
class _ChainSync:
    """Syncing-peer lock record (reference Chain.hs:193-197)."""

    peer: Peer
    timestamp: float
    best: Optional[BlockNode] = None


@dataclass(frozen=True)
class _Headers:
    peer: Peer
    headers: list[BlockHeader]


@dataclass(frozen=True)
class _PeerConnected:
    peer: Peer


@dataclass(frozen=True)
class _PeerDisconnected:
    peer: Peer


class _Ping:
    pass


class Chain:
    """The chain actor handle + read API (reference ``Chain`` Chain.hs:129-132
    and the ``chainGet*`` helpers Chain.hs:676-762)."""

    def __init__(self, cfg: ChainConfig, on_failure=None):
        self.cfg = cfg
        self.db = ChainDB(cfg.store)
        self.mailbox: Mailbox = Mailbox(name="chain")
        self._syncing: Optional[_ChainSync] = None
        self._peers: list[Peer] = []
        self._been_in_sync = False
        self._catching_up = False
        self._tasks = LinkedTasks(name="chain", on_failure=on_failure)

    # -- lifecycle ----------------------------------------------------------

    async def __aenter__(self) -> "Chain":
        # DB init completes before the actor loop starts (reference
        # Chain.hs:294-295; CHANGELOG 0.17.2 records the bug when it didn't).
        self.db.init(self.cfg.net)
        self._tasks.link(self._main_loop(), name="chain-main")
        self._tasks.link(self._ping_loop(), name="chain-ping")
        return self

    async def __aexit__(self, *exc) -> None:
        await self._tasks.__aexit__(*exc)

    async def _main_loop(self) -> None:
        best = self.db.get_best()
        log.info(
            "[Chain] starting at height %d (%s)",
            best.height,
            best.hash[::-1].hex()[:16],
        )
        self._emit(ChainBestBlock(best))
        while True:
            msg = await self.mailbox.receive()
            if isinstance(msg, _Headers):
                await self._process_headers(msg.peer, msg.headers)
                # a headers message's pipeline trace (started in the peer
                # wire loop, carried here by the mailbox) ends at import
                _finish_active_trace()
            elif isinstance(msg, _PeerConnected):
                self._add_peer(msg.peer)
                self._sync_new_peer()
            elif isinstance(msg, _PeerDisconnected):
                self._finish_peer(msg.peer)
                self._sync_new_peer()
            elif isinstance(msg, _Ping):
                self._check_timeout()

    async def _ping_loop(self) -> None:
        """Jittered housekeeping timer (reference ``withSyncLoop``
        Chain.hs:429-446)."""
        while True:
            await asyncio.sleep(random.uniform(2.0, 20.0))
            self.mailbox.send(_Ping())

    def _emit(self, event: ChainEvent) -> None:
        self.cfg.pub.publish(event)

    # -- sync state machine (single-threaded: runs inside the actor loop) ----

    async def _process_headers(self, p: Peer, headers: list[BlockHeader]) -> None:
        """Validate/persist one batch (reference ``processHeaders``
        Chain.hs:323-350 + ``importHeaders`` Chain.hs:496-520).  The
        persist is awaited DURABLE before any downstream signal (events,
        the continuation ``getheaders``): an acked import survives a crash.
        The await runs on the group-commit writer thread for stores that
        have one, so the actor loop is never inside an fsync; the mailbox
        simply queues the next messages until the commit lands (the actor
        is single-threaded, so no state can interleave mid-import)."""
        prev_best = self.db.get_best()
        with span("chain.import_headers"):
            try:
                nodes, best = connect_blocks(
                    self.db, self.cfg.net, int(self.cfg.now()), headers
                )
            except BadHeaders as e:
                log.warning(
                    "[Chain] peer %s sent bad headers: %s", p.label, e
                )
                # the peer.ban event comes from the peer manager's death
                # path (PeerSentBadHeaders is in _BAN_ERRORS) — emitting
                # here too would double-count the incident
                p.kill(PeerSentBadHeaders(str(e)))
                return
            await self.db.put_headers_durable(
                nodes, best if best.hash != prev_best.hash else None
            )
        metrics.inc("chain.headers", len(nodes))
        if nodes:
            log.debug(
                "[Chain] imported %d headers from %s up to height %d",
                len(nodes),
                p.label,
                nodes[-1].height,
            )
            events.emit(
                "chain.headers", peer=p.label, count=len(nodes),
                height=nodes[-1].height,
            )
        if best.hash != prev_best.hash:
            metrics.set_gauge("chain.height", best.height)
            # Reorg detection: if the new best simply extends the old tip
            # (the first imported node's parent IS the old tip, or the old
            # tip lies on the new nodes' path) this is free; otherwise one
            # ancestor walk finds the fork point.
            extended = bool(nodes) and (
                nodes[0].header.prev == prev_best.hash
                or any(n.hash == prev_best.hash for n in nodes)
            )
            if not extended:
                try:
                    fork = split_point(self.db, prev_best, best)
                except BadHeaders:
                    fork = None
                if fork is not None and fork.hash != prev_best.hash:
                    depth = prev_best.height - fork.height
                    metrics.inc("chain.reorgs")
                    log.warning(
                        "[Chain] reorg depth %d: %s -> %s (fork at %d)",
                        depth, prev_best.hash_hex, best.hash_hex, fork.height,
                    )
                    events.emit(
                        "chain.reorg", depth=depth,
                        fork_height=fork.height,
                        old_tip=prev_best.hash_hex, old_height=prev_best.height,
                        new_tip=best.hash_hex, new_height=best.height,
                    )
        if self._syncing is not None:
            self._syncing.timestamp = time.monotonic()
            if nodes:
                # remember the peer's tip so the next locator continues from it
                self._syncing.best = nodes[-1]
        if best.hash != prev_best.hash:
            log.info(
                "[Chain] new best height %d (%s)",
                best.height,
                best.hash[::-1].hex()[:16],
            )
            self._emit(ChainBestBlock(best))
        # continuation signal (Chain.hs:513-515)
        done = len(headers) != self.cfg.headers_batch
        if self._syncing is None or self._syncing.peer is p:
            # only the sync peer's stream drives the live catch-up view: a
            # one-header announcement from another peer must not mask an
            # in-flight continuation
            self._catching_up = not done
        if done:
            p.send_message(MsgSendHeaders())
            self._finish_peer(p)
            self._sync_new_peer()
            self._sync_notif()
        else:
            self._sync_peer(p)

    def _sync_new_peer(self) -> None:
        """If nothing is syncing, pick the next queued peer.  A peer whose
        busy lock is held elsewhere stays in the queue for a later retry
        (reference Chain.hs:352-362,549-558 — ``nextPeer`` leaves busy peers
        queued; the ping tick retries)."""
        if self._syncing is not None:
            return
        for p in list(self._peers):
            if self._set_syncing_peer(p):
                self._sync_peer(p)
                return

    def _sync_notif(self) -> None:
        """One-shot synced notification (reference ``notifySynced``
        Chain.hs:529-546).

        Divergence, deliberate: the reference additionally guards on the best
        header being MORE than 7200s old (Chain.hs:535), which reads inverted —
        on a live chain whose tip is recent it would never report synced.  We
        instead report synced the first time the sync queue drains with no
        locked peer, which covers both the reference's own test environment
        (old regtest fixture) and live chains.  ``ChainConfig.synced_min_age``
        restores the reference's exact gate when set.
        """
        if self._been_in_sync or self._syncing is not None or self._peers:
            return
        best = self.db.get_best()
        if self.cfg.synced_min_age is not None:
            if self.cfg.now() - best.header.timestamp <= self.cfg.synced_min_age:
                return  # reference gate: tip not old enough yet
        self._been_in_sync = True
        log.info("[Chain] chain synced at height %d", best.height)
        self._emit(ChainSynced(best))

    def _sync_peer(self, p: Peer) -> None:
        """Request more headers from ``p`` if appropriate
        (reference ``syncPeer`` Chain.hs:372-403)."""
        if self._syncing is not None:
            if self._syncing.peer is not p:
                return
            base = self._syncing.best or self.db.get_best()
            self._syncing.timestamp = time.monotonic()
        else:
            if not self._set_syncing_peer(p):
                return
            base = self.db.get_best()
        locator = block_locator(self.db, base)
        p.send_message(
            MsgGetHeaders(
                version=PROTOCOL_VERSION, locator=tuple(locator), stop=ZERO_HASH
            )
        )

    def _set_syncing_peer(self, p: Peer) -> bool:
        """Claim the peer through its busy flag (reference ``setSyncingPeer``
        Chain.hs:613-638)."""
        if not p.set_busy():
            return False
        self._syncing = _ChainSync(peer=p, timestamp=time.monotonic())
        if p in self._peers:
            self._peers.remove(p)
        return True

    def _finish_peer(self, p: Peer) -> None:
        """Drop from queue / release the sync lock (reference ``finishPeer``
        Chain.hs:642-668)."""
        if self._syncing is not None and self._syncing.peer is p:
            self._syncing = None
            p.set_free()
        elif p in self._peers:
            self._peers.remove(p)

    def _add_peer(self, p: Peer) -> None:
        if p not in self._peers:
            self._peers.insert(0, p)

    def _check_timeout(self) -> None:
        """Kill a stalled syncing peer; otherwise try to start one
        (reference ``chainMessage ChainPing`` Chain.hs:416-427)."""
        if self._syncing is not None:
            if time.monotonic() - self._syncing.timestamp > self.cfg.timeout:
                log.warning(
                    "[Chain] sync peer %s stalled; killing",
                    self._syncing.peer.label,
                )
                self._syncing.peer.kill(PeerTimeout("chain sync stalled"))
        else:
            self._sync_new_peer()

    # -- notifications from the node glue (reference Chain.hs:727-772) -------

    def peer_connected(self, p: Peer) -> None:
        self.mailbox.send(_PeerConnected(p))

    def peer_disconnected(self, p: Peer) -> None:
        self.mailbox.send(_PeerDisconnected(p))

    def headers(self, p: Peer, headers: list[BlockHeader]) -> None:
        self.mailbox.send(_Headers(p, headers))

    # -- read queries (reference Chain.hs:676-762) ---------------------------

    def get_block(self, block_hash: bytes) -> Optional[BlockNode]:
        return self.db.get_header(block_hash)

    def get_best(self) -> BlockNode:
        return self.db.get_best()

    def get_ancestor(self, height: int, node: BlockNode) -> Optional[BlockNode]:
        return get_ancestor(self.db, height, node)

    def get_parents(self, height: int, node: BlockNode) -> list[BlockNode]:
        return get_parents(self.db, height, node)

    def get_split_block(self, left: BlockNode, right: BlockNode) -> BlockNode:
        return split_point(self.db, left, right)

    def block_main(self, block_hash: bytes) -> bool:
        """Is the hash on the main chain? (reference Chain.hs:746-757)"""
        node = self.get_block(block_hash)
        if node is None:
            return False
        anc = self.get_ancestor(node.height, self.get_best())
        return anc is not None and anc.hash == node.hash

    def is_synced(self) -> bool:
        """Live view: ever synced AND not currently chasing a continuation.

        Divergence from the reference (whose ``chainIsSynced`` is a sticky
        latch, Chain.hs:760-762): after the first ChainSynced, falling
        behind by a full continuation batch flips this back to False until
        the catch-up drains.  The ChainSynced EVENT stays one-shot like the
        reference's."""
        return self._been_in_sync and not self._catching_up
