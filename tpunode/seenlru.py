"""Bounded seen/verdict LRU with alias keys — extracted from the mempool.

The mempool's admission dedup and the serve layer's shared verdict-cache
tier (serve.py) need the same structure: an insertion-ordered map of
``key -> entry`` bounded at ``max_entries``, with

* **alias keys** — a secondary ``alias -> key`` index so one entry is
  reachable under two names (mempool: wtxid -> txid for witness
  serializations; serve: raw-bytes digest -> item digest), and
* **pinned-aware eviction** — entries the owner marks *pinned* (a
  predicate over the entry, e.g. "verdict still in flight") are rotated
  to the tail instead of evicted, bounded by one full scan per insert
  and a hard ``2 * max_entries`` ceiling so an all-pinned map (verify
  engine wedged: nothing ever resolves) degrades to forced eviction
  instead of an unbounded leak.

Eviction policy is the owner's business: ``insert`` returns the evicted
``(key, entry)`` pairs and the caller drops its own secondary indexes
(mempool ``_forget``; serve cache-hit accounting).  The structure itself
is not thread-safe — both owners are loop-owned actors.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Iterator, Optional

__all__ = ["SeenLru"]

_MISSING = object()


class SeenLru:
    """Insertion-ordered bounded map with alias keys and pinned rotation."""

    __slots__ = ("max_entries", "_map", "_alias", "_pinned")

    def __init__(
        self,
        max_entries: int,
        pinned: Optional[Callable[[object], bool]] = None,
    ) -> None:
        self.max_entries = max_entries
        self._map: "OrderedDict[bytes, object]" = OrderedDict()
        self._alias: dict = {}  # alias -> primary key (differs)
        self._pinned = pinned

    # -- reads ---------------------------------------------------------------

    def __len__(self) -> int:
        return len(self._map)

    def __contains__(self, key) -> bool:
        return key in self._map

    def __iter__(self) -> Iterator:
        return iter(self._map)

    def get(self, key, default=None):
        """The entry under the primary key (no alias resolution)."""
        return self._map.get(key, default)

    def lookup(self, key):
        """The entry under ``key``, trying the alias index second."""
        e = self._map.get(key)
        if e is not None:
            return e
        alt = self._alias.get(key)
        return self._map.get(alt) if alt is not None else None

    def resolve(self, key):
        """The primary key ``key`` maps to (itself when unaliased)."""
        return self._alias.get(key, key)

    def items(self):
        return self._map.items()

    def values(self):
        return self._map.values()

    # -- writes (loop-owned callers only) ------------------------------------

    def touch(self, key) -> None:
        """Mark ``key`` recently relevant (move to the LRU tail)."""
        self._map.move_to_end(key)

    def pop(self, key, default=None):
        """Drop the primary entry.  Alias cleanup is the caller's (an
        owner popping for re-admission re-establishes the alias itself)."""
        return self._map.pop(key, default)

    def alias(self, alt, key) -> None:
        """Record ``alt`` as a secondary name for primary ``key``."""
        self._alias[alt] = key

    def drop_alias(self, alt) -> None:
        self._alias.pop(alt, None)

    def insert(self, key, entry) -> "list[tuple]":
        """Insert (or refresh) ``key`` at the LRU tail and evict down to
        the bound.  Returns the evicted ``(key, entry)`` pairs, oldest
        first — the caller owns secondary-index teardown and metrics.

        Pinned entries (per the constructor predicate) rotate to the
        tail instead of evicting, so a pinned head never shields
        evictable entries behind it.  The rotation is bounded: at most
        one full scan per insert (all-pinned maps accept the overshoot)
        and a hard ``2 * max_entries`` ceiling past which pinned status
        is ignored.
        """
        self._map[key] = entry
        self._map.move_to_end(key)
        evicted: list = []
        scanned, max_scan = 0, len(self._map)
        while len(self._map) > self.max_entries and scanned < max_scan:
            old_key, old = self._map.popitem(last=False)
            scanned += 1
            if (
                self._pinned is not None
                and self._pinned(old)
                and len(self._map) < 2 * self.max_entries
            ):
                self._map[old_key] = old
                continue
            evicted.append((old_key, old))
        return evicted
