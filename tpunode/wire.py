"""Bitcoin wire protocol codec: messages, transactions, blocks, framing.

The reference obtains its codec from haskoin-core (``getMessage``/``putMessage``
and the ``Message`` sum type; consumed at src/Haskoin/Node/Peer.hs:61-82 and
framed at src/Haskoin/Node/Peer.hs:247-283).  This module is a from-scratch
implementation of the same wire format: a 24-byte envelope (magic, command,
length, checksum) followed by the payload, plus codecs for every message the
node exchanges.

Hash values are held in *internal* byte order (raw double-SHA256 output); use
``util.hash_to_hex`` for display order.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import cached_property
from typing import Optional

from .params import Network
from .util import (
    Reader,
    double_sha256,
    hash_to_hex,
    write_varint,
    write_varstr,
)

__all__ = [
    "MessageHeader",
    "NetworkAddress",
    "InvType",
    "InvVector",
    "OutPoint",
    "TxIn",
    "TxOut",
    "Tx",
    "BlockHeader",
    "Block",
    "LazyBlock",
    "LazyTx",
    "MsgVersion",
    "MsgVerAck",
    "MsgPing",
    "MsgPong",
    "MsgAddr",
    "MsgInv",
    "MsgGetData",
    "MsgNotFound",
    "MsgGetBlocks",
    "MsgGetHeaders",
    "MsgHeaders",
    "MsgBlock",
    "MsgTx",
    "MsgGetAddr",
    "MsgMempool",
    "MsgSendHeaders",
    "MsgFeeFilter",
    "MsgReject",
    "MsgOther",
    "encode_message",
    "decode_message",
    "decode_message_header",
    "build_merkle_root",
    "DecodeError",
    "HEADER_SIZE",
    "MAX_PAYLOAD",
]

HEADER_SIZE = 24
# Largest payload the peer loop will accept (reference: Peer.hs:266).
MAX_PAYLOAD = 32 * 1024 * 1024


class DecodeError(ValueError):
    """Raised when wire bytes cannot be decoded."""


# --- envelope --------------------------------------------------------------


@dataclass(frozen=True)
class MessageHeader:
    """24-byte message envelope: magic | command[12] | length | checksum."""

    magic: int
    command: str
    length: int
    checksum: bytes

    def serialize(self) -> bytes:
        cmd = self.command.encode("ascii")
        if len(cmd) > 12:
            raise DecodeError(f"command too long: {self.command}")
        return (
            self.magic.to_bytes(4, "big")
            + cmd.ljust(12, b"\x00")
            + self.length.to_bytes(4, "little")
            + self.checksum
        )

    @classmethod
    def deserialize(cls, data: bytes) -> "MessageHeader":
        if len(data) < HEADER_SIZE:
            raise DecodeError("short message header")
        magic = int.from_bytes(data[0:4], "big")
        command = data[4:16].rstrip(b"\x00").decode("ascii", errors="replace")
        length = int.from_bytes(data[16:20], "little")
        checksum = data[20:24]
        return cls(magic, command, length, checksum)


# --- shared structures -----------------------------------------------------


@dataclass(frozen=True)
class NetworkAddress:
    """services + IPv6-mapped address + port (no timestamp; version-msg form)."""

    services: int
    address: bytes  # 16 bytes, IPv6 or IPv4-mapped ::ffff:a.b.c.d
    port: int

    @staticmethod
    def from_host_port(host: str, port: int, services: int = 0) -> "NetworkAddress":
        import ipaddress

        ip = ipaddress.ip_address(host)
        if ip.version == 4:
            raw = b"\x00" * 10 + b"\xff\xff" + ip.packed
        else:
            raw = ip.packed
        return NetworkAddress(services, raw, port)

    def to_host_port(self) -> tuple[str, int]:
        import ipaddress

        if self.address[:12] == b"\x00" * 10 + b"\xff\xff":
            host = str(ipaddress.IPv4Address(self.address[12:]))
        else:
            host = str(ipaddress.IPv6Address(self.address))
        return host, self.port

    def serialize(self) -> bytes:
        return (
            self.services.to_bytes(8, "little")
            + self.address
            + self.port.to_bytes(2, "big")
        )

    @classmethod
    def deserialize(cls, r: Reader) -> "NetworkAddress":
        services = r.u64()
        address = r.read(16)
        port = r.u16be()
        return cls(services, address, port)


class InvType:
    """Inventory vector types (getdata/inv/notfound)."""

    ERROR = 0
    TX = 1
    BLOCK = 2
    MERKLE_BLOCK = 3
    COMPACT_BLOCK = 4
    WITNESS_FLAG = 1 << 30
    WITNESS_TX = TX | WITNESS_FLAG
    WITNESS_BLOCK = BLOCK | WITNESS_FLAG


@dataclass(frozen=True)
class InvVector:
    type: int
    hash: bytes  # 32 bytes, internal order

    def serialize(self) -> bytes:
        return self.type.to_bytes(4, "little") + self.hash

    @classmethod
    def deserialize(cls, r: Reader) -> "InvVector":
        t = r.u32()
        h = r.read(32)
        return cls(t, h)


# --- transactions ----------------------------------------------------------


@dataclass(frozen=True)
class OutPoint:
    txid: bytes  # 32 bytes internal order
    index: int

    def serialize(self) -> bytes:
        return self.txid + self.index.to_bytes(4, "little")

    @classmethod
    def deserialize(cls, r: Reader) -> "OutPoint":
        return cls(r.read(32), r.u32())


@dataclass(frozen=True)
class TxIn:
    prevout: OutPoint
    script: bytes
    sequence: int

    def serialize(self) -> bytes:
        return (
            self.prevout.serialize()
            + write_varstr(self.script)
            + self.sequence.to_bytes(4, "little")
        )

    @classmethod
    def deserialize(cls, r: Reader) -> "TxIn":
        prevout = OutPoint.deserialize(r)
        script = r.varstr()
        sequence = r.u32()
        return cls(prevout, script, sequence)


@dataclass(frozen=True)
class TxOut:
    value: int
    script: bytes

    def serialize(self) -> bytes:
        return self.value.to_bytes(8, "little") + write_varstr(self.script)

    @classmethod
    def deserialize(cls, r: Reader) -> "TxOut":
        return cls(r.u64(), r.varstr())


@dataclass(frozen=True)
class Tx:
    """A transaction; segwit marker/flag form supported on segwit networks."""

    version: int
    inputs: tuple[TxIn, ...]
    outputs: tuple[TxOut, ...]
    locktime: int
    # per-input witness stacks; empty tuple means non-segwit serialization
    witnesses: tuple[tuple[bytes, ...], ...] = ()
    # original wire bytes when this Tx came off the network (deserialize
    # sets it) — the zero-reparse input for the native extract fast path
    # (tpunode/txextract.py).  Not part of value identity.
    raw: Optional[bytes] = field(default=None, compare=False, repr=False)

    @cached_property
    def has_witness(self) -> bool:
        # cached: wants_amount consults this per input, and an any() scan
        # per call would be O(n_inputs^2) on large transactions
        return any(self.witnesses)

    def serialize(self, include_witness: bool = True) -> bytes:
        parts = [self.version.to_bytes(4, "little", signed=False)]
        wit = include_witness and self.has_witness
        if wit:
            parts.append(b"\x00\x01")
        parts.append(write_varint(len(self.inputs)))
        parts.extend(i.serialize() for i in self.inputs)
        parts.append(write_varint(len(self.outputs)))
        parts.extend(o.serialize() for o in self.outputs)
        if wit:
            for stack in self.witnesses:
                parts.append(write_varint(len(stack)))
                parts.extend(write_varstr(item) for item in stack)
        parts.append(self.locktime.to_bytes(4, "little"))
        return b"".join(parts)

    @cached_property
    def txid(self) -> bytes:
        """Hash of the non-witness serialization (internal order)."""
        return double_sha256(self.serialize(include_witness=False))

    @cached_property
    def wtxid(self) -> bytes:
        return double_sha256(self.serialize(include_witness=True))

    @classmethod
    def deserialize(cls, r: Reader) -> "Tx":
        start = r.pos
        version = r.u32()
        marker = r.peek(2)
        segwit = marker[:1] == b"\x00" and len(marker) == 2 and marker[1] == 1
        if segwit:
            r.read(2)
        n_in = r.varint()
        inputs = tuple(TxIn.deserialize(r) for _ in range(n_in))
        n_out = r.varint()
        outputs = tuple(TxOut.deserialize(r) for _ in range(n_out))
        witnesses: tuple[tuple[bytes, ...], ...] = ()
        if segwit:
            witnesses = tuple(
                tuple(r.varstr() for _ in range(r.varint())) for _ in range(n_in)
            )
        locktime = r.u32()
        return cls(
            version, inputs, outputs, locktime, witnesses,
            raw=r.slice_from(start),
        )


# --- block header / block --------------------------------------------------


@dataclass(frozen=True)
class BlockHeader:
    """80-byte block header (consensus-critical serialization)."""

    version: int
    prev: bytes  # 32 bytes internal order
    merkle: bytes  # 32 bytes internal order
    timestamp: int
    bits: int
    nonce: int

    def serialize(self) -> bytes:
        return (
            self.version.to_bytes(4, "little", signed=False)
            + self.prev
            + self.merkle
            + self.timestamp.to_bytes(4, "little")
            + self.bits.to_bytes(4, "little")
            + self.nonce.to_bytes(4, "little")
        )

    @cached_property
    def hash(self) -> bytes:
        """Header hash, internal byte order."""
        return double_sha256(self.serialize())

    @property
    def hash_hex(self) -> str:
        return hash_to_hex(self.hash)

    @classmethod
    def deserialize(cls, r: Reader) -> "BlockHeader":
        return cls(
            version=r.u32(),
            prev=r.read(32),
            merkle=r.read(32),
            timestamp=r.u32(),
            bits=r.u32(),
            nonce=r.u32(),
        )


@dataclass(frozen=True)
class Block:
    header: BlockHeader
    txs: tuple[Tx, ...]
    # original tx-region wire bytes (deserialize sets it): feeds the native
    # extract fast path without re-serializing.  Not part of value identity.
    raw_txs: Optional[bytes] = field(default=None, compare=False, repr=False)

    @property
    def tx_count(self) -> int:
        return len(self.txs)

    def serialize(self) -> bytes:
        return (
            self.header.serialize()
            + write_varint(len(self.txs))
            + b"".join(t.serialize() for t in self.txs)
        )

    @classmethod
    def deserialize(cls, r: Reader) -> "Block":
        header = BlockHeader.deserialize(r)
        n = r.varint()
        start = r.pos
        txs = tuple(Tx.deserialize(r) for _ in range(n))
        return cls(header, txs, raw_txs=r.slice_from(start))


class LazyBlock:
    """A block whose tx region stays raw wire bytes until ``.txs`` is
    touched.  ``MsgBlock`` decodes to this, so receiving a full block
    costs no Python tx parsing on the event loop: the verify-ingest fast
    path hands ``raw_txs`` + ``tx_count`` straight to the native extractor
    (tpunode/txextract.py), and only an embedder that actually reads
    ``.txs`` pays the parse (which then validates the region fully and
    yields exactly what an eager Block carries).

    The reference parses every message eagerly in its conduit
    (Peer.hs:247-279) because its node never looks inside block bodies at
    all; this framework's north-star hook does, and at spec rates (32 MB
    blocks, ~150k sigs) eager Python parsing was the round-3 IBD
    bottleneck (PERF.md gap analysis).
    """

    def __init__(self, header: BlockHeader, tx_count: int, raw_txs: bytes):
        self.header = header
        self.tx_count = tx_count
        self.raw_txs = raw_txs

    @cached_property
    def txs(self) -> tuple[Tx, ...]:
        r = Reader(self.raw_txs)
        txs = tuple(Tx.deserialize(r) for _ in range(self.tx_count))
        if r.remaining():
            raise ValueError("trailing bytes in block tx region")
        return txs

    def serialize(self) -> bytes:
        return (
            self.header.serialize()
            + write_varint(self.tx_count)
            + self.raw_txs
        )

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, (Block, LazyBlock))
            and self.header == other.header
            and self.txs == tuple(other.txs)
        )

    def __hash__(self) -> int:
        # Must match the eager Block's dataclass hash (tuple of its
        # compare fields — raw_txs is compare=False) so mixed sets/dicts
        # of Block and LazyBlock behave; hashing pays the one-time parse,
        # like any other content access.
        return hash((self.header, self.txs))

    def __repr__(self) -> str:
        return f"LazyBlock(header={self.header!r}, tx_count={self.tx_count})"


def build_merkle_root(txids: list[bytes]) -> bytes:
    """Merkle root over txids (internal order), duplicating odd tails."""
    if not txids:
        return b"\x00" * 32
    level = list(txids)
    while len(level) > 1:
        if len(level) % 2 == 1:
            level.append(level[-1])
        level = [
            double_sha256(level[i] + level[i + 1]) for i in range(0, len(level), 2)
        ]
    return level[0]


# --- messages --------------------------------------------------------------


@dataclass(frozen=True)
class MsgVersion:
    command = "version"
    version: int
    services: int
    timestamp: int
    addr_recv: NetworkAddress
    addr_from: NetworkAddress
    nonce: int
    user_agent: bytes
    start_height: int
    relay: bool = True

    def serialize_payload(self) -> bytes:
        out = (
            self.version.to_bytes(4, "little")
            + self.services.to_bytes(8, "little")
            + self.timestamp.to_bytes(8, "little")
            + self.addr_recv.serialize()
            + self.addr_from.serialize()
            + self.nonce.to_bytes(8, "little")
            + write_varstr(self.user_agent)
            + self.start_height.to_bytes(4, "little")
        )
        if self.version >= 70001:
            out += b"\x01" if self.relay else b"\x00"
        return out

    @classmethod
    def deserialize_payload(cls, r: Reader) -> "MsgVersion":
        version = r.u32()
        services = r.u64()
        timestamp = r.u64()
        addr_recv = NetworkAddress.deserialize(r)
        addr_from = NetworkAddress.deserialize(r)
        nonce = r.u64()
        user_agent = r.varstr()
        start_height = r.u32()
        relay = True
        if version >= 70001 and r.remaining() > 0:
            relay = r.u8() != 0
        return cls(
            version,
            services,
            timestamp,
            addr_recv,
            addr_from,
            nonce,
            user_agent,
            start_height,
            relay,
        )


@dataclass(frozen=True)
class MsgVerAck:
    command = "verack"

    def serialize_payload(self) -> bytes:
        return b""

    @classmethod
    def deserialize_payload(cls, r: Reader) -> "MsgVerAck":
        return cls()


@dataclass(frozen=True)
class MsgPing:
    command = "ping"
    nonce: int

    def serialize_payload(self) -> bytes:
        return self.nonce.to_bytes(8, "little")

    @classmethod
    def deserialize_payload(cls, r: Reader) -> "MsgPing":
        return cls(r.u64())


@dataclass(frozen=True)
class MsgPong:
    command = "pong"
    nonce: int

    def serialize_payload(self) -> bytes:
        return self.nonce.to_bytes(8, "little")

    @classmethod
    def deserialize_payload(cls, r: Reader) -> "MsgPong":
        return cls(r.u64())


@dataclass(frozen=True)
class MsgAddr:
    command = "addr"
    # (last-seen timestamp, address) pairs
    addrs: tuple[tuple[int, NetworkAddress], ...]

    def serialize_payload(self) -> bytes:
        out = [write_varint(len(self.addrs))]
        for ts, na in self.addrs:
            out.append(ts.to_bytes(4, "little") + na.serialize())
        return b"".join(out)

    @classmethod
    def deserialize_payload(cls, r: Reader) -> "MsgAddr":
        n = r.varint()
        addrs = tuple((r.u32(), NetworkAddress.deserialize(r)) for _ in range(n))
        return cls(addrs)


def _ser_invs(invs: tuple[InvVector, ...]) -> bytes:
    return write_varint(len(invs)) + b"".join(i.serialize() for i in invs)


def _deser_invs(r: Reader) -> tuple[InvVector, ...]:
    n = r.varint()
    return tuple(InvVector.deserialize(r) for _ in range(n))


@dataclass(frozen=True)
class MsgInv:
    command = "inv"
    invs: tuple[InvVector, ...]

    def serialize_payload(self) -> bytes:
        return _ser_invs(self.invs)

    @classmethod
    def deserialize_payload(cls, r: Reader) -> "MsgInv":
        return cls(_deser_invs(r))


@dataclass(frozen=True)
class MsgGetData:
    command = "getdata"
    invs: tuple[InvVector, ...]

    def serialize_payload(self) -> bytes:
        return _ser_invs(self.invs)

    @classmethod
    def deserialize_payload(cls, r: Reader) -> "MsgGetData":
        return cls(_deser_invs(r))


@dataclass(frozen=True)
class MsgNotFound:
    command = "notfound"
    invs: tuple[InvVector, ...]

    def serialize_payload(self) -> bytes:
        return _ser_invs(self.invs)

    @classmethod
    def deserialize_payload(cls, r: Reader) -> "MsgNotFound":
        return cls(_deser_invs(r))


@dataclass(frozen=True)
class MsgGetBlocks:
    command = "getblocks"
    version: int
    locator: tuple[bytes, ...]
    stop: bytes

    def serialize_payload(self) -> bytes:
        return (
            self.version.to_bytes(4, "little")
            + write_varint(len(self.locator))
            + b"".join(self.locator)
            + self.stop
        )

    @classmethod
    def deserialize_payload(cls, r: Reader) -> "MsgGetBlocks":
        version = r.u32()
        n = r.varint()
        locator = tuple(r.read(32) for _ in range(n))
        stop = r.read(32)
        return cls(version, locator, stop)


@dataclass(frozen=True)
class MsgGetHeaders:
    command = "getheaders"
    version: int
    locator: tuple[bytes, ...]
    stop: bytes

    def serialize_payload(self) -> bytes:
        return (
            self.version.to_bytes(4, "little")
            + write_varint(len(self.locator))
            + b"".join(self.locator)
            + self.stop
        )

    @classmethod
    def deserialize_payload(cls, r: Reader) -> "MsgGetHeaders":
        version = r.u32()
        n = r.varint()
        locator = tuple(r.read(32) for _ in range(n))
        stop = r.read(32)
        return cls(version, locator, stop)


@dataclass(frozen=True)
class MsgHeaders:
    command = "headers"
    # (header, tx-count) pairs; tx-count is a varint on the wire, normally 0
    headers: tuple[tuple[BlockHeader, int], ...]

    def serialize_payload(self) -> bytes:
        out = [write_varint(len(self.headers))]
        for h, n in self.headers:
            out.append(h.serialize() + write_varint(n))
        return b"".join(out)

    @classmethod
    def deserialize_payload(cls, r: Reader) -> "MsgHeaders":
        n = r.varint()
        headers = tuple(
            (BlockHeader.deserialize(r), r.varint()) for _ in range(n)
        )
        return cls(headers)


@dataclass(frozen=True)
class MsgBlock:
    command = "block"
    block: "Block | LazyBlock"

    def serialize_payload(self) -> bytes:
        return self.block.serialize()

    @classmethod
    def deserialize_payload(cls, r: Reader) -> "MsgBlock":
        # Lazy: the tx region is the rest of the payload by definition, so
        # no parsing happens here (see LazyBlock).
        header = BlockHeader.deserialize(r)
        n = r.varint()
        return cls(LazyBlock(header, n, r.read(r.remaining())))


class LazyTx:
    """A transaction whose parse is deferred: ``raw`` holds the exact wire
    bytes; touching any other attribute parses once and delegates to the
    eager :class:`Tx`.  ``MsgTx`` decodes to this, so a mempool firehose
    costs no Python tx parsing on the event loop — the native verify
    ingest consumes ``raw`` directly (tpunode/txextract.py), and only code
    that actually inspects the tx pays the parse (which validates the
    payload fully, surfacing what eager decode would have)."""

    __slots__ = ("raw", "_tx")

    def __init__(self, raw: bytes):
        self.raw = raw
        self._tx: Optional[Tx] = None

    def _parsed(self) -> Tx:
        tx = self._tx
        if tx is None:
            r = Reader(self.raw)
            tx = Tx.deserialize(r)
            if r.remaining():
                raise ValueError("trailing bytes after tx payload")
            self._tx = tx
        return tx

    def serialize(self, include_witness: bool = True) -> bytes:
        if include_witness:
            return self.raw
        return self._parsed().serialize(include_witness=False)

    def __getattr__(self, name):
        # reached only for names not on LazyTx itself (raw/_tx/serialize)
        return getattr(self._parsed(), name)

    def __eq__(self, other) -> bool:
        if isinstance(other, LazyTx):
            return self.raw == other.raw
        if isinstance(other, Tx):
            return self._parsed() == other
        return NotImplemented

    def __hash__(self) -> int:
        # Must match the eager Tx's dataclass hash (raw is compare=False)
        # so mixed sets/dicts of Tx and LazyTx behave; hashing pays the
        # one-time parse, like any other content access.
        return hash(self._parsed())

    def __repr__(self) -> str:
        return f"LazyTx({len(self.raw)} bytes)"


@dataclass(frozen=True)
class MsgTx:
    command = "tx"
    tx: "Tx | LazyTx"

    def serialize_payload(self) -> bytes:
        return self.tx.serialize()

    @classmethod
    def deserialize_payload(cls, r: Reader) -> "MsgTx":
        # Lazy: the payload IS the tx by definition (see LazyTx).
        return cls(LazyTx(r.read(r.remaining())))


@dataclass(frozen=True)
class MsgGetAddr:
    command = "getaddr"

    def serialize_payload(self) -> bytes:
        return b""

    @classmethod
    def deserialize_payload(cls, r: Reader) -> "MsgGetAddr":
        return cls()


@dataclass(frozen=True)
class MsgMempool:
    command = "mempool"

    def serialize_payload(self) -> bytes:
        return b""

    @classmethod
    def deserialize_payload(cls, r: Reader) -> "MsgMempool":
        return cls()


@dataclass(frozen=True)
class MsgSendHeaders:
    command = "sendheaders"

    def serialize_payload(self) -> bytes:
        return b""

    @classmethod
    def deserialize_payload(cls, r: Reader) -> "MsgSendHeaders":
        return cls()


@dataclass(frozen=True)
class MsgFeeFilter:
    command = "feefilter"
    feerate: int

    def serialize_payload(self) -> bytes:
        return self.feerate.to_bytes(8, "little")

    @classmethod
    def deserialize_payload(cls, r: Reader) -> "MsgFeeFilter":
        return cls(r.u64())


@dataclass(frozen=True)
class MsgReject:
    command = "reject"
    message: bytes
    code: int
    reason: bytes
    data: bytes = b""

    def serialize_payload(self) -> bytes:
        return (
            write_varstr(self.message)
            + self.code.to_bytes(1, "little")
            + write_varstr(self.reason)
            + self.data
        )

    @classmethod
    def deserialize_payload(cls, r: Reader) -> "MsgReject":
        message = r.varstr()
        code = r.u8()
        reason = r.varstr()
        data = r.read(r.remaining())
        return cls(message, code, reason, data)


@dataclass(frozen=True)
class MsgOther:
    """Any command this codec has no structured decoder for."""

    cmd: str
    payload: bytes

    @property
    def command(self) -> str:  # type: ignore[override]
        return self.cmd

    def serialize_payload(self) -> bytes:
        return self.payload


_MESSAGE_TYPES = {
    m.command: m
    for m in (
        MsgVersion,
        MsgVerAck,
        MsgPing,
        MsgPong,
        MsgAddr,
        MsgInv,
        MsgGetData,
        MsgNotFound,
        MsgGetBlocks,
        MsgGetHeaders,
        MsgHeaders,
        MsgBlock,
        MsgTx,
        MsgGetAddr,
        MsgMempool,
        MsgSendHeaders,
        MsgFeeFilter,
        MsgReject,
    )
}

Message = (
    MsgVersion
    | MsgVerAck
    | MsgPing
    | MsgPong
    | MsgAddr
    | MsgInv
    | MsgGetData
    | MsgNotFound
    | MsgGetBlocks
    | MsgGetHeaders
    | MsgHeaders
    | MsgBlock
    | MsgTx
    | MsgGetAddr
    | MsgMempool
    | MsgSendHeaders
    | MsgFeeFilter
    | MsgReject
    | MsgOther
)


def encode_message(net: Network, msg) -> bytes:
    """Serialize a message with its 24-byte envelope."""
    payload = msg.serialize_payload()
    header = MessageHeader(
        magic=net.magic,
        command=msg.command,
        length=len(payload),
        checksum=double_sha256(payload)[:4],
    )
    return header.serialize() + payload


def decode_message_header(net: Network, data: bytes) -> MessageHeader:
    hdr = MessageHeader.deserialize(data)
    if hdr.magic != net.magic:
        raise DecodeError(
            f"bad magic: got {hdr.magic:#x}, want {net.magic:#x}"
        )
    return hdr


def decode_message(net: Network, header: MessageHeader, payload: bytes):
    """Decode a payload given its (already validated) envelope."""
    if len(payload) != header.length:
        raise DecodeError("payload length mismatch")
    if double_sha256(payload)[:4] != header.checksum:
        raise DecodeError(f"bad checksum for command {header.command}")
    typ = _MESSAGE_TYPES.get(header.command)
    if typ is None:
        return MsgOther(header.command, payload)
    r = Reader(payload)
    try:
        msg = typ.deserialize_payload(r)
    except ValueError as e:
        raise DecodeError(f"cannot decode {header.command}: {e}") from e
    return msg
