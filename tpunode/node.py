"""Node composition: wire the chain and peer managers together.

Mirror of /root/reference/src/Haskoin/Node.hs: ``Node`` starts the Chain actor,
then the PeerMgr actor, then links two glue loops that route events between
them — the ONLY place the two managers are wired to each other (reference
Node.hs:130-174).  Everything is scoped: leaving the async context kills every
actor, peer session and timer (the ``withNode`` bracket, Node.hs:177-193).

Also provides the production TCP transport (reference ``withConnection``
Node.hs:108-128); tests inject an in-memory transport instead through
``NodeConfig.connect`` — the seam that makes the whole stack testable without
a network.
"""

from __future__ import annotations

import asyncio
import contextlib
import functools
import logging
import os
import time as _time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Optional

from . import asyncsan, threadsan
from .actors import (
    LinkedTasks,
    Publisher,
    Supervisor,
    spawn_supervised,
    task_registry,
)
from .blackbox import FlightRecorder, FlightRecorderConfig
from .chain import Chain, ChainBestBlock, ChainConfig, ChainEvent
from .debugsrv import DebugServer
from .events import StatsReporter, events
from .timeseries import Timeline
from .slo import DEFAULT_SLOS, SloDef, SloEvaluator
from .mempool import Mempool, MempoolConfig
from .metrics import metrics, percentiles
from .trace import span
from .tracectx import (
    activate as _activate_trace,
    clear_active as _clear_active_trace,
    current as _trace_current,
    discard_active as _discard_active_trace,
    finish_active as _finish_active_trace,
    tracer,
)
from .watchdog import Watchdog, WatchdogConfig
from .txverify import (
    ExtractStats,
    combine_verdicts,
    extract_sig_items,
    intra_block_prevouts,
    wants_amount,
)
from .verify.engine import VerifyConfig, VerifyEngine
from .verify.sched import affinity_key
from .params import NODE_NETWORK, Network
from .peer import (
    CannotDecodePayload,
    Connection,
    PeerAddressInvalid,
    PeerConnected,
    PeerDisconnected,
    PeerEvent,
    PeerMessage,
    WithConnection,
)
from .peermgr import PeerMgr, PeerMgrConfig, SockAddr
from .store import KVStore, Namespaced
from .receipts import ReceiptLog
from .serve import ServeServer, TenantConfig
from .ibd import BlockFetcher, IbdConfig
from .utxo import UNDO_DEPTH_DEFAULT, UTXO_NAMESPACE, UtxoStore
from .wire import (
    InvType,
    MsgAddr,
    MsgBlock,
    MsgHeaders,
    MsgInv,
    MsgNotFound,
    MsgOther,
    MsgPing,
    MsgPong,
    MsgTx,
    MsgVerAck,
    MsgVersion,
    NetworkAddress,
    Tx,
)

__all__ = [
    "NodeConfig", "Node", "TxVerdict", "VerifyShed", "tcp_connect",
    "IbdConfig",
]


log = logging.getLogger("tpunode.node")


_native_extract_state: Optional[bool] = None


def _native_extract_available() -> bool:
    """Does the native extractor load on this box?  Cached; the first call
    may run `make` (one attempt per process, like the other native libs)."""
    global _native_extract_state
    if _native_extract_state is None:
        try:
            from .txextract import have_native_extract

            _native_extract_state = have_native_extract()
        except Exception:
            _native_extract_state = False
        if not _native_extract_state:
            log.info("[Node] native tx extractor unavailable; python path")
    return _native_extract_state

def _prevout_info(res) -> "tuple[Optional[int], Optional[bytes]]":
    """Normalize a ``prevout_lookup`` result: plain satoshi amount (the
    pre-taproot form), an ``(amount, scriptPubKey)`` tuple, or None."""
    if res is None:
        return None, None
    if isinstance(res, tuple):
        return res[0], res[1]
    return res, None


@dataclass(frozen=True)
class VerifyShed:
    """Published when verify-ingest backpressure drops a message's txs
    (MAX_VERIFY_PENDING reached): embedders observe DoS-shed decisions
    instead of losing them to a silent counter (VERDICT r3 item 8).
    ``dropped_txs`` counts drops caused by ``peer`` alone (aggregated
    per peer within the rate-limit window), so per-peer banning is
    sound."""

    peer: object
    dropped_txs: int
    pending: int  # in-flight ingest submissions at the time


@dataclass(frozen=True)
class TxVerdict:
    """Published to the user bus for every tx that went through the verify
    engine — the north-star ingest hook's output (BASELINE.json north_star;
    the reference has no script validation, SURVEY.md §3.3)."""

    peer: object  # the Peer the tx arrived from
    txid: bytes
    valid: bool  # every extracted signature verified
    verdicts: tuple[bool, ...]  # per extracted signature
    stats: ExtractStats  # how many inputs were extractable at all
    error: Optional[str] = None  # engine failure: verdict is indeterminate


@dataclass
class NodeConfig:
    """The entire configuration surface (reference ``NodeConfig``
    Node.hs:74-96)."""

    net: Network
    store: KVStore
    pub: Publisher
    max_peers: int = 20
    peers: list[str] = field(default_factory=list)
    discover: bool = False
    address: NetworkAddress = field(
        default_factory=lambda: NetworkAddress.from_host_port(
            "0.0.0.0", 0, services=NODE_NETWORK
        )
    )
    timeout: float = 120.0
    max_peer_life: float = 48 * 3600.0
    # transport hook; defaults to real TCP (reference Node.hs:95,108-128)
    connect: Callable[[SockAddr], WithConnection] = None  # type: ignore[assignment]
    # north-star hook: when set, inbound tx/block signatures stream through
    # the batch verify engine and TxVerdict events reach the user bus
    verify: Optional[VerifyConfig] = None
    # mempool subsystem (tpunode/mempool.py): inv-driven tx relay with
    # fetch retry, admission dedup + verdict cache (each unique tx is
    # verified exactly once), orphan pool, confirmation eviction.  None
    # (the default) preserves the bare ingest path: pushes go straight
    # to the verify engine, inv announcements are dropped (and counted
    # under ``node.unhandled``).
    mempool: Optional[MempoolConfig] = None
    # telemetry: seconds between StatsReporter snapshots (windowed rates +
    # ``node.stats`` events on the structured event log); 0 disables the loop
    stats_interval: float = 30.0
    # stall watchdog cadence (event-loop lag, actor-mailbox head age,
    # verify dispatch in-flight time -> ``watchdog.stall`` events);
    # 0 disables the loop.  Thresholds live in tpunode/watchdog.py.
    watchdog_interval: float = 1.0
    # debug HTTP server (tpunode/debugsrv.py: /metrics /health /stats
    # /events /traces on 127.0.0.1).  None = off (the default); 0 binds an
    # ephemeral port, readable from node.debug_server.port.
    debug_port: Optional[int] = None
    # metrics timeline sampler (tpunode/timeseries.py): seconds between
    # registry snapshots into the ring-buffer history (downsampling tiers,
    # /timeseries + /fleet endpoints, Node.stats()["fleet_history"]);
    # 0 disables the sampler.  TPUNODE_NO_TSDB=1 also disables it.
    timeline_interval: float = 1.0
    # flight recorder (tpunode/blackbox.py): trigger events (watchdog
    # stalls, breaker opens, host losses, store corruption, ...) freeze a
    # rate-limited post-mortem bundle — always into the in-memory ring
    # (/flightrecords); also onto disk when blackbox_dir (or
    # $TPUNODE_BLACKBOX_DIR) is set.  False turns the recorder off.
    blackbox: bool = True
    blackbox_dir: Optional[str] = None
    # SLO engine (tpunode/slo.py, ISSUE 17): declarative objectives —
    # per-class verdict-latency targets, a dispatch-stall budget, a
    # breaker-open budget — evaluated once a second against the live
    # registry; fast/slow-window burn breaches emit ``slo.burn`` events
    # (a flight-recorder trigger) and surface at /slo, stats()["slo"]
    # and health().  None disables the evaluator entirely;
    # TPUNODE_NO_SLO=1 disables it at runtime (one-attribute-read tick).
    slos: Optional[tuple[SloDef, ...]] = DEFAULT_SLOS
    # prevout oracle for BIP143 (P2WPKH / BCH FORKID) and BIP341 (taproot)
    # sighashes: (prevout txid, vout) -> satoshi amount, or
    # (amount, scriptPubKey), or None if unknown.  The tuple form enables
    # taproot keypath extraction: a P2TR spend is only detectable from the
    # prevout script, and its BIP341 digest signs over every input's
    # amount AND script (VERDICT r4 item 3).  Block ingest resolves
    # intra-block spends automatically; this hook lets the embedder (which
    # may hold a UTXO set) resolve the rest.  Capability boundary of
    # SURVEY.md C9 / §2.2.
    prevout_lookup: Optional[
        Callable[[bytes, int], "Optional[int | tuple[int, bytes]]"]
    ] = None
    # Parallel host extraction (ISSUE 10 / ROADMAP item 5): how many
    # worker threads shard native ``ParsedTxRegion`` construction +
    # extraction over tx ranges.  0 = auto (``min(4, cpu_count)``);
    # 1 = serial (the pre-pipeline behavior, the A/B baseline — also
    # disables the extract→verify overlap ring).  The native extractor
    # releases the GIL, so threads scale on real cores.
    extract_workers: int = 0
    # persistent UTXO store (tpunode/utxo.py, ISSUE 9 / ROADMAP item 5):
    # when True the node maintains a durable UTXO set over a namespaced
    # view of ``store`` — block connect applies spends/creates + a
    # block-height watermark in ONE atomic write_batch (idempotent
    # crash-replay), the set serves the prevout oracle between the
    # mempool and ``prevout_lookup``, and blocks at or below the
    # watermark skip re-verification entirely on restart.
    utxo: bool = False
    # per-block UNDO retention (ISSUE 11): reorgs at/below the watermark
    # up to this deep disconnect cleanly (utxo.disconnect) instead of
    # going loudly stale; 0 disables undo records entirely.
    utxo_undo_depth: int = UNDO_DEPTH_DEFAULT
    # block-fetch-driven IBD (ISSUE 11 / ROADMAP item 5): when set, the
    # node schedules its own getdata block batches across the peer fleet
    # from the UTXO watermark to the header tip (tpunode/ibd.py) — a bare
    # Node syncs the whole chain with no embedder pushes, and a restart
    # resumes from the watermark re-fetching nothing below it.  Requires
    # ``utxo=True`` (the watermark IS the sync cursor).
    ibd: Optional[IbdConfig] = None
    # multi-tenant verification-as-a-service (tpunode/serve.py, ISSUE 20):
    # when set, the node exposes the batch verify engine over a
    # length-prefixed JSON TCP API to the registered ``serve_tenants`` —
    # token auth, per-tenant token-bucket quota + inflight caps,
    # priority-class mapping onto packer lanes, a shared verdict cache,
    # and SLO-burn shedding of the lowest class first.  None = off (the
    # default); 0 binds an ephemeral port, readable from
    # ``node.serve_server.port``.  Requires ``verify`` and >=1 tenant.
    serve_port: Optional[int] = None
    serve_tenants: tuple = ()
    # tamper-evident verdict receipts (tpunode/receipts.py, ISSUE 20):
    # when set, every served verify batch appends one hash-chained,
    # CRC-framed record (batch digest, verdict digest, kernel-modes
    # tuple, dispatching rung) to a segmented log in this directory —
    # auditable offline with ``python -m tpunode.receipts --audit``.
    receipts_dir: Optional[str] = None

    def __post_init__(self):
        if self.connect is None:
            self.connect = tcp_connect
        if self.ibd is not None and not self.utxo:
            raise ValueError(
                "NodeConfig.ibd requires utxo=True: the persistent UTXO "
                "watermark is the fetch planner's sync cursor"
            )
        if self.serve_port is not None:
            if self.verify is None:
                raise ValueError(
                    "NodeConfig.serve_port requires verify: the serve "
                    "layer is a tenant front-end over the batch verify "
                    "engine"
                )
            if not self.serve_tenants:
                raise ValueError(
                    "NodeConfig.serve_port requires at least one "
                    "TenantConfig in serve_tenants (unauthenticated "
                    "serving is not a mode)"
                )


class Node:
    """A running node: ``peer_mgr`` + ``chain`` (reference ``Node``
    Node.hs:98-101).  Use as an async context manager::

        async with Node(cfg) as node:
            best = node.chain.get_best()
    """

    def __init__(self, cfg: NodeConfig):
        self.cfg = cfg
        # Internal glue buses are unbounded: their only subscribers are the
        # linked router loops (always draining; death tears the node down),
        # and dropping a control message (headers, version) would corrupt
        # protocol state.  The bounded drop-oldest default protects the
        # USER bus (cfg.pub), where subscribers are outside our control.
        self._chain_pub: Publisher[ChainEvent] = Publisher(
            name="chain-internal", maxsize=None
        )
        self._peer_pub: Publisher[PeerEvent] = Publisher(
            name="peer-internal", maxsize=None
        )
        self.chain = Chain(
            ChainConfig(
                store=cfg.store,
                net=cfg.net,
                pub=self._chain_pub,
                timeout=cfg.timeout,
            ),
            on_failure=self._component_failed,
        )
        self.peer_mgr = PeerMgr(
            PeerMgrConfig(
                max_peers=cfg.max_peers,
                peers=cfg.peers,
                discover=cfg.discover,
                address=cfg.address,
                net=cfg.net,
                pub=self._peer_pub,
                timeout=cfg.timeout,
                max_peer_life=cfg.max_peer_life,
                connect=cfg.connect,
            ),
            on_failure=self._component_failed,
        )
        self._tasks = LinkedTasks(name="node", on_failure=self._component_failed)
        self._stack = contextlib.AsyncExitStack()
        self._owner: Optional[asyncio.Task] = None
        self._failure: Optional[BaseException] = None
        self.verify_engine: Optional[VerifyEngine] = (
            VerifyEngine(cfg.verify) if cfg.verify is not None else None
        )
        # persistent UTXO set over the main store (NodeConfig.utxo); the
        # watermark survives restarts, so it must be read before ingest
        self.utxo: Optional[UtxoStore] = (
            UtxoStore(
                Namespaced(cfg.store, UTXO_NAMESPACE),
                undo_depth=cfg.utxo_undo_depth,
            )
            if cfg.utxo
            else None
        )
        # block-fetch-driven IBD planner (ISSUE 11): schedules getdata
        # batches across the fleet from the watermark to the header tip
        self.ibd: Optional[BlockFetcher] = (
            BlockFetcher(
                cfg.ibd,
                net=cfg.net,
                chain=self.chain,
                peer_mgr=self.peer_mgr,
                utxo=self.utxo,
                pressure=self._ibd_pressure,
                pressure_key=self._ibd_pressure_key,
                on_failure=self._component_failed,
            )
            if cfg.ibd is not None
            else None
        )
        # block connects serialize here: applies are atomic per block, but
        # the watermark check-then-apply across concurrent ingest tasks
        # must not interleave
        self._utxo_lock = asyncio.Lock()
        # out-of-order completions parked until their predecessor lands
        # (concurrent block verification finishes in any order); bounded —
        # beyond the cap a block is dropped and re-delivery heals
        self._utxo_pending: dict[int, object] = {}
        self.mempool: Optional[Mempool] = (
            Mempool(
                cfg.mempool,
                net=cfg.net,
                submit=self._mempool_submit,
                prevout_lookup=cfg.prevout_lookup,
                pressure=self._ingest_pressure,
                pressure_key=self._ingest_pressure_key,
                on_failure=self._component_failed,
            )
            if cfg.mempool is not None
            else None
        )
        self._verify_tasks = Supervisor(
            name="verify-ingest", on_death=self._verify_task_died
        )
        self._verify_pending = 0
        # mempool-tx batch accumulator (see _submit_verify_tx)
        self._tx_accum: list = []
        self._tx_drain: Optional[asyncio.Task] = None
        # Parallel extraction (ISSUE 10): worker pool for native
        # ParsedTxRegion construction/extraction (built in _start when
        # >1 worker resolves; shut down in __aexit__), plus the bounded
        # ring that lets extraction of drain batch K+1 overlap
        # verification of K (sched.ring_occupancy gauge).
        w = cfg.extract_workers
        self._extract_workers = w if w > 0 else min(4, os.cpu_count() or 1)
        self._extract_pool: Optional[ThreadPoolExecutor] = None
        # Host-affine pool slices (ISSUE 19, fleet mode only): one lazy
        # sub-pool per verify host so a tx is parsed/prepped by the
        # worker slice feeding its verifying host.  Keyed by host name;
        # built in _pool_for, shut down with the shared pool.
        self._extract_pools: Optional[dict] = None
        self._host_pool_workers = 1
        self._extract_ring = asyncio.Semaphore(self.EXTRACT_RING)
        self._ring_busy = 0
        # shed-event aggregation (a flood must not also flood the bus),
        # keyed by peer: drops must be attributed to the peer that caused
        # them — an embedder doing per-peer DoS banning acts on this
        # (VERDICT r4 weak #4)
        self._shed_counts: dict = {}
        self._shed_last_pub = 0.0
        self._shed_flush: Optional[asyncio.Task] = None
        self._started_at: Optional[float] = None
        self._stats_reporter: Optional[StatsReporter] = None
        self._watchdog: Optional[Watchdog] = None
        self._attributor = None  # asyncsan.LoopAttributor when enabled
        self.debug_server: Optional[DebugServer] = None
        self.timeline: Optional[Timeline] = None
        self.blackbox: Optional[FlightRecorder] = None
        self.slo: Optional[SloEvaluator] = None
        # serve layer (ISSUE 20): built in _start (needs the SLO
        # evaluator's burn signal), closed in __aexit__
        self.serve_server: Optional[ServeServer] = None
        self.receipts: Optional[ReceiptLog] = None

    @staticmethod
    def _verify_task_died(task, exc) -> None:
        """An ingest task crashed outside its own error handling: record it
        (verdicts for its txs were already published or are indeterminate)."""
        if exc is not None and not isinstance(exc, asyncio.CancelledError):
            metrics.inc("node.verify_task_crashes")
            log.warning("[Node] verify ingest task crashed: %r", exc)

    def _component_failed(self, exc: BaseException) -> None:
        """An internal actor crashed: abort the embedding scope, the analog of
        the reference ``link``-ing its loops so a crash takes down the whole
        node bracket (Node.hs:191-192; crash-only design, SURVEY.md §5)."""
        if self._failure is None:
            log.error("[Node] component failed, tearing down node: %r", exc)
            self._failure = exc
            if self._owner is not None:
                self._owner.cancel()

    async def __aenter__(self) -> "Node":
        # Subscriptions must exist before the actors start so the chain's
        # initial best-block event reaches the peer manager (the startup
        # ordering constraint, reference Node.hs:183-192 + PeerMgr.hs:245-247).
        self._owner = asyncio.current_task()
        if asyncsan.enabled():
            # opt-in runtime sanitizers (TPUNODE_ASYNCSAN, ANALYSIS.md):
            # asyncio debug mode + tight slow-callback reporting, and the
            # blocked-loop attributor whose captured frames upgrade the
            # watchdog's event_loop stall events
            asyncsan.install()
            self._attributor = asyncsan.LoopAttributor()
            self._attributor.start()
        if threadsan.enabled():
            # the thread-side twin (TPUNODE_THREADSAN, ANALYSIS.md): arms
            # the lock registry's cycle/reentry/hold instrumentation and
            # marks this loop thread so blocking acquires that stall it
            # are reported
            threadsan.install()
        try:
            return await self._start()
        except BaseException:
            # a failed start never reaches __aexit__: don't leak the
            # attributor's sampler thread + heartbeat chain
            if self._attributor is not None:
                self._attributor.stop()
                self._attributor = None
            raise

    async def _start(self) -> "Node":
        await self._stack.__aenter__()
        chain_sub = await self._stack.enter_async_context(
            self._chain_pub.subscription()
        )
        peer_sub = await self._stack.enter_async_context(
            self._peer_pub.subscription()
        )
        if self.verify_engine is not None:
            await self._stack.enter_async_context(self.verify_engine)
            # Always a pool (1 worker = serial): close-ownership transfer
            # (_run_extract_owned) needs the CONCURRENT future, which
            # only executor.submit exposes — to_thread hides it behind a
            # wrapper whose cancelled() lies about a still-running job.
            self._extract_pool = ThreadPoolExecutor(
                max_workers=self._extract_workers,
                thread_name_prefix="extract",
            )
            if self._fleet_affine() and self._extract_workers > 1:
                # per-host slices (ISSUE 19): each verify host gets its
                # own extract sub-pool, sized so the slices sum to about
                # the configured worker budget
                hosts = len(self.verify_engine._hosts)
                self._extract_pools = {}
                self._host_pool_workers = max(
                    1, self._extract_workers // max(1, hosts)
                )
        if self.verify_engine is not None or self.utxo is not None:
            # utxo-only nodes still spawn supervised block-connect tasks
            await self._stack.enter_async_context(self._verify_tasks)
        if self.mempool is not None:
            await self._stack.enter_async_context(self.mempool)
        await self._stack.enter_async_context(self.chain)
        await self._stack.enter_async_context(self.peer_mgr)
        if self.ibd is not None:
            await self._stack.enter_async_context(self.ibd)
        self._tasks.link(self._chain_events(chain_sub), name="glue-chain")
        self._tasks.link(self._peer_events(peer_sub), name="glue-peer")
        self._started_at = _time.monotonic()
        if self.cfg.stats_interval > 0:
            self._stats_reporter = StatsReporter(
                interval=self.cfg.stats_interval, extra=self._stats_extra
            )
            self._tasks.link(self._stats_reporter.run(), name="stats-reporter")
        if self.cfg.watchdog_interval > 0:
            boxes = [self.chain.mailbox, self.peer_mgr.mailbox]
            if self.mempool is not None:
                boxes.append(self.mempool.mailbox)
            self._watchdog = Watchdog(
                WatchdogConfig(interval=self.cfg.watchdog_interval),
                mailboxes=boxes,
                engine=self.verify_engine,
                attributor=self._attributor,
            )
            self._tasks.link(self._watchdog.run(), name="watchdog")
        if self.cfg.timeline_interval > 0:
            self.timeline = Timeline(interval=self.cfg.timeline_interval)
            self._tasks.link(self.timeline.run(), name="timeline-sampler")
        if self.cfg.slos is not None:
            # SLO evaluator (ISSUE 17): objectives over the live registry;
            # the ledger hook folds the engine's cost attribution into
            # every snapshot (stats()["slo"], /slo, flight bundles)
            self.slo = SloEvaluator(
                self.cfg.slos,
                ledger=(
                    self.verify_engine.ledger
                    if self.verify_engine is not None
                    else None
                ),
            )
            if not self.slo.disabled:
                self._tasks.link(self.slo.run(), name="slo-evaluator")
        if self.cfg.serve_port is not None:
            # serve layer (ISSUE 20): tenant-facing verify service.  The
            # receipt log opens first so the server's very first batch is
            # already bound into the hash chain; it closes in __aexit__
            # AFTER the exit stack has drained the server's connections.
            if self.cfg.receipts_dir is not None:
                self.receipts = ReceiptLog(self.cfg.receipts_dir)
            self.serve_server = ServeServer(
                self.verify_engine,
                self.cfg.serve_tenants,
                port=self.cfg.serve_port,
                slo_burning=(
                    (lambda: self.slo.burning("fast"))
                    if self.slo is not None
                    else None
                ),
                receipts=self.receipts,
            )
            await self._stack.enter_async_context(self.serve_server)
        if self.cfg.blackbox:
            # bundle state sources: each is one lock-cheap snapshot call,
            # safe from whatever thread the trigger event fires on
            sources: dict = {"health": self.health}
            if self.verify_engine is not None:
                sources["engine"] = self.verify_engine.stats
            if self._watchdog is not None:
                sources["watchdog"] = self._watchdog.snapshot
            if self.utxo is not None:
                sources["utxo"] = self.utxo.stats
            if self.slo is not None:
                sources["slo"] = self.slo.snapshot
            if self.serve_server is not None:
                sources["serve"] = self.serve_server.stats
            sources["threadsan"] = threadsan.registry.snapshot
            self.blackbox = FlightRecorder(
                FlightRecorderConfig(dir=self.cfg.blackbox_dir),
                timeline=self.timeline,
                sources=sources,
            )
            self.blackbox.attach()
        if self.cfg.debug_port is not None:
            self.debug_server = DebugServer(
                port=self.cfg.debug_port,
                health=self.health,
                stats=self.stats,
                mempool=(
                    self.mempool.stats if self.mempool is not None else None
                ),
                timeline=self.timeline,
                blackbox=self.blackbox,
                fleet=self._fleet_now,
                slo=(
                    self.slo.snapshot if self.slo is not None else None
                ),
                serve=(
                    self.serve_server.stats
                    if self.serve_server is not None
                    else None
                ),
                receipts=self.receipts,
            )
            await self._stack.enter_async_context(self.debug_server)
        log.info(
            "[Node] started on %s (%d static peers, discover=%s, verify=%s)",
            self.cfg.net.name,
            len(self.cfg.peers),
            self.cfg.discover,
            "on" if self.verify_engine is not None else "off",
        )
        return self

    async def __aexit__(self, exc_type, exc, tb) -> None:
        log.info("[Node] stopping")
        # unclean shutdown is a flight-recorder trigger: freeze the bundle
        # BEFORE teardown (the state sources still describe the live node),
        # bypassing the rate limit — this is the last chance to record.
        if self.blackbox is not None:
            unclean = self._failure is not None or (
                exc is not None and not isinstance(exc, asyncio.CancelledError)
            )
            if unclean:
                cause = self._failure if self._failure is not None else exc
                self.blackbox.record(
                    "node.unclean_shutdown",
                    trigger={
                        "type": "node.unclean_shutdown",
                        "failure": repr(cause),
                    },
                    force=True,
                )
            self.blackbox.detach()
        self._owner = None
        try:
            await self._tasks.__aexit__(exc_type, exc, tb)
        finally:
            try:
                await self._stack.__aexit__(exc_type, exc, tb)
            finally:
                if self._extract_pool is not None:
                    # non-blocking: queued jobs are cancelled; a job
                    # already RUNNING finishes on its daemonless thread
                    # (it owns its region handle — _extract_and_close —
                    # so nothing the loop side still references is freed
                    # under it)
                    self._extract_pool.shutdown(
                        wait=False, cancel_futures=True
                    )
                    self._extract_pool = None
                if self._extract_pools is not None:
                    # host-affine slices (ISSUE 19): same non-blocking
                    # discipline as the shared pool above
                    for pool in self._extract_pools.values():
                        pool.shutdown(wait=False, cancel_futures=True)
                    self._extract_pools = None
                if self._attributor is not None:
                    self._attributor.stop()
                    self._attributor = None
                if self.receipts is not None:
                    # after the stack: the serve server has drained its
                    # connections, so no append can race the close
                    self.receipts.close()
                    self.receipts = None
                # asyncsan task-leak sweep: everything this node owned is
                # now cancelled+awaited, so any still-pending registered
                # task with no live open owner is an orphan — report it
                # (asyncsan.task_leak events) instead of letting GC eat it
                task_registry.report_leaks()
        # Surface an internal crash instead of the bare CancelledError the
        # embedding scope was aborted with.
        if self._failure is not None and isinstance(exc, asyncio.CancelledError):
            raise self._failure

    # -- telemetry snapshot API ---------------------------------------------

    def _stats_extra(self) -> dict:
        """Node-level context merged into every ``node.stats`` event."""
        fleet = self.peer_mgr.fleet()
        extra = {
            "height": self._best_height(),
            "peers": len(fleet),
            "peers_online": sum(1 for o in fleet if o.online),
        }
        if self.verify_engine is not None:
            extra["verify_backlog"] = self.verify_engine.queue_depth()
            extra["verify_pending"] = self._verify_pending
        if self.mempool is not None:
            extra["mempool_size"] = self.mempool.size()
            extra["mempool_orphans"] = self.mempool.orphan_count()
        if self.utxo is not None:
            extra["utxo_height"] = self.utxo.height
        if self.ibd is not None:
            extra["ibd_target"] = self.ibd.stats()["target"]
        return extra

    def _fleet_now(self) -> dict:
        """Live fleet state for the /fleet endpoint (history rides along
        from the timeline)."""
        if self.verify_engine is None:
            return {"enabled": False}
        fleet = self.verify_engine.stats().get("fleet")
        return fleet if fleet is not None else {"enabled": False}

    def _uptime(self) -> float:
        if self._started_at is None:
            return 0.0  # not started yet: never report wall-clock garbage
        return round(_time.monotonic() - self._started_at, 3)

    def _best_height(self) -> Optional[int]:
        """Best height, or None before the chain DB is initialized — a
        probe scraped during startup must get an unhealthy snapshot, not
        a RuntimeError from the uninitialized header store."""
        try:
            return self.chain.get_best().height
        except Exception:
            return None

    def health(self) -> dict:
        """Cheap liveness summary (the load-balancer probe shape)."""
        fleet = self.peer_mgr.fleet()
        return {
            "ok": self._failure is None and self._started_at is not None,
            "failure": repr(self._failure) if self._failure else None,
            "uptime_seconds": self._uptime(),
            "height": self._best_height(),
            "synced": self.chain.is_synced(),
            "peers": len(fleet),
            "peers_online": sum(1 for o in fleet if o.online),
            "verify": (
                self.verify_engine.device_state
                if self.verify_engine is not None
                else "off"
            ),
            # device-path breaker (ISSUE 7): ready/degraded/open/probing
            # once the device is warm, else the warmup state
            "verify_breaker": (
                self.verify_engine.breaker_state
                if self.verify_engine is not None
                else None
            ),
            # persistent UTXO watermark (ISSUE 9): the height below which
            # a restart resumes without re-verifying anything
            "utxo_height": (
                self.utxo.height if self.utxo is not None else None
            ),
            # SLO burn (ISSUE 17): degraded while any FAST-window burn
            # episode is active (the page-now condition); slow-window
            # burns surface in stats()["slo"] without degrading health
            "slo_burning": (
                self.slo.burning("fast") if self.slo is not None else []
            ),
            "degraded": bool(
                self.slo is not None and self.slo.burning("fast")
            ),
        }

    def stats(self) -> dict:
        """Full telemetry snapshot in one call: chain height, per-peer
        fleet state with RTT quantiles, verify-engine backlog and error
        counts, event totals.  Everything here is lock-cheap reads — safe
        to call from an embedder's status endpoint."""
        try:
            best = self.chain.get_best()
        except Exception:  # pre-start: DB not initialized yet
            best = None
        now = _time.monotonic()
        peers = []
        for o in self.peer_mgr.fleet():
            v = o.version
            peers.append(
                {
                    "peer": o.peer.label,
                    "address": f"{o.address[0]}:{o.address[1]}",
                    "online": o.online,
                    "connected_seconds": round(now - o.connected, 3),
                    "rtt": percentiles(o.pings, (0.5, 0.9, 0.99)),
                    "rtt_samples": len(o.pings),
                    "user_agent": (
                        v.user_agent.decode("latin-1") if v else None
                    ),
                    "start_height": v.start_height if v else None,
                }
            )
        verify: dict = {
            "enabled": self.verify_engine is not None,
            "txs": metrics.get("node.verify_txs"),
            "inputs": metrics.get("node.verify_inputs"),
            "errors": metrics.get("node.verify_errors"),
            "dropped": metrics.get("node.verify_dropped"),
        }
        if self.verify_engine is not None:
            verify.update(self.verify_engine.stats())
            verify.update(
                pending_ingest=self._verify_pending,
                accumulated_txs=len(self._tx_accum),
                extract_workers=self._extract_workers,
                ring_busy=self._ring_busy,
            )
        return {
            "uptime_seconds": self._uptime(),
            "chain": {
                "height": best.height if best is not None else None,
                "hash": best.hash_hex if best is not None else None,
                "synced": self.chain.is_synced(),
                "headers": metrics.get("chain.headers"),
                "reorgs": metrics.get("chain.reorgs"),
            },
            "peers": peers,
            "peermgr": self.peer_mgr.backoff_stats(),
            "verify": verify,
            "mempool": (
                self.mempool.stats()
                if self.mempool is not None
                else {"enabled": False}
            ),
            "utxo": (
                self.utxo.stats()
                if self.utxo is not None
                else {"enabled": False}
            ),
            "ibd": (
                self.ibd.stats()
                if self.ibd is not None
                else {"enabled": False}
            ),
            "events": events.counts(),
            # per-host fleet series history (ISSUE 16): how the queue
            # depths / breaker states / sub-mesh widths got here
            "fleet_history": (
                self.timeline.fleet_history()
                if self.timeline is not None
                else {}
            ),
            "timeline": (
                self.timeline.stats()
                if self.timeline is not None
                else {"enabled": False}
            ),
            "blackbox": (
                self.blackbox.stats()
                if self.blackbox is not None
                else {"enabled": False}
            ),
            "slo": (
                self.slo.snapshot()
                if self.slo is not None
                else {"enabled": False}
            ),
            # serve layer (ISSUE 20): per-tenant frames/items/spend,
            # cache occupancy, receipt-chain tip
            "serve": (
                self.serve_server.stats()
                if self.serve_server is not None
                else {"enabled": False}
            ),
        }

    def _verify_failure(self, where: str, error) -> None:
        """Count + record one verify-path failure (extract/engine/decode)."""
        metrics.inc("node.verify_errors")
        events.emit("verify.failure", where=where, error=str(error)[:300])

    def _publish_verdict(self, v: TxVerdict) -> None:
        """Every TxVerdict flows through here: the mempool's verdict
        cache learns it (dedup: re-relays of this tx now cost zero
        verify work) before the user bus does."""
        if self.mempool is not None:
            self.mempool.verdict(v.txid, v.valid, v.verdicts, v.error)
        self.cfg.pub.publish(v)

    def _mempool_submit(self, peer, tx) -> None:
        """Mempool admission -> verify ingest.  Without a verify engine
        the mempool still dedups/relays, but nothing verifies (entries
        stay pending until evicted)."""
        if self.verify_engine is not None:
            self._submit_verify_tx(peer, tx)

    def _mempool_shed(self, txs) -> None:
        """Shed txs never get a TxVerdict: a mempool-admitted one must
        not stay PENDING in the dedup cache (it would block its own
        re-verification on a later re-push) — the error verdict makes
        the mempool forget it, same as an engine failure."""
        if self.mempool is None:
            return
        for tx in txs:
            try:
                txid = tx.txid
            except Exception:
                continue  # unparseable: was never admitted
            self.mempool.verdict(txid, False, (), error="shed")

    def _fleet_affine(self) -> bool:
        """Host-affine ingest on?  True when the engine runs a verify
        fleet (ISSUE 19): intake then partitions by target host."""
        eng = self.verify_engine
        return eng is not None and getattr(eng, "_fleet", None) is not None

    def _affine_host(self, txid: bytes) -> Optional[str]:
        """The fleet host this txid's verify work routes to right now
        (None without a fleet, or with every host dark)."""
        if not self._fleet_affine():
            return None
        assert self.verify_engine is not None
        return self.verify_engine.route_host(affinity_key(txid))

    def _ingest_pressure(self) -> bool:
        """Is the verify ingest saturated?  The mempool defers fetch
        scheduling while true, so inv floods degrade into a stale
        want-list instead of feeding the shed path.  Fleet mode
        (ISSUE 19): the global gate trips only when EVERY active host
        is over its feed ceiling — one slow host alone must never
        stall the whole fleet's intake (its own keys defer through
        :meth:`_ingest_pressure_key` instead)."""
        if len(self._tx_accum) >= self.MAX_TX_ACCUM // 2:
            return True
        if self._fleet_affine():
            assert self.verify_engine is not None
            return self.verify_engine.hosts_all_pressured()
        return self._verify_pending >= self.MAX_VERIFY_PENDING

    def _ingest_pressure_key(self, txid: bytes) -> bool:
        """Per-tx intake gate (ISSUE 19): is THIS txid's target host
        over its feed ceiling?  The mempool skips fetching just these
        txids while true; everything else keeps flowing.  Falls back to
        the global gate semantics without a fleet."""
        if len(self._tx_accum) >= self.MAX_TX_ACCUM // 2:
            return True  # the accumulator is a global memory bound
        if self._fleet_affine():
            assert self.verify_engine is not None
            return self.verify_engine.host_pressured(affinity_key(txid))
        return self._verify_pending >= self.MAX_VERIFY_PENDING

    def _ibd_pressure(self) -> bool:
        """Should the IBD planner defer scheduling more block batches?
        Half the shed bound: the planner can keep the pipeline saturated
        but a delivery burst must never reach MAX_VERIFY_PENDING (every
        shed block costs a refetch round-trip later)."""
        return (
            self._verify_pending >= self.MAX_VERIFY_PENDING // 2
            or len(self._utxo_pending) >= self.MAX_UTXO_PENDING // 2
        )

    def _ibd_pressure_key(self, block_hash: bytes) -> bool:
        """Per-batch IBD gate (ISSUE 19): is this block's target verify
        host over its feed ceiling?  False without a fleet — the global
        :meth:`_ibd_pressure` gate already covers that case."""
        if not self._fleet_affine():
            return False
        assert self.verify_engine is not None
        return self.verify_engine.host_pressured(affinity_key(block_hash))

    def _block_priority(self) -> str:
        """Engine priority class for block verify submissions: planner-era
        backfill runs at ``ibd`` (beneath live block/mempool traffic in
        the lane packer, tpunode/verify/sched.py) so a syncing node still
        serves fresh verdicts first; live pushed blocks keep ``block``."""
        if self.ibd is not None and self.ibd.backfilling:
            return "ibd"
        return "block"

    def _prevout_oracle(self):
        """The prevout lookup the verify paths consult, in precedence
        order: the mempool's unconfirmed outputs (a child spending an
        in-mempool parent extracts with full prevout data), then the
        persistent UTXO store's confirmed outputs (ISSUE 9), then the
        embedder's ``cfg.prevout_lookup``.  None when nothing can answer
        — block ingest then skips the whole scan_prevouts + per-input
        lookup pass (hot path)."""
        sources = []
        if self.mempool is not None and self.mempool.size():
            # an empty mempool misses every lookup: skip it entirely
            sources.append(self.mempool.lookup_prevout)
        if self.utxo is not None:
            sources.append(self.utxo.lookup)
        if self.cfg.prevout_lookup is not None:
            sources.append(self.cfg.prevout_lookup)
        if not sources:
            return None
        if len(sources) == 1:
            return sources[0]

        def combined(txid: bytes, vout: int):
            for lookup in sources:
                res = lookup(txid, vout)
                if res is not None:
                    return res
            return None

        return combined

    # -- persistent UTXO block connect (ISSUE 9) ----------------------------

    def _persisted_height(self, block) -> Optional[int]:
        """Height of ``block`` if it is already covered by the UTXO
        watermark (fully verified + applied before a restart), else None.
        Height alone is NOT enough after a reorg: the delivered block
        must BE the watermark branch's block at that height (ancestor
        hash check) — a new-branch block at an old height was never
        verified and must not be skipped (review pin)."""
        if self.utxo is None:
            return None
        bn = self.chain.get_block(block.header.hash)
        if bn is None or bn.height > self.utxo.height:
            return None
        if self.utxo.block_hash is not None:
            wm = self.chain.get_block(self.utxo.block_hash)
            if wm is None:
                return None  # watermark block unknown here: re-verify
            anc = self.chain.get_ancestor(bn.height, wm)
            if anc is None or anc.hash != bn.hash:
                return None  # different branch: not covered
        return bn.height

    def _connect_block_utxo(self, block) -> None:
        """Schedule the persistent UTXO connect for an ingested block
        (supervised; ordering enforced by ``_utxo_lock``)."""
        if self.utxo is None:
            return
        self._verify_tasks.add_child(
            self._apply_block_utxo(block), name="utxo-connect"
        )

    async def _apply_block_utxo(self, block) -> None:
        """Apply one block's spends/creates + watermark atomically.  The
        tx parse and the store write both run off-loop; failures are loud
        (``utxo.error``) but never kill ingest — the UTXO set degrades to
        a stale oracle, not a crashed node."""
        bn = self.chain.get_block(block.header.hash)
        if bn is None:
            # headers-first sync means this is rare: a block whose header
            # the chain has not accepted cannot be assigned a height
            metrics.inc("utxo.no_header")
            return
        assert self.utxo is not None
        async with self._utxo_lock:
            if bn.height <= self.utxo.height:
                metrics.inc("utxo.skipped")
                return
            # CONTIGUOUS connects only: applying height N+2 over a
            # watermark of N would silently drop N+1's whole delta (its
            # later re-delivery lands below the watermark and is skipped
            # forever).  Concurrent verification completes in any order,
            # so an early arrival PARKS (bounded) until its predecessor
            # lands; past the cap it is dropped — re-delivery heals.
            expected = max(self.utxo.height + 1, 1)
            if bn.height < expected:
                # below the first applicable height (a delivered genesis
                # block on a fresh store): nothing to park for — the
                # drain loop could never reach it
                metrics.inc("utxo.skipped")
                return
            if bn.height > expected:
                if len(self._utxo_pending) < self.MAX_UTXO_PENDING:
                    self._utxo_pending[bn.height] = block
                    metrics.inc("utxo.deferred")
                else:
                    metrics.inc("utxo.out_of_order")
                    events.emit(
                        "utxo.out_of_order", height=bn.height,
                        watermark=self.utxo.height,
                    )
                return
            await self._utxo_apply_one(bn.height, block)
            # drain parked successors now contiguous with the watermark
            while True:
                nxt = self._utxo_pending.pop(self.utxo.height + 1, None)
                if nxt is None:
                    break
                await self._utxo_apply_one(self.utxo.height + 1, nxt)
        if self.ibd is not None:
            # the watermark may have moved: the planner retires finished
            # batches and schedules further ahead
            self.ibd.nudge()

    # Bound on parked out-of-order block connects (blocks are held alive
    # while parked; MAX_VERIFY_PENDING already bounds how many can be in
    # flight at once, this is belt-and-braces above it).
    MAX_UTXO_PENDING = 128

    async def _utxo_apply_one(self, height: int, block) -> None:
        """One atomic connect (caller holds ``_utxo_lock`` and guarantees
        ``height`` is the first applicable one, ``max(watermark+1, 1)``);
        parse + write both off-loop.

        HASH-chain contiguity, not just height: after a reorg beneath the
        watermark, the new branch's block at watermark+1 does not extend
        the watermark block — applying it would stack the new branch's
        deltas on the orphaned branch's UTXO state.  The per-block UNDO
        log (ISSUE 11) disconnects tip blocks back to the fork point when
        the records are retained (``utxo.undo_depth``, default 100);
        deeper reorgs keep the old behavior and go loudly STALE
        (``utxo.reorg_stale``), refusing further connects until the
        embedder rebuilds the set (delete the ``u/`` namespace and
        re-sync).

        Note the watermark gates on the block's verdicts having been
        *published*, not on every signature being valid: this node is a
        verification service reporting verdicts, not a consensus
        validator rejecting blocks (the reference has no script
        validation at all, SURVEY.md §3.3) — gating on all-valid would
        wedge the watermark forever on one hostile signature."""
        assert self.utxo is not None
        if (
            self.utxo.block_hash is not None
            and block.header.prev != self.utxo.block_hash
        ):
            if await self._utxo_unwind_reorg(block):
                # the watermark rolled back to this block's branch; the
                # parked blocks were fetched against the OLD branch state
                # and may now be stale — drop them, re-delivery heals
                # (the fetch planner replans against the new best chain)
                self._utxo_pending.clear()
                bn = self.chain.get_block(block.header.hash)
                expected = max(self.utxo.height + 1, 1)
                if bn is None or bn.height < expected:
                    metrics.inc("utxo.skipped")
                    return
                if bn.height > expected:
                    # above the rolled-back watermark: park — its
                    # predecessors on the new branch are being fetched
                    if len(self._utxo_pending) < self.MAX_UTXO_PENDING:
                        self._utxo_pending[bn.height] = block
                        metrics.inc("utxo.deferred")
                    return
                height = bn.height
                if (
                    self.utxo.block_hash is not None
                    and block.header.prev != self.utxo.block_hash
                ):
                    return  # unwound, but this block is on a third branch
            else:
                metrics.inc("utxo.reorg_stale")
                events.emit(
                    "utxo.reorg_stale", height=height,
                    watermark=self.utxo.height,
                )
                log.error(
                    "[Node] UTXO set is STALE: block %d does not extend "
                    "the watermark block (reorg beneath height %d deeper "
                    "than the undo retention); rebuild the UTXO namespace "
                    "to resume",
                    height, self.utxo.height,
                )
                return
        try:
            await self._utxo_connect_off_loop(height, block)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            metrics.inc("utxo.errors")
            events.emit(
                "utxo.error", height=height, error=str(e)[:300]
            )
            log.warning(
                "[Node] utxo connect failed at height %d: %r", height, e
            )

    async def _utxo_connect_off_loop(self, height: int, block) -> None:
        """The physical connect, off-loop.  Native fast path (ISSUE 11):
        the C++ extractor computes the whole spend/create delta + undo
        rows in ONE pass over the wire bytes (``ParsedTxRegion.utxo_ops``
        -> ``UtxoStore.apply_ops_blob``), so no Python per-tx parse ever
        runs during block connect.  The Python ``apply_block`` path stays
        the reference and the fallback (``TPUNODE_UTXO_NATIVE=0``, eager
        blocks without raw bytes, no native toolchain); both produce
        bit-identical stores (tests/test_utxo.py)."""
        assert self.utxo is not None
        raw = getattr(block, "raw_txs", None)
        if (
            raw is not None
            and _native_extract_available()
            and os.environ.get("TPUNODE_UTXO_NATIVE", "1") != "0"
        ):
            utxo = self.utxo
            block_hash = block.header.hash
            n_txs = block.tx_count

            def connect_native():
                from .txextract import ParsedTxRegion

                with ParsedTxRegion(raw, n_txs) as region:
                    blob, created, spent = region.utxo_ops()
                    return utxo.apply_ops_blob(
                        height, block_hash, blob, created, spent
                    )

            await self._run_extract(connect_native)
        else:
            txs = await asyncio.to_thread(lambda: list(block.txs))
            await asyncio.to_thread(
                self.utxo.apply_block, height, block.header.hash, txs
            )

    async def _utxo_unwind_reorg(self, block) -> bool:
        """Disconnect tip blocks (per-block UNDO records, ISSUE 11) until
        the watermark block lies on ``block``'s branch — the fork point.
        True when the unwind reached it; False (store untouched beyond
        any blocks already unwound) when an undo record is missing
        (reorg deeper than retention) or the branch is unknown — the
        caller then falls back to loudly-stale."""
        assert self.utxo is not None
        bn = self.chain.get_block(block.header.hash)
        if bn is None:
            return False
        unwound = 0
        start = self.utxo.height
        while self.utxo.height >= 0:
            wm_hash = self.utxo.block_hash
            if wm_hash is not None and self.utxo.height <= bn.height:
                anc = self.chain.get_ancestor(self.utxo.height, bn)
                if anc is not None and anc.hash == wm_hash:
                    break  # the watermark is an ancestor: fork reached
            ok = await asyncio.to_thread(self.utxo.disconnect)
            if not ok:
                return False
            unwound += 1
        if unwound:
            metrics.inc("utxo.reorg_unwound")
            events.emit(
                "utxo.reorg_unwound", from_height=start,
                to_height=self.utxo.height, blocks=unwound,
            )
            log.info(
                "[Node] reorg: disconnected %d block(s), watermark %d -> %d",
                unwound, start, self.utxo.height,
            )
        return True

    def _count_unhandled(self, msg) -> None:
        """A peer message the event router has no handler for: count it
        (bounded label set — every decoded command is one of wire.py's
        fixed message classes; unknown commands decode to MsgOther and
        collapse into one label) so the next missing handler shows up in
        /metrics instead of vanishing (ISSUE 5 satellite)."""
        if isinstance(msg, MsgNotFound):
            # not a missing handler: RPC replies are consumed by the
            # requester's own subscription (peer.get_data), and healthy
            # mempool fetch-retry traffic produces them steadily —
            # counting them would bury a real gap in noise
            return
        cmd = "other" if isinstance(msg, MsgOther) else getattr(
            msg, "command", "other"
        )
        metrics.inc("node.unhandled", labels={"cmd": cmd})

    async def _chain_events(self, sub) -> None:
        """Chain events -> PeerMgr best height + user bus
        (reference ``chainEvents`` Node.hs:130-142)."""
        while True:
            event = await sub.receive()
            if isinstance(event, ChainBestBlock):
                self.peer_mgr.set_best(event.node.height)
                if self.mempool is not None:
                    # chain activity triggers mempool housekeeping
                    # (orphan expiry, deferred fetch scheduling)
                    self.mempool.chain_event(event)
                if self.ibd is not None:
                    # new headers extend the fetch planner's target
                    self.ibd.nudge()
            self.cfg.pub.publish(event)

    async def _peer_events(self, sub) -> None:
        """Peer events -> demux raw messages to the managers + user bus
        (reference ``peerEvents`` Node.hs:144-174)."""
        mgr = self.peer_mgr
        chain = self.chain
        while True:
            event = await sub.receive()
            if isinstance(event, PeerConnected):
                chain.peer_connected(event.peer)
            elif isinstance(event, PeerDisconnected):
                chain.peer_disconnected(event.peer)
                if self.mempool is not None:
                    # release in-flight fetch slots + announcer entries
                    self.mempool.peer_gone(event.peer)
                if self.ibd is not None:
                    # in-flight block batches reassign to another peer
                    self.ibd.peer_gone(event.peer)
            elif isinstance(event, PeerMessage):
                p, msg = event.peer, event.message
                if isinstance(msg, MsgVersion):
                    mgr.version(p, msg)
                elif isinstance(msg, MsgVerAck):
                    mgr.verack(p)
                elif isinstance(msg, MsgPing):
                    mgr.ping(p, msg.nonce)
                elif isinstance(msg, MsgPong):
                    mgr.pong(p, msg.nonce)
                elif isinstance(msg, MsgAddr):
                    mgr.addrs(p, [na for _, na in msg.addrs])
                elif isinstance(msg, MsgHeaders):
                    chain.headers(p, [h for h, _ in msg.headers])
                elif self.mempool is not None and isinstance(msg, MsgInv):
                    # tx announcements feed the mempool's want-list;
                    # block invs are ignored (sync is headers-driven)
                    self.mempool.invs(
                        p,
                        [
                            iv.hash
                            for iv in msg.invs
                            if iv.type in (InvType.TX, InvType.WITNESS_TX)
                        ],
                    )
                elif self.mempool is not None and isinstance(msg, MsgTx):
                    # admission (dedup/orphan gate) before the engine
                    self.mempool.tx_pushed(p, msg.tx)
                elif self.verify_engine is not None and isinstance(msg, MsgTx):
                    self._submit_verify_tx(p, msg.tx)
                elif self.verify_engine is not None and isinstance(msg, MsgBlock):
                    # the block stays lazy (wire.LazyBlock): the native path
                    # never parses its txs in Python.  Confirmation
                    # eviction rides the ingest path (txids are computed
                    # there, natively when possible).
                    self._submit_verify(p, block=msg.block)
                elif isinstance(msg, MsgBlock) and (
                    self.mempool is not None or self.utxo is not None
                ):
                    # no verify engine: still evict confirmed txs and
                    # connect the persistent UTXO set
                    if self.mempool is not None:
                        self.mempool.block_connected(msg.block)
                    if self._persisted_height(msg.block) is None:
                        self._connect_block_utxo(msg.block)
                    else:
                        metrics.inc("node.block_replay_skipped")
                else:
                    self._count_unhandled(msg)
                # every message refreshes liveness (reference Node.hs:173)
                mgr.tickle(p)
            self.cfg.pub.publish(event)

    # Backpressure bound on in-flight ingest submissions (peer-facing DoS
    # guard: a flooding peer gets its excess dropped, mirroring how the
    # connect loop bounds the peer fleet rather than growing it).
    MAX_VERIFY_PENDING = 64
    # Mempool firehose bound: txs queued in the ingest accumulator.
    MAX_TX_ACCUM = 16384

    def _publish_shed(self, peer, n_txs: int) -> None:
        """Aggregate + rate-limit VerifyShed: under a sustained flood the
        shed path fires per message, and publishing each one would flood
        the user bus worse than the flood being shed.  At most ~2
        flushes/sec; each flush publishes ONE event PER SHEDDING PEER with
        that peer's own accumulated count, so per-peer DoS accounting in
        the embedder bans the right peer (VERDICT r4 weak #4).  Counts
        accumulated inside the window are flushed by a delayed task so a
        burst that then stops is still reported."""
        self._shed_counts[peer] = self._shed_counts.get(peer, 0) + n_txs
        now = _time.monotonic()
        if now - self._shed_last_pub >= 0.5:
            self._flush_shed()
        elif self._shed_flush is None or self._shed_flush.done():

            async def flush_later():
                # sleep until the window actually reopens (a direct flush
                # may move _shed_last_pub while we wait) so the ~2/sec cap
                # holds even when direct and delayed flushes interleave
                while True:
                    remain = self._shed_last_pub + 0.5 - _time.monotonic()
                    if remain <= 0:
                        break
                    await asyncio.sleep(remain)
                if self._shed_counts:
                    self._flush_shed()

            self._shed_flush = self._verify_tasks.add_child(
                flush_later(), name="shed-flush"
            )

    def _flush_shed(self) -> None:
        self._shed_last_pub = _time.monotonic()
        pending = len(self._tx_accum) + self._verify_pending
        counts, self._shed_counts = self._shed_counts, {}
        for peer, n in counts.items():
            self.cfg.pub.publish(VerifyShed(peer, n, pending))

    def _resolve_ext_rows(
        self, region, bch: bool
    ) -> "tuple[Optional[list[int]], Optional[list[Optional[bytes]]]]":
        """External-oracle rows for a parsed region: per-input amounts and
        scriptPubKeys from the prevout oracle (mempool outputs first,
        then ``cfg.prevout_lookup``), aligned with the region's flat
        input order (only rows the tx-level wants gate marks are looked
        up).  Shared by block and mempool ingest."""
        lookup = self._prevout_oracle()
        if lookup is None:
            return None, None
        pv_txids, pv_vouts, pv_wants = region.scan_prevouts(bch)
        ext: list[int] = [-1] * len(pv_wants)
        ext_scripts: list[Optional[bytes]] = [None] * len(pv_wants)
        for i in pv_wants.nonzero()[0]:
            amt, script = _prevout_info(
                lookup(pv_txids[i].tobytes(), int(pv_vouts[i]))
            )
            if amt is not None:
                ext[int(i)] = amt
            if script is not None:
                ext_scripts[int(i)] = script
        return ext, ext_scripts

    def _submit_verify_tx(self, peer, tx) -> None:
        """Mempool-tx ingest: append the tx's raw wire bytes to the batch
        accumulator and make sure a drain task is running.  Coalescing many
        single-tx messages into one native extract + one engine batch is
        what lifts the firehose off the per-message task/thread overhead
        that bounded round 3 at ~820 sigs/s (VERDICT r3 item 5).  Falls
        back to the per-message Python path when raw bytes or the native
        extractor are unavailable."""
        raw = tx.raw
        if raw is None or not _native_extract_available():
            self._submit_verify(peer, txs=[tx], raw=raw)
            return
        if len(self._tx_accum) >= self.MAX_TX_ACCUM:
            metrics.inc("node.verify_dropped")
            self._publish_shed(peer, 1)
            self._mempool_shed([tx])
            # the shed decision ends this message's pipeline: close its
            # trace unretained (a flood of shed stubs must not evict the
            # traces that matter from the rings)
            _discard_active_trace()
            return
        self._tx_accum.append((peer, tx, raw, _trace_current()))
        if self._tx_drain is None or self._tx_drain.done():
            self._tx_drain = self._verify_tasks.add_child(
                self._drain_tx_accum(), name="verify-tx-drain"
            )

    # Extract→verify overlap ring (ISSUE 10): how many drain batches may
    # sit between extraction start and verdict publish at once.  2 =
    # extraction of batch K+1 overlaps verification of K; the drain loop
    # blocks when the ring is full, which backpressures into MAX_TX_ACCUM.
    EXTRACT_RING = 2
    # Minimum txs per extraction shard: below this the per-shard native
    # call overhead beats the parallelism.
    MIN_SHARD_TXS = 64

    def _pool_for(self, host: Optional[str]) -> Optional[ThreadPoolExecutor]:
        """The extract pool feeding ``host`` (ISSUE 19): its lazy
        per-host slice in fleet-affine mode, the shared pool otherwise.
        Host names come from the engine's fixed fleet, so the slice dict
        is bounded by construction."""
        if host is None or self._extract_pools is None:
            return self._extract_pool
        pool = self._extract_pools.get(host)
        if pool is None:
            pool = ThreadPoolExecutor(
                max_workers=self._host_pool_workers,
                thread_name_prefix=f"extract-{host}",
            )
            self._extract_pools[host] = pool
        return pool

    async def _run_extract(self, fn, *args, _pool=None, **kw):
        """Run one native-extraction step off-loop: in the given pool
        (a host-affine slice), else the shared worker pool, else via
        ``to_thread``."""
        pool = _pool if _pool is not None else self._extract_pool
        if pool is not None:
            return await asyncio.get_running_loop().run_in_executor(
                pool, functools.partial(fn, *args, **kw)
            )
        return await asyncio.to_thread(fn, *args, **kw)

    def _split_shards(self, batch: list, workers: int) -> list[list]:
        if workers <= 1 or len(batch) < 2 * self.MIN_SHARD_TXS:
            return [batch]
        n = min(workers, len(batch) // self.MIN_SHARD_TXS)
        size = (len(batch) + n - 1) // n
        return [batch[i : i + size] for i in range(0, len(batch), size)]

    def _shard_batch(self, batch: list) -> list[list]:
        """Split a drain batch into per-worker tx ranges (mempool txs
        are independent: ``intra_amounts`` is off, so the shards share
        nothing but the prevout oracle).  Fleet-affine mode (ISSUE 19)
        groups by TARGET HOST first — every tx in a shard routes to the
        same verify host, so one shard is one affinity-keyed engine
        submission prepped by that host's extract slice — then splits
        within each group; central mode keeps contiguous ranges."""
        if not self._fleet_affine():
            return self._split_shards(batch, self._extract_workers)
        groups: dict = {}  # host (or None) -> records in arrival order
        for rec in batch:
            try:
                host = self._affine_host(rec[1].txid)
            except Exception:
                host = None
            groups.setdefault(host, []).append(rec)
        per_group = (
            self._host_pool_workers
            if self._extract_pools is not None
            else self._extract_workers
        )
        out: list[list] = []
        for group in groups.values():
            out.extend(self._split_shards(group, per_group))
        return out

    @staticmethod
    def _begin_tx_spans(batch: list, name: str) -> list:
        """Open one ``name`` span in EACH traced message's own trace
        (ISSUE 10 trace satellite: the drain used to record batch spans
        into the FIRST message's trace only)."""
        recs = []
        for _, _, _, act in batch:
            if act is not None:
                recs.append((act[0], act[0].begin(name, act[1])))
        return recs

    @staticmethod
    def _end_tx_spans(recs: list) -> None:
        for tr, rec in recs:
            tr.end(rec)

    @staticmethod
    def _extract_and_close(region, **kw):
        """Worker-thread tail of a shard extract: the thread that runs
        the native extract also frees the handle.  Closing from the loop
        side would race a cancelled-but-still-running extract (awaiting
        an executor future stops WAITING on cancellation, it does not
        stop the thread) — txx_parse_free under a live txx_extract_h2 is
        a native use-after-free (review finding)."""
        try:
            return region.extract(**kw)
        finally:
            region.close()

    async def _run_extract_owned(self, region, _pool=None, **kw):
        """Submit the extract with close-ownership attached: the worker
        thread closes the region when the job RUNS (`_extract_and_close`);
        a job cancelled while still QUEUED (node teardown, pool
        `cancel_futures`) never runs, so the done-callback closes it.

        The callback MUST watch the CONCURRENT future: it reports
        cancelled only when the cancel beat the job (no thread attached,
        close is safe).  The asyncio wrapper would report cancelled even
        while the job is still running (task cancellation cancels the
        wrapper regardless of ``concurrent.Future.cancel()`` failing) —
        closing on that signal is the very use-after-free this path
        exists to avoid (review finding)."""
        pool = _pool if _pool is not None else self._extract_pool
        assert pool is not None  # built with the engine
        cfut = pool.submit(
            self._extract_and_close, region, **kw
        )
        cfut.add_done_callback(
            lambda f: region.close() if f.cancelled() else None
        )
        return await asyncio.wrap_future(cfut)

    async def _extract_shard(self, shard: list, bch: bool):
        """One C++ extract over a contiguous run of accumulated txs
        (``intra_amounts`` off — mempool txs are independent, exactly
        like the old per-message path).  Returns RawSigItems, or None on
        failure (the caller isolates the offender per tx)."""
        from .txextract import ParsedTxRegion

        concat = b"".join(r for _, _, r, _ in shard)
        # host-affine prep (ISSUE 19): the shard's txs all route to one
        # verify host (grouped in _shard_batch), so parse + extract run
        # on that host's pool slice
        pool = None
        if self._extract_pools is not None:
            try:
                pool = self._pool_for(self._affine_host(shard[0][1].txid))
            except Exception:
                pool = None
        region = None
        submitted = False
        try:
            region = await self._run_extract(
                ParsedTxRegion, concat, len(shard), _pool=pool
            )
            # oracle lookups stay on the loop thread (they read
            # mempool/utxo state owned by it)
            ext, ext_scripts = self._resolve_ext_rows(region, bch)
            submitted = True  # from here the job owns close
            return await self._run_extract_owned(
                region,
                _pool=pool,
                bch=bch,
                intra_amounts=False,
                ext_amounts=ext,
                ext_scripts=ext_scripts,
            )
        except asyncio.CancelledError:
            raise
        except Exception:
            return None
        finally:
            if region is not None and not submitted:
                region.close()

    async def _ring_acquire(self) -> None:
        await self._extract_ring.acquire()
        self._ring_busy += 1
        metrics.set_gauge("sched.ring_occupancy", float(self._ring_busy))

    def _ring_release(self) -> None:
        self._ring_busy -= 1
        metrics.set_gauge("sched.ring_occupancy", float(self._ring_busy))
        self._extract_ring.release()

    async def _drain_tx_accum(self) -> None:
        """Drain the mempool accumulator in batches: C++ extraction
        sharded over the worker pool (``NodeConfig.extract_workers``
        contiguous tx ranges in parallel), each shard one engine
        submission (the lane packer re-bins them into full device lanes),
        verdict publication through a bounded ring so extraction of
        batch K+1 overlaps verification of K.  A malformed tx poisons
        only itself: on shard extract failure each of its txs retries
        individually (:meth:`_verify_txs_native`), so one hostile peer
        cannot fail other peers' verdicts."""
        bch = self.cfg.net.bch
        # The drain task inherited the FIRST accumulated message's trace
        # context at creation and outlives it by many batches: clear it —
        # per-tx spans are recorded into each tx's OWN trace below.
        _clear_active_trace()
        # Bounded drain batches: one giant extract+verify would add seconds
        # of verdict latency under flood; ~2k txs keeps the engine fed in
        # device-batch-sized bites while verdicts keep flowing.
        DRAIN_BATCH = 2048
        while self._tx_accum:
            batch = self._tx_accum[:DRAIN_BATCH]
            del self._tx_accum[:DRAIN_BATCH]
            shards = self._shard_batch(batch)
            # per-tx extract spans in each tx's own trace (they bound the
            # whole sharded extraction: begin before, end when all shards
            # land — exact per shard, conservative across shards)
            recs = self._begin_tx_spans(batch, "node.extract")
            try:
                # span(): the metrics histogram (stage busy fractions in
                # BENCH); the per-tx trace records are the recs above
                with span("node.extract"):
                    extracted = await asyncio.gather(
                        *(self._extract_shard(s, bch) for s in shards)
                    )
            finally:
                self._end_tx_spans(recs)
            pairs = []
            for shard, items in zip(shards, extracted):
                if items is None:
                    # isolate the offender: each tx goes through the
                    # single-tx native path on its own (error verdicts +
                    # peer kill there; finishes each tx's trace too)
                    for peer, tx, raw, act in shard:
                        with _activate_trace(act):
                            await self._verify_txs_native(
                                peer, raw, 1, txs=[tx], tracked=False
                            )
                    continue
                pairs.append((shard, items))
            if not pairs:
                continue
            if self._extract_workers > 1:
                # ring stage: ONE slot per drain batch (a slot per shard
                # would let 2 of N shards stall the loop and shrink the
                # K+1/K overlap to a fraction of a batch — review
                # finding); all shards' verdicts publish in a supervised
                # child while this loop extracts the next batch
                await self._ring_acquire()
                self._verify_tasks.add_child(
                    self._commit_batch(pairs, ring=True),
                    name="verify-drain-commit",
                )
            else:
                # serial A/B baseline: extract → verify → publish
                await self._commit_batch(pairs, ring=False)

    async def _commit_batch(self, pairs: list, ring: bool) -> None:
        """Commit one drain batch's extracted shards: all shards submit
        to the engine concurrently (the packer coalesces them into full
        lanes) and the ring slot frees when the whole batch published."""
        try:
            await asyncio.gather(
                *(self._commit_drained(shard, items)
                  for shard, items in pairs)
            )
        finally:
            if ring:
                self._ring_release()

    async def _commit_drained(self, shard: list, items) -> None:
        """Await one extracted shard's verdicts and publish per-tx
        TxVerdicts (each into its own trace)."""
        act0 = next((a for _, _, _, a in shard if a is not None), None)
        try:
            metrics.inc("node.verify_txs", len(shard))
            metrics.inc("node.verify_inputs", int(items.tx_n_inputs.sum()))
            verdicts: list[bool] = []
            if items.count:
                try:
                    assert self.verify_engine is not None
                    # the verify.queue span lands in the first traced
                    # submitter's tree (the packer's act0 convention).
                    # Affinity (ISSUE 19): the shard was grouped by
                    # target host in _shard_batch, so its first txid's
                    # key routes the whole submission home.
                    aff = None
                    if self._fleet_affine():
                        try:
                            aff = affinity_key(shard[0][1].txid)
                        except Exception:
                            aff = None
                    with _activate_trace(act0):
                        verdicts = await self.verify_engine.verify_raw(
                            items, priority="mempool", affinity=aff
                        )
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    self._verify_failure("engine", e)
                    for ti, (peer, _, _, _) in enumerate(shard):
                        self._publish_verdict(
                            TxVerdict(peer, items.txid(ti), False, (),
                                      items.stats(ti),
                                      error=f"engine: {e}")
                        )
                    return
            per_sig = items.combine(verdicts)
            sig_slices = items.sig_slices()
            for ti, (peer, _, _, act) in enumerate(shard):
                vs = tuple(per_sig[sig_slices[ti]])
                # per-tx commit span in the tx's OWN trace (ISSUE 10)
                with _activate_trace(act):
                    with span("node.commit"):
                        self._publish_verdict(
                            TxVerdict(peer, items.txid(ti), all(vs), vs,
                                      items.stats(ti))
                        )
        finally:
            # traces end AFTER the spans close, so a finished trace is
            # never mutated (retention/export reads it immediately)
            self._finish_batch_traces(shard)

    @staticmethod
    def _finish_batch_traces(batch) -> None:
        """Finish every accumulated message's trace at its verdict (the
        per-message traces are distinct; finish is idempotent anyway)."""
        for _, _, _, act in batch:
            if act is not None:
                tracer.finish(act[0])

    def _submit_verify(
        self,
        peer,
        txs: Optional[list[Tx]] = None,
        raw: Optional[bytes] = None,
        block=None,
    ) -> None:
        """Fan inbound transactions into the batch verify engine without
        blocking the event-routing loop; one TxVerdict per tx lands on the
        user bus when its batch completes (or fails: ``error`` set).

        Tx messages pass ``txs`` (+ ``raw`` wire bytes); block messages
        pass ``block`` (a wire.LazyBlock), whose tx region is handed to
        the native extractor without ever parsing txs in Python.  When the
        native extractor builds on this box, extraction runs in C++
        straight from wire bytes (~13x the Python path; PERF.md) — the
        Python path remains the reference and the fallback."""
        if block is not None and self._persisted_height(block) is not None:
            # restart replay (ISSUE 9): this block is at or below the
            # persistent UTXO watermark — it was fully verified AND its
            # UTXO delta durably applied before a crash/restart, so
            # re-delivery costs nothing: no extract, no engine batch,
            # no re-apply.
            metrics.inc("node.block_replay_skipped")
            _discard_active_trace()
            return
        n_txs = block.tx_count if block is not None else len(txs)
        if self._verify_pending >= self.MAX_VERIFY_PENDING:
            metrics.inc("node.verify_dropped", n_txs)
            self._publish_shed(peer, n_txs)
            if txs is not None:  # block txs are never mempool-admitted
                self._mempool_shed(txs)
            _discard_active_trace()  # shed: pipeline ends here, unretained
            return
        self._verify_pending += 1
        if block is not None:
            raw = block.raw_txs
        if raw is not None and _native_extract_available():
            coro = self._verify_txs_native(peer, raw, n_txs, block=block, txs=txs)
        else:
            if txs is None:
                try:
                    txs = list(block.txs)  # python fallback parses lazily
                except Exception as e:
                    # Malformed lazy tx region: the eager decode used to
                    # surface this as a DecodeError in the peer loop (and
                    # kill the peer); with lazy blocks it surfaces here —
                    # report it and kill the peer, never crash the router.
                    self._verify_pending -= 1
                    self._verify_failure("block-decode", e)
                    self._publish_verdict(
                        TxVerdict(peer, b"", False, (), ExtractStats(),
                                  error=f"block decode: {e}")
                    )
                    peer.kill(CannotDecodePayload(f"block: {e}"))
                    _finish_active_trace()  # verdict published: trace ends
                    return
            if block is not None and self.mempool is not None:
                # python-path block connect: txs parsed above anyway
                self.mempool.confirmed([tx.txid for tx in txs])
            coro = self._verify_txs(peer, txs, block=block)
        self._verify_tasks.add_child(coro, name="verify-txs")

    async def _verify_txs_native(
        self,
        peer,
        raw: bytes,
        n_txs: int,
        block=None,
        txs: Optional[list[Tx]] = None,
        tracked: bool = True,  # False: caller owns _verify_pending
    ) -> None:
        """Native-extract fast path of :meth:`_verify_txs`: parse + sighash +
        DER + pubkey decode run in C++ over the original wire bytes
        (tpunode/txextract.py), and the packed item arrays go to the engine
        with no per-item Python objects — for a block, not even Tx objects
        (prevouts for the amount oracle come from ``scan_prevouts``, C++
        too).  Bit-identical verdicts to the Python path
        (tests/test_txextract.py); one behavioral difference: a
        malformed-region extract error fails the whole message's txs
        (the Python path can fail per tx)."""
        assert self.verify_engine is not None
        from .txextract import ParsedTxRegion

        bch = self.cfg.net.bch

        def _publish_extract_error(e: Exception) -> None:
            self._verify_failure("extract", e)
            txids: list[bytes] = []
            try:
                src = txs if txs is not None else block.txs
                txids = [tx.txid for tx in src]
            except Exception:
                # tx region unparseable (lazy tx/block): one aggregate
                # verdict, and the peer dies as under eager decode
                txids = [b""]
                peer.kill(CannotDecodePayload(str(e)))
            for txid in txids:
                self._publish_verdict(
                    TxVerdict(peer, txid, False, (), ExtractStats(),
                              error=f"extract: {e}")
                )

        region: Optional[ParsedTxRegion] = None
        submitted = False  # once the extract job is in a worker thread,
        # that thread owns region.close (see _extract_and_close)
        try:
            # ONE native parse feeds both the prevout listing and the
            # extraction (ParsedTxRegion; the amount-oracle path used to
            # parse the region twice more).
            with span("node.extract"):
                try:
                    # shared worker pool (ISSUE 10): several blocks'
                    # regions parse/extract in parallel
                    region = await self._run_extract(
                        ParsedTxRegion, raw, n_txs
                    )
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    _publish_extract_error(e)
                    return
                # Out-of-block prevout rows via the embedder's oracle,
                # flattened per input in parse order.  The native side
                # consults its intra-block map FIRST, so resolving every
                # wants-marked input here matches the Python path's
                # block_outs -> prevout_lookup precedence (an in-block hit
                # shadows whatever the oracle would have said).
                ext, ext_scripts = self._resolve_ext_rows(region, bch)
                # BLOCK regions shard across the worker pool as contiguous
                # tx ranges (ISSUE 11), exactly like mempool drains: the
                # intra-block prevout map is built ONCE on the shared
                # handle (read-only for the range jobs), so sharded
                # extraction is bit-identical to serial (pinned by
                # tests/test_txextract.py).
                shard_block = (
                    block is not None
                    and self._extract_workers > 1
                    and region.n_txs >= 2 * self.MIN_SHARD_TXS
                )
                try:
                    if shard_block:
                        submitted = True
                        shards = await self._extract_block_sharded(
                            region, bch, ext, ext_scripts
                        )
                    else:
                        submitted = True
                        shards = [await self._run_extract_owned(
                            region,
                            bch=bch,
                            intra_amounts=n_txs > 1,
                            ext_amounts=ext,
                            ext_scripts=ext_scripts,
                        )]
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    _publish_extract_error(e)
                    return
            if block is not None and self.mempool is not None:
                # block connect: evict confirmed txs from the mempool.
                # The txids come from the native extract — no Python
                # parse — and arrive before the verdicts do.
                self.mempool.confirmed(
                    [it.txid(ti) for it in shards
                     for ti in range(it.n_txs)]
                )
            metrics.inc(
                "node.verify_txs", sum(it.n_txs for it in shards)
            )
            metrics.inc(
                "node.verify_inputs",
                sum(int(it.tx_n_inputs.sum()) for it in shards),
            )
            # every shard is its own engine submission (the lane packer
            # coalesces them into full device lanes); planner-era
            # backfill rides the "ibd" class beneath live traffic
            priority = (
                self._block_priority() if block is not None else "mempool"
            )
            # block affinity (ISSUE 19): a block's shards share one key
            # (the block hash) so the whole block verifies on one host —
            # its shards pack together instead of scattering
            aff = None
            if self._fleet_affine():
                try:
                    aff = affinity_key(
                        block.header.hash if block is not None
                        else txs[0].txid if txs else b""
                    )
                except Exception:
                    aff = None
            clean = all(await asyncio.gather(*(
                self._commit_items(peer, it, priority, aff)
                for it in shards
            )))
            if block is not None and clean:
                # persistent UTXO connect only AFTER the block's verdicts
                # are published: the watermark means "verified AND
                # applied", so a crash mid-verify must leave the block
                # unpersisted for its re-delivery to re-verify (extract/
                # engine failure paths return before reaching here)
                self._connect_block_utxo(block)
        finally:
            if region is not None and not submitted:
                region.close()
            if tracked:
                self._verify_pending -= 1
            # the item's pipeline trace (if any) ends with its verdicts
            _finish_active_trace()

    async def _commit_items(
        self, peer, items, priority: str, affinity: Optional[int] = None
    ) -> bool:
        """Engine round + verdict publication for one RawSigItems batch
        (a whole message, or one tx-range shard of a block).  Returns
        False when the engine failed (error verdicts published)."""
        assert self.verify_engine is not None
        verdicts: list[bool] = []
        if items.count:
            try:
                verdicts = await self.verify_engine.verify_raw(
                    items, priority=priority, affinity=affinity
                )
            except asyncio.CancelledError:
                raise
            except Exception as e:
                self._verify_failure("engine", e)
                for ti in range(items.n_txs):
                    self._publish_verdict(
                        TxVerdict(peer, items.txid(ti), False, (),
                                  items.stats(ti), error=f"engine: {e}")
                    )
                return False
        # candidate verdicts -> per-signature verdicts (consensus walk)
        with span("node.commit"):
            per_sig = items.combine(verdicts)
            for ti, sl in enumerate(items.sig_slices()):
                vs = tuple(per_sig[sl])
                self._publish_verdict(
                    TxVerdict(peer, items.txid(ti), all(vs), vs,
                              items.stats(ti))
                )
        return True

    async def _extract_block_sharded(self, region, bch: bool, ext,
                                     ext_scripts) -> list:
        """Split a parsed BLOCK region into contiguous per-worker
        tx-range sub-extractions (ISSUE 11).  The shared intra-block
        prevout map is built once (off-loop) before the range jobs go to
        the pool; each job's oracle rows are the range's slice of the
        whole-region rows.  Close ownership is collective: the region is
        freed when the LAST submitted job finishes (or every queued job
        is cancelled before running) — never under a live extract."""
        n = region.n_txs
        w = min(self._extract_workers, n // self.MIN_SHARD_TXS)
        if n > 1:
            await self._run_extract(region.build_intra)
        off = region.input_offsets()
        size = (n + w - 1) // w
        jobs = []
        for lo in range(0, n, size):
            hi = min(lo + size, n)
            fl, fh = int(off[lo]), int(off[hi])
            jobs.append(functools.partial(
                region.extract_range, lo, hi,
                bch=bch,
                intra_amounts=n > 1,
                ext_amounts=ext[fl:fh] if ext is not None else None,
                ext_scripts=(
                    ext_scripts[fl:fh] if ext_scripts is not None else None
                ),
            ))
        assert self._extract_pool is not None  # built with the engine
        cfuts = []
        try:
            for job in jobs:
                cfuts.append(self._extract_pool.submit(job))
        finally:
            self._close_when_done(region, cfuts)
        return list(await asyncio.gather(
            *(asyncio.wrap_future(f) for f in cfuts)
        ))

    @staticmethod
    def _close_when_done(region, cfuts: list) -> None:
        """Free a shared region handle once every submitted job is out of
        the pool (finished OR cancelled-before-running).  The callbacks
        watch the CONCURRENT futures — the only signal that cannot fire
        while a worker thread still holds the handle (the same
        use-after-free discipline as `_run_extract_owned`)."""
        if not cfuts:
            region.close()
            return
        state = {"remaining": len(cfuts)}
        lock = threadsan.lock("node.region_refcount")

        def _done(_f):
            with lock:
                state["remaining"] -= 1
                last = state["remaining"] == 0
            if last:
                region.close()

        for f in cfuts:
            f.add_done_callback(_done)

    async def _verify_txs(self, peer, txs: list[Tx], block=None) -> None:
        """Verify every tx of one message.  All txs' signatures are submitted
        to the engine CONCURRENTLY so a whole block coalesces into full
        device batches (awaiting per tx would degrade a 150k-sig block into
        sequential tiny batches).  ``block``: the originating block, UTXO-
        connected only after every verdict published without an error."""
        assert self.verify_engine is not None
        # Intra-block prevouts: a block message carries the funding tx for
        # every in-block spend — exactly what BIP143 (amount) and BIP341
        # (amount + script) digests need (VERDICT r2 item 5 / r4 item 3).
        # Misses fall through to cfg.prevout_lookup.
        block_outs = intra_block_prevouts(txs) if len(txs) > 1 else {}
        oracle = self._prevout_oracle()
        per_tx: list[tuple[Tx, ExtractStats, list, Optional[asyncio.Task]]] = []
        clean = True  # no extract/engine error verdicts published
        try:
            with span("node.extract"):
                for tx in txs:
                    try:
                        # everything touching tx attributes goes inside the
                        # guard: a malformed LazyTx (wire.LazyTx) raises on
                        # first attribute access, which must become an error
                        # verdict + peer kill, never a dead ingest task
                        amounts: dict[int, int] = {}
                        scripts: dict[int, bytes] = {}
                        for idx, txin in enumerate(tx.inputs):
                            key = (txin.prevout.txid, txin.prevout.index)
                            # Precedence mirrors the native resolve(): the
                            # intra-block map is consulted for EVERY input (a
                            # dict hit is free, and classification must see
                            # in-block P2TR scripts identically on both
                            # paths); the external oracle only for inputs the
                            # tx-level witness gate marks (review r5 parity
                            # finding).
                            hit = block_outs.get(key)
                            if hit is not None:
                                amt, script = hit
                            elif oracle is not None and (
                                wants_amount(tx, idx, self.cfg.net.bch)
                            ):
                                amt, script = _prevout_info(oracle(*key))
                            else:
                                amt = script = None
                            if amt is not None:
                                amounts[idx] = amt
                            if script is not None:
                                scripts[idx] = script
                        items, stats = extract_sig_items(
                            tx,
                            prevout_amounts=amounts or None,
                            bch=self.cfg.net.bch,
                            prevout_scripts=scripts or None,
                        )
                    except Exception as e:
                        clean = False
                        self._verify_failure("extract", e)
                        try:
                            txid = tx.txid
                        except Exception:
                            txid = b""  # unparseable lazy tx: aggregate
                            peer.kill(CannotDecodePayload(f"tx: {e}"))
                        self._publish_verdict(
                            TxVerdict(peer, txid, False, (), ExtractStats(),
                                      error=f"extract: {e}")
                        )
                        continue
                    metrics.inc("node.verify_txs")
                    metrics.inc("node.verify_inputs", stats.total_inputs)
                    task = None
                    if items:
                        task = spawn_supervised(
                            self.verify_engine.verify(
                                [i.verify_item for i in items],
                                priority=(
                                    self._block_priority()
                                    if block is not None
                                    else "mempool"
                                ),
                            ),
                            name="verify-sigbatch",
                            owner=self._verify_tasks,
                        )
                    per_tx.append((tx, stats, items, task))
            # Awaiting the engine happens OUTSIDE any commit span — the
            # wait is already attributed by the verify.queue spans, and
            # folding it into node.commit would make that histogram mean
            # something different on this path than on the native one.
            for tx, stats, items, task in per_tx:
                if task is None:
                    self._publish_verdict(
                        TxVerdict(peer, tx.txid, True, (), stats)
                    )
                    continue
                try:
                    verdicts = await task
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    clean = False
                    self._verify_failure("engine", e)
                    self._publish_verdict(
                        TxVerdict(peer, tx.txid, False, (), stats,
                                  error=f"engine: {e}")
                    )
                    continue
                # candidate verdicts -> per-signature (consensus walk)
                with span("node.commit"):
                    per_sig = tuple(combine_verdicts(items, verdicts))
                    self._publish_verdict(
                        TxVerdict(peer, tx.txid, all(per_sig), per_sig,
                                  stats)
                    )
            if block is not None and clean:
                # persistent UTXO connect only AFTER every verdict landed
                # cleanly (mirrors the native path): the watermark means
                # "verified AND applied" — an error-verdict block stays
                # unpersisted so its re-delivery re-verifies
                self._connect_block_utxo(block)
        finally:
            self._verify_pending -= 1
            for _, _, _, task in per_tx:
                if task is not None and not task.done():
                    task.cancel()
            # the message's pipeline trace (if any) ends with its verdicts
            _finish_active_trace()


class _TCPConnection:
    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self._reader = reader
        self._writer = writer

    async def read_chunk(self) -> bytes:
        return await self._reader.read(65536)

    async def write(self, data: bytes) -> None:
        self._writer.write(data)
        await self._writer.drain()


def _numeric_host(host: str) -> bool:
    """Is ``host`` a numeric IPv4/IPv6 literal (zone id allowed)?"""
    import ipaddress

    try:
        ipaddress.ip_address(host.split("%", 1)[0])
        return True
    except ValueError:
        return False


def tcp_connect(sa: SockAddr) -> WithConnection:
    """Production transport (reference ``withConnection`` Node.hs:108-128).

    NUMERIC hosts only (reference ``fromSockAddr`` resolves with
    NumericHost): hostnames are resolved ONCE at address-book build time
    (``peermgr.to_sock_addr``), so the connect path itself never performs
    a DNS lookup — a slow or wedged resolver must not stall a peer slot
    for its whole connect timeout.  A non-numeric host here is a caller
    bug and fails fast as PeerAddressInvalid."""

    @contextlib.asynccontextmanager
    async def factory():
        if not _numeric_host(sa[0]):
            raise PeerAddressInvalid(
                f"{sa}: non-numeric host (resolve via to_sock_addr first)"
            )
        try:
            reader, writer = await asyncio.open_connection(sa[0], sa[1])
        except OSError as e:
            raise PeerAddressInvalid(f"{sa}: {e}") from e
        try:
            yield _TCPConnection(reader, writer)
        finally:
            writer.close()
            with contextlib.suppress(Exception):
                await writer.wait_closed()

    return factory
