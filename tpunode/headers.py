"""Header-chain consensus: validation, difficulty, locators, chain work.

The reference delegates all of this to haskoin-core (``connectBlocks``,
``blockLocator``, ``getAncestor``, ``splitPoint``, ``genesisNode`` — imported
at /root/reference/src/Haskoin/Node/Chain.hs:85-100 and driven from
``importHeaders`` at Chain.hs:496-520).  This module implements the same
consensus surface from scratch:

* proof-of-work check against the compact target,
* expected-bits computation (mainnet 2016-block retarget, testnet3
  min-difficulty blocks, regtest no-retarget, and the Bitcoin Cash EDA /
  cw-144 DAA / aserti3-2d rules),
* median-time-past and future-timestamp sanity,
* cumulative chain-work tracking and best-chain selection,
* block locators, ancestor walks and split points.

Storage is abstracted behind ``HeaderStore`` so the same code runs over the
chain manager's persistent KV store or an in-memory dict in tests.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Optional, Protocol

from .params import Network
from .util import Reader, bits_to_target, hash_to_hex, header_work, target_to_bits
from .wire import BlockHeader

__all__ = [
    "BlockNode",
    "HeaderStore",
    "MemoryHeaderStore",
    "BadHeaders",
    "genesis_node",
    "connect_blocks",
    "next_work_required",
    "median_time_past",
    "get_ancestor",
    "get_parents",
    "block_locator",
    "split_point",
]

# A block is invalid if its timestamp exceeds adjusted time by this much.
MAX_FUTURE_BLOCK_TIME = 2 * 3600


class BadHeaders(Exception):
    """Raised when a header batch fails consensus validation.

    The chain manager maps this to killing the sending peer with
    ``PeerSentBadHeaders`` (reference: Chain.hs:334-338,516).
    """


@dataclass(frozen=True)
class BlockNode:
    """A validated header with its height and cumulative chain work.

    Mirror of haskoin-core's ``BlockNode`` (surveyed in SURVEY.md C6).
    """

    header: BlockHeader
    height: int
    work: int  # cumulative chain work up to and including this block

    @property
    def hash(self) -> bytes:
        return self.header.hash

    @property
    def hash_hex(self) -> str:
        return self.header.hash_hex

    def serialize(self) -> bytes:
        return (
            self.header.serialize()
            + self.height.to_bytes(4, "little")
            + self.work.to_bytes(36, "little")
        )

    @classmethod
    def deserialize(cls, data: bytes) -> "BlockNode":
        r = Reader(data)
        header = BlockHeader.deserialize(r)
        height = r.u32()
        work = int.from_bytes(r.read(36), "little")
        return cls(header, height, work)


class HeaderStore(Protocol):
    """Read side of a header store (the ``BlockHeaders`` typeclass analog,
    reference: Chain.hs:233-263)."""

    def get_header(self, block_hash: bytes) -> Optional[BlockNode]: ...

    def get_best(self) -> BlockNode: ...


class MemoryHeaderStore:
    """Dict-backed header store for tests and scratch use."""

    def __init__(self, net: Network):
        g = genesis_node(net)
        self.headers: dict[bytes, BlockNode] = {g.hash: g}
        self.best: BlockNode = g

    def get_header(self, block_hash: bytes) -> Optional[BlockNode]:
        return self.headers.get(block_hash)

    def get_best(self) -> BlockNode:
        return self.best

    def add_headers(self, nodes: Iterable[BlockNode]) -> None:
        for n in nodes:
            self.headers[n.hash] = n

    def set_best(self, node: BlockNode) -> None:
        self.best = node


def genesis_node(net: Network) -> BlockNode:
    """The genesis ``BlockNode`` (reference: haskoin-core ``genesisNode``,
    used at Chain.hs:464-468)."""
    g = net.genesis
    header = BlockHeader(
        version=g.version,
        prev=b"\x00" * 32,
        merkle=g.merkle,
        timestamp=g.timestamp,
        bits=g.bits,
        nonce=g.nonce,
    )
    return BlockNode(header=header, height=0, work=header_work(g.bits))


# --- ancestor / locator / split-point walks --------------------------------


class _Overlay:
    """HeaderStore view extended with not-yet-persisted nodes."""

    def __init__(self, store: HeaderStore, extra: dict[bytes, BlockNode]):
        self._store = store
        self._extra = extra

    def get_header(self, block_hash: bytes) -> Optional[BlockNode]:
        n = self._extra.get(block_hash)
        if n is not None:
            return n
        return self._store.get_header(block_hash)

    def get_best(self) -> BlockNode:
        return self._store.get_best()


def get_ancestor(store: HeaderStore, height: int, node: BlockNode) -> Optional[BlockNode]:
    """Ancestor of ``node`` at ``height`` by walking prev pointers
    (reference: haskoin-core ``getAncestor``, used at Chain.hs:690-697)."""
    if height > node.height or height < 0:
        return None
    cur = node
    while cur.height > height:
        parent = store.get_header(cur.header.prev)
        if parent is None:
            return None
        cur = parent
    return cur


def get_parents(store: HeaderStore, height: int, node: BlockNode) -> list[BlockNode]:
    """Parents of ``node`` from ``height`` up to ``node.height - 1``
    (reference: ``chainGetParents`` Chain.hs:700-715)."""
    acc: list[BlockNode] = []
    cur = node
    while height < cur.height:
        parent = store.get_header(cur.header.prev)
        if parent is None:
            break
        acc.append(parent)
        cur = parent
    acc.reverse()
    return acc


def median_time_past(store: HeaderStore, node: BlockNode, span: int = 11) -> int:
    """Median timestamp of the last ``span`` blocks ending at ``node``."""
    times: list[int] = []
    cur: Optional[BlockNode] = node
    while cur is not None and len(times) < span:
        times.append(cur.header.timestamp)
        if cur.height == 0:
            break
        cur = store.get_header(cur.header.prev)
    times.sort()
    return times[len(times) // 2]


def block_locator(store: HeaderStore, node: BlockNode) -> list[bytes]:
    """Compact O(log n) locator: 10 recent hashes then doubling steps back to
    genesis (reference: haskoin-core ``blockLocator``, used at Chain.hs:582)."""
    hashes: list[bytes] = []
    step = 1
    cur: Optional[BlockNode] = node
    while cur is not None:
        hashes.append(cur.hash)
        if cur.height == 0:
            break
        if len(hashes) >= 10:
            step *= 2
        height = max(0, cur.height - step)
        cur = get_ancestor(store, height, cur)
    return hashes


def split_point(store: HeaderStore, left: BlockNode, right: BlockNode) -> BlockNode:
    """Highest common ancestor of two nodes (reference: haskoin-core
    ``splitPoint``, used at Chain.hs:718-725)."""
    h = min(left.height, right.height)
    l = get_ancestor(store, h, left)
    r = get_ancestor(store, h, right)
    if l is None or r is None:
        raise BadHeaders("split point walk fell off the chain")
    while l.hash != r.hash:
        lp = store.get_header(l.header.prev)
        rp = store.get_header(r.header.prev)
        if lp is None or rp is None:
            raise BadHeaders("split point walk fell off the chain")
        l, r = lp, rp
    return l


# --- difficulty ------------------------------------------------------------


def _clamped_retarget(net: Network, parent: BlockNode, first: BlockNode) -> int:
    """Classic 2016-block retarget with the 4x clamp."""
    timespan = parent.header.timestamp - first.header.timestamp
    lo = net.pow_target_timespan // 4
    hi = net.pow_target_timespan * 4
    timespan = max(lo, min(hi, timespan))
    new_target = bits_to_target(parent.header.bits) * timespan // net.pow_target_timespan
    return target_to_bits(min(new_target, net.pow_limit))


def _last_non_min_difficulty_bits(store: HeaderStore, net: Network, parent: BlockNode) -> int:
    """Walk back over min-difficulty blocks to the last 'real' difficulty
    (the testnet3 rule from Bitcoin Core's GetNextWorkRequired)."""
    limit_bits = net.pow_limit_bits
    cur = parent
    while (
        cur.height % net.retarget_interval != 0
        and cur.header.bits == limit_bits
        and cur.height > 0
    ):
        prev = store.get_header(cur.header.prev)
        if prev is None:
            break
        cur = prev
    return cur.header.bits


def _eda_bits(store: HeaderStore, net: Network, parent: BlockNode) -> int:
    """BCH emergency difficulty adjustment (UAHF, pre-DAA): if the last six
    blocks took more than 12 hours by MTP, ease difficulty by 25%."""
    anc6 = get_ancestor(store, parent.height - 6, parent)
    if anc6 is None:
        return parent.header.bits
    mtp_diff = median_time_past(store, parent) - median_time_past(store, anc6)
    if mtp_diff < 12 * 3600:
        return parent.header.bits
    target = bits_to_target(parent.header.bits)
    target += target >> 2
    return target_to_bits(min(target, net.pow_limit))


def _suitable_block(store: HeaderStore, node: BlockNode) -> BlockNode:
    """Median-by-timestamp of a block and its two parents (BCH DAA)."""
    b2 = node
    b1 = store.get_header(b2.header.prev)
    b0 = b1 and store.get_header(b1.header.prev)
    if b1 is None or b0 is None:
        return node
    blocks = sorted([b0, b1, b2], key=lambda b: (b.header.timestamp, b.height))
    return blocks[1]


def _daa_bits(store: HeaderStore, net: Network, parent: BlockNode) -> int:
    """BCH cw-144 difficulty adjustment (Nov 2017): chain-work over the last
    144 blocks between median-of-three endpoints, scaled to 600s spacing."""
    if parent.height < 147:
        return parent.header.bits
    last = _suitable_block(store, parent)
    first_anchor = get_ancestor(store, parent.height - 144, parent)
    if first_anchor is None:
        return parent.header.bits
    first = _suitable_block(store, first_anchor)
    timespan = last.header.timestamp - first.header.timestamp
    timespan = max(72 * net.pow_target_spacing, min(288 * net.pow_target_spacing, timespan))
    work = (last.work - first.work) * net.pow_target_spacing // timespan
    if work <= 0:
        return net.pow_limit_bits
    next_target = (1 << 256) // work - 1
    return target_to_bits(min(next_target, net.pow_limit))


def _asert_bits(net: Network, parent: BlockNode, header: BlockHeader) -> int:
    """BCH aserti3-2d (Nov 2020): exponential target schedule anchored at the
    activation block, integer fixed-point per the published spec."""
    assert net.asert_anchor is not None
    anchor_height, anchor_bits, anchor_parent_time = net.asert_anchor
    ideal = net.pow_target_spacing
    halflife = 2 * 24 * 3600
    anchor_target = bits_to_target(anchor_bits)
    time_diff = parent.header.timestamp - anchor_parent_time
    height_diff = parent.height - anchor_height + 1
    exponent = ((time_diff - ideal * height_diff) << 16) // halflife
    shifts = exponent >> 16
    frac = exponent & 0xFFFF
    factor = 65536 + (
        (195766423245049 * frac + 971821376 * frac * frac + 5127 * frac * frac * frac + (1 << 47))
        >> 48
    )
    next_target = anchor_target * factor
    if shifts < 0:
        next_target >>= -shifts
    else:
        next_target <<= shifts
    next_target >>= 16
    if next_target == 0:
        return target_to_bits(1)
    return target_to_bits(min(next_target, net.pow_limit))


def next_work_required(
    store: HeaderStore, net: Network, parent: BlockNode, header: BlockHeader
) -> int:
    """Expected compact bits for a block extending ``parent``.

    Dispatches across BTC mainnet/testnet/regtest and the three generations of
    BCH difficulty rules, mirroring the capability haskoin-core provides to the
    reference's ``connectBlocks`` call (Chain.hs:519).
    """
    # Bitcoin Cash mainnet/testnet difficulty epochs (by parent height).
    if net.bch and not net.no_retargeting:
        if net.asert_height is not None and parent.height + 1 > net.asert_height:
            return _asert_bits(net, parent, header)
        if net.daa_height is not None and parent.height >= net.daa_height:
            if net.allow_min_difficulty and header.timestamp > (
                parent.header.timestamp + 2 * net.pow_target_spacing
            ):
                return net.pow_limit_bits
            return _daa_bits(store, net, parent)

    interval = net.retarget_interval
    if (parent.height + 1) % interval != 0:
        # Not a retarget boundary.
        if net.allow_min_difficulty:
            if header.timestamp > parent.header.timestamp + 2 * net.pow_target_spacing:
                return net.pow_limit_bits
            if not net.no_retargeting:
                return _last_non_min_difficulty_bits(store, net, parent)
        if (
            net.bch
            and not net.no_retargeting
            and net.eda_height is not None
            and parent.height >= net.eda_height
        ):
            return _eda_bits(store, net, parent)
        return parent.header.bits
    if net.no_retargeting:
        return parent.header.bits
    first = get_ancestor(store, parent.height + 1 - interval, parent)
    if first is None:
        raise BadHeaders("retarget ancestor missing from store")
    return _clamped_retarget(net, parent, first)


def valid_pow(header: BlockHeader, pow_limit: int) -> bool:
    """Check the header hashes below its own claimed target."""
    target = bits_to_target(header.bits)
    if target <= 0 or target > pow_limit:
        return False
    return int.from_bytes(header.hash, "little") <= target


# --- the main entry point: connect a batch of headers ----------------------


def connect_blocks(
    store: HeaderStore,
    net: Network,
    now: int,
    headers: list[BlockHeader],
) -> tuple[list[BlockNode], BlockNode]:
    """Validate and connect a contiguous batch of headers.

    Returns ``(new_nodes, new_best)``.  ``new_nodes`` must be persisted and, if
    ``new_best`` differs from the stored best, the best pointer updated — the
    chain manager does both in one batch write (the analog of the reference's
    ``connectBlocks`` + ``addBlockHeaders``/``setBestBlockHeader`` write at
    Chain.hs:256-263,519).

    Raises :class:`BadHeaders` when any header fails consensus checks; the
    caller treats the whole batch (and the sending peer) as bad.
    """
    fresh: dict[bytes, BlockNode] = {}
    view = _Overlay(store, fresh)
    nodes: list[BlockNode] = []
    best = store.get_best()

    for header in headers:
        parent = view.get_header(header.prev)
        if parent is None:
            raise BadHeaders(
                f"header {header.hash_hex} does not connect (prev "
                f"{hash_to_hex(header.prev)} unknown)"
            )
        if header.timestamp > now + MAX_FUTURE_BLOCK_TIME:
            raise BadHeaders(f"header {header.hash_hex} timestamp too far in future")
        mtp = median_time_past(view, parent)
        if header.timestamp <= mtp:
            raise BadHeaders(
                f"header {header.hash_hex} timestamp {header.timestamp} <= MTP {mtp}"
            )
        expected_bits = next_work_required(view, net, parent, header)
        if header.bits != expected_bits:
            raise BadHeaders(
                f"header {header.hash_hex} bad bits {header.bits:#x}, "
                f"expected {expected_bits:#x}"
            )
        if not valid_pow(header, net.pow_limit):
            raise BadHeaders(f"header {header.hash_hex} fails proof of work")
        node = BlockNode(
            header=header,
            height=parent.height + 1,
            work=parent.work + header_work(header.bits),
        )
        fresh[node.hash] = node
        nodes.append(node)
        if node.work > best.work:
            best = node

    return nodes, best
