"""Flight recorder: triggered post-mortem bundles (the node's black box).

An incident on a pod-shaped node — a watchdog stall, a breaker tripping
open, a fleet host partitioning, store corruption — currently evaporates
unless a human was tailing the event log when it happened.  The flight
recorder subscribes to the process event log and, when a **trigger**
event fires, freezes everything an operator would wish they had:

* the recent events ring (with per-type totals),
* the slowest + most recent causal traces (tpunode/tracectx.py),
* the metrics timeline window around the trigger (tpunode/timeseries.py)
  — including the per-host fleet series,
* live state sources wired in by the node: engine/breaker/mesh state,
  sched + fleet queue depths, watchdog surfaces, store stats, health,
* chaos-injection stats (so a chaos-driven incident is self-describing).

Triggers: ``watchdog.stall``, ``mesh.host_down``, ``store.corruption``,
``utxo.error``, ``asyncsan.task_leak``, ``slo.burn`` (an error-budget
burn-rate breach, ISSUE 17 — the bundle's ``slo`` source carries the
breached definition, budgets, burn history and cost ledger), a circuit
breaker opening (``verify.breaker`` with ``to="open"``), and — via an
explicit :meth:`record` call from ``Node.__aexit__`` — an unclean
shutdown.

Bundles are **rate-limited** (``min_interval``, default 30s): an incident
storm produces one bundle plus a ``blackbox.suppressed`` count, never a
disk flood.  Bundles always land in an in-memory ring (``/flightrecords``
endpoint); with ``TPUNODE_BLACKBOX_DIR`` (or ``FlightRecorderConfig.dir``)
set, each is also written as one JSON file.  Stdlib-only, never imports
jax; safe to fire from the engine's dispatch worker threads (one lock,
sources wrapped so a broken provider degrades to an error string).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

from . import threadsan
from .chaos import chaos
from .events import EventLog, events
from .metrics import metrics
from .tracectx import tracer

__all__ = ["FlightRecorderConfig", "FlightRecorder", "TRIGGERS"]

log = logging.getLogger("tpunode.blackbox")

# Event types that always trigger a dump.  ``verify.breaker`` is handled
# conditionally (only the transition INTO "open" is an incident) and
# ``blackbox.dump`` itself must never be here (self-triggering).
TRIGGERS = frozenset(
    {
        "watchdog.stall",
        "mesh.host_down",
        "store.corruption",
        "utxo.error",
        "asyncsan.task_leak",
        "threadsan.lock_cycle",
        "threadsan.lock_reentry",
        "slo.burn",
    }
)


@dataclass
class FlightRecorderConfig:
    dir: Optional[str] = None  # None -> $TPUNODE_BLACKBOX_DIR -> memory-only
    min_interval: float = 30.0  # seconds between dumps (rate limit)
    ring: int = 16  # in-memory bundles retained
    events_tail: int = 256  # recent events per bundle
    traces: int = 8  # slowest + recent traces per bundle
    window: float = 120.0  # timeline seconds captured before the trigger

    def __post_init__(self) -> None:
        if self.dir is None:
            self.dir = os.environ.get("TPUNODE_BLACKBOX_DIR") or None


class FlightRecorder:
    """Event-triggered post-mortem bundle writer."""

    def __init__(
        self,
        cfg: Optional[FlightRecorderConfig] = None,
        log_: Optional[EventLog] = None,
        timeline=None,  # tpunode.timeseries.Timeline (or None)
        tracer_=None,
        sources: Optional[dict[str, Callable[[], object]]] = None,
    ):
        self.cfg = cfg or FlightRecorderConfig()
        self.log = log_ if log_ is not None else events
        self.timeline = timeline
        self.tracer = tracer_ if tracer_ is not None else tracer
        # name -> zero-arg callable; each lands as a top-level bundle key
        # (engine stats, watchdog snapshot, node health, store stats, ...)
        self.sources = dict(sources or {})
        self._lock = threadsan.lock("blackbox.recorder")
        self._records: deque[dict] = deque(maxlen=self.cfg.ring)
        self._last_dump = -float("inf")
        self._suppressed = 0
        self._dumps = 0
        self._write_errors = 0
        self._unsub: Optional[Callable[[], None]] = None

    # -- wiring ---------------------------------------------------------------

    def attach(self) -> None:
        """Subscribe to the event log (idempotent)."""
        if self._unsub is None:
            self._unsub = self.log.subscribe(self._on_event)

    def detach(self) -> None:
        if self._unsub is not None:
            self._unsub()
            self._unsub = None

    def _on_event(self, ev: dict) -> None:
        type_ = ev.get("type")
        if type_ in TRIGGERS or (
            type_ == "verify.breaker" and ev.get("to") == "open"
        ):
            self.record(reason=type_, trigger=ev)

    # -- recording ------------------------------------------------------------

    def record(
        self, reason: str, trigger: Optional[dict] = None, force: bool = False
    ) -> Optional[dict]:
        """Build one bundle now (rate-limited unless ``force``); returns
        the bundle, or None when suppressed."""
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_dump < self.cfg.min_interval:
                self._suppressed += 1
                metrics.inc("blackbox.suppressed")
                return None
            self._last_dump = now
        bundle = self._build(reason, trigger)
        bundle["path"] = self._write(bundle)
        with self._lock:
            self._records.append(bundle)
            self._dumps += 1
        metrics.inc("blackbox.dumps")
        # emitted AFTER the bundle is banked; not a trigger type, so the
        # recorder never feeds itself (observers run outside the log lock)
        self.log.emit(
            "blackbox.dump",
            reason=reason,
            trigger_seq=(trigger or {}).get("seq"),
            path=bundle["path"],
        )
        log.warning("[blackbox] flight record captured: %s", reason)
        return bundle

    def _build(self, reason: str, trigger: Optional[dict]) -> dict:
        ts = time.time()
        bundle: dict = {
            "ts": round(ts, 6),
            "reason": reason,
            "trigger": dict(trigger) if trigger else None,
            "events": self.log.tail(self.cfg.events_tail),
            "event_counts": self.log.counts(),
            "traces": {
                "slowest": self._safe(
                    lambda: self.tracer.slowest(self.cfg.traces)
                ),
                "recent": self._safe(
                    lambda: self.tracer.recent_traces(self.cfg.traces)
                ),
            },
            "chaos": self._safe(chaos.stats),
        }
        if self.timeline is not None:
            bundle["timeline"] = self._safe(
                lambda: self.timeline.window(ts - self.cfg.window, ts)
            )
            bundle["fleet_history"] = self._safe(self.timeline.fleet_history)
        else:
            bundle["timeline"] = {}
            bundle["fleet_history"] = {}
        for name, fn in self.sources.items():
            bundle[name] = self._safe(fn)
        return bundle

    @staticmethod
    def _safe(fn: Callable[[], object]):
        # a broken state provider degrades to an error string — a flight
        # record from a half-dead node must still be written
        try:
            return fn()
        except Exception as e:
            return {"error": repr(e)}

    def _write(self, bundle: dict) -> Optional[str]:
        directory = self.cfg.dir
        if not directory:
            return None
        name = "blackbox-{}-{}.json".format(
            int(bundle["ts"] * 1000), bundle["reason"].replace(".", "_")
        )
        path = os.path.join(directory, name)
        try:
            os.makedirs(directory, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(bundle, f, default=str)
            os.replace(tmp, path)
            return path
        except OSError as e:
            with self._lock:
                self._write_errors += 1
            metrics.inc("blackbox.write_errors")
            log.warning("[blackbox] bundle write failed: %r", e)
            return None

    # -- query ----------------------------------------------------------------

    def records(self, n: int = 16) -> list[dict]:
        """Newest ``n`` bundles, newest first (the /flightrecords body)."""
        with self._lock:
            return list(self._records)[-n:][::-1]

    def stats(self) -> dict:
        with self._lock:
            return {
                "dir": self.cfg.dir,
                "min_interval": self.cfg.min_interval,
                "dumps": self._dumps,
                "suppressed": self._suppressed,
                "write_errors": self._write_errors,
                "attached": self._unsub is not None,
            }
