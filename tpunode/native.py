"""ctypes binding to the native C++ KV store (native/kvstore).

Implements the same :class:`tpunode.store.KVStore` protocol as the Python
engines (the reference's analogous component is RocksDB behind
rocksdb-haskell-jprupp, package.yaml:32-33).  Two on-disk modes, decided
by what is at ``path`` (ISSUE 11 — the engine used to refuse v2
directories via :class:`tpunode.store.StoreVersionError`):

* **legacy v1** single-file log for paths with no v2 artifacts — exactly
  what this engine always wrote, replayed bit-identically by the Python
  v2 reader (pinned by tests/test_store.py);
* **v2 segmented** (the CRC+seq format ``LogKV`` writes, ISSUE 9):
  replays the base snapshot/legacy file plus every segment with CRC and
  per-segment sequence validation, truncates a torn tail of the last
  file, and appends its own records into a fresh v2 segment — so the
  native engine serves the store the node actually writes, and ``LogKV``
  replays the result bit-identically (tests/test_native_v2.py).

Recovery division of labor: mid-log damage (a sealed file failing
CRC/sequence checks) makes ``kv_open`` FAIL rather than silently serve a
prefix of acked data — the quarantining salvage path belongs to
``LogKV`` (tpunode/store.py), which remains the engine of record for
damaged stores.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
import time
from typing import Iterator, Optional, Sequence

from . import threadsan
from .metrics import metrics
from .store import BatchOp, StoreVersionError, delete_op, put_op, v2_artifacts

__all__ = ["NativeKV", "load_kvstore_lib", "ensure_native_lib"]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LIB_PATH = os.path.join(_REPO_ROOT, "native", "build", "libkvstore.so")


def ensure_native_lib(lib_path: str, src_subdir: str) -> str:
    """Build ``lib_path`` via make when missing or older than its sources.

    The mtime check protects against a stale .so with an old C ABI after a
    source change, without making every process invoke (or even require) a
    build toolchain.  If the rebuild FAILS but a prebuilt .so exists, load
    it anyway with a warning: on a toolchain-less host a fresh checkout
    makes every source look newer than a perfectly current prebuilt
    library (git sets mtimes to checkout time), and crashing there would
    regress a working deployment.  The warning gives the operator the
    signal if the library genuinely is stale."""
    native_dir = os.path.join(_REPO_ROOT, "native")
    srcs = [os.path.join(native_dir, "Makefile")]
    src_dir = os.path.join(native_dir, src_subdir)
    if os.path.isdir(src_dir):
        srcs += [
            os.path.join(src_dir, f)
            for f in os.listdir(src_dir)
            if f.endswith((".cpp", ".h", ".hpp"))
        ]
    stale = not os.path.exists(lib_path) or any(
        os.path.getmtime(s) > os.path.getmtime(lib_path)
        for s in srcs
        if os.path.exists(s)
    )
    if stale:
        try:
            subprocess.run(
                ["make", "-C", native_dir,
                 os.path.join("build", os.path.basename(lib_path))],
                check=True,
                capture_output=True,
            )
        except Exception:
            if not os.path.exists(lib_path):
                raise
            import logging

            logging.getLogger("tpunode.native").warning(
                "rebuild of %s failed but a prebuilt library exists; "
                "loading it (sources look newer — verify it is not stale)",
                os.path.basename(lib_path),
            )
    return lib_path

_REC = struct.Struct("<BII")
_SCAN_HDR = struct.Struct("<II")
_OP_PUT = 1
_OP_DEL = 2

_lib_lock = threadsan.lock("native.lib")
_lib: Optional[ctypes.CDLL] = None


def load_kvstore_lib() -> ctypes.CDLL:
    """Build (if needed) and load the shared library, once per process."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        ensure_native_lib(_LIB_PATH, "kvstore")
        lib = ctypes.CDLL(_LIB_PATH)
        lib.kv_open.restype = ctypes.c_void_p
        lib.kv_open.argtypes = [ctypes.c_char_p]
        lib.kv_close.argtypes = [ctypes.c_void_p]
        lib.kv_format.restype = ctypes.c_int
        lib.kv_format.argtypes = [ctypes.c_void_p]
        lib.kv_get.restype = ctypes.c_int
        lib.kv_get.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.kv_write_batch.restype = ctypes.c_int
        lib.kv_write_batch.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.c_int,
        ]
        lib.kv_scan_prefix.restype = ctypes.c_int
        lib.kv_scan_prefix.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.kv_compact.restype = ctypes.c_int
        lib.kv_compact.argtypes = [ctypes.c_void_p]
        lib.kv_count.restype = ctypes.c_uint64
        lib.kv_count.argtypes = [ctypes.c_void_p]
        lib.kv_buf_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


class NativeKV:
    """C++ append-log KV store behind the KVStore protocol."""

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.fsync = fsync
        self._read_tick = 0
        self._h = None  # __del__ must survive an open failure
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lib = load_kvstore_lib()
        self._h = self._lib.kv_open(path.encode())
        if not self._h:
            # kv_open refuses mid-log damage (a sealed segment failing
            # CRC/sequence validation) and formats newer than v2: both
            # are LogKV's richer recovery/reader territory, never a
            # silent stale-prefix serve.
            if v2_artifacts(path):
                raise StoreVersionError(
                    f"{path}: native v2 replay refused (mid-log damage or "
                    "newer format) — open with the LogKV engine to salvage"
                )
            raise OSError(f"kv_open failed for {path!r}")
        self.format_v2 = bool(self._lib.kv_format(self._h))

    # Same 1-in-64 read-latency sampling as LogKV (store.py): the registry
    # lock must not dominate a sub-µs native lookup.
    _READ_SAMPLE_MASK = 63

    def get(self, key: bytes) -> Optional[bytes]:
        sample = False
        if not metrics.disabled:
            self._read_tick += 1
            sample = not (self._read_tick & self._READ_SAMPLE_MASK)
        t0 = time.perf_counter() if sample else 0.0
        out = ctypes.c_void_p()
        outlen = ctypes.c_uint64()
        found = self._lib.kv_get(
            self._h, key, len(key), ctypes.byref(out), ctypes.byref(outlen)
        )
        try:
            if not found:
                return None
            try:
                return ctypes.string_at(out.value, outlen.value)
            finally:
                self._lib.kv_buf_free(out)
        finally:
            if sample:
                metrics.observe(
                    "store.read_seconds", time.perf_counter() - t0
                )

    def put(self, key: bytes, value: bytes) -> None:
        self.write_batch([put_op(key, value)])

    def delete(self, key: bytes) -> None:
        self.write_batch([delete_op(key)])

    def write_batch(self, ops: Sequence[BatchOp]) -> None:
        blob = bytearray()
        for op, k, v in ops:
            if op == "put":
                blob += _REC.pack(_OP_PUT, len(k), len(v)) + k + v
            elif op == "del":
                blob += _REC.pack(_OP_DEL, len(k), 0) + k
            else:
                raise ValueError(f"unknown batch op {op!r}")
        t0 = 0.0 if metrics.disabled else time.perf_counter()
        rc = self._lib.kv_write_batch(
            self._h, bytes(blob), len(blob), 1 if self.fsync else 0
        )
        if rc != 0:
            raise OSError(f"kv_write_batch failed ({rc})")
        if not metrics.disabled:
            metrics.observe("store.write_seconds", time.perf_counter() - t0)
            metrics.inc("store.writes", len(ops))

    def scan_prefix(self, prefix: bytes) -> Iterator[tuple[bytes, bytes]]:
        out = ctypes.c_void_p()
        outlen = ctypes.c_uint64()
        rc = self._lib.kv_scan_prefix(
            self._h, prefix, len(prefix), ctypes.byref(out), ctypes.byref(outlen)
        )
        if rc != 0:
            raise OSError(f"kv_scan_prefix failed ({rc})")
        try:
            raw = ctypes.string_at(out.value, outlen.value)
        finally:
            self._lib.kv_buf_free(out)
        pos = 0
        while pos + _SCAN_HDR.size <= len(raw):
            klen, vlen = _SCAN_HDR.unpack_from(raw, pos)
            pos += _SCAN_HDR.size
            yield raw[pos : pos + klen], raw[pos + klen : pos + klen + vlen]
            pos += klen + vlen

    def compact(self) -> None:
        if self._lib.kv_compact(self._h) != 0:
            raise OSError("kv_compact failed")

    def count(self) -> int:
        return int(self._lib.kv_count(self._h))

    def close(self) -> None:
        if self._h:
            self._lib.kv_close(self._h)
            self._h = None

    def __del__(self):  # best-effort; owners should close() explicitly
        try:
            self.close()
        except Exception:
            pass
