"""chaos — deterministic fault injection for the whole node (ISSUE 7).

The reference haskoin-node earns its keep by *surviving*: peers drop,
stall, and send garbage, and the supervisor tree keeps the chain
consistent through all of it.  None of that is testable here without a
way to make those failures happen on demand — so this module is a
seeded, declarative fault registry with **named injection points** wired
into the layers that actually fail in production:

========================  =================================================
point                     actions
========================  =================================================
``peer.recv``             ``drop`` (EOF), ``stall`` (sleep ``dur`` then
                          read), ``garbage`` (replace the chunk with
                          deterministic noise), ``partial`` (truncate the
                          chunk, then EOF — a mid-frame cut)
``peer.send``             ``drop``, ``stall``, ``garbage``
``mailbox.send``          ``delay`` (deliver after ``dur``), ``reorder``
                          (jump the queue head)
``store.write``           ``error`` (raise ChaosFault from the write)
``store.append``          ``error``, ``torn_write`` (write a prefix of the
                          record blob, then hard-exit — a torn page),
                          ``bit_flip`` (flip one bit in the blob before it
                          hits disk; the process continues — simulated
                          media corruption the CRC must catch on reopen),
                          ``crash`` (``os._exit(CRASH_EXIT)`` at the
                          injection point, before the write)
``store.rotate``          ``error``, ``crash`` (at segment-rotation steps)
``store.compact``         ``error``, ``crash`` (at compaction sub-steps;
                          ``match`` selects the window: ``snapshot``,
                          ``pre_replace``, ``post_replace``, ``cleanup``)
``engine.dispatch``       ``error`` (batch failure), ``device_loss``
                          (raise ChaosDeviceLoss — the breaker's
                          signal), ``stall`` (sleep ``dur`` in the
                          dispatch worker thread — a wedged backend;
                          the SLO engine's synthetic burn source)
``engine.warmup``         ``error`` (device warmup/compile failure)
``mesh.dispatch``         ``error``, ``device_loss`` (one host's chip/
                          sub-mesh fails — that host's breaker degrades
                          it alone), ``partition`` (raise ChaosPartition
                          — the whole host is unreachable: the fleet
                          dispatcher re-queues its lanes and drops it
                          from the active set until a canary rejoins
                          it).  ``match`` scopes the fault to one host
                          and/or rung: the site label is
                          ``<host>:<rung>:chips<n>`` (ISSUE 13)
========================  =================================================

A fault plan is a seed plus a list of :class:`FaultSpec`, parsed from
the ``TPUNODE_CHAOS`` env var (or built programmatically)::

    TPUNODE_CHAOS="seed=42;peer.recv:garbage:p=0.05;engine.dispatch:device_loss:match=tpu,n=3,after=2"

Segments are ``;``-separated; a fault segment is
``<point>:<action>[:key=val[,key=val...]]`` with keys ``p`` (fire
probability, default 1), ``n`` (max fires, default unlimited),
``after`` (eligible hits skipped before the first fire), ``dur``
(seconds, for stall/delay), ``match`` (substring filter on the site
label — a peer label, mailbox name, or engine backend rung).  Every
random decision — fire/don't, garbage bytes — comes from one
``random.Random(seed)``, so a failure scenario is a *reproducible seed*:
re-running the same plan against the same workload injects the same
faults in the same order.

**Zero overhead when off** is a hard contract: every injection site is
written ``if chaos.on: ...`` so an unset ``TPUNODE_CHAOS`` costs one
attribute read and a never-taken branch on the hot paths it guards
(pinned by the micro-bench in tests/test_chaos.py).  Unknown points or
actions fail ``parse`` loudly — a typo'd plan must never silently
no-op.  Every fire is counted (``chaos.injections`` labeled metric) and
logged (``chaos.inject`` event) so a soak run's artifact shows exactly
what was injected where.
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from . import threadsan
from .events import events
from .metrics import metrics

__all__ = [
    "CRASH_EXIT",
    "POINTS",
    "ChaosDeviceLoss",
    "ChaosFault",
    "ChaosPartition",
    "ChaosPlan",
    "FaultSpec",
    "chaos",
]

#: Exit status of an injected ``crash``/``torn_write`` hard-exit: the
#: kill-torture harness (tpunode/torture.py) asserts on it to tell an
#: injected death apart from an ordinary child failure.
CRASH_EXIT = 86

log = logging.getLogger("tpunode.chaos")


class ChaosFault(RuntimeError):
    """An injected fault (store write / engine batch / warmup)."""


class ChaosDeviceLoss(ChaosFault):
    """Injected device loss: what a mid-run TPU disappearance raises on
    the engine's device rung (the circuit breaker's trigger)."""


class ChaosPartition(ChaosFault):
    """Injected host partition (ISSUE 13): the WHOLE host is gone, so
    the dispatch ladder must not serve the lane locally — the fleet
    dispatcher re-queues it onto a healthy peer and deactivates the
    host until a canary re-probe succeeds."""


#: Injection-point catalog: point -> allowed actions (ROBUSTNESS.md is
#: the user-facing version).  ``parse`` validates against this.
POINTS: dict[str, tuple[str, ...]] = {
    "peer.recv": ("drop", "stall", "garbage", "partial"),
    "peer.send": ("drop", "stall", "garbage"),
    "mailbox.send": ("delay", "reorder"),
    "store.write": ("error",),
    "store.append": ("error", "torn_write", "bit_flip", "crash"),
    "store.rotate": ("error", "crash"),
    "store.compact": ("error", "crash"),
    "engine.dispatch": ("error", "device_loss", "stall"),
    "engine.warmup": ("error",),
    "mesh.dispatch": ("error", "device_loss", "partition"),
}


@dataclass
class FaultSpec:
    """One declarative fault: where, what, and how often."""

    point: str
    action: str
    p: float = 1.0  # fire probability per eligible hit
    n: Optional[int] = None  # max fires (None = unlimited)
    after: int = 0  # eligible hits skipped before the first fire
    dur: float = 0.05  # seconds (stall / delay)
    match: str = ""  # substring filter on the site label
    # runtime counters (owned by the installed Chaos registry)
    hits: int = field(default=0, compare=False)
    fired: int = field(default=0, compare=False)

    def __post_init__(self):
        allowed = POINTS.get(self.point)
        if allowed is None:
            raise ValueError(
                f"unknown chaos point {self.point!r} (known: "
                f"{', '.join(sorted(POINTS))})"
            )
        if self.action not in allowed:
            raise ValueError(
                f"chaos point {self.point!r} has no action "
                f"{self.action!r} (allowed: {', '.join(allowed)})"
            )
        if not 0.0 <= self.p <= 1.0:
            raise ValueError(f"chaos p={self.p} outside [0, 1]")

    def describe(self) -> str:
        opts = []
        if self.p < 1.0:
            opts.append(f"p={self.p}")
        if self.n is not None:
            opts.append(f"n={self.n}")
        if self.after:
            opts.append(f"after={self.after}")
        if self.action in ("stall", "delay"):
            opts.append(f"dur={self.dur}")
        if self.match:
            opts.append(f"match={self.match}")
        tail = ":" + ",".join(opts) if opts else ""
        return f"{self.point}:{self.action}{tail}"


@dataclass
class ChaosPlan:
    """A seed plus the faults it drives (``TPUNODE_CHAOS`` syntax)."""

    seed: int = 0
    faults: list[FaultSpec] = field(default_factory=list)

    @classmethod
    def parse(cls, spec: str) -> "ChaosPlan":
        """Parse the declarative syntax (module docstring).  Raises
        ``ValueError`` on any unknown point/action/key — a chaos plan
        that silently no-ops would fake out the very tests it exists
        for."""
        seed = 0
        faults: list[FaultSpec] = []
        for seg in spec.split(";"):
            seg = seg.strip()
            if not seg:
                continue
            if seg.startswith("seed="):
                seed = int(seg[5:], 0)
                continue
            parts = seg.split(":", 2)
            if len(parts) < 2:
                raise ValueError(f"bad chaos segment {seg!r}")
            kw: dict = {"point": parts[0], "action": parts[1]}
            if len(parts) == 3 and parts[2]:
                for opt in parts[2].split(","):
                    k, _, v = opt.partition("=")
                    k = k.strip()
                    if k == "p":
                        kw["p"] = float(v)
                    elif k == "n":
                        kw["n"] = int(v)
                    elif k == "after":
                        kw["after"] = int(v)
                    elif k == "dur":
                        kw["dur"] = float(v)
                    elif k == "match":
                        kw["match"] = v
                    else:
                        raise ValueError(
                            f"unknown chaos option {k!r} in {seg!r}"
                        )
            faults.append(FaultSpec(**kw))
        return cls(seed=seed, faults=faults)

    def describe(self) -> str:
        return ";".join(
            [f"seed={self.seed}"] + [f.describe() for f in self.faults]
        )


class Chaos:
    """The process-wide injection registry.

    ``on`` is the only thing the hot paths read: injection sites are
    ``if chaos.on: <site hook>``, so the OFF path is one attribute load.
    All decision state (per-spec counters, the plan RNG) lives behind a
    lock — decisions happen on the event loop AND in the engine's
    dispatch worker thread, and determinism requires one serialized
    stream of RNG draws.
    """

    def __init__(self):
        self.on = False
        self._lock = threadsan.lock("chaos.controller")
        self._plan: Optional[ChaosPlan] = None
        self._rng: Optional[random.Random] = None
        self._by_point: dict[str, list[FaultSpec]] = {}

    # -- lifecycle -----------------------------------------------------------

    def install(self, plan: ChaosPlan) -> None:
        """Arm the registry with ``plan`` (replacing any previous plan;
        runtime counters reset)."""
        with self._lock:
            self._plan = plan
            self._rng = random.Random(plan.seed)
            self._by_point = {}
            for f in plan.faults:
                f.hits = f.fired = 0
                self._by_point.setdefault(f.point, []).append(f)
            self.on = bool(plan.faults)
        if self.on:
            log.warning("[Chaos] armed: %s", plan.describe())
            events.emit("chaos.install", plan=plan.describe())
            metrics.set_gauge("chaos.enabled", 1.0)

    def uninstall(self) -> None:
        """Disarm (test teardown): the OFF fast path is restored."""
        with self._lock:
            self.on = False
            self._plan = None
            self._rng = None
            self._by_point = {}
        metrics.set_gauge("chaos.enabled", 0.0)

    def stats(self) -> dict:
        """Injection telemetry: per-fault hit/fire counts (soak artifacts
        record this so a run shows what was actually injected)."""
        with self._lock:
            return {
                "enabled": self.on,
                "plan": self._plan.describe() if self._plan else None,
                "faults": [
                    {
                        "fault": f.describe(),
                        "hits": f.hits,
                        "fired": f.fired,
                    }
                    for f in (self._plan.faults if self._plan else ())
                ],
            }

    # -- the decision core ---------------------------------------------------

    def decide(self, point: str, label: str = "") -> Optional[FaultSpec]:
        """One injection decision at ``point`` (site context ``label``):
        the fault to apply, or None.  First matching spec wins; every
        fire is counted + logged."""
        with self._lock:
            specs = self._by_point.get(point)
            if not specs or self._rng is None:
                return None
            for spec in specs:
                if spec.match and spec.match not in label:
                    continue
                spec.hits += 1
                if spec.hits <= spec.after:
                    continue
                if spec.n is not None and spec.fired >= spec.n:
                    continue
                if spec.p < 1.0 and self._rng.random() >= spec.p:
                    continue
                spec.fired += 1
                metrics.inc(
                    "chaos.injections",
                    labels={"point": point, "action": spec.action},
                )
                events.emit(
                    "chaos.inject", point=point, action=spec.action,
                    label=label or None, fired=spec.fired,
                )
                return spec
        return None

    def maybe_raise(self, point: str, label: str = "") -> None:
        """Raise the configured fault at a raise-style point (store
        write, engine dispatch/warmup); no-op when nothing fires."""
        spec = self.decide(point, label)
        if spec is None:
            return
        if spec.action == "stall":
            # Blocks THIS dispatch worker thread for ``dur`` (ISSUE 17:
            # the SLO chaos plan's synthetic dispatch stall) — the event
            # loop stays healthy, exactly like a wedged backend.
            time.sleep(spec.dur)
            return
        msg = f"chaos[{spec.describe()}] at {label or point}"
        if spec.action == "device_loss":
            raise ChaosDeviceLoss(msg)
        if spec.action == "partition":
            raise ChaosPartition(msg)
        raise ChaosFault(msg)

    def garbage(self, n: int) -> bytes:
        """``n`` deterministic noise bytes from the plan RNG."""
        with self._lock:
            rng = self._rng or random.Random(0)
            return rng.randbytes(n)

    def maybe_crash(self, point: str, label: str = "") -> None:
        """Structural storage point (rotate/compact sub-steps): ``crash``
        hard-exits the process at the injection point; ``error`` raises
        ChaosFault; no-op when nothing fires."""
        spec = self.decide(point, label)
        if spec is None:
            return
        if spec.action == "crash":
            self.hard_exit()
        raise ChaosFault(f"chaos[{spec.describe()}] at {label or point}")

    def mutate_blob(self, spec: FaultSpec, blob: bytes) -> bytes:
        """Apply a physical-write fault to ``blob``: ``bit_flip`` flips one
        deterministic bit, ``torn_write`` keeps a deterministic strict
        prefix (the caller writes it, then hard-exits).  Draws come from
        the plan RNG so the damage is part of the reproducible seed."""
        if not blob:
            return blob
        with self._lock:
            rng = self._rng or random.Random(0)
            if spec.action == "bit_flip":
                mutated = bytearray(blob)
                mutated[rng.randrange(len(blob))] ^= 1 << rng.randrange(8)
                return bytes(mutated)
            if spec.action == "torn_write":
                return blob[: rng.randrange(1, len(blob))] if len(blob) > 1 else b""
        return blob

    @staticmethod
    def hard_exit() -> None:
        """Die like ``kill -9``: no atexit, no finally blocks, no buffer
        flushing beyond what the caller already forced.  The distinctive
        status lets the torture harness assert the death was injected."""
        os._exit(CRASH_EXIT)

    # -- transport wrapper ---------------------------------------------------

    def wrap_connection(self, conn, label: str):
        """Wrap a peer transport with the ``peer.recv``/``peer.send``
        injection points; returns ``conn`` untouched when no peer faults
        are planned (sessions opened while armed pay nothing unless the
        plan targets them)."""
        with self._lock:
            active = "peer.recv" in self._by_point or (
                "peer.send" in self._by_point
            )
        if not active:
            return conn
        return _ChaosConnection(self, conn, label)


class _ChaosConnection:
    """Transport decorator applying socket-level faults (peer.py wraps
    the injected ``Connection`` with this when chaos is armed)."""

    __slots__ = ("_chaos", "_inner", "_label", "_eof")

    def __init__(self, registry: Chaos, inner, label: str):
        self._chaos = registry
        self._inner = inner
        self._label = label
        self._eof = False

    async def read_chunk(self) -> bytes:
        if self._eof:
            return b""
        spec = self._chaos.decide("peer.recv", self._label)
        if spec is None:
            return await self._inner.read_chunk()
        if spec.action == "drop":
            self._eof = True
            return b""  # EOF: the session dies like a real disconnect
        if spec.action == "stall":
            await asyncio.sleep(spec.dur)
            return await self._inner.read_chunk()
        chunk = await self._inner.read_chunk()
        if not chunk:
            return chunk
        if spec.action == "garbage":
            return self._chaos.garbage(len(chunk))
        # partial: a mid-frame cut — half the chunk, then EOF, so the
        # reader hits DecodeHeaderError("connection closed mid-frame")
        self._eof = True
        return chunk[: max(1, len(chunk) // 2)]

    async def write(self, data: bytes) -> None:
        spec = self._chaos.decide("peer.send", self._label)
        if spec is not None:
            if spec.action == "drop":
                return  # swallowed: the remote never sees it
            if spec.action == "stall":
                await asyncio.sleep(spec.dur)
            elif spec.action == "garbage":
                data = self._chaos.garbage(len(data))
        await self._inner.write(data)


#: The process-wide registry (mirrors ``metrics``/``events``).
chaos = Chaos()

_env_plan = os.environ.get("TPUNODE_CHAOS")
if _env_plan:
    chaos.install(ChaosPlan.parse(_env_plan))
