"""Peer session actor: one async task per connected peer.

Mirror of the reference's peer process (/root/reference/src/Haskoin/Node/Peer.hs):
frames and decodes the byte stream, publishes every inbound message as a
``PeerMessage`` event, accepts ``SendMessage``/``KillPeer`` commands through its
mailbox, and offers synchronous request helpers (``get_blocks``/``get_txs``/
``get_data``/``ping_peer``, reference Peer.hs:309-399) built on pub/sub-as-RPC
with the ping-sentinel trick.

The transport is injectable (the ``WithConnection`` seam, Peer.hs:112-117):
production uses TCP (tpunode/node.py), tests use an in-memory duplex pipe —
this seam is what makes the whole node testable without a network.
"""

from __future__ import annotations

import asyncio
import logging
import random
from contextlib import AbstractAsyncContextManager
from dataclasses import dataclass, field
from typing import Callable, Optional, Protocol, Union

from .actors import Mailbox, Publisher, spawn_supervised
from .chaos import chaos
from .compat import timeout as _timeout
from .metrics import metrics
from .params import Network
from .trace import span
from .tracectx import _ACTIVE as _active_trace, tracer
from .util import hash_to_hex
from .wire import (
    Block,
    DecodeError,
    LazyBlock,
    LazyTx,
    InvType,
    InvVector,
    MAX_PAYLOAD,
    MsgBlock,
    MsgGetData,
    MsgNotFound,
    MsgPing,
    MsgPong,
    MsgTx,
    Tx,
    decode_message,
    decode_message_header,
    encode_message,
    HEADER_SIZE,
)

__all__ = [
    "Connection",
    "WithConnection",
    "ConnectionReader",
    "PeerError",
    "PeerMisbehaving",
    "DuplicateVersion",
    "DecodeHeaderError",
    "CannotDecodePayload",
    "PeerIsMyself",
    "PayloadTooLarge",
    "PeerAddressInvalid",
    "PeerSentBadHeaders",
    "NotNetworkPeer",
    "PeerNoSegWit",
    "PeerTimeout",
    "UnknownPeer",
    "PeerTooOld",
    "EmptyHeader",
    "Peer",
    "PeerConfig",
    "PeerConnected",
    "PeerDisconnected",
    "PeerMessage",
    "PeerEvent",
    "run_peer",
    "get_blocks",
    "get_txs",
    "get_data",
    "ping_peer",
]


class Connection(Protocol):
    """A byte-stream transport to one peer (the ``Conduits`` pair,
    reference Peer.hs:112-115)."""

    async def read_chunk(self) -> bytes:
        """Next chunk of inbound bytes; empty bytes means EOF."""
        ...

    async def write(self, data: bytes) -> None: ...


# A connection factory: entered per session, closes the transport on exit.
# (the ``WithConnection`` CPS connector, reference Peer.hs:117)
WithConnection = Callable[[], AbstractAsyncContextManager[Connection]]


# --- exceptions (reference Peer.hs:132-165) --------------------------------


class PeerError(Exception):
    """Base class for conditions that kill a peer session."""


class PeerMisbehaving(PeerError):
    pass


class DuplicateVersion(PeerError):
    pass


class DecodeHeaderError(PeerError):
    pass


class CannotDecodePayload(PeerError):
    pass


class PeerIsMyself(PeerError):
    pass


class PayloadTooLarge(PeerError):
    pass


class PeerAddressInvalid(PeerError):
    pass


class PeerSentBadHeaders(PeerError):
    pass


class NotNetworkPeer(PeerError):
    pass


class PeerNoSegWit(PeerError):
    pass


class PeerTimeout(PeerError):
    pass


class UnknownPeer(PeerError):
    pass


class PeerTooOld(PeerError):
    pass


class EmptyHeader(PeerError):
    pass


# --- peer handle & events ---------------------------------------------------


log = logging.getLogger("tpunode.peer")

@dataclass(frozen=True)
class _SendMessage:
    message: object


@dataclass(frozen=True)
class _KillPeer:
    error: PeerError


class Peer:
    """Handle to a peer session: its mailbox, event bus, label and busy flag
    (reference Peer.hs:170-175).  Identity comparison, like the reference's
    mailbox equality."""

    # __weakref__: the task-supervision registry holds peers weakly as
    # the owners of their session's inbound/outbound loop tasks
    __slots__ = ("mailbox", "pub", "label", "_busy", "__weakref__")

    def __init__(self, mailbox: Mailbox, pub: "Publisher[PeerEvent]", label: str):
        self.mailbox = mailbox
        self.pub = pub
        self.label = label
        self._busy = False

    # busy-lock (reference Peer.hs:293-304): single-threaded event loop makes
    # the check-and-set atomic, the STM analog.
    def get_busy(self) -> bool:
        return self._busy

    def set_busy(self) -> bool:
        """Try to acquire; True iff we took the lock."""
        if self._busy:
            return False
        self._busy = True
        return True

    def set_free(self) -> None:
        self._busy = False

    def send_message(self, msg) -> None:
        """Queue a wire message for delivery (reference Peer.hs:290-291)."""
        self.mailbox.send(_SendMessage(msg))

    def kill(self, error: PeerError) -> None:
        """Ask the session to die with ``error`` (reference Peer.hs:286-287)."""
        log.debug("[Peer] %s: kill requested: %r", self.label, error)
        self.mailbox.send(_KillPeer(error))

    def __repr__(self) -> str:
        return f"<Peer {self.label}>"


@dataclass(frozen=True)
class PeerConnected:
    peer: Peer


@dataclass(frozen=True)
class PeerDisconnected:
    peer: Peer


@dataclass(frozen=True)
class PeerMessage:
    peer: Peer
    message: object


PeerEvent = Union[PeerConnected, PeerDisconnected, PeerMessage]


@dataclass
class PeerConfig:
    """Per-session configuration (reference Peer.hs:119-124)."""

    pub: Publisher
    net: Network
    label: str
    connect: WithConnection


class ConnectionReader:
    """Exact-read buffering over chunked transport reads."""

    def __init__(self, conn: Connection):
        self._conn = conn
        self._buf = bytearray()

    async def read_exact(self, n: int) -> bytes:
        """Read exactly n bytes; raises EmptyHeader on EOF at a message
        boundary, DecodeHeaderError on EOF mid-item (reference semantics of
        Peer.hs:256-268)."""
        while len(self._buf) < n:
            chunk = await self._conn.read_chunk()
            if not chunk:
                if not self._buf:
                    raise EmptyHeader("connection closed")
                raise DecodeHeaderError("connection closed mid-frame")
            self._buf.extend(chunk)
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out


# Message commands that open a per-item pipeline trace (tracectx): the
# payloads whose lifecycle spans actor hops and the verify engine.
_TRACED_COMMANDS = ("block", "tx", "headers")


async def _inbound_loop(cfg: PeerConfig, peer: Peer, conn: Connection) -> None:
    """Frame, decode and publish every message from the peer
    (the hot loop; reference ``inPeerConduit`` Peer.hs:247-279)."""
    reader = ConnectionReader(conn)
    while True:
        raw_header = await reader.read_exact(HEADER_SIZE)
        try:
            header = decode_message_header(cfg.net, raw_header)
        except DecodeError as e:
            raise DecodeHeaderError(str(e)) from e
        if header.length > MAX_PAYLOAD:
            raise PayloadTooLarge(f"{header.command}: {header.length}")
        # Block/tx/headers messages start a causal trace here — the first
        # point the item exists — so payload delivery, decode, actor hops
        # and verify phases all land in one tree.  Other commands keep the
        # untraced hot path (one `enabled` read, no allocation).
        tok = None
        if tracer.enabled and header.command in _TRACED_COMMANDS:
            tr = tracer.start(
                header.command, peer=cfg.label, bytes=header.length
            )
            tok = _active_trace.set((tr, tr.root.id))
        try:
            if tok is not None:
                with span("peer.payload"):
                    payload = (
                        await reader.read_exact(header.length)
                        if header.length
                        else b""
                    )
                try:
                    with span("peer.decode"):
                        msg = decode_message(cfg.net, header, payload)
                except DecodeError as e:
                    raise CannotDecodePayload(f"{header.command}: {e}") from e
            else:
                payload = (
                    await reader.read_exact(header.length)
                    if header.length
                    else b""
                )
                try:
                    msg = decode_message(cfg.net, header, payload)
                except DecodeError as e:
                    raise CannotDecodePayload(f"{header.command}: {e}") from e
            if not metrics.disabled:  # hot loop: one flag read when off
                metrics.inc_batch((  # one lock for all three
                    ("peer.msgs_in", 1.0, None),
                    ("peer.bytes_in", HEADER_SIZE + header.length, None),
                    ("peer.msgs", 1.0,
                     {"peer": cfg.label, "cmd": header.command}),
                ))
            if log.isEnabledFor(logging.DEBUG):  # hot loop: skip format cost
                log.debug(
                    "[Peer] %s: received %s (%d bytes)",
                    cfg.label,
                    header.command,
                    header.length,
                )
            cfg.pub.publish(PeerMessage(peer, msg))
        finally:
            if tok is not None:
                _active_trace.reset(tok)


async def _outbound_loop(cfg: PeerConfig, inbox: Mailbox, conn: Connection) -> None:
    """Drain the mailbox into the socket; ``_KillPeer`` raises
    (reference ``dispatchMessage`` Peer.hs:234-244)."""
    while True:
        item = await inbox.receive()
        if isinstance(item, _KillPeer):
            raise item.error
        data = encode_message(cfg.net, item.message)
        if not metrics.disabled:
            metrics.inc_batch((
                ("peer.msgs_out", 1.0, None),
                ("peer.bytes_out", len(data), None),
            ))
        await conn.write(data)


async def run_peer(cfg: PeerConfig, peer: Peer, inbox: Mailbox) -> None:
    """Run a peer session in the current task until it dies
    (reference ``peer`` Peer.hs:204-231).

    Opens the injected transport, then runs the inbound decode loop and the
    outbound mailbox loop linked together: either side failing (EOF, decode
    error, kill command) tears the session down.  Exceptions propagate to the
    supervisor, which the peer manager turns into ``PeerDied`` handling.
    """
    log.debug("[Peer] %s: session starting", cfg.label)
    async with cfg.connect() as conn:
        if chaos.on:  # fault injection on the transport (tpunode/chaos.py)
            conn = chaos.wrap_connection(conn, cfg.label)
        # owner=peer: both loops are cancelled+awaited in the finally
        # below, but the registry still scopes them to this session so a
        # concurrent node's shutdown never misreads them as leaks
        t_in = spawn_supervised(
            _inbound_loop(cfg, peer, conn),
            name=f"peer-in-{cfg.label}", owner=peer,
        )
        t_out = spawn_supervised(
            _outbound_loop(cfg, inbox, conn),
            name=f"peer-out-{cfg.label}", owner=peer,
        )
        try:
            done, pending = await asyncio.wait(
                {t_in, t_out}, return_when=asyncio.FIRST_EXCEPTION
            )
        finally:
            for t in (t_in, t_out):
                t.cancel()
            await asyncio.gather(t_in, t_out, return_exceptions=True)
        for t in done:
            if not t.cancelled() and t.exception() is not None:
                log.debug(
                    "[Peer] %s: session ending: %s", cfg.label, t.exception()
                )
                raise t.exception()
        log.debug("[Peer] %s: session ended cleanly", cfg.label)


# --- synchronous request helpers -------------------------------------------


def _filter_peer(p: Peer):
    def select(ev: PeerEvent):
        if isinstance(ev, PeerMessage) and ev.peer is p:
            return ev.message
        return None

    return select


async def get_data(
    seconds: float, p: Peer, invs: list[InvVector]
) -> Optional[list[Union[Tx, Block]]]:
    """Request inventory and await the items in strict order.

    Implements the reference's pub/sub-as-RPC with a trailing ping sentinel
    (Peer.hs:349-387): subscribe first, send ``getdata`` then ``ping``; the
    matching ``pong`` bounds the wait because a peer answers requests in
    order.  Returns None on timeout, not-found, out-of-order or interleaved
    replies.
    """
    async with p.pub.subscription() as inbox:
        nonce = random.getrandbits(64)
        p.send_message(MsgGetData(tuple(invs)))
        p.send_message(MsgPing(nonce))
        select = _filter_peer(p)
        acc: list[Union[Tx, Block]] = []
        remaining = list(invs)
        try:
            async with _timeout(seconds):
                while remaining:
                    msg = await inbox.receive_match(select)
                    iv = remaining[0]
                    try:
                        tx_match = (
                            isinstance(msg, MsgTx)
                            and _is_tx_type(iv.type)
                            and msg.tx.txid == iv.hash
                        )
                    except ValueError:
                        # lazy tx whose payload does not parse: the eager
                        # decode used to kill the peer before we ever saw
                        # it; preserve the returns-None-on-garbage contract
                        return None
                    if tx_match:
                        acc.append(msg.tx)
                        remaining.pop(0)
                    elif (
                        isinstance(msg, MsgBlock)
                        and _is_block_type(iv.type)
                        and msg.block.header.hash == iv.hash
                    ):
                        acc.append(msg.block)
                        remaining.pop(0)
                    elif isinstance(msg, MsgNotFound) and (
                        {v.hash for v in msg.invs} & {v.hash for v in remaining}
                    ):
                        return None
                    elif isinstance(msg, MsgPong) and msg.nonce == nonce:
                        return None  # peer finished answering: incomplete
                    elif acc:
                        return None  # interleaved garbage mid-stream
        except TimeoutError:
            return None
        return acc


def _is_tx_type(t: int) -> bool:
    return t in (InvType.TX, InvType.WITNESS_TX)


def _is_block_type(t: int) -> bool:
    return t in (InvType.BLOCK, InvType.WITNESS_BLOCK)


async def get_blocks(
    net: Network, seconds: float, p: Peer, block_hashes: list[bytes]
) -> Optional[list["Block | LazyBlock"]]:
    """Fetch full blocks by hash (reference Peer.hs:309-324).  Wire-decoded
    blocks arrive as wire.LazyBlock (tx region unparsed until .txs)."""
    t = InvType.WITNESS_BLOCK if net.segwit else InvType.BLOCK
    out = await get_data(seconds, p, [InvVector(t, h) for h in block_hashes])
    if out is None or not all(isinstance(x, (Block, LazyBlock)) for x in out):
        return None
    return out  # type: ignore[return-value]


async def get_txs(
    net: Network, seconds: float, p: Peer, tx_hashes: list[bytes]
) -> Optional[list["Tx | LazyTx"]]:
    """Fetch transactions by txid (reference Peer.hs:329-344).  Wire-decoded
    txs arrive as wire.LazyTx (the txid match already parsed them)."""
    t = InvType.WITNESS_TX if net.segwit else InvType.TX
    out = await get_data(seconds, p, [InvVector(t, h) for h in tx_hashes])
    if out is None or not all(isinstance(x, (Tx, LazyTx)) for x in out):
        return None
    return out  # type: ignore[return-value]


async def ping_peer(seconds: float, p: Peer) -> bool:
    """Round-trip a ping; False on timeout (reference Peer.hs:391-399)."""
    async with p.pub.subscription() as inbox:
        nonce = random.getrandbits(64)
        p.send_message(MsgPing(nonce))

        def select(ev: PeerEvent):
            if (
                isinstance(ev, PeerMessage)
                and ev.peer is p
                and isinstance(ev.message, MsgPong)
                and ev.message.nonce == nonce
            ):
                return True
            return None

        try:
            async with _timeout(seconds):
                return await inbox.receive_match(select)
        except TimeoutError:
            return False
